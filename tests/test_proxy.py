"""Service VIP dataplane tests — the kube-proxy analog
(kubernetes_tpu/proxy.py; reference pkg/proxy/iptables/proxier.go:283
syncProxyRules, pkg/controller/endpoint/endpoints_controller.go)."""

import collections

from kubernetes_tpu.api.types import Resources
from kubernetes_tpu.proxy import (
    AFFINITY_CLIENT_IP,
    ClusterIPAllocator,
    EndpointAddress,
    Endpoints,
    Service,
    ServicePort,
    ServiceProxy,
)
from kubernetes_tpu.sim import HollowCluster, ReplicaSet
from kubernetes_tpu.testing import make_node, make_pod


def _cluster(n_nodes=4, cpu=4000.0):
    hub = HollowCluster(seed=7)
    for i in range(n_nodes):
        hub.add_node(make_node(f"n{i}", cpu_milli=cpu))
    return hub


def _web_service(**kw):
    return Service("web", selector={"app": "web"},
                   ports=(ServicePort("http", 80, 8080),), **kw)


def test_endpoints_track_bound_matching_pods():
    hub = _cluster()
    hub.add_service(_web_service())
    hub.add_replicaset(ReplicaSet("other", 2))  # labels rs=other: no match
    for i in range(3):
        p = make_pod(f"web-{i}", labels={"app": "web"})
        hub.create_pod(p)
    for _ in range(4):
        hub.step()
    hub.check_consistency()
    ep = hub.endpoints["default/web"]
    assert sorted(a.pod_key for a in ep.ready) == [
        "default/web-0", "default/web-1", "default/web-2"
    ]
    # each address carries the real binding node
    for a in ep.ready:
        assert hub.truth_pods[a.pod_key].node_name == a.node_name
    # pending pods (none left) / non-matching pods are excluded
    assert all("other" not in a.pod_key for a in ep.ready)


def test_endpoints_move_on_pod_delete_and_reschedule():
    hub = _cluster(n_nodes=2)
    hub.add_service(_web_service())
    hub.add_replicaset(ReplicaSet("web", 2))
    # label the RS pods into the service: ReplicaSet spawns with rs=web
    hub.services["default/web"].selector = {"rs": "web"}
    for _ in range(4):
        hub.step()
    ep = hub.endpoints["default/web"]
    assert len(ep.ready) == 2
    victim = ep.ready[0].pod_key
    hub.delete_pod(victim)
    hub.step()  # controller recreates; scheduler rebinds; endpoints follow
    hub.step()
    hub.check_consistency()
    ep2 = hub.endpoints["default/web"]
    assert len(ep2.ready) == 2
    assert victim not in {a.pod_key for a in ep2.ready}


def test_service_delete_removes_endpoints_and_releases_ip():
    hub = _cluster(n_nodes=1)
    svc = _web_service()
    hub.add_service(svc)
    ip = svc.cluster_ip
    assert ip
    hub.step()
    assert "default/web" in hub.endpoints
    hub.delete_service("default/web")
    hub.step()
    assert "default/web" not in hub.endpoints
    # released IP is reallocatable
    svc2 = Service("web2", selector={"app": "w2"})
    hub.add_service(svc2)
    assert svc2.cluster_ip  # allocator still serving


def test_proxy_resolves_vip_to_ready_backend():
    hub = _cluster()
    hub.add_service(_web_service())
    for i in range(3):
        hub.create_pod(make_pod(f"web-{i}", labels={"app": "web"}))
    for _ in range(3):
        hub.step()
    svc = hub.services["default/web"]
    seen = set()
    for node, proxy in hub.proxies.items():
        b = proxy.resolve(svc.cluster_ip, 80, client="10.0.0.9")
        assert b is not None and b.pod_key.startswith("default/web-")
        seen.add(b.pod_key)
    # unknown VIP/port rejects (None)
    assert hub.proxies["n0"].resolve(svc.cluster_ip, 81) is None
    assert hub.proxies["n0"].resolve("10.96.9.9", 80) is None


def test_proxy_distribution_roughly_uniform():
    """The statistic-random chain spreads distinct clients across
    backends (proxier.go's --probability 1/n cascade)."""
    proxy = ServiceProxy("n0")
    backends = tuple(EndpointAddress(f"default/web-{i}", f"n{i}")
                     for i in range(4))
    svc = Service("web", cluster_ip="10.96.0.1",
                  ports=(ServicePort("http", 80, 8080),))
    ep = Endpoints("web", ready=backends)
    proxy.sync({svc.key(): svc}, {ep.key(): ep})
    counts = collections.Counter(
        proxy.resolve("10.96.0.1", 80, client=f"10.1.0.{i}").pod_key
        for i in range(400)
    )
    assert set(counts) == {b.pod_key for b in backends}
    assert min(counts.values()) > 400 / 4 * 0.5  # no starved backend


def test_client_ip_session_affinity_sticks_and_expires():
    class FakeClock:
        t = 0.0

    clock = FakeClock()
    proxy = ServiceProxy("n0", clock)
    backends = tuple(EndpointAddress(f"default/web-{i}", "n0")
                     for i in range(8))
    svc = Service("web", cluster_ip="10.96.0.1",
                  ports=(ServicePort("http", 80, 8080),),
                  session_affinity=AFFINITY_CLIENT_IP, affinity_seconds=60)
    ep = Endpoints("web", ready=backends)
    proxy.sync({svc.key(): svc}, {ep.key(): ep})
    first = proxy.resolve("10.96.0.1", 80, client="1.2.3.4")
    for _ in range(10):  # sticky while inside the window
        clock.t += 5
        assert proxy.resolve("10.96.0.1", 80, client="1.2.3.4") == first
    clock.t += 61  # window expired since last hit -> re-pick allowed
    again = proxy.resolve("10.96.0.1", 80, client="1.2.3.4")
    assert again in backends
    # sticky backend drained -> re-pick among the survivors
    ep2 = Endpoints("web", ready=tuple(b for b in backends if b != first))
    proxy.sync({svc.key(): svc}, {ep2.key(): ep2})
    assert proxy.resolve("10.96.0.1", 80, client="1.2.3.4") != first


def test_node_port_routing():
    proxy = ServiceProxy("n0")
    svc = Service("web", cluster_ip="10.96.0.1",
                  ports=(ServicePort("http", 80, 8080, node_port=30080),))
    ep = Endpoints("web", ready=(EndpointAddress("default/web-0", "n1"),))
    proxy.sync({svc.key(): svc}, {ep.key(): ep})
    assert proxy.resolve_node_port(30080).pod_key == "default/web-0"
    assert proxy.resolve_node_port(30081) is None


def test_no_ready_endpoints_rejects():
    hub = _cluster(n_nodes=1)
    hub.add_service(_web_service())
    hub.step()
    svc = hub.services["default/web"]
    assert hub.proxies["n0"].resolve(svc.cluster_ip, 80) is None


def test_cluster_ip_allocator_unique_and_reusable():
    al = ClusterIPAllocator()
    ips = {al.allocate() for _ in range(300)}
    assert len(ips) == 300
    al.release("10.96.0.5")
    assert "10.96.0.5" in {al.allocate() for _ in range(300)}


def test_preset_cluster_ip_reserved_in_allocator():
    """An explicit spec.clusterIP must be reserved so the allocator never
    hands the same VIP to a second service (review r3 finding)."""
    hub = _cluster(n_nodes=1)
    hub.add_service(Service("pinned", selector={"x": "y"},
                            cluster_ip="10.96.0.1"))
    hub.add_service(Service("auto", selector={"a": "b"}))
    assert hub.services["default/auto"].cluster_ip != "10.96.0.1"


def test_selectorless_service_keeps_manual_endpoints():
    """Selector-less services carry manually-managed Endpoints (the
    external-backend pattern); the controller must neither overwrite nor
    GC them (endpoints_controller.go nil-selector early return)."""
    hub = _cluster(n_nodes=1)
    hub.add_service(Service("ext", selector={}))
    hub.put_endpoints(Endpoints(
        "ext", ready=(EndpointAddress("external/backend", ""),)))
    for _ in range(2):
        hub.step()
    ep = hub.endpoints["default/ext"]
    assert [a.pod_key for a in ep.ready] == ["external/backend"]
    svc = hub.services["default/ext"]
    assert hub.proxies["n0"].resolve(svc.cluster_ip, 0) is None  # no port 0
    # service delete DOES GC the manual endpoints
    hub.delete_service("default/ext")
    hub.step()
    assert "default/ext" not in hub.endpoints
