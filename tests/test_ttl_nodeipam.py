"""TTL controller + nodeipam range allocator (controller-breadth items
from VERDICT r4 'what's missing' #4): cluster-size-scaled TTL
annotations with hysteresis (ttl_controller.go:102) and per-node podCIDR
allocation/release from the cluster CIDR (ipam/range_allocator.go)."""

from kubernetes_tpu.sim import HollowCluster
from kubernetes_tpu.testing import make_node


def hub():
    return HollowCluster(seed=61, scheduler_kw={"enable_preemption": False})


def test_ttl_annotation_scales_with_cluster_size_with_hysteresis():
    h = hub()
    for i in range(5):
        h.add_node(make_node(f"n{i}"))
    h.step()
    ttl = h.truth_nodes["n0"].annotations["node.alpha.kubernetes.io/ttl"]
    assert ttl == "0"  # <=100 nodes

    for i in range(5, 120):
        h.add_node(make_node(f"n{i}"))
    h.step()
    assert h.truth_nodes["n0"].annotations[
        "node.alpha.kubernetes.io/ttl"] == "15"  # crossed 100

    # hysteresis: dropping to 95 (>= sizeMin 90 of the 15s band) keeps 15
    for i in range(95, 120):
        h.remove_node(f"n{i}")
    h.step()
    assert h.truth_nodes["n0"].annotations[
        "node.alpha.kubernetes.io/ttl"] == "15"
    # dropping below sizeMin 90 steps back down to 0
    for i in range(80, 95):
        h.remove_node(f"n{i}")
    h.step()
    assert h.truth_nodes["n0"].annotations[
        "node.alpha.kubernetes.io/ttl"] == "0"
    h.check_consistency()


def test_nodeipam_allocates_unique_cidrs_and_recycles():
    h = hub()
    for i in range(6):
        h.add_node(make_node(f"n{i}"))
    h.step()
    cidrs = {n.name: n.pod_cidr for n in h.truth_nodes.values()}
    assert all(c.endswith("/24") for c in cidrs.values())
    assert len(set(cidrs.values())) == 6  # unique blocks

    # release on delete, recycle to a new node
    released = cidrs["n3"]
    h.remove_node("n3")
    h.step()
    h.add_node(make_node("n9"))
    h.step()
    assert h.truth_nodes["n9"].pod_cidr == released
    h.check_consistency()


def test_nodeipam_exhaustion_is_counted_not_crashed():
    h = hub()
    h.cluster_cidr = "10.0.0.0/30"  # one /32... /30 -> 4 /32s
    h.node_cidr_prefix = 32
    for i in range(6):
        h.add_node(make_node(f"x{i}"))
    h.step()
    allocated = [n for n in h.truth_nodes.values() if n.pod_cidr]
    assert len(allocated) == 4
    assert h.cidr_exhausted_total >= 2
    h.check_consistency()


def test_nodeipam_readd_same_name_restamps_held_block():
    """Review finding r5: delete + re-add with the same name between
    reconcile passes must re-stamp the held block, not leak it while
    leaving the node CIDR-less forever."""
    h = hub()
    h.add_node(make_node("n1"))
    h.step()
    cidr = h.truth_nodes["n1"].pod_cidr
    assert cidr
    h.remove_node("n1")
    h.add_node(make_node("n1"))  # same pass: release loop sees it live
    h.step()
    assert h.truth_nodes["n1"].pod_cidr == cidr
    h.check_consistency()
