"""Hand-computed scenario tables ported (semantically, not textually) from
the reference's unit suites — the absolute-value counterpart to the
differential tests: every expectation below is derived by hand from the
reference's documented formulas, then asserted against BOTH the device
kernels and (via the shared harnesses) the oracle.

Sources:
- algorithm/predicates/predicates_test.go (TestPodFitsResources,
  TestPodFitsHost, TestPodFitsHostPorts, TestPodMatchesNodeSelectorTerms
  shapes, TestPodToleratesNodeTaints)
- algorithm/priorities/least_requested_test.go, most_requested_test.go,
  balanced_resource_allocation_test.go, taint_toleration_test.go,
  image_locality_test.go, selector_spreading_test.go
"""

import numpy as np

import pyref
from kubernetes_tpu.api.types import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    LabelSelector,
    Taint,
    Toleration,
)
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.predicates import decode_reasons
from kubernetes_tpu.testing import make_node, make_pod, node_affinity_required, req
from test_predicates import device_mask, oracle_mask
from test_priorities import build, by_node, crop

GB = 2**30
MB = 2**20


def reasons_of(reasons, i, j):
    return decode_reasons(int(reasons[i, j]))


def both_masks(nodes, existing, pending):
    """Device mask + reasons, with the pyref oracle asserted to agree —
    every predicate table below therefore pins BOTH implementations to
    the hand-computed expectation."""
    mask, reasons = device_mask(nodes, existing, pending)
    want = oracle_mask(nodes, existing, pending)
    assert (mask == want).all(), "device/oracle mask divergence"
    return mask, reasons


# ---------------------------------------------------------------------------
# TestPodFitsResources (predicates_test.go): cpu/memory/scalar/pod-count
# accounting, request > free → the per-resource insufficiency reason
# ---------------------------------------------------------------------------


def test_pod_fits_resources_table():
    node = make_node("n0", cpu_milli=4000, memory=8 * GB, pods=10)
    existing = [make_pod("e0", cpu_milli=3000, memory=5 * GB, node_name="n0")]
    cases = [
        # (pod kwargs, fits, must-have reason)
        (dict(), True, None),                                   # no requests
        (dict(cpu_milli=1000, memory=3 * GB), True, None),      # exactly free
        (dict(cpu_milli=1001), False, "PodFitsResources"),      # cpu over by 1m
        # memory accounting is f32 on device: the contract is byte-exact
        # only up to float32 ulp (512B at 8GB — the reference's int64 math
        # is exact; our overcommit bound is ~6e-8 relative, far below the
        # kubelet's own accounting noise). Test at a representable margin.
        (dict(memory=3 * GB + MB), False, "PodFitsResources"),
        (dict(cpu_milli=2000, memory=4 * GB), False, "PodFitsResources"),
    ]
    pending = [make_pod(f"p{i}", **kw) for i, (kw, _, _) in enumerate(cases)]
    mask, reasons = both_masks([node], existing, pending)
    for i, (kw, fits, reason) in enumerate(cases):
        assert bool(mask[i, 0]) == fits, (i, kw, reasons_of(reasons, i, 0))
        if reason:
            assert reason in reasons_of(reasons, i, 0)


def test_pod_count_limit():
    # allowedPodNumber is a resource like any other (predicates.go:779
    # podFitsOnNode resource loop): a full node rejects even a no-request pod
    node = make_node("n0", cpu_milli=4000, pods=2)
    existing = [make_pod(f"e{i}", node_name="n0") for i in range(2)]
    mask, reasons = both_masks([node], existing, [make_pod("p")])
    assert not mask[0, 0]
    assert "PodFitsResources" in reasons_of(reasons, 0, 0)


def test_scalar_resource_accounting():
    node = make_node("n0")
    node.allocatable.scalars["example.com/gpu"] = 2
    existing = [make_pod("e0", node_name="n0", scalars={"example.com/gpu": 1})]
    fits = make_pod("p0", scalars={"example.com/gpu": 1})
    over = make_pod("p1", scalars={"example.com/gpu": 2})
    mask, reasons = both_masks([node], existing, [fits, over])
    assert mask[0, 0] and not mask[1, 0]
    assert "PodFitsResources" in reasons_of(reasons, 1, 0)


# ---------------------------------------------------------------------------
# TestPodFitsHost (predicates.go:916): spec.nodeName pins to exactly one node
# ---------------------------------------------------------------------------


def test_pod_fits_host_table():
    nodes = [make_node("n0"), make_node("n1")]
    pinned = make_pod("p0", node_name="n0")
    free = make_pod("p1")
    mask, reasons = both_masks(nodes, [], [pinned, free])
    assert mask[0, 0] and not mask[0, 1]
    assert "PodFitsHost" in reasons_of(reasons, 0, 1)
    assert mask[1, 0] and mask[1, 1]


# ---------------------------------------------------------------------------
# TestPodFitsHostPorts (predicates.go:1084 + HostPortInfo host_ports.go:47):
# conflicts are (protocol, ip, port) aware with 0.0.0.0 wildcarding
# ---------------------------------------------------------------------------


def test_host_ports_table():
    node = make_node("n0")
    existing = [make_pod("e0", node_name="n0",
                         host_ports=[("TCP", "10.0.0.1", 8080)])]
    cases = [
        ([("TCP", "10.0.0.1", 8080)], False),  # exact conflict
        ([("TCP", "10.0.0.2", 8080)], True),   # different IP
        ([("UDP", "10.0.0.1", 8080)], True),   # different protocol
        ([("TCP", "10.0.0.1", 8081)], True),   # different port
        ([("TCP", "", 8080)], False),          # wildcard vs specific
        ([("TCP", "0.0.0.0", 8080)], False),   # explicit wildcard too
    ]
    pending = [make_pod(f"p{i}", host_ports=hp)
               for i, (hp, _) in enumerate(cases)]
    mask, reasons = both_masks([node], existing, pending)
    for i, (hp, fits) in enumerate(cases):
        assert bool(mask[i, 0]) == fits, (hp, reasons_of(reasons, i, 0))
        if not fits:
            assert "PodFitsHostPorts" in reasons_of(reasons, i, 0)


def test_wildcard_existing_blocks_specific():
    node = make_node("n0")
    existing = [make_pod("e0", node_name="n0", host_ports=[("TCP", "", 80)])]
    mask, _ = both_masks([node], existing,
                          [make_pod("p", host_ports=[("TCP", "10.1.1.1", 80)])])
    assert not mask[0, 0]


# ---------------------------------------------------------------------------
# Node-selector operator semantics (v1helper.MatchNodeSelectorTerms —
# terms OR, expressions AND, NotIn/DoesNotExist match absent keys)
# ---------------------------------------------------------------------------


def test_node_selector_operator_table():
    node = make_node("n0", labels={"disk": "ssd", "cores": "16"})
    cases = [
        ([req("disk", OP_IN, "ssd", "nvme")], True),
        ([req("disk", OP_IN, "hdd")], False),
        ([req("disk", OP_NOT_IN, "hdd")], True),
        ([req("gpu", OP_NOT_IN, "a100")], True),      # absent key: NotIn matches
        ([req("disk", OP_EXISTS)], True),
        ([req("gpu", OP_EXISTS)], False),
        ([req("gpu", OP_DOES_NOT_EXIST)], True),
        ([req("cores", OP_GT, "8")], True),
        ([req("cores", OP_GT, "16")], False),          # strict
        ([req("cores", OP_LT, "32")], True),
        # one term, two expressions: AND (second fails)
        ([req("disk", OP_IN, "ssd"), req("cores", OP_GT, "64")], False),
    ]
    pending = [make_pod(f"p{i}", affinity=node_affinity_required(rs))
               for i, (rs, _) in enumerate(cases)]
    mask, reasons = both_masks([node], [], pending)
    for i, (rs, fits) in enumerate(cases):
        assert bool(mask[i, 0]) == fits, (i, rs)
        if not fits:
            assert "PodMatchNodeSelector" in reasons_of(reasons, i, 0)


def test_node_selector_terms_are_ored():
    node = make_node("n0", labels={"disk": "ssd"})
    pod = make_pod("p", affinity=node_affinity_required(
        [req("disk", OP_IN, "hdd")],      # term 1 fails
        [req("disk", OP_IN, "ssd")],      # term 2 matches → fits
    ))
    mask, _ = both_masks([node], [], [pod])
    assert mask[0, 0]


# ---------------------------------------------------------------------------
# TestPodToleratesNodeTaints (predicates.go:1546): only NoSchedule/NoExecute
# effects filter; Equal/Exists operators; empty-key Exists tolerates all
# ---------------------------------------------------------------------------


def test_taint_toleration_predicate_table():
    nodes = [
        make_node("plain"),
        make_node("noschedule", taints=[Taint("dedicated", "gpu")]),
        make_node("noexecute",
                  taints=[Taint("critical", "", "NoExecute")]),
        make_node("prefer",
                  taints=[Taint("flaky", "", "PreferNoSchedule")]),
    ]
    cases = [
        ((), [True, False, False, True]),  # PreferNoSchedule never filters
        ((Toleration(key="dedicated", operator="Equal", value="gpu",
                     effect="NoSchedule"),),
         [True, True, False, True]),
        ((Toleration(key="dedicated", operator="Equal", value="db",
                     effect="NoSchedule"),),
         [True, False, False, True]),      # value mismatch
        ((Toleration(key="dedicated", operator="Exists"),),
         [True, True, False, True]),       # empty effect matches all effects
        ((Toleration(operator="Exists"),),
         [True, True, True, True]),        # empty key: tolerate everything
        ((Toleration(key="critical", operator="Exists",
                     effect="NoExecute"),),
         [True, False, True, True]),
    ]
    pending = [make_pod(f"p{i}", tolerations=tols)
               for i, (tols, _) in enumerate(cases)]
    mask, reasons = both_masks(nodes, [], pending)
    for i, (tols, want) in enumerate(cases):
        got = [bool(mask[i, j]) for j in range(len(nodes))]
        assert got == want, (i, tols, got)
        for j, fits in enumerate(want):
            if not fits:
                assert "PodToleratesNodeTaints" in reasons_of(reasons, i, j)


# ---------------------------------------------------------------------------
# Priority tables with hand-computed absolute scores
# ---------------------------------------------------------------------------


def test_least_and_most_requested_scores():
    # least_requested.go: int((cap-req)*10/cap) per resource, averaged with
    # integer division; most_requested.go is the dual int(req*10/cap).
    # Requests go through the nonzero defaults (non_zero.go:42,:48).
    node = make_node("n0", cpu_milli=4000, memory=8 * GB)
    quarter = make_pod("quarter", cpu_milli=1000, memory=2 * GB)
    zero = make_pod("zero")  # defaults: 100m cpu, 200MB memory
    over = make_pod("over", cpu_milli=5000, memory=GB)
    dn, dp, ds, mask = build([node], [], [quarter, zero, over])
    least = crop(prio.least_requested(dp, dn, ds, None, mask),
                 [quarter, zero, over], [node])
    most = crop(prio.most_requested(dp, dn, ds, None, mask),
                [quarter, zero, over], [node])
    # quarter: cpu int(3000*10/4000)=7, mem int(6G*10/8G)=7 → (7+7)/2=7
    assert least[0, 0] == 7.0
    # zero: cpu int(3900*10/4000)=9; mem int((8G-200MB)*10/8G)=9 → 9
    assert least[1, 0] == 9.0
    # over: cpu request > capacity scores 0; mem int(7G*10/8G)=8 → int(8/2)=4
    assert least[2, 0] == 4.0
    # most: quarter cpu int(1000*10/4000)=2, mem int(2G*10/8G)=2 → 2
    assert most[0, 0] == 2.0
    assert most[1, 0] == 0.0   # int(100*10/4000)=0, int(200MB*10/8G)=0
    assert most[2, 0] == 0.0   # over-capacity cpu scores 0; (0+1)/2 = 0
    # the oracle must land on the same hand-computed constants
    for p, l, m in [(quarter, 7, 2), (zero, 9, 0), (over, 4, 0)]:
        assert pyref.least_requested_score(p, node, []) == l
        assert pyref.most_requested_score(p, node, []) == m


def test_balanced_allocation_scores():
    # balanced_resource_allocation.go:41: int((1 - |cpuFrac-memFrac|) * 10);
    # any fraction >= 1 → 0
    node = make_node("n0", cpu_milli=4000, memory=8 * GB)
    balanced = make_pod("b", cpu_milli=1000, memory=2 * GB)    # 0.25 / 0.25
    skewed = make_pod("s", cpu_milli=2000, memory=2 * GB)      # 0.50 / 0.25
    full = make_pod("f", cpu_milli=4000, memory=2 * GB)        # 1.00 → 0
    dn, dp, ds, mask = build([node], [], [balanced, skewed, full])
    got = crop(prio.balanced_allocation(dp, dn, ds, None, mask),
               [balanced, skewed, full], [node])
    assert got[0, 0] == 10.0
    assert got[1, 0] == 7.0    # int((1-0.25)*10)
    assert got[2, 0] == 0.0
    for p, want in [(balanced, 10), (skewed, 7), (full, 0)]:
        assert pyref.balanced_allocation_score(p, node, []) == want


def test_taint_toleration_priority_scores():
    # taint_toleration.go: count untolerated PreferNoSchedule taints,
    # NormalizeReduce(10, reverse=true) → 10*(max-count)/max
    nodes = [
        make_node("clean"),
        make_node("one", taints=[Taint("a", "", "PreferNoSchedule")]),
        make_node("two", taints=[Taint("a", "", "PreferNoSchedule"),
                                 Taint("b", "", "PreferNoSchedule")]),
    ]
    pod = make_pod("p")
    dn, dp, ds, mask = build(nodes, [], [pod])
    got = crop(prio.taint_toleration(dp, dn, ds, None, mask), [pod], nodes)
    assert list(got[0]) == [10.0, 5.0, 0.0]
    m = crop(mask, [pod], nodes)
    assert pyref.taint_toleration_scores([pod], nodes, m)[0] == [10, 5, 0]


def test_image_locality_scores():
    # image_locality.go: sumScores = Σ size*(nodes-with-image/total-nodes),
    # clamped to [23MB, 1000MB], scaled → int(10*(x-lo)/(hi-lo))
    img = {"registry/app:v1": 500 * MB}
    nodes = [make_node("with", images=img), make_node("without")]
    pod = make_pod("p", images=("registry/app:v1",))
    dn, dp, ds, mask = build(nodes, [], [pod])
    got = crop(prio.image_locality(dp, dn, ds, None, mask), [pod], nodes)
    # spread = 1/2 → scaled = 250MB; int(10*(250-23)/(1000-23)) = 2
    assert got[0, 0] == 2.0
    assert got[0, 1] == 0.0    # below the 23MB floor after clamping
    assert pyref.image_locality_scores([pod], nodes)[0] == [2, 0]


def test_selector_spread_zone_weighting():
    # selector_spreading.go:34 zoneWeighting=2/3: with zones present,
    # score = (1/3)*nodeScore + (2/3)*zoneScore, each 10*(max-count)/max
    svc = LabelSelector(match_labels={"app": "web"})
    nodes = [
        make_node("a0", zone="za"),
        make_node("a1", zone="za"),
        make_node("b0", zone="zb"),
    ]
    scheduled = [
        make_pod("e0", node_name="a0", labels={"app": "web"}),
        make_pod("e1", node_name="a0", labels={"app": "web"}),
        make_pod("e2", node_name="a1", labels={"app": "web"}),
    ]
    pod = make_pod("p", labels={"app": "web"}, spread_selectors=(svc,))
    dn, dp, ds, mask = build(nodes, scheduled, [pod])
    got = crop(prio.selector_spread(dp, dn, ds, None, mask), [pod], nodes)
    # node counts: a0=2, a1=1, b0=0 (maxCount 2) → node scores 0, 5, 10
    # zone counts: za=3, zb=0 (maxZone 3)        → zone scores 0, 0, 10
    # final = int((1/3)*node + (2/3)*zone) — the reduce truncates to int
    want = [0.0, 1.0, 10.0]  # a1: int(5/3) = 1
    assert np.allclose(got[0], want, atol=1e-4), (list(got[0]), want)
    m = crop(mask, [pod], nodes)
    assert pyref.selector_spread_scores(
        [pod], nodes, by_node(nodes, scheduled), m)[0] == want
