"""Perf ledger + SLO watchdog (obs/ledger.py) — the tier-1 acceptance
suite:

- driven cycles produce ledger entries whose per-phase sums reconcile
  with the trace's span wall time (the grouping is lossless);
- model efficiency is populated on single-device AND mesh={2,8} cycles,
  and the mesh prediction folds in EXACTLY parallel/costmodel.py's
  ``model_efficiency`` (the bench/runtime parity pin — ROADMAP item 1's
  falsification instrument has ONE model);
- a fake-clock latency regression trips the fast-window burn (event
  emitted, ``backend_pressure`` engaged) and recovery clears it;
- ``/debug/ledger`` serves the thread-safe snapshot; the config block
  round-trips native AND v1alpha1 and ``validate_config`` gates it;
- the bench_compare ``ledger`` gate family honors its contract
  (efficiency floor, clean-arm burns, phase-share sanity, absence
  tolerance);
- ledger overhead stays under 2% of a contended cycle, zero new
  retraces, and graftlint stays clean over the module.
"""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.config import (
    LedgerConfig,
    ObservabilityConfig,
    ParallelConfig,
)
from kubernetes_tpu.obs.ledger import (
    CycleCostModel,
    PerfLedger,
    SLOWatchdog,
    parse_batch_shape,
    phase_of,
)
from kubernetes_tpu.scheduler import CycleResult, Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _scheduler(n_nodes=4, pods_cpu=100, **kw):
    s = Scheduler(enable_preemption=False, **kw)
    for i in range(n_nodes):
        s.on_node_add(make_node(f"n{i}", cpu_milli=16000))
    return s


def _drive(s, n_pods=8, cycles=2, prefix="p"):
    out = []
    for c in range(cycles):
        for i in range(n_pods):
            s.on_pod_add(make_pod(f"{prefix}{c}-{i}", cpu_milli=50))
        out.append(s.schedule_cycle())
    return out


# ---------------------------------------------------------------------------
# measured side: phase grouping + reconciliation
# ---------------------------------------------------------------------------


def test_phase_grouping_vocabulary():
    assert phase_of("solve:batch") == "solve"
    assert phase_of("solve:restricted") == "solve"
    assert phase_of("pipeline:pack@3") == "pack"
    assert phase_of("pipeline:dispatch@0") == "dispatch"
    assert phase_of("pipeline:readback@reasons") == "readback"
    assert phase_of("pipeline:bind@2") == "bind"
    assert phase_of("snapshot") == "snapshot"
    assert phase_of("validate") == "validate"
    assert phase_of("extender:filter") == "extenders"
    assert phase_of("Scheduling cycle") == ""  # the root is the total
    assert phase_of("something-new") == "other"
    assert parse_batch_shape("P4096xN65536+topo+mesh8") == (4096, 65536)
    assert parse_batch_shape("") == (0, 0)


def test_driven_cycles_produce_reconciling_entries():
    s = _scheduler()
    _drive(s, n_pods=8, cycles=3)
    snap = s.obs.ledger.snapshot()
    assert snap["retained"] == 3
    for entry, rec in zip(snap["entries"], s.obs.recorder.records()):
        phases = entry["phases"]
        assert phases.get("solve", 0) > 0
        assert phases.get("snapshot", 0) > 0
        # phases are DISJOINT slices of the cycle wall (child-exclusive
        # attribution): their sum reconciles with — never exceeds —
        # the measured cycle
        assert sum(phases.values()) <= entry["measured_s"] * 1.05
        # and the regrouping is lossless against the trace: for this
        # driven shape, validate nests inside solve:batch, so the
        # exclusive solve + validate phases rebuild the INCLUSIVE
        # solve:batch span the flight record keeps
        # snapshot phases are rounded to 6 decimals (±5e-7 each), so a
        # k-phase sum may deviate up to k·5e-7 from the raw spans —
        # the tolerance must cover the rounding budget or this flakes
        assert phases["solve"] + phases.get("validate", 0) == \
            pytest.approx(rec.spans["solve:batch"], rel=1e-6, abs=2e-6)
        top_level = (rec.spans["snapshot"] + rec.spans["solve:batch"]
                     + rec.spans.get("bind", 0.0))
        assert sum(phases.values()) == pytest.approx(
            top_level, rel=1e-6, abs=5e-7 * (len(phases) + 1))
    # rolling distributions exist per (phase, scope, mesh)
    assert any(k.startswith("solve|full|mesh0")
               for k in snap["distributions"])


def test_model_efficiency_populated_single_device():
    s = _scheduler()
    results = _drive(s, n_pods=8, cycles=3)
    for r in results:
        assert r.model_efficiency >= 0, "CycleResult must carry the verdict"
        assert r.modeled_s >= 0
    recs = s.obs.recorder.records()
    assert all(r.model_efficiency >= 0 for r in recs)
    # warm cycles sit near the best-observed rate (the anchor), far
    # from the clipped extremes a poisoned anchor would produce
    assert 0.2 <= recs[-1].model_efficiency <= 8.0
    # the flight-recorder dump shows the eff= flag (SIGUSR2 surface)
    assert "eff=" in s.obs.recorder.dump()


@pytest.mark.parametrize("mesh", [2, 8])
def test_model_efficiency_populated_on_mesh(mesh):
    s = _scheduler(n_nodes=8, parallel=ParallelConfig(mesh=mesh))
    _drive(s, n_pods=8, cycles=2, prefix=f"m{mesh}-")
    recs = s.obs.recorder.records()
    assert recs, "mesh cycles must record"
    for rec in recs:
        assert rec.mesh == mesh
        assert rec.model_efficiency >= 0, (
            f"efficiency must populate on mesh={mesh} cycles")
    ent = s.obs.ledger.snapshot()["entries"][-1]
    assert ent["mesh"] == mesh and ent["model_efficiency"] >= 0


def test_mesh_prediction_parity_with_costmodel():
    """The runtime's mesh prediction must fold in EXACTLY
    parallel/costmodel.model_efficiency — one model, bench and runtime
    agreeing by construction."""
    from kubernetes_tpu.parallel.costmodel import model_efficiency

    m = CycleCostModel()
    assert m.record_anchor("full", 256, 1024, 0, 0.010, rounds=1)
    single, _ = m.predict(256, 1024, 0, "full", rounds=1)
    meshed, _ = m.predict(256, 1024, 8, "full", rounds=1)
    eff = model_efficiency(8, 256, 1024)
    assert meshed == pytest.approx(single / 8 / eff, rel=1e-9)
    # and the unified helper itself: 1.0 single-device, the collective
    # model's figure beyond
    assert model_efficiency(1, 30000, 5000) == 1.0
    assert 0 < model_efficiency(8, 30000, 5000) <= 1.0


def test_bench_mesh_scale_delegates_to_costmodel():
    """The satellite pin: scripts/bench_mesh_scale.py no longer carries
    its own model_efficiency — it delegates to the one implementation
    the ledger predicts with."""
    import os

    src_path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                            "bench_mesh_scale.py")
    with open(src_path) as f:
        src = f.read()
    assert "from kubernetes_tpu.parallel.costmodel import model_efficiency" \
        in src
    assert "CollectiveCostModel(" not in src, (
        "bench_mesh_scale must not rebuild the model locally")


def test_best_rate_anchor_never_rebases_upward():
    m = CycleCostModel()
    assert m.record_anchor("full", 64, 64, 0, 0.010)
    # a slower observation (same shape, more seconds) must NOT replace
    assert not m.record_anchor("full", 64, 64, 0, 0.050)
    # a faster one must
    assert m.record_anchor("full", 64, 64, 0, 0.004)
    pred, basis = m.predict(64, 64, 0, "full")
    assert pred == pytest.approx(0.004)
    assert basis == "calibrated"


def test_restricted_scope_scales_with_batch_not_nodes():
    m = CycleCostModel()
    m.record_anchor("restricted", 64, 1024, 0, 0.002)
    small, _ = m.predict(64, 1024, 0, "restricted")
    grown_nodes, _ = m.predict(64, 8192, 0, "restricted")
    grown_pods, _ = m.predict(256, 1024, 0, "restricted")
    # the candidate bucket is a fixed static shape: node-axis growth is
    # free, batch growth is linear
    assert grown_nodes == pytest.approx(small)
    assert grown_pods == pytest.approx(small * 4)


# ---------------------------------------------------------------------------
# SLO watchdog: burn, pressure, recovery (fake clock throughout)
# ---------------------------------------------------------------------------


def _ledger_cfg(**kw):
    base = dict(e2e_p99_objective_s=0.05, fast_window_s=60.0,
                slow_window_s=600.0, burn_threshold=1.0)
    base.update(kw)
    return LedgerConfig(**base)


def _feed_cycle(s, clk, cycle, latencies, solve_s=0.001):
    obs = s.obs
    obs.begin_cycle(cycle)
    obs.note_batch_shape("P8xN8")
    with obs.span("solve:batch"):
        clk.advance(solve_s)
    res = CycleResult(
        attempted=max(len(latencies), 1), scheduled=len(latencies),
        rounds=1, solver_tier="batch",
        e2e_latency_s={f"e{cycle}-{i}": v
                       for i, v in enumerate(latencies)})
    return obs.end_cycle(res)


def test_latency_regression_trips_fast_burn_and_recovers():
    clk = FakeClock()
    events = []
    s = Scheduler(
        enable_preemption=False, clock=clk,
        observability=ObservabilityConfig(ledger=_ledger_cfg()),
        event_sink=lambda reason, obj, msg: events.append(
            (reason, obj.key(), msg)),
    )
    s.on_node_add(make_node("n0", cpu_milli=4000))
    # queue depth for the pressure probe (pod parked, never scheduled
    # in this test — we drive the obs layer directly)
    s.queue.add(make_pod("parked", cpu_milli=100))
    assert s.backend_pressure() == 1.0

    # healthy traffic: latencies under the 50ms objective
    for c in range(3):
        rec = _feed_cycle(s, clk, c, [0.01, 0.02])
        clk.advance(1.0)
        assert rec.slo == ""
    assert not s.obs.ledger.watchdog.burning()

    # regression: every pod over the objective -> burn rate 100x budget
    rec = _feed_cycle(s, clk, 10, [0.2, 0.3, 0.4])
    assert rec.slo == "e2e_p99"
    assert s.obs.ledger.watchdog.burning()
    burn_events = [e for e in events if e[0] == "SchedulerSLOBurn"]
    assert burn_events and "e2e_p99" in burn_events[0][1]
    # sustained burn reads degraded: APF sheds earlier at the same depth
    assert s.is_degraded()
    assert s.backend_pressure(degraded_factor=4.0) == 4.0
    # the flight record carries the SLO state (SIGUSR2 surface)
    assert "slo=e2e_p99" in s.obs.recorder.dump()
    # the metric exports both windows
    assert s.metrics.slo_burn_rate.value(
        objective="e2e_p99", window="fast") >= 1.0

    # recovery: the violating samples age out of the fast window
    clk.advance(120.0)
    rec = _feed_cycle(s, clk, 20, [0.01, 0.01])
    assert rec.slo == ""
    assert not s.obs.ledger.watchdog.burning()
    assert [e for e in events if e[0] == "SchedulerSLORecovered"]
    assert not s.is_degraded()
    assert s.backend_pressure() == 1.0


def test_burn_recovers_while_idle_without_eventful_cycles():
    """A burn must not freeze when traffic stops: observe_cycle only
    runs on eventful cycles, so recovery rides the idle tick and the
    pressure probe's lazy re-evaluation instead."""
    clk = FakeClock()
    events = []
    s = Scheduler(
        enable_preemption=False, clock=clk,
        observability=ObservabilityConfig(ledger=_ledger_cfg()),
        event_sink=lambda reason, obj, msg: events.append(reason),
    )
    s.queue.add(make_pod("parked", cpu_milli=100))
    _feed_cycle(s, clk, 1, [0.5, 0.5])
    assert s.obs.ledger.watchdog.burning()
    assert s.backend_pressure(degraded_factor=4.0) == 4.0
    # the queue drains; NO eventful cycle ever runs again — the idle
    # tick alone must clear the burn once the fast window empties
    clk.advance(120.0)
    s.idle_tick()
    assert not s.obs.ledger.watchdog.burning()
    assert "SchedulerSLORecovered" in events
    assert s.backend_pressure(degraded_factor=4.0) == 1.0
    # and the pressure probe alone also recovers (request threads read
    # it without any scheduler-loop help)
    _feed_cycle(s, clk, 2, [0.5, 0.5])
    assert s.obs.ledger.watchdog.burning()
    clk.advance(120.0)
    assert s.backend_pressure(degraded_factor=4.0) == 1.0
    assert not s.obs.ledger.watchdog.burning()


def test_burn_never_trips_on_stale_window_drainage():
    """The soak's clean-window flap: after a loud phase, the fast
    window drains oldest-first, so the violating FRACTION of what
    remains can cross the threshold with zero new traffic (the good
    bulk expires before a bad tail). The clock-driven evaluations
    (idle tick, pressure probe, sample-free cycles) are recovery-only:
    a burn may only START on fresh evidence."""
    clk = FakeClock()
    wd = SLOWatchdog(_ledger_cfg(), clock=clk)
    good, bad = 0.01, 0.2
    # chaos phase: legitimately trips on fresh evidence, then recovers
    # once the violating bulk leaves the 60s fast window
    wd.observe_cycle(0.0, [good] * 50 + [bad] * 50, 0.0, "full")
    assert wd.burning() and wd.burns.get("e2e_p99") == 1
    wd.observe_cycle(30.0, [good] * 200, 0.0, "full")
    wd.observe_cycle(90.0, [good] * 200, 0.0, "full")
    wd.observe_cycle(95.0, [good, bad], 0.0, "full")
    assert not wd.burning()
    # clean phase: traffic stops. Past t=150 the t=90 good bulk has
    # expired from the fast window, whose survivors are 1 bad of 2 —
    # and the slow window still holds the whole chaos phase, so BOTH
    # windows read over threshold on stale samples alone.
    for t in range(96, 152, 5):
        wd.evaluate(float(t), allow_trip=False)  # the idle-tick path
        assert not wd.burning(), f"tripped on stale drainage at t={t}"
    assert wd.burns.get("e2e_p99") == 1
    # an eventful cycle that folds NOTHING in is clock, not evidence
    wd.observe_cycle(152.0, [], 0.0, "full")
    assert not wd.burning()
    # positive control: the window STATE is trip-capable right now
    # (fast = the bad tail alone, slow = the whole chaos phase) — only
    # the evidence-freshness gate held the flap back
    wd.evaluate(153.0)
    assert wd.burning() and wd.burns.get("e2e_p99") == 2


def test_efficiency_gauge_freshness_on_solve_free_cycle():
    """A solve-free eventful cycle writes the -1 sentinel instead of
    leaving a stale verdict on the wire (gauge freshness rule)."""
    from kubernetes_tpu.metrics import SchedulerMetrics
    from kubernetes_tpu.obs.recorder import CycleRecord

    metrics = SchedulerMetrics()
    ledger = PerfLedger(LedgerConfig(), metrics=metrics)
    ledger.observe_cycle(CycleRecord(
        cycle=1, batch_shape="P8xN8", tier="batch", elapsed_s=0.02,
        spans={"solve:batch": 0.01}))
    assert metrics.cycle_model_efficiency.value() >= 0
    ledger.observe_cycle(CycleRecord(
        cycle=2, batch_shape="", elapsed_s=0.001, spans={}))
    assert metrics.cycle_model_efficiency.value() == -1.0
    assert metrics.cycle_modeled_cost.value() == -1.0


def test_self_anchored_cycle_labeled_anchor_basis():
    s = _scheduler()
    _drive(s, n_pods=8, cycles=1)
    entries = s.obs.ledger.snapshot()["entries"]
    # the cycle that IS the reference says so
    assert entries[0]["model_basis"] == "anchor"
    # best-rate-wins means a faster-than-ever cycle re-bases and is
    # labeled "anchor" again — so pin an unbeatable speed-of-light
    # anchor: the next cycles CANNOT re-base and must be judged
    # against it, which is what "calibrated" means
    s.obs.ledger.model.record_anchor("full", 8, 4, 0, 1e-9)
    _drive(s, n_pods=8, cycles=2)
    entries = s.obs.ledger.snapshot()["entries"]
    assert all(e["model_basis"] == "calibrated" for e in entries[1:])


def test_cost_drift_objective_burns_on_sustained_slowdown():
    clk = FakeClock()
    s = Scheduler(
        enable_preemption=False, clock=clk,
        observability=ObservabilityConfig(ledger=_ledger_cfg(
            e2e_p99_objective_s=0.0, cost_drift_ratio=2.0,
            baseline_decay=0.01)),
    )
    # build the baseline at ~1ms solves
    for c in range(5):
        _feed_cycle(s, clk, c, [], solve_s=0.001)
        clk.advance(1.0)
    assert not s.obs.ledger.watchdog.burning()
    # cycles now cost 10x the rolling baseline -> drift violations
    burned = False
    for c in range(10, 16):
        rec = _feed_cycle(s, clk, c, [], solve_s=0.010)
        clk.advance(1.0)
        burned = burned or rec.slo == "cost_drift"
    assert burned, "sustained cost drift must trip the watchdog"


def test_engage_pressure_false_keeps_degraded_out():
    clk = FakeClock()
    s = Scheduler(
        enable_preemption=False, clock=clk,
        observability=ObservabilityConfig(ledger=_ledger_cfg(
            engage_pressure=False)),
    )
    _feed_cycle(s, clk, 1, [0.5, 0.5])
    assert s.obs.ledger.watchdog.burning()
    assert not s.is_degraded(), (
        "engage_pressure=false must keep the burn out of APF")


# ---------------------------------------------------------------------------
# /debug/ledger + config round-trips + bench_compare contract
# ---------------------------------------------------------------------------


def test_debug_ledger_endpoint():
    from kubernetes_tpu.server import serve_scheduler

    s = _scheduler()
    _drive(s, n_pods=4, cycles=2)
    srv = serve_scheduler(s, port=0)
    try:
        host, port = srv.server_address[:2]
        body = urllib.request.urlopen(
            f"http://{host}:{port}/debug/ledger", timeout=5).read()
        doc = json.loads(body)
        assert doc["retained"] == 2
        assert doc["entries"][-1]["model_efficiency"] >= 0
        assert "anchors" in doc["model"]
        assert "burns" in doc["slo"]
    finally:
        srv.shutdown()


def test_ledger_config_native_and_v1alpha1_round_trip():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode
    from kubernetes_tpu.cli import decode_config, validate_config

    # native nested block, strict unknown-field rejection
    cfg = decode_config({"observability": {"ledger": {
        "e2e_p99_objective_s": 0.25, "cost_drift_ratio": 2.0,
        "fast_window_s": 30.0}}})
    lg = cfg.observability.ledger
    assert (lg.e2e_p99_objective_s, lg.cost_drift_ratio,
            lg.fast_window_s) == (0.25, 2.0, 30.0)
    from kubernetes_tpu.cli import ConfigError
    with pytest.raises(ConfigError):
        decode_config({"observability": {"ledger": {"bogus": 1}}})

    # v1alpha1: camelCase + duration strings, encode(decode) is stable
    doc = {"apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
           "kind": "KubeSchedulerConfiguration",
           "observability": {"ledger": {"e2eP99Objective": "250ms",
                                        "costDriftRatio": 2.0,
                                        "fastWindow": "30s"}}}
    internal = decode(doc)
    vlg = internal.observability.ledger
    assert vlg.e2e_p99_objective_s == pytest.approx(0.25)
    assert vlg.fast_window_s == pytest.approx(30.0)
    assert vlg.slow_window_s == pytest.approx(600.0)  # default
    again = decode(encode(internal))
    assert again.observability.ledger == vlg

    # validate_config gates the block with field paths
    import dataclasses
    bad = dataclasses.replace(
        internal, observability=dataclasses.replace(
            internal.observability, ledger=dataclasses.replace(
                vlg, baseline_decay=5.0, fast_window_s=-1.0,
                history=0)))
    errs = validate_config(bad)
    assert any("ledger.baselineDecay" in e for e in errs)
    assert any("ledger.fastWindow" in e for e in errs)
    assert any("ledger.history" in e for e in errs)


def _load_bench_compare():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    return bc


def _churn_record(eff_p50=0.9, burns=0, shares=None, with_ledger=True):
    led = {"cycles": 50,
           "model_efficiency": {"n": 50, "p50": eff_p50, "p99": 1.0},
           "phase_share": shares if shares is not None
           else {"snapshot": 0.2, "solve": 0.5, "bind": 0.1},
           "slo": {"burns": burns, "burning": False}}
    arm = {"p50_s": 0.01, "p99_s": 0.05, "ops_per_sec": 500.0,
           "jax": {"retraces": 0}}
    if with_ledger:
        arm["ledger"] = led
    return {"name": "churn", "arms": {"serving": dict(arm),
                                      "overload": dict(arm)},
            "errors": []}


def test_bench_compare_ledger_gate_contract(tmp_path):
    bc = _load_bench_compare()
    # registered in --list-gates
    assert any(n == "ledger" for n, _, _ in bc.GATE_FAMILIES)

    # clean record passes
    v = bc.compare_ledger(_churn_record())
    assert v["regressions"] == [] and v["checks"]

    # efficiency collapse fails the floor
    v = bc.compare_ledger(_churn_record(eff_p50=0.05))
    assert any(r["check"] == "ledger.serving.model_efficiency_p50"
               for r in v["regressions"])

    # burns on a CLEAN arm fail; the overload arm's burns are tolerated
    v = bc.compare_ledger(_churn_record(burns=2))
    assert any(r["check"] == "ledger.serving.slo_burns"
               for r in v["regressions"])
    assert not any("overload.slo_burns" in r["check"]
                   for r in v["regressions"])

    # phase-share double counting fails sanity
    v = bc.compare_ledger(_churn_record(
        shares={"solve": 1.0, "snapshot": 0.9}))
    assert any(r["check"].endswith("phase_share_sum")
               for r in v["regressions"])

    # absence-tolerant: a pre-ledger record warns, never fails
    v = bc.compare_ledger(_churn_record(with_ledger=False))
    assert v["regressions"] == [] and v["warnings"]

    # end to end through main(): one churn record on disk is enough for
    # the absolute ledger gates
    p = tmp_path / "churn_r01.json"
    p.write_text(json.dumps(_churn_record()))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    p.write_text(json.dumps(_churn_record(eff_p50=0.01)))
    assert bc.main(["--dir", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# budgets: overhead < 2% of a contended cycle, zero retraces, lint
# ---------------------------------------------------------------------------


def test_ledger_overhead_under_budget_on_contended_cycle():
    """The explain-overhead-style budget: the ledger's whole per-cycle
    cost (observe_cycle — grouping, prediction, watchdog, metrics) must
    stay under 2% of a CONTENDED cycle's measured wall time."""
    s = _scheduler(n_nodes=8)
    for i in range(192):
        s.on_pod_add(make_pod(f"w{i}", cpu_milli=50))
    s.schedule_cycle()  # cold (compiles)
    for i in range(192):
        s.on_pod_add(make_pod(f"x{i}", cpu_milli=50))
    res = s.schedule_cycle()  # warm, contended
    rec = s.obs.recorder.records()[-1]
    assert rec.elapsed_s > 0

    fresh = PerfLedger(LedgerConfig(), metrics=s.metrics,
                       clock=time.monotonic)
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        fresh.observe_cycle(rec, res)
    per_observe = (time.perf_counter() - t0) / n
    overhead = per_observe / rec.elapsed_s
    assert overhead < 0.02, (
        f"ledger costs {overhead:.2%} of a contended cycle "
        f"({per_observe*1e6:.0f}us vs {rec.elapsed_s*1e3:.1f}ms)")


def test_zero_new_retraces_with_ledger_on():
    s = _scheduler()
    _drive(s, n_pods=8, cycles=4)
    assert s.obs.jax.retrace_total() == 0, (
        "the ledger must not perturb the solve signatures")


def test_warmup_anchors_the_cost_model():
    from kubernetes_tpu.config import WarmupConfig

    s = _scheduler(warmup=WarmupConfig(enabled=True, pod_buckets=(8,)))
    compiled = s.warmup(sample_pods=[make_pod("w", cpu_milli=50)])
    assert compiled >= 1
    anchors = s.obs.ledger.model.snapshot()["anchors"]
    assert "full" in anchors, "warmup must install the rate anchor"
    assert anchors["full"]["solve_s"] > 0
    # the first live cycle then predicts from the warmup anchor
    r = _drive(s, n_pods=4, cycles=1)[0]
    assert r.model_efficiency >= 0


def test_ledger_module_lints_clean():
    """graftlint over obs/ledger.py: parse + the device-discipline
    rules (R2 host syncs, R3 jit-in-loop, R7 undeclared readbacks, R8
    sharded gathers) — the module is host code by construction, so its
    real jit roots (none) must stay empty AND nothing may smell like a
    device boundary."""
    import kubernetes_tpu.obs.ledger as ledger_mod
    from kubernetes_tpu.testing import lint_clean

    lint_clean(ledger_mod, rules=("R2", "R3", "R7", "R8"), jit_all=False)


def test_chrome_trace_carries_efficiency_counter_track():
    s = _scheduler()
    _drive(s, n_pods=4, cycles=2)
    doc = s.obs.chrome_trace()
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert counters, "the ledger must stamp a Perfetto counter track"
    assert counters[0]["name"] == "model_efficiency"
    assert "eff" in counters[0]["args"]
