"""Shim: the sequential reference oracle moved into the package
(``kubernetes_tpu.seqref``) because production code needs it too — the
preemption victim checks and bench.py's sequential-baseline denominator.
Tests keep importing ``pyref``."""

from kubernetes_tpu.seqref import *  # noqa: F401,F403
from kubernetes_tpu.seqref import _match_expressions, _term_matches_pod, _same_topology, _pod_has_affinity, _nonzero_used  # noqa: F401
