"""Differential fuzz campaign (VERDICT r2 #7; SURVEY §4 implication (a)):
randomized clusters mixing topology + volumes + priorities, the FULL
driver (Scheduler, greedy solver) vs the sequential oracle
(seqref.serial_schedule_full) end-to-end, many seeds; plus the preemption
scenario tables ported from core/generic_scheduler_test.go:1198
(TestPickOneNodeForPreemption) run against our victim-selection + 6-tier
pick.

Seed count: FUZZ_SEEDS env (default 200). All seeds share one label/zone
vocabulary and fixed-size pod groups so interner universes land in the
same power-of-two buckets — one jit compile serves the whole campaign.
"""

import os
import random

import numpy as np
import pytest

import pyref
from kubernetes_tpu.api.types import (
    OP_EXISTS,
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    Requirement,
    TopologySpreadConstraint,
)
from kubernetes_tpu.models.cluster import make_pv_pods
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.volumes import VolumeState

ZONE = "failure-domain.beta.kubernetes.io/zone"
HOSTNAME = "kubernetes.io/hostname"

N_SEEDS = int(os.environ.get("FUZZ_SEEDS", 200))


class FakeClock:
    t = 0.0

    def __call__(self):
        return self.t


def _term(key, labels):
    return PodAffinityTerm(
        label_selector=LabelSelector(match_labels=dict(labels)),
        topology_key=key,
    )


def fuzz_cluster(rng: random.Random):
    """One randomized cluster drawing every constraint family from a FIXED
    vocabulary (stable interner buckets across seeds)."""
    n_nodes = 8
    apps = ["web", "db", "cache", "batch"]
    nodes = [
        make_node(
            f"n{i}",
            cpu_milli=rng.choice([2000, 4000, 8000]),
            memory=rng.choice([4 * 2**30, 16 * 2**30]),
            pods=rng.choice([6, 110]),
            labels={"disk": rng.choice(["ssd", "hdd"])},
            zone=f"z{i % 3}",
        )
        for i in range(n_nodes)
    ]
    existing = []
    for i in range(10):
        app = rng.choice(apps)
        p = make_pod(
            f"old{i}",
            cpu_milli=rng.choice([100, 500]),
            memory=2**28,
            labels={"app": app},
            node_name=f"n{rng.randrange(n_nodes)}",
        )
        if rng.random() < 0.3:
            p.affinity = Affinity(
                pod_anti_affinity_required=(_term(ZONE, {"app": app}),)
            )
        existing.append(p)

    pending = []
    for i in range(6):  # base pods with priorities
        pending.append(
            make_pod(
                f"base{i}",
                cpu_milli=rng.choice([0, 100, 1000]),
                memory=rng.choice([0, 2**28]),
                labels={"app": rng.choice(apps)},
                priority=rng.choice([0, 0, 10, 100]),
                node_selector=(
                    {"disk": rng.choice(["ssd", "hdd"])}
                    if rng.random() < 0.4
                    else None
                ),
            )
        )
    for i in range(4):  # pod-affinity / anti-affinity pods
        app = rng.choice(apps)
        kind = rng.random()
        aff = (
            Affinity(pod_affinity_required=(_term(ZONE, {"app": app}),))
            if kind < 0.5
            else Affinity(
                pod_anti_affinity_required=(
                    _term(rng.choice([ZONE, HOSTNAME]), {"app": app}),
                )
            )
        )
        pending.append(
            make_pod(
                f"aff{i}",
                cpu_milli=100,
                memory=2**27,
                labels={"app": app},
                affinity=aff,
                priority=rng.choice([0, 10]),
            )
        )
    for i in range(3):  # topology-spread pods
        app = rng.choice(apps)
        pending.append(
            make_pod(
                f"spr{i}",
                cpu_milli=100,
                memory=2**27,
                labels={"app": app},
                topology_spread=(
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=ZONE,
                        when_unsatisfiable=(
                            "DoNotSchedule"
                            if rng.random() < 0.5
                            else "ScheduleAnyway"
                        ),
                        label_selector=LabelSelector(
                            match_labels={"app": app}
                        ),
                    ),
                ),
            )
        )
    # volume pods: pre-bound PVC/PV pairs (gce-pd attach limits + zones)
    vol_pods, pvcs, pvs = make_pv_pods(3, kind="gce-pd", name_prefix="fz-pv")
    pending.extend(vol_pods)
    rng.shuffle(pending)
    return nodes, existing, pending, pvcs, pvs


def test_fuzz_driver_vs_full_oracle():
    """End-to-end: Scheduler (greedy solver, no preemption) must place
    every pod exactly where the sequential oracle does."""
    mismatches = []
    for seed in range(N_SEEDS):
        rng = random.Random(9000 + seed)
        nodes, existing, pending, pvcs, pvs = fuzz_cluster(rng)
        s = Scheduler(solver="greedy", clock=FakeClock(),
                      enable_preemption=False)
        s.set_volume_state(pvcs, pvs, ())
        for nd in nodes:
            s.on_node_add(nd)
        for p in existing:
            s.on_pod_add(p)
        for p in pending:
            s.on_pod_add(p)
        res = s.schedule_cycle()

        vol_state = VolumeState(
            pvcs={(c.namespace, c.name): c for c in pvcs},
            pvs={v.name: v for v in pvs},
        )
        want = pyref.serial_schedule_full(pending, nodes, existing, vol_state)
        for i, pod in enumerate(pending):
            got = res.assignments.get(pod.key())
            exp = nodes[want[i][0]].name if want[i][0] >= 0 else None
            if got != exp:
                mismatches.append(
                    f"seed {seed}: {pod.name}: driver={got} oracle={exp}\n"
                    f"  pod={pod}"
                )
                break  # first divergence per seed is enough
    assert not mismatches, "\n".join(mismatches[:5]) + (
        f"\n... {len(mismatches)} seed(s) diverged of {N_SEEDS}"
    )


# ---------------------------------------------------------------------------
# TestPickOneNodeForPreemption tables (generic_scheduler_test.go:1198-1396)
# ported scenario-for-scenario: nodes are 5x the default request (500m /
# 1000MB), containers small=1x/medium=2x/large=3x/veryLarge=5x the default
# (100m / 200MB), and the expected machine is the reference's expectation.
# ---------------------------------------------------------------------------

MILLI = 100
MEM = 200 * 1024 * 1024
NEG, LOW, MID, HIGH, VERY_HIGH = -100, 0, 100, 1000, 10000


def _n(name):
    return make_node(name, cpu_milli=5 * MILLI, memory=5 * MEM, pods=110)


def _p(name, node, size, pri, start=0.0):
    return make_pod(name, cpu_milli=size * MILLI, memory=size * MEM,
                    node_name=node, priority=pri, start_time=start)


def _pick(preemptor_size, preemptor_pri, node_names, victims):
    """Run selectVictimsOnNode over each node then pickOneNodeForPreemption
    — the exact flow the reference table drives (test body :1390-1396)."""
    from kubernetes_tpu.preemption import pick_one_node, select_victims_on_node

    nodes = [_n(n) for n in node_names]
    node_pods = {n: [] for n in node_names}
    for v in victims:
        node_pods[v.node_name].append(v)
    preemptor = make_pod("preemptor", cpu_milli=preemptor_size * MILLI,
                         memory=preemptor_size * MEM, priority=preemptor_pri)
    candidates = {}
    for nd in nodes:
        r = select_victims_on_node(preemptor, nd, nodes, node_pods)
        if r is not None:
            candidates[nd.name] = r
    return pick_one_node(candidates)


def test_pick_no_node_needs_preemption():
    got = _pick(3, HIGH, ["machine1"], [_p("m1.1", "machine1", 1, MID)])
    assert got == "machine1"


def test_pick_fits_on_both_when_preempted():
    got = _pick(3, HIGH, ["machine1", "machine2"], [
        _p("m1.1", "machine1", 3, MID), _p("m2.1", "machine2", 3, MID)])
    assert got in ("machine1", "machine2")


def test_pick_prefers_no_preemption_node():
    got = _pick(3, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 3, MID), _p("m2.1", "machine2", 3, MID)])
    assert got == "machine3"


def test_pick_min_highest_priority():
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 2, MID), _p("m1.2", "machine1", 3, MID),
        _p("m2.1", "machine2", 2, MID), _p("m2.2", "machine2", 2, LOW),
        _p("m3.1", "machine3", 2, LOW), _p("m3.2", "machine3", 2, LOW)])
    assert got == "machine3"


def test_pick_min_priority_sum_when_highest_equal():
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 2, MID), _p("m1.2", "machine1", 3, MID),
        _p("m2.1", "machine2", 3, MID), _p("m2.2", "machine2", 2, LOW),
        _p("m3.1", "machine3", 2, MID), _p("m3.2", "machine3", 2, MID)])
    assert got == "machine2"


def test_pick_min_pod_count_when_sums_equal():
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 1, MID), _p("m1.2", "machine1", 1, NEG),
        _p("m1.3", "machine1", 1, MID), _p("m1.4", "machine1", 1, NEG),
        _p("m2.1", "machine2", 3, MID), _p("m2.2", "machine2", 2, NEG),
        _p("m3.1", "machine3", 2, MID), _p("m3.2", "machine3", 1, NEG),
        _p("m3.3", "machine3", 1, LOW)])
    assert got == "machine2"


def test_pick_sum_of_adjusted_priorities():
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 1, MID), _p("m1.2", "machine1", 1, NEG),
        _p("m1.3", "machine1", 1, NEG),
        _p("m2.1", "machine2", 3, MID), _p("m2.2", "machine2", 2, NEG),
        _p("m3.1", "machine3", 2, MID), _p("m3.2", "machine3", 1, NEG),
        _p("m3.3", "machine3", 1, LOW)])
    assert got == "machine2"


def test_pick_non_overlapping_tiers():
    got = _pick(5, VERY_HIGH,
                ["machine1", "machine2", "machine3", "machine4"], [
        _p("m1.1", "machine1", 1, MID), _p("m1.2", "machine1", 1, LOW),
        _p("m1.3", "machine1", 1, LOW),
        _p("m2.1", "machine2", 3, HIGH),
        _p("m3.1", "machine3", 2, MID), _p("m3.2", "machine3", 1, LOW),
        _p("m3.3", "machine3", 1, LOW), _p("m3.4", "machine3", 2, LOW),
        _p("m4.1", "machine4", 2, MID), _p("m4.2", "machine4", 1, MID),
        _p("m4.3", "machine4", 1, MID), _p("m4.4", "machine4", 1, NEG)])
    assert got == "machine1"


def test_pick_latest_start_time_per_machine():
    d3, d4, d2 = 103.0, 104.0, 102.0  # relative start days
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 2, MID, d3), _p("m1.2", "machine1", 2, MID, d3),
        _p("m2.1", "machine2", 2, MID, d4), _p("m2.2", "machine2", 2, MID, d4),
        _p("m3.1", "machine3", 2, MID, d2), _p("m3.2", "machine3", 2, MID, d2)])
    assert got == "machine2"


def test_pick_latest_start_time_all_distinct():
    d = {k: 100.0 + k for k in range(2, 8)}
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 2, MID, d[5]), _p("m1.2", "machine1", 2, MID, d[3]),
        _p("m2.1", "machine2", 2, MID, d[6]), _p("m2.2", "machine2", 2, MID, d[2]),
        _p("m3.1", "machine3", 2, MID, d[4]), _p("m3.2", "machine3", 2, MID, d[7])])
    assert got == "machine3"


def test_pick_mixed_priority_latest_start():
    d = {k: 100.0 + k for k in range(2, 8)}
    got = _pick(5, HIGH, ["machine1", "machine2", "machine3"], [
        _p("m1.1", "machine1", 2, LOW, d[5]), _p("m1.2", "machine1", 2, MID, d[3]),
        _p("m2.1", "machine2", 2, MID, d[7]), _p("m2.2", "machine2", 2, LOW, d[2]),
        _p("m3.1", "machine3", 2, LOW, d[4]), _p("m3.2", "machine3", 2, MID, d[6])])
    assert got == "machine2"


# ---------------------------------------------------------------------------
# TestPreempt tables (generic_scheduler_test.go:1525-1793) — end-to-end
# through the DRIVER: the preemptor fails its cycle, preemption evicts the
# expected victims and nominates the expected node, and the preemptor lands
# there the next cycle.
# ---------------------------------------------------------------------------


def _driver_preempt(nodes, existing, preemptor, **kw):
    clk = FakeClock()
    deleted = []
    s = Scheduler(clock=clk, victim_deleter=lambda v: deleted.append(v.name),
                  **kw)
    for nd in nodes:
        s.on_node_add(nd)
    for p in existing:
        s.on_pod_add(p)
    s.on_pod_add(preemptor)
    res = s.schedule_cycle()
    return s, res, deleted


def test_preempt_basic_logic():
    """'basic preemption logic': machine1's two small low-pri pods are the
    cheapest eviction; machine2's high-pri pod is untouchable."""
    nodes = [_n(f"machine{i}") for i in (1, 2, 3)]
    existing = [
        _p("m1.1", "machine1", 1, LOW), _p("m1.2", "machine1", 1, LOW),
        _p("m2.1", "machine2", 3, HIGH),
        _p("m3.1", "machine3", 2, MID),
    ]
    preemptor = make_pod("pod1", cpu_milli=5 * MILLI, memory=5 * MEM,
                         priority=HIGH)
    s, res, deleted = _driver_preempt(nodes, existing, preemptor)
    assert res.nominations.get("default/pod1") == "machine1"
    assert sorted(deleted) == ["m1.1", "m1.2"]


def test_preempt_prefers_node_needing_none():
    """'One node doesn't need any preemption': empty machine3 takes the pod
    without any eviction."""
    nodes = [_n(f"machine{i}") for i in (1, 2, 3)]
    existing = [
        _p("m1.1", "machine1", 1, LOW), _p("m1.2", "machine1", 1, LOW),
        _p("m2.1", "machine2", 3, HIGH),
    ]
    preemptor = make_pod("pod1", cpu_milli=5 * MILLI, memory=5 * MEM,
                         priority=HIGH)
    s, res, deleted = _driver_preempt(nodes, existing, preemptor)
    assert res.assignments.get("default/pod1") == "machine3"
    assert deleted == [] and res.preempted == 0


def test_preempt_topology_spread_constraints():
    """'preemption for topology spread constraints': skew forces node-b;
    only low-pri pod-b1 is evictable."""
    mk = lambda name, zone: make_node(
        name, cpu_milli=64000, labels={
            "zone": zone, "kubernetes.io/hostname": name,
        })
    nodes = [mk("node-a", "zone1"), mk("node-b", "zone1"),
             mk("node-x", "zone2")]
    lab = {"foo": ""}
    existing = [
        make_pod("pod-a1", node_name="node-a", priority=HIGH, labels=lab),
        make_pod("pod-a2", node_name="node-a", priority=HIGH, labels=lab),
        make_pod("pod-b1", node_name="node-b", priority=LOW, labels=lab),
        make_pod("pod-x1", node_name="node-x", priority=HIGH, labels=lab),
        make_pod("pod-x2", node_name="node-x", priority=HIGH, labels=lab),
    ]
    sel = LabelSelector(match_expressions=(
        Requirement("foo", OP_EXISTS),
    ))
    preemptor = make_pod("p", priority=HIGH, labels=lab)
    preemptor.topology_spread = (
        TopologySpreadConstraint(max_skew=1, topology_key="zone",
                                 when_unsatisfiable="DoNotSchedule",
                                 label_selector=sel),
        TopologySpreadConstraint(max_skew=1,
                                 topology_key="kubernetes.io/hostname",
                                 when_unsatisfiable="DoNotSchedule",
                                 label_selector=sel),
    )
    s, res, deleted = _driver_preempt(nodes, existing, preemptor)
    assert res.nominations.get("default/p") == "node-b"
    assert deleted == ["pod-b1"]


def test_preempt_policy_never_blocks():
    """'no preempting in pod': PreemptNever + NonPreemptingPriority gate on
    -> no preemption anywhere."""
    nodes = [_n(f"machine{i}") for i in (1, 2, 3)]
    existing = [
        _p("m1.1", "machine1", 1, LOW), _p("m1.2", "machine1", 1, LOW),
        _p("m2.1", "machine2", 3, HIGH),
        _p("m3.1", "machine3", 2, MID),
    ]
    preemptor = make_pod("pod1", cpu_milli=5 * MILLI, memory=5 * MEM,
                         priority=HIGH)
    preemptor.preemption_policy = "Never"
    s, res, deleted = _driver_preempt(nodes, existing, preemptor,
                                      enable_non_preempting=True)
    assert res.nominations == {} and deleted == []
    # gate off -> the policy is ignored (alpha default, kube_features.go)
    s2, res2, deleted2 = _driver_preempt(
        nodes, existing,
        make_pod("pod1", cpu_milli=5 * MILLI, memory=5 * MEM, priority=HIGH),
        enable_non_preempting=False,
    )
    assert res2.nominations.get("default/pod1") == "machine1"


# ---------------------------------------------------------------------------
# TestNodesWherePreemptionMightHelp (generic_scheduler_test.go:1415) —
# reason-bit resolvability tables. Two documented adaptations:
# (a) nodes ABSENT from the failure map (the reference's always-expected
#     "machine4") are not candidates here: the batched driver only enters
#     preemption for pods that failed on EVERY node, so zero-bit rows are
#     padding, never feasible nodes;
# (b) our single MatchInterPodAffinity bit does not split the reference's
#     ErrPodAffinityRulesNotMatch (pod's OWN affinity rules, unresolvable)
#     from ErrPodAffinityNotMatch (resolvable) — we treat both as
#     resolvable, a conservative superset whose extra candidates victim
#     selection then rejects.
# ---------------------------------------------------------------------------


def _bits(*names):
    from kubernetes_tpu.ops.predicates import BIT

    out = 0
    for n in names:
        out |= 1 << BIT[n]
    return out


def _might_help(bits_by_node):
    from kubernetes_tpu.preemption import nodes_where_preemption_might_help

    return set(nodes_where_preemption_might_help(bits_by_node))


def test_preemption_help_no_node_attempted():
    assert _might_help({
        "machine1": _bits("PodMatchNodeSelector"),
        "machine2": _bits("PodFitsHost"),
        "machine3": _bits("PodToleratesNodeTaints"),
        "machine4": _bits("CheckNodeUnschedulable"),
    }) == set()


def test_preemption_help_interpod_affinity_tried():
    assert _might_help({
        "machine1": _bits("MatchInterPodAffinity"),
        "machine2": _bits("PodFitsHost"),
        "machine3": _bits("CheckNodeUnschedulable"),
    }) == {"machine1"}


def test_preemption_help_mixed_predicates():
    assert _might_help({
        "machine1": _bits("PodMatchNodeSelector", "CheckNodeDiskPressure",
                          "PodFitsResources"),
        "machine2": _bits("PodFitsHost", "NoDiskConflict"),
        "machine3": _bits("PodFitsResources"),
    }) == {"machine3"}


def test_preemption_help_node_conditions_unresolvable():
    assert _might_help({
        "machine1": _bits("CheckNodeDiskPressure"),
        "machine2": _bits("CheckNodePIDPressure"),
        "machine3": _bits("CheckNodeMemoryPressure"),
        "machine4": _bits("CheckNodeCondition"),
    }) == set()


def test_preemption_help_volume_errors_unresolvable():
    assert _might_help({
        "machine1": _bits("NoVolumeZoneConflict"),
        "machine2": _bits("VolumeNodeConflict"),
        "machine3": _bits("VolumeBindConflict"),
    }) == set()


def test_preemption_help_topology_spread_tried():
    assert _might_help({
        "machine1": _bits("EvenPodsSpread"),
        "machine2": _bits("EvenPodsSpread", "PodFitsHost"),
    }) == {"machine1"}
