"""Grand tour: every round-4 subsystem composing in ONE cluster story.

A deployment behind a Service rolls to a new template while probed pods
gate endpoints and a PV-consuming pod waits on the binder controller; the
whole control plane is CHECKPOINTED mid-rollout, restored into a cold
process-equivalent hub, and the rollout must finish there; a CronJob
owner vanishes and the ownerRef graph collects two levels; the final
state is read back through the authenticated REST facade. Each feature
has focused tests elsewhere — this pins that they compose."""

import json
import http.client

from kubernetes_tpu.api.types import (
    PersistentVolume,
    PersistentVolumeClaim,
    PodVolume,
    ReadinessProbe,
    StorageClass,
)
from kubernetes_tpu.auth import Rule, RuleAuthorizer, TokenAuthenticator, UserInfo
from kubernetes_tpu.proxy import Service, ServicePort
from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.sim import CronJob, Deployment, HollowCluster
from kubernetes_tpu.testing import make_node, make_pod


def test_grand_tour_checkpoint_mid_rollout(tmp_path):
    hub = HollowCluster(seed=61, scheduler_kw={"enable_preemption": False})
    for i in range(8):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    d = Deployment("web", replicas=5, max_surge=1, max_unavailable=1)
    hub.add_deployment(d)
    hub.add_service(Service("websvc", selector={"deploy": "web"},
                            ports=(ServicePort(port=80),)))
    hub.add_cronjob(CronJob("tick", every_s=10, completions=2,
                            parallelism=1, duration_s=1e9))
    hub.add_storage_class(StorageClass("std"))
    hub.add_pv(PersistentVolume("pv0", kind="gce-pd", handle="h",
                                storage_class="std"))
    hub.add_pvc(PersistentVolumeClaim("c0", storage_class="std"))
    hub.create_pod(make_pod("vol-user", cpu_milli=100,
                            volumes=(PodVolume(pvc="c0"),)))
    hub.create_pod(make_pod(
        "probed", cpu_milli=100, labels={"deploy": "web"},
        readiness_probe=ReadinessProbe(initial_delay_s=5)))
    for _ in range(4):
        hub.step()

    # rollout starts; checkpoint taken MID-FLIGHT (both RSes populated)
    d.rollout(cpu_milli=200)
    for _ in range(2):
        hub.step()
    owners = [rs.name for rs in hub.replicasets.values()
              if rs.owner == "web"]
    assert len(owners) == 2, f"expected mid-rollout, got {owners}"
    path = str(tmp_path / "tour.ckpt")
    hub.save_checkpoint(path)

    cold = HollowCluster(seed=9, scheduler_kw={"enable_preemption": False})
    cold.restore_checkpoint(path)
    cold.check_consistency()
    d2 = cold.deployments["web"]
    assert d2.template_rev == 1  # rollout state survived

    # the restored control plane FINISHES the rollout
    for _ in range(12):
        cold.step()
    web = {k: p for k, p in cold.truth_pods.items()
           if p.labels.get("deploy") == "web" and k != "default/probed"}
    assert len(web) == 5 and all(p.node_name for p in web.values())
    assert all(p.requests.cpu_milli == 200 for p in web.values())
    assert len([rs for rs in cold.replicasets.values()
                if rs.owner == "web"]) == 1
    # PV-consumer bound through the binder controller lineage
    assert cold.pvcs["default/c0"].volume_name == "pv0"
    assert cold.truth_pods["default/vol-user"].node_name
    # probed pod serves once past its initialDelay
    ep = cold.endpoints["default/websvc"]
    assert "default/probed" in {a.pod_key for a in ep.ready}

    # ownerRef graph: CronJob raw-deleted -> Jobs and their pods collapse
    del cold.cronjobs["tick"]
    for _ in range(2):
        cold.step()
    assert not any(j.owner == "tick" for j in cold.jobs.values())
    cold.check_consistency()

    # read the final state through the authenticated facade
    authn = TokenAuthenticator({"t": UserInfo("ops")})
    authz = RuleAuthorizer([
        Rule(subjects=("ops",), verbs=("get", "list"),
             resources=("pods", "endpoints"))])
    rest = RestServer(cold, authn=authn, authz=authz)
    port = rest.serve()
    try:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", "/api/v1/pods",
                  headers={"Authorization": "Bearer t"})
        doc = json.loads(c.getresponse().read())
        c.close()
        assert doc["kind"] == "PodList"
        bound = [p for p in doc["items"] if p["spec"]["nodeName"]]
        assert len(bound) == len(cold.truth_pods)
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        c.request("GET", "/api/v1/pods")  # no token -> 401
        assert c.getresponse().status == 401
        c.close()
    finally:
        rest.close()
