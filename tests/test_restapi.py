"""REST registry tests — the apiserver facade over the hub
(kubernetes_tpu/restapi.py), exercised with a plain HTTP client the way
the reference's integration tier drives an in-process apiserver
(test/integration/util/util.go:42 StartApiserver)."""

import http.client
import json

from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.sim import HollowCluster


def start(hub):
    srv = RestServer(hub)
    port = srv.serve()
    return srv, port


def req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(method, path, json.dumps(body) if body is not None else None)
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, json.loads(data) if data else None


NODE = {
    "metadata": {"name": "n0", "labels": {"kubernetes.io/hostname": "n0"}},
    "status": {"allocatable": {"cpu": "4000m", "memory": "8589934592",
                               "pods": "110"}},
}


def make_pod_doc(name, cpu="100m"):
    return {
        "metadata": {"name": name},
        "spec": {"containers": [
            {"name": "main", "resources": {"requests": {"cpu": cpu}}}
        ]},
    }


def test_crud_and_list_resource_versions():
    hub = HollowCluster(seed=1, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        code, _ = req(port, "POST", "/api/v1/nodes", NODE)
        assert code == 201
        code, doc = req(port, "POST", "/api/v1/nodes", NODE)
        assert code == 409 and doc["reason"] == "AlreadyExists"
        code, doc = req(port, "GET", "/api/v1/nodes")
        assert code == 200 and doc["kind"] == "NodeList"
        assert len(doc["items"]) == 1
        assert int(doc["metadata"]["resourceVersion"]) >= 1

        code, doc = req(port, "POST", "/api/v1/namespaces/default/pods",
                        make_pod_doc("web"))
        assert code == 201
        assert doc["metadata"]["uid"]  # apiserver-assigned
        code, doc = req(port, "GET", "/api/v1/namespaces/default/pods/web")
        assert code == 200 and doc["metadata"]["name"] == "web"
        code, doc = req(port, "GET", "/api/v1/namespaces/other/pods/web")
        assert code == 404
        code, _ = req(port, "DELETE", "/api/v1/namespaces/default/pods/web")
        assert code == 200
        code, _ = req(port, "GET", "/api/v1/namespaces/default/pods/web")
        assert code == 404
    finally:
        srv.close()


def test_scheduler_binds_pods_created_via_rest():
    hub = HollowCluster(seed=2, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        for i in range(3):
            req(port, "POST", "/api/v1/namespaces/default/pods",
                make_pod_doc(f"w{i}"))
        hub.step()
        hub.settle()
        code, doc = req(port, "GET", "/api/v1/pods")
        assert code == 200 and len(doc["items"]) == 3
        assert all(it["spec"]["nodeName"] == "n0" for it in doc["items"])
        hub.check_consistency()
    finally:
        srv.close()


def test_binding_subresource_cas():
    hub = HollowCluster(seed=3, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("web"))
        code, _ = req(port, "POST",
                      "/api/v1/namespaces/default/pods/web/binding",
                      {"target": {"name": "n0"}})
        assert code == 201
        assert hub.truth_pods["default/web"].node_name == "n0"
        # already bound → Conflict (assignPod's already-assigned branch)
        code, doc = req(port, "POST",
                        "/api/v1/namespaces/default/pods/web/binding",
                        {"target": {"name": "n0"}})
        assert code == 409 and doc["reason"] == "Conflict"
        # recreated pod: binding with the OLD uid must hit the uid CAS
        req(port, "DELETE", "/api/v1/namespaces/default/pods/web")
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("web"))
        code, doc = req(port, "POST",
                        "/api/v1/namespaces/default/pods/web/binding",
                        {"target": {"name": "n0"},
                         "metadata": {"uid": "stale-uid"}})
        assert code == 409 and "uid changed" in doc["message"]
    finally:
        srv.close()


def test_put_node_resource_version_precondition():
    hub = HollowCluster(seed=4, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        code, doc = req(port, "GET", "/api/v1/nodes/n0")
        rv = doc["metadata"]["resourceVersion"]
        upd = dict(NODE)
        upd["metadata"] = {"name": "n0", "resourceVersion": rv,
                           "labels": {"tier": "gold"}}
        code, doc = req(port, "PUT", "/api/v1/nodes/n0", upd)
        assert code == 200
        assert hub.truth_nodes["n0"].labels.get("tier") == "gold"
        # stale rv → 409 (GuaranteedUpdate CAS, etcd3/store.go:236)
        code, doc = req(port, "PUT", "/api/v1/nodes/n0", upd)
        assert code == 409 and doc["reason"] == "Conflict"
    finally:
        srv.close()


def test_watch_stream_and_compaction_gone():
    hub = HollowCluster(seed=5, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        code, doc = req(port, "GET", "/api/v1/nodes")
        rv0 = int(doc["metadata"]["resourceVersion"])
        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("web"))
        hub.step()
        hub.settle()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", f"/api/v1/watch/pods?resourceVersion={rv0}")
        r = conn.getresponse()
        events = [json.loads(l) for l in r.read().splitlines() if l]
        conn.close()
        types = [e["type"] for e in events]
        assert types[0] == "ADDED"            # the create
        assert "MODIFIED" in types            # the bind
        assert all(e["object"]["metadata"]["resourceVersion"] for e in events)
        # node events never leak into the pod watch
        assert all("nodeName" in e["object"].get("spec", {}) for e in events)
        # compaction: watching an expired rv → 410 Gone, reason Expired
        hub.compact(hub._revision)
        code, doc = req(port, "GET",
                        f"/api/v1/watch/pods?resourceVersion={rv0}")
        assert code == 410 and doc["reason"] == "Expired"
    finally:
        srv.close()


def test_watch_bookmarks_advance_quiet_watchers_past_compaction():
    """allowWatchBookmarks (cacher.go bookmark events): a watcher whose
    selector matches NO traffic still advances its resourceVersion via
    the trailing BOOKMARK frame — so compacting the quiet interval does
    not 410 it into a relist. Without bookmarks the same watcher is
    expired."""
    hub = HollowCluster(seed=51, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)

    def watch(path):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", path)
        r = conn.getresponse()
        raw = r.read()
        conn.close()
        if r.status != 200:
            return r.status, json.loads(raw)
        return r.status, [json.loads(l) for l in raw.splitlines() if l]

    try:
        code, doc = req(port, "GET", "/api/v1/nodes")
        rv0 = int(doc["metadata"]["resourceVersion"])
        # traffic the selector will NOT match
        req(port, "POST", "/api/v1/nodes", NODE)
        for i in range(3):
            req(port, "POST", "/api/v1/namespaces/default/pods",
                make_pod_doc(f"web-{i}"))
        sel = "app%3Dnothing-matches"
        code, events = watch(
            f"/api/v1/watch/pods?resourceVersion={rv0}"
            f"&labelSelector={sel}&allowWatchBookmarks=true")
        assert code == 200
        assert [e["type"] for e in events] == ["BOOKMARK"]
        mark = int(events[-1]["object"]["metadata"]["resourceVersion"])
        assert mark > rv0
        hub.compact(mark)  # the quiet interval is compacted away
        # bookmark-anchored re-watch survives...
        code, events = watch(
            f"/api/v1/watch/pods?resourceVersion={mark}"
            f"&labelSelector={sel}&allowWatchBookmarks=true")
        assert code == 200
        # ...while the bookmark-less anchor is expired
        code, doc = watch(f"/api/v1/watch/pods?resourceVersion={rv0}")
        assert code == 410 and doc["reason"] == "Expired"
    finally:
        srv.close()


def test_admission_rejection_surfaces_as_403():
    hub = HollowCluster(seed=6, admission=True,
                        scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        # lifecycle/admission.go: creates into a terminating namespace 403
        hub.add_namespace("doomed")
        hub.terminate_namespace("doomed")
        code, doc = req(port, "POST", "/api/v1/namespaces/doomed/pods",
                        make_pod_doc("web"))
        assert code == 403 and doc["reason"] == "Forbidden"
        # a healthy namespace still admits
        code, _ = req(port, "POST", "/api/v1/namespaces/default/pods",
                      make_pod_doc("web"))
        assert code == 201
    finally:
        srv.close()


def test_api_root_and_malformed_inputs():
    hub = HollowCluster(seed=7, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        # GET /api/v1 is the discovery APIResourceList (round 4); write
        # verbs against the root stay 404
        code, doc = req(port, "GET", "/api/v1")
        assert code == 200 and doc["kind"] == "APIResourceList"
        for method in ("POST", "DELETE"):
            code, doc = req(port, method, "/api/v1")
            assert code == 404, (method, code)
        code, doc = req(port, "GET", "/api/v1/watch/pods?resourceVersion=abc")
        assert code == 400 and doc["reason"] == "BadRequest"
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/api/v1/nodes", "not json{")
        r = conn.getresponse()
        doc = json.loads(r.read())
        conn.close()
        assert r.status == 400 and doc["reason"] == "BadRequest"
    finally:
        srv.close()


def test_created_pod_response_carries_stored_uid():
    """With admission on, mutating plugins replace the pod and the hub
    assigns uid on the admitted copy — the 201 body must serialize the
    STORED object so clients can use its uid as a binding precondition."""
    hub = HollowCluster(seed=8, admission=True,
                        scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        code, doc = req(port, "POST", "/api/v1/namespaces/default/pods",
                        make_pod_doc("web"))
        assert code == 201
        uid = doc["metadata"]["uid"]
        assert uid == hub.truth_pods["default/web"].uid
        code, _ = req(port, "POST",
                      "/api/v1/namespaces/default/pods/web/binding",
                      {"target": {"name": "n0"}, "metadata": {"uid": uid}})
        assert code == 201
    finally:
        srv.close()


def test_watch_delete_frame_has_namespace_and_name():
    hub = HollowCluster(seed=9, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        rv0 = hub._revision
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("web"))
        req(port, "DELETE", "/api/v1/namespaces/default/pods/web")
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", f"/api/v1/watch/pods?resourceVersion={rv0}")
        r = conn.getresponse()
        events = [json.loads(l) for l in r.read().splitlines() if l]
        conn.close()
        dels = [e for e in events if e["type"] == "DELETED"]
        assert len(dels) == 1
        meta = dels[0]["object"]["metadata"]
        assert meta["name"] == "web" and meta["namespace"] == "default"
    finally:
        srv.close()


def test_ktpu_mutation_verbs_over_rest(tmp_path, capsys):
    """kubectl-shaped mutation path: create -f, cordon/uncordon (CAS
    read-modify-write loop), delete — all against the REST registry."""
    from kubernetes_tpu.kubectl import main as ktpu

    hub = HollowCluster(seed=41, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    api = f"127.0.0.1:{port}"
    try:
        nf = tmp_path / "node.json"
        nf.write_text(json.dumps({"kind": "Node", **NODE}))
        assert ktpu(["--api-server", api, "create", "-f", str(nf)]) == 0
        assert "n0" in hub.truth_nodes
        pf = tmp_path / "pod.json"
        pf.write_text(json.dumps({"kind": "Pod", **make_pod_doc("web")}))
        assert ktpu(["--api-server", api, "create", "-f", str(pf)]) == 0
        assert "default/web" in hub.truth_pods
        # duplicate create surfaces the AlreadyExists Status
        assert ktpu(["--api-server", api, "create", "-f", str(pf)]) == 1

        assert ktpu(["--api-server", api, "cordon", "n0"]) == 0
        assert hub.truth_nodes["n0"].unschedulable
        assert ktpu(["--api-server", api, "uncordon", "n0"]) == 0
        assert not hub.truth_nodes["n0"].unschedulable

        assert ktpu(["--api-server", api, "delete", "pod", "web"]) == 0
        assert "default/web" not in hub.truth_pods
        assert ktpu(["--api-server", api, "delete", "node", "n0"]) == 0
        assert not hub.truth_nodes
        assert ktpu(["--api-server", api, "delete", "node", "n0"]) == 1
        # kind-less manifests are refused, never guessed into a Pod
        kindless = tmp_path / "kindless.json"
        kindless.write_text(json.dumps({"metadata": {"name": "n9"}}))
        assert ktpu(["--api-server", api, "create", "-f", str(kindless)]) == 1
        assert "default/n9" not in hub.truth_pods
        # unreachable server: clean error, not a traceback
        assert ktpu(["--api-server", "127.0.0.1:9", "cordon", "n0"]) == 1
        out = capsys.readouterr()
        assert "created" in out.out and "cordoned" in out.out
        assert "missing 'kind'" in out.err and "cannot reach" in out.err
    finally:
        srv.close()


def test_node_json_round_trip_lossless():
    """cordon's read-modify-write PUTs the GET body back: images and the
    preferAvoidPods annotation must survive the round trip or a cordon
    silently erases ImageLocality/NodePreferAvoidPods inputs."""
    from kubernetes_tpu.extender import node_to_json
    from kubernetes_tpu.grpc_shim import node_from_json
    from kubernetes_tpu.testing import make_node

    nd = make_node("n0", cpu_milli=4000, labels={"disk": "ssd"},
                   images={"registry/app:v1": 500 * 2**20})
    nd.prefer_avoid_owner_uids = ("rc-1", "rc-2")
    nd.unschedulable = True
    nd.allocatable.ephemeral_storage = 5 * 2**30
    back = node_from_json(node_to_json(nd))
    assert back.images == {"registry/app:v1": 500 * 2**20}
    assert back.prefer_avoid_owner_uids == ("rc-1", "rc-2")
    assert back.unschedulable and back.labels == nd.labels
    assert back.allocatable.cpu_milli == nd.allocatable.cpu_milli
    assert back.allocatable.ephemeral_storage == 5 * 2**30
    # malformed preferAvoidPods annotations are ignored, never a crash
    doc = node_to_json(nd)
    for bad in ('{"preferAvoidPods": [42]}', "[]", "not json"):
        doc["metadata"]["annotations"] = {
            "scheduler.alpha.kubernetes.io/preferAvoidPods": bad}
        assert node_from_json(doc).prefer_avoid_owner_uids == ()


def test_audit_log_records_requests():
    """apiserver audit analog: one ResponseComplete entry per request,
    verbs resolved like RequestInfo (get/list/watch/create/update/delete),
    Request level keeping the body, bounded ring, sink streaming."""
    from kubernetes_tpu.restapi import AuditLog

    streamed = []
    audit = AuditLog(level="Request", capacity=4, sink=streamed.append)
    hub = HollowCluster(seed=71, scheduler_kw={"enable_preemption": False})
    srv = RestServer(hub, audit=audit)
    port = srv.serve()
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "GET", "/api/v1/nodes")
        req(port, "GET", "/api/v1/nodes/n0")
        req(port, "GET", "/api/v1/watch/pods?resourceVersion=0")
        req(port, "DELETE", "/api/v1/nodes/n0")
        # ResponseComplete is recorded after the body is written, so the
        # client can observe the response before the entry lands — wait
        import time
        deadline = time.monotonic() + 5
        while len(streamed) < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
        # entries can land slightly out of request order (recorded after
        # the response is written), so compare as a multiset
        verbs = sorted((e["verb"], e["code"]) for e in streamed)
        assert verbs == sorted([("create", 201), ("list", 200),
                                ("get", 200), ("watch", 200),
                                ("delete", 200)])
        create = next(e for e in streamed
                      if e.get("requestObject", {}).get("metadata", {})
                      .get("name") == "n0")
        assert create["verb"] == "create"
        assert create["stage"] == "ResponseComplete"
        assert all(e["latency_s"] >= 0 for e in streamed)
        # ring bounded at capacity (5 requests, cap 4)
        assert len(audit.entries) == 4
    finally:
        srv.close()


def test_audit_levels():
    from kubernetes_tpu.restapi import AuditLog

    meta = AuditLog(level="Metadata")
    meta.record("create", "/x", 201, 0.01, body={"secret": 1})
    assert "requestObject" not in meta.entries[0]
    none = AuditLog(level="None")
    none.record("create", "/x", 201, 0.01)
    assert len(none.entries) == 0
    import pytest
    with pytest.raises(ValueError):
        AuditLog(level="Panic")


def test_audit_verb_resolution_is_positional():
    """Regression (r3 review): a node literally named 'watch' or 'pods'
    must audit as get, and /namespaces/watch/pods as list — RequestInfo
    resolution is positional, never substring."""
    from kubernetes_tpu.restapi import AuditLog

    streamed = []
    audit = AuditLog(sink=streamed.append)
    hub = HollowCluster(seed=72, scheduler_kw={"enable_preemption": False})
    srv = RestServer(hub, audit=audit)
    port = srv.serve()
    try:
        weird = dict(NODE); weird["metadata"] = {"name": "watch"}
        req(port, "POST", "/api/v1/nodes", weird)
        req(port, "GET", "/api/v1/nodes/watch")          # get, not watch
        req(port, "GET", "/api/v1/namespaces/watch/pods")  # list
        req(port, "GET", "/api/v1/watch/pods?resourceVersion=0")  # watch
        import time
        t0 = time.monotonic()
        while len(streamed) < 4 and time.monotonic() - t0 < 5:
            time.sleep(0.01)
        by_uri = {e["requestURI"].split("?")[0]: e["verb"] for e in streamed}
        assert by_uri["/api/v1/nodes/watch"] == "get"
        assert by_uri["/api/v1/namespaces/watch/pods"] == "list"
        assert by_uri["/api/v1/watch/pods"] == "watch"
    finally:
        srv.close()


def test_events_registry_and_ktpu_get_events(capsys):
    """The scheduler's events land in the hub as API objects (the
    reference posts Events via client-go): Scheduled + FailedScheduling
    retrievable over REST with aggregation counts, and ktpu renders the
    kubectl column shape."""
    from kubernetes_tpu.kubectl import main as ktpu

    hub = HollowCluster(seed=91, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("ok"))
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("giant", cpu="64"))  # can never fit 4 CPUs
        # cross the 60s unschedulable resweep so the giant pod is
        # re-attempted and its FailedScheduling event aggregates
        for _ in range(4):
            hub.step(dt=40.0)
        hub.settle()
        code, doc = req(port, "GET", "/api/v1/namespaces/default/events")
        assert code == 200 and doc["kind"] == "EventList"
        by_reason = {}
        for it in doc["items"]:
            by_reason.setdefault(it["reason"], []).append(it)
        assert any(e["involvedObject"]["name"] == "ok"
                   for e in by_reason.get("Scheduled", []))
        failed = [e for e in by_reason.get("FailedScheduling", [])
                  if e["involvedObject"]["name"] == "giant"]
        assert failed and "Insufficient cpu" in failed[0]["message"]
        # aggregation: repeated failures bump count on ONE object
        assert failed[0]["count"] >= 2
        assert all(it["metadata"]["namespace"] == "default"
                   for it in doc["items"])

        assert ktpu(["--api-server", f"127.0.0.1:{port}",
                     "get", "events"]) == 0
        out = capsys.readouterr().out
        assert "REASON" in out and "FailedScheduling" in out
        assert "pod/giant" in out
    finally:
        srv.close()


def test_reflector_ignores_foreign_kinds_in_history():
    """Regression (r3 review): the hub's shared watch history now carries
    Event (and service/endpoint) commits; a Reflector scoped to
    pods+nodes must skip them instead of feeding them to pod handlers."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import Reflector

    hub = HollowCluster(seed=95, scheduler_kw={"enable_preemption": False})
    hub.add_node(__import__("kubernetes_tpu.testing", fromlist=["make_node"])
                 .make_node("n0", cpu_milli=4000))
    shadow = Scheduler(clock=hub.clock, enable_preemption=False)
    r = Reflector(hub, shadow)
    r.list_and_watch()
    hub.create_pod(__import__("kubernetes_tpu.testing", fromlist=["make_pod"])
                   .make_pod("w", cpu_milli=100))
    hub.step()  # scheduling emits Scheduled events into the history
    hub.settle()
    assert hub.events_v1  # events really are in the shared history
    n = r.pump()          # must not crash on the event frames
    assert n >= 1
    assert shadow.cache.pod_count() == 1


def test_ktpu_events_all_namespaces_flag(capsys):
    from kubernetes_tpu.kubectl import main as ktpu

    hub = HollowCluster(seed=96, admission=True,
                        scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        hub.add_namespace("prod")
        req(port, "POST", "/api/v1/namespaces/prod/pods", make_pod_doc("w"))
        hub.step(); hub.settle()
        # default namespace scope: the prod event is invisible
        assert ktpu(["--api-server", f"127.0.0.1:{port}",
                     "get", "events"]) == 0
        out_default = capsys.readouterr().out
        assert "pod/w" not in out_default
        # -A widens to the cluster
        assert ktpu(["--api-server", f"127.0.0.1:{port}",
                     "get", "events", "-A"]) == 0
        out_all = capsys.readouterr().out
        assert "pod/w" in out_all and "Scheduled" in out_all
    finally:
        srv.close()


def test_services_and_endpoints_lists():
    """Read-only REST for the service dataplane kinds: ServiceList with
    spec/clusterIP/ports, EndpointsList deriving live pod targets from
    the endpoints controller."""
    from kubernetes_tpu.proxy import Service, ServicePort

    hub = HollowCluster(seed=97, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        hub.add_service(Service(
            "web", selector={"app": "web"},
            ports=(ServicePort(port=80, target_port=8080),
                   ServicePort(port=443))))  # targetPort defaults to port
        for i in range(2):
            doc = make_pod_doc(f"w{i}")
            doc["metadata"]["labels"] = {"app": "web"}
            req(port, "POST", "/api/v1/namespaces/default/pods", doc)
        # two steps: endpoints reconcile before the same tick's binds land
        # (step order mirrors controller-manager vs scheduler asynchrony)
        hub.step(); hub.step(); hub.settle()

        code, doc = req(port, "GET", "/api/v1/namespaces/default/services")
        assert code == 200 and doc["kind"] == "ServiceList"
        assert len(doc["items"]) == 1
        spec = doc["items"][0]["spec"]
        assert spec["clusterIP"].startswith("10.96.")
        assert spec["ports"] == [
            {"port": 80, "targetPort": 8080, "protocol": "TCP"},
            {"port": 443, "targetPort": 443, "protocol": "TCP"}]

        code, doc = req(port, "GET", "/api/v1/endpoints")
        assert code == 200 and doc["kind"] == "EndpointsList"
        addrs = doc["items"][0]["subsets"][0]["addresses"]
        assert sorted(a["targetRef"]["name"] for a in addrs) == ["w0", "w1"]
        assert all(a["nodeName"] == "n0" for a in addrs)
        # namespace scoping excludes
        code, doc = req(port, "GET", "/api/v1/namespaces/other/services")
        assert code == 200 and doc["items"] == []
    finally:
        srv.close()


def test_rest_fuzz_never_crashes_always_status():
    """Property: whatever bytes arrive, every response is valid JSON with
    a known code (2xx or a metav1.Status 4xx/410), and the server keeps
    serving — no handler thread ever turns a bad request into a hang or
    a non-JSON 500."""
    import random

    rng = random.Random(4242)
    hub = HollowCluster(seed=98, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        segments = ["api", "v1", "pods", "nodes", "namespaces", "default",
                    "watch", "binding", "events", "services", "endpoints",
                    "", "..", "%2e", "n0", "watch", "x" * 64]
        bodies = [None, {}, {"metadata": "notadict"}, {"kind": "Node"},
                  {"target": {}}, {"metadata": {"resourceVersion": "x"}},
                  [], 42, {"spec": {"containers": "no"}}]
        methods = ["GET", "POST", "PUT", "DELETE"]
        for i in range(120):
            path = "/" + "/".join(
                rng.choice(segments)
                for _ in range(rng.randrange(1, 6))
            )
            if rng.random() < 0.3:
                path += "?resourceVersion=" + rng.choice(["0", "abc", "-5"])
            body = rng.choice(bodies)
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            try:
                conn.request(rng.choice(methods), path,
                             json.dumps(body) if body is not None else None)
                r = conn.getresponse()
                data = r.read()
            finally:
                conn.close()
            doc = json.loads(data) if data else None
            assert r.status in (200, 201, 400, 404, 409, 410, 501), (
                path, r.status)
            if r.status >= 400 and r.status != 501:
                assert doc["kind"] == "Status", (path, doc)
        # the server still works after the storm
        code, doc = req(port, "GET", "/api/v1/nodes")
        assert code == 200 and len(doc["items"]) == 1
    finally:
        srv.close()


def test_binding_requires_target_and_rest_nodes_get_hostname_label():
    """Regressions (r3 review): an empty binding target is a 400, never a
    phantom 'bound' pod; REST-ingested nodes get the kubelet's
    kubernetes.io/hostname self-label so hostname-pinned placement
    (DaemonSet affinity) works on them."""
    from kubernetes_tpu.sim import DaemonSet

    hub = HollowCluster(seed=99, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        bare = {"metadata": {"name": "plain"},  # no labels at all
                "status": {"allocatable": {"cpu": "4000m",
                                           "memory": "8589934592",
                                           "pods": "110"}}}
        req(port, "POST", "/api/v1/nodes", bare)
        assert hub.truth_nodes["plain"].labels[
            "kubernetes.io/hostname"] == "plain"
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("w"))
        before = hub.bound_total
        code, doc = req(port, "POST",
                        "/api/v1/namespaces/default/pods/w/binding", {})
        assert code == 400 and doc["reason"] == "BadRequest"
        assert hub.bound_total == before
        assert hub.truth_pods["default/w"].node_name == ""
        # daemon pods pin by hostname: the REST-created node must take one
        hub.add_daemonset(DaemonSet("agent"))
        for _ in range(2):
            hub.step()
        hub.settle()
        hub.check_consistency()
        assert any(p.node_name == "plain" for p in hub.truth_pods.values()
                   if p.labels.get("ds") == "agent")
    finally:
        srv.close()


def test_concurrent_step_and_rest_reads():
    """Regression (r3 review): hub.step() mutates truth dicts on the
    driver thread; concurrent REST list reads must serialize against it
    (shared hub lock) instead of racing into dropped connections."""
    import threading

    hub = HollowCluster(seed=100, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        from kubernetes_tpu.sim import Deployment
        hub.add_deployment(Deployment("web", replicas=6))

        errors = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                try:
                    code, doc = req(port, "GET", "/api/v1/pods")
                    assert code == 200 and doc["kind"] == "PodList"
                    code, doc = req(port, "GET", "/api/v1/events")
                    assert code == 200
                except Exception as e:  # any dropped/non-JSON response
                    errors.append(repr(e))
                    return

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        # the driver thread churns the hub while readers hammer it
        for i in range(30):
            hub.scale_deployment("web", 2 + (i % 5))
            hub.step()
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        hub.settle()
        hub.check_consistency()
    finally:
        srv.close()


def test_discovery_and_openapi_surface():
    """Discovery (/api, /api/v1) + /openapi/v2 + /version — the
    machine-readable surface description (routes/openapi.go:30,
    endpoints/discovery). The OpenAPI paths are DERIVED from the same
    RESOURCES table the routes implement, and this test closes the loop:
    every published path template must answer (non-404) when
    instantiated, so the published surface cannot drift from the served
    one."""
    hub = HollowCluster(seed=77, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("d0"))
        # a Lease fixture so the group routes' {name} instantiation hits
        # a real object (the drift loop substitutes name -> d0)
        from kubernetes_tpu.leaderelection import LeaderElectionRecord

        hub.cas_lease("default", "d0",
                      LeaderElectionRecord(holder_identity="x",
                                           renew_time=1.0), 0)
        req(port, "POST", "/api/v1/namespaces",
            {"metadata": {"name": "d0"}})  # namespace-route fixture
        # apps-group route fixtures ({name} -> d0 in both item routes)
        from kubernetes_tpu.sim import Deployment, ReplicaSet

        hub.add_deployment(Deployment("d0", replicas=1))
        hub.add_replicaset(ReplicaSet("d0", replicas=0))
        # a pod fixture for the item-routed PATCH op (empty merge patch
        # must answer 200 against an existing object)
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("d0"))

        code, doc = req(port, "GET", "/api")
        assert code == 200 and doc["kind"] == "APIVersions"
        assert doc["versions"] == ["v1"]

        code, doc = req(port, "GET", "/api/v1")
        assert code == 200 and doc["kind"] == "APIResourceList"
        by_name = {r["name"]: r for r in doc["resources"]}
        assert by_name["pods"]["namespaced"] and by_name["pods"]["kind"] == "Pod"
        assert not by_name["nodes"]["namespaced"]
        assert "watch" in by_name["pods"]["verbs"]
        assert by_name["pods/binding"]["verbs"] == ["create"]

        code, ver = req(port, "GET", "/version")
        assert code == 200 and ver

        code, spec = req(port, "GET", "/openapi/v2")
        assert code == 200 and spec["swagger"] == "2.0"
        # the served binding route must be published at its ITEM path
        bind_route = "/api/v1/namespaces/{namespace}/pods/{name}/binding"
        assert "post" in spec["paths"][bind_route]
        gvk = spec["paths"][bind_route]["post"][
            "x-kubernetes-group-version-kind"]
        assert gvk["kind"] == "Binding"
        # ...and the pods-collection POST still documents Pod creation
        pods_col = "/api/v1/namespaces/{namespace}/pods"
        assert spec["paths"][pods_col]["post"][
            "x-kubernetes-group-version-kind"]["kind"] == "Pod"

        # every published op, instantiated, must answer with the exact
        # success code — not merely "not 404" (a 500 is drift too).
        # Deletes run LAST (sorted below) so they cannot eat the
        # fixtures other ops need; each delete re-creates what it ate.
        # fixtures for the r5 read-only item routes ({name} -> d0):
        hub.put_configmap("default", "d0", {"k": "v"})
        from kubernetes_tpu.certificates import CertificateSigningRequest
        from kubernetes_tpu.sim import DaemonSet, StatefulSet

        hub.create_csr(CertificateSigningRequest(name="d0"))
        hub.daemonsets["d0"] = DaemonSet("d0")
        hub.statefulsets["d0"] = StatefulSet("d0", replicas=1)
        ops = sorted(
            ((method, route)
             for route, methods in spec["paths"].items()
             for method in methods),
            key=lambda mr: (mr[0] == "delete", mr[1]))
        for method, route in ops:
            path = (route.replace("{namespace}", "default")
                         .replace("{name}", "n0" if "/nodes" in route
                                  else "d0"))
            if "/watch/" in path:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=10)
                conn.request("GET", path + "?resourceVersion=0")
                r = conn.getresponse(); r.read(); conn.close()
                assert r.status == 200, path
                continue
            body = None
            want = {"get": (200,), "put": (200,), "delete": (200,),
                    "patch": (200,)}[
                method] if method != "post" else (201,)
            if method == "post":
                if path.endswith("/binding"):
                    body = {"target": {"name": "n0"}}
                    want = (201, 409)  # d0 may already be bound
                elif path.endswith("/eviction"):
                    body, want = {"kind": "Eviction"}, (201, 429)
                elif "/nodes" in path:
                    body, want = NODE, (201, 409)  # n0 exists
                elif path.endswith("/namespaces"):
                    body = {"metadata": {"name": "d0"}}
                    want = (201, 409)  # fixture namespace exists
                elif path.endswith("/deployments"):
                    body = {"metadata": {"name": "d0"}, "spec": {}}
                    want = (201, 409)  # fixture deployment exists
                else:
                    body = make_pod_doc("new1")
            if method == "put":
                if "/apis/apps/" in path:
                    body = {"spec": {"replicas": 1}}
                else:
                    _, body = req(port, "GET", "/api/v1/nodes/n0")
            if method == "patch":
                # an empty merge patch is the no-op probe: 200 against
                # every patchable published route
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=10)
                conn.request("PATCH", path, "{}",
                             {"Content-Type":
                              "application/merge-patch+json"})
                r = conn.getresponse()
                data = r.read()
                conn.close()
                assert r.status == 200, (method, path, r.status, data)
                continue
            code, doc = req(port, method.upper(), path, body)
            assert code in want, (method, path, code, doc)
            if method == "delete" or path.endswith("/eviction"):
                # restore the fixture the op consumed
                if "/nodes" in path:
                    req(port, "POST", "/api/v1/nodes", NODE)
                elif "/deployments" in path:
                    req(port, "POST",
                        "/apis/apps/v1/namespaces/default/deployments",
                        {"metadata": {"name": "d0"},
                         "spec": {"replicas": 1}})
                else:
                    req(port, "POST", "/api/v1/namespaces/default/pods",
                        make_pod_doc("d0"))
    finally:
        srv.close()


def test_namespace_crud_and_termination_drain():
    """Namespace lifecycle over REST (registry/core/namespace +
    pkg/controller/namespace): create -> Active; delete -> Terminating
    (object still readable) -> the controller drains its pods and
    removes it; system namespaces are protected."""
    hub = HollowCluster(seed=79, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        req(port, "POST", "/api/v1/nodes", NODE)
        code, doc = req(port, "POST", "/api/v1/namespaces",
                        {"metadata": {"name": "team-a"}})
        assert code == 201 and doc["status"]["phase"] == "Active"
        code, _ = req(port, "POST", "/api/v1/namespaces",
                      {"metadata": {"name": "team-a"}})
        assert code == 409
        code, doc = req(port, "GET", "/api/v1/namespaces")
        assert code == 200 and doc["kind"] == "NamespaceList"
        names = {i["metadata"]["name"] for i in doc["items"]}
        assert {"default", "kube-system", "team-a"} <= names

        # a pod in the namespace, bound by the scheduler
        pod = make_pod_doc("w0")
        code, _ = req(port, "POST", "/api/v1/namespaces/team-a/pods", pod)
        assert code == 201
        hub.step()
        assert hub.truth_pods["team-a/w0"].node_name

        code, doc = req(port, "DELETE", "/api/v1/namespaces/team-a")
        assert code == 200 and doc["status"]["phase"] == "Terminating"
        code, doc = req(port, "GET", "/api/v1/namespaces/team-a")
        assert code == 200 and doc["status"]["phase"] == "Terminating"
        for _ in range(3):
            hub.step()  # controller drains + removes (admission-less hub)
        code, _ = req(port, "GET", "/api/v1/namespaces/team-a")
        assert code == 404
        assert "team-a/w0" not in hub.truth_pods
        hub.check_consistency()

        for protected in ("default", "kube-system"):
            code, doc = req(port, "DELETE", f"/api/v1/namespaces/{protected}")
            assert code == 403, protected
    finally:
        srv.close()


def test_namespace_validation_protection_and_full_drain():
    """Review regressions: non-DNS-label names are 400 (a slash would
    mint an unaddressable object), protection lives in the HUB guard,
    and termination drains EVERY namespaced resource — not just pods."""
    import pytest

    from kubernetes_tpu.api.types import PersistentVolume, PersistentVolumeClaim, StorageClass
    from kubernetes_tpu.leaderelection import LeaderElectionRecord
    from kubernetes_tpu.proxy import Service, ServicePort

    hub = HollowCluster(seed=80, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        for bad in ("a/b", "UPPER", "", "-lead", "x" * 64):
            code, _ = req(port, "POST", "/api/v1/namespaces",
                          {"metadata": {"name": bad}})
            assert code == 400, bad
        # hub-level protection guard (not a REST special case)
        with pytest.raises(ValueError):
            hub.terminate_namespace("kube-system")

        req(port, "POST", "/api/v1/nodes", NODE)
        req(port, "POST", "/api/v1/namespaces",
            {"metadata": {"name": "team-b"}})
        hub.add_service(Service("svc", namespace="team-b",
                                selector={"app": "x"},
                                ports=(ServicePort(port=80),)))
        hub.add_storage_class(StorageClass("std"))
        hub.add_pv(PersistentVolume("pvb", kind="gce-pd", handle="h",
                                    storage_class="std"))
        hub.add_pvc(PersistentVolumeClaim("claim", namespace="team-b",
                                          storage_class="std"))
        hub.cas_lease("team-b", "lock",
                      LeaderElectionRecord(holder_identity="z",
                                           renew_time=1.0), 0)
        hub.step()  # PV controller binds the claim
        assert hub.pvcs["team-b/claim"].volume_name == "pvb"

        req(port, "DELETE", "/api/v1/namespaces/team-b")
        for _ in range(3):
            hub.step()
        assert "team-b" not in hub.namespaces
        assert not any(k.startswith("team-b/") for k in hub.services)
        assert not any(k.startswith("team-b/") for k in hub.endpoints)
        assert not any(k.startswith("team-b/") for k in hub.leases)
        assert not any(k.startswith("team-b/") for k in hub.pvcs)
        # the released PV is claimable again
        assert hub.pvs["pvb"].claim_ref == ""
        hub.check_consistency()
    finally:
        srv.close()


def test_endpoints_with_no_addresses_serialize_empty_subsets():
    """ADVICE r5 low: an Endpoints whose address lists are both empty
    must emit ``subsets: []`` — the reference never publishes a subset
    with no addresses (a selector-matching Service with zero ready pods
    shows an empty-subsets Endpoints, not a husk subset)."""
    from kubernetes_tpu.proxy import Service, ServicePort

    hub = HollowCluster(seed=55, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    try:
        hub.add_service(Service("lonely", selector={"app": "nobody"},
                                ports=(ServicePort(port=80),)))
        hub.step()
        code, doc = req(port, "GET",
                        "/api/v1/namespaces/default/endpoints")
        assert code == 200
        items = {i["metadata"]["name"]: i for i in doc["items"]}
        assert items["lonely"]["subsets"] == []
    finally:
        srv.close()


def test_watch_trim_compacts_and_answers_clean_410_relist():
    """Regression pin (serving PR): the watch-trim path must ENFORCE the
    WATCH_WINDOW — a REST-only hub (no sim step loop) compacts through
    _trim itself — and a watcher resuming from below the floor gets the
    clean 410 Gone with the reference's relist hint ("too old resource
    version: requested (floor)"), never a silent empty drain. A future
    resourceVersion (stale client state from another hub incarnation) is
    also 410, not a forever-empty 200 stream."""
    hub = HollowCluster(seed=77, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)
    srv.WATCH_WINDOW = 16  # instance override so the boundary is cheap
    try:
        code, doc = req(port, "GET", "/api/v1/nodes")
        rv0 = int(doc["metadata"]["resourceVersion"])
        # mint > WATCH_WINDOW revisions purely through REST mutations
        for i in range(40):
            req(port, "POST", "/api/v1/namespaces/default/pods",
                make_pod_doc(f"churn-{i}"))
        srv._trim()  # what the per-request _begin and the 1 s trimmer run
        assert hub._compacted_rev > rv0, \
            "REST-only hub never compacted: watch history is unbounded"
        # the boundary: at/above the floor drains fine (NDJSON frames)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", f"/api/v1/watch/pods?resourceVersion="
                            f"{hub._compacted_rev}")
        r = conn.getresponse()
        frames = [json.loads(l) for l in r.read().splitlines() if l]
        conn.close()
        assert r.status == 200 and frames
        # ...below it is the clean 410 + relist hint
        code, doc = req(port, "GET",
                        f"/api/v1/watch/pods?resourceVersion={rv0}")
        assert code == 410 and doc["reason"] == "Expired"
        assert f"too old resource version: {rv0}" in doc["message"]
        assert str(hub._compacted_rev) in doc["message"]
        # a FUTURE rv can never be served silently
        code, doc = req(port, "GET",
                        f"/api/v1/watch/pods?resourceVersion="
                        f"{hub._revision + 1000}")
        assert code == 410 and doc["reason"] == "Expired"
        assert "relist" in doc["message"]
        # the compaction stayed bounded, not total: recent history lives
        assert len(hub._history) > 0
    finally:
        srv.close()
