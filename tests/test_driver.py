"""Driver-loop tests — the analog of ``pkg/scheduler/scheduler_test.go``
(scheduleOne driven with a mock binder capturing bindings) plus queue/cache
integration: retry-on-event, bind-failure Forget, assume-capacity carry."""

import pytest

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.scheduler import RecordingBinder, Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _sched(**kw):
    clk = FakeClock()
    kw.setdefault("clock", clk)
    s = Scheduler(**kw)
    return s, clk


def test_schedules_all_when_capacity_allows():
    s, _ = _sched()
    for i in range(4):
        s.on_node_add(make_node(f"n{i}", cpu_milli=4000))
    for i in range(8):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=500))
    r = s.schedule_cycle()
    assert r.attempted == 8 and r.scheduled == 8 and r.unschedulable == 0
    assert len(s.binder.bindings) == 8
    # all pods assumed in cache
    assert s.cache.pod_count() == 8


def test_unschedulable_gets_reasons_and_requeues():
    s, clk = _sched()
    s.on_node_add(make_node("n0", cpu_milli=1000, pods=10))
    for i in range(3):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=600))
    r = s.schedule_cycle()
    assert r.scheduled == 1
    assert r.unschedulable == 2
    for key, reasons in r.failure_reasons.items():
        assert "PodFitsResources" in reasons
    # failed pods sit in unschedulableQ (no move request since)
    assert s.queue.pending_counts()["unschedulable"] == 2


def test_retry_after_node_add():
    s, clk = _sched()
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("a", cpu_milli=800))
    s.on_pod_add(make_pod("b", cpu_milli=800))
    r1 = s.schedule_cycle()
    assert r1.scheduled == 1 and r1.unschedulable == 1

    # new node arrives -> MoveAllToActiveQueue; backoff must elapse first
    s.on_node_add(make_node("n1", cpu_milli=1000))
    clk.advance(2.0)
    r2 = s.schedule_cycle()
    assert r2.scheduled == 1
    assert {n for _, n in s.binder.bindings} == {"n0", "n1"}


class FailingBinder:
    def __init__(self, fail_keys):
        self.fail_keys = set(fail_keys)
        self.bindings = []

    def bind(self, pod: Pod, node_name: str) -> None:
        if pod.key() in self.fail_keys:
            self.fail_keys.discard(pod.key())  # fail once
            raise RuntimeError("apiserver unavailable")
        self.bindings.append((pod.key(), node_name))


def test_bind_failure_forgets_and_retries():
    binder = FailingBinder({"default/a"})
    s, clk = _sched(binder=binder)
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("a", cpu_milli=800))
    r1 = s.schedule_cycle()
    assert r1.bind_errors == 1 and r1.scheduled == 0
    # capacity was released (ForgetPod), pod requeued; a cluster event +
    # backoff expiry brings it back
    assert s.cache.pod_count() == 0
    s.queue.move_all_to_active()
    clk.advance(2.0)
    r2 = s.schedule_cycle()
    assert r2.scheduled == 1
    assert binder.bindings == [("default/a", "n0")]


def test_assumed_capacity_visible_across_cycles():
    s, clk = _sched()
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("a", cpu_milli=800))
    assert s.schedule_cycle().scheduled == 1
    # second pod cannot double-book the assumed capacity
    s.on_pod_add(make_pod("b", cpu_milli=800))
    r = s.schedule_cycle()
    assert r.scheduled == 0 and r.unschedulable == 1


def test_priority_order_wins_contention():
    s, _ = _sched()
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("low", cpu_milli=800, priority=1))
    s.on_pod_add(make_pod("high", cpu_milli=800, priority=100))
    r = s.schedule_cycle()
    assert r.assignments.get("default/high") == "n0"
    assert "default/low" in r.failure_reasons


def test_greedy_solver_parity_small():
    s1, _ = _sched(solver="batch")
    s2, _ = _sched(solver="greedy")
    for s in (s1, s2):
        for i in range(3):
            s.on_node_add(make_node(f"n{i}", cpu_milli=2000))
        for i in range(5):
            s.on_pod_add(make_pod(f"p{i}", cpu_milli=700))
    r1 = s1.schedule_cycle()
    r2 = s2.schedule_cycle()
    assert r1.scheduled == r2.scheduled == 5


def test_events_emitted():
    events = []
    s, _ = _sched(event_sink=lambda reason, pod, msg: events.append((reason, pod.name)))
    s.on_node_add(make_node("n0", cpu_milli=1000, pods=10))
    s.on_pod_add(make_pod("ok", cpu_milli=100))
    s.on_pod_add(make_pod("toobig", cpu_milli=5000))
    s.schedule_cycle()
    assert ("Scheduled", "ok") in events
    assert ("FailedScheduling", "toobig") in events


def test_pod_update_confirms_assumption():
    """The watch's unassigned->assigned UPDATE (not just Add) must confirm
    the assumption — otherwise the TTL expires a successfully bound pod and
    its capacity double-books."""
    s, clk = _sched()
    s.on_node_add(make_node("n0", cpu_milli=1000))
    old = make_pod("a", cpu_milli=800)
    s.on_pod_add(old)
    assert s.schedule_cycle().scheduled == 1
    bound = make_pod("a", cpu_milli=800, node_name="n0")
    s.on_pod_update(old, bound)
    assert not s.cache.is_assumed("default/a")
    clk.advance(31)
    s.cache.cleanup_expired()
    assert s.cache.pod_count() == 1  # still there
    s.on_pod_add(make_pod("b", cpu_milli=800))
    r = s.schedule_cycle()
    assert r.scheduled == 0  # no double-booking


def test_queue_update_preserves_fifo_position():
    from kubernetes_tpu.queue import SchedulingQueue

    clk = FakeClock(100.0)
    q = SchedulingQueue(clock=clk)
    a = make_pod("a")
    q.add(a)
    clk.advance(100)
    b = make_pod("b")
    q.add(b)
    # watch delivers a fresh API object for b (queued_at unset)
    q.update(b.key(), make_pod("b", node_selector={"x": "y"}))
    assert [p.name for p in q.pop_batch()] == ["a", "b"]


def test_run_until_settled_drains_queue():
    s, clk = _sched()

    # wrap the clock ticks into the loop: advance between cycles so backoff
    # never starves progress
    for i in range(2):
        s.on_node_add(make_node(f"n{i}", cpu_milli=4000, pods=4))
    for i in range(12):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=100))
    results = s.run_until_settled()
    total = sum(r.scheduled for r in results)
    assert total == 8  # pods cap: 4 per node x 2 nodes
    assert s.queue.pending_counts()["unschedulable"] == 4


def test_fit_error_per_reason_node_counts():
    """FitError.Error parity (generic_scheduler.go:105-122): events carry
    per-reason NODE COUNTS, not a bare union of reason names."""
    from kubernetes_tpu.api.types import Taint

    from kubernetes_tpu.events import EventRecorder

    rec = EventRecorder()
    s = Scheduler(clock=FakeClock(), enable_preemption=False,
                  event_sink=rec.sink())
    # two nodes too small (Insufficient cpu), one tainted but big enough
    s.on_node_add(make_node("small-0", cpu_milli=500))
    s.on_node_add(make_node("small-1", cpu_milli=500))
    s.on_node_add(make_node("tainted", cpu_milli=64000,
                            taints=(Taint("k", "v", "NoSchedule"),)))
    s.on_pod_add(make_pod("p", cpu_milli=1000))
    res = s.schedule_cycle()
    assert res.scheduled == 0
    msg = res.fit_errors["default/p"]
    assert msg.startswith("0/3 nodes are available: ")
    assert "2 Insufficient cpu" in msg
    assert "1 node(s) had taints that the pod didn't tolerate" in msg
    assert msg.endswith(".")
    # the event text matches the fit error
    ev = [e for e in rec.events("default/p")
          if e.reason == "FailedScheduling"]
    assert ev and ev[-1].message == msg


def test_fit_error_splits_insufficient_resources():
    s = Scheduler(clock=FakeClock(), enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=500, memory=2**30))
    s.on_pod_add(make_pod("p", cpu_milli=1000, memory=2 * 2**30))
    res = s.schedule_cycle()
    msg = res.fit_errors["default/p"]
    assert "1 Insufficient cpu" in msg and "1 Insufficient memory" in msg


def test_exact_solver_falls_back_on_host_ports():
    """The exact Hungarian cannot model in-batch port coupling; such
    batches must auto-fall back to the round solver (VERDICT r2 #6)."""
    s = Scheduler(solver="exact", clock=FakeClock(), enable_preemption=False)
    for i in range(2):
        s.on_node_add(make_node(f"n{i}", cpu_milli=4000))
    # three pods demanding the same host port: at most one per node
    for i in range(3):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=100,
                              host_ports=(("", "TCP", 8080),)))
    res = s.schedule_cycle()
    assert s.exact_fallbacks == 1
    assert res.scheduled == 2  # one per node; the third waits
    nodes = list(res.assignments.values())
    assert len(set(nodes)) == 2


def test_exact_solver_still_used_for_plain_batches():
    s = Scheduler(solver="exact", clock=FakeClock(), enable_preemption=False)
    for i in range(4):
        s.on_node_add(make_node(f"n{i}", cpu_milli=2000))
    for i in range(8):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=900))
    res = s.schedule_cycle()
    assert s.exact_fallbacks == 0
    assert res.scheduled == 8


def test_exact_solver_hazard_is_batch_scoped():
    """A topology pod seen in an earlier cycle must not disable the exact
    solver for later plain batches (the universe interners are monotonic;
    the hazard check must look at THIS batch)."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
    )

    clk = FakeClock()
    s = Scheduler(solver="exact", clock=clk, enable_preemption=False)
    for i in range(2):
        s.on_node_add(make_node(f"n{i}", cpu_milli=4000))
    aff = Affinity(pod_anti_affinity_required=(PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}),
        topology_key="kubernetes.io/hostname",
    ),))
    s.on_pod_add(make_pod("a0", cpu_milli=100, labels={"app": "x"},
                          affinity=aff))
    s.schedule_cycle()
    assert s.exact_fallbacks == 1
    s.on_pod_add(make_pod("plain", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 1
    assert s.exact_fallbacks == 1  # no new fallback: batch had no topo terms
