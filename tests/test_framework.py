"""Framework extension points + ComponentConfig/Policy tests — the analog
of the reference's framework_test.go and scheduler integration
framework_test.go plugin hooks (PreFilter/Filter/Score/Reserve/Permit/
PreBind/Bind/PostBind/Unreserve), plus Policy decode semantics."""

import numpy as np
import jax.numpy as jnp

from kubernetes_tpu import config as cfg
from kubernetes_tpu.framework import (
    ERROR,
    SKIP,
    SUCCESS,
    UNSCHEDULABLE,
    WAIT,
    CycleState,
    Framework,
    Plugin,
    Status,
)
from kubernetes_tpu.ops.predicates import BIT
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def sched_with(plugins, **kw):
    clk = FakeClock()
    s = Scheduler(
        framework=Framework(plugins=plugins, clock=clk),
        clock=clk,
        enable_preemption=False,
        **kw,
    )
    return s, clk


# ---------------------------------------------------------------------------
# extension points through the driver
# ---------------------------------------------------------------------------


def test_prefilter_rejects_pod():
    class RejectBig(Plugin):
        def pre_filter(self, state, pod):
            if pod.requests.cpu_milli > 1000:
                return Status(UNSCHEDULABLE, "too big")
            return None

    s, _ = sched_with([RejectBig()])
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("small", cpu_milli=100))
    s.on_pod_add(make_pod("big", cpu_milli=4000))
    res = s.schedule_cycle()
    assert res.scheduled == 1
    assert "PreFilter:prefilter plugin RejectBig: too big" in res.failure_reasons[
        "default/big"
    ]


def test_batch_filter_and_score_plugins():
    class OnlyNode1(Plugin):
        """Device-side batch filter: mask everything but node row 1."""

        def filter_batch(self, state, dp, dn, ds):
            m = jnp.zeros((dp.valid.shape[0], dn.valid.shape[0]), bool)
            return m.at[:, 1].set(True)

    s, _ = sched_with([OnlyNode1()])
    for i in range(3):
        s.on_node_add(make_node(f"n{i}"))
    s.on_pod_add(make_pod("p0"))
    res = s.schedule_cycle()
    assert res.assignments["default/p0"] == s.cache.node_order()[1]


def test_host_filter_and_score_plugins():
    class AvoidN0(Plugin):
        def filter(self, state, pod, node_name):
            return Status(UNSCHEDULABLE, "no") if node_name == "n0" else None

    class PreferN2(Plugin):
        def score(self, state, pod, node_name):
            return (100 if node_name == "n2" else 0), None

        def score_weight(self):
            return 2.0

    s, _ = sched_with([AvoidN0(), PreferN2()])
    for i in range(3):
        s.on_node_add(make_node(f"n{i}"))
    s.on_pod_add(make_pod("p0"))
    res = s.schedule_cycle()
    assert res.assignments["default/p0"] == "n2"


def test_reserve_failure_requeues():
    class FailReserve(Plugin):
        def reserve(self, state, pod, node_name):
            return Status(ERROR, "nope")

        def unreserve(self, state, pod, node_name):
            self.unreserved = pod.key()

    p = FailReserve()
    s, _ = sched_with([p])
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0"))
    res = s.schedule_cycle()
    assert res.scheduled == 0 and res.unschedulable == 1
    assert p.unreserved == "default/p0"
    assert not s.cache.is_assumed("default/p0")


def test_permit_wait_allow_and_timeout():
    class Gate(Plugin):
        def permit(self, state, pod, node_name):
            return Status(WAIT, ""), 10.0

    gate = Gate()
    s, clk = sched_with([gate])
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p-allow"))
    s.on_pod_add(make_pod("p-late"))
    res = s.schedule_cycle()
    assert res.waiting == 2 and res.scheduled == 0
    # capacity is held while waiting
    assert s.cache.is_assumed("default/p-allow")

    s.framework.waiting.get("default/p-allow").allow()
    res2 = s.schedule_cycle()
    assert dict(s.binder.bindings)["default/p-allow"] == "n0"

    clk.t += 30.0  # p-late times out -> forgotten + requeued
    res3 = s.schedule_cycle()
    assert any("Permit:" in r for r in res3.failure_reasons.get("default/p-late", ()))
    assert not s.cache.is_assumed("default/p-late")


def test_permit_reject():
    class Gate(Plugin):
        def permit(self, state, pod, node_name):
            return Status(WAIT, ""), 100.0

    s, _ = sched_with([Gate()])
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0"))
    s.schedule_cycle()
    s.framework.waiting.get("default/p0").reject("denied")
    res = s.schedule_cycle()
    assert "Permit:denied" in res.failure_reasons["default/p0"]
    assert not s.cache.is_assumed("default/p0")


def test_prebind_failure_frees_capacity():
    class FailPreBind(Plugin):
        def __init__(self):
            self.calls = 0

        def pre_bind(self, state, pod, node_name):
            self.calls += 1
            return Status(ERROR, "boom") if self.calls == 1 else None

    s, clk = sched_with([FailPreBind()])
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("p0", cpu_milli=800))
    res = s.schedule_cycle()
    assert res.scheduled == 0 and res.bind_errors == 1
    assert not s.cache.is_assumed("default/p0")
    # capacity was freed: the pod schedules on retry
    clk.t += 30.0
    s.queue.move_all_to_active()
    res2 = s.schedule_cycle()
    assert res2.scheduled == 1


def test_bind_plugin_handles_and_postbind_runs():
    bound = []

    class CustomBinder(Plugin):
        def bind(self, state, pod, node_name):
            if pod.name.startswith("mine-"):
                bound.append((pod.key(), node_name))
                return Status(SUCCESS)
            return Status(SKIP, "")

        def post_bind(self, state, pod, node_name):
            bound.append(("post", pod.key()))

    s, _ = sched_with([CustomBinder()])
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("mine-a"))
    s.on_pod_add(make_pod("other-b"))
    res = s.schedule_cycle()
    assert res.scheduled == 2
    assert ("default/mine-a", "n0") in bound
    assert ("post", "default/mine-a") in bound and ("post", "default/other-b") in bound
    # the default binder only saw the skipped pod
    assert dict(s.binder.bindings) == {"default/other-b": "n0"}


def test_queue_sort_plugin_orders_pops():
    class ByName(Plugin):
        def less(self, a, b):
            return a.name < b.name

    s, _ = sched_with([ByName()])
    s.on_node_add(make_node("n0"))
    for name in ["zeta", "alpha", "mid"]:
        s.on_pod_add(make_pod(name, priority=len(name)))  # priority ignored
    batch = s.queue.pop_batch(1)
    assert batch[0].name == "alpha"


# ---------------------------------------------------------------------------
# config: feature gates, policy decode, from_config
# ---------------------------------------------------------------------------


def test_feature_gates_parse_and_defaults():
    g = cfg.FeatureGates()
    assert g.enabled("AttachVolumeLimit") and not g.enabled("EvenPodsSpread")
    g.set_from_string("EvenPodsSpread=true,AttachVolumeLimit=false")
    assert g.enabled("EvenPodsSpread") and not g.enabled("AttachVolumeLimit")
    try:
        g.set_from_string("NoSuchGate=true")
        assert False
    except ValueError:
        pass


def test_default_masks_and_gated_additions():
    base = cfg.default_predicate_mask()
    assert not (base & (1 << BIT["EvenPodsSpread"]))
    g = cfg.FeatureGates({"EvenPodsSpread": True, "ResourceLimitsPriorityFunction": True})
    gated = cfg.default_predicate_mask(g)
    assert gated & (1 << BIT["EvenPodsSpread"])
    w = cfg.default_priority_weights(g)
    assert w["EvenPodsSpreadPriority"] == 1 and w["ResourceLimitsPriority"] == 1


def test_load_policy_predicates_and_priorities():
    from kubernetes_tpu.snapshot import Universe

    u = Universe()
    pol = cfg.load_policy(
        {
            "predicates": [{"name": "HostName"}, {"name": "PodFitsResources"}],
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 2},
                {
                    "name": "RackSpread",
                    "weight": 3,
                    "argument": {
                        "labelPreference": {"label": "rack", "presence": True}
                    },
                },
                {
                    "name": "Packing",
                    "weight": 1,
                    "argument": {
                        "requestedToCapacityRatioArguments": {
                            "utilizationShape": [
                                {"utilization": 0, "score": 0},
                                {"utilization": 100, "score": 10},
                            ]
                        }
                    },
                },
            ],
            "extenders": [
                {"urlPrefix": "http://x/", "filterVerb": "filter", "weight": 5}
            ],
        },
        universe=u,
    )
    # mandatory bits always present; selector NOT enabled
    assert pol.predicate_mask & (1 << BIT["PodFitsHost"])
    assert pol.predicate_mask & (1 << BIT["CheckNodeCondition"])
    assert not (pol.predicate_mask & (1 << BIT["PodMatchNodeSelector"]))
    # parameterized priorities register under unique internal names (two
    # policies may configure the same name with different parameters)
    from kubernetes_tpu.ops.priorities import PRIORITY_REGISTRY

    by_prefix = {k.split("#")[0]: (k, v) for k, v in pol.priority_weights.items()}
    assert by_prefix["LeastRequestedPriority"][1] == 2
    assert by_prefix["RackSpread"][1] == 3 and by_prefix["Packing"][1] == 1
    assert by_prefix["RackSpread"][0] in PRIORITY_REGISTRY
    assert by_prefix["Packing"][0] in PRIORITY_REGISTRY
    assert pol.extenders[0].url_prefix == "http://x/" and pol.extenders[0].weight == 5
    del PRIORITY_REGISTRY[by_prefix["RackSpread"][0]]
    del PRIORITY_REGISTRY[by_prefix["Packing"][0]]


def test_policy_disables_resource_predicate_end_to_end():
    # Policy enabling ONLY HostName: a pod over the node's capacity still
    # schedules because PodFitsResources is bypassed
    pol = cfg.load_policy(
        {"predicates": [{"name": "HostName"}], "priorities": []}
    )
    conf = cfg.KubeSchedulerConfiguration(policy=pol)
    clk = FakeClock()
    s = Scheduler.from_config(conf, clock=clk, enable_preemption=False)
    s.on_node_add(make_node("tiny", cpu_milli=100))
    s.on_pod_add(make_pod("huge", cpu_milli=99999))
    res = s.schedule_cycle()
    assert res.scheduled == 1

    # same pod with the default provider mask: rejected
    s2 = Scheduler.from_config(
        cfg.KubeSchedulerConfiguration(), clock=FakeClock(), enable_preemption=False
    )
    s2.on_node_add(make_node("tiny", cpu_milli=100))
    s2.on_pod_add(make_pod("huge", cpu_milli=99999))
    res2 = s2.schedule_cycle()
    assert res2.scheduled == 0
    assert "PodFitsResources" in res2.failure_reasons["default/huge"]


def test_delete_of_permit_parked_pod_frees_capacity():
    """Regression (review): deleting a pod parked by Permit must remove the
    waiting entry and forget the assumption, or its capacity leaks and a
    later allow() binds a deleted pod."""
    class Gate(Plugin):
        def permit(self, state, pod, node_name):
            return Status(WAIT, ""), 100.0

        def unreserve(self, state, pod, node_name):
            self.unreserved = pod.key()

    gate = Gate()
    s, _ = sched_with([gate])
    s.on_node_add(make_node("n0", cpu_milli=1000))
    parked = make_pod("parked", cpu_milli=900)
    s.on_pod_add(parked)
    s.schedule_cycle()
    assert s.cache.is_assumed("default/parked")
    s.on_pod_delete(parked)
    assert s.framework.waiting.get("default/parked") is None
    assert not s.cache.is_assumed("default/parked")
    assert gate.unreserved == "default/parked"
    # the freed capacity is usable immediately
    s.on_pod_add(make_pod("next", cpu_milli=900))
    res = s.schedule_cycle()
    assert res.waiting == 1  # made it past Filter into Permit


def test_empty_priorities_policy_means_no_scoring():
    """Regression (review): weights={} must mean NO priorities (policy with
    an empty list), not the default suite."""
    pol = cfg.load_policy({"priorities": []})
    assert pol.priority_weights == {}
    s = Scheduler.from_config(
        cfg.KubeSchedulerConfiguration(policy=pol),
        clock=FakeClock(), enable_preemption=False,
    )
    # busy node vs idle node: with no priorities every feasible node scores
    # 0 and the solver takes the lowest row index deterministically
    s.on_node_add(make_node("a-busy", cpu_milli=10000))
    s.on_node_add(make_node("b-idle", cpu_milli=10000))
    s.on_pod_add(make_pod("pre", cpu_milli=9000, node_name="a-busy"))
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    res = s.schedule_cycle()
    # LeastRequested would pick b-idle; no-priorities picks the first row
    assert res.assignments["default/p0"] == "a-busy"


def test_host_plugin_arbitrary_exception_fails_only_that_pod():
    """Advisor fix: a host Filter/Score plugin raising ANY exception must
    become a per-pod failure (the reference converts plugin errors into a
    per-pod status), not abort the whole batch with popped pods lost."""

    class ExplodesOnP1(Plugin):
        def filter(self, state, pod, node_name):
            if pod.name == "p1":
                raise ValueError("boom")
            return None

    s, _ = sched_with([ExplodesOnP1()])
    for i in range(2):
        s.on_node_add(make_node(f"n{i}"))
    s.on_pod_add(make_pod("p0"))
    s.on_pod_add(make_pod("p1"))
    s.on_pod_add(make_pod("p2"))
    res = s.schedule_cycle()
    assert "default/p0" in res.assignments
    assert "default/p2" in res.assignments
    assert "default/p1" not in res.assignments
    (reason,) = res.failure_reasons["default/p1"]
    assert "HostPlugin" in reason and "boom" in reason
