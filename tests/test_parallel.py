"""Sharded scheduling over the 8-virtual-device CPU mesh: results must be
identical to single-device (collectives change the execution plan, not the
answer) — the analog of the reference asserting identical scheduling
decisions regardless of goroutine fan-out."""

import numpy as np

from kubernetes_tpu.models.cluster import make_nodes, make_pods, make_spread_pods
from kubernetes_tpu.ops.arrays import nodes_to_device, pods_to_device, selectors_to_device
from kubernetes_tpu.ops.assign import batch_assign
from kubernetes_tpu.ops.predicates import run_predicates
from kubernetes_tpu.parallel import make_mesh, shard_cluster
from kubernetes_tpu.snapshot import SnapshotPacker


def build(n_nodes=64, n_existing=40, n_pending=96):
    nodes = make_nodes(n_nodes, zones=4)
    existing = make_pods(n_existing, "old", assigned_round_robin_over=n_nodes)
    pending = make_spread_pods(n_pending, n_services=6)
    pk = SnapshotPacker()
    for p in existing + pending:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, existing))
    dp = pods_to_device(pk.pack_pods(pending))
    ds = selectors_to_device(pk.pack_selector_tables())
    return dp, dn, ds, pending


def test_mesh_has_8_devices():
    import jax

    assert len(jax.devices()) == 8


def test_sharded_predicates_match_single_device():
    dp, dn, ds, pending = build()
    want = np.asarray(run_predicates(dp, dn, ds).mask)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    got = np.asarray(run_predicates(sdp, sdn, sds).mask)
    assert (got == want).all()


def test_sharded_batch_assign_matches_single_device():
    dp, dn, ds, pending = build()
    want, _, _ = batch_assign(dp, dn, ds)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    got, _, rounds = batch_assign(sdp, sdn, sds)
    assert (np.asarray(got) == np.asarray(want)).all()


# ---------------------------------------------------------------------------
# Sharded-vs-single equality for the hard kernels (VERDICT r1/r2 ask):
# topology segment-sums, volume predicates, and the sinkhorn plan all
# reduce over the SHARDED node axis — exactly where GSPMD has to insert
# collectives, and exactly what the earlier tests avoided.
# ---------------------------------------------------------------------------


def _pack(nodes, existing, pending, pvcs=(), pvs=()):
    from kubernetes_tpu.ops.arrays import topology_to_device, volumes_to_device

    pk = SnapshotPacker()
    if pvcs or pvs:
        pk.set_volume_state(pvcs, pvs, ())
    for p in list(existing) + list(pending):
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, existing))
    dp = pods_to_device(pk.pack_pods(pending))
    ds = selectors_to_device(pk.pack_selector_tables())
    tt = pk.pack_topology_tables()
    dt = topology_to_device(tt) if tt.n_pairs else None
    dv = (
        volumes_to_device(pk.pack_volume_tables(pending))
        if (pvcs or pvs or any(p.volumes for p in pending))
        else None
    )
    return dp, dn, ds, dt, dv


def test_sharded_topology_matches_single_device():
    """Spread constraints + pod affinity: per-pair count matrices reduce
    along the sharded node axis (ops/topology.py segment ops)."""
    from kubernetes_tpu.models.cluster import (
        make_pod_affinity_pods,
        make_spread_constraint_pods,
    )
    from kubernetes_tpu.parallel import replicate

    nodes = make_nodes(64, zones=4)
    existing = make_pods(48, "old", assigned_round_robin_over=64)
    pending = (make_spread_constraint_pods(48, hard=True)
               + make_pod_affinity_pods(48, n_groups=6))
    dp, dn, ds, dt, _ = _pack(nodes, existing, pending)
    assert dt is not None
    want, _, _ = batch_assign(dp, dn, ds, topo=dt)
    mesh = make_mesh()
    sdp, sdn, sds, sdt = shard_cluster(dp, dn, ds, mesh, topo=dt)
    got, _, _ = batch_assign(sdp, sdn, sds, topo=sdt)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_sharded_volumes_match_single_device():
    """PV/PVC workload: attach limits + zone conflicts computed against
    sharded per-node volume state."""
    from kubernetes_tpu.models.cluster import make_pv_pods
    from kubernetes_tpu.parallel import replicate

    nodes = make_nodes(32, zones=4)
    pending, pvcs, pvs = make_pv_pods(64, kind="gce-pd")
    dp, dn, ds, dt, dv = _pack(nodes, [], pending, pvcs=pvcs, pvs=pvs)
    assert dv is not None
    want, _, _ = batch_assign(dp, dn, ds, vol=dv)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    sdv = replicate(dv, mesh)
    got, _, _ = batch_assign(sdp, sdn, sds, vol=sdv)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_sharded_sinkhorn_matches_single_device():
    """Sinkhorn plan: row/column logsumexp scaling — the column pass is a
    reduction across the sharded node axis every iteration."""
    nodes = make_nodes(32, zones=4)
    # varied existing usage -> distinct node scores, so plan argmaxes are
    # not float-tie sensitive to collective reduction order
    existing = make_pods(80, "old", assigned_round_robin_over=32)
    pending = make_pods(96, "pend")
    dp, dn, ds, _, _ = _pack(nodes, existing, pending)
    want, _, _ = batch_assign(dp, dn, ds, use_sinkhorn=True)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    got, _, _ = batch_assign(sdp, sdn, sds, use_sinkhorn=True)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_collective_cost_model_structure_and_bounds():
    """The config-5 analytical model (VERDICT r4 item 6): the enumerated
    per-round collective volume must stay vector-shaped — orders of
    magnitude below ONE (P, N) matrix — and the prediction must carry
    the falsifiable efficiency claim."""
    from kubernetes_tpu.parallel.costmodel import config5_model

    m = config5_model(8)
    per_round = m.per_round_collectives()
    pn_matrix_bytes = m.pods_per_batch * m.nodes_padded * 4
    assert per_round["total_bytes"] < pn_matrix_bytes / 50, (
        "collective volume must be vector-shaped, not matrix-shaped")
    pred = m.predict()
    assert pred["scaleout_efficiency_cpu_anchor"] >= 0.99
    assert pred["predicted_pods_per_s_cpu_anchor"] > (
        m.single_device_cpu_pods_per_s * 7)  # ~linear at 8 devices
    # collective time well under a millisecond per round at both ends
    assert max(pred["per_round_collective_time_s"]) < 1e-3
    doc = m.document()
    assert "prediction" in doc and "per_round_collectives_bytes" in doc
