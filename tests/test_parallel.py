"""Sharded scheduling over the 8-virtual-device CPU mesh: results must be
identical to single-device (collectives change the execution plan, not the
answer) — the analog of the reference asserting identical scheduling
decisions regardless of goroutine fan-out."""

import numpy as np

from kubernetes_tpu.models.cluster import make_nodes, make_pods, make_spread_pods
from kubernetes_tpu.ops.arrays import nodes_to_device, pods_to_device, selectors_to_device
from kubernetes_tpu.ops.assign import batch_assign
from kubernetes_tpu.ops.predicates import run_predicates
from kubernetes_tpu.parallel import make_mesh, shard_cluster
from kubernetes_tpu.snapshot import SnapshotPacker


def build(n_nodes=64, n_existing=40, n_pending=96):
    nodes = make_nodes(n_nodes, zones=4)
    existing = make_pods(n_existing, "old", assigned_round_robin_over=n_nodes)
    pending = make_spread_pods(n_pending, n_services=6)
    pk = SnapshotPacker()
    for p in existing + pending:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, existing))
    dp = pods_to_device(pk.pack_pods(pending))
    ds = selectors_to_device(pk.pack_selector_tables())
    return dp, dn, ds, pending


def test_mesh_has_8_devices():
    import jax

    assert len(jax.devices()) == 8


def test_sharded_predicates_match_single_device():
    dp, dn, ds, pending = build()
    want = np.asarray(run_predicates(dp, dn, ds).mask)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    got = np.asarray(run_predicates(sdp, sdn, sds).mask)
    assert (got == want).all()


def test_sharded_batch_assign_matches_single_device():
    dp, dn, ds, pending = build()
    want, _, _ = batch_assign(dp, dn, ds)
    mesh = make_mesh()
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh)
    got, _, rounds = batch_assign(sdp, sdn, sds)
    assert (np.asarray(got) == np.asarray(want)).all()
