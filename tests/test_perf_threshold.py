"""The scheduler_perf integration-tier throughput test
(test/integration/scheduler_perf/scheduler_test.go:65
TestSchedule100Node3KPods, thresholds :34-38): 100 nodes / 3000 pods
through the FULL driver against the hollow hub, asserting the reference's
own floor — min sustained throughput >= 30 pods/s (hard failure), with
the ~100 pods/s warning level reported. Runs on the CPU backend in CI;
the TPU number lives in bench.py.
"""

import time

from kubernetes_tpu.sim import HollowCluster
from kubernetes_tpu.testing import make_node, make_pod

MIN_PODS_PER_SEC = 30.0  # threshold3K, scheduler_test.go:34-38
WARN_PODS_PER_SEC = 100.0


def test_schedule_100_node_3k_pods_threshold():
    hub = HollowCluster(seed=0, scheduler_kw={"enable_preemption": False})
    for i in range(100):
        # scheduler_test.go:49 base node: 4 cpu / 32Gi / 110 pods
        hub.add_node(make_node(f"n{i}", cpu_milli=4000, memory=32 * 2**30,
                               pods=110))
    for i in range(3000):
        # runners.go:1233 base pod: 100m / 500Mi
        hub.create_pod(make_pod(f"p{i}", cpu_milli=100, memory=500 * 2**20))
    hub.settle()

    # warmup compile excluded (the reference measures scheduling rate, not
    # first-compile latency; bench.py does the same). The warm cluster must
    # use the SAME node/pod counts: device arrays bucket to powers of two,
    # so a smaller warmup would compile different shapes and leave the real
    # compile inside the timed region (r3 review finding).
    warm = HollowCluster(seed=1, scheduler_kw={"enable_preemption": False})
    for i in range(100):
        warm.add_node(make_node(f"w{i}", cpu_milli=4000, memory=32 * 2**30,
                                pods=110))
    for i in range(3000):
        warm.create_pod(make_pod(f"w{i}", cpu_milli=100, memory=500 * 2**20))
    warm.settle()
    warm.sched.schedule_cycle()

    t0 = time.perf_counter()
    scheduled = 0
    for _ in range(40):
        res = hub.sched.schedule_cycle()
        scheduled += res.scheduled
        if scheduled >= 3000:
            break
        hub.clock.advance(2.0)  # let backoffs expire between cycles
        hub.sched.queue.move_all_to_active()
    elapsed = time.perf_counter() - t0

    assert scheduled == 3000, f"only {scheduled}/3000 scheduled"
    rate = scheduled / elapsed
    # the reference's hard floor; the in-process expectation is ~100+/s
    assert rate >= MIN_PODS_PER_SEC, f"{rate:.0f} pods/s < 30 pods/s floor"
    print(f"\n100-node/3k-pod sustained rate: {rate:.0f} pods/s "
          f"({'ok' if rate >= WARN_PODS_PER_SEC else 'BELOW the 100/s warning level'})")
    hub.check_consistency()
