"""Tier-1 static-analysis gates.

Two layers, cheapest first:

1. ``test_parse_all`` — byte-compile every first-party ``.py`` under the
   running interpreter (3.10 semantics in CI). The seed shipped an
   f-string-backslash SyntaxError in metrics.py that took ~300 tests
   down with it at collection time; this gate turns that whole failure
   class into ONE named test with the offending file in the message.

2. ``test_lint_gate`` — run graftlint (rules R0–R6, see docs/lint.md)
   over ``kubernetes_tpu/ scripts/ tests/`` and fail on any finding not
   grandfathered in the committed ``.graftlint-baseline.json``. The
   merged tree lints clean, so the baseline is empty — any new finding
   is a regression and names its rule, file and line here.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every first-party python root (tests_tpu is TPU-only and excluded from
#: tier-1 *execution*, but it must still parse — a SyntaxError there
#: would kill a hardware run at collection time the same way)
PARSE_ROOTS = ("kubernetes_tpu", "scripts", "tests", "tests_tpu")
PARSE_FILES = ("bench.py", "__graft_entry__.py")

#: what the lint gate enforces (the acceptance surface of the linter CLI:
#: ``python -m kubernetes_tpu.lint kubernetes_tpu/ scripts/ tests/``)
LINT_PATHS = ("kubernetes_tpu", "scripts", "tests")

BASELINE = os.path.join(REPO_ROOT, ".graftlint-baseline.json")


def _first_party_files(roots=PARSE_ROOTS, files=PARSE_FILES):
    out = []
    for root in roots:
        top = os.path.join(REPO_ROOT, root)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    for f in files:
        p = os.path.join(REPO_ROOT, f)
        if os.path.exists(p):
            out.append(p)
    return sorted(out)


def test_parse_all():
    """Every first-party file byte-compiles under this interpreter."""
    files = _first_party_files()
    assert len(files) > 100, f"suspiciously few files found: {len(files)}"
    failures = []
    for path in files:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            src = fh.read()
        try:
            compile(src, path, "exec")
        except SyntaxError as e:
            rel = os.path.relpath(path, REPO_ROOT)
            failures.append(f"{rel}:{e.lineno}: {e.msg}")
    assert not failures, (
        "first-party files failed to byte-compile (the seed-breaking "
        "failure class):\n" + "\n".join(failures)
    )


def test_lint_gate():
    """graftlint exits clean over the enforced tree (baseline-aware) —
    the tier-1 wiring of ``python -m kubernetes_tpu.lint --format json``."""
    import json

    from kubernetes_tpu.lint import load_baseline, run_lint, subtract_baseline
    from kubernetes_tpu.lint.report import render_json, render_text

    paths = [os.path.join(REPO_ROOT, p) for p in LINT_PATHS]
    findings = run_lint(paths, root=REPO_ROOT)
    baselined = 0
    if os.path.exists(BASELINE):
        findings, baselined = subtract_baseline(findings, load_baseline(BASELINE))
    # machine-readable wiring stays honest: the JSON payload must parse
    # and agree with the finding list the human output renders
    payload = json.loads(render_json(findings, baselined))
    assert payload["baselined"] == baselined
    assert len(payload["findings"]) == len(findings)
    assert not findings, (
        "graftlint found non-baselined findings — fix them or add a "
        "justified inline suppression (docs/lint.md):\n"
        + render_text(findings, baselined)
    )


def test_lint_cli_json_exit_codes(tmp_path):
    """The CLI contract the docs promise: exit 0 + empty findings on a
    clean file, exit 1 + populated JSON on a dirty one."""
    import json
    import subprocess
    import sys

    clean = tmp_path / "clean.py"
    clean.write_text("import time\n\nSTAMP = time.monotonic\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import time\n\ndef f():\n    return time.time()\n")

    def run(target):
        return subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.lint", str(target),
             "--format", "json", "--no-baseline", "--root", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO_ROOT,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )

    ok = run(clean)
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout)["findings"] == []

    bad = run(dirty)
    assert bad.returncode == 1, bad.stderr
    payload = json.loads(bad.stdout)
    assert payload["counts"].get("R4") == 1
    f = payload["findings"][0]
    assert f["rule"] == "R4" and f["path"] == "dirty.py" and f["line"] == 4

    # a typo'd explicit path is a usage error (exit 2), NOT a clean run —
    # otherwise a misspelled path in CI becomes a permanent false pass
    typo = run(tmp_path / "no_such_dir")
    assert typo.returncode == 2, (typo.stdout, typo.stderr)
    assert "do not exist" in typo.stderr


def test_lint_cli_unknown_select_rule_is_usage_error(tmp_path):
    """``--select`` with a rule id the engine doesn't know is a usage
    error (exit 2) naming the known rules — a typo like ``--select R01``
    in CI must fail loudly, not silently lint nothing and pass."""
    import subprocess
    import sys

    from kubernetes_tpu.lint.engine import RULE_IDS

    target = tmp_path / "ok.py"
    target.write_text("X = 1\n")

    bad = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.lint", str(target),
         "--select", "R9,R99", "--no-baseline", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert bad.returncode == 2, (bad.stdout, bad.stderr)
    assert "unknown rule id" in bad.stderr and "R99" in bad.stderr
    # the error message must enumerate the valid universe so the fix is
    # one glance away
    for rule in RULE_IDS:
        assert rule in bad.stderr
