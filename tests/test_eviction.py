"""Eviction subresource + kubectl drain (policy/v1beta1 Eviction,
registry/core/pod/storage/eviction.go:147): PDB-guarded graceful
deletes over REST, and the drain flow = cordon + evict-all with
DaemonSet pods ignored and budget blocks reported honestly."""

import json
import http.client

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.kubectl import main as ktpu
from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.sim import DaemonSet, Deployment, HollowCluster
from kubernetes_tpu.testing import make_node, make_pod


def _req(port, method, path, body=None):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    c.request(method, path, json.dumps(body) if body is not None else None)
    r = c.getresponse()
    d = r.read()
    c.close()
    return r.status, json.loads(d) if d else None


def test_eviction_respects_pdb_budget():
    hub = HollowCluster(seed=71, scheduler_kw={"enable_preemption": False})
    for i in range(3):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    for i in range(3):
        hub.create_pod(make_pod(f"w{i}", cpu_milli=100,
                                labels={"app": "web"}))
    hub.add_pdb(PodDisruptionBudget(
        name="web-pdb", selector=LabelSelector(match_labels={"app": "web"}),
        min_available=2))
    for _ in range(2):
        hub.step()  # bind + PDB status
    srv = RestServer(hub)
    port = srv.serve()
    try:
        # budget = 3 healthy - 2 minAvailable = 1 disruption allowed
        code, _ = _req(port, "POST",
                       "/api/v1/namespaces/default/pods/w0/eviction",
                       {"kind": "Eviction"})
        assert code == 201
        assert "default/w0" not in hub.truth_pods
        # the next one violates the budget -> 429, pod stays
        code, doc = _req(port, "POST",
                         "/api/v1/namespaces/default/pods/w1/eviction",
                         {"kind": "Eviction"})
        assert code == 429 and doc["reason"] == "TooManyRequests"
        assert "disruption budget" in doc["message"]
        assert "default/w1" in hub.truth_pods
        # absent pod is a plain 404
        code, _ = _req(port, "POST",
                       "/api/v1/namespaces/default/pods/nope/eviction",
                       {"kind": "Eviction"})
        assert code == 404
        # once the controller restores health, the budget reopens
        for _ in range(3):
            hub.step()
        hub.check_consistency()
    finally:
        srv.close()


def test_ktpu_drain_evicts_ignores_daemons_reports_blocks(capsys):
    hub = HollowCluster(seed=72, scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    hub.add_deployment(Deployment("web", replicas=4))
    hub.add_daemonset(DaemonSet("agent"))
    for _ in range(3):
        hub.step()
    srv = RestServer(hub)
    port = srv.serve()
    try:
        target = next(p.node_name for p in hub.truth_pods.values()
                      if p.labels.get("deploy") == "web")
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "drain", target])
        out = capsys.readouterr()
        assert rc == 0, out.err
        assert "drained" in out.out
        assert "ignoring DaemonSet-managed pod" in out.out
        # cordoned + empty of non-daemon pods
        assert hub.truth_nodes[target].unschedulable
        left = [p for p in hub.truth_pods.values()
                if p.node_name == target]
        assert all(
            any(r.kind == "DaemonSet" for r in p.owner_refs) for p in left
        ), left
        # controllers repopulate ELSEWHERE (the cordon holds)
        for _ in range(4):
            hub.step()
        web = [p for p in hub.truth_pods.values()
               if p.labels.get("deploy") == "web"]
        assert len(web) == 4
        assert all(p.node_name and p.node_name != target for p in web)
        hub.check_consistency()
    finally:
        srv.close()


def test_ktpu_drain_blocked_by_pdb_exits_nonzero(capsys):
    hub = HollowCluster(seed=73, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_node(make_node("n1", cpu_milli=8000))
    for i in range(2):
        hub.create_pod(make_pod(f"w{i}", cpu_milli=100,
                                labels={"app": "web"}))
    hub.add_pdb(PodDisruptionBudget(
        name="web-pdb", selector=LabelSelector(match_labels={"app": "web"}),
        min_available=2))  # zero disruptions allowed
    for _ in range(2):
        hub.step()
    srv = RestServer(hub)
    port = srv.serve()
    try:
        target = hub.truth_pods["default/w0"].node_name
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "drain", target])
        out = capsys.readouterr()
        assert rc == 1
        assert "blocked by" in out.err and "disruption budget" in out.err
        assert hub.truth_nodes[target].unschedulable  # cordon still applied
        assert "default/w0" in hub.truth_pods or "default/w1" in hub.truth_pods
    finally:
        srv.close()


def test_ktpu_get_namespaces(capsys):
    hub = HollowCluster(seed=74, scheduler_kw={"enable_preemption": False})
    hub.add_namespace("team-x")
    srv = RestServer(hub)
    port = srv.serve()
    try:
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "get", "namespaces"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "team-x" in out and "default" in out and "Active" in out
    finally:
        srv.close()


def test_apps_routes_and_ktpu_rollout_status(capsys):
    """apps/v1 read-only routes + ktpu: `get deployments` shows rollout
    counts; `rollout status` exits 1 mid-rollout and 0 when complete."""
    hub = HollowCluster(seed=75, scheduler_kw={"enable_preemption": False})
    for i in range(6):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    d = Deployment("web", replicas=3)
    hub.add_deployment(d)
    for _ in range(3):
        hub.step()
    srv = RestServer(hub)
    port = srv.serve()
    api = ["--api-server", f"127.0.0.1:{port}"]
    try:
        rc = ktpu(api + ["rollout", "status", "deployment/web"])
        out = capsys.readouterr().out
        assert rc == 0 and "successfully rolled out" in out

        d.rollout(cpu_milli=300)
        hub.step()
        rc = ktpu(api + ["rollout", "status", "deployment/web"])
        out = capsys.readouterr().out
        assert rc == 1 and "Waiting for deployment" in out
        for _ in range(10):
            hub.step()
        rc = ktpu(api + ["rollout", "status", "deployment/web"])
        assert rc == 0

        rc = ktpu(api + ["get", "deployments"])
        out = capsys.readouterr().out
        assert rc == 0 and "web" in out and "3/3" in out
        # replicasets visible with ownerReferences
        code, doc = _req(port, "GET", "/apis/apps/v1/replicasets")
        assert code == 200
        rs = [i for i in doc["items"]
              if i["metadata"].get("ownerReferences")]
        assert rs and rs[0]["metadata"]["ownerReferences"][0]["name"] == "web"
    finally:
        srv.close()
