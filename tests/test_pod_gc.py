"""Pod GC + TTL-after-finished controllers (VERDICT r4 controller
breadth): run-to-completion pods linger in the store as Succeeded until
the pod GC's terminated-pod threshold collects the oldest
(podgc/gc_controller.go:94 gc, :108 gcTerminated); unscheduled
terminating pods are force-deleted (:172 gcUnscheduledTerminating);
finished Jobs with spec.ttlSecondsAfterFinished are deleted after the
TTL (ttlafterfinished_controller.go:186 processJob)."""

from kubernetes_tpu.api.types import (
    POD_RUNNING,
    POD_SUCCEEDED,
    is_pod_terminated,
)
from kubernetes_tpu.sim import CronJob, HollowCluster, Job
from kubernetes_tpu.testing import make_node, make_pod


def _hub(**kw):
    hub = HollowCluster(seed=77, scheduler_kw={"enable_preemption": False})
    for k, v in kw.items():
        setattr(hub, k, v)
    return hub


def _run_to_completion_pod(name, duration_s=10.0):
    return make_pod(name, cpu_milli=100, run_duration_s=duration_s)


def test_run_to_completion_pod_lingers_as_succeeded():
    """The kubelet hops the phase and leaves the object — the real
    kubelet never deletes API pods (threshold off => linger forever)."""
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.create_pod(_run_to_completion_pod("p", duration_s=10.0))
    hub.step()   # bind
    hub.step()   # Running
    assert hub.truth_pods["default/p"].phase == POD_RUNNING
    for _ in range(3):  # past duration at the 15 s default tick
        hub.step()
    p = hub.truth_pods.get("default/p")
    assert p is not None and p.phase == POD_SUCCEEDED
    assert is_pod_terminated(p)
    # phase hop is watchable and committed
    assert hub.resource_version["pods/default/p"] > 0
    # the consistency oracle holds with the terminal pod in truth but
    # (by informer field-selector design) absent from the cache
    hub.check_consistency()


def test_terminal_pod_releases_node_capacity():
    """A Succeeded pod's resources are free: a node-filling second pod
    schedules onto the same node after the first finishes."""
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=1000, pods=10))
    hub.create_pod(make_pod("big1", cpu_milli=900, run_duration_s=10.0))
    hub.step()
    hub.step()
    for _ in range(3):
        hub.step()
    assert hub.truth_pods["default/big1"].phase == POD_SUCCEEDED
    hub.create_pod(make_pod("big2", cpu_milli=900))
    for _ in range(3):
        hub.step()
    p2 = hub.truth_pods["default/big2"]
    assert p2.node_name == "n0", "terminal pod still holds capacity"
    # and the kubelet's admission pass does not evict either one
    assert "default/big1" in hub.truth_pods
    hub.check_consistency()


def test_gc_terminated_threshold_deletes_oldest_first():
    hub = _hub(terminated_pod_threshold=2)
    hub.add_node(make_node("n0", cpu_milli=8000, pods=32))
    # three run-to-completion pods created on successive ticks so their
    # creationTimestamps are ordered
    for i in range(3):
        hub.create_pod(_run_to_completion_pod(f"p{i}", duration_s=1.0))
        hub.step()
    for _ in range(6):
        hub.step()
    terminated = [k for k, p in hub.truth_pods.items()
                  if is_pod_terminated(p)]
    assert len(terminated) <= 2
    # oldest (p0) went first
    assert "default/p0" not in hub.truth_pods
    assert hub.pods_gced_total >= 1
    hub.check_consistency()


def test_gc_unscheduled_terminating():
    """A terminating pod that never got a node has no kubelet to finish
    its kill — the pod GC force-deletes it."""
    hub = _hub()
    # no nodes: the pod stays unbound
    hub.create_pod(make_pod("stuck", cpu_milli=100))
    hub.mark_terminating("default/stuck", grace_s=30.0)
    assert hub.truth_pods["default/stuck"].deletion_timestamp > 0
    hub.step()
    assert "default/stuck" not in hub.truth_pods
    hub.check_consistency()


def test_graceful_delete_bound_pod_waits_for_grace():
    """mark_terminating on a BOUND pod: the kubelet finishes the kill
    only after the grace period; the terminating pod is skipped by the
    scheduler (skipPodSchedule) and stays visible meanwhile."""
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.create_pod(make_pod("p", cpu_milli=100))
    hub.step()
    hub.step()
    assert hub.truth_pods["default/p"].phase == POD_RUNNING
    hub.mark_terminating("default/p", grace_s=45.0)
    hub.step()  # 15 s elapsed < 45 s grace: still there
    assert "default/p" in hub.truth_pods
    for _ in range(4):
        hub.step()
    assert "default/p" not in hub.truth_pods
    hub.check_consistency()


def test_reflector_fed_scheduler_releases_terminal_pod_capacity():
    """Review finding r5: a selector-less feed (Reflector, gRPC snapshot
    bridge) delivers the Running->Succeeded hop as a pod UPDATE; the
    scheduler sink must treat a terminal pod as a DELETE (its informer's
    status.phase!= field selector, factory.go NewPodInformer) or the
    remote scheduler's node permanently loses that capacity."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import Reflector

    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=1000, pods=10))
    shadow = Scheduler()  # fed only through the Reflector, no selector
    r = Reflector(hub, shadow)
    r.pump()
    hub.create_pod(make_pod("big", cpu_milli=900, run_duration_s=10.0))
    hub.step()   # bind
    hub.step()   # Running
    for _ in range(3):
        hub.step()  # Succeeded (lingers; threshold off)
    while r.pump():
        pass
    assert hub.truth_pods["default/big"].phase == POD_SUCCEEDED
    # the shadow's cache released n0: it can place a 900m pod there
    assert not shadow.cache.pods_on("n0"), (
        "terminal pod still holds capacity in the reflector-fed cache")


def test_ttl_after_finished_deletes_job():
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.jobs["j"] = Job("j", completions=2, parallelism=2, duration_s=10.0,
                        ttl_seconds_after_finished=60.0)
    hub.jobs["keep"] = Job("keep", completions=1, duration_s=10.0)
    for _ in range(30):
        hub.step()
        if "j" not in hub.jobs:
            break
    assert "j" not in hub.jobs, "TTL'd job still present"
    # a finished job WITHOUT ttl is kept forever
    assert "keep" in hub.jobs and hub.jobs["keep"].done()
    assert hub.jobs["keep"].finished_at is not None
    hub.check_consistency()


def test_ttl_after_finished_respects_clock():
    """The TTL clock starts at completionTime, not at pod exit — a just-
    finished job survives until the TTL elapses."""
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.jobs["j"] = Job("j", completions=1, duration_s=10.0,
                        ttl_seconds_after_finished=300.0)
    for _ in range(5):
        hub.step()
    assert hub.jobs["j"].done() and hub.jobs["j"].finished_at is not None
    assert "j" in hub.jobs  # 300 s not yet elapsed at 15 s ticks
    for _ in range(25):
        hub.step()
    assert "j" not in hub.jobs


def test_ttl_after_finished_cleans_cronjob_bookkeeping():
    hub = _hub()
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.cronjobs["cj"] = CronJob("cj", every_s=3600.0, completions=1,
                                 duration_s=10.0)
    hub.step()  # spawns cj-1
    spawned = list(hub.cronjobs["cj"].spawned)
    assert spawned
    hub.jobs[spawned[0]].ttl_seconds_after_finished = 30.0
    for _ in range(15):
        hub.step()
    assert spawned[0] not in hub.jobs
    assert spawned[0] not in hub.cronjobs["cj"].spawned
