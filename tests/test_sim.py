"""Hollow-cluster end-to-end simulation tests (kubemark analog) plus
NodeTree / truncation / debugger units."""

import numpy as np

from kubernetes_tpu.debugger import compare, dump
from kubernetes_tpu.nodetree import NodeTree, num_feasible_nodes_to_find
from kubernetes_tpu.sim import HollowCluster, ReplicaSet
from kubernetes_tpu.testing import make_node, make_pod


# ---------------------------------------------------------------------------
# NodeTree / numFeasibleNodesToFind
# ---------------------------------------------------------------------------


def test_num_feasible_nodes_to_find():
    assert num_feasible_nodes_to_find(50) == 50  # below the 100 floor
    assert num_feasible_nodes_to_find(5000, 100) == 5000
    # adaptive: 50 - 5000/125 = 10% -> 500
    assert num_feasible_nodes_to_find(5000) == 500
    # adaptive floors at 5%: 50 - 12500/125 = -50 -> 5% -> 625
    assert num_feasible_nodes_to_find(12500) == 625
    # result floors at 100: 300 nodes, 10% = 30 -> 100
    assert num_feasible_nodes_to_find(300, 10) == 100


def test_node_tree_zone_round_robin():
    t = NodeTree()
    for z, names in [("a", ["a1", "a2", "a3"]), ("b", ["b1"]), ("c", ["c1", "c2"])]:
        for n in names:
            t.add_node(make_node(n, zone=z))
    got = [t.next() for _ in range(6)]
    # interleaves zones: one from each zone per sweep round
    assert got[:3] == ["a1", "b1", "c1"]
    assert set(got) == {"a1", "a2", "a3", "b1", "c1", "c2"}
    # resumes across calls; take() returns distinct nodes
    assert sorted(t.take(6)) == ["a1", "a2", "a3", "b1", "c1", "c2"]
    t.remove_node(make_node("b1", zone="b"))
    assert t.num_nodes == 5
    assert "b1" not in t.take(5)


def test_truncated_scheduling_sweeps_zones():
    """With percentage truncation the per-cycle node subset rotates, so a
    multi-cycle run still reaches every zone."""
    from kubernetes_tpu.scheduler import Scheduler

    class Clk:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clk()
    # percentage 50 of 200 nodes -> 100-node floor per cycle
    s = Scheduler(clock=clk, enable_preemption=False,
                  percentage_of_nodes_to_score=50)
    for i in range(200):
        s.on_node_add(make_node(f"n{i}", zone=f"z{i % 4}", cpu_milli=1000))
    for i in range(40):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 40
    used = set(res.assignments.values())
    assert len(used) <= 100  # confined to the truncated subset


# ---------------------------------------------------------------------------
# debugger dump/compare
# ---------------------------------------------------------------------------


def test_debugger_dump_and_compare():
    from kubernetes_tpu.scheduler import Scheduler

    class Clk:
        t = 0.0

        def __call__(self):
            return self.t

    s = Scheduler(clock=Clk(), enable_preemption=False)
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()
    text = dump(s)
    assert "node n0" in text and "default/p0" in text

    # truth agrees (assumed pods are tolerated)
    nd, pd = compare(s, {"default/p0": ""}, ["n0"])
    assert nd == [] and pd == []
    # truth says the pod bound elsewhere -> diff
    s.on_pod_update(make_pod("p0", cpu_milli=100),
                    make_pod("p0", cpu_milli=100, node_name="n0"))
    nd, pd = compare(s, {"default/p0": "nX"}, ["n0"])
    assert any("cache says n0" in d for d in pd)


# ---------------------------------------------------------------------------
# hollow-cluster simulations
# ---------------------------------------------------------------------------


def test_sim_steady_state_with_churn():
    hc = HollowCluster(seed=42)
    for i in range(20):
        hc.add_node(make_node(f"n{i}", zone=f"z{i % 3}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("web", replicas=60, cpu_milli=200))
    hc.add_replicaset(ReplicaSet("db", replicas=10, cpu_milli=800, priority=100))
    for tick in range(12):
        hc.step()
        if tick % 3 == 2:
            hc.churn(kill_pods=8)
        hc.check_consistency()
    # controllers converge: everything placed
    hc.step()
    hc.check_consistency()
    assert hc.pending_count() == 0
    assert len(hc.truth_pods) == 70


def test_sim_flaky_bindings_retry_to_convergence():
    hc = HollowCluster(seed=7, bind_fail_rate=0.3)
    for i in range(10):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("app", replicas=40, cpu_milli=300))
    for _ in range(20):
        hc.step(dt=15.0)
        hc.check_consistency()
    assert hc.pending_count() == 0
    assert hc.binder.failures > 0  # the flake actually exercised the path


def test_sim_node_flap_reschedules_lost_pods():
    hc = HollowCluster(seed=3)
    for i in range(8):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("svc", replicas=24, cpu_milli=400))
    for _ in range(4):
        hc.step()
    hc.check_consistency()
    assert hc.pending_count() == 0
    # two nodes die; their pods are recreated and rescheduled elsewhere
    hc.churn(flap_nodes=2)
    for _ in range(8):
        hc.step()
        hc.check_consistency()
    assert hc.pending_count() == 0
    assert len(hc.truth_nodes) == 6


def test_sim_preemption_under_pressure():
    hc = HollowCluster(seed=9)
    for i in range(4):
        hc.add_node(make_node(f"n{i}", cpu_milli=1000))
    # fill the cluster with low-priority pods
    hc.add_replicaset(ReplicaSet("low", replicas=8, cpu_milli=500, priority=0))
    for _ in range(3):
        hc.step()
    assert hc.pending_count() == 0
    # high-priority arrivals must preempt
    hc.add_replicaset(ReplicaSet("high", replicas=4, cpu_milli=500, priority=100))
    for _ in range(10):
        res = hc.step()
        # hub-side victim deletion: default victim_deleter removed them
        # from cache; truth must follow (simulate the watch delete)
        for key, p in list(hc.truth_pods.items()):
            if p.deletion_timestamp:
                hc.truth_pods.pop(key)
                for rs in hc.replicasets.values():
                    rs.live.pop(key, None)
        if all(
            p.node_name
            for p in hc.truth_pods.values()
            if p.labels.get("rs") == "high"
        ) and len([p for p in hc.truth_pods.values() if p.labels.get("rs") == "high"]) == 4:
            break
    highs = [p for p in hc.truth_pods.values() if p.labels.get("rs") == "high"]
    assert len(highs) == 4 and all(p.node_name for p in highs)
