"""Hollow-cluster end-to-end simulation tests (kubemark analog) plus
NodeTree / truncation / debugger units."""

import numpy as np
import pytest

from kubernetes_tpu.debugger import compare, dump
from kubernetes_tpu.nodetree import NodeTree, num_feasible_nodes_to_find
from kubernetes_tpu.sim import HollowCluster, ReplicaSet
from kubernetes_tpu.testing import make_node, make_pod


# ---------------------------------------------------------------------------
# NodeTree / numFeasibleNodesToFind
# ---------------------------------------------------------------------------


def test_num_feasible_nodes_to_find():
    assert num_feasible_nodes_to_find(50) == 50  # below the 100 floor
    assert num_feasible_nodes_to_find(5000, 100) == 5000
    # adaptive: 50 - 5000/125 = 10% -> 500
    assert num_feasible_nodes_to_find(5000) == 500
    # adaptive floors at 5%: 50 - 12500/125 = -50 -> 5% -> 625
    assert num_feasible_nodes_to_find(12500) == 625
    # result floors at 100: 300 nodes, 10% = 30 -> 100
    assert num_feasible_nodes_to_find(300, 10) == 100


def test_node_tree_zone_round_robin():
    t = NodeTree()
    for z, names in [("a", ["a1", "a2", "a3"]), ("b", ["b1"]), ("c", ["c1", "c2"])]:
        for n in names:
            t.add_node(make_node(n, zone=z))
    got = [t.next() for _ in range(6)]
    # interleaves zones: one from each zone per sweep round
    assert got[:3] == ["a1", "b1", "c1"]
    assert set(got) == {"a1", "a2", "a3", "b1", "c1", "c2"}
    # resumes across calls; take() returns distinct nodes
    assert sorted(t.take(6)) == ["a1", "a2", "a3", "b1", "c1", "c2"]
    t.remove_node(make_node("b1", zone="b"))
    assert t.num_nodes == 5
    assert "b1" not in t.take(5)


def test_truncated_scheduling_sweeps_zones():
    """With percentage truncation the per-cycle node subset rotates, so a
    multi-cycle run still reaches every zone."""
    from kubernetes_tpu.scheduler import Scheduler

    class Clk:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clk()
    # percentage 50 of 200 nodes -> 100-node floor per cycle
    s = Scheduler(clock=clk, enable_preemption=False,
                  percentage_of_nodes_to_score=50)
    for i in range(200):
        s.on_node_add(make_node(f"n{i}", zone=f"z{i % 4}", cpu_milli=1000))
    for i in range(40):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 40
    used = set(res.assignments.values())
    assert len(used) <= 100  # confined to the truncated subset


# ---------------------------------------------------------------------------
# debugger dump/compare
# ---------------------------------------------------------------------------


def test_debugger_dump_and_compare():
    from kubernetes_tpu.scheduler import Scheduler

    class Clk:
        t = 0.0

        def __call__(self):
            return self.t

    s = Scheduler(clock=Clk(), enable_preemption=False)
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()
    text = dump(s)
    assert "node n0" in text and "default/p0" in text

    # truth agrees (assumed pods are tolerated)
    nd, pd = compare(s, {"default/p0": ""}, ["n0"])
    assert nd == [] and pd == []
    # truth says the pod bound elsewhere -> diff
    s.on_pod_update(make_pod("p0", cpu_milli=100),
                    make_pod("p0", cpu_milli=100, node_name="n0"))
    nd, pd = compare(s, {"default/p0": "nX"}, ["n0"])
    assert any("cache says n0" in d for d in pd)


# ---------------------------------------------------------------------------
# hollow-cluster simulations
# ---------------------------------------------------------------------------


def test_sim_steady_state_with_churn():
    hc = HollowCluster(seed=42)
    for i in range(20):
        hc.add_node(make_node(f"n{i}", zone=f"z{i % 3}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("web", replicas=60, cpu_milli=200))
    hc.add_replicaset(ReplicaSet("db", replicas=10, cpu_milli=800, priority=100))
    for tick in range(12):
        hc.step()
        if tick % 3 == 2:
            hc.churn(kill_pods=8)
        hc.check_consistency()
    # controllers converge: everything placed
    hc.step()
    hc.check_consistency()
    assert hc.pending_count() == 0
    assert len(hc.truth_pods) == 70


def test_sim_flaky_bindings_retry_to_convergence():
    hc = HollowCluster(seed=7, bind_fail_rate=0.3)
    for i in range(10):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("app", replicas=40, cpu_milli=300))
    for _ in range(20):
        hc.step(dt=15.0)
        hc.check_consistency()
    assert hc.pending_count() == 0
    assert hc.binder.failures > 0  # the flake actually exercised the path


def test_sim_node_flap_reschedules_lost_pods():
    hc = HollowCluster(seed=3)
    for i in range(8):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("svc", replicas=24, cpu_milli=400))
    for _ in range(4):
        hc.step()
    hc.check_consistency()
    assert hc.pending_count() == 0
    # two nodes die; their pods are recreated and rescheduled elsewhere
    hc.churn(flap_nodes=2)
    for _ in range(8):
        hc.step()
        hc.check_consistency()
    assert hc.pending_count() == 0
    assert len(hc.truth_nodes) == 6


def test_sim_preemption_under_pressure():
    hc = HollowCluster(seed=9)
    for i in range(4):
        hc.add_node(make_node(f"n{i}", cpu_milli=1000))
    # fill the cluster with low-priority pods
    hc.add_replicaset(ReplicaSet("low", replicas=8, cpu_milli=500, priority=0))
    for _ in range(3):
        hc.step()
    assert hc.pending_count() == 0
    # high-priority arrivals must preempt
    hc.add_replicaset(ReplicaSet("high", replicas=4, cpu_milli=500, priority=100))
    for _ in range(10):
        res = hc.step()
        # hub-side victim deletion: default victim_deleter removed them
        # from cache; truth must follow (simulate the watch delete)
        for key, p in list(hc.truth_pods.items()):
            if p.deletion_timestamp:
                hc.truth_pods.pop(key)
                for rs in hc.replicasets.values():
                    rs.live.pop(key, None)
        if all(
            p.node_name
            for p in hc.truth_pods.values()
            if p.labels.get("rs") == "high"
        ) and len([p for p in hc.truth_pods.values() if p.labels.get("rs") == "high"]) == 4:
            break
    highs = [p for p in hc.truth_pods.values() if p.labels.get("rs") == "high"]
    assert len(highs) == 4 and all(p.node_name for p in highs)


# ---------------------------------------------------------------------------
# hub fidelity: resourceVersion CAS, conflicts, stale watches (VERDICT r1 #3)
# ---------------------------------------------------------------------------


def test_hub_resource_versions_monotonic():
    from kubernetes_tpu.sim import Conflict

    hc = HollowCluster(seed=1)
    hc.add_node(make_node("n0"))
    rv_node = hc.resource_version["nodes/n0"]
    hc.create_pod(make_pod("p0"))
    rv_pod = hc.resource_version["pods/default/p0"]
    assert rv_pod > rv_node > 0
    hc.confirm_binding(hc.truth_pods["default/p0"], "n0")
    assert hc.resource_version["pods/default/p0"] > rv_pod


def test_binding_cas_rejects_stale_writes():
    import pytest

    from kubernetes_tpu.sim import Conflict

    hc = HollowCluster(seed=2)
    hc.add_node(make_node("n0"))
    hc.create_pod(make_pod("p0"))
    stale = hc.truth_pods["default/p0"]

    # double bind: second writer loses
    hc.confirm_binding(stale, "n0")
    with pytest.raises(Conflict, match="already assigned"):
        hc.confirm_binding(stale, "n0")

    # deleted mid-bind
    hc.create_pod(make_pod("p1"))
    stale1 = hc.truth_pods["default/p1"]
    hc.delete_pod("default/p1")
    with pytest.raises(Conflict, match="not found"):
        hc.confirm_binding(stale1, "n0")

    # recreated under the same key (uid changes) mid-bind
    p2 = make_pod("p2")
    p2.uid = "gen-1"
    hc.create_pod(p2)
    stale2 = hc.truth_pods["default/p2"]
    hc.delete_pod("default/p2")
    p2b = make_pod("p2")
    p2b.uid = "gen-2"
    hc.create_pod(p2b)
    with pytest.raises(Conflict, match="uid changed"):
        hc.confirm_binding(stale2, "n0")


def test_bind_conflict_forget_and_requeue_end_to_end():
    """A competing writer binds pods behind the scheduler's back; every
    scheduler bind for such a pod must CAS-fail, Forget, and requeue, and
    the system must still converge with no double booking."""
    hc = HollowCluster(seed=3, competing_bind_rate=0.3)
    for i in range(6):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.add_replicaset(ReplicaSet("web", replicas=40, cpu_milli=400))
    for _ in range(20):
        hc.step()
        hc.check_consistency()
        if hc.pending_count() == 0:
            break
    assert hc.pending_count() == 0
    assert hc.competing_bound > 0  # the race actually happened
    # every pod bound exactly once in truth; no capacity violation
    # (check_consistency already asserted overcommit invariants)
    assert hc.bound_total == 40


def test_delayed_watch_events_stale_reads_converge():
    """Watch events lag up to 3 ticks: the scheduler schedules against
    stale state (nodes it thinks exist may be gone; pods it thinks are
    pending may be bound). Conflicts + Forget/requeue + GC must converge
    to a consistent settled state."""
    hc = HollowCluster(seed=4, event_delay_ticks=3, competing_bind_rate=0.15)
    for i in range(8):
        hc.add_node(make_node(f"n{i}", cpu_milli=4000))
    hc.settle()  # nodes visible before workload arrives
    hc.add_replicaset(ReplicaSet("api", replicas=50, cpu_milli=300))
    for t in range(30):
        hc.step()
        if t % 7 == 6:
            hc.churn(kill_pods=3, flap_nodes=1)
    for _ in range(25):  # drain: backoffs, delayed events, recreated pods
        hc.step()
        if hc.pending_count() == 0 and not hc._watch_q:
            break
    hc.check_consistency()
    assert hc.pending_count() == 0
    assert len(hc.truth_nodes) < 8  # flaps happened
    # conflict path exercised: flaky ordering must have produced at least
    # one CAS rejection or competing bind during the run
    assert hc.binder.conflicts + hc.competing_bound > 0


def test_binding_to_dead_node_is_gced():
    """The apiserver accepts bindings to dead nodes (assignPod does not
    check node existence); the node-lifecycle/GC analog must clean up."""
    from kubernetes_tpu.sim import Conflict

    hc = HollowCluster(seed=5)
    hc.add_node(make_node("n0"))
    hc.add_node(make_node("n1"))
    hc.create_pod(make_pod("p0"))
    # hub-side: n1 dies, but a (stale) writer still binds p0 there
    del hc.truth_nodes["n1"]
    hc.confirm_binding(hc.truth_pods["default/p0"], "n1")
    assert hc.truth_pods["default/p0"].node_name == "n1"
    hc.gc_orphaned()
    assert "default/p0" not in hc.truth_pods


# ---------------------------------------------------------------------------
# node-lifecycle + disruption controllers (VERDICT r1 #5/#8)
# ---------------------------------------------------------------------------


def test_node_lifecycle_heartbeat_taint_eviction_and_recovery():
    """kill_kubelet stops heartbeats (node object stays): after the grace
    period the lifecycle controller taints NoExecute + marks NotReady; the
    scheduler avoids the node; after the toleration window its pods are
    evicted and rescheduled elsewhere; healing the kubelet untaints."""
    hc = HollowCluster(seed=11, node_grace_s=40.0, eviction_wait_s=30.0)
    for i in range(4):
        hc.add_node(make_node(f"n{i}", cpu_milli=8000))
    hc.add_replicaset(ReplicaSet("svc", replicas=12, cpu_milli=500))
    for _ in range(3):
        hc.step(dt=15.0)
    hc.check_consistency()
    assert hc.pending_count() == 0
    victim = next(p.node_name for p in hc.truth_pods.values() if p.node_name)
    n_on_victim = sum(
        1 for p in hc.truth_pods.values() if p.node_name == victim
    )
    assert n_on_victim > 0
    hc.kill_kubelet(victim)
    for _ in range(3):  # grace (40s) passes at dt=15 -> tainted
        hc.step(dt=15.0)
    nd = hc.truth_nodes[victim]
    assert any(t.key == HollowCluster.TAINT_UNREACHABLE for t in nd.taints)
    assert not nd.conditions.ready
    for _ in range(8):  # eviction wait passes; replicas recreated elsewhere
        hc.step(dt=15.0)
    hc.check_consistency()
    assert all(p.node_name != victim for p in hc.truth_pods.values())
    assert hc.pending_count() == 0  # rescheduled on the healthy nodes
    # recovery: heartbeats resume -> taint cleared, node schedulable again
    hc.heal_kubelet(victim)
    hc.step(dt=15.0)
    nd = hc.truth_nodes[victim]
    assert not any(t.key == HollowCluster.TAINT_UNREACHABLE for t in nd.taints)
    assert nd.conditions.ready


def test_pdb_status_maintained_by_disruption_controller():
    from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget

    hc = HollowCluster(seed=12)
    for i in range(3):
        hc.add_node(make_node(f"n{i}", cpu_milli=8000))
    pdb = PodDisruptionBudget(
        name="keep3",
        selector=LabelSelector(match_labels={"rs": "guarded"}),
        min_available=3,
    )
    hc.add_pdb(pdb)
    hc.add_replicaset(ReplicaSet("guarded", replicas=5, cpu_milli=500))
    for _ in range(3):
        hc.step()
    assert hc.pending_count() == 0
    hc.step()
    assert pdb.disruptions_allowed == 2  # 5 healthy - 3 minAvailable
    # two guarded pods die -> healthy drops -> budget goes to 0... then the
    # replicaset recreates them and the budget recovers
    hc.churn(kill_pods=2)
    hc.reconcile_pdbs()
    assert pdb.disruptions_allowed <= 1
    for _ in range(4):
        hc.step()
    assert pdb.disruptions_allowed == 2


def test_preemption_respects_live_pdb_status():
    """Preemption's victim choice reads the LIVE budget: it must pick the
    node whose victims violate no PDB (pickOneNodeForPreemption tier 1,
    generic_scheduler.go:862; filterPodsWithPDBViolation :1129)."""
    from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget

    hc = HollowCluster(seed=13)
    hc.add_node(make_node("n-guarded", cpu_milli=1000))
    hc.add_node(make_node("n-free", cpu_milli=1000))
    hc.add_pdb(
        PodDisruptionBudget(
            name="guard",
            selector=LabelSelector(match_labels={"rs": "guarded"}),
            min_available=2,  # both guarded pods needed -> 0 disruptions
        )
    )
    # fill each node with one low-pri pod; only "guarded" ones carry the PDB
    guarded = make_pod("g0", cpu_milli=800, priority=0, labels={"rs": "guarded"})
    free = make_pod("f0", cpu_milli=800, priority=0, labels={"rs": "free"})
    hc.create_pod(guarded)
    hc.create_pod(free)
    # second guarded pod elsewhere keeps minAvailable meaningful
    g1 = make_pod("g1", cpu_milli=100, priority=0, labels={"rs": "guarded"})
    hc.create_pod(g1)
    for _ in range(3):
        hc.step()
    assert hc.pending_count() == 0
    # a high-priority pod arrives needing 800m: must evict f0, not g0
    hc.create_pod(make_pod("boss", cpu_milli=800, priority=100))
    for _ in range(6):
        hc.step()
        for key, p in list(hc.truth_pods.items()):
            if p.deletion_timestamp:
                hc.delete_pod(key)
        if hc.truth_pods.get("default/boss", None) is not None and \
           hc.truth_pods["default/boss"].node_name:
            break
    assert "default/g0" in hc.truth_pods  # PDB-protected pod survived
    assert "default/f0" not in hc.truth_pods  # unprotected pod evicted
    boss = hc.truth_pods["default/boss"]
    assert boss.node_name == "n-free"


# ---------------------------------------------------------------------------
# Watch history / compaction / Reflector (etcd3 watchable-store + client-go
# ListAndWatch semantics; VERDICT r2 §2.2 "no watch history/compaction",
# "no fan-out/resync machinery")
# ---------------------------------------------------------------------------


def test_watch_history_and_cursor_fanout():
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=1)
    hub.add_node(make_node("n0", cpu_milli=4000))
    rev, _, _ = hub.list_state()
    c1 = hub.watch(rev)
    c2 = hub.watch(rev)  # independent second watcher (fan-out)
    hub.create_pod(make_pod("a", cpu_milli=100))
    hub.create_pod(make_pod("b", cpu_milli=100))
    ev1 = c1.poll()
    assert [(k, t) for _, k, t, _ in ev1] == [
        ("pods/default/a", "ADDED"), ("pods/default/b", "ADDED")]
    assert c1.poll() == []  # cursor advanced
    # second cursor sees the same stream independently
    assert [(k, t) for _, k, t, _ in c2.poll()] == [
        ("pods/default/a", "ADDED"), ("pods/default/b", "ADDED")]


def test_compaction_forces_relist():
    from kubernetes_tpu.sim import Compacted, HollowCluster

    hub = HollowCluster(seed=2)
    hub.add_node(make_node("n0", cpu_milli=4000))
    # opening a watch from before the compaction floor fails outright
    # (unwatched writes auto-compact; the floor is already past rev 0)
    with pytest.raises(Compacted):
        hub.watch(0)
    # a live cursor that lags behind an explicit compaction also fails
    stale = hub.watch(hub._revision)
    hub.create_pod(make_pod("a", cpu_milli=100))
    hub.compact()  # etcd compaction can outpace a slow watcher
    with pytest.raises(Compacted):
        stale.poll()
    # a fresh watch from the current revision works
    cur = hub.watch(hub._revision)
    hub.create_pod(make_pod("b", cpu_milli=100))
    assert len(cur.poll()) == 1


def test_reflector_drives_second_scheduler():
    """A second scheduler fed ONLY through a Reflector reaches the same
    state as the hub truth — list, watch, compaction-relist, resync."""
    from kubernetes_tpu.debugger import compare
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=3)
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    for i in range(6):
        hub.create_pod(make_pod(f"p{i}", cpu_milli=500))
    hub.sched.schedule_cycle()  # primary scheduler binds via the hub

    def assert_synced(sched):
        truth = {k: p.node_name for k, p in hub.truth_pods.items()}
        node_diffs, pod_diffs = compare(sched, truth, list(hub.truth_nodes))
        assert not node_diffs and not pod_diffs, (node_diffs, pod_diffs)

    shadow = Scheduler(clock=hub.clock, enable_preemption=False)
    r = Reflector(hub, shadow)
    r.list_and_watch()
    assert_synced(shadow)

    # hub keeps moving while the shadow's watch lags, then compacts:
    # pump() must take the Compacted -> relist path and still converge,
    # including the DELETE the relist has to synthesize
    hub.delete_pod("default/p0")
    hub.create_pod(make_pod("late", cpu_milli=100))
    hub.compact()
    n = r.pump()
    assert r.relists == 1 and n == 1
    assert_synced(shadow)

    # resync is a no-op when nothing changed
    before = shadow.cache.pod_count()
    r.resync()
    assert shadow.cache.pod_count() == before
    assert_synced(shadow)


def test_reflector_watch_streams_incremental_events():
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=4)
    hub.add_node(make_node("n0", cpu_milli=4000))
    shadow = Scheduler(clock=hub.clock, enable_preemption=False)
    r = Reflector(hub, shadow)
    r.list_and_watch()
    hub.create_pod(make_pod("w", cpu_milli=100))
    assert r.pump() == 1
    res = shadow.schedule_cycle()
    assert res.assignments.get("default/w") == "n0"
    assert r.relists == 0


def test_reflector_relist_splits_recreated_pod():
    """A pod deleted-and-recreated (same key, new uid, unbound) while the
    watch was compacted away must replay as delete+add — a single update
    would leave the stale bound pod holding capacity in the shadow cache."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=5)
    hub.add_node(make_node("n0", cpu_milli=1000))
    hub.create_pod(make_pod("r", cpu_milli=800))
    hub.sched.schedule_cycle()  # binds r -> n0 in truth

    shadow = Scheduler(clock=hub.clock, enable_preemption=False)
    r = Reflector(hub, shadow)
    r.list_and_watch()
    assert shadow.cache.pod_count() == 1

    # hub: delete + recreate under the same key (fresh uid, pending)
    hub.delete_pod("default/r")
    hub.create_pod(make_pod("r", cpu_milli=800))
    hub.compact()
    r.pump()  # relist path
    assert r.relists == 1
    # the stale bound copy is gone; n0's capacity is free for the new copy
    assert shadow.cache.pod_count() == 0
    res = shadow.schedule_cycle()
    assert res.assignments.get("default/r") == "n0"


def test_history_stays_bounded_without_watchers():
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=6)
    hub.add_node(make_node("n0", cpu_milli=64000))
    for i in range(50):
        hub.create_pod(make_pod(f"p{i}", cpu_milli=10))
    assert hub._history == []  # no cursor open -> nothing pinned
    cur = hub.watch(hub._revision)
    hub.create_pod(make_pod("x", cpu_milli=10))
    assert len(hub._history) == 1  # recorded only while watched
    assert len(cur.poll()) == 1


# ---------------------------------------------------------------------------
# HollowKubelet (per-node hollow agent; pkg/kubemark/hollow_kubelet.go:44)
# ---------------------------------------------------------------------------


def test_hollow_kubelet_reports_memory_pressure():
    """Crossing the eviction-manager threshold reports MemoryPressure in
    node status; the scheduler then rejects BestEffort pods there
    (CheckNodeMemoryPressure, predicates.go:1583); receding clears it."""
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=7)
    hub.add_node(make_node("n0", cpu_milli=64000, memory=10 * 2**30))
    hub.add_node(make_node("n1", cpu_milli=64000, memory=10 * 2**30))
    # fill n0 beyond 95% memory via the hub (competing-writer style bind)
    big = make_pod("hog", cpu_milli=100, memory=int(9.7 * 2**30))
    hub.create_pod(big)
    hub.settle()
    hub.sched.schedule_cycle()
    hub.settle()
    hogged = hub.truth_pods["default/hog"].node_name
    hub.kubelets[hogged].sync()
    assert hub.truth_nodes[hogged].conditions.memory_pressure
    hub.settle()
    # BestEffort pod (zero requests) avoids the pressured node
    hub.create_pod(make_pod("be", cpu_milli=0))
    hub.settle()
    res = hub.sched.schedule_cycle()
    other = "n1" if hogged == "n0" else "n0"
    assert res.assignments.get("default/be") == other
    # hog leaves -> pressure clears on the next sync
    hub.delete_pod("default/hog")
    hub.kubelets[hogged].sync()
    assert not hub.truth_nodes[hogged].conditions.memory_pressure


def test_hollow_kubelet_owns_heartbeats():
    """monitor_node_health only CONSUMES heartbeat age; a dead kubelet's
    node goes unreachable because nothing refreshes it."""
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=8, node_grace_s=40.0)
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.kill_kubelet("n0")
    assert not hub.kubelets["n0"].alive
    for _ in range(5):
        hub.step(dt=15.0)
    nd = hub.truth_nodes["n0"]
    assert not nd.conditions.ready
    assert any(t.key == hub.TAINT_UNREACHABLE for t in nd.taints)
    hub.heal_kubelet("n0")
    assert hub.kubelets["n0"].alive
    for _ in range(3):
        hub.step(dt=15.0)
    nd = hub.truth_nodes["n0"]
    assert nd.conditions.ready and not nd.taints


# ---------------------------------------------------------------------------
# Deployment / Job controllers + ownerRef GC
# (kube-controller-manager analogs, controllermanager.go:376-412 registry)
# ---------------------------------------------------------------------------


def test_deployment_scales_and_cascade_deletes():
    from kubernetes_tpu.sim import Deployment, HollowCluster

    hub = HollowCluster(seed=10, scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    hub.add_deployment(Deployment("web", replicas=6))
    for _ in range(3):
        hub.step()
    hub.check_consistency()
    live = [k for k in hub.truth_pods if k.startswith("default/web-rs-")]
    assert len(live) == 6
    # scale down
    hub.scale_deployment("web", 2)
    for _ in range(3):
        hub.step()
    hub.check_consistency()
    live = [k for k in hub.truth_pods if k.startswith("default/web-rs-")]
    assert len(live) == 2
    # cascading delete via the GC pass
    hub.delete_deployment("web")
    for _ in range(2):
        hub.step()
    hub.check_consistency()
    assert not any(k.startswith("default/web-rs-") for k in hub.truth_pods)
    assert "web-rs" not in hub.replicasets


def test_job_runs_to_completion_through_scheduler():
    from kubernetes_tpu.sim import HollowCluster, Job

    hub = HollowCluster(seed=11, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.add_job(Job("batch", completions=5, parallelism=2, duration_s=20.0))
    for _ in range(25):
        hub.step(dt=15.0)
        if hub.jobs["batch"].done():
            break
    j = hub.jobs["batch"]
    assert j.done() and j.succeeded == 5
    # finished pods are cleaned up; no stragglers left
    assert not any(k.startswith("default/batch-") for k in hub.truth_pods)
    hub.check_consistency()


def test_standalone_rs_with_rs_suffix_survives_gc():
    """Regression (r3 review): GC must use the explicit owner field, not a
    name pattern — a standalone ReplicaSet named '*-rs' is nobody's child."""
    from kubernetes_tpu.sim import HollowCluster, ReplicaSet

    hub = HollowCluster(seed=12, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.add_replicaset(ReplicaSet("standalone-rs", replicas=3))
    hub.step()
    assert "standalone-rs" in hub.replicasets
    assert sum(1 for k in hub.truth_pods
               if k.startswith("default/standalone-rs-")) == 3


# ---------------------------------------------------------------------------
# DaemonSet / StatefulSet controllers
# (pkg/controller/daemon manage(), pkg/controller/statefulset OrderedReady)
# ---------------------------------------------------------------------------


def test_daemonset_one_pod_per_node_through_scheduler():
    """ScheduleDaemonSetPods (v1.16 default): the controller only creates
    affinity-pinned pods; the DEFAULT scheduler places each on exactly its
    node. Nodes added later get their daemon pod on the next sync; removed
    nodes' pods are GC'd and not recreated elsewhere."""
    from kubernetes_tpu.sim import DaemonSet, HollowCluster

    hub = HollowCluster(seed=21, scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    hub.add_daemonset(DaemonSet("fluentd"))
    for _ in range(2):
        hub.step()
    hub.check_consistency()
    placed = {p.node_name for p in hub.truth_pods.values()
              if p.labels.get("ds") == "fluentd"}
    assert placed == {f"n{i}" for i in range(4)}  # one per node, pinned
    # node join -> daemon pod follows
    hub.add_node(make_node("n4", cpu_milli=4000))
    for _ in range(2):
        hub.step()
    assert any(p.node_name == "n4" for p in hub.truth_pods.values()
               if p.labels.get("ds") == "fluentd")
    # node gone -> its daemon pod is deleted, never rescheduled elsewhere
    hub.remove_node("n2")
    for _ in range(2):
        hub.step()
    hub.check_consistency()
    ds_pods = [p for p in hub.truth_pods.values()
               if p.labels.get("ds") == "fluentd"]
    assert len(ds_pods) == 4 and all(p.node_name != "n2" for p in ds_pods)
    # cascade delete
    hub.delete_daemonset("fluentd")
    hub.step()
    assert not any(p.labels.get("ds") == "fluentd"
                   for p in hub.truth_pods.values())


def test_daemonset_node_selector_limits_eligibility():
    from kubernetes_tpu.sim import DaemonSet, HollowCluster

    hub = HollowCluster(seed=22, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("gpu-0", cpu_milli=4000, labels={"accel": "gpu"}))
    hub.add_node(make_node("cpu-0", cpu_milli=4000))
    hub.add_daemonset(DaemonSet("driver", node_selector={"accel": "gpu"}))
    for _ in range(2):
        hub.step()
    placed = {p.node_name for p in hub.truth_pods.values()
              if p.labels.get("ds") == "driver"}
    assert placed == {"gpu-0"}


def test_daemonset_pods_tolerate_unreachable_taint():
    """The taint manager evicts ordinary pods from an unreachable node;
    daemon pods carry the Exists/NoExecute tolerations the daemonset
    controller stamps (daemon/util AddOrUpdateDaemonPodTolerations) and
    must stay bound for the whole outage."""
    from kubernetes_tpu.sim import DaemonSet, HollowCluster, ReplicaSet

    hub = HollowCluster(seed=23, node_grace_s=40.0, eviction_wait_s=30.0)
    for i in range(3):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    hub.add_daemonset(DaemonSet("fluentd"))
    hub.add_replicaset(ReplicaSet("svc", replicas=6, cpu_milli=500))
    for _ in range(3):
        hub.step(dt=15.0)
    assert hub.pending_count() == 0
    hub.kill_kubelet("n1")
    for _ in range(11):  # grace + eviction window pass
        hub.step(dt=15.0)
    hub.check_consistency()
    on_n1 = [p for p in hub.truth_pods.values() if p.node_name == "n1"]
    assert [p.labels.get("ds") for p in on_n1] == ["fluentd"]  # only the daemon
    hub.heal_kubelet("n1")
    hub.step(dt=15.0)
    assert any(p.node_name == "n1" for p in hub.truth_pods.values()
               if p.labels.get("ds") == "fluentd")


def test_statefulset_ordered_creation_and_reverse_scale_down():
    from kubernetes_tpu.sim import HollowCluster, StatefulSet

    hub = HollowCluster(seed=24, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_statefulset(StatefulSet("db", replicas=3))
    seen_order = []
    for _ in range(5):
        hub.step()
        for p in hub.truth_pods.values():
            if p.labels.get("ss") == "db" and p.name not in seen_order:
                seen_order.append(p.name)
    assert seen_order == ["db-0", "db-1", "db-2"]  # strict ordinal order
    hub.check_consistency()
    # reverse-order scale down, one per sync
    hub.scale_statefulset("db", 1)
    hub.step()
    names = sorted(p.name for p in hub.truth_pods.values()
                   if p.labels.get("ss") == "db")
    assert names == ["db-0", "db-1"]  # db-2 went first
    hub.step()
    names = sorted(p.name for p in hub.truth_pods.values()
                   if p.labels.get("ss") == "db")
    assert names == ["db-0"]


def test_statefulset_stable_identity_fresh_uid():
    """A deleted middle ordinal is recreated under the SAME name before
    any higher work proceeds, with a fresh apiserver-assigned uid (the
    Binding CAS distinguishes incarnations by uid)."""
    from kubernetes_tpu.sim import HollowCluster, StatefulSet

    hub = HollowCluster(seed=25, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_statefulset(StatefulSet("db", replicas=3))
    for _ in range(5):
        hub.step()
    old_uid = hub.truth_pods["default/db-1"].uid
    hub.delete_pod("default/db-1")
    for _ in range(2):
        hub.step()
    hub.check_consistency()
    new = hub.truth_pods["default/db-1"]
    assert new.uid != old_uid and new.node_name  # same identity, new life


def test_daemonset_repairs_mispinned_pod():
    """The apiserver accepts a Binding that violates required node
    affinity (assignPod does not re-check predicates); a competing writer
    can therefore land a daemon pod on the wrong node. The controller's
    expectations pass must delete the mispin and recreate it on its node
    (r3 review: ds.live trusted the intended node and never repaired)."""
    from kubernetes_tpu.sim import DaemonSet, HollowCluster

    hub = HollowCluster(seed=26, scheduler_kw={"enable_preemption": False})
    for i in range(3):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    hub.add_daemonset(DaemonSet("fluentd"))
    hub.step()
    hub.settle()
    # forge a competing-writer mispin: rebind n0's daemon pod onto n1
    key = "default/fluentd-n0"
    pod = hub.truth_pods[key]
    assert pod.node_name == "n0"
    import dataclasses
    hub.truth_pods[key] = dataclasses.replace(pod, node_name="")
    hub.confirm_binding(hub.truth_pods[key], "n1")
    hub.sched.on_pod_update(pod, hub.truth_pods[key])
    for _ in range(3):
        hub.step()
    hub.check_consistency()
    by_node = {p.node_name for p in hub.truth_pods.values()
               if p.labels.get("ds") == "fluentd"}
    assert by_node == {"n0", "n1", "n2"}
    assert hub.truth_pods["default/fluentd-n0"].node_name == "n0"


def test_daemonset_defers_cordoned_and_tainted_nodes():
    """shouldSchedule vs shouldContinueRunning: a cordoned or untolerated-
    tainted node gets NO daemon pod (no permanently-pending pod parked on
    it), but the pod appears on the sync after the gate clears."""
    from kubernetes_tpu.api.types import Taint
    from kubernetes_tpu.sim import DaemonSet, HollowCluster

    hub = HollowCluster(seed=27, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("ok", cpu_milli=4000))
    cordoned = make_node("cordoned", cpu_milli=4000)
    cordoned.unschedulable = True
    hub.add_node(cordoned)
    hub.add_node(make_node("dedicated", cpu_milli=4000,
                           taints=[Taint("team", "infra")]))
    hub.add_daemonset(DaemonSet("fluentd"))
    for _ in range(2):
        hub.step()
    assert hub.pending_count() == 0  # nothing parked forever
    placed = {p.node_name for p in hub.truth_pods.values()
              if p.labels.get("ds") == "fluentd"}
    assert placed == {"ok"}
    # uncordon + untaint -> next syncs place the daemons
    import dataclasses
    hub._update_node(dataclasses.replace(
        hub.truth_nodes["cordoned"], unschedulable=False))
    hub._update_node(dataclasses.replace(
        hub.truth_nodes["dedicated"], taints=()))
    for _ in range(2):
        hub.step()
    hub.check_consistency()
    placed = {p.node_name for p in hub.truth_pods.values()
              if p.labels.get("ds") == "fluentd"}
    assert placed == {"ok", "cordoned", "dedicated"}


# ---------------------------------------------------------------------------
# CronJob / HPA controllers
# (pkg/controller/cronjob syncOne, pkg/controller/podautoscaler horizontal.go)
# ---------------------------------------------------------------------------


def test_cronjob_spawns_on_schedule_and_gcs_history():
    from kubernetes_tpu.sim import CronJob, HollowCluster

    hub = HollowCluster(seed=31, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_cronjob(CronJob("tick", every_s=30.0, duration_s=10.0,
                            history_limit=2))
    for _ in range(12):
        hub.step(dt=15.0)  # 180s -> 6 scheduled runs
    cj = hub.cronjobs["tick"]
    assert cj.runs == 6
    # history trimmed to the limit: only the newest finished jobs remain
    finished = [jn for jn in cj.spawned if hub.jobs[jn].done()]
    assert len(finished) <= 2
    hub.check_consistency()


def test_cronjob_forbid_skips_while_active():
    from kubernetes_tpu.sim import CronJob, HollowCluster

    hub = HollowCluster(seed=32, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    # each run outlives the period: Forbid must skip overlapping starts
    hub.add_cronjob(CronJob("slow", every_s=15.0, duration_s=120.0,
                            concurrency="Forbid"))
    for _ in range(6):
        hub.step(dt=15.0)
    cj = hub.cronjobs["slow"]
    assert cj.runs == 1  # later ticks all skipped while run 1 is active
    active = [p for p in hub.truth_pods.values()
              if p.labels.get("job", "").startswith("slow-")]
    assert len(active) == 1


def test_cronjob_replace_preempts_active_run():
    from kubernetes_tpu.sim import CronJob, HollowCluster

    hub = HollowCluster(seed=33, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_cronjob(CronJob("fresh", every_s=15.0, duration_s=120.0,
                            concurrency="Replace"))
    for _ in range(4):
        hub.step(dt=15.0)
    cj = hub.cronjobs["fresh"]
    assert cj.runs == 4  # every tick replaces the previous run
    live_jobs = {jn for jn in cj.spawned if jn in hub.jobs}
    assert live_jobs == {"fresh-4"}
    hub.check_consistency()


def test_hpa_scales_deployment_with_load():
    from kubernetes_tpu.sim import (
        Deployment,
        HollowCluster,
        HorizontalPodAutoscaler,
    )

    hub = HollowCluster(seed=34, scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    hub.add_deployment(Deployment("web", replicas=2))
    load = {"util": 1.0}  # 2x the 0.5 target -> double the replicas
    hub.add_hpa(HorizontalPodAutoscaler(
        "web-hpa", deployment="web", min_replicas=2, max_replicas=10,
        target_utilization=0.5, load_fn=lambda: load["util"]))
    hub.step()
    assert hub.deployments["web"].replicas == 4
    hub.step()
    assert hub.deployments["web"].replicas == 8
    hub.step()
    assert hub.deployments["web"].replicas == 10  # max clamp
    # load collapses -> scale down to the min clamp
    load["util"] = 0.01
    hub.step()
    assert hub.deployments["web"].replicas == 2
    # inside the 10% tolerance dead-band: no resize
    load["util"] = 0.52
    hub.step()
    assert hub.deployments["web"].replicas == 2
    for _ in range(2):
        hub.step()
    hub.check_consistency()


def test_cronjob_forbid_drops_missed_runs_no_burst():
    """Regression (r3 review): while a long job blocks Forbid, the
    schedule must catch up past NOW — finishing the job must not unleash
    a burst of make-up runs for every missed period."""
    from kubernetes_tpu.sim import CronJob, HollowCluster

    hub = HollowCluster(seed=35, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    hub.add_cronjob(CronJob("slow", every_s=10.0, duration_s=100.0,
                            concurrency="Forbid"))
    for _ in range(10):  # run 1 finishes at t=105; fresh run at t=120
        hub.step(dt=15.0)
    cj = hub.cronjobs["slow"]
    assert cj.runs == 2  # run 1, then exactly one fresh run after it ended
    assert cj.next_run > 120.0


def test_cronjob_never_overwrites_foreign_job():
    """Regression (r3 review): a user Job occupying '{cron}-{n}' must not
    be clobbered — the apiserver would reject the duplicate create."""
    from kubernetes_tpu.sim import CronJob, HollowCluster, Job

    hub = HollowCluster(seed=36, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000))
    user_job = Job("tick-1", completions=3, duration_s=200.0)
    hub.add_job(user_job)
    hub.add_cronjob(CronJob("tick", every_s=30.0, duration_s=10.0))
    for _ in range(3):
        hub.step(dt=15.0)
    assert hub.jobs["tick-1"] is user_job  # untouched
    cj = hub.cronjobs["tick"]
    assert "tick-1" not in cj.spawned and cj.spawned[0] == "tick-2"
    hub.check_consistency()


def test_multiple_schedulers_split_responsibility():
    """TestMultipleSchedulers analog (test/integration/scheduler,
    eventhandlers.go:328 responsibleForPod): a pod naming a different
    scheduler is invisible to the default scheduler's queue but its
    BOUND form still consumes capacity in every scheduler's cache."""
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=61, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))
    hub.create_pod(make_pod("mine", cpu_milli=500))
    foreign = make_pod("theirs", cpu_milli=3000)
    foreign.scheduler_name = "custom-scheduler"
    hub.create_pod(foreign)

    hub.step()
    hub.settle()
    # default scheduler bound only its own pod; the foreign one pends
    assert hub.truth_pods["default/mine"].node_name == "n0"
    assert hub.truth_pods["default/theirs"].node_name == ""
    assert hub.pending_count() == 1

    # the custom scheduler, fed through a Reflector, picks it up
    custom = Scheduler(clock=hub.clock, enable_preemption=False,
                       scheduler_name="custom-scheduler",
                       binder=hub.binder)
    r = Reflector(hub, custom)
    r.list_and_watch()
    res = custom.schedule_cycle()
    assert res.scheduled == 1
    hub.settle()
    assert hub.truth_pods["default/theirs"].node_name == "n0"
    hub.check_consistency()

    # capacity accounting: the foreign BOUND pod (3000m) now crowds out
    # the default scheduler — a 2000m pod of its own cannot fit
    r.pump()
    hub.create_pod(make_pod("mine2", cpu_milli=2000))
    hub.step()
    hub.settle()
    assert hub.truth_pods["default/mine2"].node_name == ""
    # while a 500m pod still fits beside it
    hub.create_pod(make_pod("mine3", cpu_milli=500))
    hub.step()
    hub.settle()
    assert hub.truth_pods["default/mine3"].node_name == "n0"
    hub.check_consistency()


def test_foreign_pod_update_stays_out_of_queue():
    from kubernetes_tpu.scheduler import Scheduler

    s = Scheduler(enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=4000))
    p = make_pod("x", cpu_milli=100)
    p.scheduler_name = "other"
    s.on_pod_add(p)
    import dataclasses
    s.on_pod_update(p, dataclasses.replace(p, labels={"a": "b"}))
    res = s.schedule_cycle()
    assert res.attempted == 0 and res.assignments == {}


def test_responsibility_handover_dequeues():
    """Regression (r3 review): an update that moves a queued pod to a
    different schedulerName must dequeue it here (the reference's
    FilteringResourceEventHandler emits a Delete on the transition)."""
    import dataclasses

    from kubernetes_tpu.scheduler import Scheduler

    s = Scheduler(enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=4000))
    p = make_pod("x", cpu_milli=100)
    s.on_pod_add(p)
    s.on_pod_update(p, dataclasses.replace(p, scheduler_name="other"))
    res = s.schedule_cycle()
    assert res.attempted == 0 and res.assignments == {}
    # and the reverse handover queues it
    q = dataclasses.replace(p, scheduler_name="other")
    s.on_pod_update(q, p)
    res = s.schedule_cycle()
    assert res.scheduled == 1


def test_zone_spreading_ubernetes_lite_analog():
    """test/e2e/scheduling/ubernetes_lite.go analog: replicas of a
    service spread across zones via SelectorSpread's 2/3 zone weighting,
    end-to-end. Measured solver fidelity (canaries, not aspirations):

    - greedy (serial parity): 4/3/2 over zones sized 4/2/2 — the
      reference's RANDOMIZED selectHost tie-break would average 3/3/3;
      our deterministic lowest-index tie-break (documented divergence,
      PARITY.md) biases the low-index zone by one.
    - batch (default): 5/2/2 — usage-sensitive spread scores are stale
      within a round (all nine admit before counts update), the
      throughput/fidelity tradeoff per_node_cap governs. Every pod still
      places and z0 never exceeds its node share + 1.
    """
    from kubernetes_tpu.api.types import LabelSelector
    from kubernetes_tpu.scheduler import Scheduler

    layout = ["z0", "z0", "z0", "z0", "z1", "z1", "z2", "z2"]
    svc = LabelSelector(match_labels={"app": "web"})

    node_zone = {f"n{i}": z for i, z in enumerate(layout)}

    def spread_with(solver):
        s = Scheduler(enable_preemption=False, solver=solver)
        for name, z in node_zone.items():
            s.on_node_add(make_node(name, cpu_milli=8000, zone=z))
        for i in range(9):
            s.on_pod_add(make_pod(f"w{i}", cpu_milli=100,
                                  labels={"app": "web"},
                                  spread_selectors=(svc,)))
        res = s.schedule_cycle()
        assert res.scheduled == 9
        # pre-seed every zone so a fully starved zone shows up as 0
        zones = {z: 0 for z in layout}
        for nd in res.assignments.values():
            zones[node_zone[nd]] += 1
        return zones

    greedy = spread_with("greedy")
    assert max(greedy.values()) - min(greedy.values()) <= 2, greedy
    assert greedy["z1"] >= 2 and greedy["z2"] >= 2, greedy
    batch = spread_with("batch")
    assert max(batch.values()) <= 5, batch       # zone share + 1 bound
    assert min(batch.values()) >= 2, batch       # no zone starved
