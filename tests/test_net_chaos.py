"""Network-fault robustness (ISSUE 15): the ambiguous-RPC bind
protocol, watch-stream fuzzing, and the state-conservation auditor.

Four layers, cheapest first:

1. the fault primitives — ``FaultInjector.rpc_hook`` (the ambiguous
   commit-coin, determinism under a seed) and the per-replica jitter
   seeding of the hub-seam RetryPolicies;
2. the scheduler's ambiguous-outcome bind protocol — a timed-out bind
   is resolved by read-your-write verification (adopt / requeue /
   conflict / gone), parked when the verification GET is itself
   unreachable, and NEVER blind-retried;
3. reflector/informer hardening — resourceVersion-monotonic dedupe
   (fuzzed duplicate/reorder/drop tapes converge to the clean-tape
   state, seeds 1/2/3), the progress-deadline stall detector
   (regression-pinned with a fake clock), and the jittered relist
   backoff under a 410 storm;
4. the composed :class:`~kubernetes_tpu.chaos.NetChaos` harness — the
   invariant the whole stack must keep under all of it at once: every
   schedulable pod bound, zero bind RPCs reaching the hub for an
   already-bound pod, zero state-conservation violations.

Plus the contracts that ride along: the auditor's invariant set, the
REST facade's network-fault seam, the new config fields' round-trip +
validation, the bench_compare ``netchaos`` gate family, and graftlint
R2/R3/R7 pinned over the new modules.
"""

from __future__ import annotations

import os
import random
from types import SimpleNamespace

import pytest

from kubernetes_tpu.chaos import AmbiguousBinder, FuzzedCursor, NetChaos
from kubernetes_tpu.faults import (
    FaultInjector,
    RetryPolicy,
    RPCError,
    RPCTimeout,
)
from kubernetes_tpu.obs.audit import INVARIANTS, StateAuditor
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Clock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class Truth:
    """Minimal CAS'd hub truth for the protocol unit tests: a binder
    that can commit-then-timeout (the ambiguous class), and a reader
    the scheduler verifies against."""

    def __init__(self) -> None:
        self.bound: dict = {}
        self.uids: dict = {}
        self.double_bind_attempts = 0
        self.commits = 0
        #: script for the next bind calls: "ok", "timeout_committed",
        #: "timeout_lost", "error" (consumed left to right; empty = ok)
        self.script: list = []
        #: when True every reader GET raises RPCTimeout (unreachable)
        self.reader_down = False

    def register(self, pod) -> None:
        self.uids[pod.key()] = pod.uid

    def _commit(self, pod, node_name: str) -> None:
        if pod.key() in self.bound:
            self.double_bind_attempts += 1
            raise RuntimeError(f"{pod.key()} already bound")
        self.bound[pod.key()] = node_name
        self.commits += 1

    def bind(self, pod, node_name: str) -> None:
        self.register(pod)
        action = self.script.pop(0) if self.script else "ok"
        if action == "error":
            raise RPCError("injected: definitely not committed")
        if action == "timeout_committed":
            self._commit(pod, node_name)
            raise RPCTimeout("injected: committed, response lost")
        if action == "timeout_lost":
            raise RPCTimeout("injected: not committed, looks identical")
        self._commit(pod, node_name)

    def read(self, key: str):
        if self.reader_down:
            raise RPCTimeout("injected: verification GET unreachable")
        if key not in self.uids:
            return None
        return SimpleNamespace(uid=self.uids[key],
                               node_name=self.bound.get(key, ""))


def _sched(truth: Truth, clock=None, reader=True, **kw):
    clock = clock or Clock()
    s = Scheduler(
        binder=truth, clock=clock, enable_preemption=False,
        retry_sleep=lambda _s: None, jitter_seed=1,
        pod_reader=truth.read if reader else None, **kw)
    s.on_node_add(make_node("n0", cpu_milli=8000))
    s.on_node_add(make_node("n1", cpu_milli=8000))
    return s, clock


# ---------------------------------------------------------------------------
# fault primitives: rpc_hook + per-replica jitter
# ---------------------------------------------------------------------------


def test_rpc_hook_ambiguous_commit_coin():
    """rpc_timeout rolls the rule's commit-coin; commit_rate 0/1 force
    the outcome and the same seed replays the same stream."""
    inj = FaultInjector(seed=3)
    inj.arm("rpc:bind", "rpc_timeout", rate=1.0, commit_rate=1.0)
    kind, _rule, committed = inj.rpc_hook("rpc:bind")
    assert kind == "rpc_timeout" and committed
    inj2 = FaultInjector(seed=3)
    inj2.arm("rpc:bind", "rpc_timeout", rate=1.0, commit_rate=0.0)
    kind, _rule, committed = inj2.rpc_hook("rpc:bind")
    assert kind == "rpc_timeout" and not committed
    # determinism: two injectors with one seed agree coin-for-coin
    a = FaultInjector(seed=9).arm("x", "rpc_timeout", commit_rate=0.5)
    b = FaultInjector(seed=9).arm("x", "rpc_timeout", commit_rate=0.5)
    assert [a.rpc_hook("x")[2] for _ in range(16)] == \
           [b.rpc_hook("x")[2] for _ in range(16)]


def test_rpc_hook_error_never_commits():
    inj = FaultInjector(seed=1)
    inj.arm("rpc:bind", "rpc_error", rate=1.0)
    kind, _rule, committed = inj.rpc_hook("rpc:bind")
    assert kind == "rpc_error" and committed is False


def test_per_replica_jitter_streams_decorrelate():
    """Two replicas sharing one RetryPolicy CONFIG must not share the
    jitter STREAM — lockstep retry trains from a whole fleet landing on
    a recovering hub at once is the stampede the full jitter exists to
    prevent. Unpinned schedulers derive distinct seeds; a pinned seed
    replays exactly (the tests' determinism handle)."""
    t = Truth()
    a = Scheduler(binder=t, enable_preemption=False,
                  retry_sleep=lambda _s: None)
    b = Scheduler(binder=t, enable_preemption=False,
                  retry_sleep=lambda _s: None)
    assert a._jitter_seed != b._jitter_seed
    seq_a = [a._transport_retry.backoff_s(i) for i in range(6)]
    seq_b = [b._transport_retry.backoff_s(i) for i in range(6)]
    assert seq_a != seq_b
    # pinned: same seed -> identical streams (reproducible tests)
    c = Scheduler(binder=t, enable_preemption=False, jitter_seed=7,
                  retry_sleep=lambda _s: None)
    d = Scheduler(binder=t, enable_preemption=False, jitter_seed=7,
                  retry_sleep=lambda _s: None)
    assert [c._transport_retry.backoff_s(i) for i in range(6)] == \
           [d._transport_retry.backoff_s(i) for i in range(6)]
    # the bind-verify policy rides the same replica stream, offset so
    # the two policies inside one replica don't mirror each other
    assert [c._bind_verify_retry.backoff_s(i) for i in range(4)] != \
           [c._transport_retry.backoff_s(i) for i in range(4)]


# ---------------------------------------------------------------------------
# the ambiguous-outcome bind protocol
# ---------------------------------------------------------------------------


def test_ambiguous_bind_adopted_never_rebinds():
    """The hub committed before the response was lost: read-your-write
    sees uid+node agree -> ADOPT. The pod lands scheduled, exactly one
    commit reached the hub, and no second bind RPC was issued."""
    t = Truth()
    t.script = ["timeout_committed"]
    s, _ = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 1 and "default/p0" in res.assignments
    assert t.commits == 1 and t.double_bind_attempts == 0
    assert s.metrics.bind_ambiguous.value(resolution="adopted") == 1


def test_ambiguous_bind_requeued_when_verified_uncommitted():
    """The timeout was a true failure: verification sees the pod
    unbound -> the normal requeue path retries SAFELY (the retry is a
    fresh bind of an unbound pod, not a blind re-send)."""
    t = Truth()
    t.script = ["timeout_lost"]
    s, clock = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 0 and res.bind_errors == 1
    assert t.commits == 0
    assert s.metrics.bind_ambiguous.value(resolution="requeued") == 1
    # the retry binds cleanly once the backoff / unschedulable flush
    # elapses (bind failures park in the unschedulable queue, 60s)
    for _ in range(30):
        clock.advance(10.0)
        if s.schedule_cycle().scheduled:
            break
    assert t.bound.get("default/p0") and t.commits == 1
    assert t.double_bind_attempts == 0


def test_ambiguous_bind_parked_until_hub_answers():
    """Verification unreachable too: the pod PARKS assumed (capacity
    held, no TTL) and every cycle / idle tick re-probes; when the hub
    answers the park resolves exactly like the in-cycle path."""
    t = Truth()
    t.script = ["timeout_committed"]
    t.reader_down = True
    s, clock = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    res = s.schedule_cycle()
    assert res.scheduled == 0
    assert "default/p0" in s._ambiguous_binds
    assert s.cache.is_assumed("default/p0")
    assert s.metrics.bind_ambiguous.value(resolution="deferred") == 1
    # a long outage must NOT TTL-reap the park into a requeue — that
    # blind retry is exactly the double-placement the protocol forbids
    clock.advance(s.cache.ttl_s + 5)
    s.idle_tick()
    assert "default/p0" in s._ambiguous_binds
    assert t.commits == 1 and t.double_bind_attempts == 0
    # hub heals -> the re-probe adopts; nothing was re-bound
    t.reader_down = False
    s.idle_tick()
    assert not s._ambiguous_binds
    assert not s.cache.is_assumed("default/p0")  # confirmed bound
    assert s.cache.pod("default/p0") is not None
    assert t.commits == 1 and t.double_bind_attempts == 0
    assert s.metrics.bind_ambiguous.value(resolution="adopted") == 1


def test_ambiguous_bind_gone_and_conflict():
    """Deleted mid-bind reads as gone; recreated under a new uid (or
    bound elsewhere) reads as conflict — both forget-and-requeue, never
    adopt a binding that is not provably OURS."""
    t = Truth()
    t.script = ["timeout_lost"]
    s, _ = _sched(t)
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    t.bind = lambda pod, node: (_ for _ in ()).throw(
        RPCTimeout("lost"))  # never commits, never registers
    # gone: the reader has never seen the pod (deleted mid-bind)
    s.schedule_cycle()
    assert s.metrics.bind_ambiguous.value(resolution="gone") == 1
    # conflict: recreated under a different uid, bound elsewhere
    s.queue.delete("default/p0")
    p2 = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p2)
    t.uids["default/p0"] = "someone-else"
    t.bound["default/p0"] = "n1"
    s.schedule_cycle()
    assert s.metrics.bind_ambiguous.value(resolution="conflict") == 1
    assert not s.cache.is_assumed("default/p0")


def test_ambiguous_bind_without_reader_falls_back_to_ttl():
    """No pod_reader attached: the legacy optimistic fallback — the
    assume TTL arms, the watch confirm or the TTL reap settle it."""
    t = Truth()
    t.script = ["timeout_committed"]
    s, clock = _sched(t, reader=False)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()
    assert s.cache.is_assumed("default/p0")
    assert not s._ambiguous_binds  # parked ONLY when a reader exists
    assert s.metrics.bind_ambiguous.value(resolution="ttl-parked") == 1


def test_expired_assumption_adopts_instead_of_blind_requeue():
    """A lost watch confirmation expires the assume TTL — the SAME
    ambiguity as a timed-out bind. With a reader the reap verifies:
    the hub confirms the binding -> adopt; a blind requeue would have
    re-bound a committed pod (the double-bind the reap used to risk)."""
    t = Truth()
    s, clock = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()  # clean bind; the confirmation never arrives
    assert t.bound.get("default/p0") and s.cache.is_assumed("default/p0")
    clock.advance(s.cache.ttl_s + 1)
    s.idle_tick()
    assert s.metrics.bind_ambiguous.value(
        resolution="expired-adopted") == 1
    assert not s.cache.is_assumed("default/p0")  # confirmed bound
    assert s.cache.pod("default/p0") is not None
    assert s.queue.pod("default/p0") is None
    for _ in range(5):  # and no later cycle re-binds it
        clock.advance(10.0)
        s.schedule_cycle()
    assert t.commits == 1 and t.double_bind_attempts == 0


def test_expired_assumption_requeues_only_when_verified_unbound():
    """The reap's requeue survives, but only after the hub CONFIRMS the
    pod is unbound (a genuinely lost bind, e.g. hub state rollback)."""
    t = Truth()
    s, clock = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()
    del t.bound["default/p0"]  # the hub lost the binding
    clock.advance(s.cache.ttl_s + 1)
    s.idle_tick()
    assert s.metrics.bind_ambiguous.value(
        resolution="expired-requeued") == 1
    assert s.queue.pod("default/p0") is not None
    assert not s.cache.is_assumed("default/p0")


def test_expired_assumption_parks_during_hub_outage():
    """TTL expiry while the hub is unreachable: the pod re-parks
    assumed (capacity held, no TTL) rather than requeueing into a
    potential double bind; the park resolves when the hub answers —
    WITHOUT replaying the success tail (the original bind already
    fired its Scheduled event and postbind)."""
    t = Truth()
    events = []
    s, clock = _sched(t)
    s.event_sink = lambda reason, obj, msg="": events.append(reason)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()
    assert events.count("Scheduled") == 1
    t.reader_down = True
    clock.advance(s.cache.ttl_s + 1)
    s.idle_tick()
    assert "default/p0" in s._ambiguous_binds
    assert s.cache.is_assumed("default/p0")
    t.reader_down = False
    s.idle_tick()
    assert not s._ambiguous_binds
    assert s.cache.pod("default/p0") is not None
    assert t.commits == 1 and t.double_bind_attempts == 0
    assert events.count("Scheduled") == 1  # no duplicate event


def test_watch_settled_park_still_runs_success_tail():
    """An in-cycle park the WATCH settles (confirmed add before the
    re-probe) owes the full success tail its original bind never
    reached: Scheduled event, adopted resolution — not a silent drop."""
    import dataclasses as _dc

    t = Truth()
    events = []
    t.script = ["timeout_committed"]
    t.reader_down = True
    s, _ = _sched(t)
    s.event_sink = lambda reason, obj, msg="": events.append(reason)
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    s.schedule_cycle()
    assert "default/p0" in s._ambiguous_binds
    assert events.count("Scheduled") == 0  # tail never ran
    # the watch MODIFIED confirms the bind while the hub GET is down
    s.on_pod_update(p, _dc.replace(p, node_name="n0"))
    s.idle_tick()
    assert not s._ambiguous_binds
    assert events.count("Scheduled") == 1
    assert s.metrics.bind_ambiguous.value(resolution="adopted") == 1
    assert t.commits == 1 and t.double_bind_attempts == 0


def test_deleted_parked_pod_releases_assumption():
    """A parked ambiguous bind resolves by deletion: the pod is gone
    whatever the RPC did — the TTL-less assumption must not leak."""
    t = Truth()
    t.script = ["timeout_committed"]
    t.reader_down = True
    s, _ = _sched(t)
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    s.schedule_cycle()
    assert s.cache.is_assumed("default/p0")
    s.on_pod_delete(p)
    assert not s._ambiguous_binds
    assert not s.cache.is_assumed("default/p0")
    assert s.cache.pod("default/p0") is None


# ---------------------------------------------------------------------------
# reflector/informer hardening
# ---------------------------------------------------------------------------


def _mirror(hub):
    return Scheduler(clock=hub.clock, enable_preemption=False)


def _truth_map(hub):
    return {k: p.node_name for k, p in hub.truth_pods.items()}


def _synced(sched, hub) -> None:
    from kubernetes_tpu.debugger import compare

    node_diffs, pod_diffs = compare(sched, _truth_map(hub),
                                    list(hub.truth_nodes))
    assert not node_diffs and not pod_diffs, (node_diffs, pod_diffs)


def _churn_tape(hub, rng, steps, on_step):
    """Seeded mutation tape: creates, binds (via the hub's own
    scheduler), deletes — the event stream the reflectors mirror."""
    n = 0
    for step in range(steps):
        for _ in range(rng.randrange(1, 4)):
            hub.create_pod(make_pod(f"t{n}", cpu_milli=100))
            n += 1
        if step % 3 == 1:
            hub.sched.schedule_cycle()
        if step % 4 == 3:
            bound = [k for k, p in hub.truth_pods.items() if p.node_name]
            if bound:
                hub.delete_pod(rng.choice(bound))
        on_step(step)
        hub.clock.advance(0.25)


@pytest.mark.parametrize("seed", (1, 2, 3))
def test_reflector_fuzz_dup_reorder_converges_without_relist(seed):
    """Duplicated + reordered watch frames over a seeded tape are pure
    no-ops: the resourceVersion-monotonic dedupe converges the fuzzed
    informer to the clean-tape state with ZERO relists."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=seed,
                        scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=16000))
    inj = FaultInjector(seed=seed)
    inj.arm("watch:event", "duplicate", rate=0.35)
    inj.arm("watch:batch", "reorder", rate=0.6)
    clean, fuzzed = _mirror(hub), _mirror(hub)
    rc = Reflector(hub, clean)
    rf = Reflector(hub, fuzzed,
                   cursor_wrap=lambda c: FuzzedCursor(c, inj, seed=seed))
    rc.list_and_watch()
    rf.list_and_watch()
    rng = random.Random(seed)
    _churn_tape(hub, rng, 16, lambda _s: (rc.pump(), rf.pump()))
    rc.pump()
    rf.pump()
    assert rf.deduped > 0, "the fuzz must have actually duplicated"
    assert rf.relists == 0, "dedupe alone absorbs dup/reorder"
    _synced(clean, hub)
    _synced(fuzzed, hub)
    assert {k: p.node_name for k, p in rf.pods.items()} == \
           {k: p.node_name for k, p in rc.pods.items()}


@pytest.mark.parametrize("seed", (1, 2, 3))
def test_reflector_fuzz_with_drops_converges_via_relist(seed):
    """Dropped frames are partial silence — only a relist (resync or
    stall-forced) can heal them; with the healing machinery running the
    fuzzed informer still converges to the clean-tape state."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=seed,
                        scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=16000))
    inj = FaultInjector(seed=seed)
    inj.arm("watch:event", "drop", rate=0.25)
    inj.arm("watch:event", "duplicate", rate=0.2)
    inj.arm("watch:batch", "reorder", rate=0.4)
    clean, fuzzed = _mirror(hub), _mirror(hub)
    rc = Reflector(hub, clean)
    rf = Reflector(hub, fuzzed, clock=hub.clock,
                   progress_deadline_s=2.0,
                   relist_backoff=RetryPolicy(base_s=0.1, max_s=0.5,
                                              jitter=0.5, seed=seed),
                   cursor_wrap=lambda c: FuzzedCursor(c, inj, seed=seed))
    rc.list_and_watch()
    rf.list_and_watch()
    rng = random.Random(seed)

    def step(i):
        rc.pump()
        rf.pump()
        if i % 5 == 4:  # the SharedInformer resync period
            rf.list_and_watch()

    _churn_tape(hub, rng, 20, step)
    rc.pump()
    rf.list_and_watch()  # final resync heals the tail drops
    cursor = rf._cursor
    assert cursor.dropped > 0 or rf.deduped > 0
    _synced(clean, hub)
    _synced(fuzzed, hub)


def test_stalled_watch_forces_jittered_relist():
    """Satellite regression pin (fake clock): a cursor yielding nothing
    past the progress deadline WHILE the hub advanced revisions is
    stalled — forced relist with backoff, never indefinite idle. A hub
    that genuinely went quiet never triggers it."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=5,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))

    class EatingCursor:
        """Half-open connection: the hub advances, this delivers
        nothing, raises nothing."""

        def __init__(self, inner) -> None:
            self.inner = inner

        def poll(self):
            self.inner.poll()
            return []

    sink = _mirror(hub)
    r = Reflector(hub, sink, clock=hub.clock, progress_deadline_s=5.0,
                  relist_backoff=RetryPolicy(base_s=0.1, max_s=0.5,
                                             jitter=0.5, seed=2),
                  cursor_wrap=EatingCursor)
    r.list_and_watch()
    hub.create_pod(make_pod("stalled", cpu_milli=100))
    for _ in range(4):  # 4s < deadline: not stalled yet
        r.pump()
        hub.clock.advance(1.0)
    assert r.stalled_relists == 0
    assert sink.queue.pod("default/stalled") is None
    for _ in range(3):
        r.pump()
        hub.clock.advance(1.0)
    assert r.stalled_relists >= 1
    # the relist's Replace delivered what the dead stream ate
    assert sink.queue.pod("default/stalled") is not None
    # genuine idle is NOT a stall: hub quiet, deadline elapsing freely
    before = r.stalled_relists
    for _ in range(30):
        r.pump()
        hub.clock.advance(1.0)
    assert r.stalled_relists == before


def test_stalled_watch_without_deadline_idles_forever():
    """The pre-hardening behavior, pinned: an explicit
    progress_deadline_s=0 (the off switch) never force-relists — the
    exact silent-stall hang the deadline exists to break. Left unset,
    the deadline inherits robustness.watchProgressDeadline from a
    Scheduler sink (the config knob governs real reflectors)."""
    from kubernetes_tpu.config import RobustnessConfig
    from kubernetes_tpu.scheduler import Scheduler as _S
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=6,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))

    class EatingCursor:
        def __init__(self, inner) -> None:
            self.inner = inner

        def poll(self):
            self.inner.poll()
            return []

    sink = _mirror(hub)
    # unset -> the sink scheduler's config supplies the deadline
    inherits = Reflector(hub, sink, clock=hub.clock)
    assert inherits.progress_deadline_s == \
        sink.robustness.watch_progress_deadline_s == 30.0
    tuned = _S(clock=hub.clock, enable_preemption=False,
               robustness=RobustnessConfig(
                   watch_progress_deadline_s=7.0))
    assert Reflector(hub, tuned,
                     clock=hub.clock).progress_deadline_s == 7.0
    r = Reflector(hub, sink, clock=hub.clock, progress_deadline_s=0,
                  cursor_wrap=EatingCursor)
    r.list_and_watch()
    hub.create_pod(make_pod("lost", cpu_milli=100))
    for _ in range(50):
        r.pump()
        hub.clock.advance(10.0)
    assert r.stalled_relists == 0 and r.relists == 0
    assert sink.queue.pod("default/lost") is None


def test_relist_storm_backoff_bounds_the_stampede():
    """A 410 storm (every poll Compacted) forces ONE relist per
    jittered cool-down window, not one per poll — the anti-stampede
    half of the storm handling."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=7,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))
    inj = FaultInjector(seed=7)
    inj.arm("watch:batch", "compacted", rate=1.0)
    sink = _mirror(hub)
    r = Reflector(hub, sink, clock=hub.clock,
                  relist_backoff=RetryPolicy(base_s=8.0, max_s=64.0,
                                             jitter=0.25, seed=7),
                  cursor_wrap=lambda c: FuzzedCursor(c, inj, seed=7))
    r.list_and_watch()
    for _ in range(40):  # 40 polls over 4s, all 410
        r.pump()
        hub.clock.advance(0.1)
    # base_s=8 with +-25% jitter: at most ONE relist fit in 4s
    assert r.relists <= 1
    assert r._cursor.forced_410 >= 1


# ---------------------------------------------------------------------------
# the state-conservation auditor
# ---------------------------------------------------------------------------


def test_auditor_clean_scheduler_is_clean():
    t = Truth()
    s, _ = _sched(t)
    aud = s.attach_auditor(StateAuditor())
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    assert aud.audit(s) == []
    s.schedule_cycle()
    assert aud.audit(s) == []
    assert aud.audits == 2 and aud.violations_total == 0


def test_auditor_multi_state_and_capacity():
    t = Truth()
    s, _ = _sched(t)
    aud = s.attach_auditor(StateAuditor())
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    s.schedule_cycle()
    # corrupt deliberately: the bound pod re-enters the queue (the
    # double-bind-in-waiting shape)
    s.queue.add_if_not_present(make_pod("p0", cpu_milli=100))
    out = aud.audit(s)
    assert [v.invariant for v in out] == ["multi-state"]
    s.queue.delete("default/p0")
    # capacity: a committed bind that cannot fit
    big = make_pod("huge", cpu_milli=999000, node_name="n0")
    s.cache.add_pod(big)
    out = aud.audit(s)
    assert "capacity" in [v.invariant for v in out]
    assert aud.violations_total >= 2
    assert set(v.invariant for v in list(aud.recent)) <= set(INVARIANTS)


def test_auditor_conservation_needs_explained_exits():
    """A pod that leaves every local state with no note_gone is LOST;
    the same exit with the watch-delete accounting is conserved."""
    t = Truth()
    s, _ = _sched(t)
    aud = s.attach_auditor(StateAuditor())
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    aud.audit(s)
    # silent removal: reach around the scheduler's event surface
    s.queue.delete("default/p0")
    out = aud.audit(s)
    assert [v.invariant for v in out] == ["lost-pod"]
    # explained removal: the watch DELETE path reports note_gone
    p1 = make_pod("p1", cpu_milli=100)
    s.on_pod_add(p1)
    aud.audit(s)
    s.on_pod_delete(p1)
    assert aud.audit(s) == []


def test_auditor_truth_mode_two_strike():
    """Truth-mode checks confirm only across two consecutive audits:
    watch lag alone (resolved before the second audit) never pages."""
    t = Truth()
    s, _ = _sched(t)
    aud = s.attach_auditor(StateAuditor())
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    truth = [make_pod("p0", cpu_milli=100, node_name="n1")]
    truth[0].uid = p.uid
    # first sight: a strike, not a violation (could be watch lag)
    assert aud.audit(s, truth_pods=truth) == []
    # still queued next audit -> double-bind-risk CONFIRMED
    out = aud.audit(s, truth_pods=truth)
    assert [v.invariant for v in out] == ["double-bind-risk"]
    # transient case: the strike clears when the state heals in time
    s2, _ = _sched(t)
    aud2 = s2.attach_auditor(StateAuditor())
    p2 = make_pod("q0", cpu_milli=100)
    s2.on_pod_add(p2)
    truth2 = [make_pod("q0", cpu_milli=100, node_name="n1")]
    truth2[0].uid = p2.uid
    assert aud2.audit(s2, truth_pods=truth2) == []
    s2.on_pod_update(p2, truth2[0])  # the lagging watch catches up
    assert aud2.audit(s2, truth_pods=truth2) == []
    assert aud2.violations_total == 0


def test_auditor_truth_strikes_survive_truthless_sweeps():
    """One auditor serving both the runtime's structural sweeps AND
    periodic truth audits: a truthless sweep between two truth audits
    must not reset a pending strike — 'two consecutive audits' means
    two consecutive audits that LOOKED at the truth."""
    t = Truth()
    s, _ = _sched(t)
    aud = s.attach_auditor(StateAuditor())
    p = make_pod("p0", cpu_milli=100)
    s.on_pod_add(p)
    truth = [make_pod("p0", cpu_milli=100, node_name="n1")]
    truth[0].uid = p.uid
    assert aud.audit(s, truth_pods=truth) == []  # strike one
    assert aud.audit(s) == []                    # structural sweep
    out = aud.audit(s, truth_pods=truth)         # strike two: confirms
    assert [v.invariant for v in out] == ["double-bind-risk"]


def test_reap_origin_park_resolutions_keep_expired_labels():
    """A park made by the TTL reap resolving later must count under
    the expired-* metric labels — the TTL-expiry series stays
    distinguishable from in-cycle bind timeouts."""
    t = Truth()
    s, clock = _sched(t)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()  # clean bind, confirmation never arrives
    t.reader_down = True
    clock.advance(s.cache.ttl_s + 1)
    s.idle_tick()  # expiry -> verification unreachable -> parked
    assert s.metrics.bind_ambiguous.value(
        resolution="expired-deferred") == 1
    t.reader_down = False
    s.idle_tick()  # the park resolves: still an EXPIRED adoption
    assert s.metrics.bind_ambiguous.value(
        resolution="expired-adopted") == 1
    assert s.metrics.bind_ambiguous.value(resolution="adopted") == 0


def test_idle_path_verification_retries_despite_stale_cycle_deadline():
    """The cycle deadline bounds in-cycle verification only: after the
    cycle ends the absolute timestamp is in the past, and the idle-path
    TTL-expiry verification must still get its full retry budget."""
    from kubernetes_tpu.config import RobustnessConfig

    t = Truth()
    calls = {"n": 0}
    real_read = t.read

    def flaky_read(key):
        calls["n"] += 1
        if calls["n"] == 1:  # one transient failure, then truth
            raise RPCTimeout("transient")
        return real_read(key)

    clock = Clock()
    s = Scheduler(binder=t, clock=clock, enable_preemption=False,
                  retry_sleep=lambda _s: None, jitter_seed=1,
                  pod_reader=flaky_read,
                  robustness=RobustnessConfig(cycle_deadline_s=5.0))
    s.on_node_add(make_node("n0", cpu_milli=8000))
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    s.schedule_cycle()  # sets _cycle_deadline = now + 5
    clock.advance(s.cache.ttl_s + 1)  # far past the stale deadline
    s.idle_tick()  # expiry verification: retry must fire -> adopted
    assert s.metrics.bind_ambiguous.value(
        resolution="expired-adopted") == 1
    assert calls["n"] >= 2


def test_reflector_dedupe_floor_compacts_at_relist():
    """The per-object dedupe floor is bounded by the LIVE set: deleted
    pods' entries drop at every relist instead of accumulating forever
    under sustained create/delete churn."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=9,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=16000))
    sink = _mirror(hub)
    r = Reflector(hub, sink)
    r.list_and_watch()
    for i in range(50):
        hub.create_pod(make_pod(f"churn-{i}", cpu_milli=100))
        r.pump()
        hub.delete_pod(f"default/churn-{i}")
        r.pump()
    # the LIVE floor map stays sized to the live set even BETWEEN
    # relists (deleted objects migrate to the bounded tombstone LRU —
    # pre-tombstone this map held every churned pod ever seen)
    assert len(r._obj_rev) < 50
    assert len(r._gone_rev) >= 50  # churned pods (+ their event objects)
    r.list_and_watch()  # relist compacts BOTH maps to the live set
    assert len(r._obj_rev) == 1  # just the node
    assert len(r._gone_rev) == 0
    # dedupe still correct post-compaction
    hub.create_pod(make_pod("after", cpu_milli=100))
    r.pump()
    assert sink.queue.pod("default/after") is not None


def test_auditor_publishes_metric_event_and_flight_flag():
    t = Truth()
    s, _ = _sched(t)
    events = []
    aud = StateAuditor(metrics=s.metrics,
                       event_sink=lambda r, o, m: events.append((r, m)),
                       obs=s.obs)
    s.attach_auditor(aud)
    s.on_pod_add(make_pod("p0", cpu_milli=100))
    aud.audit(s)
    s.queue.delete("default/p0")  # silent loss
    aud.audit(s)
    assert s.metrics.invariant_violations.value(invariant="lost-pod") == 1
    assert events and events[0][0] == "InvariantViolation"
    # the violation parks for the next cycle's flight record
    assert s.obs._pending_invariants == 1


# ---------------------------------------------------------------------------
# the composed NetChaos harness (chaos.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", (1, 2, 3))
def test_net_chaos_converges_with_zero_double_binds(seed):
    """The whole stack under ambiguous binds + fuzzed watch + a relist
    storm: every pod bound, zero bind RPCs reaching the hub for an
    already-bound pod, zero conservation violations, nothing leaked."""
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=seed,
                        scheduler_kw={"enable_preemption": False})
    nc = NetChaos(hub, seed=seed)
    rep = nc.run(n_pods=32, n_nodes=6)
    assert rep["converged"], rep
    assert rep["all_bound"], rep
    assert rep["double_bind_attempts"] == 0, rep
    assert rep["invariant_violations"] == 0, rep["violations"]
    assert rep["leaked_assumptions"] == [] and \
           rep["parked_ambiguous"] == [], rep
    # the chaos demonstrably happened
    assert rep["ambiguous_timeouts"] > 0
    assert rep["watch_deduped"] > 0
    assert rep["relists"] >= 1  # the forced storm at minimum


def test_net_chaos_ambiguous_binder_counts_double_attempts():
    """AmbiguousBinder's invariant meter: a bind RPC REACHING the hub
    for an already-bound pod counts, whoever wins the CAS."""
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=4,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("m0", cpu_milli=4000))
    inj = FaultInjector(seed=4)  # nothing armed: clean network
    b = AmbiguousBinder(hub, inj)
    p = make_pod("dbl", cpu_milli=100)
    hub.create_pod(p)
    b.bind(p, "m0")
    assert b.double_bind_attempts == 0
    # the blind retry the protocol must never issue: the attempt is
    # COUNTED (it reached the hub) and the CAS rejects it
    from kubernetes_tpu.sim import Conflict

    with pytest.raises(Conflict):
        b.bind(p, "m0")
    assert b.double_bind_attempts == 1


# ---------------------------------------------------------------------------
# REST facade network-fault seam
# ---------------------------------------------------------------------------


def test_rest_seam_error_latency_and_ambiguous_timeout():
    """rest:{VERB} rules: rpc_error answers 500 BEFORE the handler acts
    (nothing committed); rpc_timeout lets the handler run but kills the
    response on the wire — the client sees a dead socket while the
    server-side state mutated, the exact ambiguity class."""
    import http.client
    import json as _json

    from kubernetes_tpu.restapi import RestServer
    from kubernetes_tpu.sim import HollowCluster

    hub = HollowCluster(seed=8,
                        scheduler_kw={"enable_preemption": False})
    inj = FaultInjector(seed=8)
    srv = RestServer(hub, fault_injector=inj)
    port = srv.serve()
    pod_doc = {"metadata": {"name": "amb"},
               "spec": {"containers": [{"name": "c", "resources": {
                   "requests": {"cpu": "100m"}}}]}}
    try:
        inj.arm("rest:POST", "rpc_error", rate=1.0, count=1)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/api/v1/namespaces/default/pods",
                     _json.dumps(pod_doc))
        r = conn.getresponse()
        assert r.status == 500
        r.read()
        conn.close()
        assert "default/amb" not in hub.truth_pods  # NOT committed
        # the ambiguous kind: the create COMMITS but the answer dies
        inj.arm("rest:POST", "rpc_timeout", rate=1.0, count=1)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/api/v1/namespaces/default/pods",
                     _json.dumps(pod_doc))
        with pytest.raises(Exception):
            conn.getresponse().read()
        conn.close()
        assert "default/amb" in hub.truth_pods  # committed server-side
        # clean requests keep working afterwards
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/api/v1/namespaces/default/pods/amb")
        r = conn.getresponse()
        assert r.status == 200
        r.read()
        conn.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# config plumbing + serving-runtime wiring
# ---------------------------------------------------------------------------


def test_config_v1alpha1_round_trip_and_validation():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode
    from kubernetes_tpu.cli import validate_config

    cfg = decode({
        "apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
        "kind": "KubeSchedulerConfiguration",
        "robustness": {"bindVerifyRetries": 5,
                       "watchProgressDeadline": "12s"},
        "observability": {"auditInterval": "3s"},
    })
    assert cfg.robustness.bind_verify_retries == 5
    assert cfg.robustness.watch_progress_deadline_s == 12.0
    assert cfg.observability.audit_interval_s == 3.0
    assert validate_config(cfg) == []
    out = encode(cfg)
    assert out["robustness"]["bindVerifyRetries"] == 5
    assert out["robustness"]["watchProgressDeadline"] == "12s"
    assert out["observability"]["auditInterval"] == "3s"
    # defaults: verification on, stall detection on, serving sweep off
    dflt = decode({
        "apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
        "kind": "KubeSchedulerConfiguration",
    })
    assert dflt.robustness.bind_verify_retries == 3
    assert dflt.robustness.watch_progress_deadline_s == 30.0
    assert dflt.observability.audit_interval_s == 0.0
    # a negative duration dies at decode with the field path named
    from kubernetes_tpu.api.scheme import SchemeError

    with pytest.raises(SchemeError, match="watchProgressDeadline"):
        decode({
            "apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
            "kind": "KubeSchedulerConfiguration",
            "robustness": {"watchProgressDeadline": "-5s"},
        })
    # validate_config polices internal configs built directly
    import dataclasses

    bad = dataclasses.replace(
        dflt,
        robustness=dataclasses.replace(
            dflt.robustness, bind_verify_retries=-1,
            watch_progress_deadline_s=-5.0),
        observability=dataclasses.replace(
            dflt.observability, audit_interval_s=-1.0))
    errs = "\n".join(validate_config(bad))
    assert "bindVerifyRetries" in errs
    assert "watchProgressDeadline" in errs
    assert "auditInterval" in errs


def test_serving_runtime_runs_low_frequency_audit():
    """observability.auditInterval > 0 attaches the auditor to the
    composed runtime and sweeps between loop iterations."""
    from kubernetes_tpu.config import ObservabilityConfig
    from kubernetes_tpu.serving import ServingRuntime

    t = Truth()
    clock = Clock()
    s = Scheduler(binder=t, clock=clock, enable_preemption=False,
                  observability=ObservabilityConfig(audit_interval_s=1.0))
    s.on_node_add(make_node("n0", cpu_milli=8000))
    rt = ServingRuntime(s, clock=clock)
    assert rt.auditor is not None and s.auditor is rt.auditor
    # the audit is CHAINED onto the maintenance hook (add_maintenance),
    # so a soak/bench hook added later composes instead of replacing it
    assert rt.loop.maintenance is not None
    rt.loop.maintenance()
    assert rt.auditor.audits == 1
    rt.loop.maintenance()  # not due yet
    assert rt.auditor.audits == 1
    seen = []
    rt.add_maintenance(lambda: seen.append(True))
    clock.advance(1.5)
    rt.loop.maintenance()
    assert rt.auditor.audits == 2 and seen == [True]
    # interval 0 (the default): no auditor, maintenance not armed
    s2 = Scheduler(binder=t, enable_preemption=False)
    s2.on_node_add(make_node("n0", cpu_milli=8000))
    rt2 = ServingRuntime(s2)
    assert rt2.auditor is None and rt2.loop.maintenance is None
    assert rt2.maybe_audit() == 0


# ---------------------------------------------------------------------------
# gate + lint contracts riding along
# ---------------------------------------------------------------------------


def _load_bench_compare():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_compare_netchaos",
        os.path.join(REPO_ROOT, "scripts", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _net_record(**over):
    arm = {
        "double_bind_attempts": 0,
        "invariant_violations": 0,
        "final_truth_audit_violations": 0,
        "audits": 40,
        "drained": True,
        "bound_truth": 500,
        "created": 500,
        "leaked_assumptions": 0,
        "parked_ambiguous": 0,
        "ambiguous_frac_of_binds": 0.03,
        "faults_fired": {"watch:event:duplicate": 30,
                         "watch:batch:reorder": 12},
        "relist_storms": 1,
        "jax": {"retraces": 0},
        "p99_s": 0.2,
        "creates_per_sec": 180.0,
    }
    arm.update(over)
    return {"arms": {"net_chaos": arm}, "errors": []}


def test_bench_compare_netchaos_gates():
    bc = _load_bench_compare()
    assert any(n == "netchaos" for n, _g, _e in bc.GATE_FAMILIES)
    clean = bc.compare_churn_net({}, _net_record(), 0.10)
    assert clean["regressions"] == [], clean
    # every absolute trips on its own violation
    for bad, key, val in (
        ("netchaos.double_bind_attempts", "double_bind_attempts", 1),
        ("netchaos.invariant_violations", "invariant_violations", 2),
        ("netchaos.final_truth_audit_violations",
         "final_truth_audit_violations", 1),
        ("netchaos.all_bound", "leaked_assumptions", 3),
        ("netchaos.ambiguous_frac_of_binds",
         "ambiguous_frac_of_binds", 0.0),
        ("netchaos.relist_storms", "relist_storms", 0),
        ("netchaos.retraces", "jax", {"retraces": 4}),
    ):
        v = bc.compare_churn_net({}, _net_record(**{key: val}), 0.10)
        assert any(r["check"] == bad for r in v["regressions"]), (bad, v)
    # an auditor that never ran fails the violations gate even at 0
    v = bc.compare_churn_net({}, _net_record(audits=0), 0.10)
    assert any(r["check"] == "netchaos.invariant_violations"
               for r in v["regressions"])
    # delta gates: p99 under faults must not erode past the threshold
    v = bc.compare_churn_net(_net_record(), _net_record(p99_s=0.5), 0.10)
    assert any(r["check"] == "netchaos.p99_s"
               for r in v["regressions"])
    # absence-tolerant: a record without the arm warns, never fails
    v = bc.compare_churn_net({}, {"arms": {}}, 0.10)
    assert v["regressions"] == [] and v["warnings"]


def test_net_chaos_modules_lint_clean():
    """graftlint pinned over the new modules: parse is covered by
    test_parse_all; here R2 (host sync), R3 (retrace), R7 (undeclared
    readback) must stay clean on the network-fault code — all host-side
    control plane, so any finding means device work leaked in."""
    import kubernetes_tpu.chaos as chaos_mod
    import kubernetes_tpu.faults as faults_mod
    import kubernetes_tpu.obs.audit as audit_mod
    from kubernetes_tpu.testing import lint_clean

    for mod in (audit_mod, faults_mod, chaos_mod):
        lint_clean(mod, rules=("R2", "R3", "R7"), jit_all=False)
