"""Incremental solve (PR 13 tentpole): steady-state cycles that cost
O(churn), not O(P x N).

What this suite pins:

- restricted cycles engage on clean/delta resident snapshots, place
  through the real admission tail, and stamp ``solve_scope`` /
  ``reuse_frac`` provenance on the CycleResult AND the flight record;
- warm-vs-cold parity fuzz (seeds >= 3): the restricted solve places
  exactly as many pods as the cold solve on identical seeded clusters,
  every placement lands on a genuinely feasible node, and the mean
  lean quality stays inside the documented ``quality_delta`` gate;
- EVERY invalidation edge drops the score cache and the warm
  potentials and falls back to the cold solve: pack-epoch growth
  (volume-state replacement), interner growth, dirty-frac blowout,
  takeover ``reconcile()``, device-loss recovery;
- zero post-warmup retraces across churn (the warmed restricted bucket
  shapes are reused), and the d2h readback stays answer-sized;
- Sinkhorn warm start (ops/sinkhorn.py): a warm start from a previous
  equilibrium early-exits under the tolerance loop and reproduces the
  cold plan;
- config plumbing: native decode, v1alpha1 round-trip, validate_config
  field gates, the --incremental flag;
- the bench_compare ``incremental`` gate family contract.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.config import IncrementalConfig, RecoveryConfig, WarmupConfig
from kubernetes_tpu.faults import FaultInjector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def build(n_nodes=96, candidate_bucket=32, clock=None, warm_buckets=(),
          hetero=False, **kw):
    """A scheduler with the incremental mode on over a cluster LARGER
    than the candidate bucket (bucket_size(96)=128 > C=32), so the
    restricted route is actually shrinking something."""
    inc = kw.pop("incremental", None) or IncrementalConfig(
        enabled=True, candidate_bucket=candidate_bucket)
    wu = (WarmupConfig(enabled=True, pod_buckets=tuple(warm_buckets))
          if warm_buckets else None)
    s = Scheduler(enable_preemption=False, incremental=inc,
                  clock=clock or FakeClock(),
                  **({"warmup": wu} if wu else {}), **kw)
    rng = random.Random(7)
    for i in range(n_nodes):
        cpu = rng.choice([16000, 32000, 64000]) if hetero else 64000
        mem = (rng.choice([64, 128, 256]) if hetero else 256) * 2**30
        s.on_node_add(make_node(f"n{i}", cpu_milli=cpu, memory=mem,
                                pods=500))
    if warm_buckets:
        s.warmup(sample_pods=[make_pod("warm-sample", cpu_milli=50,
                                       memory=128 * 2**20)])
    return s


def churn_pods(s, n, tag, cpu=50, mem=128 * 2**20):
    for i in range(n):
        s.on_pod_add(make_pod(f"{tag}-{i}", cpu_milli=cpu, memory=mem))


# ---------------------------------------------------------------------------
# the restricted route: engagement, provenance, placements
# ---------------------------------------------------------------------------


def test_restricted_cycle_engages_and_places():
    s = build()
    churn_pods(s, 4, "a")
    r1 = s.schedule_cycle()
    # the first snapshot is a full upload — warm state starts cold
    assert r1.snapshot_mode == "full"
    assert r1.solve_scope == "full"
    assert r1.scheduled == 4
    churn_pods(s, 6, "b")
    r2 = s.schedule_cycle()
    assert r2.snapshot_mode in ("clean", "delta")
    assert r2.solve_scope == "restricted"
    assert r2.scheduled == 6
    # the first restricted cycle lazily REBUILT the score plane —
    # honest reuse is zero; the next one reuses the patched plane
    assert r2.reuse_frac == 0.0
    churn_pods(s, 3, "c")
    r3 = s.schedule_cycle()
    assert r3.solve_scope == "restricted"
    assert 0.0 < r3.reuse_frac <= 1.0
    # every placement landed on a real, existing node
    for _key, node in r2.assignments.items():
        assert s.cache.node(node) is not None
    # provenance reaches the flight record and its dump
    rec = s.obs.recorder.records()[-1]
    assert rec.solve_scope == "restricted"
    assert "scope=restricted" in s.obs.recorder.dump()
    assert s.metrics.incremental_cycles.value(scope="restricted") == 2


def test_restricted_metrics_and_reuse_gauge():
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    churn_pods(s, 2, "b")
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    assert s.metrics.incremental_reuse_fraction.value() == pytest.approx(
        r.reuse_frac)
    assert s.metrics.incremental_cycles.value(scope="full") == 1
    assert s.metrics.incremental_cycles.value(scope="restricted") == 1


def test_under_placed_batch_falls_back_to_cold():
    """A pod nothing can host: the restricted attempt under-places and
    the SAME cycle re-solves cold (full failure analytics, standard
    error path) — the correctness fallback, not a silent drop."""
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    s.on_pod_add(make_pod("giant", cpu_milli=10_000_000))
    churn_pods(s, 2, "b")
    r = s.schedule_cycle()
    assert r.solve_scope == "full"  # fell back
    assert r.scheduled == 2
    assert r.unschedulable == 1
    assert "default/giant" in r.failure_reasons
    assert s.metrics.incremental_cycles.value(scope="under-placed") == 1


def test_ineligible_features_take_cold_solve():
    """Cross-node in-batch coupling (host ports here) keeps the cold
    path even in steady state."""
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    for i in range(2):
        s.on_pod_add(make_pod(f"hp{i}", cpu_milli=10,
                              host_ports=(("TCP", "", 8080 + i),)))
    r = s.schedule_cycle()
    assert r.solve_scope == "full"
    assert r.scheduled == 2


def test_gangs_ride_restricted():
    """Gangs are NO LONGER blanket-excluded: a complete gang whose
    members all fit rides the restricted path and binds atomically
    (the all-or-nothing re-check happens inside the tail)."""
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    for i in range(3):
        s.on_pod_add(make_pod(f"g{i}", cpu_milli=10, pod_group="gang",
                              pod_group_min_available=3))
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    assert r.scheduled == 3


def test_incomplete_gang_declines_restricted():
    """A gang whose minMember can't be met by the PRESENT batch
    declines the restricted attempt up front — the dense ladder owns
    the gang-rollback failure analytics."""
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    s.on_pod_add(make_pod("g0", cpu_milli=10, pod_group="gang",
                          pod_group_min_available=3))
    r = s.schedule_cycle()
    assert r.solve_scope == "full"
    assert s.metrics.incremental_cycles.value(scope="declined") >= 1


def test_small_cluster_never_restricts():
    """A cluster whose padded node bucket fits inside the candidate
    bucket gains nothing from restriction — always cold."""
    s = build(n_nodes=16, candidate_bucket=32)
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    churn_pods(s, 2, "b")
    r = s.schedule_cycle()
    assert r.solve_scope == "full"


# ---------------------------------------------------------------------------
# warm-vs-cold parity fuzz (the quality gate)
# ---------------------------------------------------------------------------


def _lean_quality(s, assignments):
    scores = []
    for _key, node_name in assignments.items():
        nd = s.cache.node(node_name)
        used_cpu = sum(p.effective_requests().cpu_milli
                       for p in s.cache.pods_on(node_name))
        used_mem = sum(p.effective_requests().memory
                       for p in s.cache.pods_on(node_name))
        cf = max(0.0, nd.allocatable.cpu_milli - used_cpu) \
            / max(nd.allocatable.cpu_milli, 1e-9)
        mf = max(0.0, nd.allocatable.memory - used_mem) \
            / max(nd.allocatable.memory, 1e-9)
        scores.append(0.5 * (cf + mf))
    return float(np.mean(scores)) if scores else 0.0


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_warm_vs_cold_parity_fuzz(seed):
    """Identical seeded clusters + pod batches through an incremental
    and a cold scheduler: placed counts MUST match (under-placement
    falls back to cold by construction, so the restricted path can
    never bind fewer), every restricted placement is feasible (the
    admission tail + fused validator both passed), and the mean lean
    quality stays inside the documented quality_delta gate."""
    rng = random.Random(seed)
    preload = [(rng.randrange(96), rng.choice([500, 2000, 8000]),
                rng.choice([1, 4, 16]) * 2**30) for _ in range(40)]
    batches = [[(rng.choice([100, 250, 500]),
                 rng.choice([128, 256, 512]) * 2**20)
                for _ in range(rng.randrange(4, 14))]
               for _ in range(3)]
    results = {}
    for mode in ("warm", "cold"):
        s = build(hetero=True, incremental=IncrementalConfig(
            enabled=(mode == "warm"), candidate_bucket=32))
        for i, (n, cpu, mem) in enumerate(preload):
            s.cache.add_pod(make_pod(f"pre-{i}", node_name=f"n{n}",
                                     cpu_milli=cpu, memory=mem))
        assigns = {}
        placed = 0
        scopes = []
        for bi, batch in enumerate(batches):
            for pi, (cpu, mem) in enumerate(batch):
                s.on_pod_add(make_pod(f"p{bi}-{pi}", cpu_milli=cpu,
                                      memory=mem))
            r = s.schedule_cycle()
            placed += r.scheduled
            scopes.append(r.solve_scope)
            assigns.update(r.assignments)
        results[mode] = (placed, scopes, _lean_quality(s, assigns), s)
    warm_placed, warm_scopes, warm_q, warm_s = results["warm"]
    cold_placed, _cold_scopes, cold_q, _ = results["cold"]
    assert warm_placed == cold_placed
    # the steady-state cycles actually ran restricted under the warm arm
    assert "restricted" in warm_scopes[1:]
    delta = (cold_q - warm_q) / max(cold_q, 1e-9)
    assert delta <= warm_s.incremental.quality_delta
    # feasibility: every warm placement's node exists and ended within
    # allocatable (the cache tracks the post-bind usage)
    for node in {n for _k, n in results["warm"][3].cache._pod_node.items()}:
        nd = warm_s.cache.node(node)
        if nd is None:
            continue
        used = sum(p.effective_requests().cpu_milli
                   for p in warm_s.cache.pods_on(node))
        assert used <= nd.allocatable.cpu_milli + 1e-6


# ---------------------------------------------------------------------------
# invalidation edges: drop the cache + potentials, solve cold
# ---------------------------------------------------------------------------


def _steady(s):
    """Drive to a steady restricted state; returns the last result."""
    churn_pods(s, 2, "warmin-a")
    s.schedule_cycle()
    churn_pods(s, 2, "warmin-b")
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    return r


def test_invalidation_pack_epoch_growth():
    """Volume-state replacement bumps the pack epoch and invalidates
    the snapshot — the next cycle MUST rebuild full and solve cold,
    and the score-cache generation must move."""
    s = build()
    _steady(s)
    gen0 = s.cache.summary_generation
    s.set_volume_state(pvcs=[], pvs=[], classes=[])
    churn_pods(s, 2, "after")
    r = s.schedule_cycle()
    assert r.snapshot_mode == "full"
    assert r.solve_scope == "full"
    assert s.cache.summary_generation > gen0
    assert s.metrics.incremental_invalidations.value(
        reason="full-snapshot") >= 1
    # and the NEXT steady cycle is restricted again (cache rebuilt)
    churn_pods(s, 2, "resume")
    assert s.schedule_cycle().solve_scope == "restricted"


def test_invalidation_interner_growth():
    """A pod interning a brand-new selector key grows the universe —
    clean rows' packed content changes, the snapshot rebuilds full,
    the cycle solves cold."""
    s = build()
    _steady(s)
    gen0 = s.cache.summary_generation
    s.on_pod_add(make_pod("sel", cpu_milli=10,
                          node_selector={"brand-new-key": "v"}))
    r = s.schedule_cycle()
    assert r.snapshot_mode == "full"
    assert r.solve_scope == "full"
    assert s.cache.summary_generation > gen0


def test_invalidation_dirty_frac_blowout():
    """More dirty columns than incremental.maxDirtyFrac allows: the
    score cache drops (generation bump) and the cycle solves cold even
    though the snapshot itself still patched as a delta."""
    s = build(incremental=IncrementalConfig(
        enabled=True, candidate_bucket=32, max_dirty_frac=0.05))
    s.cache.max_dirty_frac = 0.5  # snapshot layer stays on the delta path
    _steady(s)
    gen0 = s.cache.summary_generation
    for i in range(10):  # ~10% of 96 nodes dirty > the 5% threshold
        s.on_node_update(make_node(f"n{i}", cpu_milli=64000,
                                   memory=256 * 2**30, pods=499))
    churn_pods(s, 2, "after")
    r = s.schedule_cycle()
    assert r.snapshot_mode == "delta"
    assert r.solve_scope == "full"
    assert s.cache.summary_generation > gen0
    assert s.metrics.incremental_invalidations.value(
        reason="dirty-frac") == 1


def test_invalidation_takeover_reconcile():
    """reconcile() (takeover / cold start) drops the resident snapshot,
    the score cache, AND the warm potentials; the next cycle rebuilds
    full and solves cold."""
    s = build()
    _steady(s)
    s._sk_warm_pot = ("sentinel", None)
    gen0 = s.cache.summary_generation
    s.reconcile([])
    assert s._sk_warm_pot is None
    assert s.cache.summary_generation > gen0
    assert s.metrics.incremental_invalidations.value(
        reason="takeover") == 1
    churn_pods(s, 2, "after")
    r = s.schedule_cycle()
    assert r.snapshot_mode == "full"
    assert r.solve_scope == "full"


def test_invalidation_device_loss_heal():
    """Device loss at the snapshot seam: host-mode cycles solve cold
    (no resident table, no score cache), the potentials drop, and the
    heal (full re-place) re-enters restricted service afterwards."""
    fi = FaultInjector(seed=0)
    clk = FakeClock()
    s = build(clock=clk, fault_injector=fi,
              recovery=RecoveryConfig(device_reset_limit=1,
                                      device_cooloff_s=5.0))
    _steady(s)
    s._sk_warm_pot = ("sentinel", None)
    # NOW lose the device (arming earlier would burn the shots during
    # the warm-in cycles)
    fi.arm("snapshot:device", "device_lost", count=4)
    churn_pods(s, 2, "loss")
    r = s.schedule_cycle()  # exhausts the rebuild budget -> host mode
    assert r.snapshot_mode == "host"
    assert r.solve_scope == "full"
    assert s._sk_warm_pot is None
    assert s.metrics.incremental_invalidations.value(
        reason="device-loss") >= 1
    clk.advance(6)  # cooloff passes; injector still has shots
    churn_pods(s, 2, "probe")
    r2 = s.schedule_cycle()
    assert r2.snapshot_mode == "host"
    clk.advance(6)  # injector exhausted: the device heals
    churn_pods(s, 2, "heal")
    r3 = s.schedule_cycle()
    assert r3.snapshot_mode == "full"  # re-placed resident
    assert r3.solve_scope == "full"
    churn_pods(s, 2, "steady")
    r4 = s.schedule_cycle()
    assert r4.solve_scope == "restricted"  # back in incremental service


# ---------------------------------------------------------------------------
# zero retraces + readback budget
# ---------------------------------------------------------------------------


def test_zero_retraces_across_churn():
    """Warmup pre-compiles the restricted signatures; steady churn
    across pod buckets then causes ZERO retraces at the solve site."""
    s = build(warm_buckets=(4, 8, 16))
    for n, tag in ((3, "a"), (7, "b"), (12, "c"), (2, "d")):
        churn_pods(s, n, tag)
        s.schedule_cycle()
    assert s.obs.jax.retrace_total() == 0


def test_restricted_readback_answer_sized():
    """The candidate index list never crosses the boundary: a
    restricted cycle's d2h is the padded assignment vector + verdict
    scalars, nothing (P, N)- or (C,)-shaped extra."""
    s = build()
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    churn_pods(s, 6, "b")
    before = s.obs.jax.d2h_bytes_total()
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    delta = s.obs.jax.d2h_bytes_total() - before
    # padded assignment (8 * 4B) + rounds + code/valid scalars
    assert delta <= 8 * 4 + 64


def test_restricted_on_mesh():
    """The sharded backend composes: a mesh-backed incremental
    scheduler's steady-state cycles run restricted against the SHARDED
    resident table (the candidate gather is answer-sized, so the
    transfer contract holds) and every placement lands on a real
    node."""
    import jax

    from kubernetes_tpu.config import ParallelConfig

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (XLA_FLAGS host platform count)")
    s = build(parallel=ParallelConfig(mesh=2))
    churn_pods(s, 4, "a")
    assert s.schedule_cycle().solve_scope == "full"
    churn_pods(s, 6, "b")
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    assert r.scheduled == 6
    for _k, node in r.assignments.items():
        assert s.cache.node(node) is not None


# ---------------------------------------------------------------------------
# Sinkhorn warm start (ops/sinkhorn.py)
# ---------------------------------------------------------------------------


def test_sinkhorn_warm_start_early_exit_and_parity():
    import jax.numpy as jnp

    from kubernetes_tpu.ops.sinkhorn import sinkhorn_plan

    rng = np.random.RandomState(0)
    score = jnp.asarray(rng.rand(12, 20).astype(np.float32))
    mask = jnp.asarray(rng.rand(12, 20) > 0.2)
    cap = jnp.asarray(np.full((20,), 2.0, np.float32))
    cold_plan, cold_stats, cold_pot = sinkhorn_plan(
        score, mask, cap, iters=60, with_stats=True, tol=1e-6,
        return_potentials=True)
    # warm restart from the converged equilibrium: the tolerance loop
    # exits after ONE verification iteration and reproduces the plan
    warm_plan, warm_stats, _ = sinkhorn_plan(
        score, mask, cap, iters=60, with_stats=True, tol=1e-6,
        init=cold_pot, return_potentials=True)
    assert float(warm_stats[0]) <= 2.0
    assert float(warm_stats[0]) < float(cold_stats[0])
    np.testing.assert_allclose(np.asarray(warm_plan),
                               np.asarray(cold_plan), atol=1e-4)


def test_sinkhorn_warm_start_sanitizes_nonfinite_init():
    import jax.numpy as jnp

    from kubernetes_tpu.ops.sinkhorn import sinkhorn_plan

    score = jnp.zeros((4, 6))
    mask = jnp.ones((4, 6), bool)
    cap = jnp.full((6,), 2.0)
    bad = (jnp.full((4,), -np.inf), jnp.full((6,), np.nan))
    plan = sinkhorn_plan(score, mask, cap, iters=30, init=bad, tol=1e-6)
    assert bool(np.isfinite(np.asarray(plan)).all())
    assert float(np.asarray(plan).sum()) > 0


def test_batch_assign_potentials_roundtrip():
    """potentials_out / sk_init thread through the solver: the carried
    pair has the solver shapes and re-feeding it changes nothing about
    the placements (scaling converges to the same fixpoint)."""
    import jax.numpy as jnp

    from kubernetes_tpu.ops.arrays import (
        nodes_to_device,
        pods_to_device,
        selectors_to_device,
    )
    from kubernetes_tpu.ops.assign import batch_assign
    from kubernetes_tpu.snapshot import SnapshotPacker

    pk = SnapshotPacker()
    pods = [make_pod(f"p{i}", cpu_milli=100, memory=2**20)
            for i in range(6)]
    nodes = [make_node(f"n{i}", cpu_milli=4000, memory=2**30)
             for i in range(8)]
    for p in pods:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, []))
    dp = pods_to_device(pk.pack_pods(pods))
    ds = selectors_to_device(pk.pack_selector_tables())
    a1, _u1, _r1, pot = batch_assign(
        dp, dn, ds, use_sinkhorn=True, sk_tol=1e-4, potentials_out=True)
    assert pot[0].shape[0] == dp.valid.shape[0]
    assert pot[1].shape[0] == dn.valid.shape[0]
    a2, _u2, _r2, _pot2 = batch_assign(
        dp, dn, ds, use_sinkhorn=True, sk_init=pot, sk_tol=1e-4,
        potentials_out=True)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


def test_scheduler_carries_sinkhorn_potentials():
    """A sinkhorn-solver incremental scheduler stores the potential
    carry after a restricted cycle and reuses it while the key (pod
    bucket, candidate bucket, cache generation) matches."""
    s = build(solver="sinkhorn", warm_buckets=(4,))
    churn_pods(s, 2, "a")
    s.schedule_cycle()
    churn_pods(s, 2, "b")
    r = s.schedule_cycle()
    assert r.solve_scope == "restricted"
    assert s._sk_warm_pot is not None
    key0 = s._sk_warm_pot[0]
    churn_pods(s, 2, "c")
    r2 = s.schedule_cycle()
    assert r2.solve_scope == "restricted"
    assert s._sk_warm_pot[0] == key0  # same bucket family, carried
    s.reconcile([])
    assert s._sk_warm_pot is None  # takeover kills the carry


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_native_config_decode_and_validation():
    from kubernetes_tpu.cli import ConfigError, decode_config, validate_config

    cfg = decode_config({"incremental": {
        "enabled": True, "candidate_bucket": 128, "max_dirty_frac": 0.1,
    }})
    assert cfg.incremental.enabled
    assert cfg.incremental.candidate_bucket == 128
    assert cfg.incremental.max_dirty_frac == 0.1
    assert validate_config(cfg) == []
    with pytest.raises(ConfigError):
        decode_config({"incremental": {"bogus": 1}})
    bad = decode_config({"incremental": {
        "candidate_bucket": 0, "max_batch_frac": 0.0, "warm_tol": 0.0,
        "quality_delta": -1.0}})
    errs = "\n".join(validate_config(bad))
    for field in ("candidateBucket", "maxBatchFrac", "warmTol",
                  "qualityDelta"):
        assert field in errs


def test_v1alpha1_round_trip():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode
    from kubernetes_tpu.config import KubeSchedulerConfiguration

    cfg = KubeSchedulerConfiguration(
        incremental=IncrementalConfig(
            enabled=True, candidate_bucket=512, max_batch_frac=0.25,
            max_dirty_frac=0.1, warm_potentials=False, warm_tol=1e-4,
            quality_delta=0.05))
    doc = encode(cfg)
    inc = doc["incremental"]
    assert inc["enabled"] is True
    assert inc["candidateBucket"] == 512
    assert inc["warmPotentials"] is False
    back = decode(doc)
    assert back.incremental == cfg.incremental
    # wire defaulting: an empty versioned doc lands the internal defaults
    empty = decode({"apiVersion": doc["apiVersion"], "kind": doc["kind"]})
    assert empty.incremental == IncrementalConfig()


def test_incremental_cli_flag():
    from kubernetes_tpu.cli import build_parser, resolve_config

    args = build_parser().parse_args(["--incremental", "true"])
    cfg = resolve_config(args)
    assert cfg.incremental.enabled
    args = build_parser().parse_args(["--incremental", "false"])
    assert not resolve_config(args).incremental.enabled


# ---------------------------------------------------------------------------
# kernel lint + bench_compare gate contract
# ---------------------------------------------------------------------------


def test_incremental_kernels_lint_clean():
    """The new score-cache kernels keep the kernel discipline (R2/R3/
    R5 via lint_clean's default set; R7/R8 are enforced module-wide by
    the tier-1 graftlint gate in test_static_analysis)."""
    import kubernetes_tpu.ops.fused_score as fs
    from kubernetes_tpu.testing import lint_clean

    lint_clean(fs)


def _incr_record(warm_growth=1.05, cold_growth=2.0, retraces=0,
                 bpp=5.0, restricted=1.0, qdelta=0.001,
                 placed_equal=True):
    return {
        "name": "churn_incr",
        "sizes": [1024, 4096],
        "quality_bound": 0.02,
        "flatness": {"warm_growth": warm_growth,
                     "cold_growth": cold_growth},
        "cells": {
            "warm_1024": {"jax": {"retraces": retraces},
                          "readback_bytes_per_pod": bpp,
                          "restricted_frac": restricted,
                          "steady_mean_solve_s": 0.002},
            "cold_1024": {"jax": {"retraces": 0},
                          "readback_bytes_per_pod": 4.0,
                          "steady_mean_solve_s": 0.002},
        },
        "quality": {"placed_equal": placed_equal,
                    "restricted_engaged": True,
                    "score_delta_frac_max": qdelta},
    }


def test_bench_compare_incremental_gates():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)

    ok = bc.compare_churn_incr({}, _incr_record(), 0.10)
    assert not ok["regressions"]
    # flatness blown
    bad = bc.compare_churn_incr({}, _incr_record(warm_growth=1.6), 0.10)
    assert any(r["check"] == "incremental.flatness.warm_growth"
               for r in bad["regressions"])
    # cold arm no longer grows past the warm arm
    bad = bc.compare_churn_incr({}, _incr_record(cold_growth=1.0), 0.10)
    assert any(r["check"] == "incremental.flatness.cold_grows"
               for r in bad["regressions"])
    # quality delta over the documented bound
    bad = bc.compare_churn_incr({}, _incr_record(qdelta=0.5), 0.10)
    assert any(r["check"] == "incremental.quality.score_delta"
               for r in bad["regressions"])
    # a retrace or a readback blowout is absolute
    bad = bc.compare_churn_incr({}, _incr_record(retraces=2), 0.10)
    assert any("retraces" in r["check"] for r in bad["regressions"])
    bad = bc.compare_churn_incr({}, _incr_record(bpp=99.0), 0.10)
    assert any("readback_budget" in r["check"]
               for r in bad["regressions"])
    # restricted engagement collapsed
    bad = bc.compare_churn_incr({}, _incr_record(restricted=0.1), 0.10)
    assert any("restricted_frac" in r["check"]
               for r in bad["regressions"])
    # delta gate: warm cycle cost regressed vs the previous record
    prev = _incr_record()
    cur = _incr_record()
    cur["cells"]["warm_1024"]["steady_mean_solve_s"] = 0.02
    v = bc.compare_churn_incr(prev, cur, 0.10)
    assert any(r["check"] == "incremental.warm_1024.steady_mean_solve_s"
               for r in v["regressions"])
    # the gate family is registered
    assert any(n == "incremental" for n, _g, _e in bc.GATE_FAMILIES)


def test_list_gates_includes_incremental(capsys):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare2", os.path.join(os.path.dirname(__file__), "..",
                                       "scripts", "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    assert bc.main(["--list-gates"]) == 0
    out = capsys.readouterr().out
    assert "incremental" in out and "churn_incr_r*.json" in out
