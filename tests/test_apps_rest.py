"""apps/v1 write path + Scale subresource + PATCH verb (VERDICT r4
item 4): a rollout driven END-TO-END through REST — create a Deployment
over the wire, scale it through /scale (the HPA's contract,
pkg/registry/apps/deployment/storage/storage.go:230 ScaleREST), roll it
out by merge-patching the template (patch.go:59 PatchResource), and read
completion through `ktpu rollout status`."""

import http.client
import json

import pytest

from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.sim import HollowCluster
from kubernetes_tpu.testing import make_node

from tests.test_restapi import req, start


def patch_req(port, path, body, ctype="application/merge-patch+json"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("PATCH", path, json.dumps(body),
                 {"Content-Type": ctype})
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, json.loads(data) if data else None


def cluster():
    hub = HollowCluster(seed=21, scheduler_kw={"enable_preemption": False})
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000, pods=60))
    srv, port = start(hub)
    return hub, srv, port


def settle(hub, ticks=30):
    for _ in range(ticks):
        hub.step()


DEPLOY = {
    "apiVersion": "apps/v1", "kind": "Deployment",
    "metadata": {"name": "web"},
    "spec": {"replicas": 3, "template": {"cpuMilli": 200}},
}


def test_rollout_end_to_end_through_rest(capsys):
    from kubernetes_tpu.kubectl import main as ktpu

    hub, srv, port = cluster()
    try:
        code, doc = req(port, "POST",
                        "/apis/apps/v1/namespaces/default/deployments",
                        DEPLOY)
        assert code == 201 and doc["spec"]["replicas"] == 3
        code, doc = req(port, "POST",
                        "/apis/apps/v1/namespaces/default/deployments",
                        DEPLOY)
        assert code == 409
        settle(hub)
        code, doc = req(port, "GET",
                        "/apis/apps/v1/namespaces/default/deployments/web")
        assert doc["status"]["readyReplicas"] == 3

        # scale UP through ktpu (PUT /scale under the hood)
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "scale",
                   "deployment/web", "--replicas", "5"])
        assert rc == 0
        settle(hub)
        code, doc = req(port, "GET",
                        "/apis/apps/v1/namespaces/default/deployments/"
                        "web/scale")
        assert code == 200 and doc["kind"] == "Scale"
        assert doc["spec"]["replicas"] == 5 and doc["status"]["replicas"] == 5

        # roll out by patching the template (the image-patch analog):
        # revision must bump and the rollout must complete
        code, doc = patch_req(
            port, "/apis/apps/v1/namespaces/default/deployments/web",
            {"spec": {"template": {"cpuMilli": 300}}})
        assert code == 200, doc
        assert doc["status"]["observedRevision"] == 1
        settle(hub, 60)
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "rollout",
                   "status", "deployment/web"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "successfully rolled out" in out
        # every live pod runs the new template
        pods = [p for p in hub.truth_pods.values()
                if p.name.startswith("web-")]
        assert len(pods) == 5
        assert all(p.requests.cpu_milli == 300 for p in pods)

        # DELETE cascades through the ownerRef GC
        code, doc = req(port, "DELETE",
                        "/apis/apps/v1/namespaces/default/deployments/web")
        assert code == 200
        settle(hub)
        assert not any(p.name.startswith("web-")
                       for p in hub.truth_pods.values())
    finally:
        srv.close()


def test_scale_subresource_validation_and_put_spec():
    hub, srv, port = cluster()
    try:
        req(port, "POST", "/apis/apps/v1/namespaces/default/deployments",
            DEPLOY)
        code, doc = req(port, "PUT",
                        "/apis/apps/v1/namespaces/default/deployments/"
                        "web/scale",
                        {"spec": {"replicas": -1}})
        assert code == 422
        code, doc = req(port, "PUT",
                        "/apis/apps/v1/namespaces/default/deployments/"
                        "web/scale",
                        {"spec": {"replicas": 7}})
        assert code == 200 and doc["spec"]["replicas"] == 7

        # PUT the full spec: invalid budgets are 422 Invalid
        code, doc = req(port, "PUT",
                        "/apis/apps/v1/namespaces/default/deployments/web",
                        {"spec": {"replicas": 2, "maxSurge": 0,
                                  "maxUnavailable": 0}})
        assert code == 422 and "cannot both" in doc["message"]
        code, doc = req(port, "PUT",
                        "/apis/apps/v1/namespaces/default/deployments/web",
                        {"spec": {"replicas": 2}})
        assert code == 200 and doc["spec"]["replicas"] == 2

        # unknown deployment
        code, _ = req(port, "PUT",
                      "/apis/apps/v1/namespaces/default/deployments/"
                      "ghost/scale", {"spec": {"replicas": 1}})
        assert code == 404
        # bad name on create
        code, doc = req(port, "POST",
                        "/apis/apps/v1/namespaces/default/deployments",
                        {"metadata": {"name": "Bad/Name"}, "spec": {}})
        assert code == 422
    finally:
        srv.close()


def test_patch_pods_and_nodes_merge_semantics():
    from tests.test_restapi import NODE, make_pod_doc

    hub, srv, port = cluster()
    try:
        pod = make_pod_doc("p0")
        pod["metadata"]["labels"] = {"app": "web", "tier": "fe"}
        req(port, "POST", "/api/v1/namespaces/default/pods", pod)

        # merge: add one label, delete another via null (RFC 7386)
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"metadata": {"labels": {"version": "v2", "tier": None}}})
        assert code == 200
        assert doc["metadata"]["labels"] == {"app": "web", "version": "v2"}
        assert hub.truth_pods["default/p0"].labels == {
            "app": "web", "version": "v2"}

        # the patched label is immediately visible to server-side selectors
        code, doc = req(port, "GET",
                        "/api/v1/pods?labelSelector=version%3Dv2")
        assert [p["metadata"]["name"] for p in doc["items"]] == ["p0"]

        # placement is immutable through PATCH (Binding owns nodeName)
        code, doc = patch_req(port, "/api/v1/namespaces/default/pods/p0",
                              {"spec": {"nodeName": "n1"}})
        assert code == 422 and "Binding" in doc["message"]

        # stale rv precondition -> 409
        cur_rv = doc and hub.resource_version["pods/default/p0"]
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"metadata": {"resourceVersion": "1",
                          "labels": {"x": "y"}}})
        assert code == 409

        # nodes: patch a label through merge semantics
        req(port, "PATCH", "/api/v1/nodes/n0", None)  # no body -> 415 path
        code, doc = patch_req(port, "/api/v1/nodes/n0",
                              {"metadata": {"labels": {"disk": "ssd"}}})
        assert code == 200
        assert hub.truth_nodes["n0"].labels.get("disk") == "ssd"

        # only merge-patch+json is served
        code, doc = patch_req(port, "/api/v1/nodes/n0",
                              {"metadata": {}},
                              ctype="application/json-patch+json")
        assert code == 415
        code, _ = patch_req(port, "/api/v1/namespaces/default/pods/ghost",
                            {"metadata": {}})
        assert code == 404
    finally:
        srv.close()


def test_write_path_validation_rejects_crash_vectors():
    """Review findings r5: values that would crash hub.step()'s rolling
    reconcile LATER must be rejected at the write (422), negative
    replicas are invalid on every write path (not just /scale), a
    type-invalid merge patch is 422 not a dropped connection, and a
    deployment patch carrying an rv precondition is an explicit 400
    (controller objects are not individually versioned)."""
    hub, srv, port = cluster()
    try:
        req(port, "POST", "/apis/apps/v1/namespaces/default/deployments",
            DEPLOY)

        code, doc = patch_req(
            port, "/apis/apps/v1/namespaces/default/deployments/web",
            {"spec": {"maxSurge": "abc"}})
        assert code == 422 and "maxSurge" in doc["message"]
        code, doc = patch_req(
            port, "/apis/apps/v1/namespaces/default/deployments/web",
            {"spec": {"maxUnavailable": [1]}})
        assert code == 422
        code, doc = req(port, "POST",
                        "/apis/apps/v1/namespaces/default/deployments",
                        {"metadata": {"name": "neg"},
                         "spec": {"replicas": -3}})
        assert code == 422 and "non-negative" in doc["message"]
        code, doc = patch_req(
            port, "/apis/apps/v1/namespaces/default/deployments/web",
            {"spec": {"replicas": -1}})
        assert code == 422
        # the cluster still steps (no poisoned deployment landed)
        settle(hub, 3)

        code, doc = patch_req(
            port, "/apis/apps/v1/namespaces/default/deployments/web",
            {"metadata": {"resourceVersion": "5"},
             "spec": {"replicas": 2}})
        assert code == 400 and "not individually versioned" in doc["message"]

        from tests.test_restapi import make_pod_doc

        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("p0"))
        code, doc = patch_req(port, "/api/v1/namespaces/default/pods/p0",
                              {"spec": {"priority": "high"}})
        assert code == 422 and doc["reason"] == "Invalid"
        code, doc = patch_req(port, "/api/v1/nodes/n0",
                              {"metadata": {"labels": "notadict"}})
        assert code == 422
    finally:
        srv.close()


def test_pod_patch_preserves_non_wire_fields_and_scopes_to_metadata():
    """Review findings r5 (pod PATCH): a pure label patch must not
    disturb fields the wire doc doesn't carry (tolerations, queue
    position, ...), spec/status mutations are 422 (quota admission
    would be bypassed), metadata.namespace is immutable like name, and
    the cluster-scoped apps write spelling 404s."""
    from kubernetes_tpu.api.types import Toleration
    from kubernetes_tpu.testing import make_pod

    hub, srv, port = cluster()
    try:
        p = make_pod("tolerant", cpu_milli=100)
        import dataclasses

        p = dataclasses.replace(
            p, tolerations=(Toleration(key="k", operator="Exists",
                                       effect="NoExecute",
                                       toleration_seconds=300),),
            queued_at=42.0)
        hub.create_pod(p)
        before = hub.truth_pods["default/tolerant"]

        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/tolerant",
            {"metadata": {"labels": {"patched": "yes"}}})
        assert code == 200
        after = hub.truth_pods["default/tolerant"]
        assert after.labels == {"patched": "yes"}
        assert after.tolerations == before.tolerations  # NOT zeroed
        assert after.queued_at == 42.0
        assert after.uid == before.uid

        # spec mutation through PATCH is rejected (not silently applied
        # sans admission)
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/tolerant",
            {"spec": {"containers": [{"name": "main", "resources":
                                      {"requests": {"cpu": "64000m"}}}]}})
        assert code == 422 and "admission" in doc["message"]
        assert hub.truth_pods["default/tolerant"].requests.cpu_milli == 100

        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/tolerant",
            {"metadata": {"namespace": "other"}})
        assert code == 422 and "namespace" in doc["message"]
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/tolerant",
            {"metadata": {"uid": "forged"}})
        assert code == 422 and "uid" in doc["message"]

        # cluster-scoped write spellings are unpublished -> 404
        req(port, "POST", "/apis/apps/v1/namespaces/default/deployments",
            DEPLOY)
        code, _ = req(port, "DELETE", "/apis/apps/v1/deployments/web")
        assert code == 404
        assert "web" in hub.deployments  # untouched
        code, _ = req(port, "PUT", "/apis/apps/v1/deployments/web/scale",
                      {"spec": {"replicas": 1}})
        assert code == 404
    finally:
        srv.close()


def test_pod_patch_fk_guard_matches_exact_path_segments():
    """ADVICE r5 (restapi.py:1902, verified already fixed — this pins
    it): the PATCH foreign-key guard compares GUARDED names against
    exact dotted-path segments. An unmodeled field whose name merely
    CONTAINS a guarded token ('volumesAttached' ⊃ 'volumes',
    'hostPorts' ⊃ 'Ports') keeps the documented lenient
    drop-as-POST-dropped behavior (200); a genuinely guarded path
    ('spec.tolerations') still 422s."""
    from kubernetes_tpu.testing import make_pod

    hub, srv, port = cluster()
    try:
        hub.create_pod(make_pod("web", cpu_milli=100))
        code, _ = patch_req(
            port, "/api/v1/namespaces/default/pods/web",
            {"status": {"volumesAttached": [{"name": "pv1"}]}})
        assert code == 200  # substring of 'volumes' — NOT guarded
        code, _ = patch_req(
            port, "/api/v1/namespaces/default/pods/web",
            {"spec": {"hostPorts": [8080]}})
        assert code == 200  # substring of 'ports' — NOT guarded
        assert hub.truth_pods["default/web"].requests.cpu_milli == 100
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/web",
            {"spec": {"tolerations": [{"key": "k", "operator": "Exists"}]}})
        assert code == 422  # exact guarded segment
    finally:
        srv.close()


def test_ktpu_apply_create_then_configure(tmp_path, capsys):
    """kubectl apply analog: absent -> created; present -> merge-patched
    ('configured'); a deployment apply drives a real scale + rollout."""
    import json as _json

    from kubernetes_tpu.kubectl import main as ktpu

    hub, srv, port = cluster()
    try:
        mf = tmp_path / "web.json"
        doc = {"apiVersion": "apps/v1", "kind": "Deployment",
               "metadata": {"name": "web"},
               "spec": {"replicas": 2, "template": {"cpuMilli": 150}}}
        mf.write_text(_json.dumps(doc))
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "apply",
                   "-f", str(mf)])
        assert rc == 0
        assert "created" in capsys.readouterr().out
        settle(hub)
        assert hub.deployments["web"].replicas == 2

        doc["spec"] = {"replicas": 4, "template": {"cpuMilli": 250}}
        mf.write_text(_json.dumps(doc))
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "apply",
                   "-f", str(mf)])
        assert rc == 0
        assert "configured" in capsys.readouterr().out
        settle(hub, 60)
        d = hub.deployments["web"]
        assert d.replicas == 4 and d.template_rev == 1  # rollout happened
        pods = [p for p in hub.truth_pods.values()
                if p.name.startswith("web-")]
        assert len(pods) == 4
        assert all(p.requests.cpu_milli == 250 for p in pods)

        # pod metadata apply: created then label-patched
        pf = tmp_path / "p.json"
        pdoc = {"kind": "Pod", "metadata": {"name": "solo",
                                            "labels": {"v": "1"}},
                "spec": {"containers": [{"name": "m", "resources":
                                         {"requests": {"cpu": "50m"}}}]}}
        pf.write_text(_json.dumps(pdoc))
        assert ktpu(["--api-server", f"127.0.0.1:{port}", "apply",
                     "-f", str(pf)]) == 0
        pdoc["metadata"]["labels"] = {"v": "2"}
        pf.write_text(_json.dumps(pdoc))
        assert ktpu(["--api-server", f"127.0.0.1:{port}", "apply",
                     "-f", str(pf)]) == 0
        assert hub.truth_pods["default/solo"].labels == {"v": "2"}

        # a genuine pod SPEC change must FAIL loudly (rc 1), never a
        # silent 'configured' that dropped the change (review finding)
        pdoc["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "75m"
        pf.write_text(_json.dumps(pdoc))
        capsys.readouterr()
        assert ktpu(["--api-server", f"127.0.0.1:{port}", "apply",
                     "-f", str(pf)]) != 0
        assert hub.truth_pods["default/solo"].requests.cpu_milli == 50

        # nodeipam/TTL round-trip: a cordon (GET->PUT) must not wipe
        # the controller-stamped podCIDR/annotations (review finding)
        hub.step()
        cidr = hub.truth_nodes["n0"].pod_cidr
        assert cidr
        assert ktpu(["--api-server", f"127.0.0.1:{port}",
                     "cordon", "n0"]) == 0
        assert hub.truth_nodes["n0"].pod_cidr == cidr
        assert hub.truth_nodes["n0"].annotations.get(
            "node.alpha.kubernetes.io/ttl") == "0"
    finally:
        srv.close()


def test_pod_patch_rejects_modeled_fields_outside_the_wire_projection():
    """A patch touching a spec field the TRUTH MODEL carries but the
    wire projection doesn't (tolerations, affinity, volumes, limits,
    ports) must 422 — applying it is impossible and waving it through
    would silently drop a real semantic change."""
    from tests.test_restapi import make_pod_doc

    hub, srv, port = cluster()
    try:
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("p0"))
        for patch in (
            {"spec": {"tolerations": [{"key": "k", "operator": "Exists"}]}},
            {"spec": {"affinity": {"nodeAffinity": {}}}},
            {"spec": {"containers": [{"name": "main", "resources": {
                "requests": {"cpu": "100m"},
                "limits": {"cpu": "200m"}}}]}},
        ):
            code, doc = patch_req(
                port, "/api/v1/namespaces/default/pods/p0", patch)
            assert code == 422, (patch, code, doc)
    finally:
        srv.close()


def test_pod_patch_apply_is_idempotent_on_unmodeled_fields():
    """kubectl-apply idempotency (review finding r5 round 5): re-sending
    the exact manifest that CREATED the pod must 200 as an unchanged
    no-op even when it carries fields modeled NOWHERE (containers[0]
    .image, env) — POST dropped them leniently, so the PATCH comparison
    must drop them the same way, not 422."""
    from tests.test_restapi import make_pod_doc

    hub, srv, port = cluster()
    try:
        doc = make_pod_doc("p0")
        doc["spec"]["containers"][0]["image"] = "nginx:1.25"
        req(port, "POST", "/api/v1/namespaces/default/pods", doc)
        code, out = patch_req(
            port, "/api/v1/namespaces/default/pods/p0", doc)
        assert code == 200, (code, out)
        # and the stored pod is unchanged
        assert hub.truth_pods["default/p0"].labels == (
            doc["metadata"].get("labels") or {})
    finally:
        srv.close()


def test_pod_patch_metadata_split_semantics():
    """Metadata follows the same split as spec (review r5 round 5):
    modeled-nowhere keys (annotations — real kubectl apply always
    writes last-applied-configuration — finalizers) drop as leniently
    as POST dropped them, keeping apply's 'unchanged' path working;
    projection-carried server-owned keys (ownerReferences,
    deletionTimestamp) may only be echoed unchanged — an edit 422s."""
    from tests.test_restapi import make_pod_doc

    hub, srv, port = cluster()
    try:
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("p0"))
        # lenient: annotations/finalizers are modeled nowhere
        for patch in (
            {"metadata": {"annotations": {
                "kubectl.kubernetes.io/last-applied-configuration": "{}"}}},
            {"metadata": {"finalizers": ["x"]}},
        ):
            code, doc = patch_req(
                port, "/api/v1/namespaces/default/pods/p0", patch)
            assert code == 200, (patch, code, doc)
        # server-owned: an ownerReferences edit is rejected
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"metadata": {"ownerReferences": [
                {"kind": "ReplicaSet", "name": "rs-x"}]}})
        assert code == 422, (code, doc)
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"metadata": {"deletionTimestamp": "2026-01-01T00:00:00Z"}})
        assert code == 422, (code, doc)
        # labels still patch fine
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"metadata": {"labels": {"app": "web"}}})
        assert code == 200, (code, doc)
        assert hub.truth_pods["default/p0"].labels == {"app": "web"}
    finally:
        srv.close()


def test_pod_patch_guard_matches_path_segments_not_substrings():
    """ADVICE r5 low (restapi PATCH foreign-key guard): an unmodeled
    field whose NAME merely contains a guarded token as a substring
    ('volumesAttached', 'hostPorts' under status) keeps the documented
    lenient drop-as-POST-dropped behavior — only exact dotted-path
    segments ('volumes', 'ports') still 422."""
    from tests.test_restapi import make_pod_doc

    hub, srv, port = cluster()
    try:
        req(port, "POST", "/api/v1/namespaces/default/pods",
            make_pod_doc("p0"))
        # substring-only collisions: lenient no-op, like POST dropped them
        for patch in (
            {"status": {"volumesAttached": [{"name": "pv0"}]}},
            {"spec": {"hostPorts": [8080]}},
        ):
            code, doc = patch_req(
                port, "/api/v1/namespaces/default/pods/p0", patch)
            assert code == 200, (patch, code, doc)
        # exact guarded segment still rejects
        code, doc = patch_req(
            port, "/api/v1/namespaces/default/pods/p0",
            {"spec": {"volumes": [{"persistentVolumeClaim":
                                   {"claimName": "c"}}]}})
        assert code == 422, (code, doc)
    finally:
        srv.close()
