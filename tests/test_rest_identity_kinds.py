"""REST read surface for the identity/config kinds the round-5
controllers maintain: ServiceAccounts, ConfigMaps (root-CA +
cluster-info publishers), certificates.k8s.io CSRs — plus Service
Type/LoadBalancer status on the wire and the matching ktpu verbs."""

from kubernetes_tpu.bootstrap import init_cluster
from kubernetes_tpu.certificates import node_bootstrap_csr
from kubernetes_tpu.kubectl import main as ktpu
from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.sim import HollowCluster

from tests.test_restapi import req


def start(hub):
    srv = RestServer(hub, port=0)
    srv.serve()
    return srv, srv.port


def test_serviceaccounts_and_configmaps_served():
    hub = HollowCluster(seed=61, scheduler_kw={"enable_preemption": False})
    hub.add_namespace("team-a")
    hub.step()  # SA controller + root-CA publisher run
    srv, port = start(hub)
    try:
        code, doc = req(port, "GET",
                        "/api/v1/namespaces/team-a/serviceaccounts")
        assert code == 200 and doc["kind"] == "ServiceAccountList"
        assert [i["metadata"]["name"] for i in doc["items"]] == ["default"]
        assert doc["items"][0]["secrets"] == [{"name": "default-token"}]

        code, doc = req(port, "GET",
                        "/api/v1/namespaces/team-a/configmaps")
        assert code == 200
        names = [i["metadata"]["name"] for i in doc["items"]]
        assert "kube-root-ca.crt" in names
        code, doc = req(
            port, "GET",
            "/api/v1/namespaces/team-a/configmaps/kube-root-ca.crt")
        assert code == 200 and doc["data"]["ca.crt"] == hub.cluster_ca
        # the token VALUE never rides the wire
        import json as _json

        assert hub.service_account_token("team-a", "default") not in _json.dumps(doc)
    finally:
        srv.close()


def test_csrs_served_with_conditions():
    hub = HollowCluster(seed=62, scheduler_kw={"enable_preemption": False})
    hub.create_csr(node_bootstrap_csr("n0"))
    hub.create_csr(node_bootstrap_csr(
        "nX", username="mallory", groups=("devs",)))
    hub.step()  # approve+sign n0; mallory stays pending
    srv, port = start(hub)
    try:
        code, doc = req(
            port, "GET",
            "/apis/certificates.k8s.io/v1beta1/certificatesigningrequests")
        assert code == 200 and len(doc["items"]) == 2
        by_name = {i["metadata"]["name"]: i for i in doc["items"]}
        ok = by_name["csr-n0"]["status"]
        assert (ok["certificateIssued"]
                and ok["conditions"][0]["type"] == "Approved")
        pending = by_name["csr-nX"]["status"]
        assert not pending["conditions"]
        # the CREDENTIAL never rides the wire
        cert = hub.csrs["csr-n0"].certificate
        import json as _json

        assert cert not in _json.dumps(doc)
        # discovery advertises the group at v1beta1
        code, doc = req(port, "GET", "/apis/certificates.k8s.io/v1beta1")
        assert code == 200
        assert doc["resources"][0]["name"] == "certificatesigningrequests"
    finally:
        srv.close()


def test_lb_service_status_on_the_wire():
    from kubernetes_tpu.cloud import FakeCloud, Instance
    from kubernetes_tpu.proxy import Service
    from kubernetes_tpu.testing import make_node

    hub = HollowCluster(seed=63, scheduler_kw={"enable_preemption": False})
    cloud = FakeCloud()
    cloud.add_instance(Instance("n0", zone="z0"))
    hub.add_node(make_node("n0", cpu_milli=1000))
    hub.attach_cloud(cloud)
    hub.add_service(Service("web", selector={"app": "w"},
                            type="LoadBalancer"))
    hub.step()
    srv, port = start(hub)
    try:
        code, doc = req(port, "GET", "/api/v1/namespaces/default/services")
        assert code == 200
        svc = doc["items"][0]
        assert svc["spec"]["type"] == "LoadBalancer"
        assert svc["status"]["loadBalancer"]["ingress"][0]["ip"].startswith(
            "192.0.2.")
    finally:
        srv.close()


def test_ktpu_get_identity_kinds(capsys):
    hub, token = init_cluster()
    hub.create_csr(node_bootstrap_csr("n1"))
    hub.step()
    srv, port = start(hub)
    try:
        api = ["--api-server", f"127.0.0.1:{port}"]
        assert ktpu(api + ["get", "csr"]) == 0
        out = capsys.readouterr().out
        assert "csr-n1" in out and "system:node:n1" in out
        assert ktpu(api + ["get", "cm", "-n", "kube-public"]) == 0
        out = capsys.readouterr().out
        assert "cluster-info" in out
        assert ktpu(api + ["get", "sa", "-A"]) == 0
        out = capsys.readouterr().out
        assert "kube-system" in out and "default" in out
    finally:
        srv.close()


def test_event_field_selectors(capsys):
    """Server-side event field selectors (event/strategy.go
    ToSelectableFields): reason=, involvedObject.name=, type= filter at
    the hub before serialization; unsupported keys are 400; ktpu get
    events --field-selector rides the same query."""
    from kubernetes_tpu.kubectl import main as ktpu

    hub = HollowCluster(seed=64, scheduler_kw={"enable_preemption": False})
    hub.record_controller_event("CSRApproved", "default/csr-a", "ok")
    hub.record_controller_event("FailedToCreateRoute", "default/n0",
                                "quota", type_="Warning")
    hub.record_controller_event("FailedToCreateRoute", "default/n1",
                                "quota", type_="Warning")
    srv, port = start(hub)
    try:
        code, doc = req(
            port, "GET",
            "/api/v1/events?fieldSelector=reason%3DFailedToCreateRoute")
        assert code == 200 and len(doc["items"]) == 2
        code, doc = req(
            port, "GET",
            "/api/v1/events?fieldSelector=type%3DWarning,"
            "involvedObject.name%3Dn0")
        assert code == 200 and len(doc["items"]) == 1
        assert doc["items"][0]["involvedObject"]["name"] == "n0"
        code, doc = req(
            port, "GET", "/api/v1/events?fieldSelector=bogus%3Dx")
        assert code == 400
        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "get", "events",
                   "-A", "--field-selector", "reason=CSRApproved"])
        out = capsys.readouterr().out
        assert rc == 0 and "csr-a" in out and "n0" not in out
    finally:
        srv.close()


def test_watch_services_endpoints_events():
    """The watch surface beyond pods/nodes (the reference watches every
    kind): service/endpoints/event frames ride the same NDJSON feed
    with full wire docs; the events watch takes the same field
    selectors as the list; selector-less kinds reject selectors loudly."""
    import http.client

    from kubernetes_tpu.proxy import Service, ServicePort
    from kubernetes_tpu.testing import make_node, make_pod

    hub = HollowCluster(seed=66, scheduler_kw={"enable_preemption": False})
    srv, port = start(hub)

    def watch(path):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", path)
        r = conn.getresponse()
        raw = r.read()
        conn.close()
        if r.status != 200:
            import json as _json

            return r.status, _json.loads(raw)
        import json as _json

        return r.status, [_json.loads(l) for l in raw.splitlines() if l]

    try:
        rv0 = hub._revision
        hub.add_node(make_node("n0", cpu_milli=4000))
        hub.add_service(Service("web", selector={"app": "w"},
                                ports=(ServicePort(port=80),)))
        hub.create_pod(make_pod("w1", cpu_milli=100, labels={"app": "w"}))
        hub.step()
        hub.settle()
        code, frames = watch(f"/api/v1/watch/services?resourceVersion={rv0}")
        assert code == 200 and frames
        assert frames[0]["object"]["spec"]["clusterIP"].startswith("10.96.")
        code, frames = watch(
            f"/api/v1/watch/endpoints?resourceVersion={rv0}")
        assert code == 200 and frames
        assert any(f["object"].get("subsets") for f in frames)
        hub.record_controller_event("CSRApproved", "default/x", "ok")
        hub.record_controller_event("SuccessfulDelete", "default/y", "bye")
        code, frames = watch(
            f"/api/v1/watch/events?resourceVersion={rv0}"
            "&fieldSelector=reason%3DCSRApproved")
        assert code == 200
        reasons = {f["object"]["reason"] for f in frames}
        assert reasons == {"CSRApproved"}
        # label-less kinds: a labelSelector matches nothing (the
        # reference's semantics for unlabeled objects) — identical on
        # list and watch, so the informer pair accepts the same options
        code, frames = watch(
            f"/api/v1/watch/services?resourceVersion={rv0}"
            "&labelSelector=app%3Dw")
        assert code == 200
        assert not any(f["type"] == "ADDED" and "spec" in f["object"]
                       for f in frames)
        code, doc = req(port, "GET",
                        "/api/v1/services?labelSelector=app%3Dw")
        assert code == 200 and doc["items"] == []
        # metadata field selectors DO select on these kinds
        code, doc = req(
            port, "GET",
            "/api/v1/services?fieldSelector=metadata.name%3Dweb")
        assert code == 200 and len(doc["items"]) == 1
        # unknown field keys error at request time
        code, doc = req(port, "GET",
                        "/api/v1/services?fieldSelector=spec.bogus%3Dx")
        assert code == 400
    finally:
        srv.close()
