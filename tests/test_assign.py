"""Assignment solver tests: greedy scan vs the serial oracle (exact parity),
and batch rounds vs validity/quality invariants — the analog of
generic_scheduler_test.go's Schedule/selectHost suites."""

import random

import numpy as np

import pyref
from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.ops.arrays import nodes_to_device, pods_to_device, selectors_to_device
from kubernetes_tpu.ops.assign import batch_assign, greedy_assign
from kubernetes_tpu.snapshot import SnapshotPacker
from kubernetes_tpu.testing import make_node, make_pod
from test_predicates import random_cluster


def build(nodes, scheduled, pending):
    pk = SnapshotPacker()
    for p in list(scheduled) + list(pending):
        pk.intern_pod(p)
    nt = pk.pack_nodes(nodes, scheduled)
    pt = pk.pack_pods(pending)
    st = pk.pack_selector_tables()
    return nodes_to_device(nt), pods_to_device(pt), selectors_to_device(st)


def check_valid_assignment(assigned, pending, nodes, scheduled):
    """Every placement must be feasible under serial re-simulation in
    arrival order of the assignment (capacity, ports, selectors, taints)."""
    node_pods = {nd.name: [] for nd in nodes}
    for p in scheduled:
        if p.node_name in node_pods:
            node_pods[p.node_name].append(p)
    order = sorted(range(len(pending)), key=lambda i: (-pending[i].priority, i))
    placed = 0
    for i in order:
        j = assigned[i]
        if j < 0:
            continue
        pod, nd = pending[i], nodes[j]
        assert pyref.feasible(pod, nd, node_pods[nd.name]), (
            f"invalid placement: {pod.name} -> {nd.name}"
        )
        node_pods[nd.name].append(pod)
        placed += 1
    return placed


def test_greedy_matches_serial_oracle():
    for seed in range(8):
        rng = random.Random(400 + seed)
        nodes, scheduled, pending = random_cluster(rng, n_nodes=8, n_sched=12, n_pending=10)
        # priorities exercise the queue ordering
        for p in pending:
            p.priority = rng.choice([0, 0, 10, 100])
        dn, dp, ds = build(nodes, scheduled, pending)
        got, _ = greedy_assign(dp, dn, ds)
        got = np.asarray(got)[: len(pending)]
        want = [j for j, _ in pyref.serial_schedule(pending, nodes, scheduled)]
        if not (got == np.asarray(want)).all():
            k = int(np.argwhere(got != np.asarray(want))[0][0])
            raise AssertionError(
                f"seed {seed}: pod {pending[k].name}: device={got[k]} oracle={want[k]}\n"
                f"pod={pending[k]}"
            )


def test_batch_assign_validity_and_coverage():
    for seed in range(5):
        rng = random.Random(500 + seed)
        nodes, scheduled, pending = random_cluster(rng, n_nodes=8, n_sched=10, n_pending=14)
        dn, dp, ds = build(nodes, scheduled, pending)
        assigned, _, rounds = batch_assign(dp, dn, ds)
        assigned = np.asarray(assigned)[: len(pending)]
        check_valid_assignment(assigned, pending, nodes, scheduled)
        # coverage parity: batch must place at least as many pods as exist
        # in the serial solution (greedy serial never does better than a
        # round-based solver with the same feasibility rules on count)
        serial = [j for j, _ in pyref.serial_schedule(pending, nodes, scheduled)]
        n_serial = sum(1 for j in serial if j >= 0)
        n_batch = sum(1 for j in assigned if j >= 0)
        assert n_batch >= n_serial - 1, (seed, n_batch, n_serial)


def test_batch_capacity_contention():
    # 20 identical pods, 2 nodes with room for 3 pods each -> exactly 6 land
    nodes = [make_node(f"n{i}", cpu_milli=3000, memory=64 * 2**30, pods=110) for i in range(2)]
    pending = [make_pod(f"p{i}", cpu_milli=1000) for i in range(20)]
    dn, dp, ds = build(nodes, [], pending)
    assigned, _, rounds = batch_assign(dp, dn, ds)
    assigned = np.asarray(assigned)[: len(pending)]
    placed = check_valid_assignment(assigned, pending, nodes, [])
    assert placed == 6
    # high-priority pods must win the contended slots
    pending2 = [make_pod(f"q{i}", cpu_milli=1000, priority=100 if i >= 14 else 0)
                for i in range(20)]
    dn, dp, ds = build(nodes, [], pending2)
    assigned2, _, _ = batch_assign(dp, dn, ds)
    assigned2 = np.asarray(assigned2)[: len(pending2)]
    winners = {i for i in range(20) if assigned2[i] >= 0}
    assert winners == {14, 15, 16, 17, 18, 19}


def test_batch_port_conflicts_within_batch():
    nodes = [make_node(f"n{i}") for i in range(2)]
    pending = [make_pod(f"p{i}", host_ports=[("TCP", "", 8080)]) for i in range(4)]
    dn, dp, ds = build(nodes, [], pending)
    assigned, _, _ = batch_assign(dp, dn, ds)
    assigned = np.asarray(assigned)[: len(pending)]
    check_valid_assignment(assigned, pending, nodes, [])
    # exactly one port-8080 pod per node
    assert sum(1 for j in assigned if j >= 0) == 2
    assert len({j for j in assigned if j >= 0}) == 2


def test_spread_prefers_empty_nodes():
    svc = LabelSelector(match_labels={"app": "web"})
    nodes = [make_node(f"n{i}") for i in range(4)]
    scheduled = [
        make_pod("s0", node_name="n0", labels={"app": "web"}),
        make_pod("s1", node_name="n0", labels={"app": "web"}),
    ]
    pod = make_pod("p", labels={"app": "web"}, spread_selectors=(svc,))
    dn, dp, ds = build(nodes, scheduled, [pod])
    assigned, _ = greedy_assign(dp, dn, ds)
    assert int(assigned[0]) != 0  # avoids the loaded node


def test_secrets_variant_is_volume_inert_and_matches_base():
    """BenchmarkSchedulingSecrets analog (VERDICT r4 item 8): pods with
    a Secret volume must schedule EXACTLY like base pods — the volume
    fan-in machinery runs (volume tables packed, kernels invoked) but no
    volume predicate fires, mirroring the reference's 'no special
    handling' contract."""
    import numpy as np

    from bench import build_variant
    from kubernetes_tpu.ops.assign import batch_assign

    ws = build_variant("secrets", 50, 25, 96)
    wb = build_variant("base", 50, 25, 96)
    assert ws.has_vol and not wb.has_vol  # fan-in actually exercised
    dps, dvs = ws.device_batch(ws.pending[:96], 96)
    dpb, dvb = wb.device_batch(wb.pending[:96], 96)
    assert dvs is not None
    a_s, u_s, r_s = batch_assign(dps, ws.dn, ws.ds, vol=dvs, per_node_cap=4)
    a_b, u_b, r_b = batch_assign(dpb, wb.dn, wb.ds, vol=dvb, per_node_cap=4)
    assert (np.asarray(a_s) == np.asarray(a_b)).all()
    assert int((np.asarray(a_s) >= 0).sum()) == 96
