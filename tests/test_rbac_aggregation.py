"""rbac/v1 role/binding model + the ClusterRole aggregation controller
(clusterroleaggregation_controller.go:76 syncClusterRole): aggregated
roles materialize the union of matching roles' rules; the
RBACAuthorizer resolves bindings against the LIVE role dicts, so an
aggregation update changes authorization without rebuilding anything."""

from kubernetes_tpu.auth import (
    ALLOW,
    NO_OPINION,
    Attributes,
    ClusterRole,
    ClusterRoleBinding,
    PolicyRule,
    RBACAuthorizer,
    UserInfo,
    aggregate_cluster_roles,
)
from kubernetes_tpu.sim import HollowCluster


def _attrs(user, verb, resource, ns=""):
    return Attributes(user=user, verb=verb, resource=resource,
                      namespace=ns, name="", path="")


ALICE = UserInfo(name="alice", groups=("devs",))


def test_admin_edit_view_aggregation_stack():
    """The reference's admin/edit/view roles are built exactly this
    way: view aggregates rbac.authorization.k8s.io/aggregate-to-view
    labeled roles; edit aggregates view + more; granting a new CRD's
    reader role to view is ONE labeled role away."""
    roles = {
        "view": ClusterRole("view", aggregation_selectors=[
            {"rbac.example.com/aggregate-to-view": "true"}]),
        "pods-reader": ClusterRole(
            "pods-reader",
            rules=[PolicyRule(verbs=("get", "list"), resources=("pods",))],
            labels={"rbac.example.com/aggregate-to-view": "true"}),
    }
    assert aggregate_cluster_roles(roles) == 1
    assert roles["view"].rules == (
        PolicyRule(verbs=("get", "list"), resources=("pods",)),)
    # adding another labeled role extends view on the next pass
    roles["cm-reader"] = ClusterRole(
        "cm-reader",
        rules=[PolicyRule(verbs=("get",), resources=("configmaps",))],
        labels={"rbac.example.com/aggregate-to-view": "true"})
    assert aggregate_cluster_roles(roles) == 1
    assert len(roles["view"].rules) == 2
    # idempotent once settled
    assert aggregate_cluster_roles(roles) == 0


def test_chained_aggregation_resolves_in_one_call():
    """view -> edit -> admin chained aggregation (the real stack's
    shape): one aggregate pass must reach the fixpoint even though
    'admin' sorts BEFORE its source 'edit' — the reference converges
    via re-enqueues; here the function loops until settled."""
    roles = {
        "admin": ClusterRole("admin", aggregation_selectors=[
            {"to-admin": "true"}]),
        "edit": ClusterRole("edit", labels={"to-admin": "true"},
                            aggregation_selectors=[{"to-edit": "true"}]),
        "view": ClusterRole(
            "view", labels={"to-edit": "true"},
            rules=[PolicyRule(verbs=("get",), resources=("pods",))]),
    }
    aggregate_cluster_roles(roles)
    assert PolicyRule(verbs=("get",), resources=("pods",)) in \
        roles["admin"].rules
    assert aggregate_cluster_roles(roles) == 0  # settled


def test_authorizer_resolves_bindings_live():
    roles = {
        "view": ClusterRole("view", aggregation_selectors=[
            {"aggregate-to-view": "true"}]),
    }
    bindings = [ClusterRoleBinding(role="view", subjects=("devs",))]
    authz = RBACAuthorizer(roles, bindings)
    a = _attrs(ALICE, "get", "pods", "default")
    assert authz.authorize(a) == NO_OPINION  # nothing aggregated yet
    roles["pods-reader"] = ClusterRole(
        "pods-reader", rules=[PolicyRule(verbs=("get",),
                                         resources=("pods",))],
        labels={"aggregate-to-view": "true"})
    aggregate_cluster_roles(roles)
    assert authz.authorize(a) == ALLOW  # same authorizer, live dicts
    # RBAC never denies — an uncovered verb is NO_OPINION, not DENY
    assert authz.authorize(
        _attrs(ALICE, "delete", "pods", "default")) == NO_OPINION


def test_hub_runs_aggregation_pass():
    hub = HollowCluster(seed=41, scheduler_kw={"enable_preemption": False})
    hub.cluster_roles["view"] = ClusterRole(
        "view", aggregation_selectors=[{"to-view": "true"}])
    hub.cluster_roles["leaf"] = ClusterRole(
        "leaf", rules=[PolicyRule(verbs=("list",), resources=("nodes",))],
        labels={"to-view": "true"})
    hub.step()
    assert hub.cluster_roles["view"].rules == (
        PolicyRule(verbs=("list",), resources=("nodes",)),)


def test_self_and_nonmatching_excluded():
    roles = {
        "agg": ClusterRole(
            "agg", aggregation_selectors=[{"pick": "yes"}],
            labels={"pick": "yes"},  # self-label must NOT self-include
            rules=[PolicyRule(verbs=("x",), resources=("y",))]),
        "other": ClusterRole(
            "other", rules=[PolicyRule(verbs=("get",),
                                       resources=("pods",))],
            labels={"pick": "no"}),
    }
    aggregate_cluster_roles(roles)
    # nothing matched: rules overwritten to empty (the reference PUTs
    # the recomputed union, which may be empty)
    assert roles["agg"].rules == ()
