"""Integration-shim tests: extender protocol (both directions, over real
HTTP like test/integration/scheduler/extender_test.go), metrics exposition,
event aggregation, operation tracing, and leader election."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.config import ExtenderConfig, LeaderElectionConfig
from kubernetes_tpu.events import REASON_FAILED, REASON_SCHEDULED, EventRecorder
from kubernetes_tpu.extender import HTTPExtender, build_extenders
from kubernetes_tpu.leaderelection import InMemoryLock, LeaderElector
from kubernetes_tpu.metrics import SchedulerMetrics
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod
from kubernetes_tpu.utils.trace import Trace


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# a tiny extender webhook (the fake extender of extender_test.go)
# ---------------------------------------------------------------------------


def start_fake_extender(filter_fn=None, prioritize_fn=None, bind_log=None):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n).decode())
            verb = self.path.strip("/")
            if verb == "filter":
                out = filter_fn(payload)
            elif verb == "prioritize":
                out = prioritize_fn(payload)
            elif verb == "bind":
                bind_log.append(payload)
                out = {"error": ""}
            else:
                out = {"error": f"bad verb {verb}"}
            body = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def test_extender_filter_prioritize_bind_over_http():
    bind_log = []

    def filt(payload):
        # reject node n0; wire shape: nodeCacheCapable name lists
        names = [n for n in payload["nodenames"] if n != "n0"]
        return {
            "nodenames": names,
            "failedNodes": {"n0": "extender says no"},
            "error": "",
        }

    def prio(payload):
        return [
            {"host": n, "score": 10 if n == "n2" else 1}
            for n in payload["nodenames"]
        ]

    srv, url = start_fake_extender(filt, prio, bind_log)
    try:
        cfgs = [ExtenderConfig(
            url_prefix=url, filter_verb="filter", prioritize_verb="prioritize",
            bind_verb="bind", weight=5, node_cache_capable=True,
        )]
        s = Scheduler(
            extenders=build_extenders(cfgs), clock=FakeClock(),
            enable_preemption=False,
        )
        for i in range(3):
            s.on_node_add(make_node(f"n{i}"))
        s.on_pod_add(make_pod("p0"))
        res = s.schedule_cycle()
        # filter removed n0; prioritize (weight 5) pushes n2 over n1
        assert res.assignments["default/p0"] == "n2"
        # the binder-extender took the binding: default binder untouched
        assert s.binder.bindings == []
        assert bind_log and bind_log[0]["node"] == "n2"
        assert bind_log[0]["podName"] == "p0"
    finally:
        srv.shutdown()


def test_extender_error_policy():
    # unreachable extender: ignorable -> scheduling proceeds; otherwise the
    # pod fails with an Extender reason
    cfg_bad = ExtenderConfig(url_prefix="http://127.0.0.1:9", filter_verb="filter",
                             http_timeout_s=0.2)
    s = Scheduler(extenders=build_extenders([cfg_bad]), clock=FakeClock(),
                  enable_preemption=False)
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0"))
    res = s.schedule_cycle()
    assert res.scheduled == 0
    assert any("Extender:" in r for r in res.failure_reasons["default/p0"])

    cfg_ign = ExtenderConfig(url_prefix="http://127.0.0.1:9", filter_verb="filter",
                             http_timeout_s=0.2, ignorable=True)
    s2 = Scheduler(extenders=build_extenders([cfg_ign]), clock=FakeClock(),
                   enable_preemption=False)
    s2.on_node_add(make_node("n0"))
    s2.on_pod_add(make_pod("p0"))
    res2 = s2.schedule_cycle()
    assert res2.scheduled == 1


def test_extender_managed_resources_gate_interest():
    ext = HTTPExtender(ExtenderConfig(
        url_prefix="http://x", managed_resources=("example.com/gpu",)
    ))
    assert not ext.is_interested(make_pod("plain"))
    assert ext.is_interested(make_pod("gpu", scalars={"example.com/gpu": 1}))


# ---------------------------------------------------------------------------
# serving the framework AS an extender (the reverse seam)
# ---------------------------------------------------------------------------


def test_extender_server_reverse_seam():
    from kubernetes_tpu.server import ExtenderServer, serve_scheduler

    s = Scheduler(clock=FakeClock(), enable_preemption=False)
    s.on_node_add(make_node("big", cpu_milli=32000))
    s.on_node_add(make_node("small", cpu_milli=200))
    srv = serve_scheduler(s, extender=ExtenderServer(s))
    try:
        port = srv.server_address[1]

        def post(verb, payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/{verb}",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read().decode())

        # a Go scheduler would POST exactly this shape
        args = {
            "pod": {
                "metadata": {"name": "w", "namespace": "default"},
                "spec": {"containers": [
                    {"resources": {"requests": {"cpu": "1000m", "memory": "1Gi"}}}
                ]},
            },
            "nodenames": ["big", "small"],
        }
        out = post("filter", args)
        assert out["nodenames"] == ["big"]
        assert "PodFitsResources" in out["failedNodes"]["small"]
        scores = post("prioritize", args)
        assert {h["host"] for h in scores} == {"big", "small"}

        # healthz + metrics ride the same server
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=60) as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=60) as r:
            text = r.read().decode()
        assert "scheduler_schedule_attempts_total" in text
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# metrics / events / trace / leader election units
# ---------------------------------------------------------------------------


def test_metrics_recorded_by_cycle():
    clk = FakeClock()
    s = Scheduler(clock=clk, enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("ok", cpu_milli=100))
    s.on_pod_add(make_pod("big", cpu_milli=5000))
    s.schedule_cycle()
    m = s.metrics
    assert m.schedule_attempts.value(result="scheduled") == 1
    assert m.schedule_attempts.value(result="unschedulable") == 1
    assert m.e2e_scheduling_duration.count() == 1
    assert m.pending_pods.value(queue="unschedulable") == 1
    text = m.registry.expose()
    assert 'scheduler_schedule_attempts_total{result="scheduled"} 1' in text
    assert "scheduler_e2e_scheduling_duration_seconds_bucket" in text


def test_event_recorder_aggregates():
    clk = FakeClock()
    rec = EventRecorder(clock=clk)
    s = Scheduler(clock=clk, enable_preemption=False, event_sink=rec.sink())
    s.on_node_add(make_node("n0", cpu_milli=100))
    s.on_pod_add(make_pod("big", cpu_milli=5000))
    s.schedule_cycle()
    clk.t += 120
    s.queue.move_all_to_active()
    s.schedule_cycle()
    evs = rec.events("default/big")
    assert len(evs) == 1 and evs[0].reason == REASON_FAILED and evs[0].count == 2
    s.on_pod_add(make_pod("ok", cpu_milli=10))
    s.schedule_cycle()
    assert rec.events("default/ok")[0].reason == REASON_SCHEDULED


def test_trace_log_if_long():
    clk = FakeClock()
    tr = Trace("op", clock=clk, pod="x")
    clk.t += 0.02
    tr.step("fast part")
    clk.t += 0.2
    tr.step("slow part")
    text = tr.log_if_long(0.1)
    assert text and "slow part" in text and "op" in text
    tr2 = Trace("quick", clock=clk)
    assert tr2.log_if_long(0.1) is None


def test_leader_election_failover():
    clk = FakeClock()
    lock = InMemoryLock()
    cfg = LeaderElectionConfig(lease_duration_s=15)
    events = []
    a = LeaderElector("a", lock, cfg, clk,
                      on_started_leading=lambda: events.append("a+"),
                      on_stopped_leading=lambda: events.append("a-"))
    b = LeaderElector("b", lock, cfg, clk,
                      on_started_leading=lambda: events.append("b+"))
    assert a.tick() and a.is_leader()
    assert not b.tick() and not b.is_leader()  # lease held by a
    clk.t += 10
    assert a.tick()  # renew
    assert not b.tick()
    # a dies; b waits out the full lease from its last observation
    clk.t += 14
    assert not b.tick()
    clk.t += 2  # now past a's lease
    assert b.tick() and b.is_leader()
    assert events == ["a+", "b+"]
    rec = lock.get()
    assert rec.holder_identity == "b" and rec.leader_transitions == 1


def test_filelock_interleaved_cas_single_winner(tmp_path):
    """Split-brain regression (advisor): two candidates that both read the
    same record must not both win the CAS — the flock makes the
    read-compare-write atomic, so the loser observes the winner's write."""
    from kubernetes_tpu.leaderelection import FileLock, LeaderElectionRecord

    path = str(tmp_path / "lease.json")
    a, b = FileLock(path), FileLock(path)
    rec_a = LeaderElectionRecord("a", 15, 0.0, 0.0, 0)
    rec_b = LeaderElectionRecord("b", 15, 0.0, 0.0, 0)

    # interleave: while A is inside its locked read-modify-write, B starts
    # the same CAS from the same observed (None) state and blocks on the
    # flock; once A lands, B must re-read, see A's record, and lose.
    results = {}
    b_started = threading.Event()

    def b_attempt():
        b_started.set()
        results["b"] = b.create_or_update(rec_b, None)

    orig_read = a._read

    def hooked_read():
        out = orig_read()
        threading.Thread(target=b_attempt, daemon=True).start()
        b_started.wait(5)
        import time as _t

        _t.sleep(0.05)  # give B time to reach (and block on) the flock
        return out

    a._read = hooked_read
    results["a"] = a.create_or_update(rec_a, None)
    a._read = orig_read
    # wait for B to finish
    for _ in range(100):
        if "b" in results:
            break
        import time as _t

        _t.sleep(0.05)
    assert results["a"] is True
    assert results["b"] is False
    assert a.get().holder_identity == "a"


def test_extender_server_prioritize_normalizes_to_0_10():
    """Advisor fix: the fused kernel total routinely exceeds 10; the server
    must normalize per request (max feasible node -> 10) instead of
    clamping everything to the ceiling, or the seam carries no ranking."""
    from kubernetes_tpu.server import ExtenderServer

    s = Scheduler(clock=lambda: 0.0, enable_preemption=False)
    s.on_node_add(make_node("idle", cpu_milli=32000, memory=64 * 2**30))
    s.on_node_add(make_node("busy", cpu_milli=32000, memory=64 * 2**30))
    s.on_node_add(make_node("tiny", cpu_milli=100))
    s.on_pod_add(make_pod("filler", cpu_milli=30000, node_name="busy"))
    ext = ExtenderServer(s)
    out = ext._prioritize(
        {
            "pod": {
                "metadata": {"name": "w", "namespace": "default"},
                "spec": {"containers": [
                    {"resources": {"requests": {"cpu": "1000m", "memory": "1Gi"}}}
                ]},
            },
            "nodenames": ["idle", "busy", "tiny"],
        }
    )
    scores = {h["host"]: h["score"] for h in out}
    assert scores["idle"] == 10  # best feasible node maps to the ceiling
    assert 0 < scores["busy"] < 10  # ranking signal survives
    assert scores["tiny"] == 0  # infeasible
