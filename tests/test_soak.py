"""Day-in-the-life soak (ISSUE 16): the composed phase engine, the
leak-sentinel layer, and the scheduler fixes the soak surfaced.

Four layers, cheapest first:

1. sentinel mechanics — the growth verdict over clean-phase boundary
   samples (monotonic ratchet = leak; plateau / sawtooth = fine), the
   tolerance prefix table, and gauge freshness via the WRITE counter
   (a gauge maintained every cycle but sampled at drained moments must
   read fresh — the fingerprint-only version regressed exactly that);
2. regression pins for the unbounded structures and livelocks the
   soak found — the reflector tombstone LRU bound, pod-keyed side
   state returning to baseline on every exit path, the gang-member
   rebind livelock (a member whose bind failed transiently re-queues
   alone and must still pass the minMember gate by crediting its
   already-placed siblings), and the nominated-pods solve variant
   joining the warmup sweep (the first post-preemption cycle must not
   pay a hot-path compile);
3. the steady-state consolidation re-pack
   (``scenario.repack_interval_s``): off-cadence no-op, fragmentation
   strictly decreases after a drain + re-solve, foreign/in-flight
   pods pin their node;
4. the composed fake-clock soak (seeds 1/2/3): the full phase
   sequence — traffic, clean, rpc chaos, clean, preemption cascade,
   clean — compressed into seconds, with 0 double binds, 0 auditor
   violations, clean-phase counter deltas all 0, and flat sentinel
   curves over the clean boundaries.
"""

from __future__ import annotations

import dataclasses
import random
from types import SimpleNamespace

import pytest

from kubernetes_tpu.config import ScenarioConfig, WarmupConfig
from kubernetes_tpu.faults import FaultInjector, RPCError, RPCTimeout
from kubernetes_tpu.metrics import Gauge, Registry
from kubernetes_tpu.obs.audit import StateAuditor
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.soak import (
    DEFAULT_TOLERANCE,
    SoakEngine,
    SoakPhase,
    SoakSentinels,
    standard_counters,
)
from kubernetes_tpu.testing import make_node, make_pod


class Clock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class Truth:
    """Minimal CAS'd hub truth (the test_net_chaos pattern, plus the
    spec registry the auditor's truth view needs): binding an
    already-bound key is the never-double-place violation, counted and
    refused."""

    def __init__(self, injector: FaultInjector = None) -> None:
        self.bound: dict = {}
        self.spec: dict = {}
        self.double_bind_attempts = 0
        self.commits = 0
        self.script: list = []

    def register(self, pod) -> None:
        self.spec[pod.key()] = pod

    def delete(self, key: str) -> None:
        self.spec.pop(key, None)
        self.bound.pop(key, None)

    def unbind(self, key: str) -> None:
        self.bound.pop(key, None)

    def bind(self, pod, node_name: str) -> None:
        self.spec.setdefault(pod.key(), pod)
        action = self.script.pop(0) if self.script else "ok"
        if action == "error":
            raise RPCError("injected: definitely not committed")
        if pod.key() in self.bound:
            self.double_bind_attempts += 1
            raise RuntimeError(f"{pod.key()} already bound")
        self.bound[pod.key()] = node_name
        self.commits += 1

    def read(self, key: str):
        spec = self.spec.get(key)
        if spec is None:
            return None
        return SimpleNamespace(uid=spec.uid,
                               node_name=self.bound.get(key, ""))

    def list_pods(self):
        return [dataclasses.replace(p, node_name=self.bound.get(k, ""),
                                    deletion_timestamp=0.0)
                for k, p in self.spec.items()]


def _sched(truth: Truth, clock=None, **kw):
    clock = clock or Clock()
    kw.setdefault("enable_preemption", False)
    s = Scheduler(binder=truth, clock=clock,
                  retry_sleep=lambda _s: None, jitter_seed=1,
                  pod_reader=truth.read, **kw)
    return s, clock


def _confirm(s, res) -> None:
    """Relay the bind confirmations a watch stream would deliver: the
    assumed pods flip to watch-confirmed BOUND (cache state machine),
    exactly what the soak driver's hub relay does."""
    for key, node in dict(res.assignments).items():
        cached = s.cache.pod(key)
        if cached is None:
            continue
        new = dataclasses.replace(cached, node_name=node)
        s.on_pod_update(cached, new)


# ---------------------------------------------------------------------------
# sentinel mechanics
# ---------------------------------------------------------------------------


def _stub_sched(sizes: dict):
    return SimpleNamespace(state_sizes=lambda: dict(sizes))


def test_growth_verdict_flags_monotonic_ratchet():
    """A clean-boundary series that never decreases, rises twice, and
    exceeds tolerance is a leak; a plateau or a sawtooth is not."""
    sizes = {"why_pending": 10}
    sent = SoakSentinels(sched=_stub_sched(sizes), rss_reader=lambda: 0)
    for v in (10, 13, 17):
        sizes["why_pending"] = v
        sent.sample(tag="phase-end", clean=True)
    assert "sched.why_pending" in sent.leaking()
    rep = sent.growth_report()["sched.why_pending"]
    assert rep["judged"] and rep["growing"] and rep["growth"] == 7

    # sawtooth (state that drains) is NOT a leak
    sizes2 = {"why_pending": 10}
    sent2 = SoakSentinels(sched=_stub_sched(sizes2), rss_reader=lambda: 0)
    for v in (10, 17, 11):
        sizes2["why_pending"] = v
        sent2.sample(tag="phase-end", clean=True)
    assert sent2.leaking() == []

    # flat plateau is NOT a leak
    sizes3 = {"why_pending": 10}
    sent3 = SoakSentinels(sched=_stub_sched(sizes3), rss_reader=lambda: 0)
    for _ in range(3):
        sent3.sample(tag="phase-end", clean=True)
    assert sent3.leaking() == []


def test_growth_verdict_needs_three_clean_samples():
    sizes = {"why_pending": 0}
    sent = SoakSentinels(sched=_stub_sched(sizes), rss_reader=lambda: 0)
    for v in (0, 50):
        sizes["why_pending"] = v
        sent.sample(tag="phase-end", clean=True)
    # two clean points cannot be judged — growing stays False
    assert sent.leaking() == []
    assert not sent.growth_report()["sched.why_pending"]["judged"]


def test_tolerance_prefix_matching_and_override():
    """Plateauing series within their tolerance row pass; driver
    overrides merge over the defaults; prefix rows (``reflector.``)
    cover every instance-numbered key."""
    sizes = {"interned_items": 0}
    sent = SoakSentinels(sched=_stub_sched(sizes), rss_reader=lambda: 0,
                         tolerance={"rss_kb": 999999.0})
    for v in (0, 100, 200):  # within the 256 interner tolerance
        sizes["interned_items"] = v
        sent.sample(tag="phase-end", clean=True)
    assert sent.leaking() == []
    assert sent.tolerance["rss_kb"] == 999999.0  # override merged
    assert sent.tolerance["sched.interned_items"] == \
        DEFAULT_TOLERANCE["sched.interned_items"]
    # traffic-phase samples never join the clean series
    sizes["interned_items"] = 10 ** 6
    sent.sample(tag="cadence", clean=False)
    assert sent.leaking() == []


def test_gauge_freshness_counts_writes_not_value_changes():
    """Regression pin (soak finding): scheduler_pending_pods is set on
    every queue mutation but reads 0 at every drained sample point — a
    value-only fingerprint called it stale. The write counter joins
    the fingerprint, so maintained-and-idle reads FRESH while a gauge
    nobody writes still goes stale."""
    reg = Registry()
    maintained = reg.register(Gauge("maintained", ""))
    abandoned = reg.register(Gauge("abandoned", ""))
    maintained.set(0.0)
    abandoned.set(3.0)
    sent = SoakSentinels(registry=reg,
                         fresh_gauges=["maintained", "abandoned"],
                         rss_reader=lambda: 0)
    sent.sample()        # idx 0: first sight fingerprints both
    maintained.set(0.0)  # a WRITE of the same value
    sent.sample()        # idx 1
    maintained.set(0.0)
    sent.sample()        # idx 2
    # value-only fingerprinting would read BOTH as unchanged since 0
    assert sent.stale_since(1) == ["abandoned"]


# ---------------------------------------------------------------------------
# regression pins: the structures and livelocks the soak surfaced
# ---------------------------------------------------------------------------


def test_reflector_tombstone_lru_bounded():
    """Deleted-object dedupe floors migrate to a bounded LRU: the live
    map stays sized to the live set and the tombstone set can never
    grow past its capacity, however many deletes churn through."""
    from kubernetes_tpu.sim import HollowCluster, Reflector

    hub = HollowCluster(seed=3,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=64000))
    sink = Scheduler(clock=hub.clock, enable_preemption=False)
    r = Reflector(hub, sink)
    r.tombstone_capacity = 8
    r.list_and_watch()
    for i in range(50):
        hub.create_pod(make_pod(f"t{i}", cpu_milli=10))
        hub.delete_pod(f"default/t{i}")
        r.pump()
    assert len(r._gone_rev) <= 8
    # live floors track the live set only (node + nothing else)
    assert all(not k.startswith("pods/default/t")
               for k in r._obj_rev)


def test_pod_side_state_returns_to_baseline_on_exit():
    """Exit-path parity: every pod-keyed side structure must pop on
    every exit (bind, delete) — the leak class the sentinels watch at
    zero tolerance."""
    t = Truth()
    s, clock = _sched(t)
    s.on_node_add(make_node("n0", cpu_milli=8000))
    for i in range(4):
        p = make_pod(f"p{i}", cpu_milli=100)
        t.register(p)
        s.on_pod_add(p)
    res = s.schedule_cycle()
    assert res.scheduled == 4
    _confirm(s, res)
    for i in range(4):
        key = f"default/p{i}"
        pod = s.cache.pod(key)
        t.delete(key)
        s.on_pod_delete(pod)
    clock.advance(120.0)
    s.schedule_cycle()
    sizes = s.state_sizes()
    for key in ("why_pending", "ambiguous_binds", "cycle_states",
                "waiting_pods", "queue_pending", "cache_assumed",
                "cache_pods", "packer_pod_refs"):
        assert sizes[key] == 0, (key, sizes)


def test_gang_member_rebind_is_not_livelocked():
    """Regression pin (soak finding): a gang member whose bind failed
    transiently re-queues ALONE. The minMember gate must credit its
    already-placed siblings (cache.group_members) — counting only
    batch-present members parks the straggler at GangIncomplete
    forever while the rest of its gang runs."""
    t = Truth()
    s, clock = _sched(t)
    s.on_node_add(make_node("n0", cpu_milli=8000))
    s.on_node_add(make_node("n1", cpu_milli=8000))
    gang = [make_pod(f"g{i}", cpu_milli=100, pod_group="job",
                     pod_group_min_available=3) for i in range(3)]
    t.script = ["ok", "ok", "error"]  # third member's bind RPC fails
    for p in gang:
        t.register(p)
        s.on_pod_add(p)
    res = s.schedule_cycle()
    assert len(t.bound) == 2 and res.bind_errors == 1
    assert s.cache.group_members("job") == 2
    # the straggler retries ALONE once its backoff elapses — and binds
    for _ in range(30):
        clock.advance(10.0)
        if s.schedule_cycle().scheduled:
            break
    assert len(t.bound) == 3 and t.double_bind_attempts == 0


def test_warmup_registers_nominated_solve_variant():
    """Regression pin (soak finding): with preemption enabled the
    cycle after a preemption carries a (P, N) nominated-pods mask and
    ``extra_mask is None`` flips in the solve digest — a different
    compiled program. The warmup sweep must register BOTH variants, or
    the first post-preemption cycle pays a hot-path compile exactly
    when capacity is tightest."""
    captured = []

    def _capture(s):
        orig = s.obs.jax.record_call

        def spy(site, *trees, static=None, warmup=False):
            if site == "solve" and warmup and static is not None:
                captured.append(static)
            return orig(site, *trees, static=static, warmup=warmup)

        s.obs.jax.record_call = spy

    t = Truth()
    s, _ = _sched(t, enable_preemption=True,
                  warmup=WarmupConfig(enabled=True, pod_buckets=(4,),
                                      include_filter=False))
    s.on_node_add(make_node("n0", cpu_milli=8000))
    _capture(s)
    assert s.warmup(sample_pods=[make_pod("w", cpu_milli=100)]) > 0
    assert any(st[8] is False for st in captured), \
        "masked (nominated) solve variant never warmed"
    assert any(st[8] is True for st in captured)

    # without preemption no nomination can ever arise — the masked
    # variant is NOT warmed (no compile budget spent on a dead shape)
    captured.clear()
    t2 = Truth()
    s2, _ = _sched(t2, enable_preemption=False,
                   warmup=WarmupConfig(enabled=True, pod_buckets=(4,),
                                       include_filter=False))
    s2.on_node_add(make_node("n0", cpu_milli=8000))
    _capture(s2)
    s2.warmup(sample_pods=[make_pod("w", cpu_milli=100)])
    assert all(st[8] is True for st in captured)


# ---------------------------------------------------------------------------
# steady-state consolidation re-pack
# ---------------------------------------------------------------------------


def _repack_sched(interval: float = 5.0):
    t = Truth()
    s, clock = _sched(
        t, scenario=ScenarioConfig(pack="consolidation",
                                   repack_interval_s=interval,
                                   repack_max_pods=8))
    for i in range(3):
        s.on_node_add(make_node(f"n{i}", cpu_milli=8000, pods=32))

    def evictor(p):
        # hub-integration seam: unbind at the truth, then converge
        # local state (what the soak driver's watch relay does)
        t.unbind(p.key())
        s.cache.remove_pod(p.key())
        s.queue.add_if_not_present(dataclasses.replace(
            p, node_name="", deletion_timestamp=0.0))

    s.repack_evictor = evictor
    return s, t, clock


def _nodes_used(t: Truth) -> int:
    return len(set(t.bound.values()))


def test_repack_consolidates_fragmented_cluster():
    """Quality pin: churn strands a straggler on its own node (the
    post-churn shape admission-time consolidation never revisits); the
    cadence re-pack drains it and the next cycle's consolidation
    objective packs it onto the occupied node — nodes-used strictly
    decreases, and no bind RPC ever re-binds a still-bound key."""
    s, t, clock = _repack_sched(interval=5.0)
    # the fragmented state arrives via the informer: 5 pods bound on
    # n0, one straggler alone on n1 (assigned pods enter the cache
    # whoever bound them; watch-confirmed, so they are movable)
    for i in range(5):
        p = make_pod(f"c{i}", cpu_milli=1000, node_name="n0")
        t.register(p)
        t.bound[p.key()] = "n0"
        s.on_pod_add(p)
    straggler = make_pod("straggler", cpu_milli=1000, node_name="n1")
    t.register(straggler)
    t.bound[straggler.key()] = "n1"
    s.on_pod_add(straggler)
    before = _nodes_used(t)
    assert before == 2
    # cadence: first observation arms, a full interval later it drains
    assert s.maybe_repack() == 0
    clock.advance(6.0)
    drained = s.maybe_repack()
    assert drained == 1
    assert s.metrics.scenario_repacks.value() == 1
    assert t.bound.get("default/straggler") is None  # evicted at truth
    res = s.schedule_cycle()
    assert res.scheduled == 1
    _confirm(s, res)
    assert _nodes_used(t) < before, dict(t.bound)
    assert t.double_bind_attempts == 0
    # the drained pod is bound again — repack never loses a pod
    assert sum(s.queue.pending_counts().values()) == 0


def test_repack_off_cadence_and_packless_are_noops():
    s, t, clock = _repack_sched(interval=0.0)
    assert s.maybe_repack() == 0  # interval 0 = disabled
    s2, t2, clock2 = _repack_sched(interval=5.0)
    assert s2.maybe_repack() == 0  # arms the cadence
    clock2.advance(1.0)
    assert s2.maybe_repack() == 0  # within the interval


def test_repack_skips_nodes_with_assumed_pods():
    """In-flight (assumed, not yet watch-confirmed) pods pin their
    node: draining a pod whose bind is still settling would race the
    confirmation."""
    s, t, clock = _repack_sched(interval=5.0)
    pods = [make_pod(f"a{i}", cpu_milli=1000) for i in range(3)]
    for p in pods:
        t.register(p)
        s.on_pod_add(p)
    res = s.schedule_cycle()
    assert res.scheduled == 3
    # NO confirmation relay: everything stays assumed
    assert s.maybe_repack() == 0
    clock.advance(6.0)
    assert s.maybe_repack() == 0
    assert s.metrics.scenario_repacks.value() == 0


# ---------------------------------------------------------------------------
# the composed fake-clock soak (seeds 1/2/3)
# ---------------------------------------------------------------------------


class MiniSoak:
    """The driver's day-in-the-life arc compressed to a fake clock:
    one scheduler, one truth, scripted traffic/chaos/cascade phases,
    auditor + sentinels armed throughout. Single-threaded, so every
    phase boundary is exact (no in-flight cycles straddling it)."""

    def __init__(self, seed: int) -> None:
        self.rng = random.Random(seed)
        self.clock = Clock()
        self.injector = FaultInjector(seed=seed)
        self.truth = Truth()
        self.sched, _ = _sched(
            self.truth, clock=self.clock, enable_preemption=True,
            fault_injector=self.injector,
            scenario=ScenarioConfig(pack="consolidation",
                                    repack_interval_s=0.0,
                                    repack_max_pods=8))
        for i in range(2):
            self.sched.on_node_add(
                make_node(f"n{i}", cpu_milli=8000, pods=64))
        self.auditor = self.sched.attach_auditor(StateAuditor())
        self.victims: list = []
        self.sched.victim_deleter = self.victims.append
        self.seq = 0
        self.created = 0

    def spawn(self, priority: int = 0, group: str = "",
              min_available: int = 0) -> None:
        self.seq += 1
        p = make_pod(f"m{self.seq}", cpu_milli=1000, priority=priority,
                     pod_group=group,
                     pod_group_min_available=min_available)
        self.truth.register(p)
        self.sched.on_pod_add(p)
        self.created += 1

    def cycle(self) -> None:
        res = self.sched.schedule_cycle()
        # victim deletes relay AFTER the cycle (watch-stream order)
        for v in self.victims:
            self.truth.delete(v.key())
            self.sched.on_pod_delete(v)
        self.victims.clear()
        for key, node in dict(res.assignments).items():
            cached = self.sched.cache.pod(key)
            if cached is not None:
                self.sched.on_pod_update(
                    cached, dataclasses.replace(cached, node_name=node))

    def drain(self) -> None:
        """True quiescence: advance past every backoff until the queue
        is empty (the driver's quiesce())."""
        for _ in range(40):
            if sum(self.sched.queue.pending_counts().values()) == 0:
                return
            self.clock.advance(10.0)
            self.sched.queue.move_all_to_active()
            self.cycle()

    def audit(self) -> None:
        self.auditor.audit(self.sched,
                           truth_pods=self.truth.list_pods())


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fake_clock_soak_sequence(seed):
    """The full arc in seconds: trace-driven mixed traffic (priority
    tiers + a gang), an rpc-error chaos window, a preemption cascade
    over capacity — each followed by a clean phase where the
    clean-zero counters must not move and the sentinel boundary sample
    joins the growth series. End of life: every surviving pod bound,
    zero double binds, zero auditor violations, flat sentinels."""
    m = MiniSoak(seed)
    sent = SoakSentinels(
        sched=m.sched, registry=m.sched.metrics.registry,
        fresh_gauges=["scheduler_pending_pods"],
        rss_reader=lambda: 0)  # deterministic: structures only
    counters = standard_counters(
        m.sched, auditor=m.auditor,
        extra={"double_binds":
               lambda: float(m.truth.double_bind_attempts),
               "preempted":
               lambda: float(m.sched.metrics.preemption_victims.value())})
    engine = SoakEngine(
        phases=[], sentinels=sent, counters=counters,
        clean_zero=("slo_burns", "auditor_violations", "double_binds",
                    "retraces", "fenced_binds", "preempted"),
        clock=m.clock, sleep=m.clock.advance, step_s=1.0,
        sample_every_s=4.0)

    def traffic_tick(_elapsed):
        m.spawn(priority=self_prio(m.rng))
        m.cycle()

    def self_prio(rng):
        r = rng.random()
        return 0 if r < 0.6 else (50 if r < 0.9 else 100)

    def gang_tick(elapsed):
        if int(elapsed) == 2 and not getattr(gang_tick, "done", False):
            gang_tick.done = True
            m.spawn(group="mgang", min_available=2)
            m.spawn(group="mgang", min_available=2)
        traffic_tick(elapsed)

    def clean_tick(_elapsed):
        m.cycle()

    def chaos_arm():
        m.injector.arm("rpc:bind", "rpc_error", rate=0.3)

    def chaos_disarm():
        m.injector.rules.clear()
        m.drain()

    def cascade_tick(_elapsed):
        m.spawn(priority=100)
        m.cycle()

    def clean_probe():
        m.audit()
        return {"resident": len(m.truth.bound),
                "queue": sum(m.sched.queue.pending_counts().values())}

    engine.phases = [
        SoakPhase("traffic", 8.0, "traffic", tick=gang_tick,
                  disarm=m.drain),
        SoakPhase("clean-1", 4.0, "clean", tick=clean_tick,
                  probe=clean_probe),
        SoakPhase("rpc-chaos", 6.0, "chaos", arm=chaos_arm,
                  tick=traffic_tick, disarm=chaos_disarm),
        SoakPhase("clean-2", 4.0, "clean", tick=clean_tick,
                  probe=clean_probe),
        SoakPhase("cascade", 4.0, "chaos", tick=cascade_tick,
                  disarm=m.drain),
        SoakPhase("clean-3", 4.0, "clean", tick=clean_tick,
                  probe=clean_probe),
    ]
    record = engine.run()

    assert m.truth.double_bind_attempts == 0
    assert m.auditor.violations_total == 0 and m.auditor.audits >= 3
    for rep in record["phases"]:
        assert rep["ok"], rep["violations"]
    assert record["verdict"]["sentinels_flat"], \
        record["verdict"]["leaking"]
    assert record["verdict"]["ok"]
    # end of life: everything surviving is bound, nothing parked
    assert sum(m.sched.queue.pending_counts().values()) == 0
    assert not m.sched.cache.assumed_keys()
    assert len(m.truth.bound) == len(m.truth.spec)
    # capacity arithmetic: 16 slots, >16 ever created — the cascade
    # demonstrably preempted (hub-deleter mode: victims deleted)
    if m.created > 16:
        assert m.sched.metrics.preemption_victims.value() > 0
