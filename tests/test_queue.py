"""Unit tests for the scheduling queue — the analog of
``pkg/scheduler/internal/queue/scheduling_queue_test.go``."""

from kubernetes_tpu.api.types import Affinity, LabelSelector, PodAffinityTerm
from kubernetes_tpu.queue import (
    INITIAL_BACKOFF_S,
    MAX_BACKOFF_S,
    UNSCHEDULABLEQ_FLUSH_S,
    PodBackoffMap,
    SchedulingQueue,
)
from kubernetes_tpu.testing import make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_pop_order_priority_then_fifo():
    q = SchedulingQueue(clock=FakeClock())
    low1 = make_pod("low1", priority=0)
    high = make_pod("high", priority=10)
    low2 = make_pod("low2", priority=0)
    for p in (low1, high, low2):
        q.add(p)
    assert [p.name for p in q.pop_batch()] == ["high", "low1", "low2"]
    assert q.scheduling_cycle == 1


def test_unschedulable_goes_to_backoff_after_move_request():
    clk = FakeClock()
    q = SchedulingQueue(clock=clk)
    a, b = make_pod("a"), make_pod("b")
    q.add(a)
    q.add(b)
    batch = q.pop_batch()
    cycle = q.scheduling_cycle

    # no move request since the pod's cycle -> unschedulableQ
    q.record_failure(batch[0])
    q.add_unschedulable_if_not_present(batch[0], cycle)
    assert q.pending_counts()["unschedulable"] == 1

    # a move request DURING scheduling -> pod must go to backoffQ instead
    # (the lost-wakeup defense, scheduling_queue.go:127-134). Pod a is also
    # swept to backoffQ by the move request itself (still backing off).
    q.move_all_to_active()
    q.record_failure(batch[1])
    q.add_unschedulable_if_not_present(batch[1], cycle)
    counts = q.pending_counts()
    assert counts == {"active": 0, "backoff": 2, "unschedulable": 0}


def test_backoff_expiry_exponential():
    clk = FakeClock()
    bm = PodBackoffMap()
    bm.backoff_pod("k", clk())
    assert bm.backoff_time("k") == INITIAL_BACKOFF_S
    bm.backoff_pod("k", clk())
    assert bm.backoff_time("k") == 2 * INITIAL_BACKOFF_S
    for _ in range(10):
        bm.backoff_pod("k", clk())
    assert bm.backoff_time("k") == MAX_BACKOFF_S


def test_flush_backoff_completed():
    clk = FakeClock()
    q = SchedulingQueue(clock=clk)
    p = make_pod("p")
    q.add(p)
    (popped,) = q.pop_batch()
    q.record_failure(popped)
    q.move_all_to_active()  # force backoff path
    q.add_unschedulable_if_not_present(popped, q.scheduling_cycle)
    assert q.pending_counts()["backoff"] == 1
    q.tick()
    assert q.pending_counts()["backoff"] == 1  # 1 s not elapsed
    clk.advance(1.1)
    q.tick()
    assert q.pending_counts() == {"active": 1, "backoff": 0, "unschedulable": 0}


def test_unschedulable_leftover_flush_after_60s():
    clk = FakeClock()
    q = SchedulingQueue(clock=clk)
    p = make_pod("p")
    q.add(p)
    (popped,) = q.pop_batch()
    q.add_unschedulable_if_not_present(popped, q.scheduling_cycle)
    clk.advance(UNSCHEDULABLEQ_FLUSH_S - 1)
    q.tick()
    assert q.pending_counts()["unschedulable"] == 1
    clk.advance(2)
    q.tick()
    assert q.pending_counts()["unschedulable"] == 0
    assert q.pending_counts()["active"] == 1


def test_assigned_pod_added_moves_affinity_waiters():
    clk = FakeClock()
    q = SchedulingQueue(clock=clk)
    waiter = make_pod(
        "waiter",
        affinity=Affinity(
            pod_affinity_required=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "db"}),
                    topology_key="kubernetes.io/hostname",
                ),
            )
        ),
    )
    other = make_pod("other")
    q.add(waiter)
    q.add(other)
    batch = q.pop_batch()
    for p in batch:
        q.add_unschedulable_if_not_present(p, q.scheduling_cycle)
    assert q.pending_counts()["unschedulable"] == 2

    # a non-matching assigned pod moves nothing
    q.assigned_pod_added(make_pod("x", labels={"app": "web"}, node_name="n1"))
    assert q.pending_counts()["unschedulable"] == 2
    # a matching one moves only the waiter
    q.assigned_pod_added(make_pod("db-1", labels={"app": "db"}, node_name="n1"))
    counts = q.pending_counts()
    assert counts["unschedulable"] == 1 and counts["active"] == 1


def test_update_unschedulable_moves_to_active():
    q = SchedulingQueue(clock=FakeClock())
    p = make_pod("p")
    q.add(p)
    (popped,) = q.pop_batch()
    q.add_unschedulable_if_not_present(popped, q.scheduling_cycle)
    newp = make_pod("p", node_selector={"disk": "ssd"})
    newp.queued_at = popped.queued_at
    q.update(popped.key(), newp)
    assert q.pending_counts()["active"] == 1


def test_delete_removes_everywhere_and_clears_backoff():
    q = SchedulingQueue(clock=FakeClock())
    p = make_pod("p")
    q.add(p)
    q.record_failure(p)
    q.delete(p.key())
    assert len(q) == 0
    assert q.backoff_map.backoff_time(p.key()) == 0.0


def test_nominated_pod_map():
    q = SchedulingQueue(clock=FakeClock())
    p = make_pod("p", priority=5)
    p.nominated_node_name = "node-1"
    q.add(p)
    assert [x.name for x in q.nominated.pods_for_node("node-1")] == ["p"]
    q.delete(p.key())
    assert q.nominated.pods_for_node("node-1") == []


def test_pop_batch_respects_max():
    q = SchedulingQueue(clock=FakeClock())
    for i in range(5):
        q.add(make_pod(f"p{i}"))
    first = q.pop_batch(2)
    assert len(first) == 2
    assert q.scheduling_cycle == 1
    rest = q.pop_batch()
    assert len(rest) == 3
    assert q.scheduling_cycle == 2
