"""Unit tests for the scheduler cache — assumed-pod state machine
(``cache/interface.go:36-47``) and incremental snapshot parity
(``cache.go:211`` UpdateNodeInfoSnapshot)."""

import numpy as np
import pytest

from kubernetes_tpu.cache import CacheError, SchedulerCache
from kubernetes_tpu.snapshot import RES_CPU, SnapshotPacker
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _table_row(cache, node_name):
    t = cache.snapshot()
    i = cache.node_order().index(node_name)
    return t, i


def test_assume_finish_add_lifecycle():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    p = make_pod("p", cpu_milli=500)
    c.assume_pod(p, "n1")
    assert c.is_assumed(p.key())
    t, i = _table_row(c, "n1")
    assert t.requested[i, RES_CPU] == 500

    c.finish_binding(p.key())
    # watch confirms
    bound = make_pod("p", cpu_milli=500, node_name="n1")
    c.add_pod(bound)
    assert not c.is_assumed(p.key())
    t, i = _table_row(c, "n1")
    assert t.requested[i, RES_CPU] == 500


def test_assume_expiry_frees_capacity():
    clk = FakeClock()
    c = SchedulerCache(clock=clk, ttl_s=30)
    c.add_node(make_node("n1"))
    p = make_pod("p", cpu_milli=500)
    c.assume_pod(p, "n1")
    c.finish_binding(p.key())
    clk.advance(31)
    expired = c.cleanup_expired()
    assert expired == [p.key()]
    t, i = _table_row(c, "n1")
    assert t.requested[i, RES_CPU] == 0


def test_assume_without_finish_never_expires():
    clk = FakeClock()
    c = SchedulerCache(clock=clk, ttl_s=30)
    c.add_node(make_node("n1"))
    c.assume_pod(make_pod("p", cpu_milli=500), "n1")
    clk.advance(1000)
    assert c.cleanup_expired() == []
    assert c.is_assumed("default/p")


def test_forget_pod():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    p = make_pod("p", cpu_milli=500)
    c.assume_pod(p, "n1")
    c.forget_pod(p.key())
    t, i = _table_row(c, "n1")
    assert t.requested[i, RES_CPU] == 0
    with pytest.raises(CacheError):
        c.forget_pod(p.key())


def test_double_assume_raises():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    p = make_pod("p")
    c.assume_pod(p, "n1")
    with pytest.raises(CacheError):
        c.assume_pod(p, "n1")


def test_add_pod_corrects_wrong_assumption():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    c.add_node(make_node("n2"))
    p = make_pod("p", cpu_milli=300)
    c.assume_pod(p, "n1")
    # API says it actually landed on n2
    c.add_pod(make_pod("p", cpu_milli=300, node_name="n2"))
    t = c.snapshot()
    order = c.node_order()
    assert t.requested[order.index("n1"), RES_CPU] == 0
    assert t.requested[order.index("n2"), RES_CPU] == 300


def _assert_tables_equal(a, b):
    for f in (
        "allocatable requested nonzero_req pair_mh taint_hard_mh port_any_mh "
        "owner_counts matcher_counts anti_counts sym_counts aff_pod_count"
    ).split():
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)


def test_incremental_snapshot_matches_full_repack():
    """After arbitrary mutations, the dirty-row incremental snapshot must be
    identical to a from-scratch pack of the same state."""
    c = SchedulerCache(clock=FakeClock())
    for i in range(6):
        c.add_node(make_node(f"n{i}", zone=f"z{i % 2}"))
    c.snapshot()  # establish the cached table

    # mutations: pods land, one leaves, one node updates
    for i in range(8):
        c.add_pod(make_pod(f"p{i}", cpu_milli=100 * (i + 1), node_name=f"n{i % 3}",
                           labels={"app": f"a{i % 2}"}))
    c.remove_pod("default/p3")
    c.update_node(make_node("n4", cpu_milli=64000, zone="z0"))
    inc = c.snapshot()

    # fresh cache, same end state
    c2 = SchedulerCache(packer=SnapshotPacker(), clock=FakeClock())
    for i in range(6):
        if i == 4:
            c2.add_node(make_node("n4", cpu_milli=64000, zone="z0"))
        else:
            c2.add_node(make_node(f"n{i}", zone=f"z{i % 2}"))
    for i in range(8):
        if i == 3:
            continue
        c2.add_pod(make_pod(f"p{i}", cpu_milli=100 * (i + 1), node_name=f"n{i % 3}",
                            labels={"app": f"a{i % 2}"}))
    full = c2.snapshot()

    # row orders agree (same insertion order)
    assert c.node_order() == c2.node_order()
    _assert_tables_equal(inc, full)


def test_incremental_snapshot_after_universe_growth_falls_back():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    c.snapshot()
    # a pod with a brand-new label selector universe entry forces widths to
    # change -> full repack path (must not crash or corrupt)
    c.add_pod(make_pod("p", node_name="n1", node_selector={"brand-new-key": "v"}))
    t = c.snapshot()
    assert t.n == 1


def test_node_remove_drops_row():
    c = SchedulerCache(clock=FakeClock())
    c.add_node(make_node("n1"))
    c.add_node(make_node("n2"))
    c.snapshot()
    c.remove_node("n1")
    t = c.snapshot()
    assert t.n == 1
    assert c.node_order() == ["n2"]
