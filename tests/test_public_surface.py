"""Public-surface conformance: the entry points README.md and PARITY.md
promise must exist with their documented shapes. This is the contract a
reference user migrates against — a rename or signature break here is an
API break even if every behavior test still passes."""

import inspect


def test_package_root():
    import kubernetes_tpu

    assert kubernetes_tpu.__version__
    doc = kubernetes_tpu.version_info()
    assert doc["gitVersion"].startswith("v")


def test_driver_surface():
    from kubernetes_tpu.scheduler import CycleResult, RecordingBinder, Scheduler

    sig = inspect.signature(Scheduler.__init__)
    for kw in ("binder", "weights", "solver", "per_node_cap", "clock",
               "enable_preemption", "pdb_lister", "framework", "pred_mask",
               "extenders", "percentage_of_nodes_to_score", "volume_binder",
               "scheduler_name"):
        assert kw in sig.parameters, kw
    for method in ("on_pod_add", "on_pod_update", "on_pod_delete",
                   "on_node_add", "on_node_update", "on_node_delete",
                   "schedule_cycle", "set_volume_state", "from_config",
                   "responsible_for"):
        assert callable(getattr(Scheduler, method)), method
    assert {f.name for f in
            __import__("dataclasses").fields(CycleResult)} >= {
        "scheduled", "unschedulable", "assignments", "failure_reasons",
        "fit_errors", "preempted", "nominations", "elapsed_s"}
    RecordingBinder().bind  # the test binder contract


def test_solver_surface():
    from kubernetes_tpu.ops.assign import batch_assign, greedy_assign
    from kubernetes_tpu.ops.predicates import (
        decode_reasons,
        pods_have_no_ports,
        run_predicates,
        static_predicate_reasons,
    )
    from kubernetes_tpu.ops.priorities import (
        EMPTY_CONSTANTS,
        empty_priorities,
        register_priority,
        run_priorities,
        solver_gates,
    )

    for fn, kws in (
        (batch_assign, ("per_node_cap", "topo", "vol", "use_sinkhorn",
                        "skip_priorities", "no_ports", "no_pod_affinity",
                        "no_spread")),
        (greedy_assign, ("topo", "vol", "skip_priorities", "no_ports")),
        (run_predicates, ("topo", "vol", "hoisted", "no_ports",
                          "no_pod_affinity", "no_spread")),
        (run_priorities, ("weights", "topo", "skip")),
    ):
        sig = inspect.signature(fn)
        for kw in kws:
            assert kw in sig.parameters, (fn.__name__, kw)
    assert set(EMPTY_CONSTANTS) and callable(decode_reasons)
    assert callable(empty_priorities) and callable(solver_gates)
    assert callable(register_priority) and callable(static_predicate_reasons)
    assert callable(pods_have_no_ports)


def test_snapshot_and_device_surface():
    from kubernetes_tpu.ops.arrays import (
        nodes_to_device,
        pods_to_device,
        selectors_to_device,
        topology_to_device,
        volumes_to_device,
    )
    from kubernetes_tpu.snapshot import SnapshotPacker

    pk = SnapshotPacker()
    for method in ("intern_pod", "pack_nodes", "pack_pods",
                   "pack_selector_tables", "pack_topology_tables",
                   "pack_volume_tables", "set_volume_state"):
        assert callable(getattr(pk, method)), method
    assert "pad_to" in inspect.signature(pods_to_device).parameters
    for f in (nodes_to_device, selectors_to_device, topology_to_device,
              volumes_to_device):
        assert callable(f)


def test_control_plane_surface():
    from kubernetes_tpu.restapi import AuditLog, RestServer
    from kubernetes_tpu.sim import (
        CronJob,
        DaemonSet,
        Deployment,
        HollowCluster,
        HorizontalPodAutoscaler,
        Job,
        Reflector,
        ReplicaSet,
        StatefulSet,
    )

    hub_methods = ("add_node", "remove_node", "create_pod", "delete_pod",
                   "confirm_binding", "watch", "compact", "step", "settle",
                   "check_consistency", "add_service", "add_pdb",
                   "add_daemonset", "add_statefulset", "add_cronjob",
                   "add_hpa", "add_deployment", "add_replicaset", "add_job",
                   "kill_kubelet", "heal_kubelet", "churn")
    for m in hub_methods:
        assert callable(getattr(HollowCluster, m)), m
    assert "audit" in inspect.signature(RestServer.__init__).parameters
    assert AuditLog("Metadata")
    for cls in (Deployment, ReplicaSet, Job, DaemonSet, StatefulSet,
                CronJob, HorizontalPodAutoscaler, Reflector):
        assert cls is not None


def test_tooling_surface():
    from kubernetes_tpu.cli import main as cli_main
    from kubernetes_tpu.kubectl import main as ktpu_main
    import __graft_entry__ as ge

    assert callable(cli_main) and callable(ktpu_main)
    assert callable(ge.entry) and callable(ge.dryrun_multichip)


def test_round5_controller_surface():
    """The round-5 controllers' documented entry points (PARITY.md rows:
    certificates, bootstrap tokens, cloud LB/routes, RBAC aggregation,
    pod GC, volume protection, history/rollback)."""
    from kubernetes_tpu.auth import (
        ClusterRole,
        ClusterRoleBinding,
        PolicyRule,
        RBACAuthorizer,
        aggregate_cluster_roles,
    )
    from kubernetes_tpu.bootstrap import (
        bootstrap_signer,
        token_cleaner,
        verify_cluster_info,
    )
    from kubernetes_tpu.certificates import (
        CertificateController,
        RootCACertPublisher,
        is_node_client_csr,
        node_bootstrap_csr,
    )
    from kubernetes_tpu.cloud import (
        CloudProvider,
        RouteController,
        ServiceLBController,
    )
    from kubernetes_tpu.sim import ControllerRevision, HollowCluster

    for method in ("create_csr", "cert_user", "credential_user",
                   "bootstrap_token_user", "delete_pvc", "delete_pv",
                   "reconcile_pod_gc", "reconcile_ttl_after_finished",
                   "reconcile_volume_protection", "rollback",
                   "add_replication_controller", "mark_terminating",
                   "put_configmap", "record_controller_event"):
        assert callable(getattr(HollowCluster, method)), method
    for method in ("ensure_load_balancer", "ensure_load_balancer_deleted",
                   "list_load_balancers", "list_routes", "create_route",
                   "delete_route"):
        assert callable(getattr(CloudProvider, method)), method
    assert callable(aggregate_cluster_roles)
    assert callable(verify_cluster_info)
    assert ControllerRevision and PolicyRule and ClusterRoleBinding
    assert (CertificateController and RootCACertPublisher
            and ServiceLBController and RouteController
            and RBACAuthorizer and ClusterRole
            and is_node_client_csr and node_bootstrap_csr
            and bootstrap_signer and token_cleaner)


def test_lint_surface():
    """The graftlint contract README.md and docs/lint.md promise: the
    programmatic API, the rule registry, and the kernel-test helper."""
    from kubernetes_tpu.lint import (
        Finding,
        lint_source,
        load_baseline,
        run_lint,
        subtract_baseline,
        write_baseline,
    )
    from kubernetes_tpu.lint.engine import RULE_IDS
    from kubernetes_tpu.lint.rules import RULE_SUMMARIES
    from kubernetes_tpu.testing import lint_clean

    assert RULE_IDS == ("R0", "R1", "R2", "R3", "R4", "R5", "R6", "R7",
                        "R8", "R9", "R10")
    assert set(RULE_SUMMARIES) == set(RULE_IDS)
    sig = inspect.signature(run_lint)
    for kw in ("root", "select", "respect_suppressions"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(lint_source)
    for kw in ("filename", "select", "jit_all"):
        assert kw in sig.parameters, kw
    sig = inspect.signature(lint_clean)
    for kw in ("rules", "filename", "jit_all"):
        assert kw in sig.parameters, kw
    f = Finding("a.py", 1, 0, "R1", "m", "x = 1")
    assert f.fingerprint() and f.as_dict()["rule"] == "R1"
    assert callable(load_baseline) and callable(write_baseline)
    assert callable(subtract_baseline)
