"""Sinkhorn OT solver + gang scheduling tests (BASELINE config 4:
gang/coscheduling via batched Sinkhorn assignment)."""

import numpy as np
import jax.numpy as jnp

from kubernetes_tpu.ops.sinkhorn import sinkhorn_plan
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    t = 0.0

    def __call__(self):
        return self.t


def test_plan_respects_marginals():
    rng = np.random.RandomState(0)
    P, N = 64, 16
    score = rng.uniform(0, 10, (P, N)).astype(np.float32)
    mask = rng.uniform(size=(P, N)) > 0.2
    mask[5] = False  # one fully infeasible pod
    cap = rng.randint(1, 8, N).astype(np.float32)
    plan = np.asarray(sinkhorn_plan(jnp.asarray(score), jnp.asarray(mask),
                                    jnp.asarray(cap), iters=60, pallas=False))
    rows = plan.sum(1)
    cols = plan.sum(0)
    assert np.all(rows <= 1.0 + 1e-3)
    assert np.all(cols <= cap + 0.05 * cap + 1e-2)
    assert rows[5] == 0.0  # infeasible pod ships nothing
    assert np.all(plan[~mask] == 0.0)


def test_pallas_interpret_matches_jnp():
    rng = np.random.RandomState(1)
    P, N = 32, 24
    score = rng.uniform(0, 10, (P, N)).astype(np.float32)
    mask = rng.uniform(size=(P, N)) > 0.3
    cap = rng.randint(1, 5, N).astype(np.float32)
    a = np.asarray(sinkhorn_plan(jnp.asarray(score), jnp.asarray(mask),
                                 jnp.asarray(cap), iters=20, pallas=False))
    b = np.asarray(sinkhorn_plan(jnp.asarray(score), jnp.asarray(mask),
                                 jnp.asarray(cap), iters=20, pallas=True,
                                 interpret=True))
    assert np.allclose(a, b, rtol=1e-4, atol=1e-5)


def test_sinkhorn_solver_schedules_contended_batch():
    s = Scheduler(solver="sinkhorn", clock=FakeClock(), enable_preemption=False)
    for i in range(8):
        s.on_node_add(make_node(f"n{i}", cpu_milli=2000))
    for i in range(16):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=1000))
    res = s.schedule_cycle()
    assert res.scheduled == 16
    counts = {}
    for n in res.assignments.values():
        counts[n] = counts.get(n, 0) + 1
    assert max(counts.values()) <= 2  # capacity respected, spread out


def test_gang_all_or_nothing():
    s = Scheduler(clock=FakeClock(), enable_preemption=False)
    for i in range(4):
        s.on_node_add(make_node(f"n{i}", cpu_milli=4000))
    # group A: all feasible -> schedules atomically
    for i in range(3):
        s.on_pod_add(make_pod(f"a{i}", cpu_milli=500, pod_group="A"))
    # group B: one member demands the impossible -> whole group holds back
    s.on_pod_add(make_pod("b0", cpu_milli=500, pod_group="B"))
    s.on_pod_add(make_pod("b1", cpu_milli=999999, pod_group="B"))
    # a singleton is unaffected
    s.on_pod_add(make_pod("solo", cpu_milli=500))
    res = s.schedule_cycle()
    assert res.scheduled == 4  # a0,a1,a2 + solo
    assert all(f"default/a{i}" in res.assignments for i in range(3))
    assert "default/solo" in res.assignments
    assert "default/b0" not in res.assignments
    assert res.failure_reasons["default/b0"] == ("GangIncomplete:B",)
    assert "PodFitsResources" in res.failure_reasons["default/b1"]
    # no partial capacity held for the failed gang
    assert not s.cache.is_assumed("default/b0")


def test_gang_schedules_when_whole_group_fits_later():
    clk = FakeClock()
    s = Scheduler(clock=clk, enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("g0", cpu_milli=600, pod_group="G"))
    s.on_pod_add(make_pod("g1", cpu_milli=600, pod_group="G"))
    res = s.schedule_cycle()
    assert res.scheduled == 0  # only one fits -> rollback
    # capacity grows: both fit now
    s.on_node_add(make_node("n1", cpu_milli=1000))
    clk.t += 30
    s.queue.move_all_to_active()
    res2 = s.schedule_cycle()
    assert res2.scheduled == 2


def test_gang_rollback_leaves_no_phantom_state():
    """Regression (review): rolled-back gang members must not appear in the
    usage fed to the failure-reason pass, must not trigger preemption
    nominations, and must not hold capacity."""
    clk = FakeClock()
    s = Scheduler(clock=clk)  # preemption ON
    s.on_node_add(make_node("n0", cpu_milli=1000))
    s.on_pod_add(make_pod("g0", cpu_milli=600, pod_group="G"))
    s.on_pod_add(make_pod("g1", cpu_milli=600, pod_group="G"))
    res = s.schedule_cycle()
    assert res.scheduled == 0
    assert res.nominations == {} and res.preempted == 0
    assert res.failure_reasons["default/g0"][0].startswith("GangIncomplete")
    # full capacity must be available to the next arrival
    s.on_pod_add(make_pod("big", cpu_milli=1000))
    res2 = s.schedule_cycle()
    assert res2.assignments.get("default/big") == "n0"


def test_gang_min_available_blocks_fragment():
    """Regression (review): a group fragment smaller than minMember must
    not bind, even though every present member fits."""
    clk = FakeClock()
    s = Scheduler(clock=clk, enable_preemption=False)
    s.on_node_add(make_node("n0", cpu_milli=4000))
    s.on_pod_add(make_pod("g0", cpu_milli=100, pod_group="G",
                          pod_group_min_available=2))
    res = s.schedule_cycle()
    assert res.scheduled == 0
    assert res.failure_reasons["default/g0"] == ("GangIncomplete:G",)
    # the missing member arrives; the fragment rejoins after the 60s
    # unschedulable resweep (new-pod creates don't wake unschedulables in
    # the reference either — scheduling_queue.go:368)
    clk.t += 70
    s.on_pod_add(make_pod("g1", cpu_milli=100, pod_group="G",
                          pod_group_min_available=2))
    res2 = s.schedule_cycle()
    assert res2.scheduled == 2


def test_pallas_handles_unpadded_shapes():
    """Regression (review): non-block-multiple shapes must not read
    uninitialized memory (grid floor division)."""
    rng = np.random.RandomState(2)
    P, N = 303, 41
    score = rng.uniform(0, 10, (P, N)).astype(np.float32)
    mask = rng.uniform(size=(P, N)) > 0.3
    cap = rng.randint(1, 5, N).astype(np.float32)
    a = np.asarray(sinkhorn_plan(jnp.asarray(score), jnp.asarray(mask),
                                 jnp.asarray(cap), iters=15, pallas=False))
    b = np.asarray(sinkhorn_plan(jnp.asarray(score), jnp.asarray(mask),
                                 jnp.asarray(cap), iters=15, pallas=True,
                                 interpret=True))
    assert np.allclose(a, b, rtol=1e-4, atol=1e-5)


def test_block_shapes_fixed_point():
    """The compile probe re-derives the tiling from the padded shape via
    `_scale_pallas`; `_block_shapes` must therefore be a fixed point on
    its own output or the probe validates a different kernel config than
    the real call runs (round-3 review finding)."""
    from kubernetes_tpu.ops.sinkhorn import VMEM_SLAB_BUDGET, _block_shapes

    shapes = [(8192, 5120), (64, 16), (303, 41), (2048, 1024), (2300, 4000),
              (8192, 128), (1, 1), (4096, 50176), (100000, 128), (513, 4097)]
    for P0, N0 in shapes:
        bp, bn, P, N = _block_shapes(P0, N0)
        assert bp % 128 == 0 and bn % 128 == 0
        assert P % bp == 0 and N % bn == 0 and P >= P0 and N >= N0
        # slabs within budget whenever shrinkage could still act
        if bp > 128:
            assert bp * N * 4 <= VMEM_SLAB_BUDGET
        if bn > 128:
            assert P * bn * 4 <= VMEM_SLAB_BUDGET
        # fixed point: re-deriving from the padded shape with the chosen
        # blocks as caps reproduces the identical config
        assert _block_shapes(P, N, bp, bn) == (bp, bn, P, N)


def tied_preferences_workload(n_hot=4, n_cold=20, n_steep=16,
                              n_flat=80):
    """The ONE construction both the CPU and TPU quality tests pin
    (round-4 "prove it wins or demote it" verdict): steep pods (hot=10,
    cold=0) tie with flat pods (hot=10, cold=9) on scarce hot nodes,
    flat population listed FIRST so ordering-based tie-breaks oppose the
    steep pods. Returns (nodes, pods, points_fn) where points_fn scores
    an assignment row-vector on the workload's quality axis."""
    from kubernetes_tpu.api.types import (
        Affinity,
        Node,
        NodeSelectorTerm,
        Pod,
        PreferredSchedulingTerm,
        Requirement,
        Resources,
    )

    ZONE = "failure-domain.beta.kubernetes.io/zone"

    def node(name, zone):
        return Node(name=name,
                    allocatable=Resources(cpu_milli=4000,
                                          memory=32 * 2**30, pods=110),
                    labels={"kubernetes.io/hostname": name, ZONE: zone})

    def prefer(*weight_zone):
        return Affinity(node_preferred=tuple(
            PreferredSchedulingTerm(
                weight=w,
                preference=NodeSelectorTerm((Requirement(ZONE, "In", (z,)),)))
            for w, z in weight_zone))

    nodes = [node(f"hot{i}", "hot") for i in range(n_hot)] + [
        node(f"cold{i}", "cold") for i in range(n_cold)]
    pods = [Pod(name=f"flat{i}",
                requests=Resources(cpu_milli=900, memory=2**30),
                affinity=prefer((10, "hot"), (9, "cold")))
            for i in range(n_flat)]
    pods += [Pod(name=f"steep{i}",
                 requests=Resources(cpu_milli=900, memory=2**30),
                 affinity=prefer((10, "hot")))
             for i in range(n_steep)]

    def points(assigned):
        total = 0
        for i, p in enumerate(pods):
            if assigned[i] < 0:
                continue
            on_hot = int(assigned[i]) < n_hot
            total += (10 if on_hot else 0) if p.name.startswith("steep") \
                else (10 if on_hot else 9)
        return total

    return nodes, pods, points


def run_tied_preferences_comparison(**sizes):
    """Solve the tied-preferences workload with argmax and with the OT
    plan; returns {False: points, True: points} after asserting both
    placements are full. Shared by the CPU test here and the compiled
    TPU test (tests_tpu/test_solver_compiled.py)."""
    from kubernetes_tpu.ops.arrays import (
        nodes_to_device,
        pods_to_device,
        selectors_to_device,
    )
    from kubernetes_tpu.ops.assign import batch_assign
    from kubernetes_tpu.snapshot import SnapshotPacker

    nodes, pods, points = tied_preferences_workload(**sizes)
    pk = SnapshotPacker()
    for p in pods:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, []))
    dp = pods_to_device(pk.pack_pods(pods))
    ds = selectors_to_device(pk.pack_selector_tables())
    results = {}
    for flag in (False, True):
        # auto_sinkhorn OFF: this comparison characterizes pure argmax
        # vs the plan (the r5 auto-router would route the False arm to
        # the plan too — that equality is pinned by its own test)
        assigned, _, _ = batch_assign(dp, dn, ds, per_node_cap=2,
                                      use_sinkhorn=flag,
                                      auto_sinkhorn=False)
        a = np.asarray(assigned)[:len(pods)]
        assert int((a >= 0).sum()) == len(pods)
        results[flag] = points(a)
    return results


def test_plan_beats_argmax_on_tied_preferences():
    """Argmax admission sees identical bids on the hot nodes and hands
    every hot slot to the (first-listed) flat pods; the transport plan
    prices hot-column contention and routes flat mass to the plentiful
    near-equal cold columns — strictly better placement quality."""
    results = run_tied_preferences_comparison()
    assert results[True] > results[False], results


def test_auto_routing_fires_on_tied_contention_by_default():
    """VERDICT r4 item 5: the tied-preferences win must materialize
    under DEFAULT config — no solver flag. The auto-router detects the
    tie-contention cohort (pre-window, so queued tail populations
    count) and routes the batch to the transport plan: default ==
    forced-plan quality, strictly above the argmax-only path."""
    from kubernetes_tpu.ops.arrays import (
        nodes_to_device,
        pods_to_device,
        selectors_to_device,
    )
    from kubernetes_tpu.ops.assign import batch_assign
    from kubernetes_tpu.snapshot import SnapshotPacker

    nodes, pods, points = tied_preferences_workload()
    pk = SnapshotPacker()
    for p in pods:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, []))
    dp = pods_to_device(pk.pack_pods(pods))
    ds = selectors_to_device(pk.pack_selector_tables())
    res = {}
    for label, kw in (("default", {}),
                      ("argmax_only", {"auto_sinkhorn": False}),
                      ("forced_plan", {"use_sinkhorn": True})):
        a, _, _ = batch_assign(dp, dn, ds, per_node_cap=2, **kw)
        res[label] = points(np.asarray(a)[:len(pods)])
    assert res["default"] == res["forced_plan"], res
    assert res["default"] > res["argmax_only"], res


def test_auto_routing_stays_on_argmax_for_plain_workloads():
    """The router must NOT fire without the full win signature: a
    uniform batch (everything ties everywhere -> no runner-up
    asymmetry) and a margin-ordered batch (unique bests -> no tie
    cohort) must produce placements IDENTICAL to the forced-argmax
    path."""
    from bench import build_variant
    from kubernetes_tpu.ops.assign import batch_assign

    # uniform: the headline base shape in miniature
    w = build_variant("base", 40, 20, 128)
    dp, dv = w.device_batch(w.pending[:128], 128)
    a_auto, u_auto, r_auto = batch_assign(dp, w.dn, w.ds, vol=dv,
                                          per_node_cap=2)
    a_arg, u_arg, r_arg = batch_assign(dp, w.dn, w.ds, vol=dv,
                                       per_node_cap=2,
                                       auto_sinkhorn=False)
    assert (np.asarray(a_auto) == np.asarray(a_arg)).all()
    assert int(r_auto) == int(r_arg)

    # margin-ordered: steep strictly outscores flat on the hot zone
    # (unique bests -> tc0 == 1 everywhere -> empty cohort)
    nodes, pods, _ = tied_preferences_workload()
    from dataclasses import replace as dc_replace

    from kubernetes_tpu.api.types import (
        Affinity,
        NodeSelectorTerm,
        PreferredSchedulingTerm,
        Requirement,
    )

    ZONE = "failure-domain.beta.kubernetes.io/zone"
    margin_pods = []
    for p in pods:
        if p.name.startswith("flat"):
            # flat now PREFERS cold outright: no tie with steep on hot
            aff = Affinity(node_preferred=(
                PreferredSchedulingTerm(
                    weight=10,
                    preference=NodeSelectorTerm(
                        (Requirement(ZONE, "In", ("cold",)),))),))
            margin_pods.append(dc_replace(p, affinity=aff))
        else:
            margin_pods.append(p)
    from kubernetes_tpu.ops.arrays import (
        nodes_to_device,
        pods_to_device,
        selectors_to_device,
    )
    from kubernetes_tpu.snapshot import SnapshotPacker

    pk = SnapshotPacker()
    for p in margin_pods:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, []))
    dp = pods_to_device(pk.pack_pods(margin_pods))
    ds = selectors_to_device(pk.pack_selector_tables())
    a_auto, _, _ = batch_assign(dp, dn, ds, per_node_cap=2)
    a_arg, _, _ = batch_assign(dp, dn, ds, per_node_cap=2,
                               auto_sinkhorn=False)
    assert (np.asarray(a_auto) == np.asarray(a_arg)).all()
