"""Interleaving fuzz for the threaded seams (VERDICT r3 item 10) — the
`-race` CI analog (hack/make-rules/test.sh:78) for this repo's actually-
threaded surfaces: REST handler threads + the gRPC SyncState stream +
the driver's hub.step(), all hammering one hub concurrently under
seed-derived schedules.

Each seed runs six concurrent actors with seeded jitter:
  - driver: hub.step() churn (controllers, scheduler, kubelets),
  - REST writer: pod/node create+delete (every response must be
    HTTP-valid and Status-shaped on error),
  - REST reader: list + watch polls,
  - gRPC service: SnapshotDelta pump -> remote scheduler cycle -> CAS
    binds back into the hub (the deployment loop of
    test_integration_grpc_hub, now racing the hub's own scheduler),
  - evictor: PDB-guarded Eviction posts against whatever is bound
    (only 201/404/429 are legal answers),
  - elector pair: two LeaderElectors CASing the same hub Lease
    (holder always one of them, rv monotonic; dual self-belief is
    legal lease semantics when the sim clock jumps — see the actor),
  - checkpointer: save_checkpoint under full churn — every snapshot
    must be internally consistent (restorable into a fresh hub whose
    oracle passes), proving the hub lock covers the whole state walk.

After the threads join, the settled state must satisfy the hub
consistency oracle AND the remote service's cache must equal hub truth
— any lost/duplicated/reordered event or unserialized mutation shows up
as a diff. Seed count: INTERLEAVE_FUZZ_SEEDS (campaigns recorded in
ROUNDLOG.md like the differential campaign)."""

import json
import os
import random
import threading

import pytest

grpc = pytest.importorskip("grpc")

from kubernetes_tpu.debugger import compare
from kubernetes_tpu.grpc_shim import (
    GrpcSchedulerClient,
    SnapshotDeltaBridge,
    TpuSchedulerService,
    serve_grpc,
)
from kubernetes_tpu.restapi import RestServer
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.sim import Deployment, FlakyBinder, HollowCluster
from kubernetes_tpu.testing import make_node, make_pod

N_SEEDS = int(os.environ.get("INTERLEAVE_FUZZ_SEEDS", 8))
STEPS = 25


def _http(port, method, path, body=None, ndjson=False):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request(method, path, json.dumps(body) if body is not None else None)
    r = conn.getresponse()
    data = r.read()
    conn.close()
    if not data:
        return r.status, None
    if ndjson and r.status == 200:
        # watch streams are newline-delimited frames; every frame must
        # itself be valid JSON (a torn frame = a race in the buffer path)
        return r.status, [json.loads(line) for line in data.splitlines()]
    return r.status, json.loads(data)


def _run_seed(seed: int) -> None:
    hub = HollowCluster(seed=seed,
                        scheduler_kw={"enable_preemption": False})
    for i in range(5):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000, pods=30))
    hub.add_deployment(Deployment("web", replicas=4))

    rest = RestServer(hub)
    port = rest.serve()

    remote = Scheduler(clock=hub.clock, enable_preemption=False,
                       binder=FlakyBinder(hub, 0.0, random.Random(seed)))
    svc = TpuSchedulerService(remote)
    server, gport = serve_grpc(remote, service=svc)
    client = GrpcSchedulerClient(f"127.0.0.1:{gport}")
    bridge = SnapshotDeltaBridge(hub, client, lock=hub.lock)

    errors = []
    stop = threading.Event()

    def guarded(fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — the fuzz verdict
                errors.append(f"{fn.__name__}: {e!r}")
                stop.set()
        return run

    def driver():
        rng = random.Random(seed * 31 + 1)
        for i in range(STEPS):
            if stop.is_set():
                return
            hub.scale_deployment("web", 2 + (i % 4))
            hub.step(dt=rng.choice([1.0, 5.0, 16.0]))
            if rng.random() < 0.3:
                stop.wait(rng.random() * 0.004)
        stop.set()

    def rest_writer():
        rng = random.Random(seed * 31 + 2)
        i = 0
        while not stop.is_set():
            i += 1
            pod = {"metadata": {"name": f"w{i}"},
                   "spec": {"containers": [{"name": "m", "resources": {
                       "requests": {"cpu": f"{rng.choice([100, 300])}m"}}}]}}
            code, doc = _http(port, "POST",
                              "/api/v1/namespaces/default/pods", pod)
            assert code in (201, 403, 409), (code, doc)
            if rng.random() < 0.4:
                code, doc = _http(
                    port, "DELETE", f"/api/v1/namespaces/default/pods/w{i}")
                assert code in (200, 404), (code, doc)
            stop.wait(rng.random() * 0.004)

    def rest_reader():
        rng = random.Random(seed * 31 + 3)
        rv = 0
        while not stop.is_set():
            code, doc = _http(port, "GET", "/api/v1/pods")
            assert code == 200 and doc["kind"] == "PodList", (code, doc)
            # selector property under churn: a server-filtered list must
            # be a subset of the full list and agree with client-side
            # evaluation of the same predicate over the full list's rv
            # window (bounded by concurrent mutators: assert subset +
            # field correctness of what WAS returned, not exact equality)
            if rng.random() < 0.5:
                full = {p["metadata"]["name"]: p for p in doc["items"]}
                code, fdoc = _http(
                    port, "GET",
                    "/api/v1/pods?fieldSelector=spec.nodeName%21%3D")
                assert code == 200, (code, fdoc)
                for p in fdoc["items"]:
                    assert p["spec"].get("nodeName"), p["metadata"]
                code, ldoc = _http(port, "GET", "/api/v1/pods?limit=3")
                assert code == 200 and len(ldoc["items"]) <= 3, ldoc
                if "continue" in ldoc["metadata"]:
                    tok = ldoc["metadata"]["continue"]
                    code, cdoc = _http(
                        port, "GET", f"/api/v1/pods?limit=50&continue={tok}")
                    # 410 legal if churn compacted past the token
                    assert code in (200, 410), (code, cdoc)
                    if code == 200:
                        first = {p["metadata"]["name"]
                                 for p in ldoc["items"]}
                        rest_names = {p["metadata"]["name"]
                                      for p in cdoc["items"]}
                        assert not (first & rest_names), "page overlap"
            code, doc = _http(port, "GET",
                              f"/api/v1/watch/pods?resourceVersion={rv}",
                              ndjson=True)
            assert code in (200, 410), (code, doc)
            if code == 200 and doc:
                # advance the cursor like a real poller (frames carry rv)
                rv = max(rv, max(int(f["object"]["metadata"]
                                     ["resourceVersion"]) for f in doc))
            if code == 410:
                code, doc = _http(port, "GET", "/api/v1/pods")
                assert code == 200
                rv = int(doc["metadata"]["resourceVersion"])
            stop.wait(rng.random() * 0.004)

    def grpc_service():
        rng = random.Random(seed * 31 + 4)
        while not stop.is_set():
            bridge.pump()
            with svc.lock:
                remote.schedule_cycle()
            bridge.pump()
            stop.wait(rng.random() * 0.004)

    def evictor():
        # the drain actor: evictions race binds/deletes/controllers;
        # every answer must be one of the legal eviction outcomes
        rng = random.Random(seed * 31 + 5)
        while not stop.is_set():
            code, doc = _http(port, "GET", "/api/v1/pods")
            assert code == 200
            bound = [p["metadata"] for p in doc["items"]
                     if p["spec"].get("nodeName")]
            if bound:
                m = rng.choice(bound)
                code, doc = _http(
                    port, "POST",
                    f"/api/v1/namespaces/{m['namespace']}/pods/"
                    f"{m['name']}/eviction", {"kind": "Eviction"})
                assert code in (201, 404, 429), (code, doc)
            stop.wait(rng.random() * 0.006)

    def elector_pair():
        # two electors CAS the same hub Lease while the driver jumps the
        # sim clock concurrently. Both believing they lead in one loop
        # iteration is LEGAL lease semantics (the clock can jump past
        # lease_duration between the two ticks — an expired leader only
        # learns on its next tick, exactly like the reference); the
        # invariant that must hold is hub-side: one record, a holder
        # that is always one of the candidates, a monotonic rv.
        from kubernetes_tpu.config import LeaderElectionConfig
        from kubernetes_tpu.leaderelection import LeaderElector, LeaseLock

        cfg = LeaderElectionConfig(lease_duration_s=3,
                                   renew_deadline_s=2, retry_period_s=1)
        a = LeaderElector("fz-a", LeaseLock(hub), cfg, hub.clock)
        b = LeaderElector("fz-b", LeaseLock(hub), cfg, hub.clock)
        rng = random.Random(seed * 31 + 6)
        last_rv = 0
        while not stop.is_set():
            a.tick()
            b.tick()
            record, rv = hub.get_lease("kube-system", "kube-scheduler")
            if record is not None:
                assert record.holder_identity in ("fz-a", "fz-b"), record
                assert rv >= last_rv, "lease rv went backwards"
                last_rv = rv
            stop.wait(rng.random() * 0.004)

    snapshots = []

    def checkpointer():
        # a checkpoint taken at ANY interleaving point must be a
        # consistent cut (the save walks every registry under the hub
        # lock); restorability is verified after the threads join
        import tempfile

        rng = random.Random(seed * 31 + 7)
        n = 0
        while not stop.is_set() and n < 3:
            stop.wait(0.05 + rng.random() * 0.05)
            # mkstemp: collision-free against concurrent suite runs on
            # the same machine (fixed names would race another process's
            # writes and unlinks)
            fd, path = tempfile.mkstemp(prefix=f"fuzz_ckpt_{seed}_",
                                        suffix=".ckpt")
            os.close(fd)
            manifest = hub.save_checkpoint(path)
            assert manifest["revision"] >= 0
            snapshots.append(path)
            n += 1

    def patcher():
        # the PATCH actor (VERDICT r4 item 4): merge-patches racing the
        # controllers — deployment scale/template patches drive real
        # scale-ups and rollouts mid-churn, pod label patches race the
        # writer's deletes. Every answer must be one of the verb's legal
        # outcomes; 409 only when the patch carried a stale rv (ours
        # never do), 422 only for immutable-field attempts (ours never).
        import http.client

        rng = random.Random(seed * 31 + 9)
        while not stop.is_set():
            if rng.random() < 0.5:
                body = ({"spec": {"replicas": 1 + rng.randrange(4)}}
                        if rng.random() < 0.7 else
                        {"spec": {"template": {
                            "cpuMilli": rng.choice([100, 150, 200])}}})
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=10)
                conn.request(
                    "PATCH",
                    "/apis/apps/v1/namespaces/default/deployments/web",
                    json.dumps(body),
                    {"Content-Type": "application/merge-patch+json"})
                r = conn.getresponse()
                out = r.read()
                conn.close()
                assert r.status in (200, 404), (r.status, out[-200:])
            else:
                code, doc = _http(port, "GET", "/api/v1/pods?limit=1")
                items = (doc or {}).get("items") or []
                if items:
                    m = items[0]["metadata"]
                    conn = http.client.HTTPConnection("127.0.0.1", port,
                                                      timeout=10)
                    conn.request(
                        "PATCH",
                        f"/api/v1/namespaces/{m['namespace']}/pods/"
                        f"{m['name']}",
                        json.dumps({"metadata": {"labels": {
                            "fuzz": str(rng.randrange(10))}}}),
                        {"Content-Type": "application/merge-patch+json"})
                    r = conn.getresponse()
                    out = r.read()
                    conn.close()
                    # 404: the writer/evictor deleted it between list
                    # and patch; 409: bind landed between read-doc and
                    # replace inside the handler is impossible (one
                    # lock), so only the legal pair remains
                    assert r.status in (200, 404), (r.status, out[-200:])
            stop.wait(rng.random() * 0.005)

    actors = (driver, rest_writer, rest_reader, grpc_service, evictor,
              elector_pair, checkpointer, patcher)
    threads = [threading.Thread(target=guarded(f), name=f.__name__)
               for f in actors]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), f"{t.name} wedged"
        assert not errors, errors
        # settled-state oracles: hub invariants AND the remote service's
        # wire-fed cache equals hub truth
        hub.step()
        hub.check_consistency()
        bridge.pump()
        with svc.lock:
            truth = {k: p.node_name for k, p in hub.truth_pods.items()}
            nd, pd = compare(remote, truth, list(hub.truth_nodes))
        assert not nd and not pd, (seed, nd, pd)
        # every mid-churn checkpoint is a consistent cut: it restores
        # into a fresh hub whose own oracle passes
        for path in snapshots:
            cold = HollowCluster(seed=seed + 10_000,
                                 scheduler_kw={"enable_preemption": False})
            cold.restore_checkpoint(path)
            cold.check_consistency()
            os.unlink(path)
    finally:
        stop.set()
        rest.close()
        client.close()
        server.stop(grace=None)


def test_interleaving_fuzz_campaign():
    for seed in range(N_SEEDS):
        _run_seed(seed)
