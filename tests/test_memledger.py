"""Device-memory ledger (obs/memledger.py) — the tier-1 acceptance
suite:

- modeled resident accounting: register/deregister through the
  cache/warmup seams, ranked forensic ordering, last-write-wins;
- measured side: cycle-boundary samples are interval-gated on the
  owner clock, sample-free boundaries publish the -1 sentinel;
- capacity preflight: warmup lands the per-bucket
  ``memory_analysis()`` peak table; an over-budget shape SPLITS to the
  largest warmed smaller bucket or SHEDS back to the queue — driven
  cycles with a tight limit schedule everything with ZERO device OOMs;
- OOM forensics: injected device_oom chaos (snapshot and warmup
  sites) lands a ranked forensic record on the ring, the flight
  recorder's ``mem=`` flag, /debug/memory, and the debugger dump —
  and the recovery path RELEASES every registered resident (the
  satellite drop-audit);
- the config block round-trips native AND v1alpha1,
  ``validate_config`` gates it, the bench_compare ``memory`` gate
  family honors its contract, SoakSentinels watch the ``mem.*``
  namespace, and graftlint stays clean over the module.
"""

import dataclasses
import json
import urllib.request

import pytest

from kubernetes_tpu.config import (
    MemoryLedgerConfig,
    ObservabilityConfig,
    WarmupConfig,
)
from kubernetes_tpu.obs.memledger import OOM_RING, MemoryLedger
from kubernetes_tpu.faults import FaultInjector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _mlcfg(**kw):
    kw.setdefault("sample_interval_s", 0.0)  # sample every boundary
    return MemoryLedgerConfig(**kw)


def _scheduler(n_nodes=4, **kw):
    kw.setdefault("observability",
                  ObservabilityConfig(memory_ledger=_mlcfg()))
    s = Scheduler(enable_preemption=False, **kw)
    for i in range(n_nodes):
        s.on_node_add(make_node(f"n{i}", cpu_milli=16000))
    return s


def _drive(s, n_pods=8, cycles=2, prefix="p"):
    out = []
    for c in range(cycles):
        for i in range(n_pods):
            s.on_pod_add(make_pod(f"{prefix}{c}-{i}", cpu_milli=50))
        out.append(s.schedule_cycle())
    return out


# ---------------------------------------------------------------------------
# modeled side: resident accounting
# ---------------------------------------------------------------------------


def test_register_deregister_and_forensic_ranking():
    ml = MemoryLedger(_mlcfg(), clock=FakeClock())
    ml.register("cache.node_table", 4096, shape="N64")
    ml.register("cache.score_summary", 1024, shape="N64")
    ml.register("scheduler.pod_batch", 8192)
    assert ml.resident_count() == 3
    assert ml.resident_bytes() == 4096 + 1024 + 8192
    # ranked largest-first (the forensic ordering), top truncates
    assert [n for n, _, _ in ml.ranked_residents()] == [
        "scheduler.pod_batch", "cache.node_table", "cache.score_summary"]
    assert len(ml.ranked_residents(top=2)) == 2
    # re-register: last write wins; zero bytes drops the row
    ml.register("cache.node_table", 100)
    assert dict((n, b) for n, b, _ in ml.ranked_residents())[
        "cache.node_table"] == 100
    ml.register("scheduler.pod_batch", 0)
    assert ml.resident_count() == 2
    ml.deregister("cache.node_table")
    assert ml.deregister_prefix("cache.") == 1
    assert ml.resident_count() == 0


def test_disabled_ledger_is_inert():
    ml = MemoryLedger(_mlcfg(enabled=False), clock=FakeClock())
    ml.register("x", 100)
    assert ml.resident_count() == 0
    assert ml.observe_cycle() is None
    assert not ml.preflight_on
    assert ml.preflight(8, 8, 0)[0] == "ok"


# ---------------------------------------------------------------------------
# measured side: interval gating + the -1 sentinel
# ---------------------------------------------------------------------------


def test_sample_interval_gates_on_owner_clock():
    from kubernetes_tpu.metrics import SchedulerMetrics

    clk = FakeClock()
    metrics = SchedulerMetrics()
    ml = MemoryLedger(MemoryLedgerConfig(sample_interval_s=10.0),
                      metrics=metrics, clock=clk)
    ml.register("r", 1000)
    e1 = ml.observe_cycle()
    assert ml.samples == 1  # first boundary always samples
    assert e1["modeled_bytes"] == 1000
    # within the interval: no sample, the sentinel publishes
    clk.advance(1.0)
    e2 = ml.observe_cycle()
    assert ml.samples == 1
    assert e2["measured_bytes"] == -1 and e2["efficiency"] == -1.0
    assert metrics.memory_model_efficiency.value() == -1.0
    # past the interval: sampled again, watermark history grows
    clk.advance(10.0)
    ml.observe_cycle()
    assert ml.samples == 2
    assert len(ml.snapshot()["watermarks"]) == 2


def test_census_fallback_measures_live_arrays():
    """CPU backends report no memory_stats: the bounded live-array
    census stands in, so measured bytes are populated and efficiency
    is judgeable on the laptop."""
    import jax.numpy as jnp

    keep = jnp.ones((128, 128))  # ensure at least one live array
    ml = MemoryLedger(_mlcfg(), clock=FakeClock())
    ml.register("r", int(keep.nbytes))
    e = ml.observe_cycle()
    assert ml.census_count() >= 1
    assert e["measured_bytes"] >= keep.nbytes
    assert 0.0 <= e["efficiency"] <= 8.0
    snap = ml.snapshot()
    assert snap["devices"].get("census", {}).get("resident", 0) > 0
    assert snap["peak_bytes"] >= e["measured_bytes"]


# ---------------------------------------------------------------------------
# capacity preflight: the per-bucket peak table
# ---------------------------------------------------------------------------


def test_preflight_verdicts_against_bucket_table():
    from kubernetes_tpu.metrics import SchedulerMetrics

    metrics = SchedulerMetrics()
    ml = MemoryLedger(_mlcfg(limit_bytes=1000, headroom_frac=0.9),
                      metrics=metrics, clock=FakeClock())
    stats = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
             "code_bytes": 0, "alias_bytes": 0}
    ml.record_bucket_memory(4, 8, 0, dict(stats, total_bytes=500))
    ml.record_bucket_memory(8, 8, 0, dict(stats, total_bytes=880))
    ml.record_bucket_memory(16, 8, 0, dict(stats, total_bytes=2000))

    # fits: need <= limit x headroom
    act, split, v = ml.preflight(8, 8, 0)
    assert (act, split, v["basis"]) == ("ok", 8, "fits")
    assert v["budget"] == 900 and v["need"] == 880
    # over budget, a smaller warmed bucket fits: split to the LARGEST
    act, split, v = ml.preflight(16, 8, 0)
    assert (act, split, v["basis"]) == ("split", 8, "over-budget")
    # unwarmed shape: absence-tolerant ok — never shed on a guess
    act, _, v = ml.preflight(32, 64, 0)
    assert (act, v["basis"]) == ("ok", "unwarmed")
    # over budget, nothing smaller warmed at this (N, mesh): shed
    ml2 = MemoryLedger(_mlcfg(limit_bytes=100), clock=FakeClock())
    ml2.record_bucket_memory(4, 8, 0, dict(stats, total_bytes=500))
    act, split, v = ml2.preflight(4, 8, 0)
    assert (act, split, v["basis"]) == ("shed", 0,
                                        "over-budget-no-bucket")
    # verdicts count on the ledger AND the metrics counter
    assert ml.preflights == {"ok": 2, "split": 1, "shed": 0}
    assert metrics.memory_preflight.value(action="split") == 1


def test_preflight_without_limit_never_fires():
    ml = MemoryLedger(_mlcfg(), clock=FakeClock())  # limit unknown (CPU)
    ml.record_bucket_memory(8, 8, 0, {"total_bytes": 10**12})
    act, _, v = ml.preflight(8, 8, 0)
    assert (act, v["basis"]) == ("ok", "no-limit")


# ---------------------------------------------------------------------------
# OOM forensics: the ranked record + ring bound
# ---------------------------------------------------------------------------


def test_record_oom_ranked_record_and_flag():
    clk = FakeClock()
    ml = MemoryLedger(_mlcfg(limit_bytes=10000), clock=clk)
    ml.register("cache.node_table", 5000, shape="N64")
    ml.register("cache.score_summary", 300)
    ml.observe_cycle()
    ml.preflight(8, 8, 0)
    rec = ml.record_oom("snapshot:device", error="RESOURCE_EXHAUSTED",
                        shapes="P8xN64", cycle=7)
    assert rec["site"] == "snapshot:device" and rec["cycle"] == 7
    assert rec["modeled_bytes"] == 5300
    assert rec["limit_bytes"] == 10000
    assert rec["top_residents"][0] == {
        "name": "cache.node_table", "bytes": 5000, "shape": "N64"}
    assert rec["watermarks"] and rec["preflight"]["action"] == "ok"
    assert ml.oom_flag(rec) == \
        "oom@snapshot:device top=cache.node_table:5000B"
    # the ring is bounded: an OOM storm must not grow memory while the
    # process is already memory-sick
    for i in range(OOM_RING + 5):
        ml.record_oom("warmup:compile", cycle=i)
    assert len(ml.oom_records()) == OOM_RING
    # the dump shows the forensic lines (SIGUSR2 surface)
    assert "Memory ledger: modeled=" in ml.dump()
    assert "OOM @warmup:compile" in ml.dump()


# ---------------------------------------------------------------------------
# driven integration: residents, state_sizes, warmup capture
# ---------------------------------------------------------------------------


def test_driven_cycles_register_residents_and_state_sizes():
    s = _scheduler()
    _drive(s, n_pods=8, cycles=2)
    ml = s.obs.memledger
    names = {n for n, _, _ in ml.ranked_residents()}
    assert "cache.node_table" in names
    assert "scheduler.pod_batch" in names
    sizes = s.state_sizes()
    assert sizes["dev_node_table"] == 1
    assert sizes["mem_residents"] >= 2
    assert sizes["mem_census_arrays"] >= 1
    # boundary entries exist, the dump line carries the mem= byte flag
    assert ml.snapshot()["observed"] == 2
    assert "mem=" in s.obs.recorder.dump()
    # dropping the snapshot releases the cache-side registrations
    s.cache.drop_device_snapshot()
    assert "cache.node_table" not in {
        n for n, _, _ in ml.ranked_residents()}


def test_warmup_lands_bucket_memory_table():
    s = _scheduler(warmup=WarmupConfig(enabled=True, pod_buckets=(4, 8)))
    compiled = s.warmup(sample_pods=[make_pod("w", cpu_milli=50)])
    assert compiled >= 2
    table = s.obs.memledger.bucket_table()
    ps = sorted(p for p, _, _ in table)
    assert ps == [4, 8]
    for entry in table.values():
        assert entry["total_bytes"] > 0
        assert entry["argument_bytes"] > 0
    # the larger pod bucket needs more bytes — the table is judgeable
    (k4, k8) = sorted(table, key=lambda k: k[0])
    assert table[k8]["total_bytes"] > table[k4]["total_bytes"]


def test_soak_sentinels_watch_mem_namespace():
    from kubernetes_tpu.soak import SoakSentinels

    s = _scheduler()
    _drive(s, n_pods=4, cycles=1)
    out = SoakSentinels(sched=s).collect()
    assert out["mem.residents"] >= 2
    assert out["mem.modeled_bytes"] > 0
    assert out["mem.oom_records"] == 0


# ---------------------------------------------------------------------------
# preflight on the cycle path: split / shed with ZERO device OOMs
# ---------------------------------------------------------------------------


def test_over_budget_batch_splits_to_warmed_bucket():
    """8 pods against a limit only the P4 bucket fits: the cycle trims
    to 4, requeues 4, and the next cycle schedules the rest — zero
    OOMs, the preflight verdict on the flight records."""
    s = _scheduler(warmup=WarmupConfig(enabled=True, pod_buckets=(4, 8)))
    assert s.warmup(sample_pods=[make_pod("w", cpu_milli=50)]) >= 2
    ml = s.obs.memledger
    table = ml.bucket_table()
    (k4, k8) = sorted(table, key=lambda k: k[0])
    frac = ml.config.headroom_frac
    # budget exactly covers the P4 bucket, not the P8 one
    ml.config.limit_bytes = int(table[k4]["total_bytes"] / frac) + 2
    assert ml.preflight(k8[0], k8[1], k8[2])[0] == "split"

    for i in range(8):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=50))
    r1 = s.schedule_cycle()
    assert r1.attempted == 4 and r1.scheduled == 4
    r2 = s.schedule_cycle()
    assert r2.scheduled == 4  # the requeued half lands next cycle
    assert ml.preflights["split"] >= 1
    assert s.metrics.recovery_device_resets.value() == 0
    assert ml.oom_records() == []
    recs = s.obs.recorder.records()
    assert any(r.preflight == "split" for r in recs)


def test_over_budget_batch_sheds_whole_when_no_bucket_fits():
    s = _scheduler(warmup=WarmupConfig(enabled=True, pod_buckets=(8,)))
    assert s.warmup(sample_pods=[make_pod("w", cpu_milli=50)]) >= 1
    ml = s.obs.memledger
    ml.config.limit_bytes = 100  # nothing fits
    for i in range(4):
        s.on_pod_add(make_pod(f"p{i}", cpu_milli=50))
    r = s.schedule_cycle()
    assert r.attempted == 0 and r.scheduled == 0
    assert ml.preflights["shed"] >= 1
    # requeued whole, not dropped
    assert sum(s.queue.pending_counts().values()) == 4
    assert ml.oom_records() == []
    # lifting the limit drains the queue — the shed was a deferral
    ml.config.limit_bytes = 0
    assert s.schedule_cycle().scheduled == 4


# ---------------------------------------------------------------------------
# chaos: injected device_oom becomes an incident record, and the
# drop-audit — recovery releases every registered resident
# ---------------------------------------------------------------------------


def test_device_oom_at_snapshot_leaves_forensic_record():
    fi = FaultInjector(seed=0).arm("snapshot:device", "device_oom",
                                   count=1)
    s = _scheduler(fault_injector=fi)
    res = _drive(s, n_pods=4, cycles=2)
    assert sum(r.scheduled for r in res) == 8  # recovered, no crash
    ml = s.obs.memledger
    recs = ml.oom_records()
    assert recs and recs[0]["site"] == "snapshot:device"
    assert "mem=oom@snapshot:device" in s.obs.recorder.dump()
    # the ranked record reaches the debugger dump too
    from kubernetes_tpu import debugger

    text = debugger.dump(s)
    assert "Memory ledger:" in text and "OOM @snapshot:device" in text


def test_warmup_oom_releases_residents_and_parks_flag():
    """The satellite drop-audit: a warmup abort must deregister every
    device resident (score cache, warm potentials, node table) — and
    its forensic flag, captured BETWEEN cycles, parks for the next
    flight record."""
    fi = FaultInjector(seed=0).arm("warmup:compile", "device_oom",
                                   count=1)
    s = _scheduler(fault_injector=fi,
                   warmup=WarmupConfig(enabled=True, pod_buckets=(4,)))
    _drive(s, n_pods=4, cycles=1)  # populate residents first
    ml = s.obs.memledger
    assert ml.resident_count() >= 2
    assert s.warmup(sample_pods=[make_pod("w", cpu_milli=50)]) == 0
    assert ml.resident_count() == 0, (
        "warmup abort leaked ledger registrations: "
        f"{ml.ranked_residents()}")
    assert s._sk_warm_pot is None
    recs = ml.oom_records()
    assert recs and recs[-1]["site"] == "warmup:compile"
    # the parked flag stamps the NEXT cycle's record
    _drive(s, n_pods=2, cycles=1, prefix="after")
    assert any(r.oom_forensic.startswith("oom@warmup:compile")
               for r in s.obs.recorder.records())


# ---------------------------------------------------------------------------
# /debug/memory + config round-trips + bench_compare contract
# ---------------------------------------------------------------------------


def test_debug_memory_endpoint():
    from kubernetes_tpu.server import serve_scheduler

    s = _scheduler()
    _drive(s, n_pods=4, cycles=2)
    srv = serve_scheduler(s, port=0)
    try:
        host, port = srv.server_address[:2]
        body = urllib.request.urlopen(
            f"http://{host}:{port}/debug/memory", timeout=5).read()
        doc = json.loads(body)
        assert doc["enabled"] and doc["observed"] == 2
        assert doc["residents"][0]["bytes"] > 0
        assert doc["modeled_bytes"] > 0
        assert "preflight" in doc and "oom_records" in doc
        assert doc["model_efficiency"]["n"] >= 1
    finally:
        srv.shutdown()


def test_memledger_config_native_and_v1alpha1_round_trip():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode
    from kubernetes_tpu.cli import ConfigError, decode_config, \
        validate_config

    # native nested block, strict unknown-field rejection
    cfg = decode_config({"observability": {"memory_ledger": {
        "sample_interval_s": 2.0, "headroom_frac": 0.8,
        "limit_bytes": 1 << 30}}})
    mlg = cfg.observability.memory_ledger
    assert (mlg.sample_interval_s, mlg.headroom_frac,
            mlg.limit_bytes) == (2.0, 0.8, 1 << 30)
    with pytest.raises(ConfigError):
        decode_config({"observability": {"memory_ledger": {"bogus": 1}}})

    # v1alpha1: camelCase + duration strings, encode(decode) is stable
    doc = {"apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
           "kind": "KubeSchedulerConfiguration",
           "observability": {"memoryLedger": {"sampleInterval": "2s",
                                              "headroomFrac": 0.8,
                                              "limitBytes": 1 << 30}}}
    internal = decode(doc)
    vml = internal.observability.memory_ledger
    assert vml.sample_interval_s == pytest.approx(2.0)
    assert vml.headroom_frac == pytest.approx(0.8)
    assert vml.preflight is True  # default
    assert decode(encode(internal)).observability.memory_ledger == vml

    # validate_config gates the block with camelCase field paths
    bad = dataclasses.replace(
        internal, observability=dataclasses.replace(
            internal.observability,
            memory_ledger=dataclasses.replace(
                vml, headroom_frac=1.5, sample_interval_s=-1.0,
                history=0)))
    errs = validate_config(bad)
    assert any("memoryLedger.headroomFrac" in e for e in errs)
    assert any("memoryLedger.sampleInterval" in e for e in errs)
    assert any("memoryLedger.history" in e for e in errs)


def _load_bench_compare():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    return bc


def _mem_record(eff_p50=0.6, peak=1000, limit=0, ooms=0,
                preflights=None, with_memory=True):
    mem = {"cycles": 50,
           "resident_bytes": {"modeled": 900, "measured": 1000,
                              "peak": peak},
           "model_efficiency": {"n": 50, "p50": eff_p50, "p99": 1.0},
           "limit_bytes": limit,
           "preflight": preflights if preflights is not None
           else {"ok": 50, "split": 0, "shed": 0},
           "oom_records": ooms}
    arm = {"p50_s": 0.01, "p99_s": 0.05, "ops_per_sec": 500.0,
           "jax": {"retraces": 0}}
    if with_memory:
        arm["memory"] = mem
    return {"name": "churn", "arms": {"serving": dict(arm),
                                      "overload": dict(arm)},
            "errors": []}


def test_bench_compare_memory_gate_contract():
    bc = _load_bench_compare()
    # registered in --list-gates
    assert any(n == "memory" for n, _, _ in bc.GATE_FAMILIES)

    # clean record passes
    v = bc.compare_memory(_mem_record())
    assert v["regressions"] == [] and v["checks"]

    # efficiency collapse fails the floor (untracked device memory)
    v = bc.compare_memory(_mem_record(eff_p50=0.01))
    assert any(r["check"] == "memory.serving.model_efficiency_p50"
               for r in v["regressions"])

    # peak watermark past a KNOWN limit fails; unknown limit tolerated
    v = bc.compare_memory(_mem_record(peak=2000, limit=1500))
    assert any(r["check"].endswith("peak_vs_limit_bytes")
               for r in v["regressions"])
    v = bc.compare_memory(_mem_record(peak=2000, limit=0))
    assert not any("peak_vs_limit" in r["check"]
                   for r in v["regressions"])

    # forensic records on a CLEAN arm fail
    v = bc.compare_memory(_mem_record(ooms=1))
    assert any(r["check"] == "memory.serving.oom_records"
               for r in v["regressions"])

    # absence-tolerant: a pre-ledger record warns, never fails
    v = bc.compare_memory(_mem_record(with_memory=False))
    assert v["regressions"] == [] and v["warnings"]


# ---------------------------------------------------------------------------
# budgets + lint
# ---------------------------------------------------------------------------


def test_zero_new_retraces_with_memledger_on():
    s = _scheduler()
    _drive(s, n_pods=8, cycles=4)
    assert s.obs.jax.retrace_total() == 0, (
        "the memory ledger must not perturb the solve signatures")


def test_memledger_module_lints_clean():
    """graftlint over obs/memledger.py: the device-discipline rules
    (R2 host syncs, R3 jit-in-loop, R7 undeclared readbacks, R8
    sharded gathers) — the module is host code by construction; its
    two measured-side boundaries (memory_stats, the live-array census)
    carry declared-boundary pragmas."""
    import kubernetes_tpu.obs.memledger as memledger_mod
    from kubernetes_tpu.testing import lint_clean

    lint_clean(memledger_mod, rules=("R2", "R3", "R7", "R8"),
               jit_all=False)
