"""Sharded execution backend (the mesh PR): the ``parallel:`` config
block, validated mesh construction, the sharded device-resident
snapshot, the mesh-aware degradation ladder, and the sharded-vs-single
**bit-parity** contract — collectives change the execution plan, never
the answer (the analog of the reference asserting identical scheduling
decisions regardless of goroutine fan-out, and the production
promotion of the test_parallel.py dryrun).

Runs on the 8-virtual-device CPU mesh tests/conftest.py forces."""

import dataclasses
import importlib.util
import os
import random

import numpy as np
import pytest

import jax

from kubernetes_tpu.cache import SchedulerCache
from kubernetes_tpu.config import (
    KubeSchedulerConfiguration,
    ParallelConfig,
    RecoveryConfig,
)
from kubernetes_tpu.faults import FaultInjector
from kubernetes_tpu.models.cluster import make_gang_pods, make_nodes, make_pods
from kubernetes_tpu.ops.arrays import (
    nodes_to_device,
    pods_to_device,
    selectors_to_device,
)
from kubernetes_tpu.ops.assign import batch_assign
from kubernetes_tpu.parallel import (
    largest_pow2,
    make_mesh,
    mesh_from_spec,
    mesh_size,
    shard_cluster,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.snapshot import SnapshotPacker
from kubernetes_tpu.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def mesh_of(d):
    return make_mesh(jax.devices()[:d])


# ---------------------------------------------------------------------------
# Mesh construction: power-of-two validation + the spec resolver
# ---------------------------------------------------------------------------


def test_largest_pow2():
    assert [largest_pow2(n) for n in (1, 2, 3, 5, 6, 7, 8, 9)] == \
        [1, 2, 2, 4, 4, 4, 8, 8]


@pytest.mark.parametrize("given,kept", [(3, 2), (6, 4), (8, 8), (1, 1)])
def test_make_mesh_falls_back_to_pow2_subset(given, kept):
    """A 3- or 6-device set can never divide the power-of-two node
    buckets; make_mesh keeps the largest dividing subset instead of
    dying with an opaque XLA shape error mid-solve."""
    m = make_mesh(jax.devices()[:given])
    assert int(m.devices.size) == kept


def test_mesh_from_spec_vocabulary():
    assert mesh_from_spec("off") is None
    assert mesh_from_spec(None) is None
    assert mesh_size(mesh_from_spec("auto")) == 8
    assert mesh_size(mesh_from_spec(4)) == 4
    # more than available clamps (with a logged warning)
    assert mesh_size(mesh_from_spec(64)) == 8
    with pytest.raises(ValueError):
        mesh_from_spec(-1)


# ---------------------------------------------------------------------------
# Config: the parallel block, native + v1alpha1 round-trip + validation
# ---------------------------------------------------------------------------


def test_parallel_block_native_decode_and_validation():
    from kubernetes_tpu.cli import decode_config, validate_config

    cfg = decode_config({"parallel": {"mesh": "auto"}})
    assert cfg.parallel.mesh == "auto"
    assert validate_config(cfg) == []
    assert validate_config(decode_config({"parallel": {"mesh": 8}})) == []
    errs = validate_config(decode_config({"parallel": {"mesh": 3}}))
    assert any("parallel.mesh" in e and "power of two" in e for e in errs)
    errs = validate_config(decode_config({"parallel": {"mesh": "sideways"}}))
    assert any("parallel.mesh" in e for e in errs)
    with pytest.raises(Exception):
        decode_config({"parallel": {"lanes": 2}})  # unknown field


def test_parallel_block_v1alpha1_round_trip():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode

    cfg = KubeSchedulerConfiguration(parallel=ParallelConfig(mesh=4))
    doc = encode(cfg)
    assert doc["parallel"] == {"mesh": 4}
    back = decode(doc)
    assert back.parallel == ParallelConfig(mesh=4)
    # versioned defaulting: an absent block decodes to "off"
    doc2 = encode(KubeSchedulerConfiguration())
    doc2.pop("parallel")
    assert decode(doc2).parallel.mesh == "off"


def test_cli_mesh_flag_overlay():
    from kubernetes_tpu.cli import build_parser, resolve_config

    args = build_parser().parse_args(["--mesh", "4"])
    assert resolve_config(args).parallel.mesh == 4
    args = build_parser().parse_args(["--mesh", "auto"])
    assert resolve_config(args).parallel.mesh == "auto"
    from kubernetes_tpu.cli import ConfigError

    with pytest.raises(ConfigError):
        resolve_config(build_parser().parse_args(["--mesh", "3"]))


# ---------------------------------------------------------------------------
# Sharded-vs-single bit parity: randomized fuzz across mesh sizes
# {1, 2, 4, 8}, the contended/gang/pred-mask variants, and the
# asymmetric 512x137 shape from the dryrun
# ---------------------------------------------------------------------------


def _fuzz_workload(seed: int, n_nodes=48, n_pending=96):
    """Randomized cluster from a fixed vocabulary (stable buckets):
    heterogeneous node sizes + existing load + mixed pod requests, so
    scores are non-trivial and ties real."""
    rng = random.Random(seed)
    nodes = [
        make_node(
            f"n{i}",
            cpu_milli=rng.choice([4000, 8000, 16000]),
            memory=rng.choice([8 * 2**30, 32 * 2**30]),
            pods=rng.choice([16, 110]),
            zone=f"z{i % 4}",
        )
        for i in range(n_nodes)
    ]
    existing = [
        make_pod(f"old{i}", cpu_milli=rng.choice([100, 500]),
                 memory=2**28, node_name=f"n{rng.randrange(n_nodes)}")
        for i in range(n_nodes // 2)
    ]
    pending = [
        make_pod(f"p{i}", cpu_milli=rng.choice([100, 250, 500]),
                 memory=rng.choice([2**27, 2**28]),
                 priority=rng.choice([0, 0, 10]))
        for i in range(n_pending)
    ]
    pk = SnapshotPacker()
    for p in existing + pending:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, existing))
    dp = pods_to_device(pk.pack_pods(pending))
    ds = selectors_to_device(pk.pack_selector_tables())
    return dp, dn, ds


@pytest.mark.parametrize("d", [1, 2, 4, 8])
def test_sharded_bit_parity_fuzz(d):
    dp, dn, ds = _fuzz_workload(seed=20260804 + d)
    want, _, _ = batch_assign(dp, dn, ds, per_node_cap=4)
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh_of(d))
    got, _, _ = batch_assign(sdp, sdn, sds, per_node_cap=4)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_sharded_bit_parity_contended():
    """Capacity-bound workload: multiple auction rounds, per-node
    admission prefix sums, and the rotation tie-break all reduce over
    the sharded axis."""
    dp, dn, ds = _fuzz_workload(seed=7, n_nodes=16, n_pending=96)
    want, _, r1 = batch_assign(dp, dn, ds, per_node_cap=2)
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh_of(8))
    got, _, r2 = batch_assign(sdp, sdn, sds, per_node_cap=2)
    assert int(r1) == int(r2) > 1  # genuinely contended, same rounds
    assert (np.asarray(got) == np.asarray(want)).all()


def test_sharded_bit_parity_pred_mask():
    """A Policy-style predicate bitmask is a static jit key — the
    sharded compile must honor the same mask bit-for-bit."""
    from kubernetes_tpu.config import default_predicate_mask
    from kubernetes_tpu.ops.predicates import BIT

    mask = default_predicate_mask() & ~(1 << BIT["PodFitsResources"])
    dp, dn, ds = _fuzz_workload(seed=11, n_nodes=16, n_pending=64)
    want, _, _ = batch_assign(dp, dn, ds, enabled_mask=mask)
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh_of(8))
    got, _, _ = batch_assign(sdp, sdn, sds, enabled_mask=mask)
    assert (np.asarray(got) == np.asarray(want)).all()


def test_sharded_bit_parity_asymmetric_512x137():
    """The dryrun's asymmetric shape: 137 nodes pad to a 256 bucket, so
    shards carry uneven VALID populations — padding rows must stay
    rejected on every shard."""
    nodes = make_nodes(137, zones=4)
    pending = make_pods(512, "asym")
    pk = SnapshotPacker()
    for p in pending:
        pk.intern_pod(p)
    dn = nodes_to_device(pk.pack_nodes(nodes, []))
    dp = pods_to_device(pk.pack_pods(pending))
    ds = selectors_to_device(pk.pack_selector_tables())
    want, _, _ = batch_assign(dp, dn, ds, per_node_cap=4)
    sdp, sdn, sds = shard_cluster(dp, dn, ds, mesh_of(8))
    got, _, _ = batch_assign(sdp, sdn, sds, per_node_cap=4)
    w = np.asarray(want)
    assert (np.asarray(got) == w).all()
    assert (w[: len(pending)] < 137).all()  # never a padding node


def _drive(parallel, pods_fn, n_nodes=8, cycles=1):
    s = Scheduler(clock=FakeClock(), enable_preemption=False,
                  parallel=parallel)
    for i in range(n_nodes):
        s.on_node_add(make_node(f"node-{i}", cpu_milli=8000, pods=32))
    out = []
    for c in range(cycles):
        for p in pods_fn(c):
            s.on_pod_add(p)
        out.append(s.schedule_cycle())
    return s, out


def test_restricted_primary_placement_parity_across_mesh():
    """Sparsity-first placements on the LIVE mesh: the same cold
    (partitioned) + steady (restricted) churn at widths {1, 2, 4, 8}
    reproduces the single-device assignments bit-for-bit, and every
    cycle keeps its sparsity-first scope — the placement-level
    complement of the kernel parity fuzz in
    tests/test_sparse_primary.py."""
    from kubernetes_tpu.config import IncrementalConfig

    def drive(parallel):
        s = Scheduler(clock=FakeClock(), enable_preemption=False,
                      parallel=parallel,
                      incremental=IncrementalConfig(
                          enabled=True, primary=True,
                          candidate_bucket=8))
        # heterogeneous sizes so the rank order (and therefore the
        # candidate cut) is contended, not alphabetical
        for i in range(64):
            s.on_node_add(make_node(f"node-{i}",
                                    cpu_milli=(4000 if i % 2 else 8000),
                                    pods=32, zone=f"z{i % 4}"))
        out = []
        for c in range(2):
            for i in range(4):
                s.on_pod_add(make_pod(f"c{c}-{i}",
                                      cpu_milli=300 + 100 * i))
            out.append(s.schedule_cycle())
        return out

    ref = drive(None)
    assert [r.solve_scope for r in ref] == ["partitioned", "restricted"]
    for d in (1, 2, 4, 8):
        got = drive(ParallelConfig(mesh=d))
        assert [r.solve_scope for r in got] == \
            ["partitioned", "restricted"], d
        for rg, rr in zip(got, ref):
            assert rg.assignments == rr.assignments, d


def test_sharded_bit_parity_gang_driver():
    """Driver-level gang (all-or-nothing) parity: group rollback and
    the usage rebuild after it run against the sharded table."""

    def pods(_c):
        ok = make_gang_pods(2, 4, name_prefix="g")
        # a group that cannot fully place (more members than the
        # cluster's pod slots allow at once) rolls back atomically
        big = make_gang_pods(1, 8, name_prefix="huge")
        for p in big:
            p.requests = dataclasses.replace(
                p.requests, cpu_milli=40000)  # no node fits
        return ok + big

    s_off, r_off = _drive(None, pods)
    s_on, r_on = _drive(ParallelConfig(mesh=8), pods)
    assert r_off[0].assignments == r_on[0].assignments
    assert r_off[0].scheduled == r_on[0].scheduled == 8
    assert r_off[0].unschedulable == r_on[0].unschedulable == 8


# ---------------------------------------------------------------------------
# Sharded resident snapshot: delta-scatter-after-churn == full rebuild
# ---------------------------------------------------------------------------


def _churned_caches(mesh):
    c = SchedulerCache()
    c.set_mesh(mesh)
    for i in range(64):
        c.add_node(make_node(f"n{i}"))
    _, dev0, mode0 = c.device_snapshot()
    assert mode0 == "full"
    # churn a small dirty set (update, assume, confirm) — under the 25%
    # delta threshold
    c.update_node(make_node("n3", cpu_milli=1234))
    c.assume_pod(make_pod("a", cpu_milli=100), "n7")
    c.add_pod(make_pod("b", cpu_milli=50, node_name="n9"))
    _, dev_delta, mode1 = c.device_snapshot()
    assert mode1 == "delta"
    # the oracle: a fresh cache packing the SAME final state in full
    c2 = SchedulerCache()
    c2.set_mesh(mesh)
    for i in range(64):
        c2.add_node(make_node(
            f"n{i}", cpu_milli=(1234 if i == 3 else 32000)))
    c2.assume_pod(make_pod("a", cpu_milli=100), "n7")
    c2.add_pod(make_pod("b", cpu_milli=50, node_name="n9"))
    _, dev_full, _ = c2.device_snapshot()
    return dev_delta, dev_full


def test_sharded_delta_scatter_matches_full_rebuild():
    mesh = mesh_of(8)
    dev_delta, dev_full = _churned_caches(mesh)
    for name, a, b in zip(type(dev_delta)._fields, dev_delta, dev_full):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name
    # the scatter must PRESERVE the node-axis sharding (a silent
    # fallback to single-device would still be bit-correct)
    from kubernetes_tpu.parallel.mesh import NODE_AXIS

    spec = dev_delta.allocatable.sharding.spec
    assert spec[0] == NODE_AXIS


def test_set_mesh_change_invalidates_resident():
    c = SchedulerCache()
    c.set_mesh(mesh_of(4))
    c.add_node(make_node("n0"))
    _, _, mode = c.device_snapshot()
    assert mode == "full"
    _, _, mode = c.device_snapshot()
    assert mode == "clean"
    c.set_mesh(mesh_of(2))  # mesh change drops the resident table
    _, dev, mode = c.device_snapshot()
    assert mode == "full"
    assert int(dev.allocatable.sharding.mesh.devices.size) == 2


def test_tiny_cluster_pads_node_bucket_to_mesh():
    """A 1-node cluster on an 8-device mesh pads its bucket up to 8
    rows so the shard split stays legal."""
    c = SchedulerCache()
    c.set_mesh(mesh_of(8))
    c.add_node(make_node("only"))
    _, dev, _ = c.device_snapshot()
    assert dev.allocatable.shape[0] == 8


# ---------------------------------------------------------------------------
# Scheduler end-to-end under the mesh: steady-state modes, provenance,
# zero retraces, warmup, ladder, chaos
# ---------------------------------------------------------------------------


def test_scheduler_mesh_steady_state_and_provenance():
    def pods(c):
        return [make_pod(f"p{c}-{i}", cpu_milli=100) for i in range(4)]

    s_off, r_off = _drive(None, pods, n_nodes=32, cycles=3)
    s_on, r_on = _drive(ParallelConfig(mesh=8), pods, n_nodes=32, cycles=3)
    for a, b in zip(r_off, r_on):
        assert a.assignments == b.assignments
        assert a.scheduled == b.scheduled == 4
    # steady state: full upload once, then delta scatters on churn
    assert [r.snapshot_mode for r in r_on] == ["full", "delta", "delta"]
    assert s_on.metrics.mesh_devices.value() == 8
    rec = s_on.obs.recorder.records()[-1]
    assert rec.mesh == 8
    assert "+mesh8" in rec.batch_shape
    # cycles 2..3 hit warmed shapes: zero retraces at the solve site
    assert s_on.obs.jax.retrace_total("solve") == 0
    assert s_off.metrics.mesh_devices.value() == 0
    assert s_off.obs.recorder.records()[-1].mesh == 0


def test_scheduler_mesh_warmup_registers_sharded_shapes():
    from kubernetes_tpu.config import WarmupConfig

    s = Scheduler(clock=FakeClock(), enable_preemption=False,
                  parallel=ParallelConfig(mesh=8),
                  warmup=WarmupConfig(enabled=True, pod_buckets=(8,)))
    for i in range(16):
        s.on_node_add(make_node(f"node-{i}"))
    assert s.warmup(sample_pods=[make_pod("w", cpu_milli=10)]) == 1
    s.on_pod_add(make_pod("real", cpu_milli=10))
    res = s.schedule_cycle()
    assert res.scheduled == 1
    # the warmed sharded signature served the real cycle: no retrace
    assert s.obs.jax.retrace_total("solve") == 0


def test_mesh_ladder_single_device_rung():
    """device_lost at the sharded solve site: the mesh-aware ladder
    demotes sharded -> batch-single (one device of the mesh) before
    batch-cpu/greedy, and the cycle still binds."""
    fi = FaultInjector(seed=0).arm("solve:batch", "device_lost")
    s = Scheduler(clock=FakeClock(), enable_preemption=False,
                  fault_injector=fi, parallel=ParallelConfig(mesh=8))
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0"))
    res = s.schedule_cycle()
    assert res.scheduled == 1
    assert res.solver_tier == "batch-single"
    assert res.solver_fallbacks >= 1


def test_mesh_device_loss_cooloff_demotes_then_heals_sharded():
    """A lost shard at the snapshot seam exhausts the rebuild budget ->
    single-device host-mode snapshots for the cooloff; once it passes
    and the device heals, the resident table re-places ONTO THE MESH
    (the chaos entry of the ISSUE's test satellite)."""
    fi = FaultInjector(seed=0).arm("snapshot:device", "device_lost",
                                   count=2)
    clk = FakeClock()
    s = Scheduler(clock=clk, enable_preemption=False, fault_injector=fi,
                  parallel=ParallelConfig(mesh=8),
                  recovery=RecoveryConfig(device_reset_limit=1,
                                          device_cooloff_s=5.0))
    s.on_node_add(make_node("n0", cpu_milli=64000, pods=200))
    modes, recs = [], []
    for i in range(3):
        s.on_pod_add(make_pod(f"q{i}", cpu_milli=10))
        res = s.schedule_cycle()
        assert res.scheduled == 1
        modes.append(res.snapshot_mode)
        recs.append(s.obs.recorder.records()[-1])
        clk.advance(6)
    # cycle 0: budget exhausted -> host (single-device) fallback;
    # cycles 1-2: cooloff expired, injector spent -> sharded resident
    assert modes == ["host", "full", "full"]
    # the flight record's mesh flag is truthful PER CYCLE: the cooloff
    # cycle ran single-device even though the scheduler owns a mesh
    assert [r.mesh for r in recs] == [0, 8, 8]
    assert s.metrics.recovery_device_resets.value() == 2
    _, dev, _ = s.cache.device_snapshot()
    assert int(dev.allocatable.sharding.mesh.devices.size) == 8


def test_reconcile_replaces_resident_onto_mesh():
    """Takeover reconciliation drops + rebuilds the resident table —
    under a mesh it must come back SHARDED (the PR-8 recovery path is
    mesh-aware by construction: one re-place seam in the cache)."""
    s = Scheduler(clock=FakeClock(), enable_preemption=False,
                  parallel=ParallelConfig(mesh=4))
    s.on_node_add(make_node("n0"))
    s.on_pod_add(make_pod("p0"))
    s.schedule_cycle()
    s.reconcile([])
    s.on_pod_add(make_pod("p1"))
    res = s.schedule_cycle()
    assert res.snapshot_mode == "full"  # resident was dropped
    _, dev, _ = s.cache.device_snapshot()
    assert int(dev.allocatable.sharding.mesh.devices.size) == 4


# ---------------------------------------------------------------------------
# bench_compare mesh gates (contract test)
# ---------------------------------------------------------------------------


def _load_bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mesh_record(pps=3000.0, eff=0.999, bpp=4.5):
    return {
        "headline": {"pods_per_sec": pps, "readback_bytes_per_pod": bpp},
        "weak_scaling": [
            {"devices": 1, "pods_per_sec": pps / 4,
             "model_efficiency": 1.0, "readback_bytes_per_pod": bpp},
            {"devices": 8, "pods_per_sec": pps,
             "model_efficiency": eff, "readback_bytes_per_pod": bpp},
        ],
    }


def test_bench_compare_mesh_gates():
    bc = _load_bench_compare()
    ok = bc.compare_mesh(_mesh_record(), _mesh_record(), 0.10)
    assert ok["regressions"] == []
    # headline throughput drop past the threshold regresses
    bad = bc.compare_mesh(_mesh_record(), _mesh_record(pps=2000.0), 0.10)
    assert any(r["check"] == "mesh.headline.pods_per_sec"
               for r in bad["regressions"])
    # weak-scaling efficiency at the widest point regresses
    bad = bc.compare_mesh(_mesh_record(), _mesh_record(eff=0.5), 0.10)
    assert any("model_efficiency" in r["check"] for r in bad["regressions"])
    # the absolute readback budget fires on the NEW record alone — a
    # (P, N)-sized gather would be ~N x over it
    bad = bc.compare_mesh(_mesh_record(), _mesh_record(bpp=4096.0), 0.10)
    assert any(r["check"].endswith("readback_budget")
               for r in bad["regressions"])
    # absence-tolerant: an empty prev record warns, never fails
    warnonly = bc.compare_mesh({}, _mesh_record(), 0.10)
    assert not [r for r in warnonly["regressions"]
                if not r["check"].endswith("readback_budget")]
