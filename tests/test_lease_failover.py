"""Hub-backed leader election — HA mediated by the control plane itself.

The reference's production leader election CASes a coordination Lease
API object through the apiserver (resourcelock/leaselock.go, chosen via
interface.go:100); failover is therefore observable in the object store
and subject to the same optimistic concurrency as every other write.
These tests pin that behavior for :class:`LeaseLock` + the hub, up to a
full scheduler failover with zero double-binds (VERDICT r3 item 8)."""

from kubernetes_tpu.config import LeaderElectionConfig
from kubernetes_tpu.leaderelection import (
    LeaderElectionRecord,
    LeaderElector,
    LeaseLock,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.sim import HollowCluster, Reflector
from kubernetes_tpu.testing import make_node, make_pod


def test_lease_lock_cas_through_hub():
    hub = HollowCluster(seed=9)
    clk = hub.clock
    cfg = LeaderElectionConfig(lease_duration_s=15)
    a = LeaderElector("a", LeaseLock(hub), cfg, clk)
    b = LeaderElector("b", LeaseLock(hub), cfg, clk)
    assert a.tick() and a.is_leader()
    assert not b.tick()
    rec, rv = hub.get_lease("kube-system", "kube-scheduler")
    assert rec.holder_identity == "a" and rv > 0
    clk.advance(10)
    assert a.tick()  # renew CASes a new record -> rv bumps
    _, rv2 = hub.get_lease("kube-system", "kube-scheduler")
    assert rv2 > rv
    assert not b.tick()  # b observes the renewal (expiry clock restarts)
    # a dies; b steals only after the lease expires from ITS observation
    clk.advance(14)
    assert not b.tick()
    clk.advance(2)
    assert b.tick() and b.is_leader()
    rec3, _ = hub.get_lease("kube-system", "kube-scheduler")
    assert rec3.holder_identity == "b" and rec3.leader_transitions == 1


def test_lease_cas_interleaved_single_winner():
    """Split-brain guard: two candidates that both observed rv N race
    the CAS; exactly one wins (the atomicity the apiserver provides and
    hub.cas_lease reproduces under the hub lock)."""
    hub = HollowCluster(seed=10)
    la, lb = LeaseLock(hub), LeaseLock(hub)
    assert la.get() is None and lb.get() is None  # both observe rv 0
    ra = LeaderElectionRecord(holder_identity="a", renew_time=1.0)
    rb = LeaderElectionRecord(holder_identity="b", renew_time=1.0)
    assert la.create_or_update(ra, None)
    assert not lb.create_or_update(rb, None)  # stale rv -> conflict
    rec, _ = hub.get_lease("kube-system", "kube-scheduler")
    assert rec.holder_identity == "a"


def test_leases_observable_over_rest_and_ktpu(capsys):
    """HA state is API-observable: the Lease the electors CAS shows up
    under /apis/coordination.k8s.io/v1 (group discovery included) and in
    `ktpu get leases` — the operator's `kubectl get leases -n
    kube-system` loop."""
    import http.client
    import json

    from kubernetes_tpu.kubectl import main as ktpu
    from kubernetes_tpu.restapi import RestServer

    hub = HollowCluster(seed=12)
    cfg = LeaderElectionConfig(lease_duration_s=15)
    a = LeaderElector("sched-a", LeaseLock(hub), cfg, hub.clock)
    assert a.tick()
    srv = RestServer(hub)
    port = srv.serve()
    try:
        def get(path):
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            c.request("GET", path)
            r = c.getresponse()
            d = json.loads(r.read())
            c.close()
            return r.status, d

        code, doc = get("/apis")
        assert code == 200
        assert "coordination.k8s.io" in {g["name"] for g in doc["groups"]}
        code, doc = get("/apis/coordination.k8s.io/v1/namespaces/"
                        "kube-system/leases/kube-scheduler")
        assert code == 200
        assert doc["spec"]["holderIdentity"] == "sched-a"
        rv1 = int(doc["metadata"]["resourceVersion"])
        hub.clock.advance(5)
        a.tick()  # renew -> rv bumps, visible over the API
        code, doc = get("/apis/coordination.k8s.io/v1/leases")
        assert code == 200 and len(doc["items"]) == 1
        assert int(doc["items"][0]["metadata"]["resourceVersion"]) > rv1

        rc = ktpu(["--api-server", f"127.0.0.1:{port}", "get", "leases",
                   "-n", "kube-system"])
        out = capsys.readouterr().out
        assert rc == 0 and "sched-a" in out and "kube-scheduler" in out
    finally:
        srv.close()


def test_scheduler_failover_no_double_binds_queue_continuity():
    """Kill the leader mid-run; the standby acquires the Lease through
    the hub and finishes the queue. Every pod binds exactly once and
    pods created before the failover are not lost."""
    hub = HollowCluster(seed=11)
    for i in range(4):
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))

    clk = hub.clock
    cfg = LeaderElectionConfig(
        lease_duration_s=15, renew_deadline_s=10, retry_period_s=2
    )

    class Agent:
        """One HA scheduler replica: elector + reflector-fed scheduler
        binding through the hub (app/server.go:261 — the scheduling loop
        runs only while leading)."""

        def __init__(self, name):
            self.sched = Scheduler(binder=hub.binder, clock=clk,
                                   enable_preemption=False)
            self.reflector = Reflector(hub, self.sched)
            self.reflector.list_and_watch()
            self.elector = LeaderElector(name, LeaseLock(hub), cfg, clk)
            self.cycles = 0

        def tick(self):
            self.reflector.pump()  # informers run on leaders AND standbys
            if self.elector.tick():
                self.sched.schedule_cycle()
                self.cycles += 1

    a, b = Agent("a"), Agent("b")

    for i in range(6):
        hub.create_pod(make_pod(f"pre{i}", cpu_milli=500))
    for _ in range(3):
        a.tick()
        b.tick()
        clk.advance(2)
    assert a.cycles > 0 and b.cycles == 0  # only the leader schedules
    assert sum(1 for p in hub.truth_pods.values() if p.node_name) == 6

    # pods created while the leader is dying: the standby must pick
    # them up after failover (queue continuity through list+watch)
    for i in range(6):
        hub.create_pod(make_pod(f"mid{i}", cpu_milli=500))
    # 'a' dies (stops ticking). 'b' keeps ticking and takes over once
    # the lease expires from its last observation of a's renew.
    took_over_at = None
    for _ in range(12):
        b.tick()
        if b.elector.is_leader() and took_over_at is None:
            took_over_at = clk()
        clk.advance(2)
    assert took_over_at is not None, "standby never acquired the lease"
    assert b.cycles > 0
    rec, _ = hub.get_lease("kube-system", "kube-scheduler")
    assert rec.holder_identity == "b" and rec.leader_transitions == 1

    # zero double-binds: every pod bound exactly once, CAS conflicts 0
    assert hub.bound_total == 12
    bound = {k: p.node_name for k, p in hub.truth_pods.items()}
    assert all(bound.values()), bound
    assert hub.binder.conflicts == 0
    # queue continuity: the mid-failover pods all landed
    assert all(bound[f"default/mid{i}"] for i in range(6))
    hub.check_consistency()
