"""Service LoadBalancer + route controllers over the cloud seam, and
the v1 ReplicationController riding the ReplicaSet machinery.

References: pkg/controller/service/service_controller.go:293
syncLoadBalancerIfNeeded (+ :632 node inclusion), pkg/controller/route/
route_controller.go:139 reconcile (+ NetworkUnavailable clearing),
pkg/controller/replication/replication_controller.go:58 (RC == RS
behind conversion adapters)."""

import dataclasses

from kubernetes_tpu.cloud import FakeCloud, Instance
from kubernetes_tpu.proxy import Service, ServicePort
from kubernetes_tpu.sim import HollowCluster
from kubernetes_tpu.testing import make_node, make_pod


def _cloud_hub(n_nodes=2):
    hub = HollowCluster(seed=17, scheduler_kw={"enable_preemption": False})
    cloud = FakeCloud()
    for i in range(n_nodes):
        cloud.add_instance(Instance(f"n{i}", zone="z0", region="r0"))
        hub.add_node(make_node(f"n{i}", cpu_milli=4000))
    hub.attach_cloud(cloud)
    return hub, cloud


def test_lb_service_gets_ingress_over_ready_nodes():
    hub, cloud = _cloud_hub()
    hub.add_service(Service(
        "web", selector={"app": "web"}, type="LoadBalancer",
        ports=(ServicePort(port=80, target_port=8080),)))
    hub.create_pod(make_pod("w1", cpu_milli=100, labels={"app": "web"}))
    hub.step()
    svc = hub.services["default/web"]
    assert svc.load_balancer_ingress.startswith("192.0.2.")
    lb = cloud.load_balancers["default/web"]
    assert lb["nodes"] == ("n0", "n1")
    hub.check_consistency()


def test_lb_backend_set_tracks_node_membership():
    """nodeSyncLoop: cordoning a node removes it from every balancer's
    backend set on the next pass."""
    hub, cloud = _cloud_hub()
    hub.add_service(Service("web", selector={"app": "web"},
                            type="LoadBalancer"))
    hub.step()
    assert cloud.load_balancers["default/web"]["nodes"] == ("n0", "n1")
    nd = hub.truth_nodes["n0"]
    hub._update_node(dataclasses.replace(nd, unschedulable=True))
    hub.step()
    assert cloud.load_balancers["default/web"]["nodes"] == ("n1",)


def test_lb_torn_down_on_delete_and_type_change():
    hub, cloud = _cloud_hub()
    hub.add_service(Service("a", selector={"x": "1"}, type="LoadBalancer"))
    hub.add_service(Service("b", selector={"x": "2"}, type="LoadBalancer"))
    hub.step()
    assert set(cloud.load_balancers) == {"default/a", "default/b"}
    hub.delete_service("default/a")
    hub.services["default/b"].type = "ClusterIP"
    hub.step()
    assert cloud.load_balancers == {}
    assert hub.services["default/b"].load_balancer_ingress == ""


def test_routes_follow_pod_cidrs_and_clear_network_condition():
    """Every podCIDR node gets a cloud route; the route's creation
    clears NetworkUnavailable; a deleted node's route is withdrawn."""
    hub, cloud = _cloud_hub()
    # nodes register network-unavailable until routes exist
    for name in list(hub.truth_nodes):
        nd = hub.truth_nodes[name]
        hub._update_node(dataclasses.replace(
            nd, conditions=dataclasses.replace(
                nd.conditions, network_unavailable=True)))
    hub.step()  # nodeipam assigns podCIDRs
    hub.step()  # route controller installs on the next pass
    want = {n: nd.pod_cidr for n, nd in hub.truth_nodes.items()}
    assert cloud.list_routes("ktpu") == want
    assert all(not nd.conditions.network_unavailable
               for nd in hub.truth_nodes.values())
    hub.remove_node("n1")
    hub.step()
    assert "n1" not in cloud.list_routes("ktpu")


def test_route_create_failure_raises_network_unavailable():
    """A node without a working route must carry NetworkUnavailable
    (route_controller.go:222 updateNetworkingCondition) — the
    CheckNodeCondition predicate keeps pods off it; recovery clears."""
    hub, cloud = _cloud_hub()
    cloud.fail_routes = True
    hub.step()  # nodeipam assigns podCIDRs
    hub.step()  # route pass attempts creates and fails
    assert hub.route_controller.create_failures > 0
    assert all(nd.conditions.network_unavailable
               for nd in hub.truth_nodes.values())
    cloud.fail_routes = False
    hub.step()
    assert cloud.list_routes("ktpu")  # retried and installed
    assert all(not nd.conditions.network_unavailable
               for nd in hub.truth_nodes.values())
    # the failure was recorded as a Warning event on the node
    assert any(ev.reason == "FailedToCreateRoute"
               and ev.type == "Warning"
               for ev in hub.events_v1.values())


def test_replication_controller_keeps_replicas():
    hub = HollowCluster(seed=23, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000, pods=16))
    rc = hub.add_replication_controller("rc-a", replicas=3)
    for _ in range(3):
        hub.step()
    assert len(rc.live) == 3
    pods = [hub.truth_pods[k] for k in rc.live]
    assert all(p.owner_refs[0].kind == "ReplicationController"
               for p in pods)
    assert all(p.labels.get("rc") == "rc-a" for p in pods)
    # a killed pod is replaced with a fresh uid
    victim = next(iter(rc.live))
    hub.delete_pod(victim)
    hub.step()
    assert len(rc.live) == 3
    hub.check_consistency()


def test_replication_controller_cascade_on_delete():
    """RC gone -> its pods cascade through the ownerRef GC graph."""
    hub = HollowCluster(seed=29, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000, pods=16))
    hub.add_replication_controller("rc-a", replicas=2)
    for _ in range(2):
        hub.step()
    assert sum(1 for p in hub.truth_pods.values()
               if p.labels.get("rc") == "rc-a") == 2
    del hub.replication_controllers["rc-a"]
    hub.step()
    assert not any(p.labels.get("rc") == "rc-a"
                   for p in hub.truth_pods.values())
    hub.check_consistency()


def test_rc_and_rs_same_name_do_not_collide():
    """Separate registries + kind-keyed GC: an RS and an RC sharing a
    name own their pods independently."""
    from kubernetes_tpu.sim import ReplicaSet

    hub = HollowCluster(seed=31, scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=8000, pods=32))
    hub.replicasets["twin"] = ReplicaSet("twin", 2)
    hub.add_replication_controller("twin", replicas=2)
    for _ in range(2):
        hub.step()
    rs_pods = [k for k, p in hub.truth_pods.items()
               if p.owner_refs and p.owner_refs[0].kind == "ReplicaSet"]
    rc_pods = [k for k, p in hub.truth_pods.items()
               if p.owner_refs
               and p.owner_refs[0].kind == "ReplicationController"]
    assert len(rs_pods) == 2 and len(rc_pods) == 2
    del hub.replication_controllers["twin"]
    hub.step()
    # only the RC's pods cascaded
    assert all(k in hub.truth_pods for k in rs_pods)
    assert not any(k in hub.truth_pods for k in rc_pods)


def test_cluster_scoped_node_events_carry_empty_namespace():
    """ADVICE r5 low (cloud.py): events about cluster-scoped Nodes must
    record an EMPTY involvedObject.namespace (the reference's shape for
    cluster-scoped involved objects), not a fabricated 'default' — so
    involvedObject.namespace field selectors match kubectl expectations."""
    from kubernetes_tpu.api.selectors import event_fields

    hub, cloud = _cloud_hub()
    cloud.fail_routes = True
    hub.step()
    hub.step()
    evs = [(k, ev) for k, ev in hub.events_v1.items()
           if ev.reason == "FailedToCreateRoute"]
    assert evs
    for key, ev in evs:
        assert ev.involved_kind == "Node"
        ns, _, name = ev.object_key.partition("/")
        assert ns == "" and name in hub.truth_nodes
        fields = event_fields(key, ev)
        assert fields["involvedObject.namespace"] == ""
        assert fields["involvedObject.name"] == name
