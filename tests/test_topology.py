"""Differential tests for inter-pod affinity + topology spread — the analog
of predicates_test.go (TestInterPodAffinity*, TestEvenPodsSpreadPredicate)
and priorities' interpod_affinity_test.go / even_pods_spread_test.go, run as
device-vs-oracle comparisons over randomized clusters."""

import random

import numpy as np

import pyref
from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.ops.arrays import (
    nodes_to_device,
    pods_to_device,
    selectors_to_device,
    topology_to_device,
)
from kubernetes_tpu.ops.predicates import BIT, run_predicates
from kubernetes_tpu.ops.topology import (
    even_pods_spread_score,
    inter_pod_affinity_score,
)
from kubernetes_tpu.ops.predicates import selector_program_match
from kubernetes_tpu.snapshot import SnapshotPacker
from kubernetes_tpu.testing import make_node, make_pod

HOSTNAME = "kubernetes.io/hostname"
ZONE = "zone"


def build(nodes, scheduled, pending):
    pk = SnapshotPacker()
    for p in list(scheduled) + list(pending):
        pk.intern_pod(p)
    nt = pk.pack_nodes(nodes, scheduled)
    pt = pk.pack_pods(pending)
    st = pk.pack_selector_tables()
    tt = pk.pack_topology_tables()
    dn, dp = nodes_to_device(nt), pods_to_device(pt)
    ds, dt = selectors_to_device(st), topology_to_device(tt)
    return dn, dp, ds, dt


def by_node(nodes, scheduled):
    d = {nd.name: [] for nd in nodes}
    for p in scheduled:
        if p.node_name in d:
            d[p.node_name].append(p)
    return d


def oracle_mask(pending, nodes, node_pods):
    rows = []
    for p in pending:
        rows.append([
            pyref.feasible(p, nd, node_pods[nd.name])
            and pyref.inter_pod_affinity_feasible(p, nd, nodes, node_pods)
            and pyref.even_pods_spread_feasible(p, nd, nodes, node_pods)
            for nd in nodes
        ])
    return np.asarray(rows)


def term(key, labels, namespaces=()):
    return PodAffinityTerm(
        label_selector=LabelSelector(match_labels=dict(labels)),
        topology_key=key,
        namespaces=tuple(namespaces),
    )


def random_affinity_cluster(rng, n_nodes=10, n_sched=20, n_pending=12):
    nodes = [
        make_node(f"n{i}", labels={ZONE: f"z{i % 3}"})
        for i in range(n_nodes)
    ]
    apps = ["web", "db", "cache"]
    scheduled = []
    for i in range(n_sched):
        app = rng.choice(apps)
        p = make_pod(
            f"s{i}",
            node_name=f"n{rng.randrange(n_nodes)}",
            labels={"app": app},
            namespace=rng.choice(["default", "other"]),
        )
        r = rng.random()
        if r < 0.25:
            # existing pod with required anti-affinity (symmetry pressure)
            p.affinity = Affinity(
                pod_anti_affinity_required=(term(rng.choice([HOSTNAME, ZONE]), {"app": app}),)
            )
        elif r < 0.4:
            p.affinity = Affinity(
                pod_affinity_required=(term(ZONE, {"app": rng.choice(apps)}),)
            )
        elif r < 0.55:
            p.affinity = Affinity(
                pod_affinity_preferred=(
                    WeightedPodAffinityTerm(rng.choice([1, 5]), term(ZONE, {"app": rng.choice(apps)})),
                ),
                pod_anti_affinity_preferred=(
                    WeightedPodAffinityTerm(rng.choice([1, 3]), term(HOSTNAME, {"app": app})),
                ),
            )
        scheduled.append(p)
    pending = []
    for i in range(n_pending):
        app = rng.choice(apps)
        p = make_pod(f"p{i}", labels={"app": app}, namespace=rng.choice(["default", "other"]))
        r = rng.random()
        if r < 0.25:
            p.affinity = Affinity(
                pod_affinity_required=(term(ZONE, {"app": rng.choice(apps)}),)
            )
        elif r < 0.45:
            p.affinity = Affinity(
                pod_anti_affinity_required=(term(rng.choice([HOSTNAME, ZONE]), {"app": app}),)
            )
        elif r < 0.6:
            p.affinity = Affinity(
                pod_affinity_required=(term(ZONE, {"app": app}),),  # maybe self-match
                pod_anti_affinity_required=(term(HOSTNAME, {"app": app}),),
            )
        elif r < 0.8:
            p.affinity = Affinity(
                pod_affinity_preferred=(
                    WeightedPodAffinityTerm(rng.choice([2, 7]), term(ZONE, {"app": rng.choice(apps)})),
                ),
                pod_anti_affinity_preferred=(
                    WeightedPodAffinityTerm(rng.choice([1, 4]), term(ZONE, {"app": rng.choice(apps)})),
                ),
            )
        pending.append(p)
    return nodes, scheduled, pending


def test_inter_pod_affinity_mask_differential():
    for seed in range(8):
        rng = random.Random(500 + seed)
        nodes, scheduled, pending = random_affinity_cluster(rng)
        dn, dp, ds, dt = build(nodes, scheduled, pending)
        got = np.asarray(run_predicates(dp, dn, ds, dt).mask)[: len(pending), : len(nodes)]
        want = oracle_mask(pending, nodes, by_node(nodes, scheduled))
        if not (got == want).all():
            i, j = np.argwhere(got != want)[0]
            reasons = np.asarray(run_predicates(dp, dn, ds, dt).reasons)[i, j]
            raise AssertionError(
                f"seed {seed}: pod {pending[i].name} node {nodes[j].name}: "
                f"device={got[i,j]} oracle={want[i,j]} reasons={reasons:#x}\n"
                f"pod={pending[i]}"
            )


def test_self_match_first_pod_of_group():
    """A pod with affinity to its own labels must schedule when no matching
    pod exists anywhere (predicates.go:1437)."""
    nodes = [make_node(f"n{i}", labels={ZONE: "z0"}) for i in range(3)]
    lone = make_pod("lone", labels={"app": "solo"})
    lone.affinity = Affinity(pod_affinity_required=(term(ZONE, {"app": "solo"}),))
    stranger = make_pod("stranger", labels={"app": "x"})
    stranger.affinity = Affinity(pod_affinity_required=(term(ZONE, {"app": "nonexistent"}),))
    dn, dp, ds, dt = build(nodes, [], [lone, stranger])
    mask = np.asarray(run_predicates(dp, dn, ds, dt).mask)
    assert mask[0, :3].all()  # self-match escape
    assert not mask[1, :3].any()  # no self-match, no existing match


def test_existing_anti_affinity_symmetry():
    """An existing pod with required anti-affinity against app=web on a zone
    keeps web pods out of that whole zone."""
    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 2}"}) for i in range(4)]
    guard = make_pod("guard", labels={"app": "guard"}, node_name="n0")
    guard.affinity = Affinity(pod_anti_affinity_required=(term(ZONE, {"app": "web"}),))
    web = make_pod("web", labels={"app": "web"})
    other = make_pod("other", labels={"app": "db"})
    dn, dp, ds, dt = build(nodes, [guard], [web, other])
    mask = np.asarray(run_predicates(dp, dn, ds, dt).mask)
    # z0 = n0, n2 blocked for web; z1 = n1, n3 open
    assert not mask[0, 0] and not mask[0, 2]
    assert mask[0, 1] and mask[0, 3]
    assert mask[1, :4].all()


def random_spread_cluster(rng, n_nodes=9, n_sched=18, n_pending=8):
    nodes = [
        make_node(f"n{i}", labels={ZONE: f"z{i % 3}"})
        for i in range(n_nodes)
    ]
    scheduled = [
        make_pod(
            f"s{i}",
            node_name=f"n{rng.randrange(n_nodes)}",
            labels={"app": rng.choice(["web", "db"])},
            namespace=rng.choice(["default", "other"]),
        )
        for i in range(n_sched)
    ]
    pending = []
    for i in range(n_pending):
        p = make_pod(f"p{i}", labels={"app": "web"})
        cons = []
        if rng.random() < 0.7:
            cons.append(TopologySpreadConstraint(
                max_skew=rng.choice([1, 2]),
                topology_key=rng.choice([ZONE, HOSTNAME]),
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": "web"}),
            ))
        if rng.random() < 0.5:
            cons.append(TopologySpreadConstraint(
                max_skew=1,
                topology_key=ZONE,
                when_unsatisfiable="ScheduleAnyway",
                label_selector=LabelSelector(match_labels={"app": rng.choice(["web", "db"])}),
            ))
        p.topology_spread = tuple(cons)
        if rng.random() < 0.3:
            p.node_selector = {ZONE: rng.choice(["z0", "z1"])}
        pending.append(p)
    return nodes, scheduled, pending


def test_even_pods_spread_mask_differential():
    for seed in range(8):
        rng = random.Random(700 + seed)
        nodes, scheduled, pending = random_spread_cluster(rng)
        dn, dp, ds, dt = build(nodes, scheduled, pending)
        got = np.asarray(run_predicates(dp, dn, ds, dt).mask)[: len(pending), : len(nodes)]
        want = oracle_mask(pending, nodes, by_node(nodes, scheduled))
        if not (got == want).all():
            i, j = np.argwhere(got != want)[0]
            raise AssertionError(
                f"seed {seed}: pod {pending[i].name} node {nodes[j].name}: "
                f"device={got[i,j]} oracle={want[i,j]}\npod={pending[i]}"
            )


def test_interpod_affinity_score_differential():
    for seed in range(6):
        rng = random.Random(900 + seed)
        nodes, scheduled, pending = random_affinity_cluster(rng, n_nodes=8, n_sched=14, n_pending=8)
        dn, dp, ds, dt = build(nodes, scheduled, pending)
        mask = run_predicates(dp, dn, ds, dt).mask
        got = np.asarray(inter_pod_affinity_score(dp, dn, dt, mask))[: len(pending), : len(nodes)]
        node_pods = by_node(nodes, scheduled)
        m = np.asarray(mask)[: len(pending), : len(nodes)]
        want = np.asarray(
            pyref.interpod_affinity_scores(pending, nodes, node_pods, m), np.float64
        )
        ok = (np.abs(got - want) < 1e-6) | ~m
        if not ok.all():
            i, j = np.argwhere(~ok)[0]
            raise AssertionError(
                f"seed {seed}: pod {pending[i].name} node {nodes[j].name}: "
                f"device={got[i,j]} oracle={want[i,j]}\npod={pending[i]}"
            )


def test_batch_assign_anti_affinity_in_round():
    """Regression: with per_node_cap > 1, mutually anti-affine pods must NOT
    co-locate within one admission round (code-review finding r1)."""
    from kubernetes_tpu.ops.assign import batch_assign, greedy_assign

    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 2}"}) for i in range(4)]
    pend = []
    for i in range(4):
        p = make_pod(f"x{i}", labels={"app": "x"}, cpu_milli=100, memory=2**28)
        p.affinity = Affinity(
            pod_anti_affinity_required=(term(HOSTNAME, {"app": "x"}),)
        )
        pend.append(p)
    dn, dp, ds, dt = build(nodes, [], pend)
    for cap in (1, 4):
        a, _, _ = batch_assign(dp, dn, ds, per_node_cap=cap, topo=dt)
        a = np.asarray(a)[:4]
        placed = a[a >= 0]
        assert len(placed) == 4 and len(set(placed.tolist())) == 4, (cap, a)
    g, _ = greedy_assign(dp, dn, ds, topo=dt)
    g = np.asarray(g)[:4]
    assert len(set(g[g >= 0].tolist())) == len(g[g >= 0]) == 4


def test_batch_assign_zone_anti_affinity_in_round():
    """Zone-scope anti-affinity: same-round admissions to *different nodes*
    of one zone must also be serialized (violation possible even at
    per_node_cap=1)."""
    from kubernetes_tpu.ops.assign import batch_assign

    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 2}"}) for i in range(6)]
    pend = []
    for i in range(4):
        p = make_pod(f"x{i}", labels={"app": "x"}, cpu_milli=100, memory=2**28)
        p.affinity = Affinity(
            pod_anti_affinity_required=(term(ZONE, {"app": "x"}),)
        )
        pend.append(p)
    dn, dp, ds, dt = build(nodes, [], pend)
    a, _, _ = batch_assign(dp, dn, ds, per_node_cap=4, topo=dt)
    a = np.asarray(a)[:4]
    placed = a[a >= 0]
    zones = [int(n) % 2 for n in placed]
    assert len(placed) == 2 and len(set(zones)) == 2, a


def test_batch_assign_spread_in_round():
    """Hard spread maxSkew=1 must hold within rounds at per_node_cap > 1."""
    from kubernetes_tpu.ops.assign import batch_assign

    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 3}"}) for i in range(9)]
    pend = []
    for i in range(9):
        p = make_pod(f"s{i}", labels={"app": "web"}, cpu_milli=100, memory=2**28)
        p.topology_spread = (TopologySpreadConstraint(
            1, ZONE, "DoNotSchedule", LabelSelector(match_labels={"app": "web"})
        ),)
        pend.append(p)
    dn, dp, ds, dt = build(nodes, [], pend)
    a, _, _ = batch_assign(dp, dn, ds, per_node_cap=8, topo=dt)
    a = np.asarray(a)[:9]
    assert (a >= 0).all(), a
    zc = {}
    for n in a:
        zc[int(n) % 3] = zc.get(int(n) % 3, 0) + 1
    assert max(zc.values()) - min(zc.values()) <= 1, zc


def test_batch_assign_single_escapee_per_round():
    """Two first-pods-of-a-group (self-match escape) must land in the SAME
    topology group — the second may not escape in the same round."""
    from kubernetes_tpu.ops.assign import batch_assign

    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 3}"}) for i in range(6)]
    pend = []
    for i in range(3):
        p = make_pod(f"g{i}", labels={"app": "gang"}, cpu_milli=100, memory=2**28)
        p.affinity = Affinity(
            pod_affinity_required=(term(ZONE, {"app": "gang"}),)
        )
        pend.append(p)
    dn, dp, ds, dt = build(nodes, [], pend)
    a, _, _ = batch_assign(dp, dn, ds, per_node_cap=4, topo=dt)
    a = np.asarray(a)[:3]
    assert (a >= 0).all(), a
    zones = {int(n) % 3 for n in a}
    assert len(zones) == 1, f"gang split across zones: {a}"


def test_even_pods_spread_score_differential():
    for seed in range(6):
        rng = random.Random(1100 + seed)
        nodes, scheduled, pending = random_spread_cluster(rng)
        dn, dp, ds, dt = build(nodes, scheduled, pending)
        mask = run_predicates(dp, dn, ds, dt).mask
        sel_match = selector_program_match(ds, dn)
        got = np.asarray(even_pods_spread_score(dp, dn, dt, sel_match, mask))[
            : len(pending), : len(nodes)
        ]
        node_pods = by_node(nodes, scheduled)
        m = np.asarray(mask)[: len(pending), : len(nodes)]
        want = np.asarray(
            pyref.even_pods_spread_scores(pending, nodes, node_pods, m), np.float64
        )
        ok = (np.abs(got - want) < 1e-6) | ~m
        if not ok.all():
            i, j = np.argwhere(~ok)[0]
            raise AssertionError(
                f"seed {seed}: pod {pending[i].name} node {nodes[j].name}: "
                f"device={got[i,j]} oracle={want[i,j]}\npod={pending[i]}"
            )


def test_padding_rows_do_not_alias_matcher_zero():
    """Regression (r3 profiling): zero-filled padding rows in the at/st
    universes aliased (key 0, matcher 0) — real ids — so sensitive_keys()
    flagged every soft-spread/affinity pod and the batch solver serialized
    admissions to one per topology pair per round (206 rounds for a
    2048-pod soft-spread batch instead of 2)."""
    from kubernetes_tpu.models.cluster import (
        make_nodes,
        make_pods,
        make_spread_constraint_pods,
    )
    from kubernetes_tpu.ops.topology import sensitive_keys

    nodes = make_nodes(16, zones=4)
    existing = make_pods(8, "old", assigned_round_robin_over=16)
    pending = make_spread_constraint_pods(32, hard=False)  # soft only
    dn, dp, ds, dt = build(nodes, existing, pending)
    sens = np.asarray(sensitive_keys(dp, dt, dn.topo_pair_id.shape[1]))
    assert not sens.any(), "soft-only spread pods must not be serialized"
    # and the batch places everything fast (2 rounds, not one per pair)
    from kubernetes_tpu.ops.assign import batch_assign

    a, _, rounds = batch_assign(dp, dn, ds, topo=dt, per_node_cap=8)
    assert int((np.asarray(a)[:32] >= 0).sum()) == 32
    assert int(rounds) <= 4


def test_topology_gates_exact_and_disarm():
    """Batch-scoped topology gates (no_pod_affinity / no_spread static
    keys): on a CLEAN batch whose packer universe has seen affinity
    before (the monotonic-dt case long-lived drivers hit), gated and
    ungated passes must agree bit-for-bit; any affinity/spread/symmetry
    evidence must disarm the corresponding gate."""
    from kubernetes_tpu.ops.priorities import empty_priorities
    from kubernetes_tpu.testing import make_pod as mk

    # universe polluted by an affinity pod that is NOT in this batch/cluster
    ghost = mk("ghost", labels={"app": "x"})
    ghost.affinity = Affinity(pod_affinity_required=(term(ZONE, {"app": "x"}),))
    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i % 2}"}) for i in range(6)]
    scheduled = [mk(f"s{i}", node_name=f"n{i % 6}", labels={"app": "db"})
                 for i in range(4)]
    pending = [mk(f"p{i}", labels={"app": "web"}) for i in range(5)]

    pk = SnapshotPacker()
    pk.intern_pod(ghost)  # grows the topology universe; dt stays non-None
    for p in scheduled + pending:
        pk.intern_pod(p)
    nt = pk.pack_nodes(nodes, scheduled)
    pt = pk.pack_pods(pending)
    dn, dp = nodes_to_device(nt), pods_to_device(pt)
    ds = selectors_to_device(pk.pack_selector_tables())
    dt = topology_to_device(pk.pack_topology_tables())
    assert dt is not None

    gate = empty_priorities(nt, pt)
    assert "InterPodAffinityPriority" in gate
    assert "EvenPodsSpreadPriority" in gate

    full = run_predicates(dp, dn, ds, dt)
    gated = run_predicates(dp, dn, ds, dt, no_pod_affinity=True,
                           no_spread=True)
    assert (np.asarray(full.mask) == np.asarray(gated.mask)).all()
    assert (np.asarray(full.reasons) == np.asarray(gated.reasons)).all()

    # disarm: an existing pod with required anti-affinity (symmetry
    # evidence lives node-side) must disarm the affinity gate even though
    # no PENDING pod declares anything
    hermit = mk("hermit", node_name="n0", labels={"app": "db"})
    hermit.affinity = Affinity(
        pod_anti_affinity_required=(term(ZONE, {"app": "web"}),))
    pk2 = SnapshotPacker()
    for p in [hermit] + pending:
        pk2.intern_pod(p)
    nt2 = pk2.pack_nodes(nodes, [hermit])
    pt2 = pk2.pack_pods(pending)
    gate2 = empty_priorities(nt2, pt2)
    assert "InterPodAffinityPriority" not in gate2

    # disarm: a pending pod with a spread constraint (packed column)
    spready = mk("sp", labels={"app": "web"})
    spready.topology_spread = (TopologySpreadConstraint(
        max_skew=1, topology_key=ZONE,
        label_selector=LabelSelector(match_labels={"app": "web"})),)
    pksp = SnapshotPacker()
    for p in pending + [spready]:
        pksp.intern_pod(p)
    gate3 = empty_priorities(pksp.pack_nodes(nodes, []),
                             pksp.pack_pods(pending + [spready]))
    assert "EvenPodsSpreadPriority" not in gate3

    # disarm: a pending pod with preferred affinity
    chatty = mk("ch", labels={"app": "web"})
    chatty.affinity = Affinity(pod_affinity_preferred=(
        WeightedPodAffinityTerm(1, term(ZONE, {"app": "web"})),))
    pk3 = SnapshotPacker()
    for p in pending + [chatty]:
        pk3.intern_pod(p)
    nt3 = pk3.pack_nodes(nodes, [])
    pt3 = pk3.pack_pods(pending + [chatty])
    assert "InterPodAffinityPriority" not in empty_priorities(nt3, pt3)
