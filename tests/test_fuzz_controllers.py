"""Control-plane fuzz: random mixes of every hollow controller under
churn, flaky binds, delayed watch events, competing writers, and node
outages — settled state must satisfy the consistency oracle and each
controller's own invariant. The control-plane counterpart of
tests/test_fuzz_differential.py (SURVEY §4 implication d: hollow-node
style simulation for end-to-end dynamics), shaped like the reference's
integration-tier soak tests rather than any single table."""

import os
import random

from kubernetes_tpu.sim import (
    CronJob,
    DaemonSet,
    Deployment,
    HollowCluster,
    HorizontalPodAutoscaler,
    Job,
    ReplicaSet,
    StatefulSet,
)
from kubernetes_tpu.testing import make_node

N_SEEDS = int(os.environ.get("CONTROLLER_FUZZ_SEEDS", 25))


def build_random_cluster(rng, seed):
    hub = HollowCluster(
        seed=seed,
        bind_fail_rate=rng.choice([0.0, 0.05]),
        event_delay_ticks=rng.choice([0, 1]),
        competing_bind_rate=rng.choice([0.0, 0.1]),
        scheduler_kw={"enable_preemption": False},
    )
    zones = ["za", "zb"]
    n_nodes = rng.randrange(4, 9)
    for i in range(n_nodes):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000, memory=16 * 2**30,
                               zone=rng.choice(zones)))
    # random controller mix
    if rng.random() < 0.8:
        hub.add_deployment(Deployment("web", replicas=rng.randrange(2, 8)))
    if rng.random() < 0.5:
        hub.add_replicaset(ReplicaSet("raw", replicas=rng.randrange(1, 5),
                                      cpu_milli=300))
    if rng.random() < 0.6:
        hub.add_daemonset(DaemonSet("agent"))
    if rng.random() < 0.6:
        hub.add_statefulset(StatefulSet("db", replicas=rng.randrange(2, 5)))
    if rng.random() < 0.5:
        hub.add_job(Job("batch", completions=rng.randrange(2, 6),
                        parallelism=2, duration_s=20.0))
    if rng.random() < 0.5:
        hub.add_cronjob(CronJob("cron", every_s=rng.choice([30.0, 45.0]),
                                duration_s=15.0,
                                concurrency=rng.choice(
                                    ["Allow", "Forbid", "Replace"])))
    if "web" in hub.deployments and rng.random() < 0.5:
        util = {"u": rng.choice([0.3, 0.5, 1.0])}
        hub.add_hpa(HorizontalPodAutoscaler(
            "web-hpa", "web", min_replicas=2, max_replicas=8,
            target_utilization=0.5, load_fn=lambda: util["u"]))
        hub._fuzz_util = util  # mutated mid-run below
    return hub


def check_controller_invariants(hub):
    """Each controller's own contract at a settled state."""
    # deployments own an RS sized to spec
    for d in hub.deployments.values():
        rs = hub.replicasets[d.rs_name()]
        assert rs.replicas == d.replicas
    # replicasets: exactly `replicas` live pods tracked AND in truth
    for rs in hub.replicasets.values():
        assert len(rs.live) == rs.replicas, (rs.name, len(rs.live))
        for key in rs.live:
            assert key in hub.truth_pods
    # daemonsets: one pod per keep-eligible node, each on its pinned node
    for ds in hub.daemonsets.values():
        placed = {}
        for key, node_name in ds.live.items():
            p = hub.truth_pods[key]
            if p.node_name:
                assert p.node_name == node_name, (key, p.node_name, node_name)
            placed[node_name] = placed.get(node_name, 0) + 1
        assert all(v == 1 for v in placed.values())
        for nd in hub.truth_nodes.values():
            if ds.can_place(nd):
                assert nd.name in placed, f"daemon missing on {nd.name}"
    # statefulsets: contiguous ordinals 0..replicas-1 once settled
    for ss in hub.statefulsets.values():
        ords = sorted(
            int(p.name.rsplit("-", 1)[1])
            for p in hub.truth_pods.values()
            if p.labels.get("ss") == ss.name
        )
        assert ords == list(range(ss.replicas)), (ss.name, ords)
    # cronjobs: history bounded; spawned jobs exist
    for cj in hub.cronjobs.values():
        done = [jn for jn in cj.spawned if hub.jobs[jn].done()]
        assert len(done) <= cj.history_limit + 1
        for jn in cj.spawned:
            assert jn in hub.jobs
    # hpa: deployment size within bounds
    for hpa in hub.hpas.values():
        d = hub.deployments.get(hpa.deployment)
        if d is not None:
            assert hpa.min_replicas <= d.replicas <= hpa.max_replicas


def test_controller_fuzz_campaign():
    for seed in range(N_SEEDS):
        rng = random.Random(7000 + seed)
        hub = build_random_cluster(rng, seed)
        try:
            for tick in range(14):
                if tick == 5 and hasattr(hub, "_fuzz_util"):
                    hub._fuzz_util["u"] = rng.choice([0.2, 0.9])
                if tick == 7 and rng.random() < 0.5:
                    hub.churn(kill_pods=rng.randrange(0, 4),
                              flap_nodes=rng.randrange(0, 2))
                if tick == 9 and rng.random() < 0.3 and hub.truth_nodes:
                    victim = rng.choice(sorted(hub.truth_nodes))
                    hub.kill_kubelet(victim)
                if tick == 6 and rng.random() < 0.4:
                    # rolling-update actor (r5): a DS/STS template
                    # update races the same churn everything else does
                    rollables = (list(hub.daemonsets.values())
                                 + list(hub.statefulsets.values()))
                    if rollables:
                        rng.choice(rollables).rollout(
                            cpu_milli=rng.choice([60, 90, 120]))
                hub.step(dt=15.0)
            # settle: quiesce the control plane with no new disruptions
            for _ in range(6):
                hub.step(dt=15.0)
            hub.check_consistency()
            check_controller_invariants(hub)
        except AssertionError as e:
            raise AssertionError(f"seed {seed}: {e}") from e


def test_long_soak_mixed_control_plane():
    """One long-lived cluster (150 ticks ≈ 37 sim-minutes) under
    everything at once — controllers, HPA load swings, cron cadence,
    rolling kubelet outages with recovery, churn — with the consistency
    oracle checked at intervals, not just at the end. Catches slow
    drifts (leaked queue entries, usage creep, history growth) that
    short scenario tests cannot."""
    rng = random.Random(424242)
    hub = HollowCluster(
        seed=424242, bind_fail_rate=0.03, event_delay_ticks=1,
        scheduler_kw={"enable_preemption": False},
    )
    for i in range(10):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000, memory=16 * 2**30,
                               zone=f"z{i % 3}"))
    hub.add_daemonset(DaemonSet("agent"))
    hub.add_deployment(Deployment("web", replicas=4))
    hub.add_statefulset(StatefulSet("db", replicas=3))
    util = {"u": 0.5}
    hub.add_hpa(HorizontalPodAutoscaler("web-hpa", "web", 2, 12,
                                        target_utilization=0.5,
                                        load_fn=lambda: util["u"]))
    hub.add_cronjob(CronJob("cron", every_s=60.0, duration_s=25.0,
                            concurrency="Forbid"))
    down = None
    for tick in range(150):
        if tick % 30 == 10:        # rolling outage
            down = f"n{rng.randrange(10)}"
            hub.kill_kubelet(down)
        if tick % 30 == 25 and down:
            hub.heal_kubelet(down)
            down = None
        if tick % 20 == 15:
            util["u"] = rng.choice([0.2, 0.5, 1.2])
        if tick % 25 == 20:
            hub.churn(kill_pods=rng.randrange(0, 3))
        hub.step(dt=15.0)
        if tick % 25 == 24:
            hub.check_consistency()
    # quiesce and verify the steady state precisely
    if down:
        hub.heal_kubelet(down)
    util["u"] = 0.5
    for _ in range(8):
        hub.step(dt=15.0)
    hub.check_consistency()
    check_controller_invariants(hub)
    # no unbounded growth: watch history compacts to the cursor floor,
    # queues drain, metric of cluster size stays sane
    assert len(hub._history) < 2000
    assert hub.pending_count() <= 2
    assert len(hub.truth_pods) < 120


def test_long_soak_round5_subsystems():
    """Round-5 soak: the identity/cloud/GC controllers under 300 ticks
    of churn on a cloud-attached kubeadm cluster — DS/STS rollouts
    mid-flight, run-to-completion pods against the GC threshold, TTL'd
    jobs on a cadence, CSR issue/expiry, PVC-protection deletes of
    in-use claims, instance termination taking a node (and its routes)
    away — consistency + controller invariants at intervals."""
    from kubernetes_tpu.api.types import (
        BINDING_IMMEDIATE,
        PersistentVolume,
        PersistentVolumeClaim,
        PodVolume,
        StorageClass,
        is_pod_terminated,
    )
    from kubernetes_tpu.bootstrap import init_cluster, join_node
    from kubernetes_tpu.certificates import node_bootstrap_csr
    from kubernetes_tpu.cloud import FakeCloud, Instance
    from kubernetes_tpu.proxy import Service
    from kubernetes_tpu.testing import make_pod

    rng = random.Random(5050)
    hub, token = init_cluster()
    hub.terminated_pod_threshold = 4
    hub.cert_controller.cert_duration_s = 600.0  # certs expire mid-soak
    cloud = FakeCloud()
    hub.attach_cloud(cloud)
    for i in range(8):
        name = f"w{i}"
        cloud.add_instance(Instance(name, zone=f"z{i % 2}"))
        join_node(hub, token, make_node(name, cpu_milli=8000,
                                        memory=16 * 2**30, pods=32))
    hub.add_daemonset(DaemonSet("agent"))
    hub.add_statefulset(StatefulSet("db", replicas=3))
    hub.add_replication_controller("rc-web", replicas=3)
    hub.add_service(Service("web", selector={"rc": "rc-web"},
                            type="LoadBalancer"))
    hub.add_storage_class(StorageClass("std", BINDING_IMMEDIATE))
    hub.add_pv(PersistentVolume("pv-a", kind="gce-pd", handle="a",
                                storage_class="std"))
    hub.add_pvc(PersistentVolumeClaim("data", storage_class="std"))
    hub.create_pod(make_pod("pvc-user", cpu_milli=100,
                            volumes=(PodVolume(pvc="data"),)))

    killed_instance = None
    for tick in range(300):
        if tick % 10 == 3:  # batch work arriving
            hub.create_pod(make_pod(f"batch-{tick}", cpu_milli=100,
                                    run_duration_s=30.0))
        if tick % 40 == 7:  # TTL'd job cadence
            hub.jobs[f"job-{tick}"] = Job(
                f"job-{tick}", completions=2, parallelism=2,
                duration_s=30.0, ttl_seconds_after_finished=120.0)
        if tick % 60 == 13:  # CSR churn under the bootstrap identity
            user = hub.credential_user(token)
            name = f"w{rng.randrange(8)}-{tick}"
            hub.create_csr(node_bootstrap_csr(
                name, username=user.name, groups=user.groups))
        if tick == 80:  # DS rollout mid-soak
            hub.daemonsets["agent"].rollout(cpu_milli=75)
        if tick == 140:  # STS rollout
            hub.statefulsets["db"].rollout(cpu_milli=150)
        if tick == 170:  # delete the in-use PVC: protection must defer
            assert hub.delete_pvc("default/data") is False
        if tick == 180:
            hub.delete_pod("default/pvc-user")  # releases the claim
        if tick == 200 and killed_instance is None:
            killed_instance = f"w{rng.randrange(8)}"
            cloud.terminate(killed_instance)
        if tick % 25 == 20:
            hub.churn(kill_pods=rng.randrange(0, 2))
        hub.step(dt=15.0)
        if tick % 50 == 49:
            hub.check_consistency()

    for _ in range(8):
        hub.step(dt=15.0)
    hub.check_consistency()
    check_controller_invariants(hub)
    # GC threshold held
    terminal = [k for k, p in hub.truth_pods.items()
                if is_pod_terminated(p)]
    assert len(terminal) <= 4
    # protection finalized the released claim; its PV is Available
    assert "default/data" not in hub.pvcs
    assert hub.pvs["pv-a"].claim_ref == ""
    # the terminated instance's node AND route are gone
    assert killed_instance not in hub.truth_nodes
    assert killed_instance not in cloud.list_routes("ktpu")
    # rollouts completed: every daemon/db pod on the current revision
    for p in hub.truth_pods.values():
        if p.labels.get("ds") == "agent":
            assert p.labels.get("rev") == str(
                hub.daemonsets["agent"].template_rev)
        if p.labels.get("ss") == "db":
            assert p.labels.get("rev") == str(
                hub.statefulsets["db"].template_rev)
    # TTL'd jobs age out; CSR cleaner + cert expiry bound the registries
    assert sum(1 for j in hub.jobs.values()
               if j.ttl_seconds_after_finished is not None) <= 2
    assert len(hub.csrs) <= 6
    # LB backend set tracks the live node set
    lb = cloud.load_balancers["default/web"]
    assert set(lb["nodes"]) == set(hub.truth_nodes) - {"control-plane"}
    # bounded growth
    assert len(hub._history) < 2000
    assert len(hub.truth_pods) < 150
