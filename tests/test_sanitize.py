"""Instrumented-lock runtime sanitizer (kubernetes_tpu/sanitize.py).

The static rules (graftlint R9/R10, tests/test_graftlint_rules.py)
prove discipline for acquisitions the linter can see lexically; these
tests prove the runtime half: the acquisition-order graph catches a
deadlock-SHAPED interleaving with plain sequential execution (no live
contention needed), hold budgets run on the injected clock, dynamic
guarded-by declarations are enforced, and the whole thing is a plain
``threading`` lock when unarmed.
"""

from __future__ import annotations

import threading

import pytest

from kubernetes_tpu.sanitize import (
    InstrumentedLock,
    LockSanitizer,
    LockSanitizerConfig,
    assert_held,
    make_lock,
)


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def run_in_thread(fn) -> None:
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


# -- order-cycle detection --------------------------------------------------


def test_two_thread_lock_order_cycle_detected_sequentially():
    """The seeded deadlock shape: thread 1 takes A then B, thread 2
    takes B then A. Nothing ever blocks (the threads run one after the
    other), but the order GRAPH gains the cycle A->B->A — exactly the
    hazard a real interleaving would deadlock on."""
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    a = san.make_lock("A")
    b = san.make_lock("B")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    run_in_thread(t1)
    assert san.counts()["order-cycle"] == 0  # one order alone is fine
    run_in_thread(t2)
    assert san.counts()["order-cycle"] == 1
    (f,) = [x for x in san.findings() if x.kind == "order-cycle"]
    assert set(f.locks) == {"A", "B"}
    assert "deadlock" in f.detail


def test_three_lock_cycle_detected_through_transitive_edges():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    a, b, c = (san.make_lock(n) for n in "ABC")

    def chain(x, y):
        def go():
            with x:
                with y:
                    pass
        return go

    run_in_thread(chain(a, b))
    run_in_thread(chain(b, c))
    assert san.counts()["order-cycle"] == 0
    run_in_thread(chain(c, a))  # closes A->B->C->A
    assert san.counts()["order-cycle"] == 1


def test_consistent_order_never_flags():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    a = san.make_lock("A")
    b = san.make_lock("B")
    for _ in range(3):
        def ordered():
            with a:
                with b:
                    pass
        run_in_thread(ordered)
    assert san.total_findings() == 0


def test_cycle_findings_dedupe():
    """One bad pattern in a hot loop is one finding, not a flood."""
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    a = san.make_lock("A")
    b = san.make_lock("B")

    def inverted():
        with b:
            with a:
                pass

    def ordered():
        with a:
            with b:
                pass

    run_in_thread(ordered)
    for _ in range(5):
        run_in_thread(inverted)
    assert san.counts()["order-cycle"] == 1


def test_rlock_reentrancy_is_not_a_cycle():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    r = san.make_lock("R", kind="rlock")
    with r:
        with r:  # re-entering the SAME lock is not an ordering edge
            pass
    assert san.total_findings() == 0


# -- held-too-long ----------------------------------------------------------


def test_held_too_long_on_fake_clock():
    clock = FakeClock()
    san = LockSanitizer(
        LockSanitizerConfig(enabled=True, hold_budget_s=0.25), clock=clock)
    lk = san.make_lock("slow")
    with lk:
        clock.advance(0.3)
    assert san.counts()["held-too-long"] == 1
    (f,) = san.findings()
    assert f.locks == ("slow",)
    # within budget: no new finding, and the first one stays deduped
    with lk:
        clock.advance(0.1)
    with lk:
        clock.advance(0.9)
    assert san.counts()["held-too-long"] == 1


def test_hold_budget_zero_disables_the_check():
    clock = FakeClock()
    san = LockSanitizer(
        LockSanitizerConfig(enabled=True, hold_budget_s=0.0), clock=clock)
    lk = san.make_lock("slow")
    with lk:
        clock.advance(60.0)
    assert san.total_findings() == 0


def test_reentrant_hold_timed_at_outermost_release():
    clock = FakeClock()
    san = LockSanitizer(
        LockSanitizerConfig(enabled=True, hold_budget_s=0.25), clock=clock)
    r = san.make_lock("R", kind="rlock")
    with r:
        with r:
            pass
        clock.advance(0.3)  # after inner release, still held
    assert san.counts()["held-too-long"] == 1


# -- guard violations -------------------------------------------------------


def test_assert_held_flags_unheld_declaration():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    lk = san.make_lock("cache.snap", kind="rlock")
    with lk:
        assert_held(lk, "site.locked_path")  # true declaration: quiet
    assert san.total_findings() == 0
    assert_held(lk, "site.locked_path")  # false declaration
    assert san.counts()["guard-violation"] == 1
    (f,) = san.findings()
    assert "site.locked_path" in f.detail
    assert_held(lk, "site.locked_path")  # same site: deduped
    assert san.counts()["guard-violation"] == 1
    assert_held(lk, "site.other")  # new site: new finding
    assert san.counts()["guard-violation"] == 2


def test_debug_guards_off_suppresses_guard_findings():
    san = LockSanitizer(
        LockSanitizerConfig(enabled=True, debug_guards=False))
    lk = san.make_lock("L")
    assert_held(lk, "anywhere")
    assert san.total_findings() == 0


def test_assert_held_noops_on_plain_locks():
    assert_held(threading.Lock(), "anywhere")
    assert_held(threading.RLock(), "anywhere")


# -- off-by-default / zero-cost seam ----------------------------------------


def test_make_lock_without_factory_returns_plain_threading_locks():
    lk = make_lock(None, "x")
    rk = make_lock(None, "x", "rlock")
    assert not isinstance(lk, InstrumentedLock)
    assert not isinstance(rk, InstrumentedLock)
    # the plain objects still do their job
    with lk:
        pass
    with rk:
        with rk:
            pass


def test_make_lock_with_factory_returns_instrumented():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    lk = make_lock(san.factory(), "obs.test")
    assert isinstance(lk, InstrumentedLock)
    assert lk.name == "obs.test"
    rk = make_lock(san.factory("pfx."), "inner", "rlock")
    assert rk.name == "pfx.inner"


def test_scheduler_off_by_default_uses_plain_locks():
    from kubernetes_tpu.scheduler import Scheduler

    s = Scheduler()
    assert s.lock_sanitizer is None
    assert not isinstance(s.cache._snap_lock, InstrumentedLock)
    assert not isinstance(s.obs.jax._lock, InstrumentedLock)
    assert not isinstance(s.obs.recorder._lock, InstrumentedLock)


# -- instrumented lock surface ----------------------------------------------


def test_instrumented_lock_acquire_release_surface():
    san = LockSanitizer(LockSanitizerConfig(enabled=True))
    lk = san.make_lock("L")
    assert lk.acquire()
    assert lk.held_by_me()
    assert san.held_names() == ("L",)
    lk.release()
    assert not lk.held_by_me()
    assert san.held_names() == ()
    # non-blocking acquire on a lock another thread holds fails clean
    lk.acquire()
    got = []
    run_in_thread(lambda: got.append(lk.acquire(blocking=False)))
    assert got == [False]
    lk.release()


def test_on_finding_callback_receives_kind_and_may_lock():
    """The metrics wiring: on_finding is invoked OUTSIDE the
    sanitizer's meta-lock, so a callback that itself takes a lock
    (a metrics registry does) cannot close a cycle through us."""
    san_holder = {}
    kinds = []
    cb_lock = threading.Lock()

    def cb(kind):
        with cb_lock:
            # re-entering the sanitizer from the callback must not
            # deadlock on _meta
            san_holder["san"].counts()
            kinds.append(kind)

    san = LockSanitizer(LockSanitizerConfig(enabled=True), on_finding=cb)
    san_holder["san"] = san
    a = san.make_lock("A")
    b = san.make_lock("B")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    run_in_thread(t1)
    run_in_thread(t2)
    assert kinds == ["order-cycle"]


def test_findings_ring_is_bounded_but_counts_accumulate():
    san = LockSanitizer(
        LockSanitizerConfig(enabled=True, max_findings=2))
    lk = san.make_lock("L")
    for i in range(5):
        assert_held(lk, f"site{i}")
    assert san.counts()["guard-violation"] == 5
    assert len(san.findings()) == 2
    snap = san.snapshot()
    assert snap["counts"]["guard-violation"] == 5
    assert len(snap["findings"]) == 2


# -- scheduler / observability integration ----------------------------------


def armed_scheduler(**kw):
    from kubernetes_tpu.config import ObservabilityConfig
    from kubernetes_tpu.scheduler import Scheduler

    return Scheduler(observability=ObservabilityConfig(
        lock_sanitizer=LockSanitizerConfig(enabled=True, **kw)))


def test_armed_scheduler_instruments_the_lock_inventory():
    s = armed_scheduler()
    assert s.lock_sanitizer is not None
    for lk, name in [
        (s.cache._snap_lock, "cache.snap"),
        (s.obs.jax._lock, "obs.jaxtel"),
        (s.obs.recorder._lock, "obs.recorder"),
        (s.obs._traces_lock, "obs.traces"),
        (s.obs.ledger._lock, "obs.ledger"),
        (s.obs.ledger.watchdog._lock, "obs.watchdog"),
        (s.obs.ledger.model._lock, "obs.costmodel"),
    ]:
        assert isinstance(lk, InstrumentedLock), name
        assert lk.name == name


def test_armed_scheduler_findings_hit_the_metric_counter():
    s = armed_scheduler()
    a = s.lock_sanitizer.make_lock("test.A")
    b = s.lock_sanitizer.make_lock("test.B")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    run_in_thread(t1)
    run_in_thread(t2)
    assert s.metrics.lock_sanitizer_findings.value(
        kind="order-cycle") == 1.0


def test_lock_findings_mark_the_cycle_eventful_in_the_flight_record():
    """A finding during an otherwise-idle cycle must still produce a
    CycleRecord — a latent deadlock hazard is black-box material."""
    s = armed_scheduler()
    obs = s.obs
    obs.begin_cycle(1)
    lk = s.lock_sanitizer.make_lock("test.L")
    assert_held(lk, "test.site")  # guard violation mid-cycle
    obs.end_cycle(None)
    recs = obs.recorder.records()
    assert len(recs) == 1
    assert recs[0].lock_findings == 1
    assert recs[0].to_json()["lock_findings"] == 1
    assert "lockfind=1" in obs.recorder.dump()
    # a clean idle cycle still records nothing
    obs.begin_cycle(2)
    obs.end_cycle(None)
    assert len(obs.recorder.records()) == 1


def test_serving_loop_lock_rides_the_sanitizer():
    from kubernetes_tpu.config import ServingConfig
    from kubernetes_tpu.serving.microbatch import ServingLoop

    s = armed_scheduler()
    loop = ServingLoop(s, ServingConfig(enabled=True))
    assert isinstance(loop.lock, InstrumentedLock)
    assert loop.lock.name == "serving.loop"


def test_soak_sentinels_sample_lock_namespace():
    from kubernetes_tpu.soak import SoakSentinels

    s = armed_scheduler()
    lk = s.lock_sanitizer.make_lock("test.L")
    assert_held(lk, "test.site")
    sent = SoakSentinels(sched=s)
    out = sent.collect()
    assert out["lock.guard_violations"] == 1.0
    assert out["lock.order_cycles"] == 0.0
    assert out["lock.total"] == 1.0
    # unarmed scheduler: no lock.* keys at all
    from kubernetes_tpu.scheduler import Scheduler

    out2 = SoakSentinels(sched=Scheduler()).collect()
    assert not [k for k in out2 if k.startswith("lock.")]


def test_armed_schedule_cycle_stays_clean():
    """The acceptance shape in miniature: a real scheduling cycle with
    every lock instrumented produces zero findings."""
    from kubernetes_tpu.testing import make_node, make_pod

    s = armed_scheduler(hold_budget_s=0.0)
    s.on_node_add(make_node("n0", cpu_milli=4000, memory=8 * 2**30,
                            pods=10))
    s.on_pod_add(make_pod("p0", cpu_milli=100, memory=2**20))
    res = s.schedule_cycle()
    assert res.scheduled == 1
    assert s.lock_sanitizer.total_findings() == 0


def test_config_roundtrip_arms_the_sanitizer():
    from kubernetes_tpu.api.config_v1alpha1 import decode, encode

    cfg = decode({
        "apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
        "kind": "KubeSchedulerConfiguration",
        "observability": {"lockSanitizer": {
            "enabled": True, "holdBudget": "100ms",
            "debugGuards": False, "maxFindings": 8}},
    })
    ls = cfg.observability.lock_sanitizer
    assert ls.enabled is True
    assert ls.hold_budget_s == pytest.approx(0.1)
    assert ls.debug_guards is False
    assert ls.max_findings == 8
    back = encode(cfg)["observability"]["lockSanitizer"]
    assert back["enabled"] is True
    assert back["holdBudget"] == "100ms"


def test_flight_recorder_len_takes_the_lock():
    """Regression pin (R9 sweep): ``len(recorder)`` reads the deque the
    scheduler thread appends to — it must go through the lock like
    every other reader, not race the append."""
    from kubernetes_tpu.obs.recorder import CycleRecord, FlightRecorder

    acquisitions = []

    class SpyLock:
        def __enter__(self):
            acquisitions.append("acquire")
            return self

        def __exit__(self, *exc):
            return None

    rec = FlightRecorder(capacity=4,
                         lock_factory=lambda name, kind="lock": SpyLock())
    rec.record(CycleRecord(cycle=1))
    acquisitions.clear()
    assert len(rec) == 1
    assert acquisitions == ["acquire"]


def test_validate_config_rejects_bad_sanitizer_budgets():
    """cli.validate_config covers the lockSanitizer block like every
    other observability knob: a negative hold budget or a zero findings
    ring is a config error, not a silent misarm."""
    from kubernetes_tpu.cli import validate_config
    from kubernetes_tpu.config import (
        KubeSchedulerConfiguration,
        ObservabilityConfig,
    )

    cfg = KubeSchedulerConfiguration(
        observability=ObservabilityConfig(
            lock_sanitizer=LockSanitizerConfig(
                hold_budget_s=-1.0, max_findings=0)))
    joined = "\n".join(validate_config(cfg))
    assert "lockSanitizer.holdBudget" in joined
    assert "lockSanitizer.maxFindings" in joined
    # the defaults stay valid
    assert validate_config(KubeSchedulerConfiguration()) == []
