"""Checkpoint/restore — the etcd snapshot+restore analog plus the
kubelet checkpointmanager slice (VERDICT r3 §5 'Checkpoint/resume:
partial'): a running cluster saved mid-flight must come back in a fresh
hub with revisions preserved, watchers forced to relist, controllers
converging, and pod lifecycle clocks intact. Also the core/v1 object
codec scheme (api/core_v1.py) — Pod/Node through the runtime.Scheme
pipeline."""

from kubernetes_tpu.api.core_v1 import decode_any, encode
from kubernetes_tpu.api.scheme import SchemeError
from kubernetes_tpu.api.types import (
    PersistentVolume,
    PersistentVolumeClaim,
    PodVolume,
    ReadinessProbe,
    StorageClass,
)
from kubernetes_tpu.sim import Compacted, Deployment, HollowCluster, Job
from kubernetes_tpu.testing import make_node, make_pod

import pytest


# -- core/v1 codec scheme ---------------------------------------------------

def test_core_v1_scheme_round_trips_pod_and_node():
    pod = make_pod("p0", cpu_milli=250, labels={"app": "x"},
                   node_name="n3", priority=7)
    doc = encode(pod)
    assert doc["apiVersion"] == "v1" and doc["kind"] == "Pod"
    back = decode_any(doc)
    assert (back.name, back.namespace, back.node_name, back.priority) == (
        "p0", "default", "n3", 7)
    assert back.requests.cpu_milli == 250 and back.labels == {"app": "x"}

    node = make_node("n0", cpu_milli=8000)
    ndoc = encode(node)
    assert ndoc["kind"] == "Node"
    nback = decode_any(ndoc)
    assert nback.name == "n0"
    assert nback.allocatable.cpu_milli == 8000

    with pytest.raises(SchemeError):
        decode_any({"apiVersion": "v2", "kind": "Pod"})
    with pytest.raises(SchemeError):
        encode(object())


# -- hub checkpoint/restore -------------------------------------------------

def _build_live_cluster(seed=41):
    hub = HollowCluster(seed=seed, scheduler_kw={"enable_preemption": False})
    for i in range(5):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    hub.add_deployment(Deployment("web", replicas=4))
    hub.add_job(Job("batch", completions=3, parallelism=1, duration_s=60))
    hub.add_storage_class(StorageClass("std"))
    hub.add_pv(PersistentVolume("pv0", kind="gce-pd", handle="h",
                                storage_class="std"))
    hub.add_pvc(PersistentVolumeClaim("c0", storage_class="std"))
    hub.create_pod(make_pod("vol-user", cpu_milli=100,
                            volumes=(PodVolume(pvc="c0"),)))
    hub.create_pod(make_pod(
        "probed", cpu_milli=100,
        readiness_probe=ReadinessProbe(initial_delay_s=5)))
    for _ in range(4):
        hub.step()
    return hub


def test_checkpoint_restore_preserves_state_and_resumes(tmp_path):
    hub = _build_live_cluster()
    # one pod created but NOT yet scheduled at checkpoint time — it must
    # survive the restore and get scheduled by the restored control plane
    hub.create_pod(make_pod("pending-at-save", cpu_milli=100))
    path = str(tmp_path / "snap.ckpt")
    manifest = hub.save_checkpoint(path)
    assert manifest["nodes"] == 5 and manifest["revision"] > 0
    want_rvs = dict(hub.resource_version)
    want_bound = {k: p.node_name for k, p in hub.truth_pods.items()}
    want_clock = hub.clock.t

    cold = HollowCluster(seed=999,
                         scheduler_kw={"enable_preemption": False})
    got = cold.restore_checkpoint(path)
    assert got["revision"] == manifest["revision"]
    # resourceVersions preserved exactly (client rvs stay meaningful)
    assert cold.resource_version == want_rvs
    assert cold.clock.t == want_clock
    assert {k: p.node_name for k, p in cold.truth_pods.items()} == want_bound
    # the scheduler cache rebuilt from truth: the oracle must hold NOW
    cold.check_consistency()
    # a watcher resuming below the restored floor relists (etcd restore)
    with pytest.raises(Compacted):
        cold.watch(0)
    # the restored control plane keeps working: pending pod schedules,
    # controllers keep reconciling, volume truth stays mutual
    for _ in range(4):
        cold.step()
    assert cold.truth_pods["default/pending-at-save"].node_name
    assert cold.pvcs["default/c0"].volume_name == "pv0"
    cold.check_consistency()


def test_checkpoint_restores_kubelet_clocks_and_probe_state(tmp_path):
    hub = _build_live_cluster(seed=42)
    hub.set_app_health("default/probed", False)
    hub.step()
    path = str(tmp_path / "snap.ckpt")
    hub.save_checkpoint(path)

    cold = HollowCluster(seed=7, scheduler_kw={"enable_preemption": False})
    cold.restore_checkpoint(path)
    # probe override survived (checkpointmanager analog)
    assert cold.app_health["default/probed"] is False
    p = cold.truth_pods["default/probed"]
    assert p.phase == "Running" and not p.ready
    # recovery after restore flows through normally
    cold.set_app_health("default/probed", True)
    for _ in range(3):
        cold.step()
    assert cold.truth_pods["default/probed"].ready
    cold.check_consistency()


def test_checkpoint_carries_events_registry(tmp_path):
    """Events are stored, REST-served API objects — they must survive a
    restore alongside their resource_version lineage (review finding)."""
    hub = _build_live_cluster(seed=43)
    assert hub.events_v1, "expected scheduler events by now"
    path = str(tmp_path / "snap.ckpt")
    hub.save_checkpoint(path)
    cold = HollowCluster(seed=3, scheduler_kw={"enable_preemption": False})
    cold.restore_checkpoint(path)
    assert cold.events_v1.keys() == hub.events_v1.keys()
    some = next(iter(cold.events_v1))
    assert cold.resource_version[f"events/{some}"] > 0


def test_checkpoint_with_hpa_strips_and_rewires_metric_source(tmp_path):
    """HPA load_fn is a live callable (a lambda in every real usage) —
    it must not crash the pickle (review finding); restore re-wires."""
    from kubernetes_tpu.sim import HorizontalPodAutoscaler

    hub = HollowCluster(seed=45, scheduler_kw={"enable_preemption": False})
    for i in range(6):
        hub.add_node(make_node(f"n{i}", cpu_milli=8000))
    hub.add_deployment(Deployment("web", replicas=2))
    load = {"u": 1.0}
    hub.add_hpa(HorizontalPodAutoscaler(
        "h", deployment="web", min_replicas=2, max_replicas=8,
        target_utilization=0.5, load_fn=lambda: load["u"]))
    hub.step()
    path = str(tmp_path / "snap.ckpt")
    hub.save_checkpoint(path)  # must not raise PicklingError
    cold = HollowCluster(seed=8, scheduler_kw={"enable_preemption": False})
    cold.restore_checkpoint(path)
    assert cold.hpas["h"].load_fn is None
    before = cold.deployments["web"].replicas
    cold.step()  # metric-less HPA holds the line
    assert cold.deployments["web"].replicas == before
    cold.hpas["h"].load_fn = lambda: 1.0  # re-wire: scaling resumes
    cold.step()
    assert cold.deployments["web"].replicas > before
    cold.check_consistency()


def test_restore_rejects_config_mismatch(tmp_path):
    """A checkpoint saved with admission ON must not restore into a hub
    without it — silent semantic divergence becomes a loud error."""
    hub = HollowCluster(seed=44, admission=True,
                        scheduler_kw={"enable_preemption": False})
    hub.add_node(make_node("n0", cpu_milli=4000))
    path = str(tmp_path / "snap.ckpt")
    hub.save_checkpoint(path)
    plain = HollowCluster(seed=5, scheduler_kw={"enable_preemption": False})
    with pytest.raises(ValueError) as ei:
        plain.restore_checkpoint(path)
    assert "admission" in str(ei.value)
    # matching construction restores fine
    twin = HollowCluster(seed=6, admission=True,
                         scheduler_kw={"enable_preemption": False})
    twin.restore_checkpoint(path)
    twin.check_consistency()


def test_restore_rejects_garbage(tmp_path):
    bad = tmp_path / "junk.ckpt"
    import pickle

    bad.write_bytes(pickle.dumps({"format": "something-else"}))
    hub = HollowCluster(seed=1)
    with pytest.raises(ValueError):
        hub.restore_checkpoint(str(bad))


def test_restore_requires_fresh_hub(tmp_path):
    """Review regression: restoring into a hub that already has state
    would leave pre-restore objects dangling in the scheduler cache —
    refuse loudly, like the config-mismatch guard."""
    hub = _build_live_cluster(seed=46)
    path = str(tmp_path / "snap.ckpt")
    hub.save_checkpoint(path)
    dirty = HollowCluster(seed=4, scheduler_kw={"enable_preemption": False})
    dirty.add_node(make_node("pre-existing", cpu_milli=1000))
    with pytest.raises(ValueError) as ei:
        dirty.restore_checkpoint(path)
    assert "freshly constructed" in str(ei.value)


def test_core_v1_round_trip_preserves_lifecycle_fields():
    """Review regression: phase/Ready/readinessProbe must survive
    encode->decode (they were emit-only; the bridge and codec silently
    reset lifecycle state)."""
    from kubernetes_tpu.api.types import POD_RUNNING

    pod = make_pod("lp", cpu_milli=100,
                   readiness_probe=ReadinessProbe(initial_delay_s=7.5))
    pod.phase = POD_RUNNING
    pod.ready = True
    back = decode_any(encode(pod))
    assert back.phase == POD_RUNNING and back.ready is True
    assert back.readiness_probe is not None
    assert back.readiness_probe.initial_delay_s == 7.5
    # probe-less pods stay probe-less (no phantom Ready condition)
    plain = decode_any(encode(make_pod("np", cpu_milli=10)))
    assert plain.readiness_probe is None and plain.ready is False


def test_restore_rejects_foreign_globals_in_checkpoint(tmp_path):
    """The restore path unpickles through a restricted Unpickler: a
    tampered stream referencing a non-framework global (the arbitrary-
    code-execution vector of raw pickle.load) must fail to LOAD, not
    execute (ADVICE r4 trust-boundary guard)."""
    import pickle

    path = str(tmp_path / "evil.ckpt")
    with open(path, "wb") as f:
        # a stream whose load would call os.system("true")
        pickle.dump({"format": "ktpu-checkpoint/1",
                     "payload": EvilPayload()}, f)
    hub = HollowCluster(seed=1)
    with pytest.raises(pickle.UnpicklingError) as ei:
        hub.restore_checkpoint(path)
    assert "forbidden global" in str(ei.value)

    # dotted-name traversal through an allowed module (STACK_GLOBAL
    # getattr-walk: module='kubernetes_tpu.native', name='os.system')
    # must not escape the allowlist either
    dotted = (b"\x80\x04\x8c\x15kubernetes_tpu.native\x8c\tos.system"
              b"\x93\x8c\x04true\x85R.")
    dpath = str(tmp_path / "dotted.ckpt")
    with open(dpath, "wb") as f:
        f.write(dotted)
    with pytest.raises(pickle.UnpicklingError):
        HollowCluster(seed=1).restore_checkpoint(dpath)


class EvilPayload:
    def __reduce__(self):
        import os

        return (os.system, ("true",))


def test_restore_updates_rbac_and_token_containers_in_place(tmp_path):
    """ADVICE r5 low (sim.py restore_checkpoint): RBACAuthorizer reads
    the hub's role/binding containers LIVE — an authorizer (and a
    bootstrap-token authenticator) wired BEFORE restore must see
    post-restore state, exactly like the admission chain's namespaces/
    quota containers."""
    from kubernetes_tpu.auth import (
        ALLOW,
        Attributes,
        ClusterRole,
        ClusterRoleBinding,
        PolicyRule,
        RBACAuthorizer,
        UserInfo,
    )

    hub = HollowCluster(seed=61, scheduler_kw={"enable_preemption": False})
    hub.cluster_roles["pods-reader"] = ClusterRole(
        "pods-reader",
        rules=[PolicyRule(verbs=("get",), resources=("pods",))])
    hub.cluster_role_bindings.append(
        ClusterRoleBinding(role="pods-reader", subjects=("devs",)))
    path = str(tmp_path / "rbac.ckpt")
    hub.save_checkpoint(path)

    cold = HollowCluster(seed=62, scheduler_kw={"enable_preemption": False})
    # wired BEFORE restore, against the fresh hub's (empty) live dicts
    authz = RBACAuthorizer(cold.cluster_roles, cold.cluster_role_bindings)
    attrs = Attributes(user=UserInfo(name="alice", groups=("devs",)),
                       verb="get", resource="pods", namespace="default",
                       name="", path="")
    assert authz.authorize(attrs) != ALLOW  # nothing restored yet
    cold.restore_checkpoint(path)
    # the SAME authorizer sees the restored roles/bindings (in-place
    # clear()/update() and [:], not container replacement)
    assert authz.authorize(attrs) == ALLOW
    assert cold.cluster_roles is authz.roles
    assert cold.cluster_role_bindings is authz.bindings
