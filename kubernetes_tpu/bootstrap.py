"""Cluster bootstrap — the kubeadm analog (SURVEY §2.2 "kubeadm:
cluster bootstrap phases"; reference ``cmd/kubeadm/app/cmd/init.go``
phase runner, ``app/phases/``, and the bootstrap-token discovery flow
``app/discovery/token``).

kubeadm's job split into the phases that matter for a hollow control
plane:

- **preflight** — config validation (``app/preflight/checks.go``);
- **control-plane** — bring up the hub (apiserver+etcd analog), the
  controller passes, and the scheduler (one HollowCluster);
- **mark-control-plane** — taint/label the control-plane node
  (``app/phases/markcontrolplane``): workloads don't land there unless
  they tolerate the master taint;
- **bootstrap-token** — mint a ``abcdef.0123456789abcdef`` token with a
  TTL (``app/phases/bootstraptoken/node``);
- **join** — a node presents the token; valid ⇒ its kubelet
  self-registers and starts heartbeating (``app/cmd/join.go``).

``init_cluster``/``join_node`` are the ``kubeadm init``/``kubeadm join``
entry points.
"""

from __future__ import annotations

import dataclasses
import secrets
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import (
    EFFECT_NO_SCHEDULE,
    Node,
    Resources,
    Taint,
)
from kubernetes_tpu.sim import HollowCluster

#: the control-plane taint/label pair (markcontrolplane/markcontrolplane.go)
TAINT_CONTROL_PLANE = "node-role.kubernetes.io/master"
LABEL_CONTROL_PLANE = "node-role.kubernetes.io/master"

TOKEN_ID_LEN = 6
TOKEN_SECRET_LEN = 16
_TOKEN_ALPHABET = "abcdefghijklmnopqrstuvwxyz0123456789"


class BootstrapError(Exception):
    """Preflight/validation/discovery failure (kubeadm's fatal errors)."""


@dataclass
class InitConfig:
    """The ClusterConfiguration slice the hollow phases consume
    (app/apis/kubeadm/types.go)."""

    cluster_name: str = "kubernetes"
    control_plane_name: str = "control-plane"
    control_plane_cpu_milli: float = 4000.0
    control_plane_memory: float = 8 * 2**30
    #: token TTL in seconds; 0 = never expires (kubeadm default 24 h)
    token_ttl_s: float = 24 * 3600.0
    #: enable the hub's admission chain (--enable-admission-plugins)
    admission: bool = False
    #: forwarded to HollowCluster (seed, rates, scheduler_kw...)
    hub_kw: Dict = field(default_factory=dict)


@dataclass
class BootstrapToken:
    token_id: str
    secret: str
    created_at: float = 0.0
    ttl_s: float = 0.0
    usages: Tuple[str, ...] = ("authentication", "signing")

    def render(self) -> str:
        return f"{self.token_id}.{self.secret}"

    def expired(self, now: float) -> bool:
        return self.ttl_s > 0 and now - self.created_at > self.ttl_s


def _rand(n: int) -> str:
    return "".join(secrets.choice(_TOKEN_ALPHABET) for _ in range(n))


def preflight(config: InitConfig) -> None:
    """app/preflight/checks.go analog: reject impossible configs before
    any state exists."""
    if not config.cluster_name:
        raise BootstrapError("preflight: cluster_name must be non-empty")
    if not config.control_plane_name:
        raise BootstrapError("preflight: control_plane_name must be non-empty")
    if config.control_plane_cpu_milli <= 0 or config.control_plane_memory <= 0:
        raise BootstrapError("preflight: control-plane resources must be > 0")
    if config.token_ttl_s < 0:
        raise BootstrapError("preflight: token_ttl_s must be >= 0")


def create_token(hub: HollowCluster, ttl_s: float = 24 * 3600.0) -> str:
    """Mint and store a bootstrap token (phases/bootstraptoken)."""
    tok = BootstrapToken(_rand(TOKEN_ID_LEN), _rand(TOKEN_SECRET_LEN),
                         created_at=hub.clock.t, ttl_s=ttl_s)
    hub.bootstrap_tokens[tok.token_id] = tok
    return tok.render()


def init_cluster(config: Optional[InitConfig] = None
                 ) -> Tuple[HollowCluster, str]:
    """``kubeadm init``: run the phases, return the running control plane
    and a join token."""
    config = config or InitConfig()
    preflight(config)
    # control-plane phase: hub (apiserver/etcd/controllers/scheduler)
    hub = HollowCluster(admission=config.admission, **config.hub_kw)
    hub.bootstrap_tokens = {}
    # mark-control-plane: the master node exists, tainted + labeled
    cp = Node(
        config.control_plane_name,
        labels={LABEL_CONTROL_PLANE: ""},
        allocatable=Resources(cpu_milli=config.control_plane_cpu_milli,
                              memory=config.control_plane_memory, pods=110),
        taints=(Taint(TAINT_CONTROL_PLANE, effect=EFFECT_NO_SCHEDULE),),
    )
    hub.add_node(cp)
    # upload-config analog: the config object is readable cluster state
    hub.cluster_config = config
    # bootstrap-token phase
    token = create_token(hub, config.token_ttl_s)
    return hub, token


def join_node(hub: HollowCluster, token: str, node: Node) -> None:
    """``kubeadm join``: token discovery then kubelet self-registration.
    Raises :class:`BootstrapError` on a bad/expired token (the TLS
    bootstrap rejection)."""
    tokens = hub.bootstrap_tokens
    tid, _, secret = token.partition(".")
    tok = tokens.get(tid)
    if tok is None or tok.secret != secret:
        raise BootstrapError("join: unknown or malformed bootstrap token")
    if tok.expired(hub.clock.t):
        del tokens[tid]
        raise BootstrapError("join: bootstrap token expired")
    if node.name in hub.truth_nodes:
        raise BootstrapError(f"join: node {node.name!r} already registered")
    hub.add_node(node)  # kubelet self-registration (ADDED event + agent)


# ---------------------------------------------------------------------------
# Bootstrap-token controllers (pkg/controller/bootstrap)
# ---------------------------------------------------------------------------

#: where the signer publishes discovery state (bootstrapapi constants:
#: the cluster-info ConfigMap in kube-public that `kubeadm join` reads
#: ANONYMOUSLY, verified via a token-keyed detached signature)
KUBE_PUBLIC = "kube-public"
CLUSTER_INFO = "cluster-info"
JWS_PREFIX = "jws-kubeconfig-"


def _detached_signature(token_id: str, secret: str, content: str) -> str:
    """The ComputeDetachedSignature analog (cluster-bootstrap/token/jws):
    an HMAC keyed on the full token over the kubeconfig content —
    possession of EITHER half alone cannot forge it, holding both
    verifies the published CA out-of-band."""
    import hashlib
    import hmac as hmac_mod

    return hmac_mod.new(f"{token_id}.{secret}".encode(), content.encode(),
                        hashlib.sha256).hexdigest()


def token_cleaner(hub: HollowCluster) -> int:
    """TokenCleaner (bootstrap/tokencleaner.go:59): proactively delete
    expired bootstrap tokens — join_node's lazy check only fires when
    someone USES the dead token; this pass revokes it for the
    authenticator too. Returns how many were deleted."""
    dead = [tid for tid, tok in hub.bootstrap_tokens.items()
            if tok.expired(hub.clock.t)]
    for tid in dead:
        del hub.bootstrap_tokens[tid]
    return len(dead)


def bootstrap_signer(hub: HollowCluster) -> None:
    """BootstrapSigner (bootstrap/bootstrapsigner.go:73 signConfigMap):
    maintain the kube-public/cluster-info ConfigMap — the kubeconfig
    (cluster CA + endpoint) plus one ``jws-kubeconfig-<id>`` detached
    signature per SIGNING-usage live token; signatures for gone tokens
    are removed (the reference strips all and recomputes)."""
    kubeconfig = (
        f"apiVersion: v1\nkind: Config\nclusters:\n- cluster:\n"
        f"    certificate-authority-data: {hub.cluster_ca}\n"
        f"    server: https://{getattr(hub, 'cluster_config', None) and hub.cluster_config.control_plane_name or 'control-plane'}:6443\n"
    )
    data = {"kubeconfig": kubeconfig}
    for tid, tok in hub.bootstrap_tokens.items():
        if "signing" not in tok.usages or tok.expired(hub.clock.t):
            continue
        data[f"{JWS_PREFIX}{tid}"] = _detached_signature(
            tid, tok.secret, kubeconfig)
    cur = hub.configmaps.get(f"{KUBE_PUBLIC}/{CLUSTER_INFO}")
    if cur is None or cur.get("data") != data:
        hub.put_configmap(KUBE_PUBLIC, CLUSTER_INFO, data)


def verify_cluster_info(hub: HollowCluster, token: str) -> str:
    """The join-side discovery check (kubeadm token-based discovery:
    fetch cluster-info anonymously, verify the JWS for YOUR token,
    then trust the embedded CA). Returns the verified kubeconfig or
    raises :class:`BootstrapError`."""
    cm = hub.configmaps.get(f"{KUBE_PUBLIC}/{CLUSTER_INFO}")
    if cm is None:
        raise BootstrapError("discovery: cluster-info not published")
    tid, _, secret = token.partition(".")
    kubeconfig = cm["data"].get("kubeconfig", "")
    sig = cm["data"].get(f"{JWS_PREFIX}{tid}")
    if sig is None:
        raise BootstrapError(
            f"discovery: no signature for token id {tid!r}")
    if sig != _detached_signature(tid, secret, kubeconfig):
        raise BootstrapError("discovery: cluster-info signature mismatch")
    return kubeconfig
