"""Leader election — active-passive HA for the scheduler, mirroring
client-go ``tools/leaderelection`` (``leaderelection.go:317``
tryAcquireOrRenew): CAS on a lease record with holder identity, lease
duration, renew deadline, and retry period. The scheduler only runs while
leading (app/server.go:261 OnStartedLeading -> sched.Run).

The lock is pluggable: :class:`InMemoryLock` for tests/single-process,
:class:`FileLock` (atomic rename CAS) for multi-process on one host, and
:class:`LeaseLock` CASing a coordination Lease API object through the
hub — the reference's production path (resourcelock/leaselock.go via
interface.go:100), which makes failover observable/mediated by the
control plane itself. The elector is tick-driven (no background threads)
so the sim/driver controls time."""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.config import LeaderElectionConfig


@dataclass
class LeaderElectionRecord:
    """resourcelock.LeaderElectionRecord wire shape."""

    holder_identity: str = ""
    lease_duration_s: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    leader_transitions: int = 0


class InMemoryLock:
    """Shared-object lock for in-process elections (tests, sim)."""

    def __init__(self) -> None:
        self._record: Optional[LeaderElectionRecord] = None

    def get(self) -> Optional[LeaderElectionRecord]:
        return self._record

    def create_or_update(self, record: LeaderElectionRecord, old) -> bool:
        """CAS: succeeds only if the current record still equals ``old``
        (the optimistic-concurrency resourceVersion check)."""
        if self._record is not old:
            return False
        self._record = record
        return True


class FileLock:
    """File-based lock: read-modify-write with atomic rename; the loaded
    JSON doubles as the resourceVersion (compare-and-swap on content).
    The compare and the replace are made atomic by holding an OS mutex
    (``fcntl.flock`` on a sidecar file) across the read-modify-write —
    without it two candidates can both pass the compare and both become
    leader (split brain), the exact failure leader election exists to
    prevent (tryAcquireOrRenew, leaderelection.go:317, relies on the
    apiserver's CAS being atomic)."""

    def __init__(self, path: str) -> None:
        self.path = path

    def _read(self) -> Optional[LeaderElectionRecord]:
        try:
            with open(self.path) as f:
                d = json.load(f)
            return LeaderElectionRecord(**d)
        except (OSError, ValueError):
            return None

    def get(self) -> Optional[LeaderElectionRecord]:
        return self._read()

    def create_or_update(self, record: LeaderElectionRecord, old) -> bool:
        import fcntl

        with open(f"{self.path}.lock", "a+") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                cur = self._read()
                if (cur is None) != (old is None):
                    return False
                if (
                    cur is not None
                    and old is not None
                    and cur.__dict__ != old.__dict__
                ):
                    return False
                tmp = f"{self.path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(record.__dict__, f)
                os.replace(tmp, self.path)
                return True
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)


class LeaseLock:
    """CAS a Lease API object through the hub — the reference's
    LeasesResourceLock (resourcelock/leaselock.go:86 Update does a
    client-go Update whose optimistic concurrency is the stored
    resourceVersion; here that is ``hub.cas_lease``). The rv observed at
    :meth:`get` bounds the CAS window, so two candidates that both read
    rv N can never both win the write."""

    def __init__(self, hub, namespace: str = "kube-system",
                 name: str = "kube-scheduler") -> None:
        self.hub = hub
        self.namespace = namespace
        self.name = name
        self._rv = 0

    def get(self) -> Optional[LeaderElectionRecord]:
        record, self._rv = self.hub.get_lease(self.namespace, self.name)
        return record

    def create_or_update(self, record: LeaderElectionRecord, old) -> bool:
        return self.hub.cas_lease(
            self.namespace, self.name, record, self._rv
        ) is not None


class LeaderElector:
    """leaderelection.go LeaderElector, tick-driven. Call ``tick()`` at
    least every retry_period; it acquires/renews and fires the callbacks."""

    def __init__(
        self,
        identity: str,
        lock,
        config: Optional[LeaderElectionConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        self.identity = identity
        self.lock = lock
        self.config = config or LeaderElectionConfig()
        self.clock = clock
        self.on_started_leading = on_started_leading or (lambda: None)
        self.on_stopped_leading = on_stopped_leading or (lambda: None)
        self._leading = False
        self._observed: Optional[LeaderElectionRecord] = None
        self._observed_at: float = 0.0
        #: fencing token: bumps on every not-leading -> leading
        #: transition, so work stamped with an older epoch is provably
        #: from a deposed incarnation (the Lamport/ZooKeeper fencing
        #: pattern; the reference gets the same property from the Lease
        #: resourceVersion its writes CAS against)
        self.epoch = 0

    def is_leader(self) -> bool:
        return self._leading

    # -- bind fencing ------------------------------------------------------

    def allow_bind(self) -> bool:
        """The fencing check the scheduler's bind path consults: may a
        side-effecting write go out NOW? True only while leading AND the
        lease, as last successfully renewed on our clock, is younger
        than ``renew_deadline_s`` — the reference's rule that a leader
        unable to renew by renewDeadline must stop acting
        (leaderelection.go:278 renew loop). A wedged leader that missed
        its ticks therefore fences ITSELF before the lease even expires,
        closing the window where a deposed leader's in-flight binds race
        the new leader's."""
        if not self._leading or self._observed is None:
            return False
        horizon = min(self.config.renew_deadline_s,
                      self._observed.lease_duration_s)
        return self.clock() < self._observed_at + horizon

    def release(self) -> bool:
        """Graceful lease release on shutdown (leaderelection.go:295
        release): CAS an already-expired anonymous record so a standby's
        next tick acquires immediately instead of waiting out the full
        lease duration. Returns True when the release wrote (we were
        leading and the CAS won); a lost CAS means someone already took
        over — nothing to release."""
        if not self._leading:
            return False
        cur = self.lock.get()
        now = self.clock()
        if cur is None or cur.holder_identity != self.identity:
            # the lease is no longer OURS (a successor already acquired
            # while our local flag was stale — e.g. a wedged leader
            # SIGTERMed after the standby took over): clobbering the
            # live record with an expired one would re-open the
            # double-leader window release() exists to avoid. Step down
            # locally, write nothing.
            self._set_leading(False)
            return False
        rec = LeaderElectionRecord(
            holder_identity="",
            lease_duration_s=0.0,
            acquire_time=now,
            renew_time=now,
            leader_transitions=(cur.leader_transitions
                                if cur is not None else 0),
        )
        wrote = self.lock.create_or_update(rec, cur)
        self._observed = rec if wrote else None
        self._observed_at = now
        self._set_leading(False)
        return wrote

    def tick(self) -> bool:
        """tryAcquireOrRenew (leaderelection.go:317). Returns leading."""
        now = self.clock()
        cur = self.lock.get()
        if cur is not None and cur != self._observed:
            self._observed = cur
            self._observed_at = now

        if cur is not None and cur.holder_identity != self.identity:
            # someone else holds it; steal only once their lease expires
            if self._observed_at + cur.lease_duration_s > now:
                self._set_leading(False)
                return False

        new = LeaderElectionRecord(
            holder_identity=self.identity,
            lease_duration_s=self.config.lease_duration_s,
            acquire_time=(
                cur.acquire_time
                if cur is not None and cur.holder_identity == self.identity
                else now
            ),
            renew_time=now,
            leader_transitions=(
                cur.leader_transitions
                if cur is not None and cur.holder_identity == self.identity
                else (cur.leader_transitions + 1 if cur is not None else 0)
            ),
        )
        if not self.lock.create_or_update(new, cur):
            self._set_leading(False)
            return False
        self._observed = new
        self._observed_at = now
        self._set_leading(True)
        return True

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            self.epoch += 1
            self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            self.on_stopped_leading()
