"""Configuration: feature gates, ComponentConfig, and legacy Policy.

Mirrors the reference's three config layers (SURVEY.md §5 config/flag
system):

- **feature gates** — ``pkg/features/kube_features.go`` catalog through
  ``component-base/featuregate``; parsed from ``K=V,K2=V2`` strings.
- **ComponentConfig** — the versioned ``KubeSchedulerConfiguration``
  (``pkg/scheduler/apis/config/types.go:43-101``): algorithm source,
  percentageOfNodesToScore, bindTimeout, leader election, plugins.
- **legacy Policy** — JSON/ConfigMap predicate+priority selection
  (``pkg/scheduler/api/types.go:46``), decoded here from dicts into an
  enabled-predicate bitmask, a priority weights dict (with custom
  registrations for parameterized priorities), and extender configs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.ops.predicates import BIT, PREDICATE_BITS
from kubernetes_tpu.sanitize import LockSanitizerConfig

# ---------------------------------------------------------------------------
# Feature gates (pkg/features/kube_features.go @ v1.16 defaults, scheduler-
# relevant subset)
# ---------------------------------------------------------------------------

DEFAULT_FEATURE_GATES: Dict[str, bool] = {
    "EvenPodsSpread": False,          # alpha (kube_features.go:479)
    "AttachVolumeLimit": True,        # beta
    "BalanceAttachedNodeVolumes": False,  # alpha
    "ResourceLimitsPriorityFunction": False,  # alpha
    "TaintNodesByCondition": True,    # beta->GA
    "PodOverhead": False,             # alpha
    "NonPreemptingPriority": False,   # alpha
    "PodPriority": True,              # GA
    "CSIMigration": False,            # alpha
    "LocalStorageCapacityIsolation": True,  # beta
}


class FeatureGates:
    """component-base/featuregate/feature_gate.go: known-gate map with
    defaults; Set() parses the --feature-gates=K=V flag format."""

    def __init__(self, overrides: Optional[Dict[str, bool]] = None) -> None:
        self._gates = dict(DEFAULT_FEATURE_GATES)
        if overrides:
            for k, v in overrides.items():
                self._set(k, v)

    def _set(self, name: str, value: bool) -> None:
        if name not in self._gates:
            raise ValueError(f"unknown feature gate {name!r}")
        self._gates[name] = bool(value)

    def set_from_string(self, spec: str) -> None:
        """Parse "K=true,K2=false" (featuregate.Set)."""
        for part in spec.split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            if v.lower() not in ("true", "false"):
                raise ValueError(f"invalid feature gate value {part!r}")
            self._set(k.strip(), v.lower() == "true")

    def enabled(self, name: str) -> bool:
        if name not in self._gates:
            raise ValueError(f"unknown feature gate {name!r}")
        return self._gates[name]

    # mutable (set_from_string), so equality only — no __hash__
    def __eq__(self, other) -> bool:
        return (isinstance(other, FeatureGates)
                and self._gates == other._gates)

    __hash__ = None

    def overrides(self) -> Dict[str, bool]:
        """Gates differing from the process defaults — the round-trippable
        spec (what a --feature-gates flag or versioned config would need
        to say to reproduce this object)."""
        return {k: v for k, v in self._gates.items()
                if DEFAULT_FEATURE_GATES[k] != v}


#: process-default gates (utilfeature.DefaultFeatureGate analog)
default_feature_gates = FeatureGates()


# ---------------------------------------------------------------------------
# ComponentConfig (apis/config/types.go:43 KubeSchedulerConfiguration)
# ---------------------------------------------------------------------------


@dataclass
class LeaderElectionConfig:
    leader_elect: bool = True
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    lock_object_namespace: str = "kube-system"
    lock_object_name: str = "kube-scheduler"


@dataclass
class RobustnessConfig:
    """Degradation-ladder knobs (no reference analog — the resilience
    layer around the out-of-process batch solver, kubernetes_tpu/faults
    + scheduler._solve_ladder). All times ride the scheduler's injected
    clock, so sim/chaos runs stay deterministic."""

    #: wall-clock budget for one scheduling cycle; 0 disables. Once the
    #: deadline passes, the ladder skips intermediate tiers straight to
    #: the terminal sequential oracle, and extender calls are shed.
    cycle_deadline_s: float = 0.0
    #: bounded in-cycle retries per solver tier before falling through
    solver_retries: int = 1
    #: transport retries (HTTP extender / gRPC shim) per request
    transport_retries: int = 2
    retry_backoff_base_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    #: +/- fractional jitter applied to each backoff interval
    retry_jitter: float = 0.2
    #: consecutive failed cycles before a tier's breaker opens
    breaker_failure_threshold: int = 3
    #: how long an open breaker sheds load before half-opening
    breaker_open_duration_s: float = 30.0
    #: trial calls admitted per half-open episode (the health probes)
    breaker_half_open_probes: int = 1
    #: validate solver results (shape/finiteness/range/capacity) before
    #: trusting them — what keeps a lying solver from binding an
    #: infeasible pod
    validate_results: bool = True
    #: route result validation through the HOST checker
    #: (ops/assign.validate_solution — the trust floor and parity oracle)
    #: instead of the fused on-device validator whose verdict rides the
    #: single end-of-solve readback. Host validation re-materializes the
    #: assignment and four tables per attempt (the PR-7 readback wall);
    #: keep it off unless debugging a suspected device-validator bug.
    host_validate: bool = False
    #: tiers tried after the configured solver fails; "greedy" is the
    #: sequential oracle floor and terminates the chain
    fallback_chain: Tuple[str, ...] = ("batch-cpu", "greedy")
    #: an open extender breaker (or blown deadline) skips the extender
    #: like an Ignorable one instead of failing its pods — progress over
    #: strictness while the remote is down
    extender_degrade_to_ignorable: bool = True
    #: read-your-write verification retries when a bind RPC times out
    #: AMBIGUOUSLY (faults.RPCTimeout — the hub may have committed): the
    #: scheduler GETs the pod and compares uid+nodeName to adopt or
    #: requeue instead of blind-retrying a bind that may have landed;
    #: this bounds the verification GETs per attempt (full-jitter
    #: backoff between them). Unresolvable verifications park the pod
    #: (still assumed) and re-probe each cycle / idle tick.
    bind_verify_retries: int = 3
    #: informer stall detection (sim.Reflector and any reflector built
    #: on it): a watch that delivers NOTHING for this long while the hub
    #: has advanced revisions is treated as silently stalled and forced
    #: to relist (with full-jitter backoff between forced relists so
    #: replicas cannot stampede a recovering hub). 0 disables.
    watch_progress_deadline_s: float = 30.0


@dataclass
class RecoveryConfig:
    """Crash/failover/device-loss recovery knobs (no reference analog —
    the process-level resilience layer above the PR-1 solver ladder):
    fenced binds, takeover reconciliation, and resident-snapshot rebuild
    after an accelerator loss. All times ride the scheduler's injected
    clock, so chaos runs stay deterministic."""

    #: gate every hub write (cache assume -> bind) on the elector's
    #: fencing check (LeaderElector.allow_bind): a deposed or
    #: renew-stalled leader's in-flight binds abort and requeue instead
    #: of racing the new leader at the hub CAS
    fenced_binds: bool = True
    #: on (re)gaining leadership, reconcile against the relisted hub
    #: truth: adopt pods a dead incarnation bound, forget assumptions
    #: the API contradicts, requeue unbound pods, rebuild the resident
    #: device snapshot, re-arm warmup
    reconcile_on_takeover: bool = True
    #: CAS an expired lease record on shutdown so the standby takes over
    #: immediately instead of waiting out the full lease duration
    release_lease_on_shutdown: bool = True
    #: consecutive resident-snapshot rebuild attempts per cycle after a
    #: device error before falling back to host-mode snapshots
    device_reset_limit: int = 2
    #: how long to stay on host-mode snapshots after the rebuild budget
    #: is exhausted before probing the device again (the heal probe)
    device_cooloff_s: float = 5.0


@dataclass
class LedgerConfig:
    """Perf ledger + SLO watchdog (obs/ledger.py): per-cycle
    measured-vs-modeled cost accounting and multi-window burn-rate
    objectives. Rides the observability block (``observability.ledger``)
    because it consumes ``end_cycle`` — the recorder's master switch
    gates it too."""

    #: fold each eventful cycle into the ledger (measured phase
    #: distributions, model efficiency, watchdog). Off = zero per-cycle
    #: cost beyond the flight record that already exists.
    enabled: bool = True
    #: ledger entry ring capacity (cycles); oldest entries evict
    history: int = 256
    #: retained samples per (phase x scope x mesh) distribution cell
    dist_window: int = 256
    #: EWMA decay for the phase trends AND the watchdog's rolling
    #: cycle-cost baseline (higher = faster re-basing after a change)
    baseline_decay: float = 0.05
    #: create-to-bind p99 objective, seconds (0 = objective off): the
    #: watchdog burns when more than 1% of bound pods exceed it
    e2e_p99_objective_s: float = 0.0
    #: cycle-cost drift objective (0 = off): a cycle whose solve cost
    #: exceeds ratio x the rolling per-scope baseline is a violation;
    #: more than 10% violating cycles in a window burns
    cost_drift_ratio: float = 0.0
    #: burn-rate windows (seconds, on the scheduler's clock): the
    #: watchdog trips only when BOTH windows burn (SRE multi-window
    #: rule) and recovers when the FAST window clears
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    #: burn rate (violating fraction / error budget) at which a window
    #: counts as burning
    burn_threshold: float = 1.0
    #: while burning, report the scheduler degraded so APF admission
    #: sheds earlier at the same queue depth (backend_pressure)
    engage_pressure: bool = True


@dataclass
class MemoryLedgerConfig:
    """Device-memory ledger (obs/memledger.py): HBM accounting with
    three faces — modeled resident-byte accounting for every
    device-resident structure, a measured side sampled at cycle
    boundaries only (``device.memory_stats()`` where the backend
    provides it, a bounded ``jax.live_arrays`` census otherwise), and
    the warmup-captured per-bucket peak table the capacity preflight
    judges each cycle's shape against. Rides the observability block
    (``observability.memoryLedger``) like the perf ledger does."""

    #: account resident structures + sample the measured side at cycle
    #: boundaries/idle ticks. Off = zero per-cycle cost and the
    #: preflight never engages.
    enabled: bool = True
    #: min seconds (owner clock) between measured-side samples; 0 =
    #: every cycle boundary. The sample is host-only metadata reads —
    #: never a device sync inside jit — but the CPU fallback's
    #: live-array census walk is O(live arrays) (~ms at bench scale),
    #: so the default keeps it off the per-cycle path: watermarks are
    #: a trend instrument, not a per-cycle one.
    sample_interval_s: float = 0.5
    #: capacity preflight: capture ``memory_analysis()`` per warmed
    #: bucket and judge each cycle's (P, N, mesh) against
    #: limit x headroom_frac, splitting to a smaller warmed bucket or
    #: shedding the batch instead of OOMing
    preflight: bool = True
    #: fraction of the device limit the preflight budgets (the rest is
    #: headroom for XLA scratch the per-bucket analysis undercounts)
    headroom_frac: float = 0.9
    #: device memory limit in bytes for the preflight budget and the
    #: ``limit`` gauge series. 0 = take the backend's
    #: ``memory_stats()['bytes_limit']`` when it reports one (CPU
    #: backends report none — the preflight then never fires unless a
    #: limit is configured here)
    limit_bytes: int = 0
    #: ledger entry ring capacity (cycles) and watermark history length
    history: int = 128
    #: max arrays the ``jax.live_arrays`` census walks per sample (the
    #: bounded fallback measured side on backends without memory_stats)
    census_limit: int = 4096


@dataclass
class JourneysConfig:
    """Per-pod journey tracer (obs/journey.py): decompose each bound
    pod's end-to-end latency into phase shares (queue-wait, backoff,
    solve, bind-rpc, ambiguous, permit) from the driver's existing host
    seams. Rides the observability block (``observability.journeys``)
    because completion feeds the flight-record vocabulary and the
    incident bundles."""

    #: track journeys (pure host bookkeeping, one lock, zero device
    #: syncs). Off = the seams no-op and /debug/journeys 404s.
    enabled: bool = True
    #: completed journeys retained per rolling window: the K slowest
    slow_k: int = 8
    #: unconditional completion sampling — every N-th bound pod is
    #: retained regardless of slowness (0 = off); keeps healthy
    #: representative timelines next to the tail
    sample_every: int = 100
    #: rolling retention window (seconds, owner clock) for the
    #: slowest-K tier
    window_s: float = 300.0
    #: max in-flight journeys tracked; pods beyond the cap are counted
    #: (``dropped``) but not tracked — pending state must stay bounded
    #: even under an unbounded backlog
    max_pending: int = 4096
    #: per-journey event/attempt row cap (beyond: counted as elided)
    max_events: int = 64


@dataclass
class IncidentsConfig:
    """Incident autopsies (obs/incidents.py): on an SLO-watchdog burn,
    auditor violation, OOM forensic, retrace storm, or ladder-fallback
    burst, capture ONE correlated bundle — flight window, ledger +
    memory + queue snapshots, slowest in-flight journeys, top reasons —
    onto a bounded ring (``/debug/incidents``, SIGUSR2). Rides the
    observability block (``observability.incidents``)."""

    #: evaluate triggers at each eventful cycle close. Off = zero cost.
    enabled: bool = True
    #: incident-bundle ring capacity; oldest bundles evict
    capacity: int = 16
    #: flight records kept per bundle: every record within this many
    #: cycles of the trigger cycle
    flight_window: int = 16
    #: slowest in-flight journeys embedded per bundle
    journeys_k: int = 4
    #: per-trigger suppression: a trigger that fired within this many
    #: cycles of its last bundle is dropped (a sustained burn yields
    #: one bundle, not one per cycle)
    cooldown_cycles: int = 64
    #: cycles a single ladder solve may fall back before the
    #: ``ladder-fallback`` trigger fires (0 = trigger off)
    fallback_burst_threshold: int = 3
    #: arm a ``jax.profiler.start_trace`` capture of this many cycles
    #: when an incident fires (0 = never profile automatically;
    #: /debug/profile can still arm one on demand)
    profile_cycles: int = 0
    #: artifact directory for profiler captures; empty = profiling off
    #: entirely (automatic AND on-demand)
    profile_dir: str = ""
    #: max profiler captures per process — the artifact dir is bounded
    #: even under a trigger flood
    max_profiles: int = 4


@dataclass
class ObservabilityConfig:
    """Observability knobs (kubernetes_tpu/obs): cycle tracing, the JAX
    compile/retrace telemetry, and the flight recorder. All times ride
    the scheduler's injected clock; sampling is deterministic
    (counter-based), so traced runs replay bit-identically."""

    #: master switch for the flight recorder + trace retention. The
    #: threshold-gated slow-cycle log (utiltrace LogIfLong) stays on
    #: either way — it is the cheap always-on profiler.
    enabled: bool = True
    #: cycles slower than this log their span breakdown (LogIfLong).
    trace_threshold_s: float = 1.0
    #: fraction of cycles whose full trace is RETAINED for /debug/traces
    #: and the Chrome exporter (1.0 = every cycle, 0 = none). Retention
    #: is deterministic: cycle k keeps its trace when floor(k*rate)
    #: advances.
    trace_sampling: float = 1.0
    #: flight-recorder ring capacity (cycles); oldest records evict.
    recorder_capacity: int = 256
    #: retained-trace ring capacity (traces held for export).
    trace_ring_capacity: int = 64
    #: retraces at one call site within the window that count as a storm
    retrace_storm_threshold: int = 8
    #: storm window, in calls at that site (count-based, no wall clock)
    retrace_storm_window: int = 64
    #: capture per-cycle Sinkhorn convergence stats (iteration count,
    #: final residual) when the sinkhorn tier solves a cycle
    sinkhorn_telemetry: bool = True
    #: batched schedulability explainer (obs/explain.py): reduce the
    #: cycle's (pod x node) failure bitmask into per-pod reason node
    #: counts, the cluster reason histogram, and one-bit-away
    #: relaxations — feeds /debug/why, the flight recorder's top
    #: reasons, and scheduler_unschedulable_* metrics. The reduction is
    #: jitted and read back at the cycle's existing host boundary; off
    #: drops the analytics but keeps the FitError event text.
    explain: bool = True
    #: relaxations kept per pod and reasons kept per flight record
    explain_top_k: int = 3
    #: state-conservation auditor (obs/audit.py): assert every pod sits
    #: in exactly one of {queued, assumed, bound, gone}, node capacity
    #: is never exceeded by committed binds, and no pod is lost or
    #: zombie-queued across audits. >0 = run it inside the serving
    #: runtime every this-many seconds (cheap: O(pods) host dict walks);
    #: 0 = off there (chaos suites run it continuously regardless).
    audit_interval_s: float = 0.0
    #: perf ledger + SLO watchdog (obs/ledger.py): per-cycle
    #: measured-vs-modeled accounting, burn-rate objectives
    ledger: LedgerConfig = field(default_factory=LedgerConfig)
    #: device-memory ledger (obs/memledger.py): modeled-vs-measured
    #: resident-byte accounting, capacity preflight, OOM forensics
    memory_ledger: MemoryLedgerConfig = field(
        default_factory=MemoryLedgerConfig)
    #: per-pod journey tracer (obs/journey.py): e2e latency decomposed
    #: into phase shares, /debug/journeys
    journeys: JourneysConfig = field(default_factory=JourneysConfig)
    #: incident autopsies (obs/incidents.py): correlated trigger
    #: bundles, /debug/incidents, optional profiler capture
    incidents: IncidentsConfig = field(default_factory=IncidentsConfig)
    #: instrumented-lock runtime sanitizer (sanitize.py): acquisition-
    #: order cycle detection, hold budgets, dynamic guarded-by checks —
    #: off by default (plain threading locks, zero overhead)
    lock_sanitizer: LockSanitizerConfig = field(
        default_factory=LockSanitizerConfig)


@dataclass
class WarmupConfig:
    """Ahead-of-time compile warmup (no reference analog): precompile the
    solver at the bucketed batch shapes the driver will hit, so first-pod
    latency never pays an XLA compile and queue-length churn cannot cause
    retraces (the shapes are already in the jit cache). Runs at startup
    (cli.run) / on demand (Scheduler.warmup); zero-valid synthetic pod
    batches make each warm call one cheap no-progress round."""

    enabled: bool = False
    #: pod-axis bucket sizes to precompile; empty = geometric x2 steps
    #: from ``min_bucket`` up to ``bucket_size(max_batch)`` (the same
    #: bucketing pods_to_device applies, so every runtime shape is
    #: covered by construction)
    pod_buckets: Tuple[int, ...] = ()
    #: smallest bucket warmed when ``pod_buckets`` is empty
    min_bucket: int = 256
    #: also warm the standalone filter pass (the failure-reason /
    #: explain path, compiled separately from the solver)
    include_filter: bool = True
    #: under a mesh, ALSO warm the single-device host-mode signatures —
    #: the shapes a device-loss cooloff cycle presents. Without it the
    #: first cycle after a lost shard pays a hot-path compile and reads
    #: as a retrace; the composed serving-on-mesh mode turns this on so
    #: shard loss mid-churn stays retrace-free end to end.
    host_fallback: bool = False
    #: when preemption is enabled, ALSO warm the nominated-pods solve
    #: variant: the cycle after a preemption carries a (P, N)
    #: feasibility mask (podFitsOnNode pass A — nominated pods counted
    #: onto their nodes), and ``extra_mask`` joins the solve's compile
    #: key. Left unwarmed, the FIRST post-preemption cycle pays a
    #: hot-path XLA compile and reads as a retrace — precisely when the
    #: cluster is tightest on capacity.
    nominated_variant: bool = True


@dataclass
class IncrementalConfig:
    """Incremental solve (docs/perf.md "incremental solve"): make the
    steady-state cycle cost proportional to CHURN instead of the full
    (P x N) plane. Three coupled pieces ride this block: the
    device-resident per-node score/feasibility cache (cache.py +
    ops/fused_score.py — clean node columns reused across cycles, dirty
    columns patched with the same donated-scatter discipline as the
    PR-5 snapshot delta), the restricted solve (the micro-batch solves
    against a bounded candidate-column bucket gathered from the cached
    plane instead of every node), and warm-started Sinkhorn potentials
    carried across cycles. The full cold solve remains the correctness
    fallback the ladder already knows how to take — on takeover,
    device-loss heal, pack-epoch growth, or dirty-frac blowout the
    cache drops and the next cycle solves cold."""

    enabled: bool = False
    #: candidate node columns the restricted solve gathers (snapped UP
    #: to a power of two so the (P, C) solve shapes stay in the warmed
    #: bucket grid — zero retraces under churn). Cycles where the
    #: padded cluster is not strictly larger than the bucket take the
    #: cold solve (restriction would not shrink anything).
    candidate_bucket: int = 256
    #: restricted solves admit at most candidate_bucket * this many
    #: pods per cycle (larger micro-batches could exhaust the candidate
    #: columns' capacity and under-place vs the cold solve)
    max_batch_frac: float = 0.5
    #: dirty-column fraction above which the score cache is dropped and
    #: the cycle solves cold (patching approaches full-recompute cost —
    #: the same blowout rule as the snapshot delta)
    max_dirty_frac: float = 0.25
    #: carry the previous solve's Sinkhorn potentials across cycles
    #: (ops/sinkhorn.py warm start) when the sinkhorn tier engages
    warm_potentials: bool = True
    #: early-exit tolerance for warm-started Sinkhorn scaling: when the
    #: warm residual is already under it, the solve exits after one
    #: verification iteration instead of the full budget
    warm_tol: float = 1e-3
    #: documented bound on the warm-vs-cold placement-quality delta
    #: (mean lean score, fraction) — the bench_compare incremental gate
    #: enforces it on every churn_incr record
    quality_delta: float = 0.02
    #: sparsity-first routing (docs/perf.md "Sparsity-first solve"):
    #: the restricted candidate solve is the PRIMARY route at scale —
    #: full-snapshot cycles lazily rebuild the score plane and still
    #: solve restricted, and the cold/full-rebuild path runs as
    #: capacity-balanced restricted BLOCKS plus one final remainder
    #: pass (partitioned cold) instead of one dense N-wide solve. The
    #: dense solve stays as the correctness oracle and the fallback for
    #: declined/under-placed attempts.
    primary: bool = False
    #: partition block count for the partitioned cold solve; 0 = auto
    #: (the padded node bucket over the candidate bucket, capped at 8 —
    #: enough blocks that no block solve sees more than ~N/8 columns,
    #: few enough that an adversarial batch can't multiply solves)
    cold_blocks: int = 0
    #: auto-tune the candidate bucket from observed micro-batch sizes
    #: and placement-depth telemetry (how deep in the candidate list
    #: accepted assignments actually land). The tuner only ever picks a
    #: bucket the warmup sweep compiled (zero retraces by
    #: construction); without a warmed ladder it stays pinned to
    #: ``candidate_bucket``.
    auto_tune: bool = False
    #: fraction of the candidate bucket that group-quota hints (a
    #: gang's home-slice columns, a scenario pack's candidate hint) may
    #: claim; a batch whose hint set exceeds the quota declines to the
    #: cold solve rather than starving the rank-picked candidates
    group_quota_frac: float = 0.5


@dataclass
class ParallelConfig:
    """Sharded execution backend (kubernetes_tpu/parallel): shard the
    node axis of the device-resident snapshot — and with it the (P, N)
    plane of every solve/validate/explain kernel — across a 1-D
    ``jax.sharding.Mesh``. Pods and selector tables replicate; GSPMD
    inserts the cross-device collectives (per-pod vectors only — no
    (P, N) matrix ever crosses ICI, see parallel/costmodel.py)."""

    #: ``"off"`` = single-device (today's behavior); ``"auto"`` = a mesh
    #: over every local device; an integer N = a mesh over the first N
    #: devices. N must be a power of two (validate_config rejects other
    #: counts — they cannot divide the power-of-two node buckets);
    #: ``make_mesh`` additionally falls back to the largest power-of-two
    #: subset when handed an odd device set at runtime.
    mesh: object = "off"  # "off" | "auto" | int


@dataclass
class ScenarioConfig:
    """Scenario packs (kubernetes_tpu/scenarios): swap the solve
    objective for a paper workload — constraint-based consolidation
    packing ("Priority Matters") or topology-aware DL gangs (Tesserae)
    — with device-computed placement-quality scores riding the cycle's
    existing readback (docs/scenarios.md)."""

    #: "" = scenario mode off (the stock spreading objective);
    #: "consolidation" | "gang-topology" select a pack
    pack: str = ""
    #: weight of the pack's extra (P, N) cost term (consolidation's
    #: occupied-node bias; the gang pack's points per ICI hop saved)
    cost_weight: float = 4.0
    #: consolidation only: nodes per fill block of the blocked
    #: fill-order tie-break (ties persist within a block so a round
    #: still admits ~fill_block * perNodeCap pods; smaller packs
    #: tighter, larger solves in fewer rounds)
    fill_block: int = 64
    #: consolidation only: solve priority-aware preemption cascades
    #: IN-BATCH — victims and displaced pods re-enter one dense solve in
    #: the same cycle instead of the per-pod nominate-and-wait loop
    preempt_in_batch: bool = True
    #: cap on preemptors + displaced pods entering one cascade re-solve
    cascade_max_pods: int = 1024
    #: gang pack only: consecutive slice (zone) indices per superpod —
    #: the middle tier of the hierarchical ICI distance
    superpod: int = 4
    #: compute + read back the per-cycle placement-quality vector
    quality: bool = True
    #: steady-state consolidation re-pack cadence (seconds; 0 = off,
    #: the pre-soak behavior where consolidation acts only at
    #: admission): every interval the scheduler drains the least-
    #: utilized occupied nodes whose pods the rest of the cluster can
    #: absorb and requeues them through the normal cycle, so sustained
    #: churn cannot ratchet fragmentation up between admissions
    repack_interval_s: float = 0.0
    #: per-repack cap on drained pods (bounds one repack's requeue
    #: burst; the cascade budget bounds the re-solve the same way)
    repack_max_pods: int = 64


@dataclass
class ServingConfig:
    """Streaming serving mode (kubernetes_tpu/serving): the event-driven
    micro-batch loop that replaces the fixed ``--cycle-interval`` sleep,
    plus the APF-style load-shedding knobs for the REST facades. All
    windows are seconds; the accumulation targets snap to the same
    power-of-two bucket grid the AOT warmup compiles, so steady-state
    churn never retraces."""

    #: run the event-driven serving loop instead of the fixed-interval
    #: legacy loop in cli.run
    enabled: bool = False
    #: shortest accumulation after the first pending pod — the burst-
    #: coalescing debounce (a bucket-fill may still flush at min_wait)
    min_wait_s: float = 0.005
    #: latency ceiling: the window always flushes by max_wait
    max_wait_s: float = 0.05
    #: accumulation cap in pods, snapped DOWN to a warmed bucket; the
    #: window flushes immediately at this depth
    target_bucket: int = 1024
    #: doorbell park time while the queue is idle (each timeout runs
    #: one idle_tick so backoff flushes still happen)
    idle_wait_s: float = 0.5
    #: APF-style per-flow seats (readonly/mutating flows)
    flow_concurrency: int = 16
    #: seats for the watch flow (fan-out is the expensive class)
    watch_concurrency: int = 8
    #: bounded FIFO of waiters per flow; full queue -> 429
    flow_queue_length: int = 64
    #: longest a queued request waits for a seat before shedding
    queue_timeout_s: float = 1.0
    #: Retry-After answered on 429s
    retry_after_s: float = 1.0
    #: per-watcher send-buffer bound: a watcher this far behind is
    #: disconnected with 410 Gone (relist) instead of stalling the hub
    watch_buffer: int = 4096
    #: backend-pressure shed bound for the mutating flow: admission
    #: sheds with 429 while ``Scheduler.backend_pressure()`` (active-
    #: queue depth, inflated when the solver ladder is degraded or the
    #: device is cooling off) exceeds it. 0 = auto: twice the
    #: accumulation target — two full micro-batches of headroom.
    shed_queue_bound: int = 0
    #: multiplier applied to the queue depth inside backend_pressure()
    #: while the backend is degraded (last cycle solved below the
    #: configured tier, or host-mode snapshots during a device cooloff):
    #: a limping solver sheds earlier at the same queue depth
    degraded_pressure_factor: float = 4.0


@dataclass
class KubeSchedulerConfiguration:
    """The typed component config. Reference fields keep their meanings;
    the ``solver``/``per_node_cap``/``max_batch`` block is this
    implementation's addition (batched-solver tuning)."""

    scheduler_name: str = "default-scheduler"
    algorithm_provider: str = "DefaultProvider"
    policy: Optional["Policy"] = None  # overrides algorithm_provider
    hard_pod_affinity_symmetric_weight: int = 1
    #: 100 = score every node (this framework's default: the dense batch
    #: solver evaluates all nodes in one fused pass, so the reference's
    #: default subsampling would only hurt quality); 0 = the reference's
    #: adaptive 50%->5% rule (parity runs); 1-99 = fixed percent.
    percentage_of_nodes_to_score: int = 100
    bind_timeout_seconds: float = 600.0
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    #: framework plugins to enable, by PLUGIN_REGISTRY name. The
    #: reference's Plugins struct (apis/config/types.go:98) enables per
    #: extension point; this framework's Plugin classes implement points
    #: by METHOD PRESENCE, so a flat enabled list is the honest recast —
    #: a plugin participates at exactly the points it implements.
    plugins: Tuple[str, ...] = ()
    #: per-plugin args (PluginConfig, types.go:127): name -> args mapping
    #: handed to the registered factory.
    plugin_config: Dict[str, dict] = field(default_factory=dict)
    # batched-solver tuning (no reference analog)
    solver: str = "batch"
    per_node_cap: int = 4
    max_rounds: int = 128
    max_batch: int = 8192
    # ---- pipelined cycle executor (scheduler._pipelined_tail) ----------
    #: 1 = today's monolithic cycle (the seqref-parity mode); >= 2 =
    #: batches larger than ``pipeline_chunk`` execute as fixed-size
    #: chunks with host packing of chunk k+1 and binding of chunk k-1
    #: overlapped with chunk k's device solve (double buffering).
    #: Chunking and data dependencies are identical at every depth >= 2,
    #: so placements are depth-invariant by construction.
    pipeline_depth: int = 2
    #: sub-batch size of the pipelined executor; batches at or under it
    #: stay monolithic. One fixed chunk shape per cycle also pins the
    #: solver's jit signature (last chunk pads to the same bucket).
    pipeline_chunk: int = 4096
    # ---- incremental device-resident snapshot (cache.device_snapshot) --
    #: keep the packed NodeTable resident on device across cycles,
    #: patching only dirty rows with a jitted scatter; False = legacy
    #: full host pack + upload every cycle
    device_resident_snapshot: bool = True
    #: dirty-row fraction above which the delta patch falls back to a
    #: full re-upload (patch cost approaches full-pack cost)
    snapshot_max_dirty_frac: float = 0.25
    #: incremental solve: device-resident score/feasibility cache,
    #: restricted candidate-column solves, warm-started potentials —
    #: steady-state cycle cost O(churn), not O(P x N)
    incremental: IncrementalConfig = field(default_factory=IncrementalConfig)
    #: AOT compile warmup of the bucketed solve shapes
    warmup: WarmupConfig = field(default_factory=WarmupConfig)
    #: degradation ladder / fault-tolerance knobs
    robustness: RobustnessConfig = field(default_factory=RobustnessConfig)
    #: crash / failover / device-loss recovery knobs (fenced binds,
    #: takeover reconciliation, resident-snapshot rebuild)
    recovery: RecoveryConfig = field(default_factory=RecoveryConfig)
    #: cycle tracing / JAX telemetry / flight-recorder knobs
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    #: streaming serving mode (event-driven micro-batch loop + APF-style
    #: load shedding)
    serving: ServingConfig = field(default_factory=ServingConfig)
    #: sharded execution backend (node-axis device mesh)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    #: scenario packs (pluggable solve objective + quality scores)
    scenario: ScenarioConfig = field(default_factory=ScenarioConfig)


# ---------------------------------------------------------------------------
# Legacy Policy (pkg/scheduler/api/types.go:46)
# ---------------------------------------------------------------------------

#: policy predicate name -> failure-reason bits it controls
#: (predicates.go:54-111 registration names)
PREDICATE_NAME_BITS: Dict[str, int] = {
    "PodFitsResources": 1 << BIT["PodFitsResources"],
    "PodFitsHostPorts": 1 << BIT["PodFitsHostPorts"],
    "HostName": 1 << BIT["PodFitsHost"],
    "MatchNodeSelector": 1 << BIT["PodMatchNodeSelector"],
    "GeneralPredicates": (
        (1 << BIT["PodFitsResources"]) | (1 << BIT["PodFitsHost"])
        | (1 << BIT["PodFitsHostPorts"]) | (1 << BIT["PodMatchNodeSelector"])
    ),
    "NoDiskConflict": 1 << BIT["NoDiskConflict"],
    "MaxEBSVolumeCount": 1 << BIT["MaxVolumeCount"],
    "MaxGCEPDVolumeCount": 1 << BIT["MaxVolumeCount"],
    "MaxAzureDiskVolumeCount": 1 << BIT["MaxVolumeCount"],
    "MaxCinderVolumeCount": 1 << BIT["MaxVolumeCount"],
    "MaxCSIVolumeCountPred": 1 << BIT["MaxVolumeCount"],
    "NoVolumeZoneConflict": 1 << BIT["NoVolumeZoneConflict"],
    "CheckVolumeBinding": (
        (1 << BIT["VolumeNodeConflict"]) | (1 << BIT["VolumeBindConflict"])
    ),
    "PodToleratesNodeTaints": 1 << BIT["PodToleratesNodeTaints"],
    "CheckNodeMemoryPressure": 1 << BIT["CheckNodeMemoryPressure"],
    "CheckNodeDiskPressure": 1 << BIT["CheckNodeDiskPressure"],
    "CheckNodePIDPressure": 1 << BIT["CheckNodePIDPressure"],
    "CheckNodeCondition": 1 << BIT["CheckNodeCondition"],
    "CheckNodeUnschedulable": 1 << BIT["CheckNodeUnschedulable"],
    "MatchInterPodAffinity": 1 << BIT["MatchInterPodAffinity"],
    "EvenPodsSpread": 1 << BIT["EvenPodsSpread"],
}

#: always-enforced regardless of Policy (RegisterMandatoryFitPredicate:
#: CheckNodeCondition register_predicates.go:119; PodToleratesNodeTaints +
#: CheckNodeUnschedulable under TaintNodesByCondition defaults.go:78-80)
#: plus VolumeError (unresolvable state is never schedulable).
MANDATORY_BITS = (
    (1 << BIT["CheckNodeCondition"])
    | (1 << BIT["PodToleratesNodeTaints"])
    | (1 << BIT["CheckNodeUnschedulable"])
    | (1 << BIT["VolumeError"])
)

ALL_PREDICATE_BITS = (1 << len(PREDICATE_BITS)) - 1

#: default provider predicate set (defaults.go:40 defaultPredicates)
DEFAULT_PREDICATE_NAMES = (
    "NoVolumeZoneConflict",
    "MaxEBSVolumeCount",
    "MaxGCEPDVolumeCount",
    "MaxAzureDiskVolumeCount",
    "MaxCSIVolumeCountPred",
    "MatchInterPodAffinity",
    "NoDiskConflict",
    "GeneralPredicates",
    "CheckNodeMemoryPressure",
    "CheckNodeDiskPressure",
    "CheckNodePIDPressure",
    "CheckNodeCondition",
    "PodToleratesNodeTaints",
    "CheckVolumeBinding",
)

#: default provider priorities (defaults.go:119 defaultPriorities) —
#: single source of truth lives next to the kernels
from kubernetes_tpu.ops.priorities import DEFAULT_WEIGHTS as DEFAULT_PRIORITY_WEIGHTS  # noqa: E402


def default_predicate_mask(gates: Optional[FeatureGates] = None) -> int:
    """Enabled-bit mask of the default provider + feature-gated additions
    (ApplyFeatureGates defaults.go:59: EvenPodsSpread joins when gated
    on)."""
    gates = gates or default_feature_gates
    bits = MANDATORY_BITS
    for name in DEFAULT_PREDICATE_NAMES:
        bits |= PREDICATE_NAME_BITS[name]
    if gates.enabled("EvenPodsSpread"):
        bits |= PREDICATE_NAME_BITS["EvenPodsSpread"]
    return bits


def default_priority_weights(gates: Optional[FeatureGates] = None) -> Dict[str, float]:
    gates = gates or default_feature_gates
    w = dict(DEFAULT_PRIORITY_WEIGHTS)
    if gates.enabled("EvenPodsSpread"):
        w["EvenPodsSpreadPriority"] = 1
    if gates.enabled("ResourceLimitsPriorityFunction"):
        w["ResourceLimitsPriority"] = 1
    return w


@dataclass
class ExtenderConfig:
    """pkg/scheduler/api/types.go:203 — out-of-process extender endpoint."""

    url_prefix: str = ""
    filter_verb: str = ""
    preempt_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout_s: float = 30.0
    node_cache_capable: bool = False
    managed_resources: Tuple[str, ...] = ()
    ignorable: bool = False


@dataclass
class Policy:
    """Decoded legacy Policy: the effective predicate mask, priority
    weights (custom parameterized priorities pre-registered under their
    policy names), and extenders."""

    predicate_mask: int = ALL_PREDICATE_BITS
    priority_weights: Dict[str, float] = field(default_factory=dict)
    extenders: List[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = 1
    always_check_all_predicates: bool = False


_policy_prio_seq = 0


def _register_unique(name: str, fn) -> str:
    """Register a policy-parameterized priority kernel under a unique
    internal name. Registrations go to a process-global registry (the
    weights dicts must reference hashable names across the jit boundary),
    so two policies configuring the SAME name with different parameters
    must not collide — each load gets its own entry; the Policy's weights
    dict carries the internal name."""
    global _policy_prio_seq
    from kubernetes_tpu.ops import priorities as prio

    _policy_prio_seq += 1
    internal = f"{name}#{_policy_prio_seq}"
    prio.register_priority(internal, fn)
    return internal


def load_policy(
    data, universe=None, gates: Optional[FeatureGates] = None
) -> Policy:
    """Decode a Policy JSON document (dict or JSON string) the way
    CreateFromConfig (factory.go:356) interprets it:

    - predicates **unspecified** -> default provider set; **empty list** ->
      only mandatory predicates;
    - priorities **unspecified** -> default priorities; **empty list** ->
      none;
    - parameterized priorities (LabelPreference,
      RequestedToCapacityRatioArguments) register custom kernels under the
      policy's name (``universe`` — a snapshot Universe — is required to
      intern label keys for LabelPreference).
    """
    from kubernetes_tpu.ops import priorities as prio

    if isinstance(data, str):
        data = json.loads(data)
    gates = gates or default_feature_gates
    out = Policy()
    out.hard_pod_affinity_symmetric_weight = int(
        data.get("hardPodAffinitySymmetricWeight", 1)
    )
    out.always_check_all_predicates = bool(
        data.get("alwaysCheckAllPredicates", False)
    )

    if "predicates" not in data:
        out.predicate_mask = default_predicate_mask(gates)
    else:
        bits = MANDATORY_BITS
        for p in data["predicates"]:
            name = p["name"]
            if name in PREDICATE_NAME_BITS:
                bits |= PREDICATE_NAME_BITS[name]
            # custom predicates (CheckNodeLabelPresence / CheckServiceAffinity)
            # attach as framework plugins — see policy_framework_plugins()
        out.predicate_mask = bits

    if "priorities" not in data:
        out.priority_weights = default_priority_weights(gates)
    else:
        weights: Dict[str, float] = {}
        for p in data["priorities"]:
            name, weight = p["name"], float(p.get("weight", 1))
            arg = p.get("argument") or {}
            if "labelPreference" in arg:
                if universe is None:
                    raise ValueError("LabelPreference needs the packer universe")
                lp = arg["labelPreference"]
                key_id = universe.label_keys.intern(lp["label"])
                name = _register_unique(
                    name, prio.make_node_label(key_id, bool(lp.get("presence", True)))
                )
            elif "requestedToCapacityRatioArguments" in arg:
                pts = arg["requestedToCapacityRatioArguments"]["utilizationShape"]
                shape = tuple(
                    (int(q["utilization"]), int(q["score"])) for q in pts
                )
                name = _register_unique(
                    name, prio.make_requested_to_capacity_ratio(shape)
                )
            elif name not in prio.PRIORITY_REGISTRY:
                raise ValueError(f"unknown priority {name!r}")
            weights[name] = weight
        out.priority_weights = weights

    for e in data.get("extenders", data.get("extenderConfigs", [])) or []:
        out.extenders.append(
            ExtenderConfig(
                url_prefix=e.get("urlPrefix", ""),
                filter_verb=e.get("filterVerb", ""),
                preempt_verb=e.get("preemptVerb", ""),
                prioritize_verb=e.get("prioritizeVerb", ""),
                bind_verb=e.get("bindVerb", ""),
                weight=int(e.get("weight", 1)),
                enable_https=bool(e.get("enableHttps", False)),
                http_timeout_s=float(e.get("httpTimeout", 30.0)),
                node_cache_capable=bool(e.get("nodeCacheCapable", False)),
                managed_resources=tuple(
                    r.get("name", "") for r in e.get("managedResources", []) or []
                ),
                ignorable=bool(e.get("ignorable", False)),
            )
        )
    return out
