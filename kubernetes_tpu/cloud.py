"""Cloud-provider seam — the in-tree cloud provider analog (SURVEY §2.2
"cloud providers: legacy in-tree AWS/GCE/Azure"; reference
``pkg/cloudprovider/providers`` via the ``cloudprovider.Interface`` in
``staging/src/k8s.io/cloud-provider/cloud.go`` and the cloud node
controller ``staging/src/k8s.io/cloud-provider/controllers/node``).

What the scheduler stack actually needs from a cloud: node *initialization*
(zone/region labels the topology kernels key on, provider IDs, addresses)
and node *existence* (is a quiet node dead or just slow — the node
lifecycle controller asks the cloud before deleting). Both are behind
:class:`CloudProvider`; :class:`FakeCloud` is the hollow in-tree provider
(the containervm/fake analog ``pkg/cloudprovider/providers/fake``).

Flow (cloud_node_controller.go syncNode): nodes register with the
``uninitialized`` NoSchedule taint; the controller looks the instance up
in the cloud, stamps provider ID + zone/region labels + addresses, and
removes the taint — only then does the scheduler see a feasible node.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import EFFECT_NO_SCHEDULE, Node, Taint

#: cloudprovider.TaintExternalCloudProvider — kubelets register with this
#: until the cloud controller initializes them (api/core/v1/well_known_taints)
TAINT_UNINITIALIZED = "node.cloudprovider.kubernetes.io/uninitialized"

LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"


@dataclass
class Instance:
    """One cloud VM record (the slice of Instances/Zones the node
    controller consumes)."""

    name: str
    provider_id: str = ""
    zone: str = ""
    region: str = ""
    instance_type: str = ""
    addresses: Tuple[Tuple[str, str], ...] = ()  # (type, address)
    exists: bool = True


class CloudProvider:
    """cloudprovider.Interface slice: Instances + Zones, plus the
    LoadBalancer and Routes halves the service/route controllers
    consume (cloud.go LoadBalancer()/Routes()). Implementations raise
    KeyError for unknown nodes (the NotFound the controller maps to
    'instance gone')."""

    def instance(self, node_name: str) -> Instance:
        raise NotImplementedError

    def instance_exists(self, node_name: str) -> bool:
        try:
            return self.instance(node_name).exists
        except KeyError:
            return False

    # -- LoadBalancer (cloud.go:116) ---------------------------------------

    def ensure_load_balancer(self, cluster: str, svc_key: str,
                             node_names: Tuple[str, ...]) -> str:
        """Create-or-update the external balancer for one service over
        the given backend node set; returns the ingress address
        (EnsureLoadBalancer is explicitly idempotent-upsert)."""
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, cluster: str,
                                     svc_key: str) -> None:
        raise NotImplementedError

    def list_load_balancers(self, cluster: str) -> Tuple[str, ...]:
        """Service keys with a live balancer — what the service
        controller's needsCleanup pass sweeps (GetLoadBalancer per
        service in the reference; a listing here so one call covers
        the sweep)."""
        raise NotImplementedError

    # -- Routes (cloud.go:134) ---------------------------------------------

    def list_routes(self, cluster: str) -> Dict[str, str]:
        """node name -> destination CIDR."""
        raise NotImplementedError

    def create_route(self, cluster: str, node_name: str,
                     cidr: str) -> None:
        raise NotImplementedError

    def delete_route(self, cluster: str, node_name: str) -> None:
        raise NotImplementedError


class FakeCloud(CloudProvider):
    """The fake in-tree provider: dicts of instances / balancers /
    routes, mutable by tests (terminate() is the cloud-side VM deletion
    the lifecycle controller must notice; ``fail_routes`` makes
    create_route raise — the cloud-quota failure the route controller
    must surface, not crash on)."""

    def __init__(self, provider: str = "fake") -> None:
        self.provider = provider
        self.instances: Dict[str, Instance] = {}
        #: svc key -> {"ingress": ip, "nodes": (names...)}
        self.load_balancers: Dict[str, dict] = {}
        self._lb_next = 1
        #: cluster routes: node name -> pod CIDR
        self.routes: Dict[str, str] = {}
        self.fail_routes = False
        self.lb_calls = 0
        self.route_calls = 0

    def add_instance(self, inst: Instance) -> None:
        if not inst.provider_id:
            inst.provider_id = f"{self.provider}://{inst.name}"
        self.instances[inst.name] = inst

    def terminate(self, node_name: str) -> None:
        if node_name in self.instances:
            self.instances[node_name].exists = False

    def instance(self, node_name: str) -> Instance:
        return self.instances[node_name]

    def ensure_load_balancer(self, cluster: str, svc_key: str,
                             node_names: Tuple[str, ...]) -> str:
        self.lb_calls += 1
        lb = self.load_balancers.get(svc_key)
        if lb is None:
            # TEST-NET-1 — an address range no real backend answers
            lb = {"ingress": f"192.0.2.{self._lb_next}", "nodes": ()}
            self._lb_next += 1
            self.load_balancers[svc_key] = lb
        lb["nodes"] = tuple(sorted(node_names))
        return lb["ingress"]

    def ensure_load_balancer_deleted(self, cluster: str,
                                     svc_key: str) -> None:
        self.load_balancers.pop(svc_key, None)

    def list_load_balancers(self, cluster: str) -> Tuple[str, ...]:
        return tuple(sorted(self.load_balancers))

    def list_routes(self, cluster: str) -> Dict[str, str]:
        return dict(self.routes)

    def create_route(self, cluster: str, node_name: str,
                     cidr: str) -> None:
        self.route_calls += 1
        if self.fail_routes:
            raise RuntimeError("cloud route quota exceeded")
        self.routes[node_name] = cidr

    def delete_route(self, cluster: str, node_name: str) -> None:
        self.routes.pop(node_name, None)


def uninitialized_node(name: str, **node_kw) -> Node:
    """A node as the kubelet registers it under an external cloud
    provider: tainted uninitialized, no zone labels yet."""
    nd = Node(name, **node_kw)
    return dataclasses.replace(
        nd, taints=nd.taints + (Taint(TAINT_UNINITIALIZED, value="true",
                                      effect=EFFECT_NO_SCHEDULE),))


class ServiceLBController:
    """The service controller (pkg/controller/service/
    service_controller.go:293 syncLoadBalancerIfNeeded): services of
    Type=LoadBalancer get an external balancer over the READY,
    schedulable node set; status.loadBalancer.ingress is written back
    through the hub; a type change away from LoadBalancer (or service
    deletion) tears the balancer down (needsCleanup). The node-set sync
    (nodeSyncLoop, :632 includeNodeFromNodeList: Ready condition,
    not-unschedulable) re-ensures every balancer when membership
    changes."""

    def __init__(self, hub, cloud: CloudProvider,
                 cluster: str = "ktpu") -> None:
        self.hub = hub
        self.cloud = cloud
        self.cluster = cluster
        self.ensures = 0
        self.teardowns = 0

    def _backend_nodes(self) -> Tuple[str, ...]:
        return tuple(sorted(
            nd.name for nd in self.hub.truth_nodes.values()
            if nd.conditions.ready and not nd.unschedulable))

    def reconcile(self) -> None:
        hub = self.hub
        nodes = self._backend_nodes()
        lb_services = set()
        for key, svc in list(hub.services.items()):
            if getattr(svc, "type", "ClusterIP") != "LoadBalancer":
                continue
            lb_services.add(key)
            ingress = self.cloud.ensure_load_balancer(
                self.cluster, key, nodes)
            self.ensures += 1
            if svc.load_balancer_ingress != ingress:
                svc.load_balancer_ingress = ingress
                hub._commit(f"services/{key}", "MODIFIED", svc)
                hub.record_controller_event(
                    "EnsuredLoadBalancer", key,
                    f"Ensured load balancer at {ingress}",
                    involved_kind="Service")
        # needsCleanup: balancers whose service is gone or no longer
        # Type=LoadBalancer (the hub's delete_service cannot know about
        # cloud state — this pass owns the teardown)
        for key in [k for k in self.cloud.list_load_balancers(self.cluster)
                    if k not in lb_services]:
            self.cloud.ensure_load_balancer_deleted(self.cluster, key)
            self.teardowns += 1
        # a service that LEFT LoadBalancer type keeps no stale ingress
        for key, svc in hub.services.items():
            if (getattr(svc, "type", "ClusterIP") != "LoadBalancer"
                    and getattr(svc, "load_balancer_ingress", "")):
                svc.load_balancer_ingress = ""
                hub._commit(f"services/{key}", "MODIFIED", svc)


class RouteController:
    """The route controller (pkg/controller/route/
    route_controller.go:139 reconcile): every node with a podCIDR gets
    a cloud route; routes for deleted nodes (or stale CIDRs after a
    same-name re-add) are removed. Success clears the node's
    NetworkUnavailable condition (:222 updateNetworkingCondition) —
    the gate that keeps pods off a node the dataplane can't reach;
    a cloud-side create failure leaves the condition set and surfaces
    as a counter, never a crash."""

    def __init__(self, hub, cloud: CloudProvider,
                 cluster: str = "ktpu") -> None:
        self.hub = hub
        self.cloud = cloud
        self.cluster = cluster
        self.create_failures = 0

    def _set_network_unavailable(self, name: str, value: bool) -> None:
        nd = self.hub.truth_nodes.get(name)
        if nd is None or nd.conditions.network_unavailable == value:
            return
        self.hub._update_node(dataclasses.replace(
            nd, conditions=dataclasses.replace(
                nd.conditions, network_unavailable=value)))

    def reconcile(self) -> None:
        hub = self.hub
        routes = self.cloud.list_routes(self.cluster)
        want = {name: nd.pod_cidr
                for name, nd in hub.truth_nodes.items() if nd.pod_cidr}
        for name, cidr in routes.items():
            if want.get(name) != cidr:
                self.cloud.delete_route(self.cluster, name)
        for name, cidr in want.items():
            if routes.get(name) != cidr:
                try:
                    self.cloud.create_route(self.cluster, name, cidr)
                except Exception as e:
                    # no working route: RAISE the condition (the
                    # CheckNodeCondition predicate keeps pods off this
                    # node) — updateNetworkingCondition's failure half;
                    # a stale route was already withdrawn above, so
                    # leaving the condition clear would claim a
                    # dataplane that does not exist
                    self.create_failures += 1
                    self._set_network_unavailable(name, True)
                    # cluster-scoped involved object (Node): empty
                    # namespace segment, so involvedObject.namespace
                    # field selectors match the reference's "" instead
                    # of a fabricated "default"
                    hub.record_controller_event(
                        "FailedToCreateRoute", f"/{name}",
                        f"Could not create route {cidr}: {e}",
                        type_="Warning", involved_kind="Node")
                    continue
            self._set_network_unavailable(name, False)


class CloudNodeController:
    """cloud_node_controller.go syncNode + the lifecycle half
    (cloud_node_lifecycle_controller.go): initialize tainted nodes from
    the cloud; delete nodes whose instance is gone."""

    def __init__(self, hub, cloud: CloudProvider) -> None:
        self.hub = hub
        self.cloud = cloud
        self.initialized = 0
        self.deleted = 0

    def reconcile(self) -> None:
        for name, nd in list(self.hub.truth_nodes.items()):
            tainted = any(t.key == TAINT_UNINITIALIZED for t in nd.taints)
            if tainted:
                try:
                    inst = self.cloud.instance(name)
                except KeyError:
                    continue  # not in the cloud yet; retry next sync
                if not inst.exists:
                    # terminated before initialization finished: never
                    # un-taint a dead VM — remove it outright
                    self.hub.remove_node(name)
                    self.deleted += 1
                    continue
                labels = dict(nd.labels)
                if inst.zone:
                    labels[LABEL_ZONE] = inst.zone
                if inst.region:
                    labels[LABEL_REGION] = inst.region
                if inst.instance_type:
                    labels[LABEL_INSTANCE_TYPE] = inst.instance_type
                new = dataclasses.replace(
                    nd,
                    labels=labels,
                    taints=tuple(t for t in nd.taints
                                 if t.key != TAINT_UNINITIALIZED),
                )
                self.hub._update_node(new)
                self.initialized += 1
            elif not self.cloud.instance_exists(name):
                # the VM is gone at the provider: remove the node object
                # (cloud_node_lifecycle_controller.go MonitorNodes)
                self.hub.remove_node(name)
                self.deleted += 1
