"""Cloud-provider seam — the in-tree cloud provider analog (SURVEY §2.2
"cloud providers: legacy in-tree AWS/GCE/Azure"; reference
``pkg/cloudprovider/providers`` via the ``cloudprovider.Interface`` in
``staging/src/k8s.io/cloud-provider/cloud.go`` and the cloud node
controller ``staging/src/k8s.io/cloud-provider/controllers/node``).

What the scheduler stack actually needs from a cloud: node *initialization*
(zone/region labels the topology kernels key on, provider IDs, addresses)
and node *existence* (is a quiet node dead or just slow — the node
lifecycle controller asks the cloud before deleting). Both are behind
:class:`CloudProvider`; :class:`FakeCloud` is the hollow in-tree provider
(the containervm/fake analog ``pkg/cloudprovider/providers/fake``).

Flow (cloud_node_controller.go syncNode): nodes register with the
``uninitialized`` NoSchedule taint; the controller looks the instance up
in the cloud, stamps provider ID + zone/region labels + addresses, and
removes the taint — only then does the scheduler see a feasible node.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import EFFECT_NO_SCHEDULE, Node, Taint

#: cloudprovider.TaintExternalCloudProvider — kubelets register with this
#: until the cloud controller initializes them (api/core/v1/well_known_taints)
TAINT_UNINITIALIZED = "node.cloudprovider.kubernetes.io/uninitialized"

LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"


@dataclass
class Instance:
    """One cloud VM record (the slice of Instances/Zones the node
    controller consumes)."""

    name: str
    provider_id: str = ""
    zone: str = ""
    region: str = ""
    instance_type: str = ""
    addresses: Tuple[Tuple[str, str], ...] = ()  # (type, address)
    exists: bool = True


class CloudProvider:
    """cloudprovider.Interface slice: Instances + Zones. Implementations
    raise KeyError for unknown nodes (the NotFound the controller maps
    to 'instance gone')."""

    def instance(self, node_name: str) -> Instance:
        raise NotImplementedError

    def instance_exists(self, node_name: str) -> bool:
        try:
            return self.instance(node_name).exists
        except KeyError:
            return False


class FakeCloud(CloudProvider):
    """The fake in-tree provider: a dict of instances, mutable by tests
    (terminate() is the cloud-side VM deletion the lifecycle controller
    must notice)."""

    def __init__(self, provider: str = "fake") -> None:
        self.provider = provider
        self.instances: Dict[str, Instance] = {}

    def add_instance(self, inst: Instance) -> None:
        if not inst.provider_id:
            inst.provider_id = f"{self.provider}://{inst.name}"
        self.instances[inst.name] = inst

    def terminate(self, node_name: str) -> None:
        if node_name in self.instances:
            self.instances[node_name].exists = False

    def instance(self, node_name: str) -> Instance:
        return self.instances[node_name]


def uninitialized_node(name: str, **node_kw) -> Node:
    """A node as the kubelet registers it under an external cloud
    provider: tainted uninitialized, no zone labels yet."""
    nd = Node(name, **node_kw)
    return dataclasses.replace(
        nd, taints=nd.taints + (Taint(TAINT_UNINITIALIZED, value="true",
                                      effect=EFFECT_NO_SCHEDULE),))


class CloudNodeController:
    """cloud_node_controller.go syncNode + the lifecycle half
    (cloud_node_lifecycle_controller.go): initialize tainted nodes from
    the cloud; delete nodes whose instance is gone."""

    def __init__(self, hub, cloud: CloudProvider) -> None:
        self.hub = hub
        self.cloud = cloud
        self.initialized = 0
        self.deleted = 0

    def reconcile(self) -> None:
        for name, nd in list(self.hub.truth_nodes.items()):
            tainted = any(t.key == TAINT_UNINITIALIZED for t in nd.taints)
            if tainted:
                try:
                    inst = self.cloud.instance(name)
                except KeyError:
                    continue  # not in the cloud yet; retry next sync
                if not inst.exists:
                    # terminated before initialization finished: never
                    # un-taint a dead VM — remove it outright
                    self.hub.remove_node(name)
                    self.deleted += 1
                    continue
                labels = dict(nd.labels)
                if inst.zone:
                    labels[LABEL_ZONE] = inst.zone
                if inst.region:
                    labels[LABEL_REGION] = inst.region
                if inst.instance_type:
                    labels[LABEL_INSTANCE_TYPE] = inst.instance_type
                new = dataclasses.replace(
                    nd,
                    labels=labels,
                    taints=tuple(t for t in nd.taints
                                 if t.key != TAINT_UNINITIALIZED),
                )
                self.hub._update_node(new)
                self.initialized += 1
            elif not self.cloud.instance_exists(name):
                # the VM is gone at the provider: remove the node object
                # (cloud_node_lifecycle_controller.go MonitorNodes)
                self.hub.remove_node(name)
                self.deleted += 1
