"""Columnar cluster snapshot — the tensor form of the reference's scheduler
cache snapshot.

The reference keeps per-node ``NodeInfo`` structs (requested/allocatable
resources, pods, used ports, taints, image states —
``pkg/scheduler/nodeinfo/node_info.go:50,:146``) and re-snapshots them
incrementally each cycle (``internal/cache/cache.go:211``
UpdateNodeInfoSnapshot). Here the snapshot is *columnar*: one dense array per
attribute across all nodes, plus multihot membership matrices for every
string-set attribute (label pairs, taint ids, port ids, image ids), so that
per-(pod,node) set intersections evaluate as integer matmuls on the MXU.

Ragged selector logic (nodeSelector maps, NodeAffinity requirement trees) is
compiled host-side into flat **expression tables** over a *selector-program*
universe: each distinct selector structure is interned once, its expressions
are rows of fixed-shape arrays, and the device evaluates all programs against
all nodes with segment reductions (AND within term, OR across terms). Pods
then just gather their program's row — deduplicating the (very common) case
of thousands of pods sharing one pod-template's selector.
"""

from __future__ import annotations

import re
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    Affinity,
    LabelSelector,
    Node,
    NodeSelectorTerm,
    Pod,
    Requirement,
    Resources,
    Toleration,
)
from kubernetes_tpu.utils.interner import Interner, bucket_size
from kubernetes_tpu.volumes import (
    CONFLICT_RO_ESCAPE,
    CSI_LIMIT_PREFIX,
    N_PD_FILTERS,
    ResolvedVolumes,
    VolumeState,
    node_has_zone_label,
    node_pd_limits,
    resolve_pod_volumes,
)

# Fixed resource columns; scalar/extended resources append after these.
# Mirrors nodeinfo.Resource (node_info.go:146).
RES_CPU, RES_MEM, RES_EPH, RES_PODS = 0, 1, 2, 3
N_FIXED_RESOURCES = 4
#: column names in RES_* order (events/FitError text; scalars append after)
FIXED_RESOURCE_NAMES = ("cpu", "memory", "ephemeral-storage", "pods")

# Expression opcodes for the device-side selector interpreter.
XOP_IN, XOP_NOT_IN, XOP_EXISTS, XOP_NOT_EXISTS, XOP_GT, XOP_LT = range(6)

#: Go strconv.ParseInt-compatible integer syntax (ASCII digits, optional sign)
_GO_INT_RE = re.compile(r"^[+-]?[0-9]+$")

_OPCODE = {
    OP_IN: XOP_IN,
    OP_NOT_IN: XOP_NOT_IN,
    OP_EXISTS: XOP_EXISTS,
    OP_DOES_NOT_EXIST: XOP_NOT_EXISTS,
    OP_GT: XOP_GT,
    OP_LT: XOP_LT,
}

# Sym-term kinds: the three classes of an *existing* pod's affinity terms
# that score the incoming pod by symmetry
# (priorities/interpod_affinity.go:46 CalculateInterPodAffinityPriority):
# required affinity (weight = hardPodAffinityWeight), preferred affinity
# (+w), preferred anti-affinity (-w).
SYM_HARD_AFF, SYM_SOFT_AFF, SYM_SOFT_ANTI = 0, 1, 2


def _canon_selector(sel: LabelSelector):
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple((r.key, r.operator, tuple(r.values)) for r in sel.match_expressions),
    )


@dataclass(frozen=True)
class CompiledExpr:
    op: int
    pair_ids: Tuple[int, ...] = ()  # In/NotIn: interned (key,value) ids
    key_id: int = -1  # Exists/DoesNotExist/Gt/Lt: interned key id
    literal: float = 0.0  # Gt/Lt


class Universe:
    """All interning state shared across snapshots. Grows monotonically;
    device-side arrays are padded to power-of-two buckets so growth rarely
    changes compiled shapes."""

    def __init__(self) -> None:
        self.node_names = Interner()
        self.scalar_resources = Interner()
        self.label_pairs = Interner()  # (key, value) referenced by selectors
        self.label_keys = Interner()  # keys referenced by Exists/DNE/Gt/Lt
        self.taints = Interner()  # (key, value, effect)
        self.ports_pp = Interner()  # (protocol, port)
        self.ports_pip = Interner()  # (protocol, hostIP, port), ip != wildcard
        self.images = Interner()  # image name
        self.image_sizes: List[float] = []
        # selector programs: canonical repr -> id; terms[i] = list of terms,
        # each term a list of CompiledExpr (AND within term, OR across terms)
        self.sel_programs = Interner()
        self.sel_program_terms: List[List[List[CompiledExpr]]] = []
        # preferred programs: list of (weight, [CompiledExpr]) terms (summed)
        self.pref_programs = Interner()
        self.pref_program_terms: List[List[Tuple[float, List[CompiledExpr]]]] = []
        # toleration sets
        self.tol_sets = Interner()
        self.tol_set_items: List[Tuple[Toleration, ...]] = []
        # owner-selector sets (SelectorSpread) — (namespace, canonical sels)
        self.owner_sets = Interner()
        self.owner_set_items: List[Tuple[str, tuple]] = []
        # zone keys (region, zone) — SelectorSpread zone weighting
        self.zones = Interner()
        # controller owner UIDs — NodePreferAvoidPods
        self.owner_uids = Interner()
        # ---- inter-pod affinity / topology spread universes --------------
        # (tensor form of predicates/metadata.go topologyPairsMaps :65)
        self.topo_keys = Interner()  # topology key strings
        self.topo_pairs = Interner()  # (key_id, value)
        # pod matchers: (namespaces-or-None, canonical selector) evaluated
        # against POD labels — shared by affinity terms & spread constraints
        self.pod_matchers = Interner()
        self.pod_matcher_items: List[Tuple[Optional[Tuple[str, ...]], LabelSelector]] = []
        # required (anti)affinity programs: rows (key_id, matcher_id, is_anti)
        self.aff_programs = Interner()
        self.aff_program_rows: List[List[Tuple[int, int, bool]]] = []
        # preferred (anti)affinity programs: rows (key_id, matcher_id, ±weight)
        self.pref_aff_programs = Interner()
        self.pref_aff_program_rows: List[List[Tuple[int, int, float]]] = []
        # distinct required anti-affinity terms of ANY pod — the symmetry
        # check (satisfiesExistingPodsAntiAffinity, predicates.go:~1400)
        self.anti_terms = Interner()  # (key_id, matcher_id)
        # distinct symmetric scoring terms: (key_id, matcher_id, weight, kind)
        self.sym_terms = Interner()
        # topology-spread programs: (rows, selprog_id); candidacy of a node
        # depends on the pod's node selector (metadata.go:232)
        self.spread_hard_programs = Interner()  # rows (key, matcher, maxSkew)
        self.spread_hard_program_rows: List[Tuple[Tuple[Tuple[int, int, int], ...], int]] = []
        self.spread_soft_programs = Interner()  # rows (key, matcher)
        self.spread_soft_program_rows: List[Tuple[Tuple[Tuple[int, int], ...], int]] = []
        # ---- volume universes (kubernetes_tpu.volumes) -------------------
        self.vol_conflict = Interner()  # (kind, handle) — NoDiskConflict tokens
        self.vol_conflict_escape: List[bool] = []  # read-only escape per token
        self.pd_volumes = Interner()  # (filter_idx, token) — MaxPDVolumeCount
        self.csi_drivers = Interner()  # CSI driver names
        self.csi_volumes = Interner()  # (driver_id, handle)
        # ---- label-fingerprint memos (pack-time hot path) ----------------
        # pods overwhelmingly share label sets (every pod of one RS /
        # service carries identical labels), so matcher and owner-set
        # evaluation memoizes on (ns, sorted labels); registry LENGTH in
        # the key invalidates when new matchers/sets are interned. The
        # TPU headline measured packing at 17% of wall — these two memos
        # are most of the selector-evaluation half of that.
        self._matcher_row_memo: Dict[tuple, np.ndarray] = {}
        self._owner_sets_memo: Dict[tuple, List[int]] = {}

    # -- resources ---------------------------------------------------------

    def n_resources(self) -> int:
        return N_FIXED_RESOURCES + len(self.scalar_resources)

    def resource_vector(self, r: Resources, out_len: Optional[int] = None) -> np.ndarray:
        for name in r.scalars:
            self.scalar_resources.intern(name)
        n = out_len or self.n_resources()
        v = np.zeros((n,), np.float32)
        v[RES_CPU] = r.cpu_milli
        v[RES_MEM] = r.memory
        v[RES_EPH] = r.ephemeral_storage
        v[RES_PODS] = r.pods
        for name, q in r.scalars.items():
            v[N_FIXED_RESOURCES + self.scalar_resources.intern(name)] = q
        return v

    # -- selector compilation ---------------------------------------------

    def _compile_requirement(self, r: Requirement) -> CompiledExpr:
        op = _OPCODE[r.operator]
        if op in (XOP_IN, XOP_NOT_IN):
            pair_ids = tuple(self.label_pairs.intern((r.key, v)) for v in r.values)
            return CompiledExpr(op=op, pair_ids=pair_ids)
        key_id = self.label_keys.intern(r.key)
        lit = 0.0
        if op in (XOP_GT, XOP_LT):
            v = r.values[0] if r.values else ""
            if not _GO_INT_RE.match(v):
                # unparsable Gt/Lt literal: the reference's selector
                # conversion errors and the term matches nothing — encode
                # as an unsatisfiable In-set, never crash the pack
                return CompiledExpr(op=XOP_IN, pair_ids=())
            lit = float(int(v))
        return CompiledExpr(op=op, key_id=key_id, literal=lit)

    def _compile_term(self, term: NodeSelectorTerm) -> List[CompiledExpr]:
        return [self._compile_requirement(r) for r in term.match_expressions]

    def intern_node_selector_program(
        self, node_selector: Dict[str, str], affinity: Affinity
    ) -> int:
        """Compile a pod's required node-selection (spec.nodeSelector AND
        RequiredDuringScheduling node affinity) into one program id.

        Semantics follow predicates.PodMatchNodeSelector
        (predicates.go:904 -> podMatchesNodeSelectorAndAffinityTerms):
        nodeSelector map is AND of equality pairs; affinity required terms
        are ORed, each term AND of expressions; both must pass.
        """
        terms: List[List[CompiledExpr]] = []
        base: List[CompiledExpr] = [
            self._compile_requirement(Requirement(k, OP_IN, (v,)))
            for k, v in sorted(node_selector.items())
        ]
        if affinity.node_required:
            for t in affinity.node_required:
                # an empty NodeSelectorTerm matches NO objects (apimachinery
                # helpers: "nil or empty term matches no objects") — skip it
                # rather than letting `base` alone stand in for the branch
                if t.match_expressions:
                    terms.append(base + self._compile_term(t))
            if not terms:
                # required affinity present but every term empty: the pod
                # can match nothing — emit one unsatisfiable term (empty
                # In-set evaluates false on every node)
                terms.append([CompiledExpr(op=XOP_IN, pair_ids=())])
        elif base:
            terms.append(base)
        if not terms:
            return -1
        key = tuple(
            tuple((e.op, e.pair_ids, e.key_id, e.literal) for e in t) for t in terms
        )
        pid = self.sel_programs.intern(key)
        if pid == len(self.sel_program_terms):
            self.sel_program_terms.append(terms)
        return pid

    def intern_preferred_program(self, affinity: Affinity) -> int:
        """PreferredDuringScheduling node affinity -> weighted term list
        (priorities/node_affinity.go: score = sum of weights of matched
        terms, then NormalizeReduce to 0-10)."""
        if not affinity.node_preferred:
            return -1
        terms = [
            (float(p.weight), self._compile_term(p.preference))
            for p in affinity.node_preferred
            if p.weight > 0 and p.preference.match_expressions
        ]
        if not terms:
            return -1
        key = tuple(
            (w, tuple((e.op, e.pair_ids, e.key_id, e.literal) for e in t))
            for w, t in terms
        )
        pid = self.pref_programs.intern(key)
        if pid == len(self.pref_program_terms):
            self.pref_program_terms.append(terms)
        return pid

    # -- tolerations -------------------------------------------------------

    def intern_toleration_set(self, tolerations: Tuple[Toleration, ...]) -> int:
        if not tolerations:
            return -1
        key = tuple(
            (t.key, t.operator, t.value, t.effect) for t in tolerations
        )
        tid = self.tol_sets.intern(key)
        if tid == len(self.tol_set_items):
            self.tol_set_items.append(tuple(tolerations))
        return tid

    def intern_taint(self, key: str, value: str, effect: str) -> int:
        return self.taints.intern((key, value, effect))

    def intern_image(self, name: str, size: float) -> int:
        iid = self.images.intern(name)
        if iid == len(self.image_sizes):
            self.image_sizes.append(float(size))
        else:
            # keep the max observed size (sizes should agree per name)
            self.image_sizes[iid] = max(self.image_sizes[iid], float(size))
        return iid

    # -- inter-pod affinity / spread ---------------------------------------

    def intern_matcher(
        self, namespaces: Optional[Tuple[str, ...]], selector: LabelSelector
    ) -> int:
        """(namespaces, selector) program matched against pods.
        ``namespaces=None`` = match any namespace (the soft-spread priority
        deliberately skips the namespace check — even_pods_spread.go:137)."""
        key = (tuple(sorted(namespaces)) if namespaces is not None else None,
               _canon_selector(selector))
        mid = self.pod_matchers.intern(key)
        if mid == len(self.pod_matcher_items):
            self.pod_matcher_items.append((namespaces, selector))
        return mid

    def matcher_matches(self, mid: int, pod: Pod) -> bool:
        ns, sel = self.pod_matcher_items[mid]
        if ns is not None and pod.namespace not in ns:
            return False
        return sel.matches(pod.labels)

    def _intern_pod_aff_term(self, pod: Pod, term) -> Tuple[int, int]:
        """(key_id, matcher_id) for one PodAffinityTerm; empty namespaces
        default to the defining pod's namespace (priorities/util
        GetNamespacesFromPodAffinityTerm)."""
        k = self.topo_keys.intern(term.topology_key)
        m = self.intern_matcher(term.namespaces or (pod.namespace,), term.label_selector)
        return k, m

    def intern_affinity_program(self, pod: Pod) -> int:
        """Required pod (anti)affinity of ``pod`` -> program id; also seeds
        the anti-term and sym-term universes with this pod's terms (the
        contributions it will make as an *existing* pod)."""
        a = pod.affinity
        if not (a.pod_affinity_required or a.pod_anti_affinity_required):
            self._seed_sym_terms(pod)
            return -1
        rows: List[Tuple[int, int, bool]] = []
        for t in a.pod_affinity_required:
            k, m = self._intern_pod_aff_term(pod, t)
            rows.append((k, m, False))
        for t in a.pod_anti_affinity_required:
            k, m = self._intern_pod_aff_term(pod, t)
            rows.append((k, m, True))
            self.anti_terms.intern((k, m))
        self._seed_sym_terms(pod)
        key = tuple(rows)
        pid = self.aff_programs.intern(key)
        if pid == len(self.aff_program_rows):
            self.aff_program_rows.append(rows)
        return pid

    def _seed_sym_terms(self, pod: Pod) -> None:
        a = pod.affinity
        for t in a.pod_affinity_required:
            k, m = self._intern_pod_aff_term(pod, t)
            self.sym_terms.intern((k, m, 1.0, SYM_HARD_AFF))
        for wt in a.pod_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            self.sym_terms.intern((k, m, float(wt.weight), SYM_SOFT_AFF))
        for wt in a.pod_anti_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            self.sym_terms.intern((k, m, float(wt.weight), SYM_SOFT_ANTI))

    def pod_sym_term_ids(self, pod: Pod) -> List[int]:
        """Sym-term ids this pod carries as an existing pod (lookup only)."""
        out = []
        a = pod.affinity
        for t in a.pod_affinity_required:
            k, m = self._intern_pod_aff_term(pod, t)
            out.append(self.sym_terms.lookup((k, m, 1.0, SYM_HARD_AFF)))
        for wt in a.pod_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            out.append(self.sym_terms.lookup((k, m, float(wt.weight), SYM_SOFT_AFF)))
        for wt in a.pod_anti_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            out.append(self.sym_terms.lookup((k, m, float(wt.weight), SYM_SOFT_ANTI)))
        return [i for i in out if i >= 0]

    def pod_anti_term_ids(self, pod: Pod) -> List[int]:
        out = []
        for t in pod.affinity.pod_anti_affinity_required:
            k, m = self._intern_pod_aff_term(pod, t)
            out.append(self.anti_terms.lookup((k, m)))
        return [i for i in out if i >= 0]

    def intern_pref_affinity_program(self, pod: Pod) -> int:
        """Preferred pod (anti)affinity -> signed weighted rows (the
        incoming-pod half of CalculateInterPodAffinityPriority)."""
        a = pod.affinity
        if not (a.pod_affinity_preferred or a.pod_anti_affinity_preferred):
            return -1
        rows: List[Tuple[int, int, float]] = []
        for wt in a.pod_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            rows.append((k, m, float(wt.weight)))
        for wt in a.pod_anti_affinity_preferred:
            k, m = self._intern_pod_aff_term(pod, wt.term)
            rows.append((k, m, -float(wt.weight)))
        key = tuple(rows)
        pid = self.pref_aff_programs.intern(key)
        if pid == len(self.pref_aff_program_rows):
            self.pref_aff_program_rows.append(rows)
        return pid

    def intern_spread_programs(self, pod: Pod, selprog_id: int) -> Tuple[int, int]:
        """(hard_id, soft_id) topology-spread programs. Hard constraints
        match same-namespace pods (metadata.go:246); soft constraints match
        any namespace (even_pods_spread.go:137 — alpha quirk preserved)."""
        hard: List[Tuple[int, int, int]] = []
        soft: List[Tuple[int, int]] = []
        for c in pod.topology_spread:
            k = self.topo_keys.intern(c.topology_key)
            if c.when_unsatisfiable == "DoNotSchedule":
                m = self.intern_matcher((pod.namespace,), c.label_selector)
                hard.append((k, m, int(c.max_skew)))
            else:
                m = self.intern_matcher(None, c.label_selector)
                soft.append((k, m))
        hid = sid = -1
        if hard:
            key = (tuple(hard), selprog_id)
            hid = self.spread_hard_programs.intern(key)
            if hid == len(self.spread_hard_program_rows):
                self.spread_hard_program_rows.append((tuple(hard), selprog_id))
        if soft:
            key = (tuple(soft), selprog_id)
            sid = self.spread_soft_programs.intern(key)
            if sid == len(self.spread_soft_program_rows):
                self.spread_soft_program_rows.append((tuple(soft), selprog_id))
        return hid, sid

    def self_aff_match(self, pod: Pod) -> bool:
        """targetPodMatchesAffinityOfPod(pod, pod): the pod matches the
        namespace+selector of ALL its required affinity terms — the
        first-pod-of-a-group escape hatch (predicates.go:1437)."""
        terms = pod.affinity.pod_affinity_required
        if not terms:
            return False
        for t in terms:
            ns = t.namespaces or (pod.namespace,)
            if pod.namespace not in ns or not t.label_selector.matches(pod.labels):
                return False
        return True

    def pod_matcher_row(self, pod: Pod, width: int) -> np.ndarray:
        """Multihot of matchers this pod satisfies — its contribution to
        per-node matcher counts when it is (or becomes) scheduled.
        Memoized per (registry length, width, ns, labels); callers only
        read the row (+= / assignment into larger arrays copy), so the
        shared array is safe."""
        n = len(self.pod_matcher_items)
        key = (n, width, pod.namespace,
               tuple(sorted(pod.labels.items())))
        row = self._matcher_row_memo.get(key)
        if row is not None:
            return row
        if self._matcher_row_memo and next(
                iter(self._matcher_row_memo))[0] != n:
            # registry grew: every cached row is stale — drop them all
            # (long-lived universes would otherwise accumulate dead keys)
            self._matcher_row_memo.clear()
        row = np.zeros((width,), np.int8)
        for mid in range(n):
            if self.matcher_matches(mid, pod):
                row[mid] = 1
        self._matcher_row_memo[key] = row
        return row

    # -- volumes -----------------------------------------------------------

    def intern_volume_refs(self, rv: ResolvedVolumes) -> None:
        """Seed the volume universes (+ zone label pairs + PV-affinity
        selector programs) with one pod's resolved volumes so widths are
        stable by pack time."""
        for kind, handle, _ro in rv.conflict:
            cid = self.vol_conflict.intern((kind, handle))
            if cid == len(self.vol_conflict_escape):
                self.vol_conflict_escape.append(CONFLICT_RO_ESCAPE[kind])
        for fi, tok in rv.pd:
            self.pd_volumes.intern((fi, tok))
        for driver, handle in rv.csi:
            d = self.csi_drivers.intern(driver)
            self.csi_volumes.intern((d, handle))
        for key, allowed in rv.zone_rows:
            for z in allowed:
                self.label_pairs.intern((key, z))
        for terms in rv.bound_affinity:
            self.intern_node_selector_program({}, Affinity(node_required=tuple(terms)))
        for cands in rv.unbound_clauses:
            for terms in cands:
                if terms:
                    self.intern_node_selector_program(
                        {}, Affinity(node_required=tuple(terms))
                    )

    def pv_affinity_program(self, terms) -> int:
        """Selector-program id of a PV's node affinity (already interned)."""
        return self.intern_node_selector_program({}, Affinity(node_required=tuple(terms)))

    # -- owner selectors (SelectorSpread) ----------------------------------

    def intern_owner_set(self, namespace: str, selectors) -> int:
        if not selectors:
            return -1
        key = (
            namespace,
            tuple(
                (
                    tuple(sorted(s.match_labels.items())),
                    tuple((r.key, r.operator, tuple(r.values)) for r in s.match_expressions),
                )
                for s in selectors
            ),
        )
        oid = self.owner_sets.intern(key)
        if oid == len(self.owner_set_items):
            self.owner_set_items.append((namespace, tuple(selectors)))
        return oid


# ---------------------------------------------------------------------------
# Packed tables (host-side numpy; converted to device arrays at the jit
# boundary — see kubernetes_tpu.ops)
# ---------------------------------------------------------------------------


@dataclass
class NodeTable:
    """Columnar NodeInfo over all nodes. Row order is the packing order;
    ``name_id[i]`` maps back to the node name."""

    n: int
    name_id: np.ndarray  # (N,) i32
    allocatable: np.ndarray  # (N, R) f32
    requested: np.ndarray  # (N, R) f32 — sum of scheduled pods' requests
    nonzero_req: np.ndarray  # (N, 2) f32 — scoring request sums w/ defaults
    pair_mh: np.ndarray  # (N, Up) i8 — has (key,value) for interned pairs
    key_mh: np.ndarray  # (N, Uk) i8 — has key
    key_val: np.ndarray  # (N, Uk) f32 — numeric label value (0 if not)
    key_num: np.ndarray  # (N, Uk) i8 — label value parsed as integer OK
    taint_hard_mh: np.ndarray  # (N, Ut) i8 — NoSchedule|NoExecute taints
    taint_soft_mh: np.ndarray  # (N, Ut) i8 — PreferNoSchedule taints
    port_any_mh: np.ndarray  # (N, Upp) i8 — (proto,port) used by any pod
    port_wild_mh: np.ndarray  # (N, Upp) i8 — used with wildcard hostIP
    port_spec_mh: np.ndarray  # (N, Upip) i8 — used with specific hostIP
    image_mh: np.ndarray  # (N, Ui) i8
    owner_counts: np.ndarray  # (N, Uo) f32 — matching scheduled pods per owner set
    zone_id: np.ndarray  # (N,) i32 — interned (region, zone); -1 unlabeled
    zone_valid: np.ndarray  # (Z,) bool — static zone-universe size carrier
    avoid_mh: np.ndarray  # (N, Uu) i8 — preferAvoidPods owner UIDs
    ready: np.ndarray  # (N,) bool
    network_unavailable: np.ndarray  # (N,) bool
    schedulable: np.ndarray  # (N,) bool — NOT spec.unschedulable
    mem_pressure: np.ndarray  # (N,) bool
    disk_pressure: np.ndarray  # (N,) bool
    pid_pressure: np.ndarray  # (N,) bool
    # ---- inter-pod affinity / spread state -------------------------------
    topo_pair_id: np.ndarray  # (N, K) i32 — node's pair per topo key; -1 absent
    matcher_counts: np.ndarray  # (N, M) f32 — scheduled pods matching matcher m
    anti_counts: np.ndarray  # (N, Ua) f32 — pods carrying required anti term a
    sym_counts: np.ndarray  # (N, Us) f32 — pods carrying sym scoring term s
    aff_pod_count: np.ndarray  # (N,) f32 — pods with any (anti)affinity
    # ---- volume state ----------------------------------------------------
    vol_any_mh: np.ndarray  # (N, Uv) i8 — conflict token mounted by any pod
    vol_rw_mh: np.ndarray  # (N, Uv) i8 — mounted NOT read-only by some pod
    pd_mh: np.ndarray  # (N, Uvd) i8 — count-checked volume tokens present
    pd_limit: np.ndarray  # (N, 4) f32 — attach limit per in-tree filter kind
    csi_mh: np.ndarray  # (N, Uvc) i8 — CSI volume tokens present
    csi_limit: np.ndarray  # (N, Dc) f32 — per-driver limit; +inf = none
    has_zone_label: np.ndarray  # (N,) bool — VolumeZone fast-path carrier


@dataclass
class PodTable:
    """Columnar pending-pod batch."""

    n: int
    req: np.ndarray  # (P, R) f32
    nonzero_req: np.ndarray  # (P, 2) f32
    selprog_id: np.ndarray  # (P,) i32, -1 = unconstrained
    prefprog_id: np.ndarray  # (P,) i32, -1 = none
    tolset_id: np.ndarray  # (P,) i32, -1 = no tolerations
    name_req: np.ndarray  # (P,) i32, -1 = no spec.nodeName requirement
    priority: np.ndarray  # (P,) i32
    port_wild_pp: np.ndarray  # (P, Upp) i8 — wildcard-IP ports
    port_spec_pp: np.ndarray  # (P, Upp) i8 — specific-IP ports, (proto,port) view
    port_spec_pip: np.ndarray  # (P, Upip) i8
    image_mh: np.ndarray  # (P, Ui) i8
    owner_id: np.ndarray  # (P,) i32, -1 = no owning service/controller
    owner_uid_id: np.ndarray  # (P,) i32, -1 = no controller ownerRef
    #: which owner sets this pod's labels match — placing the pod bumps
    #: those columns of NodeTable.owner_counts (device-side spread update)
    owner_match_mh: np.ndarray  # (P, Uo) i8
    order: np.ndarray  # (P,) i32 — original index of each row (sort tracking)
    # ---- inter-pod affinity / spread -------------------------------------
    matcher_mh: np.ndarray  # (P, M) i8 — matchers this pod satisfies
    affprog_id: np.ndarray  # (P,) i32 — required (anti)affinity program; -1 none
    prefaffprog_id: np.ndarray  # (P,) i32 — preferred program; -1 none
    spread_hard_id: np.ndarray  # (P,) i32
    spread_soft_id: np.ndarray  # (P,) i32
    self_aff_match: np.ndarray  # (P,) bool — pod matches own affinity terms
    anti_term_mh: np.ndarray  # (P, Ua) i8 — its required anti terms
    sym_term_mh: np.ndarray  # (P, Us) f32 — its sym terms (counts, can repeat)
    has_aff: np.ndarray  # (P,) bool — any pod (anti)affinity at all
    # ---- volumes ---------------------------------------------------------
    vol_any_mh: np.ndarray  # (P, Uv) i8
    vol_rw_mh: np.ndarray  # (P, Uv) i8
    pd_mh: np.ndarray  # (P, Uvd) i8
    csi_mh: np.ndarray  # (P, Uvc) i8
    vol_error: np.ndarray  # (P,) bool — unresolvable volume state
    #: (P, 2) f32 cpu/mem LIMITS (ResourceLimitsPriority)
    limits: np.ndarray = None


@dataclass
class SelectorTables:
    """Flattened expression tables for required + preferred programs, plus
    per-toleration-set tolerated-taint multihots."""

    # required programs
    n_exprs: int
    n_terms: int
    n_progs: int
    expr_term: np.ndarray  # (E,) i32 — term id of each expr
    expr_op: np.ndarray  # (E,) i32
    expr_pairs_mh: np.ndarray  # (E, Up) i8
    expr_key: np.ndarray  # (E,) i32 (index into key universe; -1 unused)
    expr_lit: np.ndarray  # (E,) f32
    term_prog: np.ndarray  # (T,) i32 — program id of each term
    # preferred programs (flat weighted terms)
    p_n_exprs: int
    p_n_terms: int
    p_n_progs: int
    p_expr_term: np.ndarray
    p_expr_op: np.ndarray
    p_expr_pairs_mh: np.ndarray
    p_expr_key: np.ndarray
    p_expr_lit: np.ndarray
    p_term_prog: np.ndarray
    p_term_weight: np.ndarray  # (Tp,) f32
    # tolerations
    tol_hard_mh: np.ndarray  # (Stol, Ut) i8 — taint ids tolerated (hard effects)
    tol_soft_mh: np.ndarray  # (Stol, Ut) i8 — PreferNoSchedule taint ids tolerated
    image_sizes: np.ndarray  # (Ui,) f32


@dataclass
class TopologyTables:
    """Flattened inter-pod-affinity + topology-spread term tables — the
    static (per-universe) half of the topologyPairsMaps machinery
    (predicates/metadata.go:65); the dynamic half is the per-node count
    matrices in NodeTable (matcher/anti/sym counts) that the assignment
    loop updates as pods land."""

    n_pairs: int  # true topo-pair count (arrays padded to bucket)
    n_matchers: int  # matcher-universe width M (bucketed, = widths()["M"])
    # required (anti)affinity rows
    ra_n_rows: int
    ra_n_progs: int
    ra_prog: np.ndarray  # (Ta,) i32
    ra_key: np.ndarray  # (Ta,) i32 — topo-key index
    ra_m: np.ndarray  # (Ta,) i32 — matcher id
    ra_anti: np.ndarray  # (Ta,) bool
    # preferred rows (signed weights)
    rp_n_rows: int
    rp_n_progs: int
    rp_prog: np.ndarray
    rp_key: np.ndarray
    rp_m: np.ndarray
    rp_w: np.ndarray  # (Tp,) f32 signed
    # anti-term table (columns of NodeTable.anti_counts)
    at_key: np.ndarray  # (Ua,) i32
    at_m: np.ndarray  # (Ua,) i32
    # sym-term table (columns of NodeTable.sym_counts)
    st_key: np.ndarray  # (Us,) i32
    st_m: np.ndarray  # (Us,) i32
    st_w: np.ndarray  # (Us,) f32 — signed soft weight; 0 for hard terms
    st_hard: np.ndarray  # (Us,) f32 — 1 for hard-affinity terms
    # spread hard rows + per-program candidacy selector
    sh_n_rows: int
    sh_n_progs: int
    sh_prog: np.ndarray
    sh_key: np.ndarray
    sh_m: np.ndarray
    sh_skew: np.ndarray  # (Tsh,) f32
    shp_selprog: np.ndarray  # (Gsh,) i32 — node-selector program; -1 = all
    # spread soft rows
    ss_n_rows: int
    ss_n_progs: int
    ss_prog: np.ndarray
    ss_key: np.ndarray
    ss_m: np.ndarray
    ssp_selprog: np.ndarray  # (Gss,) i32


@dataclass
class VolumeTables:
    """Universe-level volume metadata + batch-level zone/binding constraint
    rows for one pending-pod pack (row indices reference that batch)."""

    conflict_escape: np.ndarray  # (Uv,) f32 — read-only escape per token
    pd_type: np.ndarray  # (Uvd,) i32 — filter kind of each count token
    csi_driver: np.ndarray  # (Uvc,) i32 — driver id of each CSI token
    n_csi_drivers: int
    # VolumeZone rows: AND across a pod's rows; a row passes on nodes that
    # carry one of the allowed (key, value) label pairs or no zone labels
    vz_n_rows: int
    vz_pod: np.ndarray  # (Rv,) i32
    vz_pairs_mh: np.ndarray  # (Rv, Up) i8
    # VolumeBinding CNF: AND over clauses; clause = OR over rows, each row
    # one PV-affinity selector program; empty clause = unsatisfiable
    vb_n_rows: int
    vb_n_clauses: int
    vb_row_clause: np.ndarray  # (Rb,) i32
    vb_row_prog: np.ndarray  # (Rb,) i32
    vb_clause_pod: np.ndarray  # (Cb,) i32
    vb_clause_bound: np.ndarray  # (Cb,) bool — bound- vs unbound-PVC clause


def _pod_has_affinity(pod: Pod) -> bool:
    """NodeInfo.PodsWithAffinity membership: any pod (anti)affinity,
    required or preferred (nodeinfo/node_info.go AddPod)."""
    a = pod.affinity
    return bool(
        a.pod_affinity_required
        or a.pod_anti_affinity_required
        or a.pod_affinity_preferred
        or a.pod_anti_affinity_preferred
    )


def _matching_owner_sets(u: Universe, pod: Pod) -> List[int]:
    """Owner-set ids whose (namespace, selectors) match this pod — the
    single source of truth for SelectorSpread matching, used for both
    NodeTable.owner_counts and PodTable.owner_match_mh (which the
    assignment usage updates assume are computed identically).
    Memoized per (registry length, ns, labels) — see Universe's
    fingerprint memos."""
    n = len(u.owner_set_items)
    key = (n, pod.namespace, tuple(sorted(pod.labels.items())))
    hit = u._owner_sets_memo.get(key)
    if hit is not None:
        return hit
    if u._owner_sets_memo and next(iter(u._owner_sets_memo))[0] != n:
        u._owner_sets_memo.clear()  # registry grew: all entries stale
    out = [
        o
        for o, (ns, sels) in enumerate(u.owner_set_items)
        if ns == pod.namespace and all(s.matches(pod.labels) for s in sels)
    ]
    u._owner_sets_memo[key] = out
    return out


class SnapshotPacker:
    """Packs API objects into the columnar tables. The driver calls
    ``intern_pod`` on arrival (so universes are stable by pack time), then
    ``pack_nodes`` / ``pack_pods`` per scheduling cycle.

    Column widths are padded to power-of-two buckets (``bucket_size``) so
    that XLA shapes stay stable while universes grow.
    """

    def __init__(self, universe: Optional[Universe] = None) -> None:
        self.u = universe or Universe()
        self._pod_refs: Dict[tuple, Tuple[int, int, int, int]] = {}
        #: monotonically bumped whenever state OUTSIDE the append-only
        #: universes can change already-packed row content: volume-state
        #: replacement, assume/bind claim-lifecycle invalidation, pod
        #: forgetting. Part of every pack-memo key (universe_sig), so a
        #: memoized table can never outlive the state it was packed from.
        self._pack_epoch = 0
        #: memoized PodTable / VolumeTables per (batch identity, universe
        #: signature): steady-state cycles re-pack the SAME pending pods
        #: (backoff retries, bench warm loops) — a hit turns the per-pod
        #: python packing loop into one tuple hash. Bounded LRU.
        self._pod_table_memo: "OrderedDict[tuple, PodTable]" = OrderedDict()
        self._vol_table_memo: "OrderedDict[tuple, VolumeTables]" = OrderedDict()
        # volume listers + per-pod resolution cache (state-dependent, so
        # cached separately from _pod_refs and dropped on state change)
        self.vol_state = VolumeState()
        self._vol_pods: Dict[tuple, Pod] = {}
        self._vol_cache: Dict[tuple, ResolvedVolumes] = {}
        # per-pod resource vectors (R-dependent; recomputed when the scalar
        # universe grows) feeding the native usage aggregation
        self._vec_cache: Dict[tuple, Tuple[int, np.ndarray, np.ndarray]] = {}
        #: node name -> PV names attached there WITHOUT a live bound pod
        #: using them (the attach-detach controller's actual-state
        #: residue: detach-grace stragglers). These occupy attach-limit
        #: slots, so the volume-count predicates must see them even
        #: though no pod's volumes derive them (attach_detach_controller
        #: .go:102 — actual state feeds the scheduler via node.status
        #: volumesAttached in the reference).
        self.attached_residue: Dict[str, Tuple[str, ...]] = {}

    # -- volume state ------------------------------------------------------

    def set_volume_state(self, pvcs=(), pvs=(), classes=()) -> None:
        """Replace the PVC/PV/StorageClass listers (informer feed analog).
        All known pods' volumes re-resolve so universes stay complete.
        The ASSUME overlay carries over: reservations are binder state,
        not lister data — an informer relist never clears the reference's
        pvCache assumptions (assume wins until bind or forget), and a
        hub-driven re-sync mid-Permit must not leak another claimant onto
        a reserved PV."""
        assumed = dict(self.vol_state.assumed_claims)
        self.vol_state = VolumeState.build(pvcs, pvs, classes)
        self.vol_state.assumed_claims.update(assumed)
        self._vol_cache.clear()
        self._pack_epoch += 1
        for pod in self._vol_pods.values():
            self.resolve_volumes(pod)

    def refresh_volume_resolutions(self) -> None:
        """Invalidate memoized volume resolutions — the assume/bind
        lifecycle mutates claim state in place (assumed_claims overlay,
        committed claimRefs), which changes unbound-clause candidate sets
        for other claimants. Lazy: re-resolution happens on the next
        resolve_volumes call per pod (the pack paths all go through it),
        so N lifecycle transitions in one cycle cost one re-resolution
        sweep at the next pack, not N eager sweeps."""
        self._vol_cache.clear()
        self._pack_epoch += 1

    def resolve_volumes(self, pod: Pod) -> ResolvedVolumes:
        key = (pod.key(), pod.uid)
        rv = self._vol_cache.get(key)
        if rv is None:
            rv = resolve_pod_volumes(pod, self.vol_state)
            self.u.intern_volume_refs(rv)
            self._vol_cache[key] = rv
        return rv

    def forget_pod(self, pod_key: str) -> None:
        """Drop per-pod memoization for a deleted pod so churn doesn't grow
        the caches (and set_volume_state doesn't re-resolve dead pods)
        forever. Universe tokens stay — interners are append-only by design
        (bucketed widths make stale entries cheap)."""
        for cache in (self._pod_refs, self._vol_cache, self._vol_pods,
                      self._vec_cache):
            for k in [k for k in cache if k[0] == pod_key]:
                del cache[k]
        self._pack_epoch += 1

    def _pod_vectors(self, pods: Sequence[Pod], R: int):
        """(P, R) request matrix + (P, 2) nonzero matrix, cached per pod
        (invalidated when the resource universe width changes)."""
        req = np.zeros((len(pods), R), np.float32)
        nz = np.zeros((len(pods), 2), np.float32)
        for idx, p in enumerate(pods):
            ck = (p.key(), p.uid)
            ent = self._vec_cache.get(ck)
            if ent is None or ent[0] != R:
                ent = (
                    R,
                    self.u.resource_vector(p.effective_requests(), R),
                    np.asarray(p.nonzero_requests(), np.float32),
                )
                self._vec_cache[ck] = ent
            req[idx] = ent[1]
            nz[idx] = ent[2]
        return req, nz

    # -- interning ---------------------------------------------------------

    def intern_pod(self, pod: Pod) -> Tuple[int, ...]:
        """Returns (selprog, prefprog, tolset, owner, affprog, prefaffprog,
        spread_hard, spread_soft) ids, cached per pod identity
        (namespace/name/uid — uid so a deleted-and-recreated pod with
        different spec is re-interned)."""
        if pod.volumes:
            self._vol_pods[(pod.key(), pod.uid)] = pod
            self.resolve_volumes(pod)
        cached = self._pod_refs.get((pod.key(), pod.uid))
        if cached is not None:
            return cached
        u = self.u
        selprog = u.intern_node_selector_program(pod.node_selector, pod.affinity)
        spread_hard, spread_soft = u.intern_spread_programs(pod, selprog)
        refs = (
            selprog,
            u.intern_preferred_program(pod.affinity),
            u.intern_toleration_set(pod.tolerations),
            u.intern_owner_set(pod.namespace, pod.spread_selectors),
            u.intern_affinity_program(pod),
            u.intern_pref_affinity_program(pod),
            spread_hard,
            spread_soft,
        )
        for name in pod.requests.scalars:
            u.scalar_resources.intern(name)
        for proto, ip, port in pod.host_ports:
            u.ports_pp.intern((proto, port))
            if ip and ip != "0.0.0.0":
                u.ports_pip.intern((proto, ip, port))
        for img in pod.images:
            iid = u.images.intern(img)
            if iid == len(u.image_sizes):
                u.image_sizes.append(0.0)
        if pod.owner_uid:
            u.owner_uids.intern(pod.owner_uid)
        self._pod_refs[(pod.key(), pod.uid)] = refs
        return refs

    def intern_node(self, node: Node) -> int:
        u = self.u
        nid = u.node_names.intern(node.name)
        for t in node.taints:
            u.intern_taint(t.key, t.value, t.effect)
        for img, size in node.images.items():
            u.intern_image(img, size)
        for name in node.allocatable.scalars:
            u.scalar_resources.intern(name)
        return nid

    def _intern_node_topo_pairs(self, node: Node) -> None:
        """Intern this node's (topo key, value) pairs for every topo key the
        universe knows; must run after all pods of the cycle are interned so
        the key set is complete."""
        u = self.u
        for kid, key in enumerate(u.topo_keys.items()):
            v = node.labels.get(key)
            if v is not None:
                u.topo_pairs.intern((kid, v))

    # -- universe signature / pack memo ------------------------------------

    #: memoized tables kept per packer (steady state needs exactly the
    #: in-flight batch plus the retried one; more is waste)
    PACK_MEMO_CAPACITY = 8

    def universe_sig(self) -> Tuple:
        """Cheap exact fingerprint of everything that can change packed
        row CONTENT for a fixed pod set: every interner's length (the
        interners are append-only, so equal length means equal content),
        the resource-universe width, and the pack epoch (volume-state /
        claim-lifecycle / forget invalidations). Two packs of the same
        pods under equal signatures are bit-identical."""
        return (*self.universe_node_sig(), self._pack_epoch)

    def universe_node_sig(self) -> Tuple:
        """Node-row content signature: every interner's length + the
        resource width. ANY universe growth can change already-packed
        node rows even when the power-of-two widths() don't move — a
        pending pod interning a new (key, value) selector pair must
        flip pair_mh on every clean node carrying that label (the
        sub-bucket staleness the delta property test caught). Unlike
        :meth:`universe_sig` this excludes the pack epoch: forget_pod
        and claim-lifecycle invalidations never change node rows
        (volume-STATE replacement does, and set_volume_state callers
        invalidate the snapshot explicitly — scheduler.set_volume_state)."""
        u = self.u
        lens = tuple(
            len(v) for _, v in sorted(vars(u).items())
            if isinstance(v, Interner)
        )
        return (lens, len(u.image_sizes), u.n_resources())

    @staticmethod
    def _memo_get(memo: "OrderedDict", key):
        hit = memo.get(key)
        if hit is not None:
            memo.move_to_end(key)
        return hit

    def _memo_put(self, memo: "OrderedDict", key, value):
        memo[key] = value
        if len(memo) > self.PACK_MEMO_CAPACITY:
            memo.popitem(last=False)

    # -- widths ------------------------------------------------------------

    def widths(self) -> Dict[str, int]:
        u = self.u
        return {
            "R": u.n_resources(),
            "Up": bucket_size(len(u.label_pairs)),
            "Uk": bucket_size(len(u.label_keys)),
            "Ut": bucket_size(len(u.taints)),
            "Upp": bucket_size(len(u.ports_pp)),
            "Upip": bucket_size(len(u.ports_pip)),
            "Ui": bucket_size(len(u.images)),
            "Uo": bucket_size(len(u.owner_sets)),
            "Uu": bucket_size(len(u.owner_uids)),
            "K": bucket_size(len(u.topo_keys), 2),
            "Utp": bucket_size(len(u.topo_pairs)),
            "M": bucket_size(len(u.pod_matchers)),
            "Ua": bucket_size(len(u.anti_terms), 4),
            "Us": bucket_size(len(u.sym_terms), 4),
            "Uv": bucket_size(len(u.vol_conflict), 4),
            "Uvd": bucket_size(len(u.pd_volumes), 4),
            "Uvc": bucket_size(len(u.csi_volumes), 4),
            "Dc": bucket_size(len(u.csi_drivers), 4),
        }

    # -- nodes -------------------------------------------------------------

    def pack_nodes(
        self,
        nodes: Sequence[Node],
        scheduled_pods: Sequence[Pod] = (),
    ) -> NodeTable:
        u = self.u
        for nd in nodes:
            self.intern_node(nd)
        for p in scheduled_pods:
            self.intern_pod(p)
        for nd in nodes:
            self._intern_node_topo_pairs(nd)
        if self.attached_residue:
            # residue tokens must exist in the universes BEFORE widths()
            # sizes the arrays (lookup returns -1 for unknown tokens)
            from kubernetes_tpu.volumes import attachable_tokens

            for pv_names in self.attached_residue.values():
                for pv_name in pv_names:
                    pv = self.vol_state.pv(pv_name)
                    if pv is None:
                        continue
                    for kind, a, b in attachable_tokens(pv):
                        if kind == "pd":
                            u.pd_volumes.intern((a, b))
                        else:
                            u.csi_volumes.intern(
                                (u.csi_drivers.intern(a), b))
        w = self.widths()
        n = len(nodes)
        R = w["R"]
        name_id = np.full((n,), -1, np.int32)
        allocatable = np.zeros((n, R), np.float32)
        requested = np.zeros((n, R), np.float32)
        nonzero_req = np.zeros((n, 2), np.float32)
        pair_mh = np.zeros((n, w["Up"]), np.int8)
        key_mh = np.zeros((n, w["Uk"]), np.int8)
        key_val = np.zeros((n, w["Uk"]), np.float32)
        key_num = np.zeros((n, w["Uk"]), np.int8)
        taint_hard = np.zeros((n, w["Ut"]), np.int8)
        taint_soft = np.zeros((n, w["Ut"]), np.int8)
        port_any = np.zeros((n, w["Upp"]), np.int8)
        port_wild = np.zeros((n, w["Upp"]), np.int8)
        port_spec = np.zeros((n, w["Upip"]), np.int8)
        image_mh = np.zeros((n, w["Ui"]), np.int8)
        owner_counts = np.zeros((n, w["Uo"]), np.float32)
        zone_id = np.full((n,), -1, np.int32)
        avoid_mh = np.zeros((n, w["Uu"]), np.int8)
        ready = np.zeros((n,), bool)
        net_unavail = np.zeros((n,), bool)
        schedulable = np.zeros((n,), bool)
        mem_p = np.zeros((n,), bool)
        disk_p = np.zeros((n,), bool)
        pid_p = np.zeros((n,), bool)
        topo_pair_id = np.full((n, w["K"]), -1, np.int32)
        matcher_counts = np.zeros((n, w["M"]), np.float32)
        anti_counts = np.zeros((n, w["Ua"]), np.float32)
        sym_counts = np.zeros((n, w["Us"]), np.float32)
        aff_pod_count = np.zeros((n,), np.float32)
        vol_any = np.zeros((n, w["Uv"]), np.int8)
        vol_rw = np.zeros((n, w["Uv"]), np.int8)
        pd_mh = np.zeros((n, w["Uvd"]), np.int8)
        pd_limit = np.zeros((n, N_PD_FILTERS), np.float32)
        csi_mh = np.zeros((n, w["Uvc"]), np.int8)
        csi_limit = np.full((n, w["Dc"]), np.inf, np.float32)
        has_zone = np.zeros((n,), bool)
        driver_names = u.csi_drivers.items()

        row_of: Dict[int, int] = {}
        for i, nd in enumerate(nodes):
            nid = u.node_names.intern(nd.name)
            row_of[nid] = i
            name_id[i] = nid
            allocatable[i] = self.u.resource_vector(nd.allocatable, R)
            for k, v in nd.labels.items():
                pi = u.label_pairs.lookup((k, v))
                if pi >= 0:
                    pair_mh[i, pi] = 1
                ki = u.label_keys.lookup(k)
                if ki >= 0:
                    key_mh[i, ki] = 1
                    # strict integer syntax like Go strconv.ParseInt —
                    # Python int() would accept "1_0"/" 10 "/unicode digits
                    if _GO_INT_RE.match(v):
                        key_val[i, ki] = float(int(v))
                        key_num[i, ki] = 1
            for t in nd.taints:
                ti = u.intern_taint(t.key, t.value, t.effect)
                if t.effect in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                    taint_hard[i, ti] = 1
                elif t.effect == EFFECT_PREFER_NO_SCHEDULE:
                    taint_soft[i, ti] = 1
            for img, size in nd.images.items():
                image_mh[i, u.intern_image(img, size)] = 1
            zk = nd.zone_key()
            if zk is not None:
                zone_id[i] = u.zones.intern(zk)
            for uid in nd.prefer_avoid_owner_uids:
                ui = u.owner_uids.lookup(uid)
                if ui >= 0:
                    avoid_mh[i, ui] = 1
            ready[i] = nd.conditions.ready
            net_unavail[i] = nd.conditions.network_unavailable
            schedulable[i] = not nd.unschedulable
            mem_p[i] = nd.conditions.memory_pressure
            disk_p[i] = nd.conditions.disk_pressure
            pid_p[i] = nd.conditions.pid_pressure
            for kid, key in enumerate(u.topo_keys.items()):
                v = nd.labels.get(key)
                if v is not None:
                    topo_pair_id[i, kid] = u.topo_pairs.lookup((kid, v))
            pd_limit[i] = node_pd_limits(nd)
            has_zone[i] = node_has_zone_label(nd)
            for d, driver in enumerate(driver_names):
                lim = nd.allocatable.scalars.get(CSI_LIMIT_PREFIX + driver)
                if lim is not None:
                    csi_limit[i, d] = lim

        # aggregate scheduled pods into node usage (NodeInfo.AddPod,
        # node_info.go — requested, nonzeroRequest, usedPorts, pod count).
        # The resource columns — every pod contributes, dominating full
        # repacks at scale — scatter-add through the native kernel
        # (native/ktpu.cc aggregate_usage) with cached per-pod vectors;
        # the sparse attributes (ports/owners/matchers/affinity/volumes)
        # stay in Python, gated so pods without them cost nothing.
        from kubernetes_tpu import native

        pod_rows = np.fromiter(
            (
                row_of.get(u.node_names.lookup(p.node_name), -1)
                for p in scheduled_pods
            ),
            np.int32,
            count=len(scheduled_pods),
        )
        req_mat, nz_mat = self._pod_vectors(scheduled_pods, R)
        native.aggregate_usage(req_mat, nz_mat, pod_rows, requested, nonzero_req)

        has_matchers = bool(u.pod_matcher_items)
        has_owners = bool(u.owner_set_items)
        for p, i in zip(scheduled_pods, pod_rows):
            if i < 0:
                continue
            for proto, ip, port in p.host_ports:
                ppi = u.ports_pp.intern((proto, port))
                port_any[i, ppi] = 1
                if not ip or ip == "0.0.0.0":
                    port_wild[i, ppi] = 1
                else:
                    port_spec[i, u.ports_pip.intern((proto, ip, port))] = 1
            # owner_counts: for SelectorSpread we need, per owner-set, how
            # many *matching* scheduled pods sit on each node. A scheduled
            # pod contributes to owner set `o` if it matches o's selectors.
            if has_owners:
                for o in _matching_owner_sets(u, p):
                    owner_counts[i, o] += 1
            # inter-pod affinity / spread count matrices
            if has_matchers:
                matcher_counts[i] += self.u.pod_matcher_row(p, w["M"])
            if _pod_has_affinity(p):
                for a in u.pod_anti_term_ids(p):
                    anti_counts[i, a] += 1
                for s in u.pod_sym_term_ids(p):
                    sym_counts[i, s] += 1
                aff_pod_count[i] += 1
            if p.volumes:
                rv = self.resolve_volumes(p)
                for kind, handle, ro in rv.conflict:
                    cid = u.vol_conflict.lookup((kind, handle))
                    vol_any[i, cid] = 1
                    if not ro:
                        vol_rw[i, cid] = 1
                for fi, tok in rv.pd:
                    pd_mh[i, u.pd_volumes.lookup((fi, tok))] = 1
                for driver, handle in rv.csi:
                    d = u.csi_drivers.lookup(driver)
                    csi_mh[i, u.csi_volumes.lookup((d, handle))] = 1

        # attach-controller residue: volumes still attached (detach
        # grace) with no live pod deriving them — they hold real
        # attach-limit slots on the node until the controller detaches
        if self.attached_residue:
            from kubernetes_tpu.volumes import attachable_tokens

            for i, node in enumerate(nodes):
                for pv_name in self.attached_residue.get(node.name, ()):
                    pv = self.vol_state.pv(pv_name)
                    if pv is None:
                        continue  # PV deleted mid-grace: slot freed
                    for kind, a, b in attachable_tokens(pv):
                        if kind == "pd":
                            pd_mh[i, u.pd_volumes.lookup((a, b))] = 1
                        else:
                            d = u.csi_drivers.lookup(a)
                            csi_mh[i, u.csi_volumes.lookup((d, b))] = 1

        return NodeTable(
            n=n,
            name_id=name_id,
            allocatable=allocatable,
            requested=requested,
            nonzero_req=nonzero_req,
            pair_mh=pair_mh,
            key_mh=key_mh,
            key_val=key_val,
            key_num=key_num,
            taint_hard_mh=taint_hard,
            taint_soft_mh=taint_soft,
            port_any_mh=port_any,
            port_wild_mh=port_wild,
            port_spec_mh=port_spec,
            image_mh=image_mh,
            owner_counts=owner_counts,
            zone_id=zone_id,
            zone_valid=(
                np.arange(bucket_size(max(len(u.zones), 1))) < len(u.zones)
            ),
            avoid_mh=avoid_mh,
            ready=ready,
            network_unavailable=net_unavail,
            schedulable=schedulable,
            mem_pressure=mem_p,
            disk_pressure=disk_p,
            pid_pressure=pid_p,
            topo_pair_id=topo_pair_id,
            matcher_counts=matcher_counts,
            anti_counts=anti_counts,
            sym_counts=sym_counts,
            aff_pod_count=aff_pod_count,
            vol_any_mh=vol_any,
            vol_rw_mh=vol_rw,
            pd_mh=pd_mh,
            pd_limit=pd_limit,
            csi_mh=csi_mh,
            csi_limit=csi_limit,
            has_zone_label=has_zone,
        )

    # -- node deltas -------------------------------------------------------

    def pack_nodes_delta(
        self,
        nodes: Sequence[Node],
        scheduled_pods: Sequence[Pod] = (),
    ) -> NodeTable:
        """Re-pack ONLY the given (dirty) nodes with their scheduled pods.

        pack_nodes row computation is node-local — every cross-node input
        lives in the shared append-only universe — so a subset pack yields
        rows bit-identical to the same rows of a full pack (the delta-vs-
        full property test pins this). The caller (SchedulerCache) owns
        the row mapping and scatters the result into its resident host
        and device tables; a width change during the delta pack makes the
        delta unusable and the caller must fall back to a full rebuild
        (it compares ``widths()`` before/after)."""
        return self.pack_nodes(nodes, scheduled_pods)

    # -- pods --------------------------------------------------------------

    def pack_pods(self, pods: Sequence[Pod]) -> PodTable:
        """Columnar pending-pod batch, memoized per (batch identity,
        universe signature): the steady-state driver re-packs the same
        backoff-retried pods and the bench re-packs its warmed chunk —
        under an unchanged signature the previous table is bit-identical
        by construction, so the per-pod packing loop collapses to one
        tuple hash. Any universe growth or pack-epoch bump (volume state,
        claim lifecycle, forget_pod) changes the signature and misses."""
        for p in pods:
            self.intern_pod(p)
        ids = tuple((p.key(), p.uid) for p in pods)
        key = (ids, self.universe_sig())
        hit = self._memo_get(self._pod_table_memo, key)
        if hit is not None:
            return hit
        table = self._pack_pods_uncached(pods)
        # packing the rows may itself have interned (ports seen first at
        # pack time) — store under the POST-pack signature so the next
        # identical call (whose intern loop is then a no-op) hits
        self._memo_put(self._pod_table_memo, (ids, self.universe_sig()),
                       table)
        return table

    def _pack_pods_uncached(self, pods: Sequence[Pod]) -> PodTable:
        # pods are already interned — pack_pods (the only caller) runs
        # the intern loop before computing the memo key
        u = self.u
        w = self.widths()
        n = len(pods)
        R = w["R"]
        req = np.zeros((n, R), np.float32)
        nonzero = np.zeros((n, 2), np.float32)
        selprog = np.full((n,), -1, np.int32)
        prefprog = np.full((n,), -1, np.int32)
        tolset = np.full((n,), -1, np.int32)
        name_req = np.full((n,), -1, np.int32)
        priority = np.zeros((n,), np.int32)
        port_wild_pp = np.zeros((n, w["Upp"]), np.int8)
        port_spec_pp = np.zeros((n, w["Upp"]), np.int8)
        port_spec_pip = np.zeros((n, w["Upip"]), np.int8)
        image_mh = np.zeros((n, w["Ui"]), np.int8)
        owner = np.full((n,), -1, np.int32)
        owner_uid = np.full((n,), -1, np.int32)
        owner_match = np.zeros((n, w["Uo"]), np.int8)
        matcher_mh = np.zeros((n, w["M"]), np.int8)
        affprog = np.full((n,), -1, np.int32)
        prefaffprog = np.full((n,), -1, np.int32)
        spread_hard = np.full((n,), -1, np.int32)
        spread_soft = np.full((n,), -1, np.int32)
        self_aff = np.zeros((n,), bool)
        anti_term_mh = np.zeros((n, w["Ua"]), np.float32)
        sym_term_mh = np.zeros((n, w["Us"]), np.float32)
        has_aff = np.zeros((n,), bool)
        vol_any = np.zeros((n, w["Uv"]), np.int8)
        vol_rw = np.zeros((n, w["Uv"]), np.int8)
        pd_mh = np.zeros((n, w["Uvd"]), np.int8)
        csi_mh = np.zeros((n, w["Uvc"]), np.int8)
        vol_error = np.zeros((n,), bool)
        limits = np.zeros((n, 2), np.float32)

        for i, p in enumerate(pods):
            refs = self.intern_pod(p)
            (selprog[i], prefprog[i], tolset[i], owner[i],
             affprog[i], prefaffprog[i], spread_hard[i], spread_soft[i]) = refs
            matcher_mh[i] = u.pod_matcher_row(p, w["M"])
            for a in u.pod_anti_term_ids(p):
                anti_term_mh[i, a] += 1
            for s in u.pod_sym_term_ids(p):
                sym_term_mh[i, s] += 1
            self_aff[i] = u.self_aff_match(p)
            has_aff[i] = _pod_has_affinity(p)
            req[i] = self.u.resource_vector(p.effective_requests(), R)
            nonzero[i] = p.nonzero_requests()
            limits[i, 0] = p.limits.cpu_milli
            limits[i, 1] = p.limits.memory
            if p.node_name:
                nid = u.node_names.lookup(p.node_name)
                # -2 = pinned to a node that does not exist: PodFitsHost
                # (predicates.go:916) must fail on every node, unlike -1
                # ("no requirement")
                name_req[i] = nid if nid >= 0 else -2
            priority[i] = p.priority
            for proto, ip, port in p.host_ports:
                ppi = u.ports_pp.intern((proto, port))
                if not ip or ip == "0.0.0.0":
                    port_wild_pp[i, ppi] = 1
                else:
                    port_spec_pp[i, ppi] = 1
                    port_spec_pip[i, u.ports_pip.intern((proto, ip, port))] = 1
            for img in p.images:
                ii = u.images.lookup(img)
                if ii >= 0:
                    image_mh[i, ii] = 1
            if p.owner_uid:
                # lookup, not intern: widths are frozen for this pack; the
                # uid was interned on arrival (intern_pod)
                owner_uid[i] = u.owner_uids.lookup(p.owner_uid)
            for o in _matching_owner_sets(u, p):
                owner_match[i, o] = 1
            if p.volumes:
                rv = self.resolve_volumes(p)
                vol_error[i] = rv.error
                for kind, handle, ro in rv.conflict:
                    cid = u.vol_conflict.lookup((kind, handle))
                    vol_any[i, cid] = 1
                    if not ro:
                        vol_rw[i, cid] = 1
                for fi, tok in rv.pd:
                    pd_mh[i, u.pd_volumes.lookup((fi, tok))] = 1
                for driver, handle in rv.csi:
                    d = u.csi_drivers.lookup(driver)
                    csi_mh[i, u.csi_volumes.lookup((d, handle))] = 1

        return PodTable(
            n=n,
            req=req,
            nonzero_req=nonzero,
            selprog_id=selprog,
            prefprog_id=prefprog,
            tolset_id=tolset,
            name_req=name_req,
            priority=priority,
            port_wild_pp=port_wild_pp,
            port_spec_pp=port_spec_pp,
            port_spec_pip=port_spec_pip,
            image_mh=image_mh,
            owner_id=owner,
            owner_uid_id=owner_uid,
            owner_match_mh=owner_match,
            order=np.arange(n, dtype=np.int32),
            matcher_mh=matcher_mh,
            affprog_id=affprog,
            prefaffprog_id=prefaffprog,
            spread_hard_id=spread_hard,
            spread_soft_id=spread_soft,
            self_aff_match=self_aff,
            anti_term_mh=anti_term_mh,
            sym_term_mh=sym_term_mh,
            has_aff=has_aff,
            vol_any_mh=vol_any,
            vol_rw_mh=vol_rw,
            pd_mh=pd_mh,
            csi_mh=csi_mh,
            vol_error=vol_error,
            limits=limits,
        )

    # -- volume tables -----------------------------------------------------

    def pack_volume_tables(self, pods: Sequence[Pod]) -> VolumeTables:
        """Universe volume metadata + zone/binding constraint rows for this
        pending batch (row indices reference the batch's row order, which
        must match the ``pack_pods`` call for the same sequence).
        Memoized like pack_pods — the signature's pack epoch covers every
        volume-state / claim-lifecycle invalidation."""
        ids = tuple((p.key(), p.uid) for p in pods)
        key = (ids, self.universe_sig())
        hit = self._memo_get(self._vol_table_memo, key)
        if hit is not None:
            return hit
        table = self._pack_volume_tables_uncached(pods)
        self._memo_put(self._vol_table_memo, (ids, self.universe_sig()),
                       table)
        return table

    def _pack_volume_tables_uncached(self, pods: Sequence[Pod]) -> VolumeTables:
        u = self.u
        w = self.widths()
        esc = np.zeros((w["Uv"],), np.float32)
        esc[: len(u.vol_conflict_escape)] = np.asarray(
            u.vol_conflict_escape, np.float32
        )
        pd_type = np.zeros((w["Uvd"],), np.int32)
        for t, (fi, _tok) in enumerate(u.pd_volumes.items()):
            pd_type[t] = fi
        csi_driver = np.zeros((w["Uvc"],), np.int32)
        for t, (d, _h) in enumerate(u.csi_volumes.items()):
            csi_driver[t] = d

        vz_pod: List[int] = []
        vz_rows: List[List[int]] = []
        vb_row_clause: List[int] = []
        vb_row_prog: List[int] = []
        vb_clause_pod: List[int] = []
        vb_clause_bound: List[bool] = []
        for i, p in enumerate(pods):
            if not p.volumes:
                continue
            rv = self.resolve_volumes(p)
            for key, allowed in rv.zone_rows:
                ids = (u.label_pairs.lookup((key, z)) for z in allowed)
                pair_ids = [pid for pid in ids if pid >= 0]
                vz_pod.append(i)
                vz_rows.append(pair_ids)
            for terms in rv.bound_affinity:
                cid = len(vb_clause_pod)
                vb_clause_pod.append(i)
                vb_clause_bound.append(True)
                vb_row_clause.append(cid)
                vb_row_prog.append(u.pv_affinity_program(terms))
            for cands in rv.unbound_clauses:
                if any(not t for t in cands):
                    continue  # an unconstrained candidate satisfies any node
                cid = len(vb_clause_pod)
                vb_clause_pod.append(i)
                vb_clause_bound.append(False)
                for terms in cands:
                    vb_row_clause.append(cid)
                    vb_row_prog.append(u.pv_affinity_program(terms))

        Rv = len(vz_pod)
        vz_pairs = np.zeros((Rv, w["Up"]), np.int8)
        for r, ids in enumerate(vz_rows):
            for pid in ids:
                vz_pairs[r, pid] = 1
        i32 = lambda x: np.asarray(x, np.int32)
        return VolumeTables(
            conflict_escape=esc,
            pd_type=pd_type,
            csi_driver=csi_driver,
            n_csi_drivers=len(u.csi_drivers),
            vz_n_rows=Rv,
            vz_pod=i32(vz_pod),
            vz_pairs_mh=vz_pairs,
            vb_n_rows=len(vb_row_clause),
            vb_n_clauses=len(vb_clause_pod),
            vb_row_clause=i32(vb_row_clause),
            vb_row_prog=i32(vb_row_prog),
            vb_clause_pod=i32(vb_clause_pod),
            vb_clause_bound=np.asarray(vb_clause_bound, bool),
        )

    # -- selector / toleration tables --------------------------------------

    def pack_selector_tables(self) -> SelectorTables:
        u = self.u
        w = self.widths()

        def flatten(programs, weighted: bool):
            expr_term: List[int] = []
            expr_op: List[int] = []
            expr_pairs: List[Tuple[int, ...]] = []
            expr_key: List[int] = []
            expr_lit: List[float] = []
            term_prog: List[int] = []
            term_weight: List[float] = []
            for prog_id, terms in enumerate(programs):
                for term in terms:
                    if weighted:
                        weight, exprs = term
                    else:
                        weight, exprs = 1.0, term
                    tid = len(term_prog)
                    term_prog.append(prog_id)
                    term_weight.append(weight)
                    for e in exprs:
                        expr_term.append(tid)
                        expr_op.append(e.op)
                        expr_pairs.append(e.pair_ids)
                        expr_key.append(e.key_id)
                        expr_lit.append(e.literal)
            E, T = len(expr_term), len(term_prog)
            pairs_mh = np.zeros((E, w["Up"]), np.int8)
            for r, ids in enumerate(expr_pairs):
                for pid in ids:
                    pairs_mh[r, pid] = 1
            return (
                E,
                T,
                len(programs),
                np.asarray(expr_term, np.int32),
                np.asarray(expr_op, np.int32),
                pairs_mh,
                np.asarray(expr_key, np.int32),
                np.asarray(expr_lit, np.float32),
                np.asarray(term_prog, np.int32),
                np.asarray(term_weight, np.float32),
            )

        (E, T, G, e_t, e_op, e_p, e_k, e_l, t_p, _) = flatten(
            u.sel_program_terms, weighted=False
        )
        (pE, pT, pG, pe_t, pe_op, pe_p, pe_k, pe_l, pt_p, pt_w) = flatten(
            u.pref_program_terms, weighted=True
        )

        # tolerated-taint multihots per toleration set
        S = len(u.tol_set_items)
        Ut = w["Ut"]
        tol_hard = np.zeros((S, Ut), np.int8)
        tol_soft = np.zeros((S, Ut), np.int8)
        taint_items = u.taints.items()
        from kubernetes_tpu.api.types import Taint  # local to avoid cycle noise

        for s, tols in enumerate(u.tol_set_items):
            for ti, (tk, tv, te) in enumerate(taint_items):
                taint = Taint(tk, tv, te)
                if any(t.tolerates(taint) for t in tols):
                    if te in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
                        tol_hard[s, ti] = 1
                    elif te == EFFECT_PREFER_NO_SCHEDULE:
                        tol_soft[s, ti] = 1

        sizes = np.zeros((w["Ui"],), np.float32)
        sizes[: len(u.image_sizes)] = np.asarray(u.image_sizes, np.float32)

        return SelectorTables(
            n_exprs=E,
            n_terms=T,
            n_progs=G,
            expr_term=e_t,
            expr_op=e_op,
            expr_pairs_mh=e_p,
            expr_key=e_k,
            expr_lit=e_l,
            term_prog=t_p,
            p_n_exprs=pE,
            p_n_terms=pT,
            p_n_progs=pG,
            p_expr_term=pe_t,
            p_expr_op=pe_op,
            p_expr_pairs_mh=pe_p,
            p_expr_key=pe_k,
            p_expr_lit=pe_l,
            p_term_prog=pt_p,
            p_term_weight=pt_w,
            tol_hard_mh=tol_hard,
            tol_soft_mh=tol_soft,
            image_sizes=sizes,
        )

    # -- topology / inter-pod affinity tables ------------------------------

    def pack_topology_tables(self) -> TopologyTables:
        u = self.u
        w = self.widths()

        def flat(progs_rows, with_extra: bool):
            prog_l: List[int] = []
            key_l: List[int] = []
            m_l: List[int] = []
            extra_l: List[float] = []
            for pid, rows in enumerate(progs_rows):
                for row in rows:
                    prog_l.append(pid)
                    key_l.append(row[0])
                    m_l.append(row[1])
                    if with_extra:
                        extra_l.append(float(row[2]))
            return prog_l, key_l, m_l, extra_l

        # required rows: extra = is_anti
        ra_prog, ra_key, ra_m, ra_anti = flat(u.aff_program_rows, True)
        rp_prog, rp_key, rp_m, rp_w = flat(u.pref_aff_program_rows, True)

        Ua, Us = w["Ua"], w["Us"]
        # padding rows MUST carry matcher -1: a zero-filled row aliases
        # (key 0, matcher 0) — real interned ids — and every pod matching
        # matcher 0 would spuriously read as anti-term-matched. That
        # aliasing made sensitive_keys() flag ALL soft-spread/affinity
        # pods and serialize admissions to one per topology pair per
        # round (206 rounds for a 2048-pod soft-spread batch, round-3
        # profiling; the round-2 "topology kernels are the slow path"
        # finding was THIS, not kernel cost).
        at_key = np.full((Ua,), -1, np.int32)
        at_m = np.full((Ua,), -1, np.int32)
        for a, (k, m) in enumerate(u.anti_terms.items()):
            at_key[a], at_m[a] = k, m
        st_key = np.full((Us,), -1, np.int32)
        st_m = np.full((Us,), -1, np.int32)
        st_w = np.zeros((Us,), np.float32)
        st_hard = np.zeros((Us,), np.float32)
        for s, (k, m, wt, kind) in enumerate(u.sym_terms.items()):
            st_key[s], st_m[s] = k, m
            if kind == SYM_HARD_AFF:
                st_hard[s] = 1.0
            elif kind == SYM_SOFT_AFF:
                st_w[s] = wt
            else:
                st_w[s] = -wt

        sh_prog: List[int] = []
        sh_key: List[int] = []
        sh_m: List[int] = []
        sh_skew: List[float] = []
        shp_sel: List[int] = []
        for pid, (rows, selprog) in enumerate(u.spread_hard_program_rows):
            shp_sel.append(selprog)
            for (k, m, skew) in rows:
                sh_prog.append(pid)
                sh_key.append(k)
                sh_m.append(m)
                sh_skew.append(float(skew))
        ss_prog: List[int] = []
        ss_key: List[int] = []
        ss_m: List[int] = []
        ssp_sel: List[int] = []
        for pid, (rows, selprog) in enumerate(u.spread_soft_program_rows):
            ssp_sel.append(selprog)
            for (k, m) in rows:
                ss_prog.append(pid)
                ss_key.append(k)
                ss_m.append(m)

        i32 = lambda x: np.asarray(x, np.int32)
        f32 = lambda x: np.asarray(x, np.float32)
        return TopologyTables(
            n_pairs=len(u.topo_pairs),
            n_matchers=w["M"],
            ra_n_rows=len(ra_prog),
            ra_n_progs=len(u.aff_program_rows),
            ra_prog=i32(ra_prog),
            ra_key=i32(ra_key),
            ra_m=i32(ra_m),
            ra_anti=np.asarray(ra_anti, bool) if ra_anti else np.zeros((0,), bool),
            rp_n_rows=len(rp_prog),
            rp_n_progs=len(u.pref_aff_program_rows),
            rp_prog=i32(rp_prog),
            rp_key=i32(rp_key),
            rp_m=i32(rp_m),
            rp_w=f32(rp_w),
            at_key=at_key,
            at_m=at_m,
            st_key=st_key,
            st_m=st_m,
            st_w=st_w,
            st_hard=st_hard,
            sh_n_rows=len(sh_prog),
            sh_n_progs=len(u.spread_hard_program_rows),
            sh_prog=i32(sh_prog),
            sh_key=i32(sh_key),
            sh_m=i32(sh_m),
            sh_skew=f32(sh_skew),
            shp_selprog=i32(shp_sel),
            ss_n_rows=len(ss_prog),
            ss_n_progs=len(u.spread_soft_program_rows),
            ss_prog=i32(ss_prog),
            ss_key=i32(ss_key),
            ss_m=i32(ss_m),
            ssp_selprog=i32(ssp_sel),
        )
