"""The scheduler driver — the batched analog of the reference's control
loop (``pkg/scheduler/scheduler.go:256`` Run / ``:462`` scheduleOne).

Where the reference pops ONE pod, filters/scores all nodes for it, assumes,
and binds asynchronously, this driver pops the WHOLE activeQ, solves the
batch on device (filter mask + score matrix + assignment rounds, see
``ops/assign.py``), then assumes + binds every placed pod and routes every
unplaced pod through the same error path as the reference
(record backoff → AddUnschedulableIfNotPresent, ``factory.go``
MakeDefaultErrorFunc):

    cycle():
      queue.tick(); cache.cleanup_expired()          # wait.Until loops
      batch = queue.pop_batch()                      # NextPod, batched
      snapshot = cache.snapshot()                    # UpdateNodeInfoSnapshot
      assigned = solve(batch, snapshot)              # Schedule(), batched
      for pod, node in assigned:
        cache.assume_pod(pod, node)                  # scheduler.go:538
        binder.bind(pod, node)                       # scheduler.go:598
        cache.finish_binding(...)                    # async part, inlined
      for pod in unassigned:
        queue.record_failure(pod)                    # podBackoff.BackoffPod
        queue.add_unschedulable_if_not_present(...)  # scheduler.go:493 error path

Binding is synchronous here because in-process binders are function calls;
a driver integrating with a real control plane wraps its RPC in the Binder
and may run it on a thread pool — the cache's assume/expire machinery
already tolerates that (it exists for exactly that asynchrony).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional, Protocol, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.cache import SchedulerCache
from kubernetes_tpu.ops.arrays import (
    nodes_to_device,
    pods_to_device,
    selectors_to_device,
    topology_to_device,
)
from kubernetes_tpu.ops.predicates import run_predicates
from kubernetes_tpu.ops.priorities import solver_gates
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.utils import klog
from kubernetes_tpu.utils.interner import Interner, bucket_size


@jax.jit
def _filter_pass(dp, dn, ds, dt, dv=None, sv=None, em=None):
    """One standalone filter evaluation (reasons + mask) — used for the
    nominated-pods pass-A mask and for failure-reason reporting."""
    return run_predicates(dp, dn, ds, dt, dv, sv, em)


def _new_cycle_state():
    from kubernetes_tpu.framework import CycleState

    return CycleState()


@partial(jax.jit, static_argnames=("weights_key",))
def _score_pass(dp, dn, ds, dt, mask, weights_key):
    """Standalone priority evaluation for the exact host solver."""
    from kubernetes_tpu.ops.priorities import run_priorities

    w = dict(weights_key) if weights_key is not None else None
    return run_priorities(dp, dn, ds, mask, w, dt)


@jax.jit
def _static_vol_pass(dp, dn, ds, dv):
    """Usage-independent volume reasons, computed once per cycle and shared
    by the solver rounds and the reporting passes."""
    from kubernetes_tpu.ops.predicates import static_volume_reasons

    return static_volume_reasons(dp, dn, ds, dv)


class Binder(Protocol):
    """The scheduler's only write — POST pods/{name}/binding
    (registry/core/pod/storage/storage.go:154 BindingREST.Create)."""

    def bind(self, pod: Pod, node_name: str) -> None: ...


class RecordingBinder:
    """Test binder capturing bindings (the mock binder of
    scheduler_test.go:1031)."""

    def __init__(self) -> None:
        self.bindings: List[Tuple[str, str]] = []

    def bind(self, pod: Pod, node_name: str) -> None:
        self.bindings.append((pod.key(), node_name))


@dataclass
class CycleResult:
    """What one driver cycle did (inputs to metrics + events)."""

    attempted: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    bind_errors: int = 0
    rounds: int = 0
    assignments: Dict[str, str] = field(default_factory=dict)  # pod key -> node
    failure_reasons: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    #: pod key -> FitError.Error()-shaped message with per-reason node
    #: counts (only for pods that failed the filter pass)
    fit_errors: Dict[str, str] = field(default_factory=dict)
    preempted: int = 0  # victims deleted this cycle
    nominations: Dict[str, str] = field(default_factory=dict)  # pod -> node
    waiting: int = 0  # pods parked by Permit plugins this cycle
    elapsed_s: float = 0.0
    #: which degradation-ladder tier produced this cycle's placements
    #: ("" = empty cycle; "batch" is the healthy fast path)
    solver_tier: str = ""
    #: tier-to-tier fallbacks taken this cycle (0 on the healthy path)
    solver_fallbacks: int = 0
    #: per-cycle UnschedulableReport (obs/explain.py) — why the
    #: residual pods stayed pending: per-pod reason node counts, the
    #: cluster reason histogram, one-bit-away relaxations. None when the
    #: explainer is off or the cycle ended before the solve.
    explain: Optional[object] = None
    #: how the cycle's snapshot was produced: full | delta | clean
    #: (device-resident modes), "host" = legacy full host pack + upload
    #: (device_resident_snapshot off), "" = the cycle ended before the
    #: snapshot (empty queue / all-prefilter batches)
    snapshot_mode: str = ""
    #: which solve the cycle ran: "restricted" = the incremental
    #: candidate-column solve over the cached score plane (O(churn));
    #: "full" = the cold dense solve; "" = the cycle ended before any
    #: solve. The cold solve is the correctness fallback — a restricted
    #: attempt that under-places or fails validation re-solves "full"
    #: in the SAME cycle and reports "full" here.
    solve_scope: str = ""
    #: fraction of the score plane's node columns REUSED from the cache
    #: this cycle (1 - recomputed/live; 0.0 on full solves) — the
    #: "cost proportional to churn" provenance
    reuse_frac: float = 0.0
    #: capacity-balanced blocks the PARTITIONED cold solve ran (0 =
    #: not a partitioned cycle): solve_scope == "partitioned" cycles
    #: solved B fixed-width restricted frames instead of the dense
    #: (P, N) plane — the sparsity-first cold path (docs/perf.md)
    cold_blocks: int = 0
    #: device solve time for the cycle (the span total the scheduling_
    #: algorithm histogram observes) — split by solve_scope in the
    #: churn bench so warm-start wins are visible per cycle
    solve_s: float = 0.0
    #: sub-batches the pipelined executor ran (0 = monolithic cycle)
    pipeline_chunks: int = 0
    #: per-pod create-to-bind latency (pod key -> seconds, queue-add
    #: stamp to bind) for every pod bound this cycle — the admission
    #: timestamps the serving mode's p99 rides; each value lands in
    #: scheduler_e2e_scheduling_duration_seconds
    e2e_latency_s: Dict[str, float] = field(default_factory=dict)
    #: what flushed the micro-batch window into this cycle
    #: ("bucket-fill" | "max-wait"; "" = not a serving-loop cycle)
    flush_trigger: str = ""
    #: how long the micro-batch window accumulated before flushing
    window_s: float = 0.0
    #: scenario-pack placement-quality scores for this cycle (empty =
    #: scenario mode off / quality gated off): the device-reduced
    #: nodes_used / headroom / fragmentation vector plus the pack's
    #: host-side gang bookkeeping (docs/scenarios.md quality table)
    scenario_quality: Dict[str, float] = field(default_factory=dict)
    #: perf-ledger verdict (obs/ledger.py), stamped at end_cycle: the
    #: cost model's predicted solve seconds for this cycle's batch
    #: shape and the modeled/measured efficiency (-1 = not populated —
    #: no solve ran, or the ledger is off)
    modeled_s: float = -1.0
    model_efficiency: float = -1.0


class Scheduler:
    """Batched scheduling driver over a cache + queue + device solver."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        queue: Optional[SchedulingQueue] = None,
        binder: Optional[Binder] = None,
        weights: Optional[Dict[str, float]] = None,
        solver: str = "batch",
        per_node_cap: int = 4,
        max_rounds: int = 128,
        max_batch: int = 8192,
        clock: Callable[[], float] = time.monotonic,
        event_sink: Optional[Callable[[str, Pod, str], None]] = None,
        enable_preemption: bool = True,
        enable_non_preempting: bool = False,
        max_preemptions_per_cycle: int = 16,
        pdb_lister: Optional[Callable[[], List]] = None,
        victim_deleter: Optional[Callable[[Pod], None]] = None,
        repack_evictor: Optional[Callable[[Pod], None]] = None,
        framework=None,
        pred_mask: Optional[int] = None,
        extenders=(),
        metrics=None,
        trace_threshold_s: float = 1.0,
        percentage_of_nodes_to_score: Optional[int] = None,
        volume_binder=None,
        scheduler_name: str = "default-scheduler",
        robustness=None,
        recovery=None,
        fault_injector=None,
        retry_sleep: Callable[[float], None] = time.sleep,
        pod_reader: Optional[Callable[[str], Optional[Pod]]] = None,
        jitter_seed: Optional[int] = None,
        observability=None,
        pipeline_depth: int = 2,
        pipeline_chunk: int = 4096,
        device_resident_snapshot: bool = True,
        snapshot_max_dirty_frac: Optional[float] = None,
        warmup=None,
        parallel=None,
        scenario=None,
        incremental=None,
    ) -> None:
        from kubernetes_tpu.config import (
            ObservabilityConfig,
            RecoveryConfig,
            RobustnessConfig,
        )
        from kubernetes_tpu.faults import CircuitBreaker, RetryPolicy
        from kubernetes_tpu.framework import Framework
        from kubernetes_tpu.metrics import SchedulerMetrics
        from kubernetes_tpu.nodetree import NodeTree
        from kubernetes_tpu.obs import Observability

        #: which pods this scheduler is responsible for
        #: (eventhandlers.go:328 responsibleForPod — the multi-scheduler
        #: seam): unassigned pods naming another scheduler never enter the
        #: queue; assigned pods ALWAYS enter the cache, whoever bound them,
        #: because their capacity is consumed either way (the reference's
        #: assigned-pod informer carries no scheduler-name filter)
        self.scheduler_name = scheduler_name
        self.framework = framework or Framework(clock=clock)
        #: HTTPExtender list (core/extender.go), called after the built-in
        #: filter/score passes for interested pods
        self.extenders = list(extenders)
        self.metrics = metrics or SchedulerMetrics()
        obs_config = (observability if observability is not None
                      else ObservabilityConfig(
                          trace_threshold_s=trace_threshold_s))
        #: instrumented-lock runtime sanitizer (kubernetes_tpu/sanitize):
        #: armed by observability.lockSanitizer.enabled. When on, every
        #: lock the scheduler's obs stack / cache / serving loop builds
        #: is wrapped to maintain the acquisition-order graph; findings
        #: increment scheduler_lock_sanitizer_findings_total{kind} and
        #: mark the cycle eventful in the flight record. getattr:
        #: duck-typed config fakes without the field stay valid.
        self.lock_sanitizer = None
        ls_config = getattr(obs_config, "lock_sanitizer", None)
        if ls_config is not None and ls_config.enabled:
            from kubernetes_tpu.sanitize import LockSanitizer

            self.lock_sanitizer = LockSanitizer(
                ls_config, clock=clock,
                on_finding=lambda kind: (
                    self.metrics.lock_sanitizer_findings.inc(kind=kind)))
        #: observability layer (kubernetes_tpu/obs): cycle tracer + flight
        #: recorder + runtime JAX telemetry, on the scheduler's clock
        self.obs = Observability(
            obs_config, metrics=self.metrics, clock=clock,
            lock_sanitizer=self.lock_sanitizer,
        )
        #: degradation-ladder knobs (config.RobustnessConfig): per-cycle
        #: deadline, bounded retries, breaker thresholds, fallback chain,
        #: result validation — the resilience layer for an out-of-process
        #: (TPU-service) solver that may time out, crash, or lie
        self.robustness = (robustness if robustness is not None
                           else RobustnessConfig())
        #: crash/failover/device-loss knobs (config.RecoveryConfig):
        #: fenced binds, takeover reconciliation, resident rebuild
        self.recovery = recovery if recovery is not None else RecoveryConfig()
        #: the bind fence (LeaderElector via attach_elector, or any
        #: object with allow_bind()/epoch): None = unfenced (single-
        #: writer deployments, tests)
        self.fence = None
        #: truth lister for takeover reconciliation (attach_elector):
        #: () -> iterable of hub-truth Pods; None = local-only reconcile
        self._lister = None
        #: host-mode snapshot fallback window after a device-loss
        #: recovery exhausted its per-cycle rebuild budget (monotonic
        #: deadline; 0 = device considered healthy)
        self._device_cooloff_until = 0.0
        #: faults.FaultInjector (or None): the seeded chaos harness wired
        #: into the solver entry and the extender/shim transports
        self.fault_injector = fault_injector
        rc = self.robustness
        # PER-REPLICA jitter seed at the hub seam (full jitter): two
        # replicas sharing one RetryPolicy CONFIG must not share the
        # jitter STREAM — a shared default seed makes their backoff
        # trains lockstep, so every retry wave from every replica lands
        # on a recovering hub at the same instant. Derived from process
        # + instance identity unless the caller pins one (tests).
        if jitter_seed is None:
            import os as _os
            import random as _random

            jitter_seed = (_random.SystemRandom().randrange(1 << 30)
                           ^ _os.getpid() ^ (id(self) & 0xFFFF))
        self._jitter_seed = int(jitter_seed)
        #: bounded-backoff policy shared by the transport seams; ``sleep``
        #: injectable so fake-clock tests never block
        self._transport_retry = RetryPolicy(
            max_retries=rc.transport_retries,
            base_s=rc.retry_backoff_base_s,
            max_s=rc.retry_backoff_max_s,
            jitter=rc.retry_jitter,
            seed=self._jitter_seed,
            sleep=retry_sleep,
        )
        #: hub GET for the ambiguous-bind read-your-write verification
        #: (``key -> Pod | None``, raising on transport failure). None =
        #: no reader: an ambiguous bind parks on the assume TTL instead
        #: (the watch confirm / TTL reap resolve it eventually).
        self.pod_reader = pod_reader
        #: bounded verification GETs per ambiguous bind, full jitter on
        #: the same per-replica stream
        self._bind_verify_retry = RetryPolicy(
            max_retries=rc.bind_verify_retries,
            base_s=rc.retry_backoff_base_s,
            max_s=rc.retry_backoff_max_s,
            jitter=rc.retry_jitter,
            seed=self._jitter_seed + 1,
            sleep=retry_sleep,
        )
        #: ambiguous binds whose verification GET was itself unreachable:
        #: key -> (pod, node_name, cycle_state). The pod stays ASSUMED
        #: (capacity held, no TTL) — requeueing it could re-bind a pod
        #: the hub already committed — and every cycle / idle tick
        #: re-probes until the hub answers (_verify_ambiguous_binds).
        self._ambiguous_binds: Dict[str, Tuple] = {}
        #: state-conservation auditor (obs/audit.py) — None until
        #: attach_auditor; when attached, legitimate pod exits (watch
        #: deletes, terminating skips, reconcile drops) are reported so
        #: conservation never counts them lost
        self.auditor = None
        for e in self.extenders:
            # wire retry + fault + observability hooks into transports
            # that expose the seam (HTTPExtender); duck-typed so test
            # fakes stay valid
            if getattr(e, "retry", "absent") is None:
                e.retry = self._transport_retry
            if (fault_injector is not None
                    and getattr(e, "fault_injector", "absent") is None):
                e.fault_injector = fault_injector
            if getattr(e, "obs", "absent") is None:
                e.obs = self.obs
            if getattr(e, "_clock_defaulted", False):
                e._clock = clock
                e._clock_defaulted = False
        #: per-target circuit breakers ("solver:batch",
        #: "extender:<url>"), created lazily against this clock
        self._breakers: Dict[str, CircuitBreaker] = {}
        #: absolute deadline of the cycle in flight (None = unbounded)
        self._cycle_deadline: Optional[float] = None
        #: cycles slower than this log their step trace (utiltrace
        #: LogIfLong; default is cycle-scale, not the reference's per-pod
        #: 100ms, since one cycle schedules a whole batch). A provided
        #: ObservabilityConfig owns the knob; the legacy ctor param stays
        #: the fallback.
        self.trace_threshold_s = (
            observability.trace_threshold_s if observability is not None
            else trace_threshold_s
        )
        #: enabled-predicate bitmask (config.Policy.predicate_mask);
        #: None = every implemented predicate enforced
        self.pred_mask = pred_mask
        #: per-pod CycleState, alive from prefilter to bind/fail
        self._cycle_states: Dict[str, object] = {}
        self.cache = cache or SchedulerCache(
            clock=clock,
            lock_factory=(self.lock_sanitizer.factory()
                          if self.lock_sanitizer is not None else None))
        # the device-snapshot chaos seam rides the same injector as the
        # solver/transport seams (duck-typed attach, like the extenders)
        if (fault_injector is not None
                and getattr(self.cache, "fault_injector", "absent") is None):
            self.cache.fault_injector = fault_injector
        # the device-memory ledger's cache seam (obs/memledger.py):
        # resident-table / score-plane byte registrations ride the
        # cache's own upload/drop edges (duck-typed attach, like the
        # injector above — cache fakes without the attribute stay valid)
        if getattr(self.cache, "memledger", "absent") is None:
            self.cache.memledger = self.obs.memledger
        #: pipelined cycle executor: batches larger than pipeline_chunk
        #: split into fixed-size chunks; depth >= 2 overlaps host packing
        #: of chunk k+1 and binding of chunk k-1 with chunk k's device
        #: solve (JAX async dispatch). Depth 1 keeps today's monolithic
        #: cycle — the seqref-parity mode.
        self.pipeline_depth = pipeline_depth
        self.pipeline_chunk = pipeline_chunk
        #: device-resident snapshot: keep the packed NodeTable on device
        #: across cycles, patching dirty rows with a jitted scatter
        self.device_resident_snapshot = device_resident_snapshot
        if snapshot_max_dirty_frac is not None:
            self.cache.max_dirty_frac = snapshot_max_dirty_frac
        #: AOT warmup config (config.WarmupConfig or None)
        from kubernetes_tpu.config import (
            IncrementalConfig,
            ParallelConfig,
            WarmupConfig,
        )

        self.warmup_config = warmup if warmup is not None else WarmupConfig()
        #: incremental solve (config.IncrementalConfig): steady-state
        #: cycles cost O(churn) — candidate columns come from the
        #: device-resident score cache (cache.score_summary, patched per
        #: delta), the solve restricts to a bounded (P, C) plane, and
        #: Sinkhorn potentials warm-start across cycles. The cold dense
        #: solve stays the correctness fallback (docs/perf.md).
        self.incremental = (incremental if incremental is not None
                            else IncrementalConfig())
        #: warm Sinkhorn potential carry: (key, (u, v)) where key is
        #: (pod bucket, candidate bucket, cache.summary_generation) —
        #: any invalidation edge (takeover, device loss, epoch growth,
        #: full rebuild) bumps the generation and the carry dies with it
        self._sk_warm_pot = None
        #: restricted service has engaged since the last invalidation —
        #: the signal that makes an invalidation drop COUNTABLE (see
        #: _drop_incremental)
        self._incr_active = False
        #: candidate-bucket auto-tuner state (incremental.auto_tune):
        #: the warmed-C ladder _warm_incremental compiled (the tuner
        #: may ONLY pick from this set — an unwarmed C would retrace on
        #: the hot path, breaking the zero-retrace contract), the
        #: recent raw micro-batch sizes (sliding window, host ints),
        #: and the deepest candidate-frame position any restricted
        #: solve placed into (the placement-rank telemetry — one
        #: device-side scalar riding the solve-result readback)
        self._warmed_cbuckets: set = set()
        self._tuner_batch_obs: List[int] = []
        self._tuner_depth_max = 0
        #: sharded execution backend (config.ParallelConfig): when the
        #: mesh is on, the node axis of the resident snapshot — and with
        #: it the (P, N) plane of every solve/validate/explain kernel —
        #: shards across a 1-D device mesh built HERE, at construction;
        #: pods/selector/topology/volume tables replicate (_place) and
        #: GSPMD inserts the collectives (parallel/mesh.py design). Off
        #: ("off", the default) never touches the backend.
        self.parallel = parallel if parallel is not None else ParallelConfig()
        from kubernetes_tpu.parallel.mesh import mesh_from_spec, mesh_size

        self.mesh = mesh_from_spec(self.parallel.mesh)
        set_mesh = getattr(self.cache, "set_mesh", None)
        if set_mesh is not None:  # duck-typed: cache fakes stay valid
            set_mesh(self.mesh)
        mesh_gauge = getattr(self.metrics, "mesh_devices", None)
        if mesh_gauge is not None:  # duck-typed: metrics fakes stay valid
            mesh_gauge.set(mesh_size(self.mesh))
        self.obs.note_mesh(mesh_size(self.mesh))
        #: whether THIS cycle's device tables live on the mesh (False
        #: during the device-loss cooloff, when snapshots fall back to
        #: single-device host mode — a lost shard must not keep pulling
        #: the whole mesh into every upload)
        self._mesh_live = False
        # explicit None check: SchedulingQueue defines __len__, so a
        # caller-provided EMPTY queue is falsy and `queue or ...` would
        # silently replace it with a fresh one
        self.queue = queue if queue is not None else SchedulingQueue(
            clock=clock, less=self.framework.queue_sort_less(),
            metrics=self.metrics,
        )
        # an externally built queue gets this scheduler's metrics so the
        # queue-observability surface (incoming counters, sub-queue age
        # histograms, mutation-fresh pending_pods gauges) stays live;
        # duck-typed so queue fakes without the attribute stay valid
        if getattr(self.queue, "metrics", "absent") is None:
            self.queue.metrics = self.metrics
        # the journey tracer rides the queue's residency seams (add /
        # sub-queue transitions / pop) — same duck attach as metrics
        if getattr(self.queue, "journeys", "absent") is None:
            self.queue.journeys = self.obs.journeys
        # the incident bundles embed the queue depths at trigger time
        if getattr(self.obs, "incidents", None) is not None:
            self.obs.incidents.queue_snapshot = self.queue.pending_counts
        #: latest explanation per still-pending pod (the /debug/why
        #: surface): updated each cycle from the UnschedulableReport,
        #: dropped when the pod binds or leaves
        self.why_pending: Dict[str, object] = {}
        #: the most recent cycle's UnschedulableReport (cluster summary)
        self.last_explain = None
        #: reason labels ever exported on the unschedulable gauges —
        #: lets a cycle zero out reasons that stopped firing
        self._explain_reasons_seen: set = set()
        #: node-search truncation (percentageOfNodesToScore): None =
        #: evaluate every node (the dense solver's natural mode); 0 =
        #: the reference's adaptive 50%→5% rule; 1-99 = fixed percent.
        #: Truncated cycles restrict the solve to the next K nodes in
        #: zone round-robin order (NodeTree) so consecutive cycles sweep
        #: different zones, like the reference's resumable enumeration.
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.node_tree = NodeTree()
        self.binder = binder or RecordingBinder()
        self.weights = weights
        self.solver = solver
        #: scenario pack (config.ScenarioConfig -> scenarios.resolve_pack):
        #: a pack swaps the solve objective — its weight override lands
        #: HERE so every ladder tier (and warmup) sees the scenario
        #: weights, and its (P, N) cost term joins extra_score per cycle
        #: (docs/scenarios.md). None = stock objective, zero overhead.
        from kubernetes_tpu.config import ScenarioConfig
        from kubernetes_tpu.scenarios import resolve_pack

        self.scenario = scenario if scenario is not None else ScenarioConfig()
        self.scenario_pack = resolve_pack(self.scenario)
        if self.scenario_pack is not None:
            self.weights = self.scenario_pack.weights(self.weights)
        #: score labels ever exported on the scenario-quality gauge —
        #: lets a cycle zero scores that stopped being reported (e.g.
        #: gang_locality after a gangless cycle), same freshness rule
        #: as the explain reason gauges
        self._scenario_scores_seen: set = set()
        if self.incremental.enabled:
            # arm the device-resident score cache, pinned to THIS
            # scheduler's Policy and objective: candidate eligibility
            # honors the node-condition predicates only when the Policy
            # enforces them (a permissive Policy's cold solve admits
            # pressured nodes — candidates must too), and the ranking
            # flips fullest-first under a packing objective
            from kubernetes_tpu.ops.predicates import BIT as _BIT
            from kubernetes_tpu.ops.priorities import DEFAULT_WEIGHTS

            cond_names = ("CheckNodeCondition", "CheckNodeUnschedulable",
                          "CheckNodeMemoryPressure",
                          "CheckNodeDiskPressure", "CheckNodePIDPressure")
            honor = self.pred_mask is None or all(
                self.pred_mask & (1 << _BIT[n]) for n in cond_names)
            w = self.weights if self.weights is not None else DEFAULT_WEIGHTS
            packed = (w.get("MostRequestedPriority", 0)
                      > w.get("LeastRequestedPriority", 0))
            self._summary_flags = {"honor_conditions": honor,
                                   "prefer_packed": packed}
            enable = getattr(self.cache, "enable_score_cache", None)
            if enable is not None:  # duck-typed: cache fakes stay valid
                enable(honor_conditions=honor, prefer_packed=packed)
        else:
            self._summary_flags = {"honor_conditions": True,
                                   "prefer_packed": False}
        #: count of exact->round auto-fallbacks (port/volume/topology batches)
        self.exact_fallbacks = 0
        #: NonPreemptingPriority feature gate: honor preemption_policy=Never
        self.enable_non_preempting = enable_non_preempting
        self.per_node_cap = per_node_cap
        self.max_rounds = max_rounds
        self.max_batch = max_batch
        self.clock = clock
        #: event_sink(reason, pod, message) — Scheduled / FailedScheduling /
        #: Preempted (scheduler.go:274,:335,:457); wired to the events
        #: recorder by the host shim.
        self.event_sink = event_sink or (lambda *_: None)
        # the SLO watchdog (obs/ledger.py) emits SchedulerSLOBurn /
        # SchedulerSLORecovered through the same recorder sink as every
        # other scheduler event — late-bound so a sink attached after
        # construction still receives them
        self.obs.ledger.event_sink = (
            lambda reason, obj, msg: self.event_sink(reason, obj, msg))
        self.enable_preemption = enable_preemption
        self.max_preemptions_per_cycle = max_preemptions_per_cycle
        #: PDBs come from a lister (the disruption controller maintains
        #: their status in the reference; here the hub/sim supplies them)
        self.pdb_lister = pdb_lister or (lambda: [])
        #: victim_deleter(pod): issue the victim's deletion. Default: mark
        #: terminating and remove from cache immediately (grace period 0).
        #: A hub integration instead posts the delete and lets the watch
        #: remove it, keeping the victim visible as terminating meanwhile.
        self.victim_deleter = victim_deleter
        #: repack_evictor(pod): issue a steady-state re-pack drain for a
        #: BOUND pod (scenario.repack_interval_s). Default: unbind
        #: locally and requeue (sim-style, zero-grace). A hub
        #: integration instead posts the unbind/delete+recreate and
        #: lets the watch stream converge the local state.
        self.repack_evictor = repack_evictor
        #: clock of the last re-pack sweep; None = cadence not started
        #: (the first interval elapses before the first drain)
        self._last_repack_at: Optional[float] = None
        #: delayed-binding PVC lifecycle (volume_binder.go:30): assume at
        #: assume time, commit at bind time, roll back on any forget
        from kubernetes_tpu.volumes import VolumeBinder

        self.volume_binder = volume_binder or VolumeBinder(self.cache.packer)
        #: serving doorbell (serving/doorbell.py) — None until a serving
        #: loop attaches one via attach_doorbell
        self.doorbell = None
        #: the ladder tier that produced the most recent non-empty
        #: cycle ("" before the first solve) and how many tier-to-tier
        #: fallbacks that cycle took — the backend_pressure probe reads
        #: the FALLBACK count to tell a healthy backend from a limping
        #: one (tier NAME comparison would misread the exact solver's
        #: deliberate hazard routing to "batch" as degradation)
        self.last_solver_tier = ""
        self.last_solver_fallbacks = 0

    @classmethod
    def from_config(cls, cfg, **kw) -> "Scheduler":
        """Build a Scheduler from a KubeSchedulerConfiguration — the
        CreateFromProvider / CreateFromConfig seam (factory.go:346,:356)."""
        from kubernetes_tpu.config import (
            default_predicate_mask,
            default_priority_weights,
        )

        if cfg.policy is not None:
            kw.setdefault("pred_mask", cfg.policy.predicate_mask)
            kw.setdefault("weights", dict(cfg.policy.priority_weights))
            if cfg.policy.extenders:
                from kubernetes_tpu.extender import build_extenders

                kw.setdefault("extenders", build_extenders(cfg.policy.extenders))
        else:
            kw.setdefault("pred_mask", default_predicate_mask(cfg.feature_gates))
            kw.setdefault("weights", default_priority_weights(cfg.feature_gates))
        kw.setdefault("solver", cfg.solver)
        kw.setdefault(
            "enable_non_preempting",
            cfg.feature_gates.enabled("NonPreemptingPriority"),
        )
        kw.setdefault("per_node_cap", cfg.per_node_cap)
        kw.setdefault("max_rounds", cfg.max_rounds)
        kw.setdefault("max_batch", cfg.max_batch)
        kw.setdefault("scheduler_name", cfg.scheduler_name)
        kw.setdefault("robustness", cfg.robustness)
        kw.setdefault("recovery", cfg.recovery)
        kw.setdefault("observability", cfg.observability)
        kw.setdefault("pipeline_depth", cfg.pipeline_depth)
        kw.setdefault("pipeline_chunk", cfg.pipeline_chunk)
        kw.setdefault("device_resident_snapshot", cfg.device_resident_snapshot)
        kw.setdefault("snapshot_max_dirty_frac", cfg.snapshot_max_dirty_frac)
        kw.setdefault("warmup", cfg.warmup)
        kw.setdefault("parallel", cfg.parallel)
        kw.setdefault("scenario", cfg.scenario)
        kw.setdefault("incremental", cfg.incremental)
        if getattr(cfg, "plugins", ()) and "framework" not in kw:
            # config-driven framework assembly (the NewFramework path,
            # framework.go:88: registry factories + per-plugin args from
            # PluginConfig). Unknown names fail loudly like the
            # reference's NewFramework does.
            from kubernetes_tpu.framework import PLUGIN_REGISTRY, Framework

            built = []
            for name in cfg.plugins:
                factory = PLUGIN_REGISTRY.get(name)
                if factory is None:
                    raise ValueError(
                        f"plugins: {name!r} is not registered "
                        f"(known: {sorted(PLUGIN_REGISTRY)})"
                    )
                built.append(factory(dict(cfg.plugin_config.get(name, {}))))
            kw["framework"] = Framework(
                built, clock=kw.get("clock", time.monotonic))
        # 100 (the config default) = no truncation; 0 = the reference's
        # adaptive rule; 1-99 fixed — passed through verbatim so the
        # adaptive mode stays expressible from config
        kw.setdefault(
            "percentage_of_nodes_to_score",
            None
            if cfg.percentage_of_nodes_to_score >= 100
            else cfg.percentage_of_nodes_to_score,
        )
        return cls(**kw)

    # -- ingestion (AddAllEventHandlers analog; the informer pump or test
    # drives these) --------------------------------------------------------

    def responsible_for(self, pod: Pod) -> bool:
        """eventhandlers.go:328 responsibleForPod: spec.schedulerName must
        name THIS scheduler for its unassigned pods to be queued here."""
        return pod.scheduler_name == self.scheduler_name

    def on_pod_add(self, pod: Pod) -> None:
        """eventhandlers.go:215/:256 — unassigned pods queue for scheduling
        (only this scheduler's, per the informer FilterFunc); assigned pods
        enter the cache whoever bound them, and may unblock affinity
        waiters. Terminal pods never enter: the reference scheduler's pod
        informer lists with ``status.phase!=Succeeded,status.phase!=Failed``
        (factory.go NewPodInformer nonTerminatedPodSelector) — enforced at
        this sink so EVERY feed (in-process emit, Reflector, gRPC bridge)
        gets the same view without each needing the selector."""
        from kubernetes_tpu.api.types import is_pod_terminated

        if is_pod_terminated(pod):
            return
        if pod.node_name:
            self.cache.add_pod(pod)
            self.queue.assigned_pod_added(pod)
        elif self.responsible_for(pod):
            self.queue.add(pod)

    def on_pod_update(self, old: Pod, new: Pod) -> None:
        from kubernetes_tpu.api.types import is_pod_terminated

        if is_pod_terminated(new):
            # terminal phase hop: the field-selected informer delivers
            # this as a DELETE (the pod left the selector) — its node
            # capacity is released even on feeds without the selector
            # (the gRPC snapshot bridge, a selector-less Reflector)
            self.on_pod_delete(new)
            return
        if new.node_name:
            # a Permit-parked pod bound by another writer must leave the
            # waiting map BEFORE cache.add_pod flips its state to ADDED —
            # otherwise _process_waiting later calls forget_pod on a
            # non-assumed pod and aborts the whole cycle (same cleanup
            # on_pod_delete does for parked pods)
            wp = self.framework.waiting.get(new.key())
            if wp is not None:
                self.framework.waiting.remove(new.key())
                self.volume_binder.forget_pod_volumes(new.key())
                self.framework.run_unreserve(
                    self._cycle_states.get(new.key()) or _new_cycle_state(),
                    wp.pod, wp.node_name,
                )
            self._cycle_states.pop(new.key(), None)
            # add_pod (not update_pod): an unassigned->assigned transition
            # must CONFIRM a pending assumption, or the TTL would expire a
            # successfully bound pod and double-book its capacity
            self.cache.add_pod(new)
            # ... and must LEAVE the scheduling queue: the reference's
            # unassigned-pod informer filter turns this transition into a
            # queue delete (eventhandlers.go addAllEventHandlers pod
            # FilterFunc). Without it, a pod bound by another writer (HA
            # peer, competing scheduler) would be scheduled again here and
            # double-booked.
            self.queue.delete(new.key())
            # journey: our own bind already completed it at the success
            # tail (this no-ops); a COMPETING writer's bind closes it
            # here as gone — it never bound through this scheduler
            self.obs.journeys.note_gone(new.key())
            # AssignedPodUpdated: wake only affinity-matching waiters, not
            # the whole unschedulableQ (eventhandlers.go)
            self.queue.assigned_pod_added(new)
        elif self.responsible_for(new):
            if new != old:
                # a pending pod updated IN PLACE (same uid — labels or
                # selector edited through PATCH): the packer's per-pod
                # ref cache and the pack-table memo are keyed by
                # (key, uid) + universe signature, and a changed spec
                # whose values are all already interned moves neither —
                # forget the pod so the next pack re-interns and the
                # memoized tables (pack epoch) invalidate
                self.cache.packer.forget_pod(new.key())
            self.queue.update(old.key(), new)
        elif self.responsible_for(old):
            # responsible -> not-responsible transition: the reference's
            # FilteringResourceEventHandler turns this into a Delete, so
            # the stale spec must leave our queues (schedulerName is
            # immutable in the real API, but this ingestion surface takes
            # arbitrary updates). Pod-keyed side state must leave with it
            # or it outlives the pod (the soak sentinels watch exactly
            # these dicts for monotonic growth)
            self.queue.delete(old.key())
            self._cycle_states.pop(old.key(), None)
            self.why_pending.pop(old.key(), None)
            self._note_gone(old.key())

    def on_pod_delete(self, pod: Pod) -> None:
        key = pod.key()
        self._note_gone(key)
        # a bind whose ambiguous verification was parked resolves by
        # deletion: the pod is gone whatever the RPC did — release the
        # held assumption (parked pods carry no TTL, so nothing else
        # would free this capacity)
        parked = self._ambiguous_binds.pop(key, None)
        if parked is not None and self.cache.is_assumed(key):
            apod, anode, ast = parked
            self.cache.forget_pod(key)
            self.volume_binder.forget_pod_volumes(key)
            self.framework.run_unreserve(
                ast or _new_cycle_state(), apod, anode)
        # a Permit-parked pod is assumed in the cache and holds capacity —
        # deletion must release both the wait entry and the assumption
        wp = self.framework.waiting.get(key)
        if wp is not None:
            self.framework.waiting.remove(key)
            self.cache.forget_pod(key)
            self.volume_binder.forget_pod_volumes(key)
            self.framework.run_unreserve(
                self._cycle_states.get(key) or _new_cycle_state(), wp.pod,
                wp.node_name,
            )
        if pod.node_name:
            self.cache.remove_pod(key)
            self.queue.move_all_to_active()
        else:
            self.queue.delete(key)
        self.cache.packer.forget_pod(key)
        self._cycle_states.pop(key, None)
        self.why_pending.pop(key, None)

    def on_node_add(self, node) -> None:
        self.cache.add_node(node)
        self.node_tree.add_node(node)
        self.queue.move_all_to_active()

    def on_node_update(self, node) -> None:
        old = self.cache.node(node.name)
        if old is not None:
            self.node_tree.remove_node(old)
        self.cache.update_node(node)
        self.node_tree.add_node(node)
        self.queue.move_all_to_active()

    def on_node_delete(self, name: str) -> None:
        old = self.cache.node(name)
        if old is not None:
            self.node_tree.remove_node(old)
        self.cache.remove_node(name)

    def set_volume_state(self, pvcs=(), pvs=(), classes=()) -> None:
        """PV/PVC/StorageClass informer feed. Any volume-state change can
        make pods schedulable, so the unschedulable queue resweeps (the
        reference moves on PV/PVC add/update events, eventhandlers.go).
        The cached node snapshot is invalidated: scheduled pods' volume
        tokens (NodeTable.pd_mh/csi_mh/vol_*_mh) depend on PVC->PV
        resolution, which just changed under them."""
        self.cache.packer.set_volume_state(pvcs, pvs, classes)
        self.cache.invalidate_snapshot()
        self.queue.move_all_to_active()

    def set_attached_residue(self, residue) -> None:
        """Actual-state feed from the attach-detach controller
        (attach_detach_controller.go:102): per-node PV names attached
        WITHOUT a live pod deriving them (detach-grace stragglers). They
        occupy attach-limit slots, so the snapshot is invalidated and —
        since a detach can free a slot a pending pod was waiting on —
        unschedulables resweep like any volume-state change."""
        self.cache.packer.attached_residue = dict(residue)
        self.cache.invalidate_snapshot()
        self.queue.move_all_to_active()

    # -- crash / failover / device-loss recovery ---------------------------

    def attach_elector(self, elector, lister=None):
        """Wire leader election into the scheduler's recovery protocol:
        the elector becomes the bind fence (its ``allow_bind`` gates
        every hub write when ``recovery.fenced_binds``), gaining
        leadership runs takeover reconciliation (:meth:`reconcile`), and
        losing it drains in-flight state (:meth:`on_stopped_leading`).
        ``lister`` (optional, ``() -> iterable of truth Pods``) gives the
        reconciliation an authoritative relist source; without one the
        informer feed is trusted and reconciliation is local-only.
        Pre-existing elector callbacks are preserved (chained after
        ours). Returns the elector."""
        self.fence = elector
        self._lister = lister
        prev_start = elector.on_started_leading
        prev_stop = elector.on_stopped_leading

        def started():
            self.on_started_leading()
            prev_start()

        def stopped():
            self.on_stopped_leading()
            prev_stop()

        elector.on_started_leading = started
        elector.on_stopped_leading = stopped
        return elector

    def attach_auditor(self, auditor):
        """Wire a state-conservation auditor (obs/audit.py): the
        scheduler reports legitimate pod exits (watch deletes,
        terminating skips, reconcile drops) via ``note_gone`` so the
        auditor's per-audit conservation rule never counts an explained
        exit as a lost pod. Attaches metrics / event sink / obs when the
        auditor has none. Returns the auditor."""
        self.auditor = auditor
        if getattr(auditor, "metrics", "absent") is None:
            auditor.metrics = self.metrics
        if getattr(auditor, "event_sink", "absent") is None:
            auditor.event_sink = (
                lambda reason, obj, msg: self.event_sink(reason, obj, msg))
        if getattr(auditor, "obs", "absent") is None:
            auditor.obs = self.obs
        return auditor

    def _note_gone(self, key: str) -> None:
        """A pod legitimately left the state machine — tell the
        attached auditor (no-op without one) and close its journey."""
        if self.auditor is not None:
            self.auditor.note_gone(key)
        self.obs.journeys.note_gone(key)

    def on_started_leading(self) -> None:
        """OnStartedLeading (app/server.go:261): this incarnation just
        became the writer. Reconcile before the first cycle so a crash
        of the previous leader between its hub commit and its local
        ``finish_binding`` converges instead of leaking."""
        if not self.recovery.reconcile_on_takeover:
            return
        pods = None
        if self._lister is not None:
            pods = list(self._lister())
        self.reconcile(pods)

    def on_stopped_leading(self) -> None:
        """Deposed (lease lost or released): drain in-flight cycle
        state. Permit-parked pods are rejected and requeued (their
        capacity would otherwise be held forever — the fence blocks
        their eventual bind anyway), local assumptions are forgotten and
        their pods requeued (if the bind DID commit at the hub, the
        watch MODIFIED event deletes them from the queue; if it did
        not, the new leader — or this one, re-elected — binds them).
        The queues themselves stay: informers run on standbys."""
        import dataclasses as _dc

        fw = self.framework
        drained = 0
        res = CycleResult()
        for wp in list(fw.waiting.items()):
            key = wp.pod.key()
            fw.waiting.remove(key)
            self.cache.forget_pod(key)
            self.volume_binder.forget_pod_volumes(key)
            fw.run_unreserve(
                self._cycle_states.get(key) or _new_cycle_state(),
                wp.pod, wp.node_name)
            self._fail(wp.pod, self.queue.scheduling_cycle, res,
                       ("Permit:lost leadership",))
            self._cycle_states.pop(key, None)
            drained += 1
        # ambiguous-bind parks are assumed pods too: the sweep below
        # drains the assumption; the NEW leader's reconcile resolves
        # what the hub actually committed (its relist truth is the
        # read-your-write answer)
        self._ambiguous_binds.clear()
        for key in self.cache.assumed_keys():
            pod = self.cache.pod(key)
            self.cache.forget_pod(key)
            self.volume_binder.forget_pod_volumes(key)
            # the per-attempt cycle state dies with the assumption: the
            # requeued pod starts a fresh attempt, and a row kept here
            # survives every later leadership flip (leak, sentinel-pinned)
            self._cycle_states.pop(key, None)
            if pod is not None and self.responsible_for(pod):
                self.queue.add_if_not_present(
                    _dc.replace(pod, node_name=""))
            drained += 1
        if drained:
            klog.warning("stopped leading: drained %d in-flight pods",
                         drained)
            self.metrics.recovery_drained.inc(drained)
            self._record_metrics(res)

    def reconcile(self, pods=None) -> Dict[str, int]:
        """Takeover / cold-start reconciliation — converge local state
        with the hub truth so the invariant triple holds across a crash:
        no pod double-bound, no assumption leaked, every schedulable pod
        eventually bound.

        With ``pods`` (the relisted truth): adopt bound pods this cache
        does not know (bound by a dead incarnation or another writer),
        forget assumptions the API contradicts (pod gone, recreated
        under a new uid, or bound elsewhere), and requeue responsible
        unbound pods that fell out of the queues. Always: resweep the
        unschedulable queue, drop + rebuild the device-resident
        snapshot (a new leader's resident arrays may predate the old
        leader's last commits; after a crash they don't exist), and
        re-arm the AOT warmup. Returns the action counts."""
        from kubernetes_tpu.api.types import is_pod_terminated

        adopted = forgotten = requeued = 0
        if pods is not None:
            # the relisted truth IS the read-your-write answer for any
            # parked ambiguous bind — the assumed-keys sweep below
            # settles them (adopt or forget), so the parks are moot.
            # Truthless reconciles keep them parked: clearing without a
            # verdict would leak the TTL-less assumption forever.
            self._ambiguous_binds.clear()
            truth = {p.key(): p for p in pods}
            for key in list(self.cache.assumed_keys()):
                cached = self.cache.pod(key)
                tp = truth.get(key)
                ok = (
                    tp is not None
                    and tp.node_name
                    and cached is not None
                    and tp.uid == cached.uid
                    and tp.node_name == cached.node_name
                )
                if ok:
                    # truth agrees with the assumption: the bind DID
                    # commit (possibly by our dead predecessor) —
                    # confirm it instead of waiting out the TTL
                    self.cache.add_pod(tp)
                    adopted += 1
                else:
                    self.cache.forget_pod(key)
                    self.volume_binder.forget_pod_volumes(key)
                    forgotten += 1
            for key, tp in truth.items():
                if is_pod_terminated(tp):
                    continue
                if tp.node_name:
                    cached = self.cache.pod(key)
                    if cached is None or cached.uid != tp.uid \
                            or cached.node_name != tp.node_name:
                        if cached is not None:
                            self.cache.remove_pod(key)
                        self.cache.add_pod(tp)
                        adopted += 1
                    # bound at the hub: whatever a stale queue thinks,
                    # this pod must never be scheduled again here — and
                    # its pending-explanation row retires with it (the
                    # normal bind paths pop it; adoption must too)
                    self.queue.delete(key)
                    self.why_pending.pop(key, None)
                    self._cycle_states.pop(key, None)
                    # a reconcile-adopted bind never went through THIS
                    # incarnation's bind tail: close the journey as
                    # gone (no bogus e2e sample, no bound outcome)
                    self.obs.journeys.note_gone(key)
                elif self.responsible_for(tp):
                    queued = self.queue.pod(key)
                    if (queued is not None and queued.uid == tp.uid) \
                            or self.framework.waiting.get(key) is not None:
                        continue  # already queued/parked with the live uid
                    if self.cache.pod(key) is not None:
                        # we think it's placed, the API says unbound:
                        # a half-crashed bind — forget and retry
                        if self.cache.is_assumed(key):
                            self.volume_binder.forget_pod_volumes(key)
                        self.cache.remove_pod(key)
                        forgotten += 1
                    if queued is not None:
                        # recreated under the same key with a new uid:
                        # the stale queued object must never be adopted
                        # or bound — the truth object replaces it
                        self.queue.delete(key)
                    self.queue.add_if_not_present(tp)
                    requeued += 1
            # pods the truth no longer contains must leave the queues
            # (duck-typed: queue fakes without the dump surface skip)
            pp = getattr(self.queue, "pending_pods", None)
            if pp is not None:
                for qpods in pp().values():
                    for p in qpods:
                        if p.key() not in truth:
                            self.queue.delete(p.key())
                            self._note_gone(p.key())
                            # exit path parity with on_pod_delete: the
                            # pod-keyed side state leaves with the pod,
                            # or churn between relists grows it forever
                            self._cycle_states.pop(p.key(), None)
                            self.why_pending.pop(p.key(), None)
                            self.cache.packer.forget_pod(p.key())
        # local convergence, truth or not: resweep parked pods (this
        # incarnation may have missed move events), rebuild the
        # device-resident snapshot from the host mirror, re-warm
        self.queue.move_all_to_active()
        self.cache.invalidate_snapshot()
        self.cache.drop_device_snapshot()
        # warm-solve state (score cache already died with the resident
        # table; potentials must die too — they summarize a plane the
        # old incarnation solved, not the relisted truth)
        self._drop_incremental("takeover")
        self._device_cooloff_until = 0.0
        epoch = getattr(self.fence, "epoch", 0) or 1
        self.metrics.recovery_takeovers.inc()
        if adopted:
            self.metrics.recovery_adopted.inc(adopted)
        if forgotten:
            self.metrics.recovery_forgotten.inc(forgotten)
        if requeued:
            self.metrics.recovery_requeued.inc(requeued)
        self.obs.note_takeover(epoch)
        klog.V(2).info(
            "takeover reconciliation (epoch %d): adopted=%d forgotten=%d "
            "requeued=%d", epoch, adopted, forgotten, requeued)
        if self.warmup_config.enabled and self.cache.node_count():
            # re-arm AOT warmup: the jit cache survives in-process
            # re-election (cheap no-op), but a cold-started incarnation
            # recompiles here instead of on the first cycle's hot path
            pp = getattr(self.queue, "pending_pods", None)
            sample = pp().get("active", [])[:64] if pp else []
            self.warmup(sample_pods=sample)
        return {"adopted": adopted, "forgotten": forgotten,
                "requeued": requeued}

    def _fence_ok(self) -> bool:
        """May a hub write (assume -> bind) go out now? Unfenced
        schedulers (no elector attached / fencing disabled) always may."""
        if self.fence is None or not self.recovery.fenced_binds:
            return True
        return self.fence.allow_bind()

    def _fenced(self, pod: Pod, cycle: int, res: CycleResult) -> None:
        """Abort one pod's bind at the fence: count it, flag the flight
        record, requeue through the standard error path (the NEW leader
        binds it; this one must not race the hub CAS)."""
        self.metrics.recovery_fenced_binds.inc()
        self.obs.note_fenced_bind()
        self.obs.journeys.note_fenced(pod.key())
        self._fail(pod, cycle, res, ("FencedBind:lease lost",))

    def _reap_expired_assumptions(self) -> None:
        """Drive cache TTL expiry and HANDLE the result (satellite of
        the recovery PR — both call sites previously discarded it): log,
        count, emit an AssumptionExpired event, and converge the pod.

        An expired assumption is the SAME ambiguity class as a timed-out
        bind: the commit very likely landed and only the watch
        confirmation was lost. With a ``pod_reader`` the expiry resolves
        by read-your-write verification — adopt a hub-confirmed binding,
        requeue only when verified unbound, park (re-assumed, no TTL)
        while the hub is unreachable — so the reap never blind-requeues
        a pod whose retry would re-bind at the hub. Without a reader the
        legacy optimistic path remains: requeue, and if the pod actually
        IS bound (watch merely slow) the eventual MODIFIED event deletes
        it from the queue; until then a re-bind attempt fails the hub
        CAS harmlessly."""
        import dataclasses as _dc

        expired = self.cache.pop_expired()
        if not expired:
            return
        self.metrics.cache_expired_assumptions.inc(len(expired))
        for p in expired:
            key = p.key()
            if self.pod_reader is not None:
                resolution = self._resolve_ambiguous_bind(p, p.node_name)
                self.metrics.bind_ambiguous.inc(
                    resolution=f"expired-{resolution or 'deferred'}")
                if resolution == "adopted":
                    # the hub HAS our binding — the confirmation was
                    # merely lost; re-add bound (capacity re-held)
                    self.cache.add_pod(p)
                    klog.V(2).info(
                        "assumed pod %s expired but the hub confirms "
                        "the binding to %s — adopted, not requeued",
                        key, p.node_name)
                    continue
                if resolution is None:
                    # verification unreachable too: park assumed (no
                    # TTL) and re-probe each cycle / idle tick — a
                    # requeue during a hub outage is exactly the blind
                    # retry the protocol forbids
                    self.cache.assume_pod(p, p.node_name)
                    self._ambiguous_binds[key] = (p, p.node_name, None)
                    self.obs.journeys.note_ambiguous_park(
                        key, "assume-expired")
                    klog.warning(
                        "assumed pod %s expired and verification is "
                        "unreachable; parked assumed", key)
                    continue
                if resolution in ("conflict", "gone"):
                    # deleted, recreated under a new uid, or bound by
                    # another writer: drop the stale local copy — the
                    # watch/relist delivers the truth object
                    self.volume_binder.forget_pod_volumes(key)
                    self._note_gone(key)
                    continue
                # "requeued": verified unbound — safe to retry below
            klog.warning(
                "assumed pod %s on %s expired (bind confirmation never "
                "arrived within %.0fs); requeueing", key, p.node_name,
                self.cache.ttl_s)
            self.volume_binder.forget_pod_volumes(key)
            pending = _dc.replace(p, node_name="")
            self.event_sink(
                "AssumptionExpired", pending,
                f"binding to {p.node_name} was never confirmed within "
                f"{self.cache.ttl_s:.0f}s; capacity freed, pod requeued")
            if self.responsible_for(pending):
                self.queue.add_if_not_present(pending)

    def _device_snapshot_recovering(self):
        """``cache.device_snapshot()`` with device-loss recovery: any
        error from the resident path (a lost/OOMed accelerator — or the
        injected ``snapshot:device`` chaos rules standing in for one)
        drops the resident arrays and rebuilds them from the host
        mirror, up to ``recovery.device_reset_limit`` attempts per
        cycle; past the budget the scheduler falls back to host-mode
        snapshots for ``device_cooloff_s`` (the ladder meanwhile absorbs
        solve failures: batch -> batch-cpu -> greedy), then probes the
        device again. Returns ``(table, dev_or_None, mode)`` exactly
        like the call sites expect (``dev=None`` + mode "host" on the
        fallback path)."""
        if self.clock() < self._device_cooloff_until:
            return self.cache.snapshot(), None, "host"
        attempts = 0
        while True:
            try:
                out = self.cache.device_snapshot()
                if attempts:
                    klog.V(2).info("device snapshot rebuilt after %d "
                                   "reset(s)", attempts)
                return out
            except Exception as e:
                attempts += 1
                self.metrics.recovery_device_resets.inc()
                self.obs.note_device_reset()
                # forensic snapshot BEFORE the drop below deregisters
                # the residents — the ranked record must show what was
                # on the device at the moment it was lost
                ml = self.obs.memledger
                if ml.enabled:
                    oomrec = ml.record_oom(
                        "snapshot:device", error=str(e),
                        cycle=self.queue.scheduling_cycle)
                    self.obs.note_oom_forensic(ml.oom_flag(oomrec))
                klog.warning("device snapshot failed (%s); dropping "
                             "resident table (reset %d/%d)", e, attempts,
                             self.recovery.device_reset_limit)
                self.cache.drop_device_snapshot()
                # the score cache died with the resident table; the
                # potential carry must not survive the device either
                self._drop_incremental("device-loss")
                if attempts > self.recovery.device_reset_limit:
                    self._device_cooloff_until = (
                        self.clock() + self.recovery.device_cooloff_s)
                    klog.warning(
                        "device snapshot rebuild budget exhausted; "
                        "host-mode snapshots for %.1fs",
                        self.recovery.device_cooloff_s)
                    return self.cache.snapshot(), None, "host"

    def _place(self, t):
        """Replicate a device pytree across the node-axis mesh —
        identity when the sharded backend is off OR this cycle fell
        back to single-device host-mode snapshots (device cooloff).
        The pod/selector/topology/volume tables all ride this: the
        (P, N) kernels then see replicated-P x sharded-N operands and
        GSPMD partitions them along N."""
        if t is None or not self._mesh_live:
            return t
        from kubernetes_tpu.parallel.mesh import replicate

        return replicate(t, self.mesh)

    # -- the cycle ---------------------------------------------------------

    def schedule_cycle(self, flush_trigger: str = "",
                       window_s: float = 0.0) -> CycleResult:
        """One batched scheduling pass over everything in activeQ.

        ``flush_trigger``/``window_s`` are the serving loop's micro-batch
        provenance (what flushed the accumulation window and how long it
        held) — threaded onto the CycleResult and the flight record so a
        latency incident can distinguish window time from solve time."""
        from kubernetes_tpu.ops.assign import (
            _apply_batch,
            batch_assign,
            greedy_assign,
            nodes_with_usage,
            usage_from_nodes,
        )
        from kubernetes_tpu.ops.predicates import decode_reasons

        from kubernetes_tpu.framework import CycleState

        t0 = self.clock()
        res = CycleResult(flush_trigger=flush_trigger, window_s=window_s)
        # per-cycle deadline (robustness.cycle_deadline_s): propagated to
        # the solver ladder (skip-to-oracle once blown) and the extender
        # calls (shed) so one wedged dependency can't stall the queue
        self._cycle_deadline = (
            t0 + self.robustness.cycle_deadline_s
            if self.robustness.cycle_deadline_s > 0 else None
        )
        trace = self.obs.begin_cycle(self.queue.scheduling_cycle)
        if flush_trigger:
            self.obs.note_microbatch(flush_trigger, window_s)
        self.queue.tick()
        self._reap_expired_assumptions()
        self._verify_ambiguous_binds()
        # cadence re-pack BEFORE the batch pops: pods drained here
        # re-enter this same cycle's solve under the consolidation
        # objective instead of waiting out another interval
        self.maybe_repack()
        self._process_waiting(res)
        batch = self.queue.pop_batch(self.max_batch)
        if not batch:
            res.elapsed_s = self.clock() - t0
            self._record_metrics(res)
            self._explain_retire_if_drained()
            self.obs.end_cycle(res)
            return res
        cycle = self.queue.scheduling_cycle
        self.obs.note_cycle(cycle)
        # skipPodSchedule (scheduler.go:335): a pod already marked for
        # deletion is dropped from the cycle, not retried — its DELETED
        # event (kubelet kill or pod-GC) is the terminal outcome; the
        # auditor's conservation rule learns the exit NOW so the window
        # until that event is not read as a lost pod
        for p in batch:
            if p.deletion_timestamp:
                self._note_gone(p.key())
        batch = [p for p in batch if not p.deletion_timestamp]
        res.attempted = len(batch)
        fw = self.framework

        # PreFilter (framework.go RunPrefilterPlugins): any non-success
        # aborts that pod's cycle before it reaches the device
        kept = []
        for p in batch:
            st = CycleState()
            self._cycle_states[p.key()] = st
            status = fw.run_prefilter(st, p)
            if status.is_success():
                kept.append(p)
            else:
                self._fail(p, cycle, res, (f"PreFilter:{status.message}",))
        batch = kept
        if not batch:
            # every popped pod failed PreFilter: they still get report
            # rows (status reasons, no device analytics) and the reason
            # gauges roll over to this cycle instead of going stale
            res.elapsed_s = self.clock() - t0
            if getattr(self.obs.config, "explain", True):
                self._build_explain_report(
                    cycle, [], [], None, self.cache.node_count(), res)
            self._record_metrics(res)
            self.obs.end_cycle(res)
            return res

        # pack: pods first (their programs grow universes), then snapshot
        with self.obs.span("snapshot"):
            pk = self.cache.packer
            batch_keys = {p.key() for p in batch}
            nominated = self._nominated_pods(exclude=batch_keys)
            for p in batch:
                pk.intern_pod(p)
            for p, _ in nominated:
                pk.intern_pod(p)
            if self.device_resident_snapshot:
                # incremental device-resident snapshot: the packed node
                # table lives on device across cycles; dirty rows patch
                # in with a jitted scatter, full rebuilds only on shape/
                # width changes or explicit invalidation (cache.py).
                # Device errors recover via drop + host-mirror rebuild
                # (_device_snapshot_recovering — "host" mode fallback
                # while the device is cooling off)
                nt, dn, snap_mode = self._device_snapshot_recovering()
            else:
                nt = self.cache.snapshot()
                dn = None
                snap_mode = "host"
            node_order = self.cache.node_order()
            pt = pk.pack_pods(batch)
            # host-side feature gates: priorities whose inputs are absent
            # from THIS snapshot are replaced by their exact constants
            # inside the solve, and the port-conflict matmuls are skipped
            # for port-free batches (static jit keys;
            # ops/priorities.empty_priorities,
            # ops/predicates.pods_have_no_ports)
            skip_prio, no_ports, no_pod_aff, no_spread = solver_gates(nt, pt)
            # mesh liveness for THIS cycle: resident snapshots come back
            # already sharded (cache.set_mesh); the legacy per-cycle
            # host pack re-places onto the mesh below; only the device-
            # loss cooloff (resident on, dev None) stays single-device —
            # a lost shard must not be re-engaged until the heal probe
            self._mesh_live = (self.mesh is not None
                               and (dn is not None
                                    or not self.device_resident_snapshot))
            self.obs.note_mesh_cycle(
                int(self.mesh.devices.size) if self._mesh_live else 0)
            if dn is None:
                if self._mesh_live:
                    from kubernetes_tpu.parallel.mesh import (
                        place_node_table,
                    )

                    dn = place_node_table(nt, self.mesh)
                else:
                    dn = nodes_to_device(nt)
            use_pipeline = self._pipeline_eligible(batch, nominated)
            # capacity preflight (obs/memledger.py): check this cycle's
            # padded solve shape against the warmed per-bucket peak
            # table BEFORE materialising the padded pod batch. An
            # over-budget shape splits down to the largest warmed
            # bucket that fits (tail requeued for the next cycle) or
            # sheds the whole batch — a deliberate requeue beats a
            # device OOM mid-solve. Pipelined cycles solve at the chunk
            # shape, so they preflight that and shed rather than split
            # (the chunk is already the smallest unit).
            preflight_shed = False
            pad_p = 0  # preflight's dp-padding override (0 = default)
            ml = self.obs.memledger
            if ml.preflight_on and batch:
                eff_p = bucket_size(max(
                    min(len(batch), self.pipeline_chunk) if use_pipeline
                    else len(batch), 1))
                act, split_p, verdict = ml.preflight(
                    eff_p, int(dn.valid.shape[0]),
                    int(self.mesh.devices.size) if self._mesh_live else 0)
                self.obs.note_preflight(act)
                if act == "split" and not use_pipeline and split_p > 0:
                    if split_p < len(batch):
                        for p in batch[split_p:]:
                            self._cycle_states.pop(p.key(), None)
                            self.queue.add_if_not_present(p)
                        trace.step(
                            f"preflight split {len(batch)} -> {split_p}"
                            f" pods ({verdict})")
                        batch = batch[:split_p]
                        res.attempted = len(batch)
                        # pt was packed for the full batch above — re-
                        # pack at the trimmed shape (the gate flags from
                        # the superset pack stay valid: they can only be
                        # conservative for a subset)
                        pt = pk.pack_pods(batch)
                    # the default padding (bucket_size of the remaining
                    # batch) may still round UP past the budget — e.g. a
                    # 4-pod batch whose geometric bucket is 8 when only
                    # the warmed P=4 shape fits. Pin dp to the warmed
                    # bucket the preflight actually cleared.
                    pad_p = split_p
                elif act == "shed" or (act == "split" and use_pipeline):
                    for p in batch:
                        self._cycle_states.pop(p.key(), None)
                        self.queue.add_if_not_present(p)
                    trace.step(f"preflight shed {len(batch)} pods"
                               f" ({verdict})")
                    batch = []
                    res.attempted = 0
                    preflight_shed = True
                    use_pipeline = False
            dp = (None if use_pipeline or preflight_shed else self._place(
                  pods_to_device(pt, pad_to=(
                      pad_p or bucket_size(max(len(batch), 1))))))
            ds = self._place(selectors_to_device(pk.pack_selector_tables()))
            dt = self._place(topology_to_device(pk.pack_topology_tables())
                             if _has_topo(pk.u) else None)
            dv = sv = None
            if dp is not None and any(p.volumes for p in batch):
                from kubernetes_tpu.ops.arrays import volumes_to_device

                dv = self._place(volumes_to_device(pk.pack_volume_tables(batch)))
                sv = _static_vol_pass(dp, dn, ds, dv)
            if ml.enabled:
                # this cycle's padded operand tables (pods + selector/
                # topology/volume memos) — re-registered every cycle at
                # the current shape, popped when the cycle sheds
                ml.register_tree("scheduler.pod_batch", dp, ds, dt, dv)
            trace.step(f"snapshot packed ({len(batch)} pods, {nt.n} nodes,"
                       f" {snap_mode})")
        res.snapshot_mode = snap_mode
        # host mode never touches the cache's device bookkeeping: it
        # packs+uploads the whole table right here, every cycle
        snap_rows = (nt.n if snap_mode == "host"
                     else self.cache.last_upload_rows)
        self.metrics.snapshot_packs.inc(mode=snap_mode)
        self.metrics.snapshot_rows_packed.inc(snap_rows)
        self.obs.note_snapshot(snap_mode, snap_rows)
        # h2d accounting (only what actually crossed the boundary: full
        # uploads count the whole resident table, delta cycles count the
        # scattered rows via the cache's byte ledger, clean cycles count
        # nothing) + the batch-shape digest for the flight recorder
        uploads = [t for t in (dp, ds, dt, dv) if t is not None]
        if snap_mode in ("host", "full"):
            uploads.append(dn)  # the whole node table crossed over
        elif self.cache.last_upload_nbytes:
            # delta: only the scattered rows crossed — charge the
            # cache's byte ledger, not the resident table's full size
            self.obs.jax.record_transfer(
                "snapshot", "h2d", self.cache.last_upload_nbytes)
        self.obs.jax.record_upload("snapshot", *uploads)
        self.obs.note_batch_shape(
            f"P{dp.valid.shape[0] if dp is not None else len(batch)}"
            f"xN{dn.valid.shape[0]}"
            + ("+topo" if dt is not None else "")
            + ("+vol" if dv is not None else "")
            + (f"+pipe{self.pipeline_chunk}" if use_pipeline else "")
            + (f"+mesh{int(self.mesh.devices.size)}"
               if self._mesh_live else "")
        )
        if preflight_shed:
            # the whole batch was requeued by the capacity preflight —
            # the cycle still flushes its snapshot accounting and flight
            # record (action=shed rode note_preflight above), it just
            # never builds the padded batch or touches the solver
            res.elapsed_s = self.clock() - t0
            self._record_metrics(res)
            self.obs.end_cycle(res)
            return res

        if use_pipeline:
            # the pipelined cycle executor owns the rest of the cycle on
            # the clean fast path (no extenders / host plugins / gang /
            # nominated pods — _pipeline_eligible)
            return self._pipelined_tail(
                batch, cycle, res, t0, trace, nt, dn, ds, dt, node_order,
                skip_prio, no_ports, no_pod_aff, no_spread,
            )

        # incremental solve: a steady-state micro-batch on a clean/delta
        # resident snapshot solves RESTRICTED — candidate columns from
        # the cached score plane instead of the full (P, N) dense pass.
        # A declined/under-placed/invalid attempt falls through to the
        # cold solve below (the correctness fallback).
        if self._incremental_eligible(batch, nominated, dn, dt, dv,
                                      snap_mode, no_ports, no_pod_aff,
                                      no_spread, nt):
            inc_out = self._restricted_tail(
                batch, cycle, res, t0, trace, nt, dn, ds, dp, node_order,
                skip_prio)
            if inc_out is not None:
                return inc_out

        # sparsity-first PRIMARY mode: cycles the restricted warm route
        # did not take (full-snapshot rebuilds, oversized batches,
        # declined attempts) solve PARTITIONED — capacity-balanced
        # fixed-width column blocks through the warmed restricted
        # program — before the dense plane is ever materialized. The
        # dense ladder below stays the correctness oracle: a
        # partitioned attempt that cannot place its whole batch binds
        # nothing and falls through.
        if self._partitioned_cold_eligible(batch, nominated, dn, dt, dv,
                                           no_ports, no_pod_aff,
                                           no_spread):
            cold_out = self._partitioned_cold_tail(
                batch, cycle, res, t0, trace, nt, dn, ds, dp, node_order,
                skip_prio)
            if cold_out is not None:
                return cold_out

        # framework Filter/Score contributions: device batch plugins give
        # whole (P, N) matrices; host plugins evaluate per (pod, nodeName)
        # once per cycle (the non-tensorizable escape hatch)
        extra_score = None
        batch_state = CycleState()
        fw_mask = fw.run_filter_batch(batch_state, dp, dn, ds)
        fw_score = fw.run_score_batch(batch_state, dp, dn, ds)
        if fw_score is not None:
            extra_score = fw_score
        early_fail: Dict[int, str] = {}
        if fw.has_host_filters() or fw.has_host_scores():
            hm = np.ones((dp.valid.shape[0], dn.valid.shape[0]), bool)
            hs = np.zeros((dp.valid.shape[0], dn.valid.shape[0]), np.float32)
            for i, p in enumerate(batch):
                st = self._cycle_states[p.key()]
                try:
                    for j, name in enumerate(node_order):
                        if fw.has_host_filters():
                            hm[i, j] = fw.run_host_filter(st, p, name).is_success()
                        if fw.has_host_scores() and hm[i, j]:
                            hs[i, j] = fw.run_host_score(st, p, name)
                except Exception as e:
                    # ANY host-plugin failure (a raising Filter or Score
                    # plugin included) aborts only THIS pod's cycle — the
                    # reference converts plugin errors into a per-pod
                    # error status (RunFilterPlugins/PrioritizeNodes
                    # return an error for that pod; other pods proceed);
                    # letting it propagate would abort the whole batch
                    # with popped pods never requeued
                    hm[i, :] = False
                    early_fail[i] = f"HostPlugin:{e}"
            if fw.has_host_filters():
                m = jnp.asarray(hm)
                fw_mask = m if fw_mask is None else (fw_mask & m)
            if fw.has_host_scores():
                extra_score = (
                    jnp.asarray(hs)
                    if extra_score is None
                    else extra_score + jnp.asarray(hs)
                )

        # node-search truncation: restrict this cycle's solve to the next
        # K nodes in zone rotation (numFeasibleNodesToFind semantics)
        if self.percentage_of_nodes_to_score is not None:
            from kubernetes_tpu.nodetree import num_feasible_nodes_to_find

            k = num_feasible_nodes_to_find(
                nt.n, self.percentage_of_nodes_to_score
            )
            if k < nt.n:
                subset = set(self.node_tree.take(k))
                col = np.zeros((dn.valid.shape[0],), bool)
                for j, name in enumerate(node_order):
                    col[j] = name in subset
                cm = jnp.asarray(col)[None, :]
                fw_mask = cm if fw_mask is None else (fw_mask & cm)

        # one shared built-in filter pass against the initial usage, used
        # by the extender path and the exact solver (avoid re-evaluating)
        base_fr = None
        if self.extenders or self.solver == "exact":
            base_fr = _filter_pass(dp, dn, ds, dt, dv, sv, self.pred_mask)

        # scheduler extenders (generic_scheduler.go:539-566: after built-in
        # predicates; prioritize adds weight*score to the totals :799-829)
        if self.extenders:
            with self.obs.span("extenders"):
                em, es = self._run_extenders(
                    batch, base_fr, node_order, early_fail)
            if em is not None:
                fw_mask = em if fw_mask is None else (fw_mask & em)
            if es is not None:
                extra_score = es if extra_score is None else extra_score + es
            trace.step("extenders done")

        # scenario-pack objective: the pack's (P, N) cost term joins the
        # framework/extender score seam, so it rides every ladder tier
        # (sharded batch, batch-single, batch-cpu, the greedy oracle)
        # AND the exact solver unchanged — objective selection through
        # the ladder, not a solver fork (docs/scenarios.md)
        if self.scenario_pack is not None:
            with self.obs.span("scenario:cost"):
                sc_cost = self.scenario_pack.cost(batch, nt, node_order,
                                                  dp, dn)
            if sc_cost is not None:
                extra_score = (sc_cost if extra_score is None
                               else extra_score + sc_cost)

        # nominated-pods pass A (podFitsOnNode two-pass rule,
        # generic_scheduler.go:610): feasibility must ALSO hold with the
        # nominated pods counted onto their nodes. Divergence from the
        # reference, documented: ALL nominated pods are added, not only
        # those of higher/equal priority — strictly more conservative (a
        # pod may wait one extra cycle; capacity is never double-promised).
        extra_mask = fw_mask
        if nominated:
            row_of = {name: i for i, name in enumerate(node_order)}
            nom_pods = [p for p, _ in nominated]
            dpn = self._place(pods_to_device(pk.pack_pods(nom_pods)))
            nom_rows = np.zeros((dpn.valid.shape[0],), np.int32)
            nom_ok = np.zeros((dpn.valid.shape[0],), bool)
            for j, (_, node) in enumerate(nominated):
                r = row_of.get(node, -1)
                nom_rows[j], nom_ok[j] = max(r, 0), r >= 0
            u_nom = _apply_batch(
                usage_from_nodes(dn), dpn, jnp.asarray(nom_rows),
                jnp.asarray(nom_ok) & dpn.valid,
            )
            nom_mask = _filter_pass(
                dp, nodes_with_usage(dn, u_nom), ds, dt, dv, sv, self.pred_mask
            ).mask
            extra_mask = nom_mask if extra_mask is None else (extra_mask & nom_mask)

        solver = self.solver
        if solver == "exact":
            # The exact Hungarian models capacity as per-node SLOTS only:
            # in-batch coupling through host ports, volumes, or topology
            # terms is not in its constraint matrix, so two co-admitted
            # pods could silently conflict. Round 2 documented the blind
            # spot in a docstring; now it's structural — hazardous batches
            # auto-fall back to the round solver, which models all three
            # (one-per-node-per-round guards in ops/assign.py).
            hazards = []
            # batch-scoped: in-batch coupling needs THIS batch's pods to
            # carry terms (dt reflects the monotonic universe — one
            # affinity pod ever seen would disable exact forever)
            if dt is not None and any(
                p.affinity.pod_affinity_required
                or p.affinity.pod_anti_affinity_required
                or p.affinity.pod_affinity_preferred
                or p.affinity.pod_anti_affinity_preferred
                or p.topology_spread
                for p in batch
            ):
                hazards.append("topology")
            if dv is not None:
                hazards.append("volumes")
            if not no_ports:  # host-side gate already knows; no device sync
                hazards.append("host-ports")
            if hazards:
                self.exact_fallbacks += 1
                klog.V(4).info("exact solver unsafe (%s); using round "
                               "solver", "+".join(hazards))
                trace.step(
                    f"exact solver unsafe with {'+'.join(hazards)}; "
                    "using round solver"
                )
                solver = "batch"
        # retrace telemetry: classify this solve's abstract signature at
        # the host boundary BEFORE the jitted call — a new signature at a
        # warmed site means XLA recompiles underneath (zero host syncs:
        # the digest reads shape/dtype metadata only)
        self.obs.jax.record_call(
            "solve", dp, dn, ds, dt, dv,
            # extra_mask/extra_score None-ness joins the digest: a clean
            # batch routes to the fused lean round path (ops/assign.py),
            # a different compiled program than the extender/plugin-fed
            # one — without the flags an alternation would recompile
            # invisibly to the retrace telemetry
            static=(solver, tuple(skip_prio), no_ports, no_pod_aff,
                    no_spread, self.pred_mask, self.per_node_cap,
                    self.max_rounds, extra_mask is None,
                    extra_score is None,
                    # mesh liveness joins the digest: sharding is part
                    # of XLA's compile key but invisible to the shape/
                    # dtype digest — a cooloff flip to single-device
                    # would otherwise recompile unseen by the telemetry
                    self._mesh_live),
        )
        ladder = self._solve_ladder(
            solver, batch, dp, dn, ds, dt, dv, sv, base_fr, extra_mask,
            extra_score, skip_prio, no_ports, no_pod_aff, no_spread, res,
        )
        if ladder is None:
            # every tier failed (even the in-process oracle — a total
            # solver outage): fail the whole batch through the standard
            # error path so pods requeue with backoff instead of the
            # cycle stalling or binding garbage
            for pod in batch:
                self._fail(pod, cycle, res, ("SolverUnavailable",))
            res.elapsed_s = self.clock() - t0
            self._record_metrics(res)
            trace.log_if_long(self.trace_threshold_s)
            self.obs.end_cycle(res)
            return res
        assigned, usage, rounds, tier_used = ladder
        res.solver_tier = tier_used
        # the ladder already read the (validated) answer back as ONE
        # fused d2h transfer — slice off the padding rows, writable copy
        assigned = assigned[: len(batch)].copy()

        # gang scheduling (PodGroup all-or-nothing; the coscheduling-plugin
        # semantics BASELINE config 4 targets): a group binds only when ALL
        # its present members placed AND at least minMember members are
        # present (pod_group_min_available — guards against group fragments
        # straddling batches); otherwise every member rolls back
        gang_failed: Dict[int, str] = {}
        gang_groups: Dict[str, List[int]] = {}
        for gi, gp in enumerate(batch):
            if gp.pod_group:
                gang_groups.setdefault(gp.pod_group, []).append(gi)
        for gname, idxs in gang_groups.items():
            need = max([batch[gi].pod_group_min_available for gi in idxs] + [0])
            # members the cache already placed in EARLIER cycles count
            # toward minMember: a member whose bind failed transiently
            # re-queues ALONE, and crediting only batch-present members
            # would park it at this gate forever (GangIncomplete every
            # cycle) while its siblings run — a livelock, not a guard
            placed = self.cache.group_members(gname)
            incomplete = (len(idxs) + placed < need
                          or any(assigned[gi] < 0 for gi in idxs))
            if incomplete:
                for gi in idxs:
                    if assigned[gi] >= 0:
                        assigned[gi] = -1
                        gang_failed[gi] = f"GangIncomplete:{gname}"
        if gang_failed:
            # rebuild usage from the FINAL assignment: the solver's usage
            # still contains the rolled-back members, and phantom occupancy
            # would poison the failure-reason pass and preemption
            pad_assigned = np.full((dp.valid.shape[0],), -1, np.int64)
            pad_assigned[: len(batch)] = assigned
            usage = _apply_batch(
                usage_from_nodes(dn), dp,
                jnp.asarray(np.maximum(pad_assigned, 0)),
                jnp.asarray(pad_assigned >= 0) & dp.valid,
            )
        # scenario quality: dispatch the device reduction NOW (final
        # usage + final assignment, gang rollbacks applied) so it
        # executes while the host binds; its ~28 B vector is read back
        # after the bind loop alongside the failure readbacks
        q_dev = None
        if self.scenario_pack is not None and self.scenario.quality:
            from kubernetes_tpu.ops.scenario_cost import quality_reduce

            pad_a = np.full((dp.valid.shape[0],), -1, np.int32)
            pad_a[: len(batch)] = assigned
            q_dev = quality_reduce(jnp.asarray(pad_a), usage.requested,
                                   dp, dn)

        res.rounds = int(rounds)
        solve_s = trace.total_s()
        trace.step(f"solve done ({res.rounds} rounds)")
        self.metrics.algorithm_duration.observe(solve_s)

        # reasons for the unplaced: one more filter pass against the
        # post-assignment usage (what the serial loop would have seen
        # last). EVERYTHING the host needs — per-pod reason bits,
        # per-reason node counts, the per-resource Insufficient splits,
        # the one-bit-away relaxations — is reduced ON DEVICE
        # (obs/explain.explain_reduce) and read back as one small
        # transfer; the raw (P, N) reasons matrix never crosses the
        # boundary. Only preemption still needs per-node bits, gathered
        # for exactly the pods that will attempt it (readback
        # proportional to the answer, not the problem).
        failed_idx = [i for i, a in enumerate(assigned) if a < 0]
        preemptable_idx = [i for i in failed_idx if i not in gang_failed]
        reasons_row: Dict[int, Tuple[str, ...]] = {}
        fit_msgs: Dict[int, str] = {}
        ex = None
        ex_host = None
        preempt_rows_dev = None
        if failed_idx:
            from kubernetes_tpu.obs.explain import explain_reduce

            fr = _filter_pass(
                dp, nodes_with_usage(dn, usage), ds, dt, dv, sv, self.pred_mask
            )
            fm = np.zeros((dp.valid.shape[0],), bool)
            fm[failed_idx] = True
            ex = explain_reduce(
                fr.reasons, dn.valid, jnp.asarray(fm), dp.req,
                dn.allocatable - usage.requested, dn.ready,
                dn.network_unavailable)
            if self.enable_preemption and preemptable_idx:
                preempt_rows_dev = jnp.take(
                    fr.reasons,
                    jnp.asarray(preemptable_idx, dtype=jnp.int32), axis=0)

        bind_span = trace.begin_span("bind")
        # bind the placed pods FIRST: admission is pure host work, so it
        # overlaps the failure reductions still executing on device (JAX
        # async dispatch — the monolithic cycle's readback overlap; the
        # pipelined executor's pipeline:readback@k spans are the
        # chunked analog)
        for i, pod in enumerate(batch):
            if int(assigned[i]) >= 0:
                self._admit_pod(pod, node_order[int(assigned[i])], cycle,
                                res)
        if ex is not None:
            from kubernetes_tpu.ops.predicates import (
                fit_error_message_from_counts,
            )
            from kubernetes_tpu.snapshot import FIXED_RESOURCE_NAMES

            with self.obs.span("pipeline:readback@reasons"):
                ex_host = self.obs.jax.readback("explain", ex)._asdict()
            res_names = (list(FIXED_RESOURCE_NAMES)
                         + pk.u.scalar_resources.items())[: pt.req.shape[1]]
            for i in failed_idx:
                # a pod's reason set = union over valid nodes of failed
                # bits, reduced on device (zero when no node is valid)
                bits = int(ex_host["pod_bits"][i])
                reasons_row[i] = decode_reasons(bits)
                if bits:
                    # FitError-shaped event text with per-reason node
                    # counts ("2 Insufficient cpu, 3 node(s) had
                    # taints...") — byte-identical to the raw-matrix
                    # construction, from the reductions alone
                    fit_msgs[i] = fit_error_message_from_counts(
                        ex_host["per_pod"][i], ex_host["insufficient"][i],
                        ex_host["not_ready"][i], ex_host["net_unavail"][i],
                        nt.n, pt.req[i], res_names,
                    )
        for i, pod in enumerate(batch):
            if int(assigned[i]) >= 0:
                continue
            if i in early_fail:
                reasons = (early_fail[i],)
            elif i in gang_failed:
                reasons = (gang_failed[i],)
            else:
                reasons = reasons_row.get(i, ())
            # only filter-pass failures carry the FitError text; gang
            # rollbacks and plugin failures keep their own status (a
            # gang member may fit everywhere — a fabricated "0/N nodes
            # are available" would be a lie)
            msg = (fit_msgs.get(i)
                   if i not in early_fail and i not in gang_failed
                   else None)
            self._fail(pod, cycle, res, reasons, message=msg)

        trace.end_span(bind_span)
        trace.step(f"bound {res.scheduled}, failed {res.unschedulable}")

        if q_dev is not None:
            with self.obs.span("pipeline:readback@quality"):
                qvec = self.obs.jax.readback("scenario-quality", q_dev)
            from kubernetes_tpu.scenarios.quality import decode_quality

            quality = decode_quality(qvec)
            quality.update(
                self.scenario_pack.quality_host(batch, assigned, nt))
            res.scenario_quality = quality
            self._publish_scenario_quality(quality)

        # schedulability explainer: decode the read-back reduction into
        # the cycle's UnschedulableReport — every _fail'd pod gets a row
        # (filter failures carry device analytics; plugin/gang/bind
        # failures carry their status reasons), feeding /debug/why, the
        # flight recorder's top-K, and the unschedulable metrics
        if getattr(self.obs.config, "explain", True):
            self._build_explain_report(
                cycle, batch, failed_idx, ex_host, nt.n, res)

        # preemption (scheduler.go:493 -> preempt, §3.3): failed pods try to
        # evict lower-priority pods; winners get a nominated node and retry.
        # The per-node reason bits preemption needs are gathered on device
        # for exactly the preemptable rows — (F, N) across the boundary,
        # zero bytes on cycles where nothing failed
        if (self.enable_preemption and preemptable_idx
                and preempt_rows_dev is not None):
            with self.obs.span("pipeline:readback@preempt"):
                rows = self.obs.jax.readback("preempt-reasons",
                                             preempt_rows_dev)
            rmat = np.zeros((len(batch), rows.shape[1]), rows.dtype)
            rmat[preemptable_idx] = rows
            pt0 = self.clock()
            with self.obs.span("preemption"):
                if (self.scenario_pack is not None
                        and self.scenario_pack.wants_cascade):
                    # scenario packs: victims + displaced pods re-enter
                    # ONE dense solve in this same cycle instead of the
                    # per-pod nominate-and-wait loop
                    self._run_preemption_cascade(
                        batch, preemptable_idx, rmat, node_order, res)
                else:
                    self._run_preemption(
                        batch, preemptable_idx, rmat, node_order, res)
            self.metrics.preemption_duration.observe(self.clock() - pt0)
            trace.step(f"preemption ({res.preempted} victims)")
        return self._finish_cycle(res, cycle, t0, solve_s, trace)

    def _finish_cycle(self, res: CycleResult, cycle: int, t0: float,
                      solve_s: float, trace, label: str = "") -> CycleResult:
        """The shared end-of-cycle bookkeeping (monolithic AND pipelined
        paths): elapsed stamp, summary log, metrics, slow-cycle trace
        log, flight record. New finalization steps belong HERE so the
        two executors cannot silently diverge."""
        res.elapsed_s = self.clock() - t0
        res.solve_s = solve_s
        if res.solver_tier and not res.solve_scope:
            res.solve_scope = "full"
        if res.solve_scope:
            self.obs.note_solve_scope(res.solve_scope, res.reuse_frac)
            if self.incremental.enabled:
                m = getattr(self.metrics, "incremental_cycles", None)
                if m is not None:
                    m.inc(scope=res.solve_scope)
                g = getattr(self.metrics, "incremental_reuse_fraction",
                            None)
                if g is not None:
                    g.set(res.reuse_frac)
        if res.solver_tier:
            self.last_solver_tier = res.solver_tier
            self.last_solver_fallbacks = res.solver_fallbacks
        klog.V(3).info(
            "cycle %d%s: attempted=%d scheduled=%d unschedulable=%d "
            "rounds=%d %.3fs", cycle, label, res.attempted, res.scheduled,
            res.unschedulable, res.rounds, res.elapsed_s,
        )
        # backfill the ladder tier + solve scope onto the journey
        # attempt rows this cycle touched (known only now)
        self.obs.journeys.finish_cycle(cycle, res.solver_tier,
                                       res.solve_scope)
        self._record_metrics(res, solve_s)
        trace.log_if_long(self.trace_threshold_s)
        self.obs.end_cycle(res)
        return res

    def _record_metrics(self, res: CycleResult, solve_s: float = 0.0) -> None:
        """pkg/scheduler/metrics names; per-pod attempt counts, cycle-level
        durations, queue-depth gauges. Bind errors already passed scheduling
        and count ONLY under "error" (the reference's result labels are
        disjoint per attempt)."""
        m = self.metrics
        m.schedule_attempts.inc(res.scheduled, result=m.SCHEDULED)
        m.schedule_attempts.inc(
            max(res.unschedulable - res.bind_errors, 0), result=m.UNSCHEDULABLE
        )
        m.schedule_attempts.inc(res.bind_errors, result=m.ERROR)
        # e2e latency is PER POD create-to-bind (the reference's
        # scheduleOne observes once per pod): every bound pod's
        # queue-add -> bind delta lands in the histogram. Cycles that
        # attempted but bound nothing keep the legacy cycle-elapsed
        # observation so failure latency stays visible. The fallback is
        # gated on res.attempted ALONE: off-cycle callers (the parked
        # ambiguous-bind verifier, the stopped-leading Permit drain)
        # hand in a fresh CycleResult whose elapsed_s was never stamped,
        # and their unschedulable/bind_errors counts must not emit a
        # bogus near-zero e2e sample.
        if res.e2e_latency_s:
            for v in res.e2e_latency_s.values():
                m.e2e_scheduling_duration.observe(v)
        elif res.attempted:
            m.e2e_scheduling_duration.observe(res.elapsed_s)
        if res.attempted or res.scheduled or res.unschedulable:
            m.scheduling_duration.observe(solve_s, operation="scheduling_algorithm")
        # pending_pods gauge freshness is the QUEUE's job (set in one
        # place per mutation — _sync_gauges); the cycle-boundary call
        # here only covers queue fakes without the metrics plumbing
        sync = getattr(self.queue, "_sync_gauges", None)
        if sync is not None:
            sync()
        else:
            for q, depth in self.queue.pending_counts().items():
                m.pending_pods.set(depth, queue=q)

    def _explain_retire_if_drained(self) -> None:
        """An idle cycle popped nothing: when every pod the last report
        analyzed has since left (bind and delete both drop their
        why_pending rows), the cluster summary and the reason gauges
        would otherwise keep reporting them forever — retire the report
        and zero the gauges. Pods merely parked in backoff/unschedulable
        still hold why_pending rows, so their analysis stays visible
        between retries."""
        if not getattr(self.obs.config, "explain", True):
            return
        if self.why_pending or self.last_explain is None:
            return
        if not (self.last_explain.pods
                or self.last_explain.reason_node_counts):
            return
        from kubernetes_tpu.obs.explain import UnschedulableReport

        self.last_explain = UnschedulableReport(
            cycle=self.queue.scheduling_cycle,
            n_nodes=self.last_explain.n_nodes)
        for reason in self._explain_reasons_seen:
            self.metrics.unschedulable_node_counts.set(0, reason=reason)

    def _build_explain_report(self, cycle, batch, failed_idx, ex_host,
                              n_nodes, res: CycleResult) -> None:
        """Assemble the cycle's UnschedulableReport from the read-back
        explain arrays + the driver-level failure reasons, then fan it
        out: CycleResult, /debug/why state, flight record, metrics."""
        from kubernetes_tpu.obs.explain import PodExplanation, build_report

        top_k = getattr(self.obs.config, "explain_top_k", 3)
        keys = [p.key() for p in batch]
        report = build_report(cycle, n_nodes, keys, failed_idx, ex_host,
                              top_k)
        # pods that failed OUTSIDE the filter pass (prefilter, plugins,
        # gang rollback, volume/permit/bind errors) still get a row
        for key in res.failure_reasons:
            if key not in report.pods:
                report.pods[key] = PodExplanation(key=key)
        now = self.clock()
        for key, pe in report.pods.items():
            pe.reasons = res.failure_reasons.get(key, ())
            pe.message = res.fit_errors.get(key, "")
            pe.attempts = self.queue.backoff_map.attempts(key)
            pod = self.queue.pod(key)
            if pod is not None:
                # the queue stamps queued_at on add (0.0 is a valid
                # fake-clock enqueue time, not "unset")
                pe.queue_residency_s = max(
                    now - getattr(pod, "queued_at", now), 0.0)
        res.explain = report
        self.last_explain = report
        for key, pe in report.pods.items():
            self.why_pending[key] = pe
        self.obs.note_explain(report)
        m = self.metrics
        for reason, npods in report.reason_pods.items():
            m.unschedulable_pods.inc(npods, reason=reason)
        # gauges show THIS cycle's exclusion counts; reasons that fired
        # before but not now drop to zero instead of going stale
        for reason in self._explain_reasons_seen - set(
                report.reason_node_counts):
            m.unschedulable_node_counts.set(0, reason=reason)
        for reason, pairs in report.reason_node_counts.items():
            m.unschedulable_node_counts.set(pairs, reason=reason)
            self._explain_reasons_seen.add(reason)

    # -- degradation ladder ------------------------------------------------

    def _breaker(self, target: str):
        """Lazily create the circuit breaker for a ladder tier or
        extender endpoint, wired to the breaker-state gauge and the
        SchedulerDegraded/SchedulerRecovered events."""
        br = self._breakers.get(target)
        if br is None:
            from functools import partial as _partial

            from kubernetes_tpu.faults import CircuitBreaker

            rc = self.robustness
            br = CircuitBreaker(
                failure_threshold=rc.breaker_failure_threshold,
                open_duration_s=rc.breaker_open_duration_s,
                half_open_probes=rc.breaker_half_open_probes,
                clock=self.clock,
                on_transition=_partial(self._on_breaker_transition, target),
            )
            self._breakers[target] = br
            self.metrics.breaker_state.set(0, target=target)
        return br

    def _on_breaker_transition(self, target: str, old: str, new: str) -> None:
        from kubernetes_tpu.events import (
            REASON_DEGRADED,
            REASON_RECOVERED,
            ObjectRef,
        )
        from kubernetes_tpu.faults import CLOSED, OPEN, STATE_CODE

        self.metrics.breaker_state.set(STATE_CODE[new], target=target)
        self.obs.note_breaker(target, old, new)
        ref = ObjectRef(name=self.scheduler_name, involved_kind="Scheduler")
        if new == OPEN:
            klog.warning("circuit breaker %s: %s -> open (degraded mode)",
                         target, old)
            self.event_sink(
                REASON_DEGRADED, ref,
                f"circuit breaker for {target} opened; "
                "routing around it (degraded mode)",
            )
        elif new == CLOSED and old != CLOSED:
            klog.V(2).info("circuit breaker %s: %s -> closed", target, old)
            self.event_sink(
                REASON_RECOVERED, ref,
                f"circuit breaker for {target} closed; full service restored",
            )

    def _run_tier(self, tier, batch, dp, dn, ds, dt, dv, sv, base_fr,
                  extra_mask, extra_score, skip_prio, no_ports, no_pod_aff,
                  no_spread):
        """One solve attempt on one ladder tier. Returns
        ``((assigned, usage, rounds), dp_used, dn_used)`` — the re-
        pinning tiers (batch-single, batch-cpu) hand back the tables
        they actually solved against, so the fused validator never
        mixes a single-device result with mesh-sharded tables in one
        jitted call. Exceptions propagate to the ladder."""
        from kubernetes_tpu.ops.assign import batch_assign, greedy_assign

        hook = (self.fault_injector.solver_hook
                if self.fault_injector is not None else None)
        if tier == "greedy":
            a, u = greedy_assign(
                dp, dn, ds, self.weights, topo=dt, extra_mask=extra_mask,
                vol=dv, static_vol=sv, enabled_mask=self.pred_mask,
                extra_score=extra_score, skip_priorities=skip_prio,
                no_ports=no_ports, no_pod_affinity=no_pod_aff,
                no_spread=no_spread, fault_hook=hook,
                fault_site="solve:greedy",
            )
            return (a, u, len(batch)), dp, dn
        if tier == "exact":
            out = self._exact_solve(
                dp, dn, ds, dt, base_fr, extra_mask, extra_score
            )
            if hook is not None:
                out = hook("solve:exact", *out, dn.valid.shape[0])
            return out, dp, dn
        if tier in ("batch-single", "batch-cpu"):
            if tier == "batch-single":
                # mesh-ladder rung: the identical solve re-pinned onto
                # ONE device of the mesh — survives a sick collective /
                # wedged shard without leaving the accelerator class
                # (batch-cpu and greedy remain unchanged below it)
                one = (self.mesh.devices.flat[0] if self.mesh is not None
                       else jax.devices()[0])
            else:
                # host-backend fallback: re-pin every input to the local
                # CPU device so the identical solve re-runs
                # off-accelerator (on a CPU-only install this is a clean
                # re-execution — the seam a TPU deployment uses to
                # survive a wedged chip)
                one = jax.local_devices(backend="cpu")[0]

            def put(t):
                return (None if t is None else jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, one), t))

            dp_p, dn_p = put(dp), put(dn)
            out = batch_assign(
                dp_p, dn_p, put(ds), self.weights,
                max_rounds=self.max_rounds, per_node_cap=self.per_node_cap,
                topo=put(dt), extra_mask=put(extra_mask), vol=put(dv),
                static_vol=put(sv), enabled_mask=self.pred_mask,
                extra_score=put(extra_score), use_sinkhorn=False,
                skip_priorities=skip_prio, no_ports=no_ports,
                no_pod_affinity=no_pod_aff, no_spread=no_spread,
                fault_hook=hook, fault_site=f"solve:{tier}",
            )
            return out, dp_p, dn_p
        # sinkhorn convergence telemetry rides the solve as a (2,) device
        # pair (stays on device; obs reads it back once at cycle end)
        want_stats = self.obs.config.sinkhorn_telemetry
        out = batch_assign(
            dp, dn, ds, self.weights,
            max_rounds=self.max_rounds, per_node_cap=self.per_node_cap,
            topo=dt, extra_mask=extra_mask, vol=dv, static_vol=sv,
            enabled_mask=self.pred_mask, extra_score=extra_score,
            use_sinkhorn=(tier == "sinkhorn"), skip_priorities=skip_prio,
            no_ports=no_ports, no_pod_affinity=no_pod_aff,
            no_spread=no_spread, fault_hook=hook,
            fault_site=f"solve:{tier}", stats_out=want_stats,
        )
        if want_stats:
            assigned, usage, rounds, sk_stats = out
            self.obs.note_sinkhorn(sk_stats)
            return (assigned, usage, rounds), dp, dn
        return out, dp, dn

    def _validated_readback(self, tier, out, dp, dn):
        """Validate one tier's result and read it back as ONE d2h
        transfer — the fused solve+validate boundary. The verdict is
        computed ON DEVICE (ops/assign.device_validate: range /
        invalid-node / finiteness / capacity recomputation, never
        trusting the solver's claimed usage) and rides the same readback
        as the assignment and round count, so a healthy cycle's solve
        path syncs exactly once. The host checker
        (ops/assign.validate_solution) remains the trust floor: it takes
        over when the result can't even reach the device (shape),
        whenever ``robustness.host_validate`` forces it, and as the
        parity oracle in tests/test_fused_validate.py.

        Returns ``(assigned_host, usage_dev, rounds_int)`` or raises
        SolverResultInvalid with the same reason vocabulary the host
        checker uses (the verdict gates binding exactly as before)."""
        from kubernetes_tpu.faults import SolverResultInvalid
        from kubernetes_tpu.ops.assign import (
            VALIDATE_REASONS,
            device_validate,
            validate_solution,
        )

        rc = self.robustness
        a_dev, u_dev, rounds = out
        dv_out = None
        if rc.validate_results and not rc.host_validate:
            with self.obs.span("validate"):
                dv_out = device_validate(a_dev, u_dev, dp, dn,
                                         self.pred_mask)
                if dv_out is None:
                    # not array-shaped enough to reach the device: the
                    # host checker renders the verdict (shape/dtype)
                    ok, why = validate_solution(a_dev, u_dev, dp, dn,
                                                self.pred_mask)
                    if not ok:
                        self.metrics.solver_rejections.inc(
                            tier=tier, reason=why)
                        raise SolverResultInvalid(f"{tier}: {why}")
        elif rc.validate_results:
            with self.obs.span("validate"):
                ok, why = validate_solution(a_dev, u_dev, dp, dn,
                                            self.pred_mask)
                if not ok:
                    self.metrics.solver_rejections.inc(tier=tier,
                                                       reason=why)
                    raise SolverResultInvalid(f"{tier}: {why}")
        payload = {"assigned": a_dev, "rounds": rounds}
        if dv_out is not None:
            payload["code"], payload["valid"] = dv_out
        host = self.obs.jax.readback("solve-result", payload)
        code = int(host.get("code", 0))
        if code:
            why = VALIDATE_REASONS[code]
            self.metrics.solver_rejections.inc(tier=tier, reason=why)
            raise SolverResultInvalid(f"{tier}: {why}")
        # device_get already materialized host["assigned"] as numpy
        return host["assigned"], u_dev, int(host["rounds"])

    def _solve_ladder(self, solver, batch, dp, dn, ds, dt, dv, sv, base_fr,
                      extra_mask, extra_score, skip_prio, no_ports,
                      no_pod_aff, no_spread, res):
        """The degradation ladder: try the configured solver tier, then
        each tier of ``robustness.fallback_chain`` (TPU batch → CPU-JAX
        batch → the greedy sequential oracle), with per-tier circuit
        breakers, bounded in-cycle retries, deadline-aware skip-to-oracle,
        and result validation so a lying solver can never bind an
        infeasible pod. Returns (assigned_host, usage, rounds, tier) —
        the assignment ALREADY read back (one fused d2h transfer, see
        :meth:`_validated_readback`) — or None when every tier failed
        (the caller requeues the whole batch)."""
        from kubernetes_tpu.faults import SolverResultInvalid

        rc = self.robustness
        tiers = [solver]
        if self._mesh_live and solver in ("batch", "sinkhorn", "greedy"):
            # the mesh-aware rung: a failing SHARDED solve retries on
            # one device (same backend, inputs re-pinned off the mesh)
            # before the ladder leaves the accelerator entirely —
            # sharded -> single-device -> batch-cpu -> greedy
            tiers.append("batch-single")
        for t in rc.fallback_chain:
            if t not in tiers:
                tiers.append(t)
        if "greedy" in tiers:
            # the sequential oracle is the trust floor — nothing below it
            tiers = tiers[: tiers.index("greedy") + 1]
        terminal = tiers[-1]
        m = self.metrics
        deadline = self._cycle_deadline
        deadline_counted = False

        i = 0
        while i < len(tiers):
            tier = tiers[i]
            if (deadline is not None and tier != terminal
                    and self.clock() >= deadline):
                # budget blown: no time for intermediate tiers — jump to
                # the oracle floor so the cycle still makes progress
                if not deadline_counted:
                    m.deadline_exceeded.inc()
                    self.obs.note_deadline_exceeded()
                    deadline_counted = True
                m.solver_fallbacks.inc(from_tier=tier, to_tier=terminal)
                res.solver_fallbacks += 1
                i = len(tiers) - 1
                continue
            br = self._breaker(f"solver:{tier}")
            if not br.allow() and i + 1 < len(tiers):
                # open breaker sheds the tier without burning latency;
                # the terminal tier is always attempted regardless
                m.solver_fallbacks.inc(from_tier=tier, to_tier=tiers[i + 1])
                res.solver_fallbacks += 1
                i += 1
                continue
            attempts = 1 + max(0, rc.solver_retries)
            result = last_err = None
            for attempt in range(attempts):
                ts = self.clock()
                with self.obs.span(f"solve:{tier}", attempt=attempt):
                    try:
                        out, dp_t, dn_t = self._run_tier(
                            tier, batch, dp, dn, ds, dt, dv, sv, base_fr,
                            extra_mask, extra_score, skip_prio, no_ports,
                            no_pod_aff, no_spread,
                        )
                        # fused validate + single readback (raises
                        # SolverResultInvalid on a lying solver, exactly
                        # as the host checker did) — against the tables
                        # THIS tier solved on (a re-pinning tier's
                        # result must not meet mesh-sharded tables)
                        result = self._validated_readback(tier, out,
                                                          dp_t, dn_t)
                    except Exception as e:
                        last_err = e
                    finally:
                        m.solver_tier_duration.observe(
                            self.clock() - ts, tier=tier)
                if result is not None:
                    break
                if attempt + 1 < attempts and not (
                        deadline is not None and self.clock() >= deadline):
                    m.solver_retries.inc(tier=tier)
                    self.obs.note_retry()
                    continue
                break
            if result is not None:
                br.record_success()
                usage = result[1]
                if self._mesh_live and tier in ("batch-single",
                                                "batch-cpu"):
                    # a re-pinned tier's usage lives on one device; the
                    # cycle's failure-reason pass recombines it with the
                    # SHARDED node table — re-place it onto the mesh
                    from kubernetes_tpu.parallel.mesh import shard_usage

                    usage = shard_usage(usage, self.mesh)
                return result[0], usage, int(result[2]), tier
            br.record_failure()
            klog.warning("solver tier %s failed (%s); falling back",
                         tier, last_err)
            if i + 1 < len(tiers):
                m.solver_fallbacks.inc(from_tier=tier, to_tier=tiers[i + 1])
                res.solver_fallbacks += 1
            i += 1
        return None

    # graftlint: disable-scope=R2,R7,R8 -- host oracle by design: the exact
    # tier runs the Hungarian solver on CPU, so the one filter+score result
    # is read back wholesale here (a deliberate full gather when the mesh is
    # on); the ladder only enters this tier when quality beats wall-clock
    # (gang/offline packing)
    def _exact_solve(self, dp, dn, ds, dt, base_fr, extra_mask, extra_score):
        """Exact one-shot assignment: device filter+score once, then the
        native Hungarian solver with per-node slot capacities
        (native/ktpu.cc; SURVEY.md §7.2 step 5's exact branch). Maximizes
        the batch's total score instead of auction rounds — for gang /
        offline packing where quality beats wall-clock. Multi-resource
        feasibility beyond slot counts is validated sequentially in queue
        order; in-batch coupling of ports/volumes/topology is NOT modeled
        here (use the round solver for such workloads)."""
        from kubernetes_tpu import native
        from kubernetes_tpu.ops.assign import _apply_batch, usage_from_nodes
        from kubernetes_tpu.ops.predicates import BIT
        from kubernetes_tpu.snapshot import RES_PODS

        mask = np.asarray(base_fr.mask)
        if extra_mask is not None:
            mask = mask & np.asarray(extra_mask)
        wkey = (
            tuple(sorted(self.weights.items()))
            if self.weights is not None
            else None
        )
        score = np.asarray(_score_pass(dp, dn, ds, dt, jnp.asarray(mask), wkey))
        if extra_score is not None:
            score = score + np.asarray(extra_score)

        alloc = np.asarray(dn.allocatable)
        node_valid = np.asarray(dn.valid)
        valid = np.asarray(dp.valid)
        preq = np.asarray(dp.req)
        order = np.lexsort((np.asarray(dp.order), -np.asarray(dp.priority)))

        # assign -> validate rounds: the slot capacity only encodes the pod
        # count; multi-resource feasibility is enforced by sequential
        # validation, and rejected pods re-solve against the updated usage
        # until a fixpoint (usually 1-2 rounds)
        # a Policy bypassing PodFitsResources also bypasses the resource
        # gating here (mirrors the batch solver's admission-guard bypass)
        res_on = self.pred_mask is None or bool(
            self.pred_mask & (1 << BIT["PodFitsResources"])
        )
        P = mask.shape[0]
        assigned_final = np.full((P,), -1, np.int32)
        used = np.asarray(dn.requested).copy()
        active = valid.copy()
        rounds = 0
        for _ in range(16):
            if not active.any():
                break
            rounds += 1
            fit = np.all(
                used[None, :, :] + preq[:, None, :] <= alloc[None, :, :] + 1e-6,
                axis=2,
            ) if res_on else np.ones((P, alloc.shape[0]), bool)
            # slot capacity: the pod-count column is exact; the other
            # resource columns bound the count via the SMALLEST active
            # request (an upper bound — still validated below — that keeps
            # the Hungarian from piling far more pods on a node than any
            # resource could admit, which would burn a round per few pods)
            free = np.maximum(alloc - used, 0.0)  # (N, R)
            min_req = np.where(
                active[:, None], preq, np.inf
            ).min(axis=0)  # (R,) smallest request per resource
            with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
                per_res = np.where(
                    min_req > 0, np.floor(free / np.maximum(min_req, 1e-30)), np.inf
                )
            cap = np.where(
                node_valid, np.nanmin(per_res, axis=1), 0
            )
            cap = np.where(np.isfinite(cap), cap, free[:, RES_PODS]).astype(np.int64)
            if not res_on:
                cap = np.where(node_valid, P, 0).astype(np.int64)
            m = mask & fit & active[:, None]
            a = native.exact_assign(score, m, cap)
            progress = False
            for p in order:
                if not active[p] or a[p] < 0:
                    continue
                t = a[p]
                if not res_on or np.all(used[t] + preq[p] <= alloc[t] + 1e-6):
                    used[t] += preq[p]
                    assigned_final[p] = t
                    active[p] = False
                    progress = True
            if not progress:
                break
        acc = jnp.asarray(assigned_final >= 0) & dp.valid
        usage = _apply_batch(
            usage_from_nodes(dn), dp,
            jnp.asarray(np.maximum(assigned_final, 0)), acc,
        )
        return jnp.asarray(assigned_final), usage, rounds

    # -- pipelined cycle executor ------------------------------------------

    def _pipeline_eligible(self, batch, nominated) -> bool:
        """The pipelined executor covers the clean high-throughput path:
        features that need whole-batch host coupling (extenders, host/
        batch plugins, gang groups, nominated-pod pass A, node-search
        truncation) or a host-resident solver (exact) keep the monolithic
        cycle. Depth 1 is the explicit off switch — today's behavior."""
        if self.pipeline_depth < 2 or self.pipeline_chunk < 1:
            return False
        if len(batch) <= self.pipeline_chunk:
            return False
        if self.solver not in ("batch", "sinkhorn", "greedy"):
            return False
        if self.extenders or nominated:
            return False
        fw = self.framework
        if (fw.has_host_filters() or fw.has_host_scores()
                or fw.has_batch_filters() or fw.has_batch_scores()):
            return False
        if self.percentage_of_nodes_to_score is not None:
            return False
        if self.scenario_pack is not None and (
                not self.scenario_pack.restricted_ok
                or self.scenario.quality):
            # capability-driven (mirrors _incremental_eligible): a
            # restricted_ok pack's cost term is per-column, so it
            # evaluates per CHUNK bit-for-bit and rides the pipeline.
            # The quality reduction is the remaining whole-batch
            # coupling — it wants the final monolithic usage — so
            # quality-on scenario cycles keep the monolithic executor,
            # as does any pack needing global cross-column structure.
            return False
        if any(p.pod_group for p in batch):
            # gangs stay monolithic: all-or-nothing groups straddling
            # chunk boundaries would need cross-chunk rollback
            return False
        return True

    # -- incremental solve (restricted candidate-column cycles) ------------

    def _drop_incremental(self, reason: str) -> None:
        """One invalidation edge for ALL warm-solve state: the cached
        score plane drops (rebuilt lazily from the resident table) and
        the Sinkhorn potential carry dies with the generation bump. The
        next cycle solves cold. Reasons: takeover | device-loss |
        dirty-frac | full-snapshot (epoch/interner growth and node-set
        changes all surface as full snapshot rebuilds).

        Counted only when warm state actually EXISTED to drop (the
        cache held a summary, a potential carry was live, or restricted
        service had engaged) — a scheduler whose every cycle takes full
        uploads must not mint one phantom invalidation per cycle."""
        has = getattr(self.cache, "has_score_summary", None)
        had = (self._incr_active or self._sk_warm_pot is not None
               or bool(has() if has is not None else False))
        self._sk_warm_pot = None
        # release the scheduler-side ledger registrations (the warm
        # potential carry AND the last pod-batch upload): on a
        # device-loss edge those arrays are gone; on the other edges
        # the next cycle re-registers what it re-uploads
        self.obs.memledger.deregister_prefix("scheduler.")
        self._incr_active = False
        drop = getattr(self.cache, "drop_score_summary", None)
        if drop is not None and (has is None or has()):
            # drop only a LIVE summary: the takeover/device-loss paths
            # arrive after drop_device_snapshot already cleared it (and
            # bumped the generation) — a second bump would be noise
            drop()
        if had and self.incremental.enabled:
            m = getattr(self.metrics, "incremental_invalidations", None)
            if m is not None:
                m.inc(reason=reason)

    def _note_tuner_batch(self, raw: int) -> None:
        """Feed one observed raw micro-batch size into the candidate
        auto-tuner's sliding window (last 64 cycles)."""
        self._tuner_batch_obs.append(raw)
        if len(self._tuner_batch_obs) > 64:
            del self._tuner_batch_obs[:-64]

    def _candidate_bucket(self, n_pad: int) -> int:
        """The restricted solve's candidate-column bucket: the config
        value snapped UP to a power of two so the (P, C) solve shapes
        stay inside the warmed grid.

        With ``incremental.autoTune`` on AND a warmed C ladder, the
        bucket is instead READ from observed telemetry: the smallest
        warmed C that (a) admits the recent micro-batch sizes under
        maxBatchFrac and (b) leaves 2x headroom over the deepest
        candidate-frame position restricted solves actually placed
        into (pods landing deep in the frame means the rank order is
        being fought — widen before under-placement starts declining
        cycles). Every ladder rung was compiled (and its signatures
        pre-registered) by _warm_incremental, so a tuner move NEVER
        retraces; without a warmed ladder the tuner stays pinned to
        the configured bucket."""
        inc = self.incremental
        c0 = bucket_size(max(inc.candidate_bucket, 1))
        if not inc.auto_tune or not self._warmed_cbuckets:
            return c0
        need = max(
            max(self._tuner_batch_obs, default=1)
            / max(inc.max_batch_frac, 1e-6),
            2 * self._tuner_depth_max,
            1,
        )
        ladder = sorted(self._warmed_cbuckets)
        for c in ladder:
            if c >= need:
                return c
        return ladder[-1]

    def _incremental_eligible(self, batch, nominated, dn, dt, dv,
                              snap_mode, no_ports, no_pod_aff, no_spread,
                              nt) -> bool:
        """May THIS cycle take the restricted solve? The gates mirror
        the fused lean route's trace-time facts (whole-batch host
        coupling and cross-node constraint classes need the full plane)
        plus the incremental-specific ones: a live resident snapshot in
        clean/delta mode (a full rebuild recomputed the whole score
        plane — nothing to reuse), a micro-batch small enough for the
        candidate bucket, and a dirty frontier under the blowout
        threshold. Ineligible cycles take the cold solve; blowouts also
        drop the cache (the documented invalidation edges)."""
        inc = self.incremental
        if not inc.enabled:
            return False
        if self.solver not in ("batch", "sinkhorn"):
            return False
        if snap_mode == "full":
            # the whole plane was just recomputed (node-set change,
            # interner/pack-epoch growth, explicit invalidation, dirty
            # blowout at the snapshot layer) — warm state is dead
            self._drop_incremental("full-snapshot")
            return False
        if snap_mode not in ("clean", "delta") or dn is None:
            return False
        if self.extenders or nominated:
            return False
        fw = self.framework
        if (fw.has_host_filters() or fw.has_host_scores()
                or fw.has_batch_filters() or fw.has_batch_scores()):
            return False
        if self.percentage_of_nodes_to_score is not None:
            return False
        if (self.scenario_pack is not None
                and not self.scenario_pack.restricted_ok):
            # capability-driven, not blanket: a pack whose cost term is
            # per-column (restricted_ok — it survives restriction to a
            # gathered (P, C) frame bit-for-bit) rides the restricted
            # path, its cost joining the frame's extra_score and its
            # candidate_hint reserving quota columns; packs that need
            # global cross-column structure keep the dense oracle
            return False
        # gangs RIDE the restricted path (their members' candidates
        # union in the frame; the gang-topology pack's hint reserves
        # home-slice columns): _restricted_tail re-checks all-or-
        # nothing after the solve and declines to the dense ladder —
        # which owns rollback + failure analytics — on any incomplete
        # group. No blanket exclusion.
        # constraint classes that couple across the FULL node axis:
        # ports/volumes couple in-batch per node (excluded outright);
        # topology masks reduce over whole topology groups — only safe
        # to drop when the batch-scoped gates prove them vacuous
        if dv is not None or not no_ports:
            return False
        if dt is not None and not (no_pod_aff and no_spread):
            return False
        # the tuner observes the RAW batch size BEFORE the C compare:
        # batches bounced for being too big are exactly the evidence
        # that should widen the bucket next cycle
        self._note_tuner_batch(len(batch))
        n_pad = dn.valid.shape[0]
        C = self._candidate_bucket(n_pad)
        if C >= n_pad:
            return False  # restriction would not shrink the plane
        if len(batch) > inc.max_batch_frac * C:
            return False
        dirty = len(getattr(self.cache, "last_patched_idx", ()))
        if dirty > inc.max_dirty_frac * max(nt.n, 1):
            self._drop_incremental("dirty-frac")
            return False
        return True

    def _restricted_tail(self, batch, cycle, res, t0, trace, nt, dn, ds,
                         dp, node_order, skip_prio):
        """The incremental cycle's solve + bind tail: pick candidate
        columns from the cached score plane (O(N log C) — the only
        full-N work), gather them into a (P, C) view, solve with the
        stock kernels, validate on device, and read back ONE global
        assignment vector (the candidate index list never crosses —
        d2h stays at the answer-sized budget). The result is accepted
        only when EVERY pod placed: an under-placed batch (a pod may be
        feasible on a non-candidate column, and the failure analytics
        need the full plane) and any solve/validation error return None
        so the caller re-solves cold — the PR-1 ladder's correctness
        fallback, unchanged."""
        from kubernetes_tpu.faults import SolverResultInvalid
        from kubernetes_tpu.ops.arrays import (
            gather_candidates,
            map_restricted_assignment,
        )
        from kubernetes_tpu.ops.assign import (
            VALIDATE_REASONS,
            batch_assign,
            device_validate,
            validate_solution,
        )

        inc = self.incremental
        # gang minMember pre-check (host ints only): a group that cannot
        # meet its quorum even counting cache-placed members will be
        # rolled back whoever solves it — decline NOW so the dense
        # ladder produces the proper per-pod GangIncomplete analytics
        # instead of burning a restricted solve first
        gang_need: Dict[str, List[int]] = {}
        for gp in batch:
            if gp.pod_group:
                g = gang_need.setdefault(gp.pod_group, [0, 0])
                g[0] += 1
                g[1] = max(g[1], gp.pod_group_min_available)
        for gname, (cnt, need) in gang_need.items():
            if cnt + self.cache.group_members(gname) < need:
                m = getattr(self.metrics, "incremental_cycles", None)
                if m is not None:
                    m.inc(scope="declined")
                return None
        summary = None
        get_summary = getattr(self.cache, "score_summary", None)
        if get_summary is not None:
            summary = get_summary()
        if summary is None:
            return None
        n_pad = dn.valid.shape[0]
        C = self._candidate_bucket(n_pad)
        idxs = [int(i) for i in getattr(self.cache, "last_patched_idx",
                                        ())]
        dirty = np.zeros((n_pad,), bool)
        if idxs:
            dirty[idxs] = True  # host ints from the cache's delta ledger
        # a post-drop lazy rebuild recomputed the WHOLE plane this
        # cycle — honest reuse is zero, not 1 - dirty/live
        if getattr(self.cache, "last_summary_rebuilt", False):
            reuse = 0.0
        else:
            reuse = max(0.0, 1.0 - len(idxs) / max(nt.n, 1))
        rc = self.robustness
        use_sk = self.solver == "sinkhorn"
        want_stats = bool(self.obs.config.sinkhorn_telemetry and use_sk)
        warm = bool(inc.warm_potentials and use_sk)
        gen = getattr(self.cache, "summary_generation", 0)
        pot_key = (dp.valid.shape[0], C, gen)
        sk_init = None
        if warm and self._sk_warm_pot is not None \
                and self._sk_warm_pot[0] == pot_key:
            sk_init = self._sk_warm_pot[1]
        hook = (self.fault_injector.solver_hook
                if self.fault_injector is not None else None)
        # mesh-sharded candidate pick: per-shard local top-C over the
        # node-sharded resident plane, replicated merge of the (S, C)
        # winners — bit-identical to the single-pass pick (the parity
        # suite pins it across mesh {1, 2, 4, 8})
        ns = int(self.mesh.devices.size) if self._mesh_live else 1
        # group-quota hint: the pack's candidate columns (a gang's home
        # slice) get a RESERVED split of the frame, capped at
        # groupQuotaFrac so a whole hinted zone can never crowd the
        # plain-ranked candidates out
        hint = hq = None
        if self.scenario_pack is not None:
            hm = self.scenario_pack.candidate_hint(batch, nt, node_order)
            if hm is not None:
                h = np.zeros((n_pad,), bool)
                h[: hm.shape[0]] = hm
                hint = jnp.asarray(h)
                hq = max(int(inc.group_quota_frac * C), 1)
        # retrace telemetry: the candidate/gather program and the
        # restricted solve program are distinct compiled sites — both
        # registered so the zero-retrace contract covers them
        self.obs.jax.record_call(
            "incremental", summary.rank,
            static=(C, n_pad, self._mesh_live, ns, hint is None, hq))
        try:
            with self.obs.span("solve:restricted"):
                cand, sub_dn = gather_candidates(
                    summary, jnp.asarray(dirty), dn, C, hint_mask=hint,
                    num_shards=ns, hint_quota=(hq or 0))
                # restricted_ok pack cost on the GATHERED frame: the
                # term is per-column by the capability contract, so
                # cost over sub_dn equals the dense term restricted to
                # the candidate columns — the objective survives the
                # sparsity-first route unchanged
                extra_score = None
                if self.scenario_pack is not None:
                    with self.obs.span("scenario:cost"):
                        extra_score = self.scenario_pack.cost(
                            batch, nt, node_order, dp, sub_dn)
                self.obs.jax.record_call(
                    "solve", dp, sub_dn, ds,
                    static=("restricted", self.solver, tuple(skip_prio),
                            self.pred_mask, self.per_node_cap,
                            self.max_rounds, sk_init is None,
                            extra_score is None, self._mesh_live),
                )
                out = batch_assign(
                    dp, sub_dn, ds, self.weights,
                    max_rounds=self.max_rounds,
                    per_node_cap=self.per_node_cap,
                    enabled_mask=self.pred_mask, use_sinkhorn=use_sk,
                    extra_score=extra_score,
                    skip_priorities=skip_prio, no_ports=True,
                    no_pod_affinity=True, no_spread=True,
                    fault_hook=hook, fault_site="solve:restricted",
                    stats_out=want_stats,
                    sk_init=sk_init,
                    sk_tol=(inc.warm_tol if warm else None),
                    potentials_out=warm,
                )
                a_local, u_local, rounds = out[0], out[1], out[2]
                k = 3
                if want_stats:
                    self.obs.note_sinkhorn(out[k])
                    k += 1
                potentials = out[k] if warm else None
                # placement-rank telemetry: the deepest candidate-frame
                # position any pod placed into, reduced to ONE device
                # scalar riding the existing solve-result readback (a
                # (P,) position vector would cost +4 B/pod and breach
                # the answer-sized budget). The auto-tuner reads it to
                # decide when the frame is running hot.
                payload = {"rounds": rounds,
                           "depth": jnp.max(jnp.where(
                               dp.valid & (a_local >= 0), a_local,
                               jnp.int32(-1)))}
                dv_out = None
                if rc.validate_results and not rc.host_validate:
                    with self.obs.span("validate"):
                        dv_out = device_validate(a_local, u_local, dp,
                                                 sub_dn, self.pred_mask)
                    if dv_out is not None:
                        payload["code"], payload["valid"] = dv_out
                if rc.validate_results and dv_out is None:
                    # host trust floor (host_validate / unshippable
                    # result): same checker, candidate-local frame
                    ok, why = validate_solution(a_local, u_local, dp,
                                                sub_dn, self.pred_mask)
                    if not ok:
                        raise SolverResultInvalid(f"restricted: {why}")
                payload["assigned"] = map_restricted_assignment(
                    a_local, cand)
                host = self.obs.jax.readback("solve-result", payload)
                code = int(host.get("code", 0))
                if code:
                    raise SolverResultInvalid(
                        f"restricted: {VALIDATE_REASONS[code]}")
                assigned = host["assigned"]
        except Exception as e:
            # ANY restricted failure — a lying solver, a device error,
            # a validation verdict — declines the attempt; the caller
            # re-solves cold through the full ladder (which owns the
            # breaker/retry/fallback machinery)
            klog.warning("restricted solve declined (%s); cold solve", e)
            self._drop_incremental("restricted-error")
            m = getattr(self.metrics, "incremental_cycles", None)
            if m is not None:
                m.inc(scope="declined")
            return None
        self._tuner_depth_max = max(self._tuner_depth_max,
                                    int(host.get("depth", -1)) + 1)
        placed = assigned[: len(batch)]
        if (placed < 0).any():
            # a pod the candidate set could not place might fit on a
            # non-candidate column — only the cold solve can say (and
            # produce the failure analytics / preemption inputs). For a
            # gang member this is ALSO the all-or-nothing edge: the
            # dense re-solve owns the rollback + GangIncomplete
            # analytics, so one decline covers both contracts.
            m = getattr(self.metrics, "incremental_cycles", None)
            if m is not None:
                m.inc(scope="under-placed")
            return None
        # ledger coverage for the cycle's candidate-frame residents:
        # the (C, ·) gathered sub-table + the (C,) index map (top-k
        # temporaries are XLA-internal — the warmup memory_analysis
        # capture accounts those). Re-registered per restricted cycle
        # (same name = overwrite); the scheduler. prefix dies on every
        # invalidation edge with the rest of the warm state.
        self.obs.memledger.register_tree(
            "scheduler.candidate_frame", sub_dn, cand,
            shape=f"C{C}of{n_pad}")
        if warm and potentials is not None:
            self._sk_warm_pot = (pot_key, potentials)
            # the carry is device-resident state: on the ledger until
            # the next invalidation edge (_drop_incremental)
            self.obs.memledger.register_tree(
                "scheduler.sk_warm_potentials", potentials,
                shape=f"P{pot_key[0]}xC{pot_key[1]}")
        self._incr_active = True
        # scenario quality on the restricted route: the reduction runs
        # over the CANDIDATE FRAME (every placement lands inside it, so
        # nodes_used/gang stats are exact; the capacity-shaped scores
        # are frame-local — docs/scenarios.md). Dispatched now so the
        # device works while the host binds, read back after.
        q_dev = None
        if self.scenario_pack is not None and self.scenario.quality:
            from kubernetes_tpu.ops.scenario_cost import quality_reduce

            q_dev = quality_reduce(a_local.astype(jnp.int32),
                                   u_local.requested, dp, sub_dn)
        res.rounds = int(host["rounds"])
        res.solver_tier = self.solver
        res.solve_scope = "restricted"
        res.reuse_frac = round(reuse, 4)
        solve_s = trace.total_s()
        trace.step(f"restricted solve done ({res.rounds} rounds, "
                   f"C={C}, reuse={reuse:.3f})")
        self.metrics.algorithm_duration.observe(solve_s)
        bind_span = trace.begin_span("bind")
        for i, pod in enumerate(batch):
            self._admit_pod(pod, node_order[int(placed[i])], cycle, res)
        trace.end_span(bind_span)
        trace.step(f"bound {res.scheduled}, failed {res.unschedulable}")
        if q_dev is not None:
            qvec = self.obs.jax.readback("scenario-quality", q_dev)
            from kubernetes_tpu.scenarios.quality import decode_quality

            quality = decode_quality(qvec)
            quality.update(
                self.scenario_pack.quality_host(batch, assigned, nt))
            res.scenario_quality = quality
            self._publish_scenario_quality(quality)
        if getattr(self.obs.config, "explain", True):
            # no filter-pass failures by construction (everything
            # placed), but admission-tail failures still get report
            # rows and the reason gauges roll over to this cycle
            self._build_explain_report(cycle, batch, [], None, nt.n, res)
        return self._finish_cycle(res, cycle, t0, solve_s, trace,
                                  label=" (restricted)")

    def _cold_blocks(self, n_pad: int, C: int) -> int:
        """How many capacity-balanced blocks the partitioned cold solve
        runs: ``incremental.coldBlocks``, or (0 = auto) the padded node
        bucket over the candidate bucket capped at 8 — wide enough that
        B·C covers thousands of columns at 50k nodes, bounded so cold
        latency stays a handful of fixed-size solves. Always clamped so
        B·C fits the table (the top-(B·C) pick must be a real cut)."""
        inc = self.incremental
        b = inc.cold_blocks or min(8, n_pad // max(C, 1))
        return max(min(b, n_pad // max(C, 1)), 0)

    def _partitioned_cold_eligible(self, batch, nominated, dn, dt, dv,
                                   no_ports, no_pod_aff,
                                   no_spread) -> bool:
        """May THIS cycle take the PARTITIONED cold solve (sparsity-
        first primary mode)? Engages when the restricted warm route did
        not take the cycle — a full-snapshot rebuild, an oversized
        batch, a declined/under-placed restricted attempt — and the
        same trace-time facts hold that make a candidate frame
        complete: no whole-batch host coupling, no cross-node
        constraint classes, no gangs or scenario packs (both keep the
        dense oracle's monolithic cold semantics — gang rollback and
        pack quality want the full plane when solving cold). The dense
        solve remains the correctness fallback: a partitioned attempt
        that cannot place its whole batch declines rather than binding
        a partial answer."""
        inc = self.incremental
        if not (inc.enabled and inc.primary):
            return False
        if self.solver not in ("batch", "sinkhorn"):
            return False
        if dn is None or not batch:
            return False
        if self.extenders or nominated:
            return False
        fw = self.framework
        if (fw.has_host_filters() or fw.has_host_scores()
                or fw.has_batch_filters() or fw.has_batch_scores()):
            return False
        if self.percentage_of_nodes_to_score is not None:
            return False
        if self.scenario_pack is not None:
            return False
        if any(p.pod_group for p in batch):
            return False
        if dv is not None or not no_ports:
            return False
        if dt is not None and not (no_pod_aff and no_spread):
            return False
        n_pad = dn.valid.shape[0]
        C = self._candidate_bucket(n_pad)
        return C < n_pad and self._cold_blocks(n_pad, C) >= 2

    def _partitioned_cold_tail(self, batch, cycle, res, t0, trace, nt,
                               dn, ds, dp, node_order, skip_prio):
        """The partitioned cold solve: rank every column once (the only
        full-N work), deal the top B·C columns round-robin into B
        capacity-balanced blocks of WIDTH C — the restricted path's
        candidate bucket, so every block runs the ALREADY-COMPILED
        (P, C) restricted program and a cold cycle adds zero new solver
        shapes — then solve the blocks in sequence, masking placed pods
        out of each next block's pod validity (blocks are column-
        disjoint, so no cross-block usage updates exist to miss).
        Unplaced remainder takes ONE final restricted pass over a fresh
        top-C pick from the usage-overlaid table (earlier placements
        debited). Placements accumulate HOST-side and bind only when
        the WHOLE batch placed; anything less declines to the dense
        ladder, which owns failure analytics and preemption. Cold cost:
        O(N log(B·C)) selection + (≤ B + 1) fixed (P, C) solves —
        sublinear in N, vs the dense plane's O(P·N)."""
        from kubernetes_tpu.faults import SolverResultInvalid
        from kubernetes_tpu.ops.arrays import (
            gather_candidates,
            gather_node_rows,
            map_restricted_assignment,
        )
        from kubernetes_tpu.ops.assign import (
            VALIDATE_REASONS,
            _apply_batch,
            batch_assign,
            device_validate,
            nodes_with_usage,
            usage_from_nodes,
        )
        from kubernetes_tpu.ops.fused_score import (
            node_summary,
            partition_columns,
        )

        inc = self.incremental
        n_pad = dn.valid.shape[0]
        P_pad = dp.valid.shape[0]
        C = self._candidate_bucket(n_pad)
        B = self._cold_blocks(n_pad, C)
        ns = int(self.mesh.devices.size) if self._mesh_live else 1
        flags = self._summary_flags
        get_summary = getattr(self.cache, "score_summary", None)
        summary = get_summary() if get_summary is not None else None
        if summary is None:
            # no live cache (full rebuild just invalidated it): one
            # fresh O(N) summary pass — still nothing (P, N)-shaped
            summary = node_summary(dn, **flags)
        rc = self.robustness
        use_sk = self.solver == "sinkhorn"
        warm = bool(inc.warm_potentials and use_sk)
        want_stats = bool(self.obs.config.sinkhorn_telemetry and use_sk)
        hook = (self.fault_injector.solver_hook
                if self.fault_injector is not None else None)
        solve_statics = ("restricted", self.solver, tuple(skip_prio),
                         self.pred_mask, self.per_node_cap,
                         self.max_rounds, True, True, self._mesh_live)
        self.obs.jax.record_call(
            "partition", summary.rank,
            static=(B, C, n_pad, ns, self._mesh_live))
        pending = np.zeros((P_pad,), bool)
        pending[: len(batch)] = True
        assigned = np.full((len(batch),), -1, np.int64)
        zeros_dirty = jnp.zeros((n_pad,), bool)

        def solve_frame(dp_f, sub_dn, cand, site):
            """One (P, C) frame solve + validate + global mapping; ONE
            readback per frame (the declared cold-block boundary)."""
            self.obs.jax.record_call("solve", dp_f, sub_dn, ds,
                                     static=solve_statics)
            out = batch_assign(
                dp_f, sub_dn, ds, self.weights,
                max_rounds=self.max_rounds,
                per_node_cap=self.per_node_cap,
                enabled_mask=self.pred_mask, use_sinkhorn=use_sk,
                skip_priorities=skip_prio, no_ports=True,
                no_pod_affinity=True, no_spread=True,
                fault_hook=hook, fault_site="solve:partitioned",
                stats_out=want_stats,
                sk_tol=(inc.warm_tol if warm else None),
                potentials_out=warm,
            )
            a_local, u_local, rounds = out[0], out[1], out[2]
            k = 3
            if want_stats:
                self.obs.note_sinkhorn(out[k])
            payload = {"rounds": rounds}
            if rc.validate_results and not rc.host_validate:
                dv_out = device_validate(a_local, u_local, dp_f, sub_dn,
                                         self.pred_mask)
                if dv_out is not None:
                    payload["code"], payload["valid"] = dv_out
            payload["assigned"] = map_restricted_assignment(a_local,
                                                            cand)
            host = self.obs.jax.readback(site, payload)
            code = int(host.get("code", 0))
            if code:
                raise SolverResultInvalid(
                    f"partitioned: {VALIDATE_REASONS[code]}")
            return host

        try:
            with self.obs.span("solve:partitioned", blocks=B):
                blocks = partition_columns(summary, zeros_dirty, B, C,
                                           ns)
                for b in range(B):
                    if not pending[: len(batch)].any():
                        break
                    dp_b = dp._replace(
                        valid=dp.valid & jnp.asarray(pending))
                    sub_dn = gather_node_rows(dn, blocks[b])
                    host = solve_frame(dp_b, sub_dn, blocks[b],
                                       "cold-block")
                    res.rounds += int(host["rounds"])
                    got = host["assigned"]
                    for i in range(len(batch)):
                        if pending[i] and got[i] >= 0:
                            assigned[i] = got[i]
                            pending[i] = False
                if pending[: len(batch)].any():
                    # remainder pass: one fresh top-C frame over the
                    # usage-OVERLAID table (every block placement
                    # debited — blocks were column-disjoint, so this is
                    # the first moment cross-block state must meet)
                    acc = np.full((P_pad,), -1, np.int64)
                    acc[: len(batch)] = assigned
                    u = _apply_batch(
                        usage_from_nodes(dn), dp,
                        jnp.asarray(np.maximum(acc, 0)),
                        jnp.asarray(acc >= 0) & dp.valid)
                    dn_u = nodes_with_usage(dn, u)
                    sum_u = node_summary(dn_u, **flags)
                    self.obs.jax.record_call(
                        "incremental", sum_u.rank,
                        static=(C, n_pad, self._mesh_live, ns, True,
                                None))
                    dp_r = dp._replace(
                        valid=dp.valid & jnp.asarray(pending))
                    cand, sub_dn = gather_candidates(
                        sum_u, zeros_dirty, dn_u, C, num_shards=ns)
                    host = solve_frame(dp_r, sub_dn, cand,
                                       "cold-block")
                    res.rounds += int(host["rounds"])
                    got = host["assigned"]
                    for i in range(len(batch)):
                        if pending[i] and got[i] >= 0:
                            assigned[i] = got[i]
                            pending[i] = False
        except Exception as e:
            # any failure — a lying solver, device error, validation
            # verdict — declines the whole attempt; the dense ladder
            # owns breakers/retries/fallbacks (nothing bound yet, so
            # the decline is free of rollback)
            klog.warning("partitioned cold solve declined (%s); dense "
                         "solve", e)
            m = getattr(self.metrics, "incremental_cycles", None)
            if m is not None:
                m.inc(scope="declined")
            return None
        if pending[: len(batch)].any():
            # under-placed: a remainder pod may fit on a column outside
            # every frame — only the dense solve can say, and the
            # failure analytics / preemption inputs need the full plane
            m = getattr(self.metrics, "incremental_cycles", None)
            if m is not None:
                m.inc(scope="under-placed")
            return None
        res.solver_tier = self.solver
        res.solve_scope = "partitioned"
        res.cold_blocks = B
        res.reuse_frac = 0.0
        solve_s = trace.total_s()
        trace.step(f"partitioned cold solve done ({res.rounds} rounds, "
                   f"B={B}, C={C})")
        self.metrics.algorithm_duration.observe(solve_s)
        bind_span = trace.begin_span("bind")
        for i, pod in enumerate(batch):
            self._admit_pod(pod, node_order[int(assigned[i])], cycle,
                            res)
        trace.end_span(bind_span)
        trace.step(f"bound {res.scheduled}, failed {res.unschedulable}")
        if getattr(self.obs.config, "explain", True):
            self._build_explain_report(cycle, batch, [], None, nt.n,
                                       res)
        return self._finish_cycle(res, cycle, t0, solve_s, trace,
                                  label=" (partitioned)")

    def _pipelined_tail(self, batch, cycle, res, t0, trace, nt, dn, ds, dt,
                        node_order, skip_prio, no_ports, no_pod_aff,
                        no_spread) -> CycleResult:
        """Double-buffered pack→solve→readback→bind pipeline over fixed
        sub-batches (SURVEY §7.2 step 9): while chunk k's solve runs on
        device (JAX async dispatch), the host packs chunk k+1 and applies
        chunk k−1's binds. Chunking and the usage-chain data dependencies
        are identical at every depth ≥ 2 — only host scheduling overlaps —
        so placements are depth-invariant by construction (pinned by
        tests/test_pipeline.py). Every chunk pads to ONE bucket, so the
        whole cycle runs a single solver jit signature."""
        import numpy as np

        from kubernetes_tpu.ops.assign import (
            batch_assign,
            greedy_assign,
            nodes_with_usage,
        )
        from kubernetes_tpu.ops.arrays import volumes_to_device
        from kubernetes_tpu.ops.predicates import decode_reasons
        from kubernetes_tpu.snapshot import FIXED_RESOURCE_NAMES

        pk = self.cache.packer
        C = self.pipeline_chunk
        chunks = [batch[i:i + C] for i in range(0, len(batch), C)]
        res.pipeline_chunks = len(chunks)
        self.metrics.pipeline_chunks.inc(len(chunks))
        chunk_pad = bucket_size(C)
        explain_on = getattr(self.obs.config, "explain", True)
        rc = self.robustness
        solver = self.solver
        # a restricted_ok scenario pack's per-column cost term joins
        # each chunk's solve as extra_score (the _pipeline_eligible
        # capability contract); the statics score flag flips with it so
        # warmed/monolithic/pipelined signatures stay coherent
        pack = self.scenario_pack
        statics = (solver, tuple(skip_prio), no_ports, no_pod_aff,
                   no_spread, self.pred_mask, self.per_node_cap,
                   self.max_rounds, True,  # no extra mask
                   pack is None, self._mesh_live)
        hook = (self.fault_injector.solver_hook
                if self.fault_injector is not None else None)

        dn_cur = dn
        solve_s = 0.0
        tier_last = solver
        failed_global: List[int] = []
        reasons_row: Dict[int, Tuple[str, ...]] = {}
        fit_msgs: Dict[int, str] = {}
        rmat_rows: Dict[int, np.ndarray] = {}
        ex_parts: List[Tuple[int, int, dict]] = []  # (offset, n, ex dict)

        def pack_chunk(k):
            with self.obs.span(f"pipeline:pack@{k}", pods=len(chunks[k])):
                dp_c = self._place(pods_to_device(pk.pack_pods(chunks[k]),
                                                  pad_to=chunk_pad))
                dv_c = sv_c = None
                if any(p.volumes for p in chunks[k]):
                    dv_c = self._place(
                        volumes_to_device(pk.pack_volume_tables(chunks[k])))
                    sv_c = _static_vol_pass(dp_c, dn, ds, dv_c)
                # per-chunk h2d accounting: the pod tables are the
                # steady-state cycle's largest upload
                self.obs.jax.record_upload(
                    "snapshot", dp_c,
                    *([dv_c] if dv_c is not None else []))
                return dp_c, dv_c, sv_c

        def dispatch(k, packed, dn_in):
            """Queue chunk k's solve on device (async); returns the
            device triple or None when the breaker/deadline sheds it
            straight to the ladder."""
            dp_c, dv_c, sv_c = packed
            if not self._breaker(f"solver:{solver}").allow():
                return None
            if (self._cycle_deadline is not None
                    and self.clock() >= self._cycle_deadline):
                return None
            sc = None
            if pack is not None:
                # per-chunk pack cost on THIS chunk's pod table against
                # the chunk's node view — per-column by the
                # restricted_ok contract, so chunking preserves the
                # objective exactly
                with self.obs.span(f"scenario:cost@{k}"):
                    sc = pack.cost(chunks[k], nt, node_order, dp_c,
                                   dn_in)
            with self.obs.span(f"pipeline:dispatch@{k}", tier=solver):
                self.obs.jax.record_call("solve", dp_c, dn_in, ds, dt, dv_c,
                                         static=statics)
                if solver == "greedy":
                    a, u = greedy_assign(
                        dp_c, dn_in, ds, self.weights, topo=dt, vol=dv_c,
                        static_vol=sv_c, enabled_mask=self.pred_mask,
                        extra_score=sc,
                        skip_priorities=skip_prio, no_ports=no_ports,
                        no_pod_affinity=no_pod_aff, no_spread=no_spread,
                        fault_hook=hook, fault_site="solve:greedy",
                    )
                    return a, u, len(chunks[k])
                # stats_out matches the monolithic tier's static key so
                # warmed/monolithic/pipelined solves share ONE compiled
                # program per shape; the last chunk's sinkhorn stats ride
                # to end_cycle like the monolith's single solve
                want_stats = self.obs.config.sinkhorn_telemetry
                out = batch_assign(
                    dp_c, dn_in, ds, self.weights,
                    max_rounds=self.max_rounds,
                    per_node_cap=self.per_node_cap, topo=dt, vol=dv_c,
                    static_vol=sv_c, enabled_mask=self.pred_mask,
                    extra_score=sc,
                    use_sinkhorn=(solver == "sinkhorn"),
                    skip_priorities=skip_prio, no_ports=no_ports,
                    no_pod_affinity=no_pod_aff, no_spread=no_spread,
                    fault_hook=hook, fault_site=f"solve:{solver}",
                    stats_out=want_stats,
                )
                if want_stats:
                    assigned_d, usage_d, rounds_d, sk_stats = out
                    self.obs.note_sinkhorn(sk_stats)
                    return assigned_d, usage_d, rounds_d
                return out

        def settle(k, packed, out, dn_in):
            """Block on chunk k's result — validated on device, verdict
            riding the chunk's ONE readback (_validated_readback) — and
            fall back to the full degradation ladder on any failure (the
            chunk then runs with depth-1 semantics). Returns (assigned
            host array or None, usage, tier)."""
            nonlocal solve_s
            chunk = chunks[k]
            dp_c, dv_c, sv_c = packed
            br = self._breaker(f"solver:{solver}")
            ts = self.clock()
            if out is not None:
                try:
                    with self.obs.span(f"pipeline:readback@{k}"):
                        a, u_dev, rounds = self._validated_readback(
                            solver, out, dp_c, dn_in)
                    a = a[: len(chunk)].copy()
                    br.record_success()
                    res.rounds += rounds
                    solve_s += self.clock() - ts
                    return a, u_dev, solver
                except Exception as e:
                    br.record_failure()
                    klog.warning(
                        "pipelined chunk %d solve failed (%s); ladder", k, e)
            # shed (open breaker / blown deadline) or failed readback:
            # this chunk re-solves through the full ladder — retries,
            # CPU fallback, greedy oracle, per-tier breakers included.
            # The pack cost is rebuilt so the objective survives the
            # fallback tiers exactly as it does the monolithic ladder.
            sc = (pack.cost(chunk, nt, node_order, dp_c, dn_in)
                  if pack is not None else None)
            ladder = self._solve_ladder(
                solver, chunk, dp_c, dn_in, ds, dt, dv_c, sv_c, None,
                None, sc, skip_prio, no_ports, no_pod_aff, no_spread,
                res,
            )
            if ladder is None:
                for pod in chunk:
                    self._fail(pod, cycle, res, ("SolverUnavailable",))
                solve_s += self.clock() - ts
                return None, None, ""
            a_host, u_dev, rounds, tier = ladder
            a = a_host[: len(chunk)].copy()
            res.rounds += int(rounds)
            solve_s += self.clock() - ts
            return a, u_dev, tier

        def chunk_failures(k, offset, a, packed):
            """Failure reasons + explain for chunk k's unplaced pods,
            evaluated against the post-chunk usage view (what the serial
            loop would have seen last). Everything is reduced on device
            (obs/explain.explain_reduce) and read back small; per-node
            bit rows are gathered for the failed pods only — preemption
            fodder proportional to the failures, not the chunk."""
            failed_idx = [i for i, t in enumerate(a) if t < 0]
            if not failed_idx:
                return
            dp_c, dv_c, sv_c = packed
            from kubernetes_tpu.obs.explain import explain_reduce
            from kubernetes_tpu.ops.predicates import (
                fit_error_message_from_counts,
            )

            fr = _filter_pass(dp_c, dn_cur, ds, dt, dv_c, sv_c,
                              self.pred_mask)
            fm = np.zeros((dp_c.valid.shape[0],), bool)
            fm[failed_idx] = True
            ex = explain_reduce(
                fr.reasons, dn_cur.valid, jnp.asarray(fm), dp_c.req,
                dn_cur.allocatable - dn_cur.requested, dn_cur.ready,
                dn_cur.network_unavailable)
            rows_dev = None
            if self.enable_preemption:
                rows_dev = jnp.take(
                    fr.reasons, jnp.asarray(failed_idx, dtype=jnp.int32),
                    axis=0)
            ex_h = self.obs.jax.readback("explain", ex)._asdict()
            if explain_on:
                ex_parts.append((offset, len(chunks[k]), ex_h))
            if rows_dev is not None:
                rows = self.obs.jax.readback("preempt-reasons", rows_dev)
            n_valid = nt.n
            pt_c = pk.pack_pods(chunks[k])  # host rows (pack memo hit)
            res_names = (list(FIXED_RESOURCE_NAMES)
                         + pk.u.scalar_resources.items())[: pt_c.req.shape[1]]
            for j, i in enumerate(failed_idx):
                g = offset + i
                bits = int(ex_h["pod_bits"][i])
                reasons_row[g] = decode_reasons(bits)
                if rows_dev is not None:
                    rmat_rows[g] = rows[j]
                failed_global.append(g)
                if bits:
                    fit_msgs[g] = fit_error_message_from_counts(
                        ex_h["per_pod"][i], ex_h["insufficient"][i],
                        ex_h["not_ready"][i], ex_h["net_unavail"][i],
                        n_valid, pt_c.req[i], res_names)

        def bind_chunk(k, offset, a):
            with self.obs.span(f"pipeline:bind@{k}"):
                for i, pod in enumerate(chunks[k]):
                    t = int(a[i])
                    if t < 0:
                        g = offset + i
                        self._fail(pod, cycle, res, reasons_row.get(g, ()),
                                   message=fit_msgs.get(g))
                    else:
                        self._admit_pod(pod, node_order[t], cycle, res)

        # ---- the pipeline proper ----
        offset = 0
        packed = pack_chunk(0)
        pend = (packed, dispatch(0, packed, dn_cur), dn_cur)
        for k in range(len(chunks)):
            # pack chunk k+1 NOW: the host packs while chunk k's solve
            # runs on device (the overlap the executor exists for)
            nxt = (pack_chunk(k + 1)
                   if k + 1 < len(chunks) else None)
            packed_k, out_k, dn_in = pend
            a, u_dev, tier = settle(k, packed_k, out_k, dn_in)
            if tier:
                tier_last = tier
            if u_dev is not None:
                dn_cur = nodes_with_usage(dn_in, u_dev)
            if a is not None:
                # the failure passes ride the device queue BEFORE chunk
                # k+1's solve so their readback never waits behind it
                chunk_failures(k, offset, a, packed_k)
            if nxt is not None:
                pend = (nxt, dispatch(k + 1, nxt, dn_cur), dn_cur)
            if a is not None:
                # bind on host while chunk k+1 solves on device
                bind_chunk(k, offset, a)
            offset += len(chunks[k])

        res.solver_tier = tier_last
        self.metrics.algorithm_duration.observe(solve_s)
        trace.step(
            f"pipeline done ({len(chunks)} chunks, {res.rounds} rounds)")

        if explain_on:
            ex_host = None
            if ex_parts:
                P = len(batch)
                B = int(ex_parts[0][2]["pair_hist"].shape[0])
                ex_host = {
                    "per_pod": np.zeros((P, B), np.int32),
                    "one_bit": np.zeros((P, B), np.int32),
                    "best_bit": np.zeros((P,), np.int32),
                    "best_gain": np.zeros((P,), np.int32),
                    "feasible": np.zeros((P,), np.int32),
                    "pair_hist": np.zeros((B,), np.int64),
                    "pods_blocked": np.zeros((B,), np.int64),
                }
                for off, n, part in ex_parts:
                    # parts are host arrays already (readback output)
                    for f in ("per_pod", "one_bit", "best_bit",
                              "best_gain", "feasible"):
                        ex_host[f][off:off + n] = part[f][:n]
                    ex_host["pair_hist"] += part["pair_hist"].astype(
                        np.int64)
                    ex_host["pods_blocked"] += part["pods_blocked"].astype(
                        np.int64)
            self._build_explain_report(
                cycle, batch, sorted(failed_global), ex_host, nt.n, res)

        preempt_idx = [g for g in sorted(failed_global) if g in rmat_rows]
        if self.enable_preemption and preempt_idx:
            width = next(iter(rmat_rows.values())).shape[0]
            rmat_full = np.zeros((len(batch), width), np.int64)
            for g, row in rmat_rows.items():
                rmat_full[g] = row
            pt0 = self.clock()
            with self.obs.span("preemption"):
                self._run_preemption(
                    batch, preempt_idx, rmat_full, node_order, res)
            self.metrics.preemption_duration.observe(self.clock() - pt0)
            trace.step(f"preemption ({res.preempted} victims)")

        return self._finish_cycle(res, cycle, t0, solve_s, trace,
                                  label=f" (pipelined x{len(chunks)})")

    def _run_extenders(self, batch, base_fr, node_order, early_fail):
        """Call each extender's Filter then Prioritize for interested pods
        against the built-in-feasible node set (``base_fr`` — the shared
        per-cycle filter pass). Ignorable extenders drop out on error;
        others fail the pod (generic_scheduler.go:539-566)."""
        from kubernetes_tpu.extender import ExtenderError

        interested = [
            (i, p) for i, p in enumerate(batch)
            if any(e.is_interested(p) for e in self.extenders)
        ]
        if not interested:
            return None, None
        # the built-in-feasible mask crosses to host for the extender
        # HTTP fan-out — a real d2h boundary, declared + accounted
        base = self.obs.jax.readback("extender-mask", base_fr.mask)
        rows = {n: j for j, n in enumerate(node_order)}
        nodes_by_name = {nd.name: nd for nd in self.cache.nodes()}
        em = np.ones(base.shape, bool)
        es = np.zeros(base.shape, np.float32)
        rc = self.robustness
        for i, pod in interested:
            feasible = [n for n in node_order if base[i, rows[n]]]
            allowed = set(feasible)
            for ext in self.extenders:
                if not ext.is_interested(pod):
                    continue
                ename = ext.name() if hasattr(ext, "name") else repr(ext)
                br = self._breaker(f"extender:{ename}")
                # degraded mode: an open breaker (the endpoint is known
                # down) or a blown cycle deadline sheds the call — the
                # pod schedules on built-in filters alone rather than
                # failing for as long as the remote is dead
                shed = (self._cycle_deadline is not None
                        and self.clock() >= self._cycle_deadline)
                if shed or not br.allow():
                    # ROADMAP bug (a): a config-Ignorable extender must
                    # never fail pods — shedding it is exactly the
                    # "unreachable Ignorable extender" case the flag
                    # covers (extender.go:124), independent of the
                    # degrade-to-ignorable robustness override
                    if rc.extender_degrade_to_ignorable or ext.is_ignorable():
                        self.metrics.extender_degraded.inc(extender=ename)
                        continue
                    allowed = set()
                    early_fail[i] = f"Extender:{ename} unavailable"
                    break
                # clamp the transport timeout to the remaining cycle
                # budget (deadline propagation across the HTTP seam);
                # re-armed per verb group — and explicitly CLEARED on
                # unbounded cycles so a clamp from a deadline-bearing
                # cycle can't leak into this one (ROADMAP bug (b))
                if hasattr(ext, "set_call_budget"):
                    if self._cycle_deadline is not None:
                        ext.set_call_budget(
                            max(self._cycle_deadline - self.clock(), 1e-3))
                    else:
                        ext.set_call_budget(None)
                try:
                    names, _failed = ext.filter(
                        pod, [n for n in feasible if n in allowed], nodes_by_name
                    )
                    allowed &= set(names)
                    scores, weight = ext.prioritize(
                        pod, sorted(allowed), nodes_by_name
                    )
                    br.record_success()
                    for n, sc in scores.items():
                        if n in rows:
                            es[i, rows[n]] += weight * sc
                except ExtenderError as e:
                    br.record_failure()
                    if ext.is_ignorable():
                        continue  # skip this extender (extender.go:124)
                    allowed = set()
                    early_fail[i] = f"Extender:{e}"
                    break
            keep = np.zeros(base.shape[1], bool)
            for n in allowed:
                keep[rows[n]] = True
            em[i] = keep
        return jnp.asarray(em), jnp.asarray(es)

    def _admit_pod(self, pod: Pod, node_name: str, cycle: int,
                   res: CycleResult) -> None:
        """The per-pod admission tail for a PLACED pod: AssumePodVolumes →
        Reserve → cache assume → Permit → bind. Shared by the monolithic
        bind loop and the pipelined executor's per-chunk bind stage."""
        from kubernetes_tpu.framework import WAIT as _WAIT, CycleState

        if not self._fence_ok():
            # deposed mid-cycle: abort BEFORE assuming — the new leader
            # owns this pod now; racing its bind at the hub CAS is the
            # exact split-brain window the fence closes
            self._fenced(pod, cycle, res)
            return
        fw = self.framework
        st = self._cycle_states.get(pod.key()) or CycleState()
        # AssumePodVolumes (scheduler.go:523 assumeVolumes, before
        # Reserve): reserve a PV per unbound delayed-binding claim for
        # THIS node; a racing claimant earlier in the batch may have
        # taken the last one — then this pod fails and requeues.
        # A reservation held from a PREVIOUS cycle (Permit-parked pod
        # popped again) must survive this attempt's failure paths.
        vols_held_before = pod.key() in self.volume_binder.assumed
        vok, vmsg = self.volume_binder.assume_pod_volumes(
            pod, self.cache.node(node_name)
        )
        if not vok:
            self._fail(pod, cycle, res, (f"VolumeBinding:{vmsg}",))
            return
        # Reserve (scheduler.go:531 RunReservePlugins, before assume)
        rs = fw.run_reserve(st, pod, node_name)
        if not rs.is_success():
            if not vols_held_before:
                self.volume_binder.forget_pod_volumes(pod.key())
            fw.run_unreserve(st, pod, node_name)
            self._fail(pod, cycle, res, (f"Reserve:{rs.message}",))
            return
        try:
            self.cache.assume_pod(pod, node_name)
        except Exception:
            # already in cache (e.g. duplicate queue entry) — requeue
            if not vols_held_before:
                self.volume_binder.forget_pod_volumes(pod.key())
            fw.run_unreserve(st, pod, node_name)
            self._fail(pod, cycle, res, ("AssumeError",))
            return
        # Permit (scheduler.go:561): Wait parks the pod (still assumed,
        # capacity held) until allow/reject/timeout
        ps = fw.run_permit(st, pod, node_name)
        if ps.code == _WAIT:
            res.waiting += 1
            self.obs.journeys.note_permit_park(pod.key())
            return
        if not ps.is_success():
            self.cache.forget_pod(pod.key())
            self.volume_binder.forget_pod_volumes(pod.key())
            fw.run_unreserve(st, pod, node_name)
            self._fail(pod, cycle, res, (f"Permit:{ps.message}",))
            return
        self._bind_pod(pod, node_name, st, res)

    def _bind_pod(self, pod: Pod, node_name: str, st, res: CycleResult) -> bool:
        """PreBind -> Bind (plugins, else default binder) -> PostBind —
        the tail of the reference's async binding goroutine
        (scheduler.go:580,:598,:442-457). Any failure forgets the
        assumption and requeues."""
        from kubernetes_tpu.framework import SKIP as _SKIP

        fw = self.framework
        cycle = self.queue.scheduling_cycle

        if not self._fence_ok():
            # the Permit-resume path reaches here without _admit_pod's
            # gate; the assumption is already held — release it, then
            # take the shared fenced-abort path (the bind RPC itself
            # must never leave a deposed leader)
            self.cache.forget_pod(pod.key())
            self.volume_binder.forget_pod_volumes(pod.key())
            fw.run_unreserve(st, pod, node_name)
            self._fenced(pod, cycle, res)
            self._cycle_states.pop(pod.key(), None)
            return False

        def reject(reason: str) -> bool:
            klog.warning("bind of %s to %s failed: %s", pod.key(),
                         node_name, reason)
            self.cache.forget_pod(pod.key())
            self.volume_binder.forget_pod_volumes(pod.key())
            res.bind_errors += 1
            fw.run_unreserve(st, pod, node_name)
            self._fail(pod, cycle, res, (reason,))
            self._cycle_states.pop(pod.key(), None)
            return False

        # BindPodVolumes (scheduler.go:550 bindVolumes, first step of the
        # async binding phase): commit the assumed PVC->PV claims; a write
        # failure forgets the pod AND releases un-committed reservations
        try:
            committed = self.volume_binder.bind_pod_volumes(pod)
        except Exception as e:
            return reject(f"VolumeBinding:{e}")
        if committed:
            # the pod's volume tokens (zone labels, attach counts of its
            # now-bound PVs) changed; the packed node snapshot must rebuild
            self.cache.invalidate_snapshot()
        s = fw.run_prebind(st, pod, node_name)
        if not s.is_success():
            return reject(f"PreBind:{s.message}")
        self.obs.journeys.note_bind_start(pod.key())
        bt0 = self.clock()
        bs = fw.run_bind(st, pod, node_name)
        if bs.code == _SKIP:
            # an interested binder-extender takes the binding over the
            # default binder (extender.go:360,:382)
            binder = self.binder
            for ext in self.extenders:
                if ext.is_binder() and ext.is_interested(pod):
                    binder = ext
                    break
            # ROADMAP bug (b): re-arm the transport budget for the BIND
            # verb from the remaining cycle deadline — without this the
            # bind call inherits whatever clamp the filter verb left
            # behind (stale, and from a different point in the cycle)
            if hasattr(binder, "set_call_budget"):
                if self._cycle_deadline is not None:
                    binder.set_call_budget(
                        max(self._cycle_deadline - self.clock(), 1e-3))
                else:
                    binder.set_call_budget(None)
            try:
                binder.bind(pod, node_name)
            except Exception as e:
                if self._bind_ambiguous(e):
                    # the AMBIGUOUS class: the hub may have committed
                    # before the response was lost. NEVER blind-retry —
                    # resolve by read-your-write verification instead
                    # (GET the pod, compare uid+nodeName, adopt or
                    # requeue; park when the GET itself is unreachable).
                    verdict = self._handle_ambiguous_bind(
                        pod, node_name, st, res, e, reject)
                    if verdict is not True:
                        return bool(verdict)
                    # adopted: the bind DID land — fall through to the
                    # normal success tail (finish_binding, events, ...)
                else:  # definite failure -> Forget + retry
                    return reject(f"BindError:{e}")
        elif not bs.is_success():
            return reject(f"Bind:{bs.message}")
        self.metrics.binding_duration.observe(self.clock() - bt0)
        self.cache.finish_binding(pod.key())
        self.queue.nominated.delete(pod)
        # scheduling-attempt count for the landed pod (failures recorded
        # in the backoff map + this successful try), then reset so a
        # recreated pod with the same key starts fresh
        self.metrics.pod_scheduling_attempts.observe(
            self.queue.backoff_map.attempts(pod.key()) + 1)
        self.queue.backoff_map.clear_pod(pod.key())
        self.why_pending.pop(pod.key(), None)
        res.scheduled += 1
        res.assignments[pod.key()] = node_name
        # admission timestamp -> bind: the pod's create-to-bind latency
        # (queued_at is the queue-add stamp on this scheduler's clock;
        # 0.0 is a valid fake-clock enqueue time, not "unset")
        res.e2e_latency_s[pod.key()] = max(
            self.clock() - getattr(pod, "queued_at", self.clock()), 0.0)
        self.obs.journeys.note_bound(pod.key(), cycle)
        fw.run_postbind(st, pod, node_name)
        self._cycle_states.pop(pod.key(), None)
        self.event_sink("Scheduled", pod, node_name)
        return True

    # -- ambiguous-outcome bind protocol (network-fault robustness) --------

    def _bind_ambiguous(self, e: Exception) -> bool:
        """Is this bind failure the AMBIGUOUS class (the hub may have
        committed before the response was lost)? ``faults.RPCTimeout``
        always is; raw transport timeouts (socket.timeout /
        TimeoutError) are too, but only a scheduler WITH a hub reader
        can do better than the legacy reject-and-requeue for them — so
        without one their behavior stays exactly as before."""
        import socket

        from kubernetes_tpu.faults import RPCTimeout

        if isinstance(e, RPCTimeout):
            return True
        return (self.pod_reader is not None
                and isinstance(e, (socket.timeout, TimeoutError)))

    def _resolve_ambiguous_bind(self, pod: Pod, node_name: str):
        """Read-your-write verification of an ambiguously timed-out
        bind: GET the pod from the hub (bounded retries, full jitter on
        the per-replica stream) and compare uid + nodeName.

        Returns ``"adopted"`` (the hub HAS our binding — confirm, never
        re-bind), ``"requeued"`` (verified unbound — a retry through
        the normal requeue path is safe), ``"conflict"`` (bound
        elsewhere or recreated under a new uid), ``"gone"`` (deleted
        mid-bind), ``"ttl-parked"`` (no reader attached — fall back to
        the assume TTL / watch confirmation), or ``None`` when the
        verification GET itself stayed unreachable (the caller parks
        the pod and re-probes later)."""
        if self.pod_reader is None:
            return "ttl-parked"
        key = pod.key()
        # the cycle deadline bounds IN-CYCLE verification; on the idle
        # paths (parked re-probes, TTL-expiry verification) the last
        # cycle's absolute deadline is stale — already in the past —
        # and would silently zero the retry budget
        deadline = self._cycle_deadline
        if deadline is not None and self.clock() >= deadline:
            deadline = None
        try:
            cur = self._bind_verify_retry.call(
                lambda: self.pod_reader(key),
                deadline_s=deadline, clock=self.clock)
        except Exception as e:
            klog.warning("ambiguous bind of %s -> %s: verification GET "
                         "failed (%s); parking", key, node_name, e)
            return None
        if cur is None:
            return "gone"
        if getattr(cur, "uid", None) != pod.uid:
            return "conflict"
        if cur.node_name == node_name:
            return "adopted"
        if cur.node_name:
            return "conflict"
        return "requeued"

    def _handle_ambiguous_bind(self, pod: Pod, node_name: str, st, res,
                               exc: Exception, reject) -> object:
        """Resolve one in-cycle ambiguous bind timeout. Returns ``True``
        when the hub turned out to have committed (the caller proceeds
        to the normal success tail), ``False`` when the pod was
        requeued, parked, or dropped here."""
        key = pod.key()
        self.obs.note_ambiguous_bind()
        resolution = self._resolve_ambiguous_bind(pod, node_name)
        self.metrics.bind_ambiguous.inc(
            resolution=resolution or "deferred")
        if resolution is None:
            # the hub is unreachable for verification too: the pod
            # stays ASSUMED (capacity held, NO TTL — a TTL reap would
            # requeue and risk re-binding a committed pod) and every
            # cycle / idle tick re-probes until the hub answers
            klog.warning("bind of %s -> %s timed out ambiguously and "
                         "verification is unreachable; parked assumed",
                         key, node_name)
            self._ambiguous_binds[key] = (pod, node_name, st)
            self.obs.journeys.note_ambiguous_park(key, "bind-timeout")
            self._cycle_states.pop(key, None)
            return False
        if resolution == "adopted":
            klog.V(2).info("ambiguous bind of %s -> %s resolved: hub "
                           "committed — adopted, not re-bound",
                           key, node_name)
            return True
        if resolution == "ttl-parked":
            # no reader: optimistic fallback — arm the assume TTL; the
            # watch MODIFIED confirms a committed bind, the TTL reap
            # requeues an uncommitted one (a late re-bind then fails
            # the hub CAS harmlessly)
            self.cache.finish_binding(key)
            self._cycle_states.pop(key, None)
            return False
        if resolution == "requeued":
            reject(f"BindAmbiguous:verified not committed ({exc})")
            return False
        # conflict / gone: same forget-and-requeue path as a definite
        # bind error; the watch (or reconcile) drops stale queue entries
        reject(f"BindError:ambiguous bind resolved as {resolution}: "
               f"{exc}")
        return False

    def _verify_ambiguous_binds(self) -> None:
        """Re-probe every parked ambiguous bind (cycle path AND
        idle_tick): the watch may have settled it meanwhile (confirmed
        add or delete), else the verification GET is retried and the
        pod adopted / requeued exactly like the in-cycle resolution."""
        if not self._ambiguous_binds:
            return
        res = CycleResult()
        resolved = False
        for key, (pod, node_name, st) in list(
                self._ambiguous_binds.items()):
            # st is None ONLY for a park made by the TTL reap — that
            # pod's ORIGINAL bind already ran the success tail
            # (postbind, Scheduled event, scheduling metrics), so an
            # adoption here must confirm the cache and nothing else;
            # its verdicts keep the expired-* metric labeling so the
            # TTL-expiry series stays distinguishable from in-cycle
            # bind timeouts
            reap_origin = st is None
            watch_settled = not self.cache.is_assumed(key)
            if watch_settled:
                # the watch answered first: a confirmed add flipped the
                # assumption to bound (a delete pops the park in
                # on_pod_delete and reconcile clears parks wholesale,
                # so bound is the only live way here) — an adoption
                # whose read-your-write answer is the hub's own stream;
                # an IN-CYCLE park still owes the full success tail,
                # which its original bind never reached
                del self._ambiguous_binds[key]
                if self.cache.pod(key) is None:
                    continue  # settled out-of-band; nothing to finish
                resolution = "adopted"
            else:
                resolution = self._resolve_ambiguous_bind(pod, node_name)
                if resolution is None:
                    continue  # hub still unreachable: stay parked
                del self._ambiguous_binds[key]
            self.metrics.bind_ambiguous.inc(
                resolution=(f"expired-{resolution}" if reap_origin
                            else resolution))
            resolved = True
            st = st or _new_cycle_state()
            if resolution in ("adopted", "ttl-parked"):
                if resolution == "ttl-parked":
                    # reader detached: back to TTL semantics
                    self.cache.finish_binding(key)
                    continue
                if not watch_settled:
                    # the verification GET is hub truth exactly like a
                    # relist — confirm the binding outright
                    # (reconcile's adopt), never arm a TTL whose reap
                    # would requeue a pod we just PROVED the hub bound
                    self.cache.add_pod(self.cache.pod(key) or pod)
                if reap_origin:
                    klog.V(2).info("parked expired assumption of %s -> "
                                   "%s resolved: adopted", key, node_name)
                    continue
                self.queue.nominated.delete(pod)
                self.metrics.pod_scheduling_attempts.observe(
                    self.queue.backoff_map.attempts(key) + 1)
                self.queue.backoff_map.clear_pod(key)
                self.why_pending.pop(key, None)
                res.scheduled += 1
                res.assignments[key] = node_name
                res.e2e_latency_s[key] = max(
                    self.clock() - getattr(pod, "queued_at",
                                           self.clock()), 0.0)
                self.obs.journeys.note_bound(
                    key, self.queue.scheduling_cycle)
                self.framework.run_postbind(st, pod, node_name)
                self.event_sink("Scheduled", pod, node_name)
                klog.V(2).info("parked ambiguous bind of %s -> %s "
                               "resolved: adopted", key, node_name)
            else:
                self.cache.forget_pod(key)
                self.volume_binder.forget_pod_volumes(key)
                self.framework.run_unreserve(st, pod, node_name)
                res.bind_errors += 1
                if resolution == "requeued":
                    reasons = ("BindAmbiguous:verified not committed",)
                else:
                    reasons = ("BindError:ambiguous bind resolved as "
                               f"{resolution}",)
                self._fail(pod, self.queue.scheduling_cycle, res, reasons)
        if resolved:
            self._record_metrics(res)

    def _process_waiting(self, res: CycleResult) -> None:
        """Resolve Permit waits (waiting_pods_map.go consumers): allowed
        pods proceed to binding; rejected or timed-out pods are forgotten
        and requeued — the reference rejects on timeout
        (framework.go RunPermitPlugins wait loop)."""
        from kubernetes_tpu.framework import CycleState

        fw = self.framework
        now = self.clock()
        for wp in fw.waiting.items():
            key = wp.pod.key()
            st = self._cycle_states.get(key) or CycleState()
            if wp.rejected is not None or (not wp.allowed and now >= wp.deadline):
                fw.waiting.remove(key)
                self.cache.forget_pod(key)
                self.volume_binder.forget_pod_volumes(key)
                fw.run_unreserve(st, wp.pod, wp.node_name)
                reason = wp.rejected or "permit timeout"
                self._fail(
                    wp.pod, self.queue.scheduling_cycle, res,
                    (f"Permit:{reason}",),
                )
                self._cycle_states.pop(key, None)
            elif wp.allowed:
                fw.waiting.remove(key)
                self._bind_pod(wp.pod, wp.node_name, st, res)

    def _nominated_pods(self, exclude) -> List[Tuple[Pod, str]]:
        """(pod, node) for every nominated pod not in the current batch and
        whose node still exists."""
        out: List[Tuple[Pod, str]] = []
        for node_name, pods in self.queue.nominated.items():
            if self.cache.node(node_name) is None:
                continue
            for p in pods:
                if p.key() not in exclude:
                    out.append((p, node_name))
        return out

    def _run_preemption(self, batch, failed_idx, rmat, node_order, res) -> None:
        from kubernetes_tpu.preemption import preempt

        nodes = self.cache.nodes()
        node_pods_of = {nd.name: self.cache.pods_on(nd.name) for nd in nodes}
        pdbs = list(self.pdb_lister())
        order = sorted(failed_idx, key=lambda i: -batch[i].priority)
        done = 0
        for i in order:
            if done >= self.max_preemptions_per_cycle:
                break
            pod = batch[i]
            reason_bits = {
                name: int(rmat[i, r])
                for r, name in enumerate(node_order)
                if name
            }
            self.metrics.preemption_attempts.inc()
            result = preempt(
                pod, nodes, node_pods_of, reason_bits, pdbs,
                nominated_pods_of=dict(self.queue.nominated.items()),
                vol_state=self.cache.packer.resolve_volumes,
                extenders=[e for e in self.extenders if e.supports_preemption()],
                enable_non_preempting=self.enable_non_preempting,
            )
            if result is None:
                continue
            self.metrics.preemption_victims.inc(len(result.victims))
            now = self.clock()
            for v in result.victims:
                v.deletion_timestamp = now
                self.event_sink("Preempted", v, f"by {pod.key()}")
                self.obs.journeys.note_evicted(v.key(), pod.key())
                if self.victim_deleter is not None:
                    # deletion goes through the hub; the victim stays in the
                    # cache as terminating until the watch delete arrives
                    self.victim_deleter(v)
                else:
                    self.cache.remove_pod(v.key())
                # either way, later preemptors in this cycle must not
                # re-select (and re-delete) the same victims
                node_pods_of[result.node_name] = [
                    p
                    for p in node_pods_of[result.node_name]
                    if p.key() != v.key()
                ]
            # clear lower-priority nominations on the chosen node
            # (scheduler.go:330 getLowerPriorityNominatedPods)
            for p in result.clear_nominations:
                p.nominated_node_name = ""
                self.queue.nominated.delete(p)
            pod.nominated_node_name = result.node_name
            self.queue.nominated.add(pod, result.node_name)
            res.preempted += len(result.victims)
            res.nominations[pod.key()] = result.node_name
            done += 1
        if res.preempted and self.victim_deleter is None:
            # the victims' delete "events" happened inline (grace 0); the
            # reference's watch delete -> MoveAllToActiveQueue wakeup must
            # happen here too or the nominated preemptor sits in
            # unschedulableQ until the 60 s leftover flush
            self.queue.move_all_to_active()

    def _run_preemption_cascade(self, batch, failed_idx, rmat, node_order,
                                res) -> None:
        """In-batch preemption cascade (scenario packs; docs/scenarios.md):
        victim SELECTION runs the exact per-node machinery from
        preemption.py — shared state across preemptors, so earlier
        evictions are visible to later ones — and then victims AND
        displaced pods re-enter one dense solve in THIS cycle
        (:meth:`_cascade_solve`) instead of the stock path's per-pod
        nominate-and-wait loop. Single-pod batches select bit-identical
        victim sets to :meth:`_run_preemption` by construction (pinned
        by the seeded parity test in tests/test_scenarios.py)."""
        import dataclasses as _dc

        from kubernetes_tpu.scenarios.cascade import select_cascade

        nodes = self.cache.nodes()
        node_pods_of = {nd.name: self.cache.pods_on(nd.name)
                        for nd in nodes}
        pdbs = list(self.pdb_lister())
        order = sorted(failed_idx, key=lambda i: -batch[i].priority)
        preemptors = [(batch[i], {
            name: int(rmat[i, r])
            for r, name in enumerate(node_order) if name
        }) for i in order]
        sel = select_cascade(
            preemptors, nodes, node_pods_of, pdbs,
            nominated_pods_of=dict(self.queue.nominated.items()),
            vol_state=self.cache.packer.resolve_volumes,
            extenders=[e for e in self.extenders
                       if e.supports_preemption()],
            enable_non_preempting=self.enable_non_preempting,
            max_preemptions=self.max_preemptions_per_cycle,
            # same per-processed-pod accounting as the stock loop
            on_attempt=self.metrics.preemption_attempts.inc,
        )
        if not sel.chosen:
            return
        now = self.clock()
        if sel.victims:
            self.metrics.preemption_victims.inc(len(sel.victims))
            self.metrics.scenario_cascade_victims.inc(len(sel.victims))
        # preemptors that actually RE-SOLVE this cycle (gang members
        # never do — binding one member solo would sidestep the
        # all-or-nothing rollback; they keep stock nominations)
        solve_keys = {batch[i].key() for i in order
                      if batch[i].key() in sel.chosen
                      and not batch[i].pod_group}
        displaced = []
        requeue_only = []
        for v in sel.victims:
            v.deletion_timestamp = now
            self.event_sink(
                "Preempted", v, f"by {sel.victim_of[v.key()]} (cascade)")
            self.obs.journeys.note_evicted(
                v.key(), sel.victim_of[v.key()])
            if self.victim_deleter is not None:
                # deletion goes through the hub; the victim holds its
                # capacity as terminating until the watch delete lands,
                # so it CANNOT re-enter this cycle's solve — the
                # preemptors keep the stock nomination semantics below
                self.victim_deleter(v)
            else:
                self.cache.remove_pod(v.key())
                if not self.responsible_for(v):
                    continue
                pending = _dc.replace(v, node_name="",
                                      deletion_timestamp=0.0)
                if sel.victim_of[v.key()] in solve_keys:
                    displaced.append(pending)
                else:
                    # the evacuated capacity is PROMISED to a
                    # nominated-only preemptor — re-solving this victim
                    # now could retake it (the cascade solve has no
                    # pass-A phantom occupancy); requeue instead, like
                    # the stock path's victims-then-retry flow
                    requeue_only.append(pending)
        for p in sel.clear_nominations:
            p.nominated_node_name = ""
            self.queue.nominated.delete(p)
        res.preempted += len(sel.victims)
        # the cascade re-solve: preemptors first (priority order is the
        # queue comparator inside the solve anyway), displaced victims
        # riding the same dense batch, bounded by the config budget.
        # GANG preemptors are excluded: binding one member through the
        # cascade would sidestep the all-or-nothing rollback and could
        # leave a partially-bound gang — they keep the stock nomination
        # semantics (victims evicted now, the whole gang re-solves next
        # cycle under the gang check). Displaced gang members may still
        # re-place: their gang-mates remain bound, so migration keeps
        # the group whole (the stock path would just kill them).
        resolve_pods = [batch[i] for i in order
                        if batch[i].key() in solve_keys]
        budget = max(self.scenario.cascade_max_pods, 1)
        overflow = (resolve_pods + displaced)[budget:]
        resolve_pods = (resolve_pods + displaced)[:budget]
        if self.victim_deleter is not None or not sel.victims:
            # nothing newly USABLE was freed: in hub-deleter mode the
            # victims hold their capacity as terminating, and a
            # victimless win (pick_one_node's no-victims fast path)
            # evacuated nothing — the re-solve could not place anything
            # the main solve didn't, so skip straight to the
            # nominations instead of paying a second full ladder solve
            placed, q2 = set(), None
        else:
            placed, q2 = self._cascade_solve(resolve_pods, res)
        for p in requeue_only:
            self._fail(p, self.queue.scheduling_cycle, res,
                       ("CascadeUnplaced",))
        for p in overflow:
            # a displaced pod the budget truncated was already evicted
            # from its node — it MUST requeue through the standard
            # error path, not silently vanish (preemptors in the
            # overflow keep their existing failure row + nomination)
            if p.key() not in res.failure_reasons:
                self._fail(p, self.queue.scheduling_cycle, res,
                           ("CascadeUnplaced",))
        for p in displaced:
            if p.key() in placed:
                self.metrics.scenario_displaced_replaced.inc()
        if q2:
            # the cascade changed the cluster: re-publish the
            # CLUSTER-STATE quality fields from the cascade solve's
            # final usage (nodes_used/headroom/fragmentation/free);
            # batch-relative fields (placed, nodes_used_batch,
            # priority_headroom) keep describing the main solve
            for k in ("nodes_used", "headroom", "fragmentation",
                      "free_cpu_frac"):
                res.scenario_quality[k] = q2[k]
            self._publish_scenario_quality(res.scenario_quality)
        # preemptors the re-solve could not place (victimless win,
        # hub-delete mode, or a cascade interaction took their spot)
        # keep the stock semantics: nominated onto the chosen node,
        # retried next cycle
        for i in order:
            key = batch[i].key()
            if key in sel.chosen and key not in placed:
                batch[i].nominated_node_name = sel.chosen[key]
                self.queue.nominated.add(batch[i], sel.chosen[key])
                res.nominations[key] = sel.chosen[key]
        if sel.victims and self.victim_deleter is None:
            # inline victim deletes (grace 0): the watch-delete wakeup
            # the stock path performs must happen here too
            self.queue.move_all_to_active()

    def _publish_scenario_quality(self, quality) -> None:
        """Fan one cycle's quality dict out to the flight record and
        the gauge family — scores that stopped being reported (a
        gangless cycle after a gang cycle) drop to zero instead of
        going stale, the explain-gauge freshness rule."""
        self.obs.note_scenario(quality)
        for k in self._scenario_scores_seen - set(quality):
            self.metrics.scenario_quality.set(0.0, score=k)
        for k, v in quality.items():
            self.metrics.scenario_quality.set(float(v), score=k)
            self._scenario_scores_seen.add(k)

    def maybe_repack(self) -> int:
        """Steady-state consolidation re-pack
        (``scenario.repackInterval``): every interval, drain the pods
        off the least-utilized FULLY-emptiable nodes — nodes holding
        only this scheduler's bound, non-assumed, non-terminating pods,
        whose load the rest of the occupied cluster can absorb — and
        requeue them, so the next cycles' consolidation objective packs
        them tight again. Admission-time consolidation alone ratchets:
        sustained churn strands capacity on nodes that emptied BELOW
        the pack's fill order after their pods bound, and nothing ever
        revisits them. Bounded per sweep by ``scenario.repackMaxPods``;
        returns the number of pods drained (0 off-cadence / packless).

        Callers: the serving maintenance hook (between cycles, under
        the loop lock) and :meth:`idle_tick` for the legacy loop."""
        import dataclasses as _dc

        interval = self.scenario.repack_interval_s
        if interval <= 0 or self.scenario_pack is None:
            return 0
        now = self.clock()
        if self._last_repack_at is None:
            # cadence starts at first observation: a full interval of
            # real churn elapses before the first drain
            self._last_repack_at = now
            return 0
        if now - self._last_repack_at < interval:
            return 0
        self._last_repack_at = now
        free: Dict[str, Tuple[float, int]] = {}
        occupied = []
        for nd in self.cache.nodes():
            pods = self.cache.pods_on(nd.name)
            used = sum(p.requests.cpu_milli for p in pods)
            free[nd.name] = (nd.allocatable.cpu_milli - used,
                             nd.allocatable.pods - len(pods))
            if pods:
                occupied.append(
                    (used / max(nd.allocatable.cpu_milli, 1.0),
                     nd.name, pods))
        if len(occupied) < 2:
            return 0  # nothing to consolidate INTO
        occupied.sort(key=lambda t: (t[0], t[1]))
        budget = max(self.scenario.repack_max_pods, 1)
        emptied: set = set()
        drained = 0
        for _util, name, pods in occupied:
            movable = [
                p for p in pods
                if self.responsible_for(p)
                and not self.cache.is_assumed(p.key())
                and not p.deletion_timestamp
            ]
            if len(movable) != len(pods):
                continue  # foreign / in-flight pods pin the node
            if not movable or len(movable) > budget - drained:
                continue
            need_cpu = sum(p.requests.cpu_milli for p in movable)
            # feasibility heuristic only — the SOLVER places; this just
            # avoids draining pods the remaining occupied nodes cannot
            # possibly hold (they would bounce back, or worse, land on
            # the node just emptied)
            absorb_cpu = absorb_slots = 0
            for _u2, n2, pods2 in occupied:
                if n2 == name or n2 in emptied:
                    continue
                c, s = free[n2]
                absorb_cpu += max(c, 0)
                absorb_slots += max(s, 0)
            if need_cpu > absorb_cpu or len(movable) > absorb_slots:
                continue
            for p in movable:
                if self.repack_evictor is not None:
                    # hub integration: post the unbind and let the
                    # watch stream converge local state
                    self.repack_evictor(p)
                else:
                    self.cache.remove_pod(p.key())
                    self.queue.add_if_not_present(_dc.replace(
                        p, node_name="", deletion_timestamp=0.0))
            emptied.add(name)
            drained += len(movable)
            if drained >= budget:
                break
        if drained:
            self.metrics.scenario_repacks.inc()
            self.metrics.scenario_repack_drained.inc(drained)
            self.queue.move_all_to_active()
            klog.V(2).info(
                "steady-state re-pack: drained %d pods off %d nodes",
                drained, len(emptied))
        return drained

    def _cascade_pad(self, n: int) -> int:
        """Pod-bucket for a cascade re-solve. With warmup on, snap UP
        to a bucket the warm sweep covered (the smallest explicit
        bucket that fits, or at least ``min_bucket`` for the geometric
        default sweep) so a cascade never pays a hot-path compile —
        cascades bigger than every warmed bucket keep their natural
        bucket (a one-time compile, logged by the retrace telemetry)."""
        pad = bucket_size(max(n, 1))
        wu = self.warmup_config
        if not wu.enabled:
            return pad
        explicit = sorted(b for b in wu.pod_buckets if b >= pad)
        if explicit:
            return explicit[0]
        if not wu.pod_buckets:
            return max(pad, bucket_size(max(min(wu.min_bucket,
                                                self.max_batch), 1)))
        return pad

    def _cascade_solve(self, pods_list, res: CycleResult):
        """One dense solve over the cascade's preemptors + displaced
        pods against the evacuated cluster — a fresh snapshot (the
        victims' rows are dirty, so the resident path patches them with
        the usual delta scatter), the full degradation ladder with
        fused validation, the pack's cost term, and the standard
        admission tail per placed pod. Returns ``(placed_keys,
        quality_or_None)`` — the quality vector re-reduced from the
        cascade's FINAL usage, so cascade cycles report the true
        post-cascade cluster state."""
        from kubernetes_tpu.ops.arrays import volumes_to_device

        placed: set = set()
        if not pods_list:
            return placed, None
        pk = self.cache.packer
        for p in pods_list:
            pk.intern_pod(p)
        if self.device_resident_snapshot:
            nt, dn, _ = self._device_snapshot_recovering()
        else:
            nt = self.cache.snapshot()
            dn = None
        node_order = self.cache.node_order()
        pt = pk.pack_pods(pods_list)
        skip_prio, no_ports, no_pod_aff, no_spread = solver_gates(nt, pt)
        if dn is None:
            if self._mesh_live:
                from kubernetes_tpu.parallel.mesh import place_node_table

                dn = place_node_table(nt, self.mesh)
            else:
                dn = nodes_to_device(nt)
        dp = self._place(pods_to_device(
            pt, pad_to=self._cascade_pad(len(pods_list))))
        ds = self._place(selectors_to_device(pk.pack_selector_tables()))
        dt = self._place(topology_to_device(pk.pack_topology_tables())
                         if _has_topo(pk.u) else None)
        dv = sv = None
        if any(p.volumes for p in pods_list):
            dv = self._place(volumes_to_device(
                pk.pack_volume_tables(pods_list)))
            sv = _static_vol_pass(dp, dn, ds, dv)
        extra_score = None
        if self.scenario_pack is not None:
            extra_score = self.scenario_pack.cost(
                pods_list, nt, node_order, dp, dn)
        solver = self.solver if self.solver != "exact" else "batch"
        self.obs.jax.record_call(
            "solve", dp, dn, ds, dt, dv,
            static=(solver, tuple(skip_prio), no_ports, no_pod_aff,
                    no_spread, self.pred_mask, self.per_node_cap,
                    self.max_rounds, True, extra_score is None,
                    self._mesh_live),
        )
        ladder = self._solve_ladder(
            solver, pods_list, dp, dn, ds, dt, dv, sv, None, None,
            extra_score, skip_prio, no_ports, no_pod_aff, no_spread, res,
        )
        cycle = self.queue.scheduling_cycle
        if ladder is None:
            for p in pods_list:
                if p.key() not in res.failure_reasons:
                    self._fail(p, cycle, res, ("SolverUnavailable",))
            return placed, None
        assigned, usage2, _rounds, _tier = ladder
        q2 = None
        if self.scenario.quality:
            from kubernetes_tpu.ops.scenario_cost import quality_reduce
            from kubernetes_tpu.scenarios.quality import decode_quality

            pad_a = np.full((dp.valid.shape[0],), -1, np.int32)
            pad_a[: len(pods_list)] = assigned[: len(pods_list)]
            with self.obs.span("pipeline:readback@quality"):
                q2 = decode_quality(self.obs.jax.readback(
                    "scenario-quality",
                    quality_reduce(jnp.asarray(pad_a), usage2.requested,
                                   dp, dn)))
        assigned = assigned[: len(pods_list)]
        for i, p in enumerate(pods_list):
            t = int(assigned[i])
            if t < 0:
                # displaced pods requeue through the standard error
                # path; an unplaced preemptor keeps the failure row the
                # main bind loop already recorded (no double count) and
                # gets its nomination from the caller
                if p.key() not in res.failure_reasons:
                    self._fail(p, cycle, res, ("CascadeUnplaced",))
                continue
            # a preemptor was already _fail'd by the main bind loop —
            # its stale queue entry and failure row are superseded by
            # the cascade bind
            self.queue.delete(p.key())
            had_row = p.key() in res.failure_reasons
            before_sched = res.scheduled
            before_unsched = res.unschedulable
            before_wait = res.waiting
            self._admit_pod(p, node_order[t], cycle, res)
            if res.scheduled > before_sched or res.waiting > before_wait:
                # bound — or PARKED by a Permit plugin (assumed in
                # cache, capacity held): either way the pod left the
                # unschedulable state and must NOT also be nominated
                # (a nominated + assumed pod would double-count its
                # capacity in next cycle's pass A)
                placed.add(p.key())
                if had_row:
                    res.unschedulable -= 1
                    res.failure_reasons.pop(p.key(), None)
                    res.fit_errors.pop(p.key(), None)
                    self.why_pending.pop(p.key(), None)
            elif had_row and res.unschedulable > before_unsched:
                # the admission tail _fail'd a pod the main bind loop
                # already counted — one pod, one unschedulable
                res.unschedulable -= 1
        return placed, q2

    def _fail(self, pod: Pod, cycle: int, res: CycleResult, reasons,
              message: str = None) -> None:
        res.unschedulable += 1
        res.failure_reasons[pod.key()] = tuple(reasons)
        if message is not None:
            res.fit_errors[pod.key()] = message
        # journey attempt row (tier/scope backfilled at _finish_cycle);
        # the queue re-add below then closes the solve phase
        self.obs.journeys.note_attempt_failed(
            pod.key(), cycle, reasons[0] if reasons else "")
        self._cycle_states.pop(pod.key(), None)  # cycle over for this pod
        self.queue.record_failure(pod)
        self.queue.add_unschedulable_if_not_present(pod, cycle)
        # events carry the FitError-shaped per-reason node counts when the
        # failure came from the filter pass (FitError.Error parity,
        # generic_scheduler.go:105-122); plugin/gang failures keep their
        # status text
        self.event_sink("FailedScheduling", pod,
                        message if message is not None else ",".join(reasons))

    def warmup(self, sample_pods=(), node_count: Optional[int] = None) -> int:
        """AOT warmup (config.WarmupConfig): compile the solver — and the
        standalone filter pass — at every bucketed pod-batch shape the
        driver can hit, so first-pod latency never pays an XLA compile
        and queue-length churn across bucket boundaries causes no
        retraces (`scheduler_jax_retrace_total` stays flat).

        ``sample_pods`` (optional but recommended) seeds the universes
        and derives the host-side solver gates exactly as real cycles
        will; without a sample the clean-batch gate set is warmed. The
        node axis uses the cache's current cluster (its bucket is fixed
        per cluster) or ``node_count`` before any node has synced.
        Signatures are pre-registered with the JAX telemetry, so the
        first real cycle classifies as a cache hit, not a compile.
        Returns the number of bucketed shapes compiled."""
        wu = self.warmup_config
        pk = self.cache.packer
        sample = list(sample_pods)
        for p in sample:
            pk.intern_pod(p)
        self._mesh_live = self.mesh is not None
        if self.cache.node_count():
            if self.device_resident_snapshot:
                nt, dn, _ = self._device_snapshot_recovering()
                if dn is None:  # device cooling off: warm on host tables
                    dn = nodes_to_device(nt)
                    self._mesh_live = False
            else:
                nt = self.cache.snapshot()
                if self._mesh_live:
                    from kubernetes_tpu.parallel.mesh import (
                        place_node_table,
                    )

                    dn = place_node_table(nt, self.mesh)
                else:
                    dn = nodes_to_device(nt)
        elif node_count:
            # no cluster yet: widths-complete zero-row table, padded to
            # the caller's expected node bucket (and at least the mesh
            # size, so the warmed shapes match the sharded cycle's)
            nt = pk.pack_nodes([])
            pad = bucket_size(max(node_count, 1))
            if self._mesh_live:
                from kubernetes_tpu.parallel.mesh import place_node_table

                dn = place_node_table(nt, self.mesh, pad_to=pad)
            else:
                dn = nodes_to_device(nt, pad_to=pad)
        else:
            # no cluster AND no expected size: warming now would compile
            # (and pre-register) shapes with an empty-cluster node bucket
            # no real cycle can match — the first solve would then pay a
            # hot-path compile AND read as a retrace. Callers defer until
            # the informer has synced (cli.run warms lazily).
            klog.warning("warmup skipped: no nodes synced and no "
                         "node_count given — call again after the first "
                         "node sync")
            return 0
        ds = self._place(selectors_to_device(pk.pack_selector_tables()))
        dt = self._place(topology_to_device(pk.pack_topology_tables())
                         if _has_topo(pk.u) else None)
        pt_all = pk.pack_pods(sample)
        skip_prio, no_ports, no_pod_aff, no_spread = solver_gates(nt, pt_all)
        solver = self.solver if self.solver != "exact" else "batch"
        statics = (solver, tuple(skip_prio), no_ports, no_pod_aff,
                   no_spread, self.pred_mask, self.per_node_cap,
                   self.max_rounds, True,
                   # a scenario pack fills extra_score every cycle; the
                   # warmed signature must carry the same trace-time
                   # fact or the first real cycle recompiles
                   self.scenario_pack is None,
                   self._mesh_live)
        buckets = tuple(wu.pod_buckets)
        if not buckets:
            # geometric x2 steps up to bucket_size(max_batch) — the
            # largest shape ANY cycle can present. Pipelined cycles pad
            # chunks to bucket_size(pipeline_chunk) (a power of two, so
            # it's in this sweep), but feature batches forced monolithic
            # (extenders, gang, nominated pods...) still pad the whole
            # batch, so capping at the chunk bucket would leave their
            # first cycle paying a hot-path compile
            top = bucket_size(max(self.max_batch, 1))
            out = []
            b = bucket_size(max(min(wu.min_bucket, top), 1))
            while b <= top:
                out.append(b)
                b *= 2
            buckets = tuple(out)
        has_vol_sample = any(p.volumes for p in sample)
        compiled = 0
        for P in buckets:
            try:
                if self.fault_injector is not None:
                    # device-loss chaos seam for the compile below
                    self.fault_injector.device_hook("warmup:compile")
                compiled += self._warm_bucket(
                    P, pk, sample, nt, dn, ds, dt, solver, statics,
                    (skip_prio, no_ports, no_pod_aff, no_spread),
                    has_vol_sample, wu, anchor=(compiled == 0))
            except Exception as e:
                # a lost/OOMed device during an AOT compile (injected
                # OR a real XLA runtime error — warmup runs inside the
                # takeover reconciliation, where crashing the new
                # leader is the worst outcome): abort cleanly with what
                # compiled so far. The hot path degrades via
                # _device_snapshot_recovering / the ladder, and the
                # next re-arm (reconcile, lazy-warm gate) retries.
                self.metrics.recovery_device_resets.inc()
                self.obs.note_device_reset()
                ml = self.obs.memledger
                if ml.enabled:
                    # ranked forensic record BEFORE the drops deregister
                    # the residents (parks on _pending_oom — warmup runs
                    # between cycles)
                    oomrec = ml.record_oom(
                        "warmup:compile", error=str(e),
                        shapes=f"P{P}xN{int(dn.valid.shape[0])}",
                        cycle=self.queue.scheduling_cycle)
                    self.obs.note_oom_forensic(ml.oom_flag(oomrec))
                self.cache.drop_device_snapshot()
                # the drop above only kills the resident node table; the
                # score cache and warm-potential carry reference device
                # state and must not survive it into the cooloff cycles
                self._drop_incremental("device-loss")
                klog.warning("warmup aborted at bucket %d: %s", P, e)
                return compiled
        if self.device_resident_snapshot and self.mesh is None:
            # pre-compile the PR-5 delta scatter at the dirty-row
            # buckets steady churn presents — left to first sight it
            # costs a ~0.5s XLA compile on the hot path, exactly the
            # p99 spike the warmup contract exists to kill (mesh mode
            # keeps first-sight: the replicated-sub/sharded-resident
            # layout is built per mesh and cheap to compile there)
            try:
                self._warm_delta_scatter(dn)
            except Exception as e:
                klog.warning("delta-scatter warmup aborted: %s", e)
        if self.incremental.enabled:
            # pre-compile the restricted-solve signatures (candidate
            # pick + gather + (P, C) solve + fused validate + global
            # mapping) so incremental cycles stay zero-retrace: the
            # serving loop's micro-batches flush at warmed pod buckets,
            # and the candidate bucket C is one static shape
            try:
                compiled += self._warm_incremental(buckets, pk, sample,
                                                   nt, dn, ds, skip_prio)
            except Exception as e:
                klog.warning("incremental warmup aborted: %s", e)
        if wu.host_fallback and self.mesh is not None and self._mesh_live:
            # ALSO warm the single-device host-mode signatures — the
            # shapes a device-loss cooloff cycle presents (resident
            # table dropped, host-mirror pack, _mesh_live False). The
            # composed serving mode turns this on so a shard lost
            # mid-churn degrades to host-mode WITHOUT a hot-path
            # compile: the cooloff cycles hit the jit cache and the
            # retrace counter stays flat through the whole
            # loss -> cooloff -> heal-sharded arc.
            self._mesh_live = False
            try:
                host_n = nt.n if nt.n else (node_count or 1)
                dn_h = nodes_to_device(nt,
                                       pad_to=bucket_size(max(host_n, 1)))
                ds_h = selectors_to_device(pk.pack_selector_tables())
                dt_h = (topology_to_device(pk.pack_topology_tables())
                        if _has_topo(pk.u) else None)
                statics_h = statics[:-1] + (False,)
                for P in buckets:
                    try:
                        if self.fault_injector is not None:
                            self.fault_injector.device_hook(
                                "warmup:compile")
                        compiled += self._warm_bucket(
                            P, pk, sample, nt, dn_h, ds_h, dt_h, solver,
                            statics_h,
                            (skip_prio, no_ports, no_pod_aff, no_spread),
                            has_vol_sample, wu)
                    except Exception as e:
                        self.metrics.recovery_device_resets.inc()
                        self.obs.note_device_reset()
                        ml = self.obs.memledger
                        if ml.enabled:
                            oomrec = ml.record_oom(
                                "warmup:compile", error=str(e),
                                shapes=f"P{P}(host)",
                                cycle=self.queue.scheduling_cycle)
                            self.obs.note_oom_forensic(ml.oom_flag(oomrec))
                        self.cache.drop_device_snapshot()
                        # same contract as the sharded sweep above: the
                        # score/potential carries die with the table
                        self._drop_incremental("device-loss")
                        klog.warning("host-fallback warmup aborted at "
                                     "bucket %d: %s", P, e)
                        return compiled
                if self.incremental.enabled:
                    # the restricted signatures in host mode too — the
                    # heal boundary's first post-cooloff cycles must not
                    # pay a hot-path compile either
                    try:
                        compiled += self._warm_incremental(
                            buckets, pk, sample, nt, dn_h, ds_h,
                            skip_prio)
                    except Exception as e:
                        klog.warning("incremental host-fallback warmup "
                                     "aborted: %s", e)
            finally:
                self._mesh_live = self.mesh is not None
        klog.V(2).info("warmup: compiled %d bucketed solve shapes "
                       "(nodes bucket %d)", compiled, dn.valid.shape[0])
        return compiled

    def _warm_bucket(self, P, pk, sample, nt, dn, ds, dt, solver, statics,
                     gates, has_vol_sample, wu, anchor: bool = False) -> int:
        """Compile one bucketed solve shape (the body of the warmup
        sweep); returns 1. Split out so the sweep's device-loss
        handling wraps the WHOLE per-bucket compile — injected chaos
        AND real XLA runtime errors abort the sweep identically.

        ``anchor=True`` (the sweep's first bucket) additionally feeds
        the perf ledger's model side (obs/ledger.py): the compiled
        signature's XLA ``cost_analysis`` flops and ONE timed warm
        replay as the per-round rate anchor every live prediction
        scales from."""
        import jax

        from kubernetes_tpu.ops.assign import (
            batch_assign,
            device_validate,
            greedy_assign,
        )

        skip_prio, no_ports, no_pod_aff, no_spread = gates
        dp = self._place(pods_to_device(pk.pack_pods(sample[:P]), pad_to=P))
        dv = sv = None
        if has_vol_sample:
            # a volume-bearing sample warms the volume-bearing solve
            # signature real cycles will record (dv in the digest);
            # row-table shapes scale with the batch's volume rows, so
            # coverage is exact only when the sample is representative
            from kubernetes_tpu.ops.arrays import volumes_to_device

            dv = self._place(volumes_to_device(pk.pack_volume_tables(
                sample[:P])))
            sv = _static_vol_pass(dp, dn, ds, dv)
        extra_score = None
        if self.scenario_pack is not None:
            # the pack's cost kernel builds the warm extra_score through
            # the SAME jitted path real cycles use (dtype + sharding
            # included) — a zeros placeholder would warm a different
            # compiled program and the first scenario cycle would
            # recompile on the hot path
            extra_score = self.scenario_pack.cost(
                sample[:P], nt, self.cache.node_order(), dp, dn)
        # the extra-score static must mirror what the WARM cost call
        # actually produced (a pack whose cost() returns None would
        # otherwise pre-register a signature no real cycle presents)
        statics = statics[:9] + (extra_score is None,) + statics[10:]
        self.obs.jax.record_call("solve", dp, dn, ds, dt, dv,
                                 static=statics, warmup=True)
        if solver == "greedy":
            a, wu_usage = greedy_assign(
                dp, dn, ds, self.weights, topo=dt, vol=dv,
                static_vol=sv,
                enabled_mask=self.pred_mask, extra_score=extra_score,
                skip_priorities=skip_prio,
                no_ports=no_ports, no_pod_affinity=no_pod_aff,
                no_spread=no_spread,
            )
        else:
            solve_kwargs = dict(
                max_rounds=self.max_rounds,
                per_node_cap=self.per_node_cap, topo=dt, vol=dv,
                static_vol=sv, enabled_mask=self.pred_mask,
                extra_score=extra_score,
                use_sinkhorn=(solver == "sinkhorn"),
                skip_priorities=skip_prio, no_ports=no_ports,
                no_pod_affinity=no_pod_aff, no_spread=no_spread,
                stats_out=self.obs.config.sinkhorn_telemetry,
            )
            out = batch_assign(dp, dn, ds, self.weights, **solve_kwargs)
            a, wu_usage = out[0], out[1]
            if anchor and self.obs.ledger.enabled:
                self._anchor_cost_model(dp, dn, ds, a, solve_kwargs)
            if self.obs.memledger.preflight_on:
                # EVERY bucket feeds the capacity preflight's per-shape
                # peak table (the anchor above only samples the first) —
                # the preflight can only split down to shapes it has a
                # measured budget for
                self._capture_bucket_memory(dp, dn, ds, solve_kwargs)
        if (self.robustness.validate_results
                and not self.robustness.host_validate):
            # the fused validator rides every production cycle's
            # readback — compile its program per bucket here too, or
            # the first real cycle pays it on the hot path
            dv_out = device_validate(a, wu_usage, dp, dn,
                                     self.pred_mask)
            if dv_out is not None:
                jax.block_until_ready(dv_out[0])
        if self.scenario_pack is not None and self.scenario.quality:
            # the per-cycle quality reduction rides every scenario
            # cycle's readback — compile its program per bucket here
            # too, with the host-built assignment vector real cycles
            # upload (same placement, same signature)
            from kubernetes_tpu.ops.scenario_cost import quality_reduce

            pad_a = jnp.asarray(np.full((P,), -1, np.int32))
            jax.block_until_ready(
                quality_reduce(pad_a, wu_usage.requested, dp, dn))
        jax.block_until_ready(a)
        fr_mask = None
        if wu.include_filter:
            fr = _filter_pass(dp, dn, ds, dt, dv, sv,
                              self.pred_mask)
            jax.block_until_ready(fr.mask)
            fr_mask = fr.mask
        if wu.nominated_variant and self.enable_preemption:
            # the nominated-pods variant (podFitsOnNode pass A): the
            # cycle after a preemption feeds a (P, N) feasibility mask
            # and ``extra_mask is None`` flips in the solve digest — a
            # DIFFERENT compiled program. Warm it here or the first
            # post-preemption cycle pays the compile on the hot path
            # (and the stall can blow the lease-freshness fence, turning
            # one preemption into fenced binds). The mask comes from the
            # same filter pass the real nominated path runs, so dtype,
            # shape, and sharding match the live signature exactly.
            if fr_mask is None:
                fr_mask = _filter_pass(dp, dn, ds, dt, dv, sv,
                                       self.pred_mask).mask
            self.obs.jax.record_call(
                "solve", dp, dn, ds, dt, dv,
                static=statics[:8] + (False,) + statics[9:],
                warmup=True)
            if solver == "greedy":
                a_m, _ = greedy_assign(
                    dp, dn, ds, self.weights, topo=dt,
                    extra_mask=fr_mask, vol=dv, static_vol=sv,
                    enabled_mask=self.pred_mask, extra_score=extra_score,
                    skip_priorities=skip_prio, no_ports=no_ports,
                    no_pod_affinity=no_pod_aff, no_spread=no_spread,
                )
            else:
                out_m = batch_assign(
                    dp, dn, ds, self.weights,
                    max_rounds=self.max_rounds,
                    per_node_cap=self.per_node_cap, topo=dt,
                    extra_mask=fr_mask, vol=dv, static_vol=sv,
                    enabled_mask=self.pred_mask, extra_score=extra_score,
                    use_sinkhorn=(solver == "sinkhorn"),
                    skip_priorities=skip_prio, no_ports=no_ports,
                    no_pod_affinity=no_pod_aff, no_spread=no_spread,
                    stats_out=self.obs.config.sinkhorn_telemetry,
                )
                a_m = out_m[0]
            jax.block_until_ready(a_m)
        self.metrics.warmup_compiles.inc()
        return 1

    def _anchor_cost_model(self, dp, dn, ds, warm_a, solve_kwargs) -> None:
        """The perf ledger's model-side warmup capture (obs/ledger.py):
        (a) the compiled solve signature's XLA ``cost_analysis`` flops /
        bytes-accessed (best-effort AOT — some backends decline), and
        (b) one TIMED warm replay of the just-compiled solve as the
        per-round rate anchor. The replay solves the real warmup
        sample over the full (P, N) plane and can take more than one
        assignment round, so the anchor records the EXECUTED round
        count (one warmup-only scalar readback) — crediting a
        multi-round wall to rounds=1 would inflate the per-round rate
        and flatter every live prediction. Failures are swallowed: the
        ledger self-anchors on the first live cycle instead, and
        warmup must never die for its accountant."""
        import time as _time  # perf_counter only (graftlint R4)

        import jax

        from kubernetes_tpu.ops.assign import (
            batch_assign,
            solve_cost_analysis,
        )

        ledger = self.obs.ledger
        P_pad = int(dp.valid.shape[0])
        N_pad = int(dn.valid.shape[0])
        mesh = int(self.mesh.devices.size) if self._mesh_live else 0
        try:
            ca = solve_cost_analysis(dp, dn, ds, self.weights,
                                     **solve_kwargs)
            if ca is not None:
                ledger.model.record_signature(
                    P_pad, N_pad, ca["flops"], ca["bytes_accessed"])
            jax.block_until_ready(warm_a)  # the compile, not the replay
            t0 = _time.perf_counter()
            out = batch_assign(dp, dn, ds, self.weights, **solve_kwargs)
            jax.block_until_ready(out[0])
            elapsed = _time.perf_counter() - t0
            # batch_assign's 3rd output is the executed round count —
            # the replay solves real sample pods and can take >1 round,
            # and an R-round wall credited to rounds=1 would inflate
            # the per-round rate R× (warmup-only scalar, declared site)
            rounds = int(self.obs.jax.readback("ledger-anchor", out[2]))
            ledger.model.record_anchor(
                "full", P_pad, N_pad, mesh,
                elapsed, rounds=max(rounds, 1))
        except Exception as e:
            klog.V(2).info("ledger cost-model capture skipped: %s", e)

    def _capture_bucket_memory(self, dp, dn, ds, solve_kwargs) -> None:
        """The memory ledger's per-bucket peak capture (obs/memledger):
        AOT-lower the solve at THIS warmed (P, N) shape and read the
        compiled program's ``memory_analysis`` — argument/output/temp
        bytes — into the preflight's per-shape peak table. Rides the
        warmup sweep only (one extra AOT compile per bucket, zero
        hot-path cost); failures are swallowed like the cost-model
        capture above — the preflight simply reports those shapes
        ``unwarmed`` and never splits TO them."""
        from kubernetes_tpu.ops.assign import solve_memory_analysis

        ml = self.obs.memledger
        try:
            ma = solve_memory_analysis(dp, dn, ds, self.weights,
                                       **solve_kwargs)
            if ma is not None:
                ml.record_bucket_memory(
                    int(dp.valid.shape[0]), int(dn.valid.shape[0]),
                    int(self.mesh.devices.size) if self._mesh_live else 0,
                    ma)
        except Exception as e:
            klog.V(2).info("memledger bucket capture skipped: %s", e)

    def _warm_delta_scatter(self, dn) -> int:
        """Compile the donated delta-scatter programs for the small
        dirty-row buckets (the same geometric family the cache's delta
        path buckets to). The resident template is a throwaway COPY of
        the warm table — the scatter donates its buffers, and donating
        the cache's real resident arrays would invalidate them."""
        import jax

        from kubernetes_tpu.ops.arrays import (
            gather_node_rows,
            scatter_node_rows,
        )

        n_pad = dn.valid.shape[0]
        compiled = 0
        for dpb in (4, 8, 16, 32, 64):
            sub = gather_node_rows(dn, jnp.zeros((dpb,), jnp.int32))
            resident = jax.tree_util.tree_map(jnp.copy, dn)
            out = scatter_node_rows(resident, sub,
                                    np.full((dpb,), n_pad, np.int32))
            jax.block_until_ready(out.requested)
            compiled += 1
        return compiled

    def _warm_incremental(self, buckets, pk, sample, nt, dn, ds,
                          skip_prio) -> int:
        """Pre-compile the restricted-solve programs for every pod
        bucket that can take the incremental route: the (mesh-sharded)
        candidate pick — top-k over the cached plane, per-shard local
        pick + replicated merge when the mesh is live — the node-row
        gather, the (P, C) solve — cold AND (for the sinkhorn solver)
        warm-started AND (for a restricted_ok scenario pack) cost-fed —
        the fused validator, the global mapping, one delta-bucket
        summary patch, the group-quota hint split, and (primary mode)
        the partitioned cold selection. Signatures pre-register with
        the telemetry so the first incremental cycle classifies as a
        cache hit.

        With ``incremental.autoTune`` the sweep compiles a C LADDER —
        {C/2, C, 2C} snapped to legal sizes — and records it in
        ``_warmed_cbuckets``: the auto-tuner may only ever move between
        warmed rungs, which is what makes a tuner move retrace-free by
        construction. Each warmed (P, C) shape also feeds the memory
        ledger's preflight peak table, so the capacity preflight can
        split an over-budget dense solve DOWN to a restricted shape it
        has a measured budget for."""
        import jax

        from kubernetes_tpu.ops.arrays import (
            gather_candidates,
            gather_node_rows,
            map_restricted_assignment,
        )
        from kubernetes_tpu.ops.assign import batch_assign, device_validate
        from kubernetes_tpu.ops.fused_score import (
            node_summary,
            partition_columns,
            patch_node_summary,
        )

        inc = self.incremental
        n_pad = dn.valid.shape[0]
        c0 = bucket_size(max(inc.candidate_bucket, 1))
        ladder = [c0]
        if inc.auto_tune:
            ladder = sorted({max(c0 // 2, 16), c0, c0 * 2})
        ladder = [c for c in ladder if c < n_pad]
        if not ladder:
            return 0
        ns = int(self.mesh.devices.size) if self._mesh_live else 1
        flags = self._summary_flags
        summary = node_summary(dn, **flags)
        zeros_dirty = jnp.zeros((n_pad,), bool)
        # summary patches at the delta buckets steady churn actually
        # presents (the scatter programs bucket geometrically exactly
        # like the PR-5 snapshot delta — an unwarmed bucket would
        # compile mid-churn and spike that cycle's latency)
        for dpb in (4, 8, 16, 32, 64):
            sub = gather_node_rows(dn, jnp.zeros((dpb,), jnp.int32))
            patched = patch_node_summary(
                node_summary(dn, **flags), node_summary(sub, **flags),
                np.full((dpb,), n_pad, np.int32))
            jax.block_until_ready(patched.rank)
        use_sk = self.solver == "sinkhorn"
        warm = bool(inc.warm_potentials and use_sk)
        want_stats = bool(self.obs.config.sinkhorn_telemetry and use_sk)
        pack = (self.scenario_pack
                if (self.scenario_pack is not None
                    and self.scenario_pack.restricted_ok) else None)
        node_order = self.cache.node_order()
        compiled = 0
        smallest_bucket = bucket_size(1)
        dps: Dict[int, object] = {}
        for C in ladder:
            self.obs.jax.record_call(
                "incremental", summary.rank,
                static=(C, n_pad, self._mesh_live, ns, True, None),
                warmup=True)
            cand, sub_dn = gather_candidates(
                summary, zeros_dirty, dn, C, num_shards=ns)
            if pack is not None:
                # the group-quota hint split is a DIFFERENT compiled
                # pick (two disjoint segment top-k's) — warm it with a
                # placeholder mask so the first hinted cycle hits cache
                hq = max(int(inc.group_quota_frac * C), 1)
                self.obs.jax.record_call(
                    "incremental", summary.rank,
                    static=(C, n_pad, self._mesh_live, ns, False, hq),
                    warmup=True)
                jax.block_until_ready(gather_candidates(
                    summary, zeros_dirty, dn, C,
                    hint_mask=jnp.zeros((n_pad,), bool),
                    num_shards=ns, hint_quota=hq)[0])
            part_warm = False
            if inc.primary:
                # partitioned cold selection: the block deal + one
                # block gather (block solves reuse the (P, C) cold
                # programs compiled below — identical shapes)
                B = self._cold_blocks(n_pad, C)
                if B >= 2:
                    part_warm = True
                    self.obs.jax.record_call(
                        "partition", summary.rank,
                        static=(B, C, n_pad, ns, self._mesh_live),
                        warmup=True)
                    blocks = partition_columns(summary, zeros_dirty, B,
                                               C, ns)
                    jax.block_until_ready(
                        gather_node_rows(dn, blocks[0]).requested)
            limit = inc.max_batch_frac * C
            for P in buckets:
                # warm P iff SOME eligible batch pads to it: the
                # runtime gate compares the RAW batch size
                # (<= maxBatchFrac*C) before padding, so the bucket
                # covering floor(limit) must be warmed even when the
                # bucket itself exceeds the limit
                smallest_in_bucket = (1 if P <= smallest_bucket
                                      else P // 2 + 1)
                over_limit = smallest_in_bucket > limit
                if over_limit and not part_warm:
                    continue  # no eligible batch can pad to this bucket
                # over-limit buckets are still reachable through the
                # PARTITIONED route: block frames solve the FULL batch
                # against a C-wide block (the maxBatchFrac gate is
                # restricted-only), so their cold (P, C) program must
                # compile here too — but only the cold variant;
                # partitioned never warm-starts or feeds extra_score
                if P not in dps:
                    dps[P] = self._place(pods_to_device(
                        pk.pack_pods(sample[:P]), pad_to=P))
                dp = dps[P]
                extra = None
                if pack is not None and not over_limit:
                    # the pack's cost on the gathered frame — same
                    # jitted kernel, dtype and sharding as real
                    # restricted cycles feed extra_score with
                    extra = pack.cost(sample[:P], nt, node_order, dp,
                                      sub_dn)
                solve_kwargs = dict(
                    max_rounds=self.max_rounds,
                    per_node_cap=self.per_node_cap,
                    enabled_mask=self.pred_mask, use_sinkhorn=use_sk,
                    skip_priorities=skip_prio, no_ports=True,
                    no_pod_affinity=True, no_spread=True,
                    stats_out=want_stats,
                    sk_tol=(inc.warm_tol if warm else None),
                    potentials_out=warm)
                variants = [dict(sk_init=None, extra_score=None)]
                if warm and not over_limit:
                    # the warm-started program is a DIFFERENT signature
                    # (potential operands join the trace) — compile it
                    # too or the second incremental cycle retraces
                    zp = (jnp.zeros((P,), jnp.float32),
                          jnp.zeros((C,), jnp.float32))
                    variants.append(dict(sk_init=zp, extra_score=None))
                if extra is not None:
                    variants.append(dict(sk_init=None,
                                         extra_score=extra))
                    if warm:
                        zp = (jnp.zeros((P,), jnp.float32),
                              jnp.zeros((C,), jnp.float32))
                        variants.append(dict(sk_init=zp,
                                             extra_score=extra))
                for var in variants:
                    self.obs.jax.record_call(
                        "solve", dp, sub_dn, ds,
                        static=("restricted", self.solver,
                                tuple(skip_prio), self.pred_mask,
                                self.per_node_cap, self.max_rounds,
                                var["sk_init"] is None,
                                var["extra_score"] is None,
                                self._mesh_live),
                        warmup=True)
                    out = batch_assign(dp, sub_dn, ds, self.weights,
                                       **solve_kwargs, **var)
                    a, wu_usage = out[0], out[1]
                    if (self.robustness.validate_results
                            and not self.robustness.host_validate):
                        dv_out = device_validate(a, wu_usage, dp,
                                                 sub_dn, self.pred_mask)
                        if dv_out is not None:
                            jax.block_until_ready(dv_out[0])
                    jax.block_until_ready(
                        map_restricted_assignment(a, cand))
                if (pack is not None and self.scenario.quality
                        and not over_limit):
                    # the frame-local quality reduction rides every
                    # scenario restricted cycle's readback — compile
                    # its (P, C) program here too
                    from kubernetes_tpu.ops.scenario_cost import (
                        quality_reduce,
                    )

                    jax.block_until_ready(quality_reduce(
                        a.astype(jnp.int32), wu_usage.requested, dp,
                        sub_dn))
                if self.obs.memledger.preflight_on:
                    # the preflight's peak table learns the restricted
                    # shapes too — (P, C) rows are what an over-budget
                    # dense 50k solve splits DOWN to instead of OOMing
                    # (warm-start extras are solve-only knobs the AOT
                    # analysis signature does not take)
                    self._capture_bucket_memory(
                        dp, sub_dn, ds,
                        {k: v for k, v in solve_kwargs.items()
                         if k not in ("sk_tol", "potentials_out")})
                compiled += 1
                self.metrics.warmup_compiles.inc()
            self._warmed_cbuckets.add(C)
        klog.V(2).info("incremental warmup: compiled %d restricted "
                       "solve shapes (C ladder %s)", compiled, ladder)
        return compiled

    def is_degraded(self) -> bool:
        """Is the backend limping? True while the device is in its
        post-loss cooloff (host-mode snapshots), while the most recent
        solve had to FALL THROUGH the ladder to reach a result, or
        while the configured tier's circuit breaker is open. The
        fallback COUNT is the signal, not the tier name: the exact
        solver deliberately routes hazardous batches to the round
        solver as a healthy path, and that must not read as
        degradation. A sustained SLO burn (obs/ledger.py watchdog,
        ``ledger.engage_pressure``) also reads degraded: eroding
        create-to-bind p99 means the backend clears its queue slower
        than admission assumes, so shedding must engage EARLIER at the
        same depth. The APF saturation probe reads this so shedding
        engages from the scheduler's ACTUAL state, not only from queue
        length."""
        from kubernetes_tpu.faults import OPEN

        if self.clock() < self._device_cooloff_until:
            return True
        if self.obs.ledger.pressure_engaged():
            return True
        if self.last_solver_fallbacks > 0:
            return True
        br = self._breakers.get(f"solver:{self.solver}")
        return br is not None and br.state == OPEN

    def backend_pressure(self, degraded_factor: float = 4.0) -> float:
        """Backend-pressure probe for APF shedding
        (serving/fairness.FlowController.set_saturation): the active-
        queue depth, multiplied by ``degraded_factor`` while
        :meth:`is_degraded` — a solver running on a fallback tier (or a
        device cooling off after a shard loss) clears its queue slower,
        so admission must shed EARLIER at the same depth. Cheap enough
        to call per mutating request (two dict reads and a clock)."""
        depth = float(self.queue.pending_counts().get("active", 0))
        if depth and self.is_degraded():
            depth *= max(degraded_factor, 1.0)
        return depth

    def attach_doorbell(self, bell):
        """Wire a serving doorbell into this scheduler: the queue rings
        it on every work-adding incoming event (which covers the
        informer paths — node/volume events ring through their
        move-to-active sweeps), and it gains this scheduler's metrics
        for scheduler_doorbell_rings_total. Returns the bell."""
        self.doorbell = bell
        if getattr(bell, "metrics", "absent") is None:
            bell.metrics = self.metrics
        # duck-typed like the metrics attach: queue fakes without the
        # attribute stay valid
        if getattr(self.queue, "doorbell", "absent") is None:
            self.queue.doorbell = bell
        return bell

    def idle_tick(self) -> None:
        """Queue maintenance WITHOUT a scheduling cycle — the idle path
        of both serve loops (legacy fixed-interval and serving mode).
        Runs the periodic flushes (backoff-complete, unschedulable-
        leftover — each rings the doorbell when it moves pods), expires
        stale cache assumptions, and resolves Permit waits, but begins
        no cycle: no trace, no CycleRecord, no solve, no metrics churn.
        This is what stops an idle cluster from minting empty cycle
        artifacts every --cycle-interval."""
        self.queue.tick()
        self._reap_expired_assumptions()
        self._verify_ambiguous_binds()
        self.maybe_repack()
        # keep the SLO burn-rate windows (and the recovery transition)
        # live while idle — eventful cycles may never come to run the
        # watchdog's state machine after the queue drains
        self.obs.ledger.tick()
        # memory ledger's idle-path sample: the other declared measured
        # boundary besides cycle end (interval-gated inside)
        self.obs.memledger.tick()
        res = CycleResult()
        self._process_waiting(res)
        if res.unschedulable or res.scheduled:
            # a Permit wait resolved while idle: its outcome must still
            # reach the metrics (the cycle path records via
            # _record_metrics; the idle path owns that here)
            self._record_metrics(res)

    def state_sizes(self) -> Dict[str, int]:
        """Sizes of every unbounded-unless-maintained structure this
        scheduler owns — the leak-sentinel surface (soak.SoakSentinels).
        Pure dict-length reads: cheap enough for a maintenance-cadence
        sample, and safe under the serving loop's lock (the soak calls
        it from the maintenance hook, which already holds it). Keys are
        stable: the soak record and /debug/soak serialize them as-is,
        and the flatness gate in bench_compare diffs them by name."""
        packer = self.cache.packer
        u = packer.u
        interned = sum(
            len(v) for v in vars(u).values() if isinstance(v, Interner))
        return {
            # per-pod side state — exit paths must pop these
            "why_pending": len(self.why_pending),
            "ambiguous_binds": len(self._ambiguous_binds),
            "cycle_states": len(self._cycle_states),
            "waiting_pods": len(self.framework.waiting),
            # bounded-by-construction state — watched anyway, because a
            # bound that silently stopped binding is exactly what only
            # a soak catches
            "breakers": len(self._breakers),
            "explain_reasons_seen": len(self._explain_reasons_seen),
            "sk_warm_potentials": 0 if self._sk_warm_pot is None else 1,
            "queue_pending": sum(self.queue.pending_counts().values()),
            "cache_assumed": len(self.cache.assumed_keys()),
            "cache_pods": self.cache.pod_count(),
            # packer per-pod caches (forget_pod-cleaned) + LRU memos
            "packer_pod_refs": len(packer._pod_refs),
            "packer_vol_cache": len(packer._vol_cache),
            "packer_vol_pods": len(packer._vol_pods),
            "packer_vec_cache": len(packer._vec_cache),
            "packer_pod_table_memo": len(packer._pod_table_memo),
            "packer_vol_table_memo": len(packer._vol_table_memo),
            # interner dedupe floors: grow with VOCABULARY (distinct
            # labels/images/selectors), not with churn — a churn-shaped
            # slope here means something interns per-pod-unique tokens
            "interned_items": interned,
            "universe_matcher_memo": len(u._matcher_row_memo),
            "universe_owner_sets_memo": len(u._owner_sets_memo),
            # device-side state — the drop edges (drop_device_snapshot,
            # _drop_incremental, host-mode demotion) must zero these;
            # a resident surviving its drop is device-memory leaked
            # even when host dict lengths stay flat
            "dev_node_table": (
                1 if self.cache.has_device_snapshot() else 0),
            "dev_score_summary": (
                1 if self.cache.has_score_summary() else 0),
            "mem_residents": self.obs.memledger.resident_count(),
            "mem_census_arrays": self.obs.memledger.census_count(),
            # journey/incident retention — pending journeys must DRAIN
            # with traffic, the completed tiers and the incident ring
            # must plateau at their caps
            **self.obs.journeys.sizes(),
            **self.obs.incidents.sizes(),
        }

    def run_until_settled(self, max_cycles: int = 50) -> List[CycleResult]:
        """Drive cycles until nothing schedules (tests + sim harness)."""
        out = []
        for _ in range(max_cycles):
            r = self.schedule_cycle()
            out.append(r)
            if r.scheduled == 0 and r.attempted == 0:
                break
        return out


def _has_topo(u) -> bool:
    return bool(
        len(u.aff_programs)
        or len(u.pref_aff_programs)
        or len(u.spread_hard_programs)
        or len(u.spread_soft_programs)
        or len(u.anti_terms)
        or len(u.sym_terms)
    )
