"""Device-mesh scale-out — the framework's replacement for everything the
reference does to survive big clusters: the 16-goroutine fan-outs
(``generic_scheduler.go:531,:738``), adaptive node subsampling
(``numFeasibleNodesToFind`` ``:437``), and the single-active-scheduler
leader-election model (scheduling itself never scales out in the
reference; HA is active-passive, ``tools/leaderelection``).

Design (SURVEY.md §2.4, BASELINE config 5): the **node axis is sharded**
across a ``jax.sharding.Mesh``; pods and selector tables are replicated.
Every kernel in ``ops/`` is written as plain jnp over the full arrays, so
XLA's SPMD partitioner (GSPMD) splits the (pods x nodes) matmuls along the
node dimension and inserts the cross-device collectives itself — per-pod
max-reductions (NormalizeReduce, argmax host selection) become all-reduces
riding ICI, exactly the "annotate shardings, let XLA insert collectives"
recipe. No NCCL/MPI analog is hand-written, and none is needed.

On one host this runs over ``xla_force_host_platform_device_count`` virtual
devices; on a TPU slice the same code spans real chips; multi-host extends
the mesh over DCN via ``jax.distributed`` initialization.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.arrays import DeviceNodes, DevicePods, DeviceSelectors
from kubernetes_tpu.utils import klog

NODE_AXIS = "nodes"


def largest_pow2(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or given) devices; the single axis shards nodes.

    The node axis is padded to power-of-two buckets
    (utils/interner.bucket_size), and a divisor of a power of two must
    itself be a power of two — so a 3- or 6-device slice can never
    divide ANY bucket and would die mid-solve with an opaque XLA shape
    error. Validated here instead: a non-power-of-two device count falls
    back to the largest dividing power-of-two subset with a logged
    warning (config-declared counts are additionally rejected up front
    by cli.validate_config)."""
    devices = list(devices) if devices is not None else jax.devices()
    if not devices:
        raise ValueError("make_mesh: no devices")
    keep = largest_pow2(len(devices))
    if keep != len(devices):
        klog.warning(
            "mesh: %d devices cannot divide the power-of-two node "
            "buckets; using the first %d (a power-of-two subset)",
            len(devices), keep)
        devices = devices[:keep]
    return Mesh(np.asarray(devices), (NODE_AXIS,))  # graftlint: disable=R7 -- device HANDLES (host objects), not buffers


def mesh_from_spec(
    spec: Union[str, int, None],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Optional[Mesh]:
    """Resolve the ``parallel.mesh`` config spec into a Mesh (or None).

    - ``"off"`` / ``None`` / ``0`` → None (single-device mode; never
      touches the backend, so mesh-off schedulers stay constructible
      before any device initializes);
    - ``"auto"`` → a mesh over every local device (power-of-two
      fallback as in :func:`make_mesh`);
    - an int ``N`` → a mesh over the first N local devices; more than
      available clamps with a warning, non-power-of-two falls back.

    This is THE resolver: the scheduler backend, the bench harness, and
    the weak-scaling script all build their meshes here, so "sharded"
    means the same placement everywhere."""
    if spec is None or spec == "off" or spec == 0 or spec is False:
        return None
    if spec == "auto":
        return make_mesh(devices)
    n = int(spec)
    if n < 1:
        raise ValueError(f"parallel.mesh: invalid device count {spec!r}")
    avail = list(devices) if devices is not None else jax.devices()
    if n > len(avail):
        klog.warning("mesh: %d devices requested, %d available; using %d",
                     n, len(avail), len(avail))
        n = len(avail)
    return make_mesh(avail[:n])


def mesh_size(mesh: Optional[Mesh]) -> int:
    """Device count of a mesh; 0 for None (the single-device mode)."""
    return int(mesh.devices.size) if mesh is not None else 0


def shard_nodes(nodes: DeviceNodes, mesh: Mesh) -> DeviceNodes:
    """Place node-axis arrays sharded along the mesh; universe-shaped arrays
    (zone_valid) replicated. Node buckets are powers of two, so any
    power-of-two device count divides them."""
    n = nodes.allocatable.shape[0]
    d = int(mesh.devices.size)
    if n % max(d, 1):
        # a clear error instead of the opaque XLA one: callers pad the
        # node bucket up to the mesh size (both are powers of two, so
        # max(bucket, devices) always divides)
        raise ValueError(
            f"shard_nodes: node axis {n} not divisible by {d} mesh "
            f"devices — pad the node bucket to at least {d} rows")
    sharded = NamedSharding(mesh, P(NODE_AXIS))
    replicated = NamedSharding(mesh, P())

    def place(a):
        spec = sharded if a.ndim >= 1 and a.shape[0] == n else replicated
        if a.ndim >= 2 and a.shape[0] == n:
            spec = NamedSharding(mesh, P(NODE_AXIS, *([None] * (a.ndim - 1))))
        return jax.device_put(a, spec)

    return DeviceNodes(*[place(f) for f in nodes])


def place_node_table(table, mesh: Mesh, pad_to: Optional[int] = None):
    """Host ``NodeTable`` -> mesh-sharded ``DeviceNodes`` in one call:
    pad the node bucket up to the mesh size (both are powers of two, so
    the shard split is always legal), upload, shard along N. The ONE
    placement seam for every non-resident path — the cache's full
    rebuild, the legacy per-cycle host pack, and warmup all route here,
    so a future padding-rule change cannot miss a site and resurrect
    the opaque XLA shape error :func:`shard_nodes` guards against."""
    from kubernetes_tpu.ops.arrays import nodes_to_device
    from kubernetes_tpu.utils.interner import bucket_size

    n_pad = pad_to or bucket_size(max(table.n, 1))
    n_pad = max(n_pad, int(mesh.devices.size))
    return shard_nodes(nodes_to_device(table, pad_to=n_pad), mesh)


def shard_usage(u, mesh: Mesh):
    """Shard a node-axis usage pytree (ops/assign.UsageState — every
    leaf is (N, ...) row-shaped) along the mesh, matching the resident
    DeviceNodes placement. The re-pinning ladder tiers (batch-single /
    batch-cpu) route their usage back through this before the cycle's
    failure-reason pass recombines it with the sharded node table."""
    def place(a):
        spec = NamedSharding(mesh, P(NODE_AXIS, *([None] * (a.ndim - 1))))
        return jax.device_put(a, spec)

    return type(u)(*[place(f) for f in u])


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (pods, selector tables) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, spec), tree)


def shard_cluster(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    mesh: Mesh,
    topo=None,
):
    """One-call placement for a scheduling cycle's inputs. Topology term
    tables (DeviceTopology) are universe-shaped -> replicated; the dynamic
    per-node count matrices live inside ``nodes`` and shard with it."""
    out = (replicate(pods, mesh), shard_nodes(nodes, mesh), replicate(sel, mesh))
    if topo is not None:
        return out + (replicate(topo, mesh),)
    return out
