"""Device-mesh scale-out — the framework's replacement for everything the
reference does to survive big clusters: the 16-goroutine fan-outs
(``generic_scheduler.go:531,:738``), adaptive node subsampling
(``numFeasibleNodesToFind`` ``:437``), and the single-active-scheduler
leader-election model (scheduling itself never scales out in the
reference; HA is active-passive, ``tools/leaderelection``).

Design (SURVEY.md §2.4, BASELINE config 5): the **node axis is sharded**
across a ``jax.sharding.Mesh``; pods and selector tables are replicated.
Every kernel in ``ops/`` is written as plain jnp over the full arrays, so
XLA's SPMD partitioner (GSPMD) splits the (pods x nodes) matmuls along the
node dimension and inserts the cross-device collectives itself — per-pod
max-reductions (NormalizeReduce, argmax host selection) become all-reduces
riding ICI, exactly the "annotate shardings, let XLA insert collectives"
recipe. No NCCL/MPI analog is hand-written, and none is needed.

On one host this runs over ``xla_force_host_platform_device_count`` virtual
devices; on a TPU slice the same code spans real chips; multi-host extends
the mesh over DCN via ``jax.distributed`` initialization.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.arrays import DeviceNodes, DevicePods, DeviceSelectors

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or given) devices; the single axis shards nodes."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))  # graftlint: disable=R7 -- device HANDLES (host objects), not buffers


def shard_nodes(nodes: DeviceNodes, mesh: Mesh) -> DeviceNodes:
    """Place node-axis arrays sharded along the mesh; universe-shaped arrays
    (zone_valid) replicated. Node buckets are powers of two, so any
    power-of-two device count divides them."""
    n = nodes.allocatable.shape[0]
    sharded = NamedSharding(mesh, P(NODE_AXIS))
    replicated = NamedSharding(mesh, P())

    def place(a):
        spec = sharded if a.ndim >= 1 and a.shape[0] == n else replicated
        if a.ndim >= 2 and a.shape[0] == n:
            spec = NamedSharding(mesh, P(NODE_AXIS, *([None] * (a.ndim - 1))))
        return jax.device_put(a, spec)

    return DeviceNodes(*[place(f) for f in nodes])


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (pods, selector tables) across the mesh."""
    spec = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, spec), tree)


def shard_cluster(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    mesh: Mesh,
    topo=None,
):
    """One-call placement for a scheduling cycle's inputs. Topology term
    tables (DeviceTopology) are universe-shaped -> replicated; the dynamic
    per-node count matrices live inside ``nodes`` and shard with it."""
    out = (replicate(pods, mesh), shard_nodes(nodes, mesh), replicate(sel, mesh))
    if topo is not None:
        return out + (replicate(topo, mesh),)
    return out
