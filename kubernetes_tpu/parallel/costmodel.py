"""Analytical collective-cost model for the node-sharded solve —
bounding BASELINE config 5's scale-out claim without multi-chip hardware
(VERDICT r4 item 6).

The sharded design (parallel/mesh.py): the node axis is split N/D per
device, pods replicated. The structural property that makes scale-out
cheap — and the claim this model quantifies so the first real multi-chip
run can FALSIFY it — is that **no (P, N) matrix ever crosses ICI**.
Every cross-shard exchange in a round is a per-pod vector or a per-pod
per-zone panel:

  =====================  =========================  ==================
  round phase            collective (GSPMD-inserted) payload shape
  =====================  =========================  ==================
  filter                 all-reduce OR               (P,) bool
  score: NA normalize    all-reduce MAX              (P,) f32
  score: TT normalize    all-reduce MAX              (P,) f32
  score: spread max      all-reduce MAX              (P,) f32
  score: spread zones    psum + zone-present         2 x (P, Z) f32
  score: interpod mx/mn  all-reduce MAX/MIN          2 x (P,) f32
  score: evenspread      psum total + MIN            2 x (P,) f32
  bid: rowmax            all-reduce MAX              (P,) f32
  bid: feasible_any      all-reduce OR               (P,) bool
  tie cumsum offsets     all-gather shard sums       (P,) i16 x D terms
  pick: choice argmax    all-reduce ARGMAX           (P,) f32+i32
  router (round 0 only)  2 all-reduces               (P,) f32
  acceptance: free rows  worst-case all-gather       (N, R) f32
  =====================  =========================  ==================

Usage scatters land on the owning shard locally (pods are replicated, so
each device applies the accepted subset to its own node rows) — zero
collective cost.

Cost model: ring all-reduce/all-gather moves ``2 (D-1)/D x bytes``
across the slowest link; each collective also pays a latency floor.
The v5e ICI envelope is parameterized (default 1e11 B/s per chip
aggregate with a 45 GB/s conservative floor — the public "How to Scale
Your Model" v5e numbers bracket this range) precisely so the prediction
is a RANGE the hardware run can land inside or break.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

#: ring-collective traffic factor for D devices
_RING = lambda d: 2.0 * (d - 1) / max(d, 1)


@dataclass
class CollectiveCostModel:
    devices: int
    pods_per_batch: int          # P (padded batch)
    nodes_padded: int            # N (padded node axis)
    zones: int = 16
    resources: int = 8           # R columns in the usage/free tables
    rounds_per_batch: int = 2    # measured: config5 runs resolve in 2
    ici_bytes_per_s_low: float = 4.5e10    # conservative v5e per-chip
    ici_bytes_per_s_high: float = 2.0e11   # optimistic aggregate
    collective_latency_s: float = 5e-6     # per-collective floor
    #: single-device steady throughput anchors (pods/s at this shape)
    single_device_cpu_pods_per_s: float = 144.0  # config5_cpu_mesh_r04

    def per_round_collectives(self) -> dict:
        """Enumerated payloads (bytes, pre-ring-factor) per round."""
        P, Z, N, R, D = (self.pods_per_batch, self.zones,
                         self.nodes_padded, self.resources, self.devices)
        f32, i16, b1 = 4, 2, 1
        items = {
            "filter_feasible_any_bool": P * b1,
            "score_normalize_maxes_x3": 3 * P * f32,
            "score_zone_panels_x2": 2 * P * Z * f32,
            "score_topology_reduces_x4": 4 * P * f32,
            "bid_rowmax": P * f32,
            "bid_feasible_any_bool": P * b1,
            "tie_cumsum_shard_sums": P * i16 * D,
            "pick_argmax_value_index": P * (f32 + 4),
            "acceptance_free_rows_allgather_worstcase": N * R * f32,
            # round-0 router all-reduces, amortized over the batch's
            # rounds so per-round figures stay honest multipliers
            "router_round0_amortized": int(
                2 * P * f32 / max(self.rounds_per_batch, 1)),
        }
        items["total_bytes"] = sum(items.values())
        # one collective per table row: 1 filter + 3 maxes + 2 zone
        # panels + 4 topology + rowmax + feasible_any + cumsum + argmax
        # + free-rows gather = 15, plus 2/rounds router amortized
        items["n_collectives"] = 15 + 2 / max(self.rounds_per_batch, 1)
        return items

    def predict(self) -> dict:
        d = self.devices
        per_round = self.per_round_collectives()
        wire = per_round["total_bytes"] * _RING(d)
        lat = per_round["n_collectives"] * self.collective_latency_s
        t_coll_low = wire / self.ici_bytes_per_s_low + lat
        t_coll_high = wire / self.ici_bytes_per_s_high + lat
        # per-device compute: node-axis work divides linearly (every
        # (P, N) kernel tiles along the shard); the CPU anchor gives a
        # hardware-independent LOWER bound on throughput
        t_round_cpu_1dev = (self.pods_per_batch
                            / self.single_device_cpu_pods_per_s
                            / self.rounds_per_batch)
        t_round_cpu_ddev = t_round_cpu_1dev / d
        eff_low = t_round_cpu_ddev / (t_round_cpu_ddev + t_coll_low)
        tput_cpu_basis = (self.single_device_cpu_pods_per_s * d * eff_low)
        return {
            "devices": d,
            "per_round_collective_bytes_on_wire": int(wire),
            "per_round_collective_time_s": [round(t_coll_high, 7),
                                            round(t_coll_low, 7)],
            "per_round_compute_s_cpu_anchor_per_device":
                round(t_round_cpu_ddev, 4),
            "scaleout_efficiency_cpu_anchor": round(eff_low, 5),
            "predicted_pods_per_s_cpu_anchor": round(tput_cpu_basis, 1),
            "tpu_prediction": (
                "pods_per_s(v5e-8) = 8 x S x eff. S was MEASURED this "
                "round: 7270 pods/s single-chip at 50k nodes x 4096 "
                "batch (benchres/bench_tpu_r05_full.json "
                "config5_sharded_50k) => predicted ~58k pods/s on a "
                "v5e-8 at eff 0.9999; per-round compute ~0.28 s vs "
                "collectives 0.1-0.2 ms keeps collectives <0.1% of a "
                "round — the falsifiable claims are eff >= 0.99 and NO "
                "(P,N)-sized ICI transfer in the profiled HLO"
            ),
        }

    def document(self) -> dict:
        return {
            "what": ("Analytical ICI collective-cost model for the "
                     "node-sharded solve (BASELINE config 5; "
                     "parallel/costmodel.py) — predictions for the "
                     "first real multi-chip run to falsify"),
            "inputs": asdict(self),
            "per_round_collectives_bytes": self.per_round_collectives(),
            "prediction": self.predict(),
            "anchors": {
                "single_chip_tpu_50k": (
                    "benchres/bench_tpu_r05_full.json config5_sharded_50k: "
                    "7270 pods/s, 200k pods, 98 rounds, 1.29 GB RSS — the "
                    "measured S the v5e-8 prediction scales from"),
                "single_device_cpu_50k": "benchres/config5_cpu_mesh_r04.json"
                                          " steady 144 pods/s, 2 rounds/batch",
                "virtual_8dev_cpu": ("benchres/config5_cpu_mesh_r04_8dev"
                                     ".json 1.5 pods/s — 8 shards "
                                     "timesharing ONE core plus emulated "
                                     "collectives; a lower bound on "
                                     "nothing, recorded for contrast"),
            },
        }


def config5_model(devices: int = 8) -> CollectiveCostModel:
    """The BASELINE config-5 shape: 50k nodes (padded 65536), 4096-pod
    batches, v5e-8 mesh."""
    return CollectiveCostModel(devices=devices, pods_per_batch=4096,
                               nodes_padded=65536)


def model_efficiency(devices: int, pods: int, nodes: int,
                     batch: int = 4096) -> float:
    """THE analytic scale-out efficiency for a (devices, pods, nodes)
    shape — the single figure every surface must agree on: the
    weak-scaling bench (``scripts/bench_mesh_scale.py``), the runtime
    perf ledger's mesh-cycle predictions (``obs/ledger.py``), and the
    committed ``mesh_r*.json`` records all call HERE, so bench and
    runtime can never disagree on what "the model" claims (pinned by
    the parity test in tests/test_ledger.py).

    ``pods`` is capped at ``batch`` (the per-cycle solve shape) and
    ``nodes`` pads to the same power-of-two bucket the device tables
    use. Single-device shapes are 1.0 by definition — there is nothing
    to scale out."""
    if devices < 2:
        return 1.0
    from kubernetes_tpu.utils.interner import bucket_size

    m = CollectiveCostModel(devices=devices,
                            pods_per_batch=max(min(pods, batch), 1),
                            nodes_padded=bucket_size(max(nodes, 1)))
    return float(m.predict()["scaleout_efficiency_cpu_anchor"])
