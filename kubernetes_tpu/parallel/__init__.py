from kubernetes_tpu.parallel.mesh import (  # noqa: F401
    NODE_AXIS,
    largest_pow2,
    make_mesh,
    mesh_from_spec,
    mesh_size,
    place_node_table,
    replicate,
    shard_cluster,
    shard_nodes,
    shard_usage,
)
