from kubernetes_tpu.parallel.mesh import (  # noqa: F401
    NODE_AXIS,
    make_mesh,
    replicate,
    shard_cluster,
    shard_nodes,
)
